"""Worker for the multi-host straggler bench (spawned by
``straggler_bench.py``): same two-process deployment as the multihost
tests, but rank 1 injects a blocking delay into every collective tick —
an artificially slow host — and rank 0 measures the achieved step
cadence and cross-host delivery rate.

Usage: _straggler_worker.py <rank> <base_port> <db> <delay_ms> <msgs>
"""

import asyncio
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

rank = int(sys.argv[1])
base = int(sys.argv[2])
db = sys.argv[3]
delay_ms = float(sys.argv[4])
msgs = int(sys.argv[5])

jax.distributed.initialize(coordinator_address=f"127.0.0.1:{base}",
                           num_processes=2, process_id=rank)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pushcdn_tpu.broker.mesh_group import MeshGroupConfig  # noqa: E402
from pushcdn_tpu.testing.two_host import make_two_host_node  # noqa: E402

CLIENT_SEED = [81_000, 82_000]
WINDOW_S = 0.02


async def _main() -> None:
    node = await make_two_host_node(
        rank, base, db, client_seeds=CLIENT_SEED, broker_seed_base=90,
        mesh_config=MeshGroupConfig(
            num_user_slots=64, ring_slots=64, frame_bytes=2048,
            extra_lanes=(), direct_bucket_slots=4,
            batch_window_s=WINDOW_S),
        collective_timeout_s=60.0)  # sweep delays stay FAR below this
    group, broker, client = node.group, node.broker, node.client

    if rank == 1 and delay_ms > 0:
        # the slow host: every collective tick pays a blocking delay
        # (models a host whose step thread is starved/slow)
        orig = group._collective_stop

        def slow_stop(want_stop):
            time.sleep(delay_ms / 1e3)
            return orig(want_stop)
        group._collective_stop = slow_stop

    await node.directory_rendezvous()

    # measured phase: rank 0 publishes, BOTH drain their copies
    payload = os.urandom(1024)
    t0 = time.perf_counter()
    steps0 = group.steps

    async def drain():
        got = 0
        async with asyncio.timeout(180):
            while got < msgs:
                got += len(await client.receive_messages(msgs - got))
    d = asyncio.create_task(drain())
    if rank == 0:
        for _ in range(msgs):
            await client.send_broadcast_message([0], payload)
    print(f"rank {rank}: MARK sent", flush=True)
    await d
    print(f"rank {rank}: MARK drained", flush=True)
    dt = time.perf_counter() - t0
    steps = group.steps - steps0
    print(f"rank {rank}: STRAGGLER delay_ms={delay_ms} msgs={msgs} "
          f"wall={dt:.3f} steps={steps} "
          f"cadence_ms={dt / max(steps, 1) * 1e3:.1f} "
          f"rate={msgs / dt:.1f}/s", flush=True)

    # drain barrier via directory, then exit
    await node.publish_marker(b"sdone-%d" % rank)
    await node.await_markers([b"sdone-0", b"sdone-1"])
    print(f"rank {rank}: MARK barrier passed", flush=True)
    client.close()
    await node.marshal.stop()
    print(f"rank {rank}: MARK marshal stopped", flush=True)
    await broker.stop()
    print(f"rank {rank}: MARK broker stopped", flush=True)
    if rank == 1:
        # announce imminent exit so the coordinator (rank 0) can outlive
        # us — its death fatal-terminates any process still polling the
        # coordination service
        await node.publish_marker(b"exiting-1")
    else:
        await node.await_markers([b"exiting-1"], timeout_s=30.0)
        await asyncio.sleep(1.0)  # let rank 1's os._exit land first
    print(f"rank {rank}: DONE", flush=True)
    os._exit(0)


async def main() -> None:
    try:
        await _main()
    except BaseException:
        import traceback
        traceback.print_exc()
        sys.stdout.flush()
        os._exit(1)


asyncio.run(main())
