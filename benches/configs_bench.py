#!/usr/bin/env python
"""BASELINE.json configs[1..3] benches on the in-process cluster fixture.

Covers the three driver configs between the loopback echo (configs[0],
measured in ``host_bench.py``) and the HotShot replay (configs[4],
``consensus_replay.py``):

- configs[1]: 2-broker broadcast fan-out, 8 subscribed clients, BLS auth
  (falls back to Ed25519 when the native pairing library is unavailable —
  the emitted row records which scheme ran);
- configs[2]: topic pub/sub, 4 topics x 64 subscribers, mixed broadcast +
  direct traffic;
- configs[3]: marshal-coordinated 8-broker mesh, clients load-balanced
  2-per-broker, full-mesh broadcast fan-out.

Like the reference's whole-system tests (tests/src/tests/mod.rs:62-143)
everything runs in one process over the Memory transport + shared SQLite
discovery, so numbers are routing-stack numbers, not NIC numbers.

Usage: python benches/configs_bench.py [--quick]
Prints one JSON object per bench line.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the device-mesh variant of configs[3] runs its 8 broker shards on a
# virtual 8-device CPU mesh (same stand-in the test suite uses); the
# flag must be set before jax initializes
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

from pushcdn_tpu.proto.crypto.signature import (
    BlsBn254Scheme,
    DEFAULT_SCHEME,
)
from pushcdn_tpu.proto.topic import TopicSpace
from pushcdn_tpu.proto.transport.memory import Memory
from pushcdn_tpu.testing import Cluster, wait_mesh_interest, wait_until

RESULTS: list[dict] = []


def emit(name: str, value: float, unit: str, **extra) -> None:
    row = {"bench": name, "value": round(value, 3), "unit": unit, **extra}
    RESULTS.append(row)
    print(json.dumps(row), flush=True)


def _p99(samples):
    import math
    return round(sorted(samples)[math.ceil(len(samples) * 0.99) - 1], 1)


async def _drain(client, n: int):
    """Receive exactly ``n`` messages on ``client`` via the batched
    receive API (one timeout scope for the whole drain — per-message
    wakeups cost more than the pipeline itself at these rates)."""
    got = 0
    async with asyncio.timeout(30):
        while got < n:
            got += len(await client.receive_messages(n - got))


async def _drain_raw(client, n: int):
    """Count ``n`` delivered frames at the transport layer (no app-side
    decode): the bad-connector-style load drain — measures what the CDN
    delivered into the client process, decoupled from what the app then
    does with each message."""
    from pushcdn_tpu.proto.transport.base import FrameChunk
    got = 0
    conn = client._connection
    async with asyncio.timeout(60):
        while got < n:
            for item in await conn.recv_frames(n - got):
                got += item.remaining if type(item) is FrameChunk else 1
                item.release()


_wait_mesh_interest = wait_mesh_interest


async def _connect_all(clients, concurrency: int = 32):
    """Authenticate clients through the marshal, bounded concurrency;
    returns per-client connect latencies (seconds)."""
    sem = asyncio.Semaphore(concurrency)
    lat = [0.0] * len(clients)

    async def one(i, c):
        async with sem:
            t0 = time.perf_counter()
            await c.ensure_initialized()
            lat[i] = time.perf_counter() - t0

    await asyncio.gather(*(one(i, c) for i, c in enumerate(clients)))
    return lat


# ---------------------------------------------------------------------------
# configs[1]: 2-broker fan-out, 8 subscribed clients, BLS auth
# ---------------------------------------------------------------------------

async def bench_two_broker_fanout(msgs: int):
    scheme = BlsBn254Scheme if BlsBn254Scheme.available() else DEFAULT_SCHEME
    cluster = await Cluster(num_brokers=2, scheme=scheme).start()
    try:
        clients = []
        auth_lat = []
        for i in range(8):
            await cluster.place_on(i % 2)  # 4 clients per broker
            c = cluster.client(seed=100 + i, topics=[0])
            t0 = time.perf_counter()
            await c.ensure_initialized()
            auth_lat.append((time.perf_counter() - t0) * 1e3)
            clients.append(c)
        await wait_until(
            lambda: sum(b.connections.num_users for b in cluster.brokers) == 8)
        await _wait_mesh_interest(cluster, topic=0, links=1)

        emit("configs1/auth_handshake", statistics.median(auth_lat),
             "ms_median", scheme=scheme.name, p99=_p99(auth_lat))

        # Warm twin: the SAME 8 keys drop their connections and re-auth
        # sequentially — the reconnect-storm / elastic-churn regime the
        # marshal's per-public-key Miller line-table cache serves: each
        # re-auth's pairing replays the cached table (pk ladder and
        # subgroup check amortized away) instead of re-deriving it.
        warm_lat = []
        for _ in range(2):  # 16 samples: the 8-sample cold median is jumpy
            for c in clients:
                c._disconnect_on_error()
            # let the dropped connections' teardown (reader EOF, broker
            # unregister) fully drain so the measured window holds ONLY the
            # reconnect handshake, not the previous connection's funeral
            await wait_until(
                lambda: sum(b.connections.num_users
                            for b in cluster.brokers) == 0)
            await asyncio.sleep(0.05)
            for c in clients:
                t0 = time.perf_counter()
                await c.ensure_initialized()
                warm_lat.append((time.perf_counter() - t0) * 1e3)
        await wait_until(
            lambda: sum(b.connections.num_users for b in cluster.brokers) == 8)
        emit("configs1/auth_handshake_warm", statistics.median(warm_lat),
             "ms_median", scheme=scheme.name, p99=_p99(warm_lat))

        # Burst twin: 8 additional clients authenticate CONCURRENTLY — the
        # adaptive batch verifier coalesces the pairings into shared
        # final-exponentiation batches (proto/crypto/batch.py), so
        # aggregate auth throughput beats 1/latency even on one core.
        burst = [cluster.client(seed=150 + i, topics=[0]) for i in range(8)]
        t0 = time.perf_counter()
        await asyncio.gather(*(c.ensure_initialized() for c in burst))
        dt = time.perf_counter() - t0
        emit("configs1/auth_burst_throughput", len(burst) / dt, "auths/s",
             scheme=scheme.name, concurrent=len(burst),
             window_ms=round(dt * 1e3, 2))
        for c in burst:
            c.close()

        payload = os.urandom(1024)
        publisher = clients[0]
        receivers = clients  # all 8 subscribe to topic 0, sender included

        # the cluster + clients now exist: freeze the live heap so
        # steady-state GC only walks young message garbage (same server
        # posture as the device-mesh phase below)
        from pushcdn_tpu.bin.common import tune_gc as _tg
        _tg(500_000)
        t0 = time.perf_counter()
        drains = [asyncio.create_task(_drain(c, msgs)) for c in receivers]
        for _ in range(msgs):
            await publisher.send_broadcast_message([0], payload)
        await asyncio.gather(*drains)
        dt = time.perf_counter() - t0
        emit("configs1/broadcast_fanout", msgs * len(receivers) / dt,
             "deliveries/s", scheme=scheme.name, msgs=msgs,
             publish_rate=round(msgs / dt, 1), frame=1024)
        for c in clients:
            c.close()
    finally:
        await cluster.stop()


# ---------------------------------------------------------------------------
# configs[2]: 4 topics x 64 subscribers, mixed broadcast + direct
# ---------------------------------------------------------------------------

async def bench_topic_pubsub(per_topic: int, rounds: int):
    topics = list(range(4))
    cluster = await Cluster(num_brokers=1,
                            topics=TopicSpace.range(8)).start()
    try:
        clients = []
        for t in topics:
            for j in range(per_topic):
                clients.append(cluster.client(seed=1000 + t * per_topic + j,
                                              topics=[t]))
        await _connect_all(clients)
        await wait_until(
            lambda: cluster.brokers[0].connections.num_users == len(clients),
            timeout=30)

        payload = os.urandom(1024)
        publishers = [clients[t * per_topic] for t in topics]
        # each round: 4 broadcasts (one per topic) + 4 directs to a peer on
        # another topic -> deliveries = 4*per_topic + 4 per round
        per_round = 4 * per_topic + 4

        async def recv_counts(c, t_idx):
            # subscriber on topic t receives `rounds` broadcasts; the 4
            # direct targets get `rounds` more each
            n = rounds
            if c in direct_targets:
                n += rounds
            await _drain(c, n)

        direct_targets = [clients[((t + 1) % 4) * per_topic + 1]
                          for t in topics]
        from pushcdn_tpu.bin.common import tune_gc as _tg
        _tg(500_000)  # re-freeze: 256 clients' live state is now resident
        t0 = time.perf_counter()
        drains = [asyncio.create_task(recv_counts(c, i // per_topic))
                  for i, c in enumerate(clients)]
        for _ in range(rounds):
            for t, pub in enumerate(publishers):
                await pub.send_broadcast_message([t], payload)
                await pub.send_direct_message(
                    direct_targets[t].public_key, payload)
        await asyncio.gather(*drains)
        dt = time.perf_counter() - t0
        emit("configs2/topic_pubsub_mixed", rounds * per_round / dt,
             "deliveries/s", subscribers=len(clients), topics=4,
             per_topic=per_topic, rounds=rounds, frame=1024)
        for c in clients:
            c.close()
    finally:
        await cluster.stop()


# ---------------------------------------------------------------------------
# configs[3]: marshal-coordinated 8-broker mesh
# ---------------------------------------------------------------------------

async def bench_eight_broker_mesh(msgs: int):
    cluster = await Cluster(num_brokers=8).start()
    try:
        # every broker dialed every peer (dedup rule: dial iff peer id >= own);
        # wait for formation before sampling — 28 mutual handshakes in flight
        await wait_until(
            lambda: all(b.connections.num_brokers == 7
                        for b in cluster.brokers), timeout=60)
        links = [b.connections.num_brokers for b in cluster.brokers]
        emit("configs3/mesh_links", sum(links) / len(links), "links/broker",
             expect=7.0, per_broker=links)

        clients = []
        for i in range(16):
            await cluster.place_on(i % 8)  # 2 clients per broker
            c = cluster.client(seed=2000 + i, topics=[0])
            await c.ensure_initialized()
            clients.append(c)
        await wait_until(
            lambda: sum(b.connections.num_users for b in cluster.brokers) == 16,
            timeout=30)
        await _wait_mesh_interest(cluster, topic=0, links=7)

        payload = os.urandom(1024)
        publisher = clients[0]

        # latency: sequential rounds, send -> all 16 received
        lat = []
        for _ in range(min(100, msgs)):
            t0 = time.perf_counter()
            await publisher.send_broadcast_message([0], payload)
            await asyncio.gather(*(
                asyncio.wait_for(c.receive_message(), 30) for c in clients))
            lat.append((time.perf_counter() - t0) * 1e6)
        emit("configs3/mesh_broadcast_latency", statistics.median(lat),
             "us_median", p99=_p99(lat), receivers=16, brokers=8)

        # throughput: pipelined
        t0 = time.perf_counter()
        drains = [asyncio.create_task(_drain(c, msgs)) for c in clients]
        for _ in range(msgs):
            await publisher.send_broadcast_message([0], payload)
        await asyncio.gather(*drains)
        dt = time.perf_counter() - t0
        emit("configs3/mesh_broadcast_fanout", msgs * 16 / dt,
             "deliveries/s", msgs=msgs, brokers=8,
             publish_rate=round(msgs / dt, 1), frame=1024)

        # transport-level delivery rate (raw twin of the line above; see
        # _drain_raw), 2 publishers on different brokers
        raw_msgs = msgs * 4
        t0 = time.perf_counter()
        drains = [asyncio.create_task(_drain_raw(c, raw_msgs))
                  for c in clients]
        for _ in range(raw_msgs // 2):
            await clients[0].send_broadcast_message([0], payload)
            await clients[1].send_broadcast_message([0], payload)
        await asyncio.gather(*drains)
        dt = time.perf_counter() - t0
        emit("configs3/mesh_frame_delivery", raw_msgs * 16 / dt,
             "frames/s", msgs=raw_msgs, brokers=8, frame=1024)
        for c in clients:
            c.close()
    finally:
        await cluster.stop()


# ---------------------------------------------------------------------------
# configs[3], device plane: the same 8-broker mesh with inter-broker
# traffic on the DEVICE mesh (all_gather over the broker axis — the
# BASELINE.json north-star path), zero host broker links
# ---------------------------------------------------------------------------

# coalesce window for the device-mesh phases: one constant so the cluster
# config, the latency loop's idle spacing, and the emitted row stay in sync
DEVICE_MESH_WINDOW_S = 0.002


async def bench_eight_broker_device_mesh(msgs: int, tput_msgs: int):
    import jax
    jax.config.update("jax_platforms", "cpu")

    from pushcdn_tpu.bin.common import tune_gc
    from pushcdn_tpu.testing.mesh_cluster import MeshCluster

    tune_gc()  # re-freeze: this bench just pulled the jax heap in

    # 2 ms coalesce window: deployment tuning for the sustained-fanout
    # regime (more frames per mesh step amortizes the fixed step cost).
    # The latency phase is unaffected — a burst after idle bypasses the
    # window entirely (CoalesceGate's idle-burst rule, pump_common.py).
    cluster = await MeshCluster(
        num_shards=8, ring_slots=1024, frame_bytes=2048,
        batch_window_s=DEVICE_MESH_WINDOW_S,
        devices=jax.devices("cpu"), prefix="cfg3d",
    ).start(form_host_mesh=False)
    try:
        clients = [await cluster.place_client(3000 + i, i % 8, topics=[0])
                   for i in range(16)]
        assert all(b.connections.num_brokers == 0 for b in cluster.brokers)

        payload = os.urandom(1024)
        publisher = clients[0]
        lat = []
        for _ in range(min(100, msgs)):
            # unloaded latency: let the pump go idle (>4 coalesce windows)
            # so every echo rides the idle-burst step-now path
            await asyncio.sleep(4.5 * DEVICE_MESH_WINDOW_S)
            t0 = time.perf_counter()
            await publisher.send_broadcast_message([0], payload)
            await asyncio.gather(*(
                asyncio.wait_for(c.receive_message(), 30) for c in clients))
            lat.append((time.perf_counter() - t0) * 1e6)
        emit("configs3/device_mesh_broadcast_latency", statistics.median(lat),
             "us_median", p99=_p99(lat), receivers=16, brokers=8,
             host_links=0, steps=cluster.group.steps)

        # The cluster and its jit specializations now exist: collect the
        # startup cycles and freeze the live heap so steady-state GC only
        # walks young message garbage (server posture, bin/common.py). The
        # first trial additionally absorbs the full-ring jit compile; the
        # machine shares one core with everything else, so run three
        # in-process trials and report the best, with all trials disclosed.
        tune_gc(500_000)
        trials = []
        for _ in range(3):
            t0 = time.perf_counter()
            drains = [asyncio.create_task(_drain(c, tput_msgs))
                      for c in clients]
            for _ in range(tput_msgs // 2):
                await clients[0].send_broadcast_message([0], payload)
                await clients[1].send_broadcast_message([0], payload)
            await asyncio.gather(*drains)
            dt = time.perf_counter() - t0
            trials.append(tput_msgs * 16 / dt)
        # headline = MEDIAN of the trials (VERDICT r5 #5: on a noisy
        # shared core the max systematically flatters); the max is
        # disclosed alongside, as the trials always were
        headline = statistics.median(trials)
        emit("configs3/device_mesh_broadcast_fanout", headline,
             "deliveries/s", msgs=tput_msgs, brokers=8,
             publish_rate=round(headline / 16, 1),
             frame=1024, host_links=0,
             mesh_routed=cluster.group.messages_routed,
             trials=[round(r, 1) for r in trials],
             max=round(max(trials), 1),
             batch_window_s=DEVICE_MESH_WINDOW_S, gc_refrozen=True)

        # transport-level delivery rate (raw twin; 2 publishers on
        # different shards so ingress rides two rings)
        raw_msgs = tput_msgs * 2
        t0 = time.perf_counter()
        drains = [asyncio.create_task(_drain_raw(c, raw_msgs))
                  for c in clients]
        for _ in range(raw_msgs // 2):
            await clients[0].send_broadcast_message([0], payload)
            await clients[1].send_broadcast_message([0], payload)
        await asyncio.gather(*drains)
        dt = time.perf_counter() - t0
        emit("configs3/device_mesh_frame_delivery", raw_msgs * 16 / dt,
             "frames/s", msgs=raw_msgs, brokers=8, frame=1024,
             host_links=0, steps=cluster.group.steps)
        for c in clients:
            c.close()
    finally:
        await cluster.stop()


async def bench_route_cutthrough(msgs: int):
    """Single-broker decoded-forwarding headline with the cut-through
    plane A/B (ISSUE 3): one publisher fanning 512 B broadcasts to 8
    subscribers through a real injected broker, counted at the receivers'
    drain — the SAME measurement loop ``benches/route_bench.py`` runs in
    depth (shared in ``pushcdn_tpu.testing.routebench``). One row per
    implementation so the headline tracks the cut-through flag; a host
    without the native kernel emits a skipped row, never a mislabeled
    scalar-vs-scalar 'A/B'."""
    from pushcdn_tpu.testing.routebench import forward_rate

    for impl in ("native", "python"):
        res = await forward_rate(impl, receivers=8, msgs=msgs, trials=3)
        if res is None:
            emit("configs1/route_cutthrough", 0, "skipped", impl=impl,
                 reason="native route-plan kernel unavailable")
            continue
        emit("configs1/route_cutthrough", res["median"], "msgs/s",
             impl=impl, receivers=8, msgs=res["msgs"],
             payload=res["payload"],
             delivered_msgs_s=round(res["delivered"], 1),
             trials=[round(r, 1) for r in res["trials"]])


async def bench_route_churn(msgs: int, parked_users: int):
    """Forwarding under sustained subscribe churn (ISSUE 7): the same
    8-receiver loop with ``parked_users`` extra subscriptions inflating
    the interest table and a churner connection flooding
    Subscribe/Unsubscribe throughout — one row per route-state
    maintenance mode (incremental in-place deltas vs the pre-ISSUE-7
    rebuild-guard baseline), so the headline tracks the control-plane
    regression surface the same way route_cutthrough tracks the data
    plane."""
    from pushcdn_tpu.testing.routebench import forward_rate

    for mode, inc in (("incremental", True), ("rebuild", False)):
        res = await forward_rate(
            "native", receivers=8, msgs=msgs, trials=3,
            parked_users=parked_users, churn=True, incremental=inc)
        if res is None:
            emit("configs1/route_churn", 0, "skipped", mode=mode,
                 reason="native route-plan kernel unavailable")
            return
        summary = res.get("route_summary") or {}
        emit("configs1/route_churn", res["median"], "msgs/s",
             impl="native", mode=mode, receivers=8, msgs=res["msgs"],
             parked_users=parked_users,
             churn_ops_s=round(res["churn_ops_s"], 1),
             deltas_applied=summary.get("deltas_applied"),
             rebuilds=summary.get("rebuilds"),
             trials=[round(r, 1) for r in res["trials"]])


async def amain(quick: bool):
    from pushcdn_tpu.bin.common import tune_gc
    tune_gc()  # the binaries' server GC tuning; see bin/common.py
    # The Memory transport's conformance default window is the reference's
    # 8 KiB duplex constant — test-infra parity, and at 1 KiB frames it caps
    # every read chunk (and therefore every batch through the edge pump) at
    # ~7 frames. Benches model the production edge (TCP with ~256 KiB kernel
    # buffers), so widen it for the duration of the run and restore after —
    # anything else importing this module must keep the 8 KiB parity.
    prev_window = Memory.set_duplex_window(256 * 1024)
    try:
        await bench_route_cutthrough(msgs=2_000 if quick else 10_000)
        await bench_route_churn(msgs=1_500 if quick else 6_000,
                                parked_users=1_500 if quick else 8_000)
        await bench_two_broker_fanout(msgs=100 if quick else 500)
        await bench_topic_pubsub(per_topic=16 if quick else 64,
                                 rounds=20 if quick else 100)
        await bench_eight_broker_mesh(msgs=100 if quick else 400)
        await bench_eight_broker_device_mesh(
            msgs=100 if quick else 400,
            tput_msgs=1000 if quick else 6000)
    finally:
        Memory.set_duplex_window(prev_window)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--out-json", default=None, metavar="PATH",
                    help="merge this run's rows into a machine-readable "
                         "bench file (e.g. BENCH_r09.json); shares the "
                         "file with benches/route_bench.py --out-json")
    args = ap.parse_args()
    asyncio.run(amain(args.quick))
    if args.out_json:
        # one section key per producer; route_bench's section (and any
        # other) is preserved — the bench trajectory file stops being
        # hand-curated
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from route_bench import write_bench_json
        headline = {}
        for row in RESULTS:
            if row["bench"] == "configs1/route_cutthrough" \
                    and row.get("unit") == "msgs/s":
                headline["route_cutthrough_msgs_s"] = row["value"]
            if row["bench"] == "configs1/route_churn" \
                    and row.get("unit") == "msgs/s" \
                    and row.get("mode") == "incremental":
                headline["route_churn_msgs_s"] = row["value"]
            if row["bench"] == "configs1/auth_handshake_warm":
                headline["auth_handshake_warm_ms"] = row["value"]
        write_bench_json(args.out_json, "configs_bench", headline, RESULTS)


if __name__ == "__main__":
    main()
