#!/usr/bin/env python
"""HotShot-consensus-shaped traffic replay through the device router
(BASELINE.json configs[4]: "HotShot-consensus traffic replay, 10k validator
keys, full-pod broadcast").

The reference exists to carry HotShot consensus traffic: per view, a leader
broadcasts a proposal to every validator (the `Global` topic), validators
send votes as direct messages to the next leader, and a DA committee
exchanges data-availability traffic on the `DA` topic. This bench
synthesizes that shape — 10k validator slots, view-by-view — and replays
it through the single-chip routing step, measuring consensus messages
routed per second.

Usage: python benches/consensus_replay.py [--views 50] [--validators 10000]
Prints one JSON line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from pushcdn_tpu.parallel.crdt import CrdtState
from pushcdn_tpu.parallel.router import (
    IngressBatch,
    RouterState,
    routing_step,
)
from pushcdn_tpu.proto.message import KIND_BROADCAST, KIND_DIRECT

TOPIC_GLOBAL, TOPIC_DA = 0, 1
FRAME = 512           # proposal/vote frames are small
DA_COMMITTEE = 64     # parity with the 4×64 topic config shape


def build_view_batch(view: int, validators: int, slots: int,
                     rng: np.random.Generator) -> IngressBatch:
    """One consensus view's ingress: 1 proposal broadcast + `validators`
    votes (direct to the next leader) + DA chatter, padded to `slots`."""
    leader = (view + 1) % validators
    frame_bytes = rng.integers(0, 256, (slots, FRAME)).astype(np.uint8)
    kind = np.zeros(slots, np.int32)
    length = np.full(slots, FRAME, np.int32)
    topic_mask = np.zeros(slots, np.uint32)
    dest = np.full(slots, -1, np.int32)
    valid = np.zeros(slots, bool)

    # proposal: full-pod broadcast on Global
    kind[0] = KIND_BROADCAST
    topic_mask[0] = 1 << TOPIC_GLOBAL
    valid[0] = True
    # DA proposal on the DA topic
    kind[1] = KIND_BROADCAST
    topic_mask[1] = 1 << TOPIC_DA
    valid[1] = True
    # votes: direct to next leader (as many as fit this batch)
    nvotes = min(validators, slots - 2)
    kind[2:2 + nvotes] = KIND_DIRECT
    dest[2:2 + nvotes] = leader
    valid[2:2 + nvotes] = True

    return IngressBatch(
        jnp.asarray(frame_bytes), jnp.asarray(kind), jnp.asarray(length),
        jnp.asarray(topic_mask), jnp.asarray(dest), jnp.asarray(valid))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--views", type=int, default=50)
    ap.add_argument("--validators", type=int, default=10_000)
    ap.add_argument("--slots", type=int, default=16384,
                    help="ingress slots per step (default fits a whole "
                         "10k-validator view: proposal + DA + every vote)")
    args = ap.parse_args()

    V = args.validators
    # every validator subscribes Global; the DA committee also subscribes DA
    masks = np.full(V, 1 << TOPIC_GLOBAL, np.uint32)
    masks[:DA_COMMITTEE] |= 1 << TOPIC_DA
    state = RouterState(
        crdt=CrdtState(
            owners=jnp.zeros(V, jnp.int32),
            versions=jnp.ones(V, jnp.uint32),
            identities=jnp.zeros(V, jnp.int32)),
        topic_masks=jnp.asarray(masks))

    rng = np.random.default_rng(0)
    batches = [build_view_batch(v, V, args.slots, rng)
               for v in range(min(args.views, 8))]  # reuse shapes, rotate

    # Every view's delivery matrix is consumed ON DEVICE, INSIDE ONE jit:
    # the full-matrix reduction sits in the timed accumulator's dependency
    # cone (no backend can elide it — the final count is asserted against
    # the exact expected value below), and single-jit fusion means XLA
    # never materializes the [slots, V] matrix between kernels. Both
    # earlier shapes were honest but artifact-bound on the tunneled
    # backend: a separate consume jit — and even a fused jit that called
    # the JITTED routing_step_single, since jit-in-jit is not inlined
    # there — shipped the ~164 MB matrix through the tunnel every view
    # (~38 ms/view of transfer, not routing; BASELINE.md round-4 note).
    # Calling the unjitted routing_step keeps the whole view one program.
    @jax.jit
    def fused_view(state, batch, acc):
        result = routing_step(state, batch, jnp.int32(0), axis_name=None)
        return result.state, acc + result.deliver.sum(dtype=jnp.int32)

    per_batch_msgs = [int(np.asarray(b.valid).sum()) for b in batches]
    # int32 accumulator wrapping mod 2^32 (x64 is off; modular sums are
    # order-independent, so the exact-count check compares mod 2^32 —
    # same pattern as bench.py)
    M32 = 1 << 32
    acc = jnp.zeros((), jnp.int32)
    state, acc = fused_view(state, batches[0], acc)  # compile + warm
    jax.block_until_ready(acc)
    # DELIBERATE host readback before timing — do not remove. The
    # tunneled backend has a deferred-execution mode in which
    # block_until_ready returns BEFORE the work runs (measured: a
    # 500-view loop "completes" in 21 ms and the first later readback
    # then stalls 21 s paying for all of it — an apparent free 400×).
    # Any pre-timing readback (this int(acc), or per_batch_msgs above)
    # pins the session to eager execution, where block_until_ready is
    # truthful and dt below includes real execution. Recorded so a
    # future round doesn't rediscover the fake speedup (same spirit as
    # the step-size note in BASELINE.md).
    warmup_deliveries = int(acc)

    total_msgs = 0
    t0 = time.perf_counter()
    for v in range(args.views):
        state, acc = fused_view(state, batches[v % len(batches)], acc)
        total_msgs += per_batch_msgs[v % len(batches)]
    jax.block_until_ready(acc)
    dt = time.perf_counter() - t0
    # deliveries per view: proposal -> V validators, DA -> committee,
    # votes -> 1 leader each
    per_view_deliveries = V + DA_COMMITTEE + min(V, args.slots - 2)
    # elision-proof: the accumulated on-device count must equal the
    # closed-form expectation for every timed view (+1 for the warmup)
    expected = ((args.views + 1) * per_view_deliveries) % M32
    measured = int(acc) % M32
    if measured != expected:
        raise SystemExit(
            f"delivery-count mismatch: device accumulated {measured}, "
            f"expected {expected} — the timed cone was not fully forced")

    print(json.dumps({
        "bench": "consensus_replay",
        "validators": V,
        "views": args.views,
        "consensus_msgs_per_sec": round(total_msgs / dt, 1),
        "deliveries_per_sec": round(args.views * per_view_deliveries / dt, 1),
        "views_per_sec": round(args.views / dt, 2),
        "per_view_deliveries": per_view_deliveries,
        "device_count_check": "exact",
    }))


if __name__ == "__main__":
    main()
