#!/usr/bin/env python
"""cProfile attribution for the configs[3] decoded drain (VERDICT r4 #2).

Reproduces exactly the ``configs3/device_mesh_broadcast_fanout`` phase of
``configs_bench.py`` (8-shard device mesh, 16 clients, 1 KiB frames,
2 publishers) with cProfile wrapped around the steady-state drain, then
buckets cumulative time into the four suspects the verdict names: client
decode, event loop machinery, broker egress, and the mesh step.

Usage: python benches/profile_configs3.py [--msgs N] [--raw] [--dump F]
"""

from __future__ import annotations

import argparse
import asyncio
import cProfile
import io
import json
import os
import pstats
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

from pushcdn_tpu.proto.transport.memory import Memory


async def _drain(client, n: int):
    got = 0
    async with asyncio.timeout(60):
        while got < n:
            got += len(await client.receive_messages(n - got))


async def _drain_raw(client, n: int):
    from pushcdn_tpu.proto.transport.base import FrameChunk
    got = 0
    conn = client._connection
    async with asyncio.timeout(60):
        while got < n:
            for item in await conn.recv_frames(n - got):
                got += item.remaining if type(item) is FrameChunk else 1
                item.release()


BUCKETS = {
    "client_decode": ("client/client.py", "proto/message.py",
                      "proto/limiter.py"),
    "transport_pump": ("proto/transport/",),
    "event_loop": ("asyncio/", "selectors.py", "selector_events.py"),
    "broker_egress": ("tasks/senders.py", "native/__init__", "egress"),
    "mesh_step": ("mesh_group.py", "parallel/", "jax/", "jaxlib"),
    "broker_ingress": ("tasks/handlers.py", "tasks/listeners.py",
                       "broker/connections.py"),
}


def bucket_of(path: str) -> str:
    for name, pats in BUCKETS.items():
        if any(p in path for p in pats):
            return name
    return "other"


async def amain(msgs: int, raw: bool, dump: str | None,
                profile: bool = True, trials: int = 1):
    import jax
    jax.config.update("jax_platforms", "cpu")
    from pushcdn_tpu.bin.common import tune_gc
    from pushcdn_tpu.testing.mesh_cluster import MeshCluster
    tune_gc()

    prev_window = Memory.set_duplex_window(256 * 1024)
    cluster = await MeshCluster(
        num_shards=8, ring_slots=1024, frame_bytes=2048,
        batch_window_s=float(os.environ.get("PCFG3_WINDOW", "0.001")),
        devices=jax.devices("cpu"), prefix="pcfg3",
    ).start(form_host_mesh=False)
    try:
        clients = [await cluster.place_client(7000 + i, i % 8, topics=[0])
                   for i in range(16)]
        payload = os.urandom(1024)

        # warmup: compile the step, steady the pumps
        warm = [asyncio.create_task(
            (_drain_raw if raw else _drain)(c, 200)) for c in clients]
        for _ in range(100):
            await clients[0].send_broadcast_message([0], payload)
            await clients[1].send_broadcast_message([0], payload)
        await asyncio.gather(*warm)

        drain = _drain_raw if raw else _drain
        per_client = msgs
        prof = cProfile.Profile() if profile else None
        rates = []
        import gc
        gc_mode = os.environ.get("PCFG3_GC", "off")
        if gc_mode == "refreeze":
            gc.collect(); gc.freeze()
        elif gc_mode == "refreeze_big":
            gc.collect(); gc.freeze(); gc.set_threshold(500_000, 100, 100)
        for trial in range(trials):
            if gc_mode == "off":
                gc.collect()
                gc.disable()
            t0 = time.perf_counter()
            if prof:
                prof.enable()
            drains = [asyncio.create_task(drain(c, per_client))
                      for c in clients]
            for _ in range(msgs // 2):
                await clients[0].send_broadcast_message([0], payload)
                await clients[1].send_broadcast_message([0], payload)
            await asyncio.gather(*drains)
            if prof:
                prof.disable()
            dt = time.perf_counter() - t0
            if gc_mode == "off":
                gc.enable()
            rates.append(per_client * 16 / dt)
        rate = max(rates)
        print(json.dumps({
            "bench": "profile/configs3_drain",
            "mode": "raw" if raw else "decoded",
            "deliveries_per_s": round(rate, 1), "wall_s": round(dt, 3),
            "trials": [round(r, 1) for r in rates],
        }), flush=True)

        for c in clients:
            c.close()
        if not prof:
            return
        st = pstats.Stats(prof)
        total = st.total_tt
        # tottime (self time) attribution per file bucket
        sums: dict = {}
        for (path, _line, fname), (_cc, _nc, tt, _ct, _callers) in \
                st.stats.items():
            sums.setdefault(bucket_of(path), [0.0, []])
            sums[bucket_of(path)][0] += tt
        rows = sorted(sums.items(), key=lambda kv: -kv[1][0])
        print(f"\n== self-time attribution (total {total:.2f}s profiled, "
              f"wall {dt:.2f}s) ==")
        for name, (tt, _) in rows:
            print(f"  {name:16s} {tt:7.2f}s  {tt / total * 100:5.1f}%")

        print("\n== top 25 self-time functions ==")
        out = io.StringIO()
        st.stream = out
        st.sort_stats("tottime").print_stats(25)
        print(out.getvalue())
        if dump:
            prof.dump_stats(dump)
            print(f"profile dumped to {dump}")
    finally:
        await cluster.stop()
        Memory.set_duplex_window(prev_window)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--msgs", type=int, default=4000)
    ap.add_argument("--raw", action="store_true")
    ap.add_argument("--dump")
    ap.add_argument("--noprofile", action="store_true")
    ap.add_argument("--trials", type=int, default=1)
    args = ap.parse_args()
    asyncio.run(amain(args.msgs, args.raw, args.dump,
                      profile=not args.noprofile, trials=args.trials))


if __name__ == "__main__":
    main()
