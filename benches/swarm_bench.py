#!/usr/bin/env python
"""Multi-broker swarm soak (ISSUE 12): elastic membership under load,
measured against REAL OS processes over real TCP.

Topology: SQLite discovery + marshal + 2 brokers, with a pack of worker
processes (:mod:`pushcdn_tpu.testing.clientpack`) hosting the subscriber
swarm and one in-bench publisher streaming per-topic sequence numbers.
The run drives a full membership cycle while the stream is LIVE:

    join (broker2 spawns) -> drain (operator GET /drain on the swarm's
    home broker: every user actively re-homed via typed Migrate frames)
    -> leave (drained broker exits) -> rejoin (fresh process, same
    identity) -> reconnect storm (>=10K full marshal+broker reconnect
    cycles from a separate client pool while the soak stream continues)

Measured, written to ``BENCH_r<N>.json`` (section ``swarm_soak``) and
gated by ``scripts/bench_series.py --gate``:

- aggregate delivered/s before the cycle and during the storm;
- re-home latency p50/p99 (client-observed: Migrate processed -> new
  home live) and the orphan count after the grace window;
- the elastic invariant, measured not assumed: zero delivered-message
  gaps and zero reorders across every migrated subscriber (duplicates
  during the two-home overlap are legal and reported separately);
- storm connection count, rate, and connect-latency percentiles.

The bench exits nonzero if any invariant fails (lost/reordered
deliveries, <99% of users re-homed inside the grace window, or an
orphaned user) — it is the live acceptance for the elastic tentpole.

    python benches/swarm_bench.py --quick          # CI-sized (~1 min)
    python benches/swarm_bench.py                  # full soak, 10K storm
    python benches/swarm_bench.py --json BENCH_r14.json
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from pushcdn_tpu.bin.common import spawn_binary  # noqa: E402

DRAIN_GRACE_S = 2.0


def log(msg: str) -> None:
    print(f"[swarm] {msg}", flush=True)


def http_get_json(port: int, path: str, timeout: float = 10.0):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=timeout) as resp:
            return json.loads(resp.read().decode())
    except (urllib.error.URLError, OSError, ValueError, TimeoutError):
        return None


def wait_ready(port: int, wait_s: float = 20.0) -> bool:
    deadline = time.time() + wait_s
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/readyz", timeout=1.0) as resp:
                if resp.status == 200:
                    return True
        except urllib.error.HTTPError:
            pass
        except (urllib.error.URLError, OSError, TimeoutError):
            pass
        time.sleep(0.1)
    return False


def pick_base_port() -> int:
    while True:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            candidate = s.getsockname()[1]
        if candidate <= 65000 - 200:
            return candidate


def _pctile(sorted_vals, q):
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


class Pack:
    """A clientpack worker process: JSON-line events in a reader thread,
    single-word commands down stdin."""

    def __init__(self, name: str, argv: list, logdir: str):
        self.name = name
        self.events: list = []
        self._cond = threading.Condition()
        env = dict(os.environ)
        env["PYTHONPATH"] = (REPO + os.pathsep + env["PYTHONPATH"]
                             if env.get("PYTHONPATH") else REPO)
        env.setdefault("JAX_PLATFORMS", "cpu")
        self._errlog = open(os.path.join(logdir, f"{name}.log"), "ab")
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "pushcdn_tpu.testing.clientpack", *argv],
            env=env, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=self._errlog, text=True)
        self._reader = threading.Thread(target=self._read, daemon=True)
        self._reader.start()

    def _read(self):
        for line in self.proc.stdout:
            try:
                event = json.loads(line)
            except ValueError:
                continue
            with self._cond:
                self.events.append(event)
                self._cond.notify_all()

    def send(self, cmd: str) -> None:
        try:
            self.proc.stdin.write(cmd + "\n")
            self.proc.stdin.flush()
        except (OSError, ValueError):
            pass

    def wait_event(self, kind: str, timeout: float, after: int = 0):
        """First event of ``kind`` at index >= after, or None."""
        deadline = time.time() + timeout
        with self._cond:
            while True:
                for i in range(after, len(self.events)):
                    if self.events[i].get("event") == kind:
                        return self.events[i]
                left = deadline - time.time()
                if left <= 0:
                    return None
                self._cond.wait(min(left, 0.5))

    def stop(self):
        if self.proc.poll() is None:
            self.proc.kill()
        self._errlog.close()


def mark_all(packs: list, timeout: float = 30.0):
    """Synchronized snapshot across every soak worker: send ``mark``,
    collect one fresh ``mark`` reply each, and merge."""
    starts = [len(p.events) for p in packs]
    for p in packs:
        p.send("mark")
    merged = {"clients": 0, "live": 0, "rehomed": 0, "delivered": 0,
              "unique": 0, "gaps": 0, "reorders": 0, "hard_reconnects": 0,
              "rehome_ms": [], "gap_events": 0, "gap_healed": 0}
    for p, start in zip(packs, starts):
        ev = p.wait_event("mark", timeout, after=start)
        if ev is None:
            raise RuntimeError(f"worker {p.name} never answered mark")
        for k in merged:
            merged[k] += ev[k]
    merged["rehome_ms"].sort()
    return merged


async def publisher_loop(client, topics: int, interval_s: float,
                         seqs: list, stop: asyncio.Event) -> None:
    """Round-robin per-topic sequence stream; a send error retries the
    SAME seq (at-least-once — receivers dedup), so a migration or broker
    exit under the publisher never silently skips a number."""
    from pushcdn_tpu.proto.error import Error
    tick = 0
    while not stop.is_set():
        topic = tick % topics
        payload = seqs[topic].to_bytes(4, "big") + b"swarm"
        try:
            await client.send_broadcast_message([topic], payload)
        except Error:
            await asyncio.sleep(0.2)
            continue  # retry the same seq
        seqs[topic] += 1
        tick += 1
        await asyncio.sleep(interval_s)


async def publisher_drain(client, stop: asyncio.Event) -> None:
    """Keep the publisher's inbound side serviced so a Migrate from a
    draining broker is processed promptly (make-before-break re-home)."""
    from pushcdn_tpu.proto.error import Error
    while not stop.is_set():
        try:
            await client.receive_messages()
        except asyncio.CancelledError:
            raise
        except Error:
            await asyncio.sleep(0.2)


def find_home(broker_metrics: dict, key: bytes):
    """Which broker homes the user with this public key (by the
    /debug/topology mnemonic)? Returns the broker name or None."""
    from pushcdn_tpu.proto.util import mnemonic
    wanted = mnemonic(key)
    for name, port in broker_metrics.items():
        topo = http_get_json(port, "/debug/topology")
        if topo and any(u["key"] == wanted for u in topo["users"]):
            return name
    return None


async def amain(args) -> int:
    from pushcdn_tpu.client.client import Client, ClientConfig
    from pushcdn_tpu.proto.crypto.signature import DEFAULT_SCHEME
    from pushcdn_tpu.proto.transport import Tcp
    from pushcdn_tpu.testing.provenance import provenance

    # io-impl selection rides the env into every child (brokers, the
    # marshal, the client packs — spawn_binary and Pack both inherit
    # os.environ) AND the in-process publisher: the whole soak then runs
    # on one data plane. An explicit uring ask on a kernel that denies it
    # SKIPS the run rather than mislabeling an asyncio soak.
    io_impl = None
    if args.io_impl:
        from pushcdn_tpu.native import uring as nuring
        from pushcdn_tpu.proto.transport import uring as umod
        if args.io_impl == "uring" and not nuring.available():
            log(f"SKIPPED: --io-impl uring requested but io_uring is "
                f"unavailable ({nuring.probe_errname()})")
            return 0
        umod.set_io_impl(args.io_impl)
        io_impl = umod.resolve_io_impl()
        log(f"io-impl: {io_impl} (requested {args.io_impl})")

    logdir = tempfile.mkdtemp(prefix="pushcdn-swarm-")
    db = os.path.join(logdir, "cdn.sqlite")
    bp = args.base_port or pick_base_port()
    metrics = {"broker0": bp + 100, "broker1": bp + 120,
               "broker2": bp + 160, "marshal": bp + 140}
    marshal_ep = f"127.0.0.1:{bp + 50}"
    procs: dict = {}

    def spawn_broker(i: int):
        return spawn_binary(
            "broker",
            "--discovery-endpoint", db,
            "--public-advertise-endpoint", f"127.0.0.1:{bp + i * 2}",
            "--public-bind-endpoint", f"127.0.0.1:{bp + i * 2}",
            "--private-advertise-endpoint", f"127.0.0.1:{bp + i * 2 + 1}",
            "--private-bind-endpoint", f"127.0.0.1:{bp + i * 2 + 1}",
            "--user-transport", "tcp",
            "--metrics-bind-endpoint", f"127.0.0.1:{metrics[f'broker{i}']}",
            # fast membership so join/leave/rejoin are observable in
            # bench time (and a drained broker ages out of placement)
            "--heartbeat-interval", "1", "--membership-ttl", "5",
            env_extra={"PUSHCDN_DRAIN_GRACE_S": str(DRAIN_GRACE_S),
                       "JAX_PLATFORMS": "cpu"},
            log_path=os.path.join(logdir, f"broker{i}.log"))

    packs: list = []
    publisher = None
    stop_pub = asyncio.Event()
    # broker2 joins LATE, after thousands of client sockets have pulled
    # ephemeral ports — hold placeholder binds on its endpoints until
    # spawn time or the join races an ephemeral allocation (seen live:
    # bind EADDRINUSE on the private endpoint)
    reserved = []
    for port in (bp + 4, bp + 5, metrics["broker2"]):
        s_ = socket.socket()
        s_.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s_.bind(("127.0.0.1", port))
        reserved.append(s_)
    try:
        procs["broker0"] = spawn_broker(0)
        procs["broker1"] = spawn_broker(1)
        procs["marshal"] = spawn_binary(
            "marshal",
            "--discovery-endpoint", db,
            "--bind-endpoint", marshal_ep,
            "--metrics-bind-endpoint", f"127.0.0.1:{metrics['marshal']}",
            "--user-transport", "tcp",
            env_extra={"JAX_PLATFORMS": "cpu"},
            log_path=os.path.join(logdir, "marshal.log"))
        for name in ("broker0", "broker1", "marshal"):
            if not await asyncio.to_thread(wait_ready, metrics[name]):
                log(f"FAIL: {name} never became ready")
                return 1
        log(f"cluster up (logs under {logdir})")

        # publisher FIRST: it lands within the first 256 /debug/topology
        # rows of its home broker, so the drain can target the OTHER
        # broker (the swarm, not the publisher, is what we migrate)
        publisher = Client(ClientConfig(
            marshal_endpoint=marshal_ep,
            keypair=DEFAULT_SCHEME.generate_keypair(seed=777_001),
            protocol=Tcp))
        await asyncio.wait_for(publisher.ensure_initialized(), 20.0)

        per_worker = args.soak_clients // args.workers
        for w in range(args.workers):
            packs.append(Pack(f"soak{w}", [
                "--marshal-endpoint", marshal_ep, "--mode", "soak",
                "--clients", str(per_worker),
                "--seed-base", str(90_000 + w * 10_000),
                "--topics", str(args.topics),
                "--settle-s", str(args.settle_s)], logdir))
        total_clients = per_worker * args.workers
        for p in packs:
            if await asyncio.to_thread(
                    p.wait_event, "ready", args.connect_wait_s) is None:
                log(f"FAIL: {p.name} never finished connecting")
                return 1
        log(f"packs ready ({total_clients} subscribers across "
            f"{args.workers} worker processes)")

        seqs = [0] * args.topics
        pub_task = asyncio.create_task(publisher_loop(
            publisher, args.topics, 1.0 / args.publish_rate, seqs, stop_pub))
        pub_drain = asyncio.create_task(publisher_drain(publisher, stop_pub))

        # ---- baseline delivered/s ----
        await asyncio.sleep(2.0)  # interest propagation + first deliveries
        m0, t0 = await asyncio.to_thread(mark_all, packs), time.monotonic()
        await asyncio.sleep(args.baseline_s)
        m1, t1 = await asyncio.to_thread(mark_all, packs), time.monotonic()
        delivered_per_s = (m1["delivered"] - m0["delivered"]) / (t1 - t0)
        log(f"baseline delivered/s: {delivered_per_s:.0f} "
            f"({total_clients} subscribers, {args.publish_rate}/s published)")

        # ---- JOIN: a third broker enters the mesh ----
        for s_ in reserved:
            s_.close()
        reserved.clear()
        procs["broker2"] = spawn_broker(2)
        if not await asyncio.to_thread(wait_ready, metrics["broker2"]):
            log("FAIL: joining broker2 never became ready")
            return 1
        log("join OK (broker2 in placement rotation)")

        # ---- DRAIN: operator /drain on the swarm's home broker ----
        pub_home = await asyncio.to_thread(
            find_home, {"broker0": metrics["broker0"],
                        "broker1": metrics["broker1"]},
            publisher.public_key) or "broker1"
        target = "broker0" if pub_home != "broker0" else "broker1"
        before = await asyncio.to_thread(
            http_get_json, metrics[target], "/debug/topology")
        users_before = before["num_users"] if before else -1
        t_drain = time.monotonic()
        # the stream stays LIVE through the drain: the HTTP call runs
        # in a thread so the publisher keeps ticking mid-migration
        summary = await asyncio.to_thread(
            http_get_json, metrics[target], "/drain", args.grace_s)
        if summary is None:
            log(f"FAIL: {target} /drain did not answer")
            return 1
        log(f"drain summary from {target}: {summary} "
            f"(had {users_before} users)")

        # grace window: every signaled user back live on a new home
        deadline = time.monotonic() + args.grace_s
        final = None
        while time.monotonic() < deadline:
            snap = await asyncio.to_thread(mark_all, packs)
            topo = await asyncio.to_thread(
                http_get_json, metrics[target], "/debug/topology")
            drained_empty = bool(topo) and topo["num_users"] == 0
            if snap["live"] == total_clients \
                    and snap["rehomed"] >= summary["signaled"] \
                    and drained_empty:
                final = snap
                break
            await asyncio.sleep(1.0)
        if final is None:
            final = await asyncio.to_thread(mark_all, packs)
        rehome_s = time.monotonic() - t_drain
        rehomed_pct = (100.0 * final["rehomed"] / max(summary["signaled"], 1))
        orphans = total_clients - final["live"]
        p50 = _pctile(final["rehome_ms"], 0.50) or 0.0
        p99 = _pctile(final["rehome_ms"], 0.99) or 0.0
        log(f"rehome OK: {final['rehomed']}/{summary['signaled']} re-homed "
            f"in {rehome_s:.1f}s (p50 {p50:.0f}ms p99 {p99:.0f}ms), "
            f"orphans {orphans}" if orphans == 0 and rehomed_pct >= 99.0
            else f"rehome DEGRADED: {final['rehomed']}/{summary['signaled']} "
                 f"re-homed, {orphans} orphans after {args.grace_s}s grace")

        # ---- LEAVE: the drained broker exits cleanly ----
        procs[target].send_signal(signal.SIGINT)
        try:
            await asyncio.to_thread(procs[target].wait,
                                    DRAIN_GRACE_S + 10.0)
            log(f"leave OK ({target} exited {procs[target].returncode})")
        except subprocess.TimeoutExpired:
            log(f"FAIL: {target} did not exit after SIGINT")
            return 1

        # ---- REJOIN: fresh process, same identity/endpoints ----
        procs[target] = spawn_broker(int(target[-1]))
        if not await asyncio.to_thread(wait_ready, metrics[target]):
            log(f"FAIL: {target} rejoin never became ready")
            return 1
        log(f"rejoin OK ({target} back in rotation)")

        # ---- RECONNECT STORM while the soak stream continues ----
        storm_packs = []
        per_storm = args.storm_connections // args.workers
        storm_pool = max(args.storm_clients // args.workers, 1)
        s0, st0 = await asyncio.to_thread(mark_all, packs), time.monotonic()
        for w in range(args.workers):
            storm_packs.append(Pack(f"storm{w}", [
                "--marshal-endpoint", marshal_ep, "--mode", "storm",
                "--clients", str(storm_pool),
                "--seed-base", str(200_000 + w * 10_000),
                "--storm-connections", str(per_storm),
                "--connect-concurrency", str(args.storm_concurrency)],
                logdir))
        storm = {"established": 0, "attempts": 0, "sheds": 0}
        conn_p99s = []
        for p in storm_packs:
            res = await asyncio.to_thread(
                p.wait_event, "result", args.storm_wait_s)
            if res is None:
                log(f"FAIL: storm worker {p.name} never finished")
                return 1
            storm["established"] += res["established"]
            storm["attempts"] += res["attempts"]
            storm["sheds"] += res["sheds"]
            conn_p99s.append(res["conn_p99_ms"])
        storm_s = time.monotonic() - st0
        s1 = await asyncio.to_thread(mark_all, packs)
        storm_delivered_per_s = (s1["delivered"] - s0["delivered"]) / (
            time.monotonic() - st0)
        log(f"storm OK: {storm['established']} real reconnects in "
            f"{storm_s:.1f}s ({storm['established'] / storm_s:.0f}/s, "
            f"{storm['attempts']} attempts, {storm['sheds']} sheds, "
            f"conn p99 {max(conn_p99s):.0f}ms); soak stream held "
            f"{storm_delivered_per_s:.0f} delivered/s")

        # ---- wrap up: stop the stream, settle, collect ----
        stop_pub.set()
        pub_task.cancel()
        pub_drain.cancel()
        await asyncio.gather(pub_task, pub_drain, return_exceptions=True)
        for p in packs:
            p.send("finish")
        results = []
        for p in packs:
            res = await asyncio.to_thread(p.wait_event, "result", 60.0)
            if res is None:
                log(f"FAIL: soak worker {p.name} never reported")
                return 1
            results.append(res)
        # the loss figures come from each worker's LIVE client-side gap
        # detector (cdn_client_gap_events / _healed counters), not from
        # post-hoc delivery-log diffing: gaps = holes still open at
        # wrap-up, reorders = holes a late arrival healed
        gaps = sum(r["gaps"] for r in results)
        reorders = sum(r["reorders"] for r in results)
        gap_events = sum(r.get("gap_events", 0) for r in results)
        gap_healed = sum(r.get("gap_healed", 0) for r in results)
        hard = sum(r["hard_reconnects"] for r in results)
        delivered_total = sum(r["delivered"] for r in results)
        unique_total = sum(r["unique"] for r in results)
        dups = delivered_total - unique_total
        log(f"loss check (live gap detector): open gaps {gaps} "
            f"({gap_events} opened, {gap_healed} healed), reorders "
            f"{reorders}, duplicates {dups} (legal), hard reconnects "
            f"{hard}, {delivered_total} delivered / "
            f"{sum(seqs)} published")

        ok = (gaps == 0 and reorders == 0 and orphans == 0
              and rehomed_pct >= 99.0)
        headline = {
            "soak_users": total_clients,
            "delivered_per_s": round(delivered_per_s, 1),
            "storm_delivered_per_s": round(storm_delivered_per_s, 1),
            "rehome_p50_ms": round(p50, 1),
            "rehome_p99_ms": round(p99, 1),
            "rehomed_pct": round(rehomed_pct, 2),
            "orphans": orphans,
            "loss_gaps": gaps,
            "gap_events": gap_events,
            "gap_healed": gap_healed,
            "reorder_violations": reorders,
            "storm_reconnects": storm["established"],
            "storm_conns_per_s": round(storm["established"] / storm_s, 1),
            "storm_conn_p99_ms": round(max(conn_p99s), 1),
        }
        if io_impl is not None:
            headline["io_impl"] = io_impl
        rows = [{"phase": "baseline", "delivered_per_s":
                 round(delivered_per_s, 1)},
                {"phase": "drain", "target": target,
                 "signaled": summary["signaled"],
                 "orphaned_by_broker": summary["orphaned"],
                 "rehomed": final["rehomed"],
                 "rehome_window_s": round(rehome_s, 1),
                 "rehome_ms_count": len(final["rehome_ms"])},
                {"phase": "storm", **{k: v for k, v in storm.items()
                                      if k != "conn_ms"},
                 "duration_s": round(storm_s, 1)},
                {"phase": "wrapup", "delivered_total": delivered_total,
                 "published_total": sum(seqs), "duplicates": dups,
                 "hard_reconnects": hard}]
        if args.json:
            path = os.path.join(REPO, args.json) \
                if not os.path.isabs(args.json) else args.json
            doc = {"round": args.round}
            if os.path.exists(path):
                try:
                    with open(path) as fh:
                        doc = json.load(fh)
                except (OSError, ValueError):
                    pass
            doc["swarm_soak"] = {"headline": headline, "rows": rows,
                                 "provenance": provenance()}
            with open(path, "w") as fh:
                json.dump(doc, fh, indent=1)
                fh.write("\n")
            log(f"wrote {path}")
        if not ok:
            log("FAIL: elastic invariant violated (see above)")
            return 1
        log("OK: join -> drain -> leave -> rejoin -> storm, "
            "zero loss, zero reorders, zero orphans")
        return 0
    finally:
        for s_ in reserved:
            s_.close()
        stop_pub.set()
        if publisher is not None:
            publisher.close()
        for p in packs:
            p.stop()
        for proc in procs.values():
            if proc.poll() is None:
                proc.send_signal(signal.SIGINT)
        deadline = time.time() + DRAIN_GRACE_S + 5.0
        while time.time() < deadline and any(
                p.poll() is None for p in procs.values()):
            time.sleep(0.1)
        for proc in procs.values():
            if proc.poll() is None:
                proc.kill()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="CI-sized run (~1-2 min): small swarm, 200-cycle "
                         "storm")
    ap.add_argument("--soak-clients", type=int, default=None)
    ap.add_argument("--workers", type=int, default=None)
    ap.add_argument("--topics", type=int, default=8)
    ap.add_argument("--publish-rate", type=float, default=None,
                    help="broadcasts/s across all topics")
    ap.add_argument("--storm-connections", type=int, default=None,
                    help="total reconnect cycles across storm workers")
    ap.add_argument("--storm-clients", type=int, default=None,
                    help="distinct users in the storm pool")
    ap.add_argument("--storm-concurrency", type=int, default=25,
                    help="in-flight dials per storm worker")
    ap.add_argument("--baseline-s", type=float, default=None)
    ap.add_argument("--grace-s", type=float, default=None,
                    help="re-home grace window")
    ap.add_argument("--connect-wait-s", type=float, default=None)
    ap.add_argument("--storm-wait-s", type=float, default=None)
    ap.add_argument("--settle-s", type=float, default=2.0)
    ap.add_argument("--base-port", type=int, default=0)
    ap.add_argument("--io-impl", default=None,
                    choices=("auto", "uring", "asyncio"),
                    help="pin the TCP data plane for the whole soak "
                         "(brokers, marshal, packs, publisher); uring on "
                         "a denying kernel SKIPS instead of mislabeling")
    ap.add_argument("--json", default=None, metavar="PATH",
                    help="merge the swarm_soak section into this "
                         "BENCH_r*.json (relative to the repo root)")
    ap.add_argument("--round", type=int, default=16)
    args = ap.parse_args()

    defaults = {
        # full soak: ~1K live subscribers, >=10K-connection storm
        False: dict(soak_clients=1000, workers=4, publish_rate=16.0,
                    storm_connections=10_000, storm_clients=2000,
                    baseline_s=10.0, grace_s=90.0, connect_wait_s=240.0,
                    storm_wait_s=480.0),
        True: dict(soak_clients=60, workers=2, publish_rate=20.0,
                   storm_connections=200, storm_clients=40,
                   baseline_s=5.0, grace_s=45.0, connect_wait_s=90.0,
                   storm_wait_s=180.0),
    }[args.quick]
    for key, val in defaults.items():
        if getattr(args, key) is None:
            setattr(args, key, val)
    return asyncio.run(amain(args))


if __name__ == "__main__":
    sys.exit(main())
