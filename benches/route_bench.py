#!/usr/bin/env python
"""Broker routing hot-path bench: decoded broker-forwarding, scalar vs
cut-through (ISSUE 3 tentpole; the 326K msgs/s round-5 floor is the
scalar decoded-forwarding number this targets at ≥2x).

Three tiers, each one JSON line per implementation (medians of repeated
trials, all trials disclosed — the deployment core is shared, so single
samples lie):

- ``route/plan``: the decode+route+egress-build core, no wire. scalar =
  per-frame ``deserialize`` → prune → interest query → ``EgressBatch``
  clone-appends (exactly the receive loops' per-frame work); native = one
  ``route_plan`` kernel call per chunk + numpy per-peer grouping + the
  zero-copy/gather egress build. This is the kernel's honest A/B.
- ``route/forward``: end-to-end broker forwarding — a real injected
  broker (test harness, Memory transport), one sender fanning Broadcast
  chunks to N subscribed receivers, counted at the receivers' transport
  drain. Includes wire + writer + receiver cost, so the ratio is smaller
  than route/plan's.
- ``route/ratio``: native/python summary per tier.

Usage: python benches/route_bench.py [--quick] [--route-impl auto|native|python]
(--route-impl restricts which implementations run; default both.)
"""

from __future__ import annotations

import argparse
import asyncio
import gc
import json
import os
import statistics
import sys
import time
from typing import Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

RESULTS: list[dict] = []


def emit(name: str, value: float, unit: str, **extra) -> None:
    row = {"bench": name, "value": round(value, 1), "unit": unit, **extra}
    RESULTS.append(row)
    print(json.dumps(row), flush=True)


def _build_chunk(n_frames: int, payload: int, n_topics: int,
                 direct_every: int, seed: int = 7):
    """One FrameChunk-shaped batch: length-delimited buffer + offs/lens.
    Mostly Broadcasts across ``n_topics`` topics, every ``direct_every``-th
    frame a Direct to a known local user."""
    from pushcdn_tpu.proto.message import Broadcast, Direct, serialize
    rng = np.random.default_rng(seed)
    body = bytes(rng.integers(0, 256, payload, dtype=np.uint8))
    frames = []
    for i in range(n_frames):
        if direct_every and i % direct_every == direct_every - 1:
            frames.append(serialize(Direct(b"user-1", body)))
        else:
            frames.append(serialize(Broadcast([int(i) % n_topics], body)))
    buf = bytearray()
    offs, lens = [], []
    for f in frames:
        offs.append(len(buf) + 4)
        lens.append(len(f))
        buf += len(f).to_bytes(4, "big") + f
    return bytes(buf), offs, lens


# ---------------------------------------------------------------------------
# tier 1: decode+route+egress-build, no wire (the kernel A/B)
# ---------------------------------------------------------------------------

async def bench_plan(impls, n_users: int, n_frames: int, trials: int) -> dict:
    from pushcdn_tpu.broker.tasks import cutthrough
    from pushcdn_tpu.broker.tasks.handlers import (
        EgressBatch, route_broadcast, route_direct)
    from pushcdn_tpu.broker.tasks.senders import pre_encode_frames
    from pushcdn_tpu.broker.test_harness import TestDefinition
    from pushcdn_tpu.proto.def_ import no_hook
    from pushcdn_tpu.proto.limiter import Bytes
    from pushcdn_tpu.proto.message import Broadcast, Direct, deserialize

    # 8 subscribers on topic 0 (the fan-out set), the rest parked on the
    # other TEST topic (realistic table size, not hit by the traffic); a
    # peer broker subscribed to topic 0 and owning one remote direct user
    run = await TestDefinition(
        connected_users=[[0]] * 8 + [[1]] * (n_users - 8),
        connected_brokers=[([0], [b"remote-user"])],
    ).run()
    medians: dict = {}
    try:
        broker = run.broker
        buf, offs, lens = _build_chunk(n_frames, payload=256, n_topics=1,
                                       direct_every=8)
        results = {}

        if "python" in impls:
            hook = no_hook
            topics = broker.run_def.topics
            rates = []
            for _ in range(trials):
                t0 = time.perf_counter()
                egress = EgressBatch(broker)
                interest_cache: dict = {}
                for o, ln in zip(offs, lens):
                    raw = Bytes(buf[o:o + ln])
                    message = deserialize(raw.data)
                    if hook(b"user-0", message):
                        pass
                    if isinstance(message, Direct):
                        route_direct(broker, message.recipient, raw,
                                     to_user_only=False, egress=egress)
                    elif isinstance(message, Broadcast):
                        pruned, _bad = topics.prune(message.topics)
                        if pruned:
                            route_broadcast(broker, pruned, raw,
                                            to_users_only=False,
                                            egress=egress,
                                            interest_cache=interest_cache)
                    raw.release()
                # egress-build: the flush's per-peer pre-encode (the copy
                # the scalar path pays before the writer), wire excluded
                for frames_l in list(egress.users.values()) \
                        + list(egress.brokers.values()):
                    if len(frames_l) >= 2:
                        pre_encode_frames(frames_l)
                    for f in frames_l:
                        f.release()
                egress.users.clear()
                egress.brokers.clear()
                rates.append(n_frames / (time.perf_counter() - t0))
            results["python"] = rates

        if "native" in impls:
            planner = None
            state = cutthrough.acquire(broker, no_hook)
            if state is not None and state._refresh():
                planner = state.planner
            if planner is None:
                emit("route/plan", 0, "skipped", impl="native",
                     reason="native route-plan kernel unavailable")
            else:
                offs_np = np.asarray(offs, np.int64)
                lens_np = np.asarray(lens, np.int64)
                rates = []
                for _ in range(trials):
                    t0 = time.perf_counter()
                    pos, n = 0, len(offs)
                    built = 0
                    while pos < n:
                        consumed, stop, peers, frames = planner.plan(
                            buf, offs_np, lens_np, pos, 0)
                        # per-peer grouping + egress-build (the same numpy
                        # path _send_plan runs, minus the writer enqueue)
                        if len(peers):
                            order = np.argsort(peers, kind="stable")
                            speers = peers[order]
                            sframes = frames[order]
                            bounds = np.nonzero(np.diff(speers))[0] + 1
                            starts = np.concatenate(([0], bounds))
                            ends = np.concatenate((bounds, [len(speers)]))
                            mv = memoryview(buf)
                            for s, e in zip(starts.tolist(), ends.tolist()):
                                idx = sframes[s:e]
                                first, last = int(idx[0]), int(idx[-1])
                                if last - first + 1 == len(idx):
                                    built += len(
                                        mv[int(offs_np[first]) - 4:
                                           int(offs_np[last])
                                           + int(lens_np[last])])
                                else:
                                    built += len(planner.gather(
                                        buf, offs_np, lens_np, idx))
                        pos += consumed
                        if stop == 1:  # residual (none in this mix)
                            pos += 1
                    rates.append(n_frames / (time.perf_counter() - t0))
                results["native"] = rates

        for impl, rates in results.items():
            med = statistics.median(rates)
            medians[impl] = med
            emit("route/plan", med, "msgs/s", impl=impl,
                 frames=n_frames, users=n_users, payload=256,
                 trials=[round(r, 1) for r in rates],
                 max=round(max(rates), 1))
    finally:
        await run.shutdown()
    return medians


# ---------------------------------------------------------------------------
# tier 3: trace overhead (ISSUE 4) — same forwarding loop, every 1024th
# frame stamped with the lifecycle-trace wire flag (what a publisher at
# the default PUSHCDN_TRACE_SAMPLE=1024 produces). Budget: tracing ON
# within 2% of OFF — traced frames take the instrumented scalar path,
# the other 1023 stay on the batch plan.
# ---------------------------------------------------------------------------

async def bench_profiler_overhead(impl: str, receivers: int, msgs: int,
                                  trials: int, sample: int = 1024,
                                  rounds: int = 3) -> dict:
    """ISSUE 5 budget row: what does turning on THIS PR's additions cost?

    Baseline (``plane=off``): the PR-4 shipped state — tracing at the
    default 1/1024 sample, receivers emitting delivery spans (a real
    client decodes every frame anyway; the span emit is the marginal
    cost) which feed the new ``cdn_e2e_latency_seconds`` histogram.
    Measurement (``plane=on``): the same, plus the task-sampling profiler
    ticking at its default interval. The delta — the profiler + the e2e
    histogram's per-traced-delivery observe — must stay ≤2%.

    A/B rounds are INTERLEAVED (off/on alternating) because a shared
    deployment core drifts over a multi-second bench: back-to-back
    blocks would attribute the drift to whichever side ran last.
    Also runs a denser-sampled pass (1/64) purely to populate the e2e
    latency percentiles for BENCH_r09.json."""
    from pushcdn_tpu.proto import metrics as metrics_mod
    from pushcdn_tpu.testing.routebench import forward_rate
    out: dict = {}
    offs: list = []
    ons: list = []
    skipped = False
    for r in range(rounds):
        for plane in (("off", "on") if r % 2 == 0 else ("on", "off")):
            profiler = None
            if plane == "on":
                # explicit shipped-default interval: the A/B must profile
                # even when the operator env disabled the profiler
                profiler = asyncio.create_task(
                    metrics_mod._task_profiler(0.25))
            try:
                res = await forward_rate(impl, receivers=receivers,
                                         msgs=msgs, trials=trials,
                                         trace_every=sample,
                                         deliver_spans=True)
            finally:
                if profiler is not None:
                    profiler.cancel()
            if res is None:
                skipped = True
                break
            (ons if plane == "on" else offs).append(res["median"])
            gc.collect()
        if skipped:
            break
    if skipped or not offs or not ons:
        emit("route/profiler_overhead", 0, "skipped", impl=impl,
             reason="native route-plan kernel unavailable")
        return out
    off_med = statistics.median(offs)
    on_med = statistics.median(ons)
    emit("route/profiler_overhead", off_med, "msgs/s", impl=impl,
         plane="off", sample=sample, receivers=receivers, msgs=msgs,
         trials=[round(r, 1) for r in offs])
    emit("route/profiler_overhead", on_med, "msgs/s", impl=impl,
         plane="on", sample=sample, receivers=receivers, msgs=msgs,
         trials=[round(r, 1) for r in ons])
    if off_med:
        ratio = on_med / off_med
        # the headline ``value`` rounds to 0.1 — useless against a 2%
        # budget, so the precise delta rides the pct field
        emit("route/profiler_overhead", ratio, "x", impl=impl,
             tier="on-vs-off", pct=round((ratio - 1) * 100, 2))
        out["profiler_overhead_ratio"] = round(ratio, 4)
        out["profiler_overhead_pct"] = round((ratio - 1) * 100, 2)
        out["headline_msgs_s"] = round(on_med, 1)
    # e2e percentile source: denser sampling (stats row, not a rate row)
    e2e = await forward_rate(impl, receivers=receivers,
                             msgs=max(msgs // 2, 1000), trials=1,
                             trace_every=64, deliver_spans=True)
    lats = sorted((e2e or {}).get("e2e_lat_s") or [])
    if lats:
        def pct(q):
            return lats[min(int(q * len(lats)), len(lats) - 1)]
        out["e2e_p50_ms"] = round(pct(0.50) * 1e3, 3)
        out["e2e_p99_ms"] = round(pct(0.99) * 1e3, 3)
        emit("route/e2e_latency", out["e2e_p50_ms"], "ms", impl=impl,
             tier="p50", samples=len(lats))
        emit("route/e2e_latency", out["e2e_p99_ms"], "ms", impl=impl,
             tier="p99", samples=len(lats))
    return out


async def bench_trace_overhead(impl: str, receivers: int, msgs: int,
                               trials: int, sample: int = 1024) -> None:
    from pushcdn_tpu.testing.routebench import forward_rate
    off = await forward_rate(impl, receivers=receivers, msgs=msgs,
                             trials=trials)
    on = await forward_rate(impl, receivers=receivers, msgs=msgs,
                            trials=trials, trace_every=sample)
    if off is None or on is None:
        emit("route/trace_overhead", 0, "skipped", impl=impl,
             reason="native route-plan kernel unavailable")
        return
    emit("route/trace_overhead", off["median"], "msgs/s", impl=impl,
         trace="off", receivers=receivers, msgs=off["msgs"],
         trials=[round(r, 1) for r in off["trials"]])
    emit("route/trace_overhead", on["median"], "msgs/s", impl=impl,
         trace="on", sample=sample, receivers=receivers, msgs=on["msgs"],
         trials=[round(r, 1) for r in on["trials"]])
    if off["median"]:
        emit("route/trace_overhead", on["median"] / off["median"], "x",
             impl=impl, tier="on-vs-off")


# ---------------------------------------------------------------------------
# tier 4 (ISSUE 6): multi-core shard scaling — REAL OS processes over TCP
# ---------------------------------------------------------------------------

def _free_port_block() -> int:
    import socket
    while True:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        if port <= 64000:
            return port


async def _shard_forward_once(shards: int, receivers: int, msgs: int,
                              trials: int, payload: int,
                              batch: int = 64) -> Optional[dict]:
    """One shard-count row: spawn discovery + marshal + ONE broker binary
    (``--shards N``) as real processes, drive 1 sender + R receivers via
    the real client library over TCP, count at the receivers' transport
    drain. ``--shards 1`` is the same-run baseline (byte-for-byte the
    single-process broker)."""
    import signal
    import tempfile

    from pushcdn_tpu.bin.common import keypair_from_seed, spawn_binary
    from pushcdn_tpu.client import Client, ClientConfig
    from pushcdn_tpu.proto.message import Broadcast, serialize
    from pushcdn_tpu.proto.transport.base import FrameChunk
    from pushcdn_tpu.proto.transport.tcp import Tcp

    bp = _free_port_block()
    db = os.path.join(tempfile.mkdtemp(prefix="pushcdn-shardbench-"),
                      "cdn.sqlite")
    procs = []
    clients = []
    try:
        procs.append(spawn_binary(
            "broker",
            "--discovery-endpoint", db,
            "--public-advertise-endpoint", f"127.0.0.1:{bp}",
            "--public-bind-endpoint", f"127.0.0.1:{bp}",
            "--private-advertise-endpoint", f"127.0.0.1:{bp + 1}",
            "--private-bind-endpoint", f"127.0.0.1:{bp + 1}",
            "--user-transport", "tcp", "--broker-transport", "tcp",
            "--shards", str(shards),
            # deterministic round-robin accept spread: receiver i lands on
            # worker i % N (SO_REUSEPORT's hash spread is luck-dependent
            # at 9 connections; the measured data path is identical).
            # capture=False: the bench never drains the pipe, and a
            # blocked log write would wedge the measured processes.
            env_extra={"PUSHCDN_SHARD_ACCEPT": "handoff"}, capture=False))
        procs.append(spawn_binary(
            "marshal",
            "--discovery-endpoint", db,
            "--bind-endpoint", f"127.0.0.1:{bp + 2}",
            "--user-transport", "tcp", capture=False))
        await asyncio.sleep(1.0)

        async def connect(seed: int, topics) -> Client:
            c = Client(ClientConfig(
                marshal_endpoint=f"127.0.0.1:{bp + 2}",
                keypair=keypair_from_seed(seed),
                protocol=Tcp, subscribed_topics=set(topics)))
            async with asyncio.timeout(30):
                while True:
                    try:
                        await c.ensure_initialized()
                        return c
                    except Exception:
                        await asyncio.sleep(0.3)

        for r in range(receivers):
            clients.append(await connect(100 + r, [0]))
        sender = await connect(99, [])
        clients.append(sender)
        await asyncio.sleep(0.7)  # interest deltas settle across shards

        frame = serialize(Broadcast([0], os.urandom(payload)))
        msgs = max(batch, (msgs // batch) * batch)

        async def drain(conn, n):
            got = 0
            async with asyncio.timeout(180):
                while got < n:
                    for item in await conn.recv_frames(n - got):
                        got += item.remaining if type(item) is FrameChunk \
                            else 1
                        item.release()

        rates = []
        for _ in range(trials):
            t0 = time.perf_counter()
            drains = [asyncio.create_task(
                drain(clients[r]._connection, msgs))
                for r in range(receivers)]
            send_conn = sender._connection
            for _ in range(msgs // batch):
                await send_conn.send_raw_many([frame] * batch)
                await asyncio.sleep(0)
            await asyncio.gather(*drains)
            rates.append(msgs / (time.perf_counter() - t0))
        med = statistics.median(rates)
        return {"median": med, "trials": rates, "msgs": msgs,
                "delivered": med * receivers}
    except (asyncio.TimeoutError, Exception) as exc:
        emit("route/shard_forward", 0, "skipped", shards=shards,
             reason=f"harness failed: {exc!r}")
        return None
    finally:
        for c in clients:
            try:
                c.close()
            except Exception:
                pass
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGINT)
        deadline = time.time() + 8.0
        while time.time() < deadline and any(p.poll() is None
                                             for p in procs):
            await asyncio.sleep(0.1)
        for p in procs:
            if p.poll() is None:
                p.kill()


async def bench_shard_scaling(shard_counts, receivers: int, msgs: int,
                              trials: int, payload: int = 512) -> dict:
    """Shard-count rows (1/2/4) for the 8-receiver forwarding figure.
    Labels carry the host's usable core count — on a 1-core container the
    rows are honestly flat; near-linear scaling needs cores >= shards."""
    cpus = len(os.sched_getaffinity(0)) if hasattr(os, "sched_getaffinity") \
        else (os.cpu_count() or 1)
    out: dict = {}
    for n in shard_counts:
        res = await _shard_forward_once(n, receivers, msgs, trials, payload)
        gc.collect()
        if res is None:
            continue
        out[n] = res["median"]
        emit("route/shard_forward", res["median"], "msgs/s", shards=n,
             receivers=receivers, msgs=res["msgs"], payload=payload,
             delivered_msgs_s=round(res["delivered"], 1), cpus=cpus,
             backend="cpu",
             trials=[round(r, 1) for r in res["trials"]])
    base = out.get(1)
    if base:
        for n, med in out.items():
            if n != 1:
                emit("route/shard_forward", med / base, "x",
                     tier=f"shards{n}-vs-1", cpus=cpus,
                     note=("scaling requires cores >= shards; "
                           f"this host has {cpus}"))
    return {f"shard{n}_msgs_s": round(v, 1) for n, v in out.items()}


# ---------------------------------------------------------------------------
# tier 2: end-to-end broker forwarding through the wire
# ---------------------------------------------------------------------------

async def bench_forward(impl: str, receivers: int, msgs: int,
                        trials: int) -> Optional[float]:
    # the measurement loop lives in pushcdn_tpu.testing.routebench so the
    # configs_bench headline row and bench.py's companion host row track
    # the SAME loop (no drifting copies)
    from pushcdn_tpu.testing.routebench import forward_rate
    res = await forward_rate(impl, receivers=receivers, msgs=msgs,
                             trials=trials)
    if res is None:
        emit("route/forward", 0, "skipped", impl=impl,
             reason="native route-plan kernel unavailable")
        return None
    emit("route/forward", res["median"], "msgs/s", impl=impl,
         receivers=receivers, msgs=res["msgs"], payload=res["payload"],
         delivered_msgs_s=round(res["delivered"], 1),
         trials=[round(r, 1) for r in res["trials"]],
         max=round(max(res["trials"]), 1))
    return res["median"]


async def amain(quick: bool, impl_arg: str,
                out_json: Optional[str] = None,
                shard_rows: Optional[str] = None) -> None:
    from pushcdn_tpu.bin.common import tune_gc
    tune_gc()
    impls = ("native", "python") if impl_arg == "auto" else (impl_arg,)

    plan_medians = await bench_plan(
        impls, n_users=64, n_frames=2048 if quick else 8192,
        trials=3 if quick else 5)
    if "native" in plan_medians and "python" in plan_medians \
            and plan_medians["python"]:
        emit("route/ratio", plan_medians["native"] / plan_medians["python"],
             "x", tier="plan")

    fwd: dict = {}
    for impl in impls:
        fwd[impl] = await bench_forward(
            impl, receivers=8, msgs=2_000 if quick else 10_000,
            trials=2 if quick else 3)
        gc.collect()
    if fwd.get("native") and fwd.get("python"):
        emit("route/ratio", fwd["native"] / fwd["python"], "x",
             tier="forward")

    # trace-overhead A/B on the primary deployment path (native when it
    # compiled here; otherwise the scalar loops get the same row so the
    # budget is still tracked)
    from pushcdn_tpu.native import routeplan
    trace_impl = "native" if ("native" in impls
                              and routeplan.available()) else "python"
    await bench_trace_overhead(
        trace_impl, receivers=8, msgs=2_000 if quick else 10_000,
        trials=2 if quick else 3)

    # ISSUE 5: whole-observability-plane overhead (profiler + tracing +
    # e2e histogram) under the same ≤2% budget, plus e2e percentiles
    stats = await bench_profiler_overhead(
        trace_impl, receivers=8, msgs=2_000 if quick else 10_000,
        trials=2 if quick else 3)

    # ISSUE 6: multi-core shard scaling rows (real OS processes over TCP)
    if shard_rows != "none":
        counts = [int(x) for x in
                  (shard_rows or ("1,2" if quick else "1,2,4")).split(",")]
        stats.update(await bench_shard_scaling(
            counts, receivers=8, msgs=1_500 if quick else 6_000,
            trials=2 if quick else 3))

    if out_json:
        write_bench_json(out_json, "route_bench", stats, RESULTS)


def write_bench_json(path: str, section: str, headline: dict,
                     rows: list) -> None:
    """Merge this run's rows into a machine-readable bench trajectory
    file (``BENCH_r09.json``) — the per-round artifacts stop being
    hand-curated. Each producer owns one section key; a pre-existing
    file's other sections are preserved."""
    doc: dict = {}
    if os.path.exists(path):
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            doc = {}
    doc.setdefault("round", 10)
    doc[section] = {"headline": headline, "rows": rows}
    with open(path, "w") as fh:
        json.dump(doc, fh, indent=1)
        fh.write("\n")
    print(f"wrote {path} [{section}]", file=sys.stderr)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--route-impl", choices=["auto", "native", "python"],
                    default="auto",
                    help="which routing implementation(s) to bench; "
                         "'auto' runs the native-vs-python A/B")
    ap.add_argument("--out-json", default=None, metavar="PATH",
                    help="merge this run's rows + headline into a "
                         "machine-readable bench file (e.g. BENCH_r10.json)")
    ap.add_argument("--shard-rows", default=None, metavar="N,N,...",
                    help="shard counts for the route/shard_forward tier "
                         "(default 1,2,4; 1,2 with --quick; 'none' skips)")
    args = ap.parse_args()
    asyncio.run(amain(args.quick, args.route_impl, args.out_json,
                      args.shard_rows))


if __name__ == "__main__":
    main()
