#!/usr/bin/env python
"""Broker routing hot-path bench: decoded broker-forwarding, scalar vs
cut-through (ISSUE 3 tentpole; the 326K msgs/s round-5 floor is the
scalar decoded-forwarding number this targets at ≥2x).

Three tiers, each one JSON line per implementation (medians of repeated
trials, all trials disclosed — the deployment core is shared, so single
samples lie):

- ``route/plan``: the decode+route+egress-build core, no wire. scalar =
  per-frame ``deserialize`` → prune → interest query → ``EgressBatch``
  clone-appends (exactly the receive loops' per-frame work); native = one
  ``route_plan`` kernel call per chunk + numpy per-peer grouping + the
  zero-copy/gather egress build. This is the kernel's honest A/B.
- ``route/forward``: end-to-end broker forwarding — a real injected
  broker (test harness, Memory transport), one sender fanning Broadcast
  chunks to N subscribed receivers, counted at the receivers' transport
  drain. Includes wire + writer + receiver cost, so the ratio is smaller
  than route/plan's.
- ``route/ratio``: native/python summary per tier.

Usage: python benches/route_bench.py [--quick] [--route-impl auto|native|python]
(--route-impl restricts which implementations run; default both.)
"""

from __future__ import annotations

import argparse
import asyncio
import gc
import json
import os
import re
import statistics
import sys
import time
from typing import Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

RESULTS: list[dict] = []


def emit(name: str, value: float, unit: str, **extra) -> None:
    row = {"bench": name, "value": round(value, 1), "unit": unit, **extra}
    RESULTS.append(row)
    print(json.dumps(row), flush=True)


def _build_chunk(n_frames: int, payload: int, n_topics: int,
                 direct_every: int, seed: int = 7):
    """One FrameChunk-shaped batch: length-delimited buffer + offs/lens.
    Mostly Broadcasts across ``n_topics`` topics, every ``direct_every``-th
    frame a Direct to a known local user."""
    from pushcdn_tpu.proto.message import Broadcast, Direct, serialize
    rng = np.random.default_rng(seed)
    body = bytes(rng.integers(0, 256, payload, dtype=np.uint8))
    frames = []
    for i in range(n_frames):
        if direct_every and i % direct_every == direct_every - 1:
            frames.append(serialize(Direct(b"user-1", body)))
        else:
            frames.append(serialize(Broadcast([int(i) % n_topics], body)))
    buf = bytearray()
    offs, lens = [], []
    for f in frames:
        offs.append(len(buf) + 4)
        lens.append(len(f))
        buf += len(f).to_bytes(4, "big") + f
    return bytes(buf), offs, lens


# ---------------------------------------------------------------------------
# tier 1: decode+route+egress-build, no wire (the kernel A/B)
# ---------------------------------------------------------------------------

async def bench_plan(impls, n_users: int, n_frames: int, trials: int) -> dict:
    from pushcdn_tpu.broker.tasks import cutthrough
    from pushcdn_tpu.broker.tasks.handlers import (
        EgressBatch, route_broadcast, route_direct)
    from pushcdn_tpu.broker.tasks.senders import pre_encode_frames
    from pushcdn_tpu.broker.test_harness import TestDefinition
    from pushcdn_tpu.proto.def_ import no_hook
    from pushcdn_tpu.proto.limiter import Bytes
    from pushcdn_tpu.proto.message import Broadcast, Direct, deserialize

    # 8 subscribers on topic 0 (the fan-out set), the rest parked on the
    # other TEST topic (realistic table size, not hit by the traffic); a
    # peer broker subscribed to topic 0 and owning one remote direct user
    run = await TestDefinition(
        connected_users=[[0]] * 8 + [[1]] * (n_users - 8),
        connected_brokers=[([0], [b"remote-user"])],
    ).run()
    medians: dict = {}
    try:
        broker = run.broker
        buf, offs, lens = _build_chunk(n_frames, payload=256, n_topics=1,
                                       direct_every=8)
        results = {}

        if "python" in impls:
            hook = no_hook
            topics = broker.run_def.topics
            rates = []
            for _ in range(trials):
                t0 = time.perf_counter()
                egress = EgressBatch(broker)
                interest_cache: dict = {}
                for o, ln in zip(offs, lens):
                    raw = Bytes(buf[o:o + ln])
                    message = deserialize(raw.data)
                    if hook(b"user-0", message):
                        pass
                    if isinstance(message, Direct):
                        route_direct(broker, message.recipient, raw,
                                     to_user_only=False, egress=egress)
                    elif isinstance(message, Broadcast):
                        pruned, _bad = topics.prune(message.topics)
                        if pruned:
                            route_broadcast(broker, pruned, raw,
                                            to_users_only=False,
                                            egress=egress,
                                            interest_cache=interest_cache)
                    raw.release()
                # egress-build: the flush's per-peer pre-encode (the copy
                # the scalar path pays before the writer), wire excluded
                for frames_l in list(egress.users.values()) \
                        + list(egress.brokers.values()):
                    if len(frames_l) >= 2:
                        pre_encode_frames(frames_l)
                    for f in frames_l:
                        f.release()
                egress.users.clear()
                egress.brokers.clear()
                rates.append(n_frames / (time.perf_counter() - t0))
            results["python"] = rates

        if "native" in impls:
            planner = None
            state = cutthrough.acquire(broker, no_hook)
            if state is not None and state._refresh():
                planner = state.planner
            if planner is None:
                emit("route/plan", 0, "skipped", impl="native",
                     reason="native route-plan kernel unavailable")
            else:
                offs_np = np.asarray(offs, np.int64)
                lens_np = np.asarray(lens, np.int64)
                rates = []
                for _ in range(trials):
                    t0 = time.perf_counter()
                    pos, n = 0, len(offs)
                    built = 0
                    while pos < n:
                        consumed, stop, peers, frames = planner.plan(
                            buf, offs_np, lens_np, pos, 0)
                        # per-peer grouping + egress-build (the same numpy
                        # path _send_plan runs, minus the writer enqueue)
                        if len(peers):
                            order = np.argsort(peers, kind="stable")
                            speers = peers[order]
                            sframes = frames[order]
                            bounds = np.nonzero(np.diff(speers))[0] + 1
                            starts = np.concatenate(([0], bounds))
                            ends = np.concatenate((bounds, [len(speers)]))
                            mv = memoryview(buf)
                            for s, e in zip(starts.tolist(), ends.tolist()):
                                idx = sframes[s:e]
                                first, last = int(idx[0]), int(idx[-1])
                                if last - first + 1 == len(idx):
                                    built += len(
                                        mv[int(offs_np[first]) - 4:
                                           int(offs_np[last])
                                           + int(lens_np[last])])
                                else:
                                    built += len(planner.gather(
                                        buf, offs_np, lens_np, idx))
                        pos += consumed
                        if stop == 1:  # residual (none in this mix)
                            pos += 1
                    rates.append(n_frames / (time.perf_counter() - t0))
                results["native"] = rates

        for impl, rates in results.items():
            med = statistics.median(rates)
            medians[impl] = med
            emit("route/plan", med, "msgs/s", impl=impl,
                 frames=n_frames, users=n_users, payload=256,
                 trials=[round(r, 1) for r in rates],
                 max=round(max(rates), 1))
    finally:
        await run.shutdown()
    return medians


# ---------------------------------------------------------------------------
# tier 3: trace overhead (ISSUE 4) — same forwarding loop, every 1024th
# frame stamped with the lifecycle-trace wire flag (what a publisher at
# the default PUSHCDN_TRACE_SAMPLE=1024 produces). Budget: tracing ON
# within 2% of OFF — traced frames take the instrumented scalar path,
# the other 1023 stay on the batch plan.
# ---------------------------------------------------------------------------

async def bench_profiler_overhead(impl: str, receivers: int, msgs: int,
                                  trials: int, sample: int = 1024,
                                  rounds: int = 3) -> dict:
    """ISSUE 5 budget row: what does turning on THIS PR's additions cost?

    Baseline (``plane=off``): the PR-4 shipped state — tracing at the
    default 1/1024 sample, receivers emitting delivery spans (a real
    client decodes every frame anyway; the span emit is the marginal
    cost) which feed the new ``cdn_e2e_latency_seconds`` histogram.
    Measurement (``plane=on``): the same, plus the task-sampling profiler
    ticking at its default interval. The delta — the profiler + the e2e
    histogram's per-traced-delivery observe — must stay ≤2%.

    A/B rounds are INTERLEAVED (off/on alternating) because a shared
    deployment core drifts over a multi-second bench: back-to-back
    blocks would attribute the drift to whichever side ran last.
    Also runs a denser-sampled pass (1/64) purely to populate the e2e
    latency percentiles for BENCH_r09.json."""
    from pushcdn_tpu.proto import metrics as metrics_mod
    from pushcdn_tpu.testing.routebench import forward_rate
    out: dict = {}
    offs: list = []
    ons: list = []
    skipped = False
    for r in range(rounds):
        for plane in (("off", "on") if r % 2 == 0 else ("on", "off")):
            profiler = None
            if plane == "on":
                # explicit shipped-default interval: the A/B must profile
                # even when the operator env disabled the profiler
                profiler = asyncio.create_task(
                    metrics_mod._task_profiler(0.25))
            try:
                res = await forward_rate(impl, receivers=receivers,
                                         msgs=msgs, trials=trials,
                                         trace_every=sample,
                                         deliver_spans=True)
            finally:
                if profiler is not None:
                    profiler.cancel()
            if res is None:
                skipped = True
                break
            (ons if plane == "on" else offs).append(res["median"])
            gc.collect()
        if skipped:
            break
    if skipped or not offs or not ons:
        emit("route/profiler_overhead", 0, "skipped", impl=impl,
             reason="native route-plan kernel unavailable")
        return out
    off_med = statistics.median(offs)
    on_med = statistics.median(ons)
    emit("route/profiler_overhead", off_med, "msgs/s", impl=impl,
         plane="off", sample=sample, receivers=receivers, msgs=msgs,
         trials=[round(r, 1) for r in offs])
    emit("route/profiler_overhead", on_med, "msgs/s", impl=impl,
         plane="on", sample=sample, receivers=receivers, msgs=msgs,
         trials=[round(r, 1) for r in ons])
    if off_med:
        ratio = on_med / off_med
        # the headline ``value`` rounds to 0.1 — useless against a 2%
        # budget, so the precise delta rides the pct field
        emit("route/profiler_overhead", ratio, "x", impl=impl,
             tier="on-vs-off", pct=round((ratio - 1) * 100, 2))
        out["profiler_overhead_ratio"] = round(ratio, 4)
        out["profiler_overhead_pct"] = round((ratio - 1) * 100, 2)
        out["headline_msgs_s"] = round(on_med, 1)
    # e2e percentile source: denser sampling (stats row, not a rate row)
    e2e = await forward_rate(impl, receivers=receivers,
                             msgs=max(msgs // 2, 1000), trials=1,
                             trace_every=64, deliver_spans=True)
    lats = sorted((e2e or {}).get("e2e_lat_s") or [])
    if lats:
        def pct(q):
            return lats[min(int(q * len(lats)), len(lats) - 1)]
        out["e2e_p50_ms"] = round(pct(0.50) * 1e3, 3)
        out["e2e_p99_ms"] = round(pct(0.99) * 1e3, 3)
        emit("route/e2e_latency", out["e2e_p50_ms"], "ms", impl=impl,
             tier="p50", samples=len(lats))
        emit("route/e2e_latency", out["e2e_p99_ms"], "ms", impl=impl,
             tier="p99", samples=len(lats))
    return out


async def bench_trace_overhead(impl: str, receivers: int, msgs: int,
                               trials: int, sample: int = 1024) -> None:
    from pushcdn_tpu.testing.routebench import forward_rate
    off = await forward_rate(impl, receivers=receivers, msgs=msgs,
                             trials=trials)
    on = await forward_rate(impl, receivers=receivers, msgs=msgs,
                            trials=trials, trace_every=sample)
    if off is None or on is None:
        emit("route/trace_overhead", 0, "skipped", impl=impl,
             reason="native route-plan kernel unavailable")
        return
    emit("route/trace_overhead", off["median"], "msgs/s", impl=impl,
         trace="off", receivers=receivers, msgs=off["msgs"],
         trials=[round(r, 1) for r in off["trials"]])
    emit("route/trace_overhead", on["median"], "msgs/s", impl=impl,
         trace="on", sample=sample, receivers=receivers, msgs=on["msgs"],
         trials=[round(r, 1) for r in on["trials"]])
    if off["median"]:
        emit("route/trace_overhead", on["median"] / off["median"], "x",
             impl=impl, tier="on-vs-off")


# ---------------------------------------------------------------------------
# tier 4 (ISSUE 6): multi-core shard scaling — REAL OS processes over TCP
# ---------------------------------------------------------------------------

def _free_port_block() -> int:
    import socket
    while True:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        if port <= 64000:
            return port


async def _shard_forward_once(shards: int, receivers: int, msgs: int,
                              trials: int, payload: int,
                              batch: int = 64) -> Optional[dict]:
    """One shard-count row: spawn discovery + marshal + ONE broker binary
    (``--shards N``) as real processes, drive 1 sender + R receivers via
    the real client library over TCP, count at the receivers' transport
    drain. ``--shards 1`` is the same-run baseline (byte-for-byte the
    single-process broker)."""
    import signal
    import tempfile

    from pushcdn_tpu.bin.common import keypair_from_seed, spawn_binary
    from pushcdn_tpu.client import Client, ClientConfig
    from pushcdn_tpu.proto.message import Broadcast, serialize
    from pushcdn_tpu.proto.transport.base import FrameChunk
    from pushcdn_tpu.proto.transport.tcp import Tcp

    bp = _free_port_block()
    db = os.path.join(tempfile.mkdtemp(prefix="pushcdn-shardbench-"),
                      "cdn.sqlite")
    procs = []
    clients = []
    try:
        procs.append(spawn_binary(
            "broker",
            "--discovery-endpoint", db,
            "--public-advertise-endpoint", f"127.0.0.1:{bp}",
            "--public-bind-endpoint", f"127.0.0.1:{bp}",
            "--private-advertise-endpoint", f"127.0.0.1:{bp + 1}",
            "--private-bind-endpoint", f"127.0.0.1:{bp + 1}",
            "--user-transport", "tcp", "--broker-transport", "tcp",
            "--shards", str(shards),
            # deterministic round-robin accept spread: receiver i lands on
            # worker i % N (SO_REUSEPORT's hash spread is luck-dependent
            # at 9 connections; the measured data path is identical).
            # capture=False: the bench never drains the pipe, and a
            # blocked log write would wedge the measured processes.
            env_extra={"PUSHCDN_SHARD_ACCEPT": "handoff"}, capture=False))
        procs.append(spawn_binary(
            "marshal",
            "--discovery-endpoint", db,
            "--bind-endpoint", f"127.0.0.1:{bp + 2}",
            "--user-transport", "tcp", capture=False))
        await asyncio.sleep(1.0)

        async def connect(seed: int, topics) -> Client:
            c = Client(ClientConfig(
                marshal_endpoint=f"127.0.0.1:{bp + 2}",
                keypair=keypair_from_seed(seed),
                protocol=Tcp, subscribed_topics=set(topics)))
            async with asyncio.timeout(30):
                while True:
                    try:
                        await c.ensure_initialized()
                        return c
                    except Exception:
                        await asyncio.sleep(0.3)

        for r in range(receivers):
            clients.append(await connect(100 + r, [0]))
        sender = await connect(99, [])
        clients.append(sender)
        await asyncio.sleep(0.7)  # interest deltas settle across shards

        frame = serialize(Broadcast([0], os.urandom(payload)))
        msgs = max(batch, (msgs // batch) * batch)

        async def drain(conn, n):
            got = 0
            async with asyncio.timeout(180):
                while got < n:
                    for item in await conn.recv_frames(n - got):
                        got += item.remaining if type(item) is FrameChunk \
                            else 1
                        item.release()

        rates = []
        for _ in range(trials):
            t0 = time.perf_counter()
            drains = [asyncio.create_task(
                drain(clients[r]._connection, msgs))
                for r in range(receivers)]
            send_conn = sender._connection
            for _ in range(msgs // batch):
                await send_conn.send_raw_many([frame] * batch)
                await asyncio.sleep(0)
            await asyncio.gather(*drains)
            rates.append(msgs / (time.perf_counter() - t0))
        med = statistics.median(rates)
        return {"median": med, "trials": rates, "msgs": msgs,
                "delivered": med * receivers}
    except (asyncio.TimeoutError, Exception) as exc:
        emit("route/shard_forward", 0, "skipped", shards=shards,
             reason=f"harness failed: {exc!r}")
        return None
    finally:
        for c in clients:
            try:
                c.close()
            except Exception:
                pass
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGINT)
        deadline = time.time() + 8.0
        while time.time() < deadline and any(p.poll() is None
                                             for p in procs):
            await asyncio.sleep(0.1)
        for p in procs:
            if p.poll() is None:
                p.kill()


async def bench_shard_scaling(shard_counts, receivers: int, msgs: int,
                              trials: int, payload: int = 512) -> dict:
    """Shard-count rows (1/2/4) for the 8-receiver forwarding figure.
    Labels carry the host's usable core count — on a 1-core container the
    rows are honestly flat; near-linear scaling needs cores >= shards."""
    cpus = len(os.sched_getaffinity(0)) if hasattr(os, "sched_getaffinity") \
        else (os.cpu_count() or 1)
    out: dict = {}
    for n in shard_counts:
        res = await _shard_forward_once(n, receivers, msgs, trials, payload)
        gc.collect()
        if res is None:
            continue
        out[n] = res["median"]
        emit("route/shard_forward", res["median"], "msgs/s", shards=n,
             receivers=receivers, msgs=res["msgs"], payload=payload,
             delivered_msgs_s=round(res["delivered"], 1), cpus=cpus,
             backend="cpu",
             trials=[round(r, 1) for r in res["trials"]])
    base = out.get(1)
    if base:
        for n, med in out.items():
            if n != 1:
                emit("route/shard_forward", med / base, "x",
                     tier=f"shards{n}-vs-1", cpus=cpus,
                     note=("scaling requires cores >= shards; "
                           f"this host has {cpus}"))
    return {f"shard{n}_msgs_s": round(v, 1) for n, v in out.items()}


# ---------------------------------------------------------------------------
# tier 5 (ISSUE 7): forwarding under sustained subscribe churn —
# incremental deltas vs the rebuild-guard baseline, same churn machinery
# ---------------------------------------------------------------------------

async def bench_churn_forward(receivers: int, msgs: int,
                              parked_users: int, trials: int,
                              sample: int = 64) -> dict:
    """The ISSUE 7 acceptance A/B: one broker carrying ``parked_users``
    extra subscriptions (a big interest table) forwards broadcasts while
    a churner floods Subscribe/Unsubscribe. mode=incremental applies
    typed deltas in place; mode=rebuild is the pre-ISSUE-7 baseline
    (full O(users) rebuild behind the churn guard's scalar backoff).
    Also records publish→delivery latency of traced frames under churn
    (aggregated through scripts/trace_report.py --json)."""
    import tempfile

    from pushcdn_tpu.proto import trace as trace_lib
    from pushcdn_tpu.testing.routebench import forward_rate
    out: dict = {}
    results: dict = {}
    spans_dir = tempfile.mkdtemp(prefix="pushcdn-churnspans-")
    for mode, inc in (("incremental", True), ("rebuild", False)):
        spans_path = os.path.join(spans_dir, f"{mode}.jsonl")
        trace_lib._LOG_PATH, trace_lib._log_file = spans_path, None
        try:
            res = await forward_rate(
                "native", receivers=receivers, msgs=msgs, trials=trials,
                parked_users=parked_users, churn=True, incremental=inc,
                trace_every=sample, deliver_spans=True)
        finally:
            if trace_lib._log_file is not None:
                try:
                    trace_lib._log_file.close()
                except Exception:
                    pass
            trace_lib._LOG_PATH, trace_lib._log_file = None, None
        gc.collect()
        if res is None:
            emit("route/churn_forward", 0, "skipped", mode=mode,
                 reason="native route-plan kernel unavailable")
            return out
        results[mode] = res
        summary = res.get("route_summary") or {}
        emit("route/churn_forward", res["median"], "msgs/s",
             impl="native", mode=mode, receivers=receivers,
             msgs=res["msgs"], parked_users=parked_users,
             churn_ops_s=round(res["churn_ops_s"], 1),
             deltas_applied=summary.get("deltas_applied"),
             rebuilds=summary.get("rebuilds"),
             last_delta_apply_s=summary.get("last_delta_apply_s"),
             trials=[round(r, 1) for r in res["trials"]])
        # publish→delivery percentiles under churn, aggregated by the
        # REAL scripts/trace_report.py over the run's span log (the
        # traced frames' delivery-hop latency is measured from the
        # carried publish-time origin)
        report = await run_trace_report_on(spans_path)
        delivery = ((report or {}).get("per_hop") or {}).get("delivery")
        if delivery:
            emit("route/churn_e2e", delivery["p50_ms"], "ms", mode=mode,
                 tier="p50", samples=delivery.get("count"),
                 source="trace_report")
            emit("route/churn_e2e", delivery["p99_ms"], "ms", mode=mode,
                 tier="p99", samples=delivery.get("count"),
                 source="trace_report")
            out[f"churn_e2e_p99_ms_{mode}"] = delivery["p99_ms"]
    inc_med = results["incremental"]["median"]
    reb_med = results["rebuild"]["median"]
    if reb_med:
        ratio = inc_med / reb_med
        emit("route/churn_forward", ratio, "x",
             tier="incremental-vs-rebuild", parked_users=parked_users,
             note="acceptance: >= 2x at the same churn rate")
        out["churn_forward_ratio"] = round(ratio, 2)
    out["churn_forward_msgs_s"] = round(inc_med, 1)
    return out


async def run_trace_report_on(spans_path: str) -> Optional[dict]:
    """Aggregate a spans JSONL through the REAL scripts/trace_report.py
    (the claim 'p99 via trace_report' must run the actual tool)."""
    import subprocess
    script = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "trace_report.py")
    proc = subprocess.run(
        [sys.executable, script, "--json", spans_path],
        capture_output=True, text=True, timeout=120)
    # rc 1 just means "no chain carried every hop" (this harness's
    # receivers emit delivery spans only) — the per-hop stats still hold
    try:
        return json.loads(proc.stdout)
    except ValueError:
        return None


# ---------------------------------------------------------------------------
# tier 6 (ISSUE 7): the synthetic 1M-subscription control-plane harness —
# no sockets, the Connections + RouteState pair driven directly so the
# measured object is route-state maintenance itself
# ---------------------------------------------------------------------------

async def bench_million_subs(quick: bool) -> dict:
    """Scale check for the incremental control plane: ``n_users`` users x
    ~``topics_per_user`` Zipf-skewed topics (~1M subscriptions at full
    size), then (a) subscribe/unsubscribe churn, (b) a reconnect storm
    (2% of users drop + re-add; auth itself is excluded — in production
    those reconnects ride the warm BLS pk cache, see BASELINE round 6),
    (c) a DirectMap merge wave — measuring per-batch delta-apply latency
    (p50/p99), snapshot staleness (mutation -> snapshot current), the
    memory ceiling under the admission limiter (the connection budget
    refuses users past the cap), and event-loop health (max scheduling
    lag of a concurrent ticker must stay under the /healthz budget)."""
    from pushcdn_tpu.broker import connections as connections_mod
    from pushcdn_tpu.broker.admission import AdmissionControl
    from pushcdn_tpu.broker.tasks import cutthrough
    from pushcdn_tpu.native import routeplan
    from pushcdn_tpu.proto import def_ as def_mod
    from pushcdn_tpu.proto import flightrec

    if not routeplan.available():
        emit("route/million", 0, "skipped",
             reason="native route-plan kernel unavailable")
        return {}

    # Zipf sampling WITH replacement dedups to ~15.2 unique topics/user,
    # so 68K users is what actually crosses 1M live subscriptions in the
    # native table (asserted below) — 50K would peak at ~760K
    n_users = 8_000 if quick else 68_000
    topics_per_user = 20
    churn_ops = 2_000 if quick else 20_000
    storm_users = max(n_users // 50, 100)

    class _Conn:
        def __init__(self, rec):
            self.flightrec = rec

        def close(self):
            pass

    class _Broker:
        pass

    rng = np.random.default_rng(7)
    # Zipf-skewed topic popularity over the u8 space (hot topics get the
    # bulk of the 1M subscriptions, like a consensus deployment's vote/
    # proposal topics)
    zipf = 1.0 / np.arange(1, 257)
    zipf /= zipf.sum()
    topic_choices = rng.choice(256, size=(n_users, topics_per_user),
                               p=zipf)

    from pushcdn_tpu.proto.topic import TopicSpace
    broker = _Broker()
    broker.connections = connections_mod.Connections("pub:m/priv:m")
    broker.run_def = def_mod.testing_run_def(
        topics=TopicSpace(valid=frozenset(range(256))))
    broker.device_plane = None
    broker.admission = None
    conns = broker.connections
    rec = flightrec.FlightRecorder("million-harness")  # one shared seat
    conn = _Conn(rec)

    def rss_kib() -> int:
        # current VmRSS, not ru_maxrss: the high-water mark reflects
        # whatever earlier bench tier peaked highest, not this harness
        with open("/proc/self/status") as fh:
            for line in fh:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1])
        return 0

    gc.collect()
    rss0 = rss_kib()
    # peak-tracked: the allocator reuses pages freed by earlier bench
    # tiers, so an end-of-run point sample can under-report (even go
    # negative); the ceiling is judged against the harness's own peak
    rss_peak = {"kib": rss0}

    def rss_note() -> None:
        now = rss_kib()
        if now > rss_peak["kib"]:
            rss_peak["kib"] = now
    # the STATED ceiling the run must fit in (admission budget times a
    # generous per-subscription allowance + fixed slack) — asserted, so
    # a memory regression fails the bench rather than drifting silently
    ceiling_mib = 256 + n_users * topics_per_user * 600 / (1 << 20)
    adm = AdmissionControl(broker)
    adm.max_user_conns = n_users  # the limiter IS the memory ceiling
    loop_lag = {"max": 0.0}
    ticker_stop = False

    async def ticker():
        # the /healthz loop-lag proxy: a sleep(0.01) wakeup that should
        # never be late by more than the health budget (2.0 s default)
        while not ticker_stop:
            t0 = time.perf_counter()
            await asyncio.sleep(0.01)
            late = time.perf_counter() - t0 - 0.01
            if late > loop_lag["max"]:
                loop_lag["max"] = late

    tick_task = asyncio.create_task(ticker())
    try:
        # ---- phase 1: connect the herd (admission-gated) ----
        t0 = time.perf_counter()
        shed = 0
        for i in range(n_users + 200):  # 200 over budget: must be shed
            if adm.admit_user() is not None:
                shed += 1
                continue
            key = b"mu%06d" % i
            conns.add_user(key, conn,
                           [int(t) for t in topic_choices[i % n_users]])
            if i % 2048 == 2047:
                await asyncio.sleep(0)
        connect_s = time.perf_counter() - t0
        total_subs = sum(len(conns.user_topics.get_values_of_key(k))
                         for k in list(conns.users)[:64])  # sample only
        state = cutthrough.RouteState(broker,
                                      routeplan.RoutePlanner.create())
        t0 = time.perf_counter()
        assert state._refresh()
        build_s = time.perf_counter() - t0
        stats = state.planner.stats()
        emit("route/million", stats["live_subs"], "subscriptions",
             tier="build", users=conns.num_users, shed_over_budget=shed,
             connect_s=round(connect_s, 3),
             first_build_s=round(build_s, 3),
             avg_topics_sampled=round(total_subs / 64, 1))
        assert shed == 200, "admission budget must have refused the rest"
        if not quick:
            assert stats["live_subs"] >= 1_000_000, \
                f"full-size harness must cross 1M live subscriptions " \
                f"(got {stats['live_subs']})"
        rss_note()

        # ---- phase 2: subscribe/unsubscribe churn, batched applies ----
        apply_lat: list = []
        # snapshot staleness: oldest unreflected mutation -> snapshot
        # current again (the batch window PLUS the apply, i.e. what a
        # plan call could observe at worst)
        staleness: list = []
        users = list(conns.users.keys())
        t0 = time.perf_counter()
        batch_first_mut = None
        for op in range(churn_ops):
            key = users[int(rng.integers(0, len(users)))]
            t = int(rng.integers(0, 256))
            if batch_first_mut is None:
                batch_first_mut = time.perf_counter()
            if op % 2 == 0:
                conns.subscribe_user_to(key, [t])
            else:
                conns.unsubscribe_user_from(key, [t])
            if op % 16 == 15:  # batched per plan call, like the drain
                ta = time.perf_counter()
                assert state._refresh()
                done = time.perf_counter()
                apply_lat.append(done - ta)
                staleness.append(done - batch_first_mut)
                batch_first_mut = None
                if op % 1024 == 1023:
                    await asyncio.sleep(0)
        churn_s = time.perf_counter() - t0
        rss_note()
        lat = sorted(apply_lat)

        def pct(arr, q):
            return arr[min(int(q * len(arr)), len(arr) - 1)]

        stale = sorted(staleness)
        emit("route/million", round(churn_ops / churn_s, 1), "ops/s",
             tier="churn", batches=len(apply_lat),
             apply_p50_us=round(pct(lat, 0.5) * 1e6, 1),
             apply_p99_us=round(pct(lat, 0.99) * 1e6, 1),
             staleness_p50_us=round(pct(stale, 0.5) * 1e6, 1),
             staleness_p99_us=round(pct(stale, 0.99) * 1e6, 1),
             deltas_applied=state.deltas_applied,
             rebuilds=dict(state.rebuild_counts))

        # ---- phase 3: reconnect storm (drop + re-add 2% of users) ----
        storm = [users[int(i)] for i in
                 rng.integers(0, len(users), size=storm_users)]
        t0 = time.perf_counter()
        for key in storm:
            conns.remove_user(key)
        for j, key in enumerate(storm):
            conns.add_user(key, conn,
                           [int(t) for t in topic_choices[j % n_users]])
            if j % 64 == 63:
                ta = time.perf_counter()
                assert state._refresh()
                apply_lat.append(time.perf_counter() - ta)
        ta = time.perf_counter()
        assert state._refresh()
        storm_catchup_s = time.perf_counter() - ta
        storm_s = time.perf_counter() - t0
        rss_note()
        emit("route/million", round(len(storm) * 2 / storm_s, 1), "ops/s",
             tier="reconnect_storm", storm_users=len(storm),
             catchup_s=round(storm_catchup_s, 4),
             rebuilds=dict(state.rebuild_counts),
             note="auth excluded: production reconnects ride the warm "
                  "BLS pk cache (BASELINE r6)")

        # ---- wrap-up: memory ceiling + loop health ----
        gc.collect()
        rss_note()
        rss_mib = (rss_peak["kib"] - rss0) / 1024
        stats = state.planner.stats()
        ticker_stop = True
        await tick_task
        lag_budget = float(os.environ.get("PUSHCDN_HEALTH_LAG_MAX", "")
                           or 2.0)
        green = loop_lag["max"] < lag_budget
        emit("route/million", round(rss_mib, 1), "MiB",
             tier="memory", users=conns.num_users,
             ceiling_mib=round(ceiling_mib, 1),
             rss_abs_mib=round(rss_peak["kib"] / 1024, 1),
             live_subs=stats["live_subs"],
             index_entries=stats["list_entries"],
             dmap_live=stats["dmap_live"],
             max_loop_lag_ms=round(loop_lag["max"] * 1e3, 2),
             loop_lag_green=green, lag_budget_s=lag_budget)
        assert green, (f"event loop lag {loop_lag['max']:.3f}s breached "
                       f"the {lag_budget}s health budget")
        assert rss_mib < ceiling_mib, \
            f"RSS +{rss_mib:.1f} MiB breached the {ceiling_mib:.0f} MiB " \
            f"stated ceiling"
        return {
            "million_users": conns.num_users,
            "million_subs": stats["live_subs"],
            "million_apply_p99_us": round(pct(lat, 0.99) * 1e6, 1),
            "million_staleness_p99_us": round(pct(stale, 0.99) * 1e6, 1),
            "million_storm_catchup_s": round(storm_catchup_s, 4),
            "million_rss_mib": round(rss_mib, 1),
            "million_rss_ceiling_mib": round(ceiling_mib, 1),
            "million_max_loop_lag_ms": round(loop_lag["max"] * 1e3, 2),
        }
    finally:
        ticker_stop = True
        if not tick_task.done():
            tick_task.cancel()


# ---------------------------------------------------------------------------
# tier 7 (ISSUE 8): the device data plane — dense-vs-ragged delivery A/B
# (CPU twin) + the one-collective fused mesh tick (8-device dryrun)
# ---------------------------------------------------------------------------


def bench_device_delivery(quick: bool) -> dict:
    """Dense delivery-matrix sweep vs ragged paged walk, uniform and
    zipf topic popularity, on the CPU twin (jnp reference kernels — the
    real TPU tunnel is dead, TPU_PROBES_r12.md; rows honestly labeled).

    The timed unit is what egress actually consumes per tick: dense pays
    the U x N kernel PLUS the np.nonzero bool-matrix re-scan; ragged pays
    pack + the page walk + the compact-pair extraction. Interest is a
    steady-state :class:`RaggedInterest` (subscriptions don't churn
    mid-tick), frames draw topics from the same popularity law as
    subscriptions — the zipf rows are the ISSUE 8 acceptance shape
    (skewed fan-out, >= 4K users on the full run)."""
    import jax
    import jax.numpy as jnp

    from pushcdn_tpu.ops.delivery_kernel import delivery_matrix_reference
    from pushcdn_tpu.ops.ragged_delivery import (
        RaggedInterest,
        ragged_delivery_pallas,
        ragged_delivery_reference,
        ragged_pairs,
        ragged_pairs_grouped,
        ragged_to_dense,
    )
    from pushcdn_tpu.parallel.frames import split_mask
    from pushcdn_tpu.proto.message import KIND_BROADCAST

    U = 1024 if quick else 4096
    N = 512 if quick else 2048
    T, W = 256, 8
    topics_per_user = 3
    trials = 3 if quick else 5
    ticks = 2 if quick else 3
    backend = jax.default_backend()
    out: dict = {}

    dense_fn = jax.jit(delivery_matrix_reference)
    ragged_fn = jax.jit(ragged_delivery_reference)

    for popularity in ("uniform", "zipf"):
        rng = np.random.default_rng(11)
        if popularity == "zipf":
            p = 1.0 / np.arange(1, T + 1)
            p /= p.sum()
        else:
            p = np.full(T, 1.0 / T)
        sub = rng.choice(T, size=(U, topics_per_user), p=p)
        masks = np.zeros((U, W), np.uint32)
        mask_ints = []
        for u in range(U):
            m = 0
            for t in sub[u]:
                m |= 1 << int(t)
            mask_ints.append(m)
            masks[u] = split_mask(m, W)
        local = np.ones(U, bool)
        ftopic = rng.choice(T, size=N, p=p)
        kind = np.full(N, KIND_BROADCAST, np.int32)
        tmask = np.zeros((N, W), np.uint32)
        for n in range(N):
            tmask[n] = split_mask(1 << int(ftopic[n]), W)
        dest = np.full(N, -1, np.int32)
        valid = np.ones(N, bool)

        ri = RaggedInterest(T, max_pages=8192)
        for u in range(U):
            ri.set_mask(u, mask_ints[u])
        if ri.overflowed:
            emit("device/delivery", 0, "skipped", popularity=popularity,
                 reason="page pool overflow at bench scale")
            continue

        masks_d, local_d = jnp.asarray(masks), jnp.asarray(local)
        tmask_d, kind_d = jnp.asarray(tmask), jnp.asarray(kind)
        dest_d = jnp.asarray(dest)

        # one equivalence check per popularity before timing anything
        walk = ri.pack(kind, tmask, dest, valid, page_round=64)
        assert not walk.spilled
        dense0 = np.asarray(dense_fn(masks_d, local_d, tmask_d, kind_d,
                                     dest_d))
        out_u, _cnt = ragged_fn(jnp.asarray(walk.pages),
                                jnp.asarray(walk.walk_page),
                                jnp.asarray(walk.walk_frame),
                                local_d, masks_d, tmask_d, kind_d, dest_d)
        got = ragged_to_dense(np.asarray(out_u), walk.walk_frame, U, N)
        assert (got == dense0).all(), "ragged != dense on the bench mix"
        pairs = int(dense0.sum())
        ri.release_transient()

        def dense_tick():
            d = np.asarray(dense_fn(masks_d, local_d, tmask_d, kind_d,
                                    dest_d))
            return np.nonzero(d)  # the egress pair scan the dense path pays

        def ragged_tick(grouped: bool):
            w = ri.pack(kind, tmask, dest, valid, page_round=64)
            ou, _ = ragged_fn(jnp.asarray(w.pages),
                              jnp.asarray(w.walk_page),
                              jnp.asarray(w.walk_frame),
                              local_d, masks_d, tmask_d, kind_d, dest_d)
            if grouped:
                res = ragged_pairs_grouped(np.asarray(ou), w, num_users=U)
            else:
                res = ragged_pairs(np.asarray(ou), w.walk_frame,
                                   num_users=U)
            ri.release_transient()
            return res

        # two ragged rows, labeled by ordering contract: "strict" keeps
        # per-user order identical to the dense plane (the DevicePlane
        # default); "per-topic" is the mask-group-factorized fast path
        # (cross-topic order within a tick relaxed — the opt-in knob)
        meds = {}
        variants = (("dense", None, None),
                    ("ragged", False, "strict"),
                    ("ragged", True, "per-topic"))
        for impl, grouped, order in variants:
            tick = dense_tick if impl == "dense" \
                else (lambda g=grouped: ragged_tick(g))
            tick()  # warm (compile + caches)
            rates = []
            for _ in range(trials):
                t0 = time.perf_counter()
                for _ in range(ticks):
                    tick()
                rates.append(ticks * N / (time.perf_counter() - t0))
            med = statistics.median(rates)
            key = impl if order is None else f"{impl}:{order}"
            meds[key] = med
            extra = {} if order is None else {"order": order}
            emit("device/delivery", med, "msgs/s", impl=impl,
                 popularity=popularity, users=U, frames=N, topics=T,
                 pairs=pairs, backend=backend, mode="cpu-twin",
                 trials=[round(r, 1) for r in rates], **extra)
        if meds.get("dense"):
            for order in ("strict", "per-topic"):
                ratio = meds[f"ragged:{order}"] / meds["dense"]
                emit("device/delivery", ratio, "x",
                     tier=f"ragged-vs-dense-{popularity}", order=order,
                     users=U, backend=backend, mode="cpu-twin")
                suffix = "" if order == "per-topic" else "_strict"
                out[f"delivery_ragged_vs_dense_{popularity}{suffix}"] = \
                    round(ratio, 2)

        # interpreter-mode Pallas row (recorded so the real-chip A/B is
        # one flag away; skipped-not-mislabeled when Pallas can't run)
        if popularity == "zipf":
            try:
                small = min(8, walk.n_walk) or 8
                t0 = time.perf_counter()
                ragged_delivery_pallas(
                    jnp.asarray(walk.pages), jnp.asarray(walk.walk_page[:small]),
                    jnp.asarray(walk.walk_frame[:small]), local_d, masks_d,
                    tmask_d, kind_d, dest_d, interpret=True)
                emit("device/delivery", small / (time.perf_counter() - t0),
                     "walk-entries/s", impl="ragged-pallas-interpret",
                     popularity=popularity, backend=backend, mode="cpu-twin",
                     note="interpreter walks the grid in Python; NOT a "
                          "chip measurement")
            except Exception as exc:
                emit("device/delivery", 0, "skipped",
                     impl="ragged-pallas-interpret",
                     reason=f"pallas unavailable: {exc!r}")
    return out


def bench_mesh_tick(quick: bool) -> dict:
    """The one-collective mesh hop, dryrun: an 8-shard virtual CPU mesh
    runs the fused lane step (one packed all_gather per tick) against the
    per-array schedule, with the collective count ASSERTED from the
    lowered program — the counted one-collective-per-tick invariant.
    Labeled mode=dryrun: virtual devices measure dispatch/fusion shape,
    not ICI."""
    import jax
    import jax.numpy as jnp

    from pushcdn_tpu.parallel import router as router_mod
    from pushcdn_tpu.parallel.crdt import ABSENT, CrdtState
    from pushcdn_tpu.parallel.frames import DirectBuckets, FrameRing
    from pushcdn_tpu.parallel.mesh import make_broker_mesh
    from pushcdn_tpu.parallel.router import (
        DirectIngress,
        IngressBatch,
        RouterState,
        count_collectives,
        make_mesh_lane_step,
    )

    out: dict = {}
    n = 8
    if len(jax.devices()) < n:
        emit("device/mesh_tick", 0, "skipped",
             reason=f"need {n} devices, have {len(jax.devices())}")
        return out
    mesh = make_broker_mesh(n)
    U, S, F, C = 64, 16, 256, 4
    owners = np.full((n, U), ABSENT, np.int32)
    versions = np.zeros((n, U), np.uint32)
    ids = np.full((n, U), ABSENT, np.int32)
    masks = np.zeros((n, U), np.uint32)
    for i in range(n):
        owners[i, i] = i
        versions[i, i] = 1
        ids[i, i] = i
        masks[i, i] = 0b1
    state = RouterState(
        CrdtState(jnp.asarray(owners), jnp.asarray(versions),
                  jnp.asarray(ids)), jnp.asarray(masks))
    parts = []
    for i in range(n):
        ring = FrameRing(slots=S, frame_bytes=F)
        for j in range(S // 2):
            ring.push_broadcast(b"b%d-%d" % (i, j), 0b1)
        parts.append(ring.take_batch())
    batch = IngressBatch(
        *[jnp.asarray(np.stack([getattr(x, f) for x in parts]))
          for f in ("bytes_", "kind", "length", "topic_mask", "dest",
                    "valid")])
    dparts = []
    for i in range(n):
        d = DirectBuckets(n, capacity=C, frame_bytes=F)
        d.push((i + 1) % n, b"d%d" % i, dest_slot=(i + 1) % n)
        dparts.append(d.take_batch())
    direct = DirectIngress(
        *[jnp.asarray(np.stack([getattr(x, f) for x in dparts]))
          for f in ("bytes_", "length", "dest", "valid")])
    live = jnp.ones((n, n), bool)

    trials = 3 if quick else 5
    ticks = 20 if quick else 50
    expected = None
    for label, fused in (("fused", True), ("per-array", False)):
        step = make_mesh_lane_step(mesh, gather_bytes=False, fused=fused)
        lowered = jax.jit(step).lower(state, (batch,), (direct,),
                                      live).as_text()
        collectives = count_collectives(lowered)
        if fused:
            assert collectives == 1, (
                f"fused mesh tick must be exactly ONE collective, "
                f"lowered to {collectives}")
        res = step(state, (batch,), (direct,), live)  # compile + warm
        jax.block_until_ready(res.lanes[0].deliver)
        total = int(np.asarray(res.lanes[0].deliver).sum()) \
            + int(np.asarray(res.direct_lanes[0].deliver).sum())
        if expected is None:
            expected = total
        assert total == expected, "fused and per-array ticks must agree"
        rates = []
        for _ in range(trials):
            t0 = time.perf_counter()
            for _ in range(ticks):
                res = step(state, (batch,), (direct,), live)
            jax.block_until_ready(res.lanes[0].deliver)
            rates.append(ticks / (time.perf_counter() - t0))
        med = statistics.median(rates)
        emit("device/mesh_tick", med, "ticks/s", impl=label,
             collectives=collectives, devices=n, backend="cpu",
             mode="dryrun", deliveries=total,
             trials=[round(r, 1) for r in rates])
        out[f"mesh_tick_{label.replace('-', '_')}_ticks_s"] = round(med, 1)
        out[f"mesh_tick_{label.replace('-', '_')}_collectives"] = collectives
    return out


# ---------------------------------------------------------------------------
# tier 2: end-to-end broker forwarding through the wire
# ---------------------------------------------------------------------------

async def bench_forward(impl: str, receivers: int, msgs: int,
                        trials: int) -> Optional[float]:
    # the measurement loop lives in pushcdn_tpu.testing.routebench so the
    # configs_bench headline row and bench.py's companion host row track
    # the SAME loop (no drifting copies)
    from pushcdn_tpu.testing.routebench import forward_rate
    res = await forward_rate(impl, receivers=receivers, msgs=msgs,
                             trials=trials)
    if res is None:
        emit("route/forward", 0, "skipped", impl=impl,
             reason="native route-plan kernel unavailable")
        return None
    emit("route/forward", res["median"], "msgs/s", impl=impl,
         receivers=receivers, msgs=res["msgs"], payload=res["payload"],
         delivered_msgs_s=round(res["delivered"], 1),
         trials=[round(r, 1) for r in res["trials"]],
         max=round(max(res["trials"]), 1))
    return res["median"]


async def bench_forward_decoded(impl: str, receivers: int, msgs: int,
                                trials: int) -> dict:
    """ISSUE 8 client-receive-residue row: the SAME forwarding loop, but
    receivers drain through the real client batch decode (zero-copy
    payload views) — the application-visible delivered/s, re-measured
    through ``receive_messages``' own code path (BASELINE.md tracks how
    the figure moves vs the transport-count row)."""
    from pushcdn_tpu.testing.routebench import forward_rate
    res = await forward_rate(impl, receivers=receivers, msgs=msgs,
                             trials=trials, client_decode=True)
    if res is None:
        emit("route/forward_decoded", 0, "skipped", impl=impl,
             reason="native route-plan kernel unavailable")
        return {}
    emit("route/forward_decoded", res["median"], "msgs/s", impl=impl,
         receivers=receivers, msgs=res["msgs"], payload=res["payload"],
         decode="receive_messages", zero_copy=True,
         delivered_msgs_s=round(res["delivered"], 1),
         trials=[round(r, 1) for r in res["trials"]],
         max=round(max(res["trials"]), 1))
    return {"forward_decoded_msgs_s": round(res["median"], 1),
            "forward_decoded_delivered_s": round(res["delivered"], 1)}


async def bench_io_plane(quick: bool) -> dict:
    """ISSUE 15 rows: the host I/O data plane A/B (asyncio vs io_uring).

    Four tiers, every uring row honestly skipped when the kernel denies
    io_uring (ENOSYS / seccomp EPERM) instead of mislabeling an asyncio
    run:

    - ``io/probe``: the capability probe itself (CI asserts this row).
    - ``route/forward_tcp``: the route/forward loop with user links over
      real loopback TCP, per io impl — the end-to-end A/B. Routing +
      framing CPU dominates this tier on a shared core, so the ratio
      understates the byte-path win.
    - ``io/stream``: raw RawStream throughput, no broker — the byte
      path itself.
    - ``io/syscalls_per_msg``: counted data-plane syscalls per delivered
      message (LD_PRELOAD interposer in a measurement subprocess; strace
      is absent here and /proc/self/io misses socket ops).
    """
    import subprocess

    from pushcdn_tpu.native import syscount
    from pushcdn_tpu.native import uring as nuring

    stats: dict = {}
    cap = nuring.probe()
    emit("io/probe", max(cap, 0), "bitmask",
         available=nuring.available(),
         zerocopy=nuring.zerocopy_supported(),
         errname=None if nuring.available() else nuring.probe_errname())
    stats["io_uring_available"] = nuring.available()
    impls = ["asyncio"] + (["uring"] if nuring.available() else [])
    if not nuring.available():
        reason = f"io_uring unavailable ({nuring.probe_errname()})"
        for row in ("route/forward_tcp", "io/stream",
                    "io/syscalls_per_kmsg"):
            emit(row, 0, "skipped", io_impl="uring", reason=reason)

    # Every measured tier runs in a FRESH child per impl: an earlier
    # uring run warms the allocator (its ring + pbuf mappings leave
    # reusable pages) and a following asyncio stream run measures up to
    # 2x faster in the same process — subprocess isolation removes the
    # ordering bias. The forwarding child also runs under the
    # LD_PRELOAD interposer, so one run yields both the rate row and
    # the counted syscalls-per-message row (strace is absent here and
    # /proc/self/io misses socket ops).
    lib = syscount.build()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def child(impl: str, extra: list) -> Optional[dict]:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        if lib is not None:
            env["LD_PRELOAD"] = str(lib)
        argv = [sys.executable, "-m", "pushcdn_tpu.testing.routebench",
                "--io-impl", impl, "--trials",
                str(2 if quick else 5)] + extra
        try:
            out = subprocess.run(
                argv, capture_output=True, text=True, timeout=600,
                env=env, cwd=repo).stdout.strip()
            return json.loads(out.splitlines()[-1])
        except (subprocess.SubprocessError, ValueError, IndexError):
            return None

    fwd: dict = {}
    spm: dict = {}
    for impl in impls:
        res = child(impl, ["--receivers", "8",
                           "--msgs", str(1_000 if quick else 4_000)])
        if res is None:
            emit("route/forward_tcp", 0, "skipped", io_impl=impl,
                 reason="measurement child failed")
            continue
        fwd[impl] = res["median"]
        emit("route/forward_tcp", res["median"], "msgs/s", io_impl=impl,
             receivers=res["receivers"], msgs=res["msgs"],
             payload=res["payload"],
             delivered_msgs_s=round(res["delivered"], 1),
             trials=[round(r, 1) for r in res["trials"]])
        if "syscalls_per_msg" in res:
            spm[impl] = res["syscalls_per_msg"]
            emit("io/syscalls_per_kmsg", res["syscalls_per_msg"] * 1e3,
                 "calls/kmsg", io_impl=impl,
                 syscalls={k: v for k, v in res["syscalls"].items() if v})
        elif lib is not None:
            emit("io/syscalls_per_kmsg", 0, "skipped", io_impl=impl,
                 reason="interposer inactive in child")
    if fwd.get("uring") and fwd.get("asyncio"):
        emit("io/ratio", fwd["uring"] / fwd["asyncio"], "x",
             tier="forward_tcp")
        stats["forward_tcp_uring_x"] = round(
            fwd["uring"] / fwd["asyncio"], 2)
    if spm.get("asyncio") and spm.get("uring"):
        emit("io/ratio", spm["asyncio"] / spm["uring"], "x",
             tier="syscalls_per_kmsg")
        stats["syscall_reduction_x"] = round(
            spm["asyncio"] / spm["uring"], 2)

    st: dict = {}
    for impl in impls:
        res = child(impl, ["--stream",
                           "--stream-mb", str(128 if quick else 256)])
        if res is None:
            emit("io/stream", 0, "skipped", io_impl=impl,
                 reason="measurement child failed")
            continue
        st[impl] = res["median"]
        emit("io/stream", res["median"], "MB/s", io_impl=impl,
             write_size=res["write_size"], total_mb=res["total_mb"],
             trials=[round(r, 1) for r in res["trials"]])
    if st.get("uring") and st.get("asyncio"):
        emit("io/ratio", st["uring"] / st["asyncio"], "x", tier="stream")
        stats["stream_uring_x"] = round(st["uring"] / st["asyncio"], 2)
    return stats


async def bench_pump_attribution(quick: bool) -> dict:
    """ISSUE 17 rows: the fused data-plane pump A/B + attribution.

    Both legs run io_uring + the native planner over real loopback TCP
    in fresh measurement children (same isolation rationale as
    :func:`bench_io_plane`), flipping exactly one variable — the pump:

    - ``route/pump_forward``: the 8-receiver forwarding row, pump
      off vs on.  End-to-end on a shared core this UNDERSTATES the
      broker-side win: the bench publisher and all 8 receivers are
      Python on the same core, so their drain cost bounds the rate
      (Amdahl) — which is exactly what the attribution rows below are
      for.
    - ``route/pump_attribution``: counted interpreter call transitions
      per 1k delivered messages (``sys.setprofile`` over one unmeasured
      wave), counted data-plane syscalls per 1k messages (LD_PRELOAD
      interposer), and the pump-hit vs residual-escalation split from
      the route plane's own counters.

    Every row is honestly skipped when the kernel denies io_uring or
    the composition can't engage — never a residual-path run mislabeled
    as a pump run (the measurement child refuses to report a "pump" leg
    whose pump never sent a frame)."""
    import subprocess

    from pushcdn_tpu.native import routeplan, syscount
    from pushcdn_tpu.native import uring as nuring

    stats: dict = {}
    reason = None
    if not nuring.available():
        reason = f"io_uring unavailable ({nuring.probe_errname()})"
    elif not routeplan.available():
        reason = "route-plan kernel unavailable"
    if reason is not None:
        for row in ("route/pump_forward", "route/pump_attribution"):
            emit(row, 0, "skipped", pump="auto", reason=reason)
        stats["pump_engaged"] = False
        return stats

    lib = syscount.build()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def child(pump: str) -> Optional[dict]:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        if lib is not None:
            env["LD_PRELOAD"] = str(lib)
        argv = [sys.executable, "-m", "pushcdn_tpu.testing.routebench",
                "--io-impl", "uring", "--route-impl", "native",
                "--pump", pump, "--receivers", "8", "--transitions",
                "--msgs", str(1_000 if quick else 4_000),
                "--trials", str(2 if quick else 5)]
        try:
            out = subprocess.run(
                argv, capture_output=True, text=True, timeout=600,
                env=env, cwd=repo).stdout.strip()
            return json.loads(out.splitlines()[-1])
        except (subprocess.SubprocessError, ValueError, IndexError):
            return None

    fwd: dict = {}
    for pump in ("off", "auto"):
        res = child(pump)
        if res is None:
            emit("route/pump_forward", 0, "skipped", pump=pump,
                 reason="measurement child failed (or pump never "
                        "engaged)" if pump == "auto"
                 else "measurement child failed")
            continue
        fwd[pump] = res
        label = "off" if pump == "off" else "on"
        emit("route/pump_forward", res["median"], "msgs/s", pump=label,
             io_impl="uring", route_impl="native",
             receivers=res["receivers"], msgs=res["msgs"],
             payload=res["payload"],
             delivered_msgs_s=round(res["delivered"], 1),
             trials=[round(r, 1) for r in res["trials"]])
        if "transitions_per_kmsg" in res:
            emit("route/pump_attribution", res["transitions_per_kmsg"],
                 "transitions/kmsg", pump=label)
        if "syscalls_per_msg" in res:
            emit("route/pump_attribution", res["syscalls_per_msg"] * 1e3,
                 "calls/kmsg", pump=label,
                 syscalls={k: v for k, v in res["syscalls"].items() if v})
    on = fwd.get("auto")
    if on is not None and on.get("pump_summary"):
        ps = on["pump_summary"]
        esc = sum(ps.get("escalations", {}).values())
        hit = ps.get("pump_frames", 0)
        emit("route/pump_attribution",
             hit / max(hit + esc, 1), "hit-ratio",
             pump_frames=hit, escalated_frames=esc,
             escalations=ps.get("escalations", {}),
             plan_calls=ps.get("pump_calls", 0))
        stats["pump_hit_ratio"] = round(hit / max(hit + esc, 1), 4)
        stats["pump_engaged"] = True
    if fwd.get("auto") and fwd.get("off"):
        r = fwd["auto"]["median"] / fwd["off"]["median"]
        emit("route/pump_ratio", r, "x", tier="forward_tcp",
             note="end-to-end on a shared core; bench clients bound "
                  "the rate, see route/pump_attribution")
        stats["pump_forward_x"] = round(r, 2)
        to = fwd["off"].get("transitions_per_kmsg")
        tn = fwd["auto"].get("transitions_per_kmsg")
        if to and tn:
            emit("route/pump_ratio", to / tn, "x",
                 tier="transitions_per_kmsg")
            stats["pump_transition_reduction_x"] = round(to / tn, 2)
    return stats


async def bench_telemetry_overhead(quick: bool) -> dict:
    """ISSUE 19 row: native-telemetry overhead on the PUMPED path.

    ``route/telemetry_overhead`` is the honest cost of the shm stage
    stamps + class accounting the pump pays per run: the same
    8-receiver pumped forwarding child as ``route/pump_forward``, with
    exactly one variable flipped — ``PUSHCDN_NATIVE_TELEMETRY`` (0 =
    no mmap, every C-side observe compiled out behind the null telem
    pointer; 1 = the shipped default). Legs are INTERLEAVED off/on in
    fresh measurement children because a shared core drifts thermally
    over the minutes this takes; each leg's figure is the median of
    its children's medians — 5 pairs in full mode, since single
    same-process draws on this shared core range +-10% (the r17 shard
    tier learned the same lesson) and the real C-side cost per observe
    is nanoseconds. Budget: <= 2% (the observability-plane budget
    every prior overhead row holds to).

    Skips loudly when io_uring / the planner / the pump can't engage —
    an unpumped run measures the Python writer path, where the native
    stamps never execute, and would be a mislabeled 0%."""
    import subprocess

    from pushcdn_tpu.native import routeplan
    from pushcdn_tpu.native import uring as nuring

    stats: dict = {}
    reason = None
    if not nuring.available():
        reason = f"io_uring unavailable ({nuring.probe_errname()})"
    elif not routeplan.available():
        reason = "route-plan kernel unavailable"
    if reason is not None:
        emit("route/telemetry_overhead", 0, "skipped", reason=reason)
        return stats

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def child(telemetry: str) -> Optional[dict]:
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PUSHCDN_NATIVE_TELEMETRY=telemetry)
        argv = [sys.executable, "-m", "pushcdn_tpu.testing.routebench",
                "--io-impl", "uring", "--route-impl", "native",
                "--pump", "auto", "--receivers", "8",
                "--msgs", str(1_000 if quick else 3_000),
                "--trials", str(2 if quick else 3)]
        try:
            out = subprocess.run(
                argv, capture_output=True, text=True, timeout=600,
                env=env, cwd=repo).stdout.strip()
            return json.loads(out.splitlines()[-1])
        except (subprocess.SubprocessError, ValueError, IndexError):
            return None

    legs: dict = {"0": [], "1": []}
    pairs = 2 if quick else 5
    for _ in range(pairs):
        for telemetry in ("0", "1"):  # interleaved: off, on, off, on, ...
            res = child(telemetry)
            if res is not None:
                legs[telemetry].append(res["median"])
    if not (legs["0"] and legs["1"]):
        emit("route/telemetry_overhead", 0, "skipped",
             reason="measurement children failed (or pump never engaged)")
        return stats

    off_med = statistics.median(legs["0"])
    on_med = statistics.median(legs["1"])
    emit("route/telemetry_overhead", off_med, "msgs/s", telemetry="off",
         receivers=8, pump="auto",
         trials=[round(r, 1) for r in legs["0"]])
    emit("route/telemetry_overhead", on_med, "msgs/s", telemetry="on",
         receivers=8, pump="auto",
         trials=[round(r, 1) for r in legs["1"]])
    if on_med:
        ratio = off_med / on_med  # >1 = telemetry costs throughput
        emit("route/telemetry_overhead", ratio, "x",
             overhead_pct=round((ratio - 1) * 100, 2),
             budget_pct=2.0, interleaved_pairs=pairs)
        stats["telemetry_overhead_ratio"] = round(ratio, 4)
        stats["telemetry_overhead_pct"] = round((ratio - 1) * 100, 2)
        stats["telemetry_headline_msgs_s"] = round(on_med, 1)
    return stats


async def bench_audit_overhead(quick: bool) -> dict:
    """ISSUE 20 row: frame-fate ledger overhead on the forwarding path.

    ``route/audit_overhead`` is the cost of the conservation ledger's
    per-decision accounting (queued/fate counters, per-link sent/recv
    tables, the dequeue stamps in the writer) on the same 8-receiver
    forwarding child as ``route/pump_forward``, with exactly one
    variable flipped — ``PUSHCDN_LEDGER`` (0 = every fast-path returns
    before touching a counter; 1 = the shipped default). Legs are
    INTERLEAVED off/on in fresh measurement children (same thermal-
    drift rationale as the telemetry row); each leg's figure is the
    median of its children's medians. Budget: <= 2%, the
    observability-plane budget every prior overhead row holds to."""
    import subprocess

    from pushcdn_tpu.native import uring as nuring

    stats: dict = {}
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    io_impl = "uring" if nuring.available() else "asyncio"

    def child(ledger: str) -> Optional[dict]:
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PUSHCDN_LEDGER=ledger)
        argv = [sys.executable, "-m", "pushcdn_tpu.testing.routebench",
                "--io-impl", io_impl, "--route-impl", "auto",
                "--pump", "auto", "--receivers", "8",
                "--msgs", str(1_000 if quick else 3_000),
                "--trials", str(2 if quick else 3)]
        try:
            out = subprocess.run(
                argv, capture_output=True, text=True, timeout=600,
                env=env, cwd=repo).stdout.strip()
            return json.loads(out.splitlines()[-1])
        except (subprocess.SubprocessError, ValueError, IndexError):
            return None

    legs: dict = {"0": [], "1": []}
    pair_ratios: list = []
    pairs = 2 if quick else 7
    for _ in range(pairs):
        pair: dict = {}
        for ledger in ("0", "1"):  # interleaved: off, on, off, on, ...
            res = child(ledger)
            if res is not None:
                legs[ledger].append(res["median"])
                pair[ledger] = res["median"]
        if "0" in pair and "1" in pair and pair["1"]:
            # back-to-back children see the same thermal/scheduler state,
            # so the per-pair ratio cancels the slow drift that dominates
            # this shared core's minute-scale variance (single-leg medians
            # here range +-20%, an order of magnitude above the real cost)
            pair_ratios.append(pair["0"] / pair["1"])
    if not pair_ratios:
        emit("route/audit_overhead", 0, "skipped",
             reason="measurement children failed")
        return stats

    off_med = statistics.median(legs["0"])
    on_med = statistics.median(legs["1"])
    emit("route/audit_overhead", off_med, "msgs/s", ledger="off",
         receivers=8, io_impl=io_impl,
         trials=[round(r, 1) for r in legs["0"]])
    emit("route/audit_overhead", on_med, "msgs/s", ledger="on",
         receivers=8, io_impl=io_impl,
         trials=[round(r, 1) for r in legs["1"]])
    ratio = statistics.median(pair_ratios)  # >1 = ledger costs throughput
    emit("route/audit_overhead", ratio, "x",
         overhead_pct=round((ratio - 1) * 100, 2),
         budget_pct=2.0, interleaved_pairs=len(pair_ratios),
         pair_ratios=[round(r, 3) for r in pair_ratios])
    stats["audit_overhead_ratio"] = round(ratio, 4)
    stats["audit_overhead_pct"] = round((ratio - 1) * 100, 2)
    stats["audit_headline_msgs_s"] = round(on_med, 1)
    return stats


async def amain(quick: bool, impl_arg: str,
                out_json: Optional[str] = None,
                shard_rows: Optional[str] = None,
                churn_rows: bool = False,
                io_rows: bool = True) -> None:
    from pushcdn_tpu.bin.common import tune_gc
    tune_gc()
    impls = ("native", "python") if impl_arg == "auto" else (impl_arg,)

    # ISSUE 7: the synthetic 1M-subscription control-plane harness runs
    # FIRST — its memory-ceiling row is an RSS delta, and the forwarding
    # tiers below leave gigabytes of freed-but-resident pool pages that
    # allocator reuse would silently absorb the harness's footprint into
    stats: dict = {}
    if churn_rows:
        stats.update(await bench_million_subs(quick))
        gc.collect()

    plan_medians = await bench_plan(
        impls, n_users=64, n_frames=2048 if quick else 8192,
        trials=3 if quick else 5)
    if "native" in plan_medians and "python" in plan_medians \
            and plan_medians["python"]:
        emit("route/ratio", plan_medians["native"] / plan_medians["python"],
             "x", tier="plan")

    fwd: dict = {}
    for impl in impls:
        # 5 full-mode trials: single same-process draws on this shared
        # core range ±10% (BASELINE r12 methodology note) — the r11
        # regression row needs the median to out-vote throttle dips
        fwd[impl] = await bench_forward(
            impl, receivers=8, msgs=2_000 if quick else 10_000,
            trials=2 if quick else 5)
        gc.collect()
    if fwd.get("native") and fwd.get("python"):
        emit("route/ratio", fwd["native"] / fwd["python"], "x",
             tier="forward")

    # ISSUE 8 satellite: the 8-receiver row through the real client
    # decode (zero-copy receive path)
    from pushcdn_tpu.native import routeplan as _routeplan
    dec_impl = "native" if ("native" in impls
                            and _routeplan.available()) else "python"
    stats.update(await bench_forward_decoded(
        dec_impl, receivers=8, msgs=2_000 if quick else 10_000,
        trials=2 if quick else 3))
    gc.collect()

    # ISSUE 15: the host I/O data plane A/B (asyncio vs io_uring) —
    # forwarding over real TCP, the raw byte path, and counted
    # syscalls-per-message
    if io_rows:
        stats.update(await bench_io_plane(quick))
        gc.collect()

    # ISSUE 17: the fused data-plane pump A/B (pump off vs on at
    # io_uring + native planner) with syscall / interpreter-transition
    # attribution
    if io_rows:
        stats.update(await bench_pump_attribution(quick))
        gc.collect()

    # ISSUE 19: native-telemetry overhead A/B on the pumped path
    # (PUSHCDN_NATIVE_TELEMETRY off vs on, interleaved children)
    if io_rows:
        stats.update(await bench_telemetry_overhead(quick))
        gc.collect()

    # ISSUE 20: frame-fate ledger overhead A/B on the forwarding path
    # (PUSHCDN_LEDGER off vs on, interleaved children)
    stats.update(await bench_audit_overhead(quick))
    gc.collect()

    # ISSUE 8: the device data plane — dense-vs-ragged delivery A/B on
    # the CPU twin + the one-collective fused mesh tick (dryrun)
    stats.update(bench_device_delivery(quick))
    gc.collect()
    stats.update(bench_mesh_tick(quick))
    gc.collect()

    # trace-overhead A/B on the primary deployment path (native when it
    # compiled here; otherwise the scalar loops get the same row so the
    # budget is still tracked)
    from pushcdn_tpu.native import routeplan
    trace_impl = "native" if ("native" in impls
                              and routeplan.available()) else "python"
    await bench_trace_overhead(
        trace_impl, receivers=8, msgs=2_000 if quick else 10_000,
        trials=2 if quick else 3)

    # ISSUE 5: whole-observability-plane overhead (profiler + tracing +
    # e2e histogram) under the same ≤2% budget, plus e2e percentiles
    stats.update(await bench_profiler_overhead(
        trace_impl, receivers=8, msgs=2_000 if quick else 10_000,
        trials=2 if quick else 3))

    # ISSUE 7: forwarding under sustained subscribe churn (incremental
    # deltas vs the rebuild-guard baseline; the 1M harness ran first,
    # see above)
    if churn_rows:
        stats.update(await bench_churn_forward(
            receivers=8, msgs=1_500 if quick else 6_000,
            parked_users=1_500 if quick else 8_000,
            trials=2 if quick else 3))
        gc.collect()

    # ISSUE 6: multi-core shard scaling rows (real OS processes over TCP)
    if shard_rows != "none":
        counts = [int(x) for x in
                  (shard_rows or ("1,2" if quick else "1,2,4")).split(",")]
        stats.update(await bench_shard_scaling(
            counts, receivers=8, msgs=1_500 if quick else 6_000,
            trials=2 if quick else 3))

    if out_json:
        write_bench_json(out_json, "route_bench", stats, RESULTS)


def write_bench_json(path: str, section: str, headline: dict,
                     rows: list) -> None:
    """Merge this run's rows into a machine-readable bench trajectory
    file (``BENCH_r09.json``) — the per-round artifacts stop being
    hand-curated. Each producer owns one section key; a pre-existing
    file's other sections are preserved."""
    doc: dict = {}
    if os.path.exists(path):
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            doc = {}
    # the round number rides in the artifact name (BENCH_r18.json -> 18)
    # so a re-run into a new round's file never inherits a stale constant
    m = re.search(r"_r0*(\d+)\.json$", os.path.basename(path))
    doc.setdefault("round", int(m.group(1)) if m else 19)
    from pushcdn_tpu.testing.provenance import provenance
    doc[section] = {"headline": headline, "rows": rows,
                    "provenance": provenance()}
    with open(path, "w") as fh:
        json.dump(doc, fh, indent=1)
        fh.write("\n")
    print(f"wrote {path} [{section}]", file=sys.stderr)


def main() -> None:
    # the mesh-tick dryrun tier needs 8 virtual CPU devices; the flag
    # must land before jax first initializes (all jax imports in this
    # bench are lazy, so here is early enough)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--route-impl", choices=["auto", "native", "python"],
                    default="auto",
                    help="which routing implementation(s) to bench; "
                         "'auto' runs the native-vs-python A/B")
    ap.add_argument("--out-json", default=None, metavar="PATH",
                    help="merge this run's rows + headline into a "
                         "machine-readable bench file (e.g. BENCH_r10.json)")
    ap.add_argument("--shard-rows", default=None, metavar="N,N,...",
                    help="shard counts for the route/shard_forward tier "
                         "(default 1,2,4; 1,2 with --quick; 'none' skips)")
    ap.add_argument("--churn-rows", action="store_true",
                    help="ISSUE 7 tiers: forwarding-under-churn A/B "
                         "(incremental deltas vs the rebuild-guard "
                         "baseline) + the synthetic 1M-subscription "
                         "control-plane harness")
    ap.add_argument("--no-io-rows", action="store_true",
                    help="skip the ISSUE 15 host-I/O (asyncio vs "
                         "io_uring) tiers")
    args = ap.parse_args()
    asyncio.run(amain(args.quick, args.route_impl, args.out_json,
                      args.shard_rows, args.churn_rows,
                      io_rows=not args.no_io_rows))


if __name__ == "__main__":
    main()
