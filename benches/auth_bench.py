#!/usr/bin/env python
"""Own-process marshal handshake benchmark (the production auth shape).

Every in-repo auth number so far came from in-process fixtures where
client, marshal, and brokers share ONE event loop — round 5 attributed
~1.3 ms of its 3.2 ms configs[1] handshake to that fixture floor and
called the own-process number a projection ("verify-bound, ~1.9 ms").
This bench measures it: the marshal runs as its OWN OS process (spawned
`pushcdn_tpu.bin.marshal`, real TCP, real SQLite discovery), and this
process plays N repeat connectors doing the full marshal half of the
handshake (sign timestamp → AuthenticateWithKey → permit response).

Two regimes are reported, p50/p99 each:

- **cold**: a key's FIRST handshake — the marshal's per-public-key
  Miller line-table cache misses and records the pk ladder;
- **warm**: every later handshake by the same key — the cache-hit
  steady state of reconnect storms and elastic-client churn.

Plus an in-process microbench of the native verify itself (plain loop
vs warm cached table) so the handshake delta is attributable, and the
marshal's /metrics cache counters scraped at the end as evidence the
own-process marshal actually served from the cache.

Prints JSON lines like the other benches. Usage:

    python benches/auth_bench.py [--keys 8] [--rounds 25] [--json]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import socket
import statistics
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from pushcdn_tpu.bin.common import spawn_binary  # noqa: E402
from pushcdn_tpu.native import bls  # noqa: E402
from pushcdn_tpu.proto.auth import user as user_auth  # noqa: E402
from pushcdn_tpu.proto.crypto.signature import (  # noqa: E402
    BlsBn254Scheme,
    Ed25519Scheme,
    Namespace,
    _namespaced,
)
from pushcdn_tpu.proto.discovery.base import BrokerIdentifier  # noqa: E402
from pushcdn_tpu.proto.discovery.embedded import Embedded  # noqa: E402
from pushcdn_tpu.proto.transport import Tcp  # noqa: E402


def _free_ports(n: int) -> list:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _pctl(samples, q):
    xs = sorted(samples)
    return xs[min(len(xs) - 1, int(q * len(xs)))]


def _row(metric, samples_ms, extra=None):
    row = {"metric": metric,
           "p50_ms": round(statistics.median(samples_ms), 3),
           "p99_ms": round(_pctl(samples_ms, 0.99), 3),
           "n": len(samples_ms)}
    if extra:
        row.update(extra)
    return row


def verify_microbench(iters: int = 60, cold_keys: int = 10) -> dict:
    """Warm-cached vs plain vs cold single verify, same process (the
    marshal's C-stage floor; the acceptance bar's >=1.25x warm-vs-cold
    figure). Cold is a MEDIAN over ``cold_keys`` distinct first-seen keys
    (miss path: parse + subgroup check + ladder recording + replay);
    warm and plain round-robin the same keys so no single key's locality
    flatters the numbers."""
    ns = Namespace.USER_MARSHAL_AUTH
    probes = []
    for i in range(cold_keys):
        kp = BlsBn254Scheme.generate_keypair(seed=4242 + i)
        msg = b"microbench %d" % i
        probes.append((kp.public_key, _namespaced(ns, msg),
                       BlsBn254Scheme.sign(kp.private_key, ns, msg)))
    bls.pk_cache_clear()
    cold = []
    for pk, raw, sig in probes:
        t0 = time.perf_counter()
        assert bls.verify_cached(pk, raw, sig)
        cold.append((time.perf_counter() - t0) * 1e3)

    def times(fn):
        out = []
        for i in range(iters):
            pk, raw, sig = probes[i % cold_keys]
            t0 = time.perf_counter()
            assert fn(pk, raw, sig)
            out.append((time.perf_counter() - t0) * 1e3)
        return out

    warm = times(bls.verify_cached)
    plain = times(bls.verify)
    warm_med = statistics.median(warm)
    plain_med = statistics.median(plain)
    cold_med = statistics.median(cold)
    return {"metric": "auth/single_verify",
            "cold_p50_ms": round(cold_med, 3),
            "plain_p50_ms": round(plain_med, 3),
            "warm_cached_p50_ms": round(warm_med, 3),
            "warm_vs_plain_speedup": round(plain_med / warm_med, 2),
            "warm_vs_cold_speedup": round(cold_med / warm_med, 2),
            # min-based twin: on the shared single core, scheduler
            # preemption inflates individual samples by whole timeslices;
            # the mins estimate the uncontended C-stage cost
            "cold_min_ms": round(min(cold), 3),
            "plain_min_ms": round(min(plain), 3),
            "warm_cached_min_ms": round(min(warm), 3),
            "min_warm_vs_cold_speedup": round(min(cold) / min(warm), 2),
            "n": iters, "cold_keys": cold_keys}


async def drive_handshakes(endpoint: str, keys: int, rounds: int, scheme):
    """Returns (cold_ms, warm_ms) per-handshake samples. One handshake =
    TCP connect + signed-timestamp auth + permit response + close — the
    complete marshal half of the reference handshake (hop 2, the broker,
    is out of scope: no broker process is running)."""
    keypairs = [scheme.generate_keypair(seed=31_000 + i)
                for i in range(keys)]

    async def one(kp) -> float:
        # same shape as Client._connect_once: sign overlaps the dial
        # (the sleep(0) lets the dial issue its connect syscall first)
        t0 = time.perf_counter()
        dial = asyncio.ensure_future(Tcp.connect(endpoint))
        await asyncio.sleep(0)
        presigned = user_auth.presign_timestamp(scheme, kp)
        conn = await dial
        try:
            await user_auth.authenticate_with_marshal(
                conn, scheme, kp, presigned=presigned)
        finally:
            conn.close()
        return (time.perf_counter() - t0) * 1e3

    # connectivity settle (the marshal just booted): retry the first dial
    for attempt in range(50):
        try:
            conn = await Tcp.connect(endpoint)
            conn.close()
            break
        except Exception:
            await asyncio.sleep(0.2)
    else:
        raise SystemExit("marshal never came up")

    cold = [await one(kp) for kp in keypairs]
    warm = []
    for _ in range(rounds - 1):
        for kp in keypairs:
            warm.append(await one(kp))
    return cold, warm


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--keys", type=int, default=8,
                    help="distinct repeat-connector keypairs")
    ap.add_argument("--rounds", type=int, default=25,
                    help="handshakes per key (first is the cold sample)")
    ap.add_argument("--json", action="store_true",
                    help="JSON rows only (no prose)")
    ap.add_argument("--scheme", default="bls-bn254",
                    choices=["bls-bn254", "ed25519"],
                    help="ed25519 measures the protocol floor (microsecond "
                         "crypto) for attribution of the BLS rows")
    args = ap.parse_args()

    scheme = (BlsBn254Scheme if args.scheme == "bls-bn254"
              else Ed25519Scheme)
    micro = verify_microbench() if scheme is BlsBn254Scheme else None

    db = os.path.join(tempfile.mkdtemp(prefix="pushcdn-authbench-"),
                      "cdn.sqlite")
    marshal_port, metrics_port = _free_ports(2)
    endpoint = f"127.0.0.1:{marshal_port}"

    # a registered (but never dialed) broker so the marshal's least-
    # loaded pick and permit issue succeed — the bench stops at the
    # marshal's permit response, like the reference's bad-connector
    ident = BrokerIdentifier("127.0.0.1:1", "127.0.0.1:2")
    disc = Embedded(db, ident)
    asyncio.run(disc.perform_heartbeat(0, heartbeat_expiry_s=3600.0))

    marshal = spawn_binary(
        "marshal", "--discovery-endpoint", db,
        "--bind-endpoint", endpoint,
        "--metrics-bind-endpoint", f"127.0.0.1:{metrics_port}",
        "--user-transport", "tcp", "--scheme", args.scheme)
    try:
        cold, warm = asyncio.run(
            drive_handshakes(endpoint, args.keys, args.rounds, scheme))
        cache_lines = {}
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{metrics_port}/metrics",
                timeout=5).read().decode()
            for line in body.splitlines():
                # labeled family: cdn_bls_pk_cache{stat="hits"} 12
                if line.startswith('cdn_bls_pk_cache{stat="') \
                        and " " in line:
                    k, v = line.rsplit(" ", 1)
                    cache_lines[k.split('"')[1]] = float(v)
        except Exception as exc:
            cache_lines = {"scrape_error": repr(exc)}
    finally:
        if marshal.poll() is None:
            marshal.send_signal(signal.SIGINT)
            try:
                marshal.wait(timeout=10)
            except Exception:
                marshal.kill()

    tag = "" if scheme is BlsBn254Scheme else f"_{args.scheme}"
    rows = ([micro] if micro else []) + [
        _row(f"auth/own_process_handshake_cold{tag}", cold,
             {"keys": args.keys, "scheme": args.scheme}),
        _row(f"auth/own_process_handshake_warm{tag}", warm,
             {"keys": args.keys, "rounds": args.rounds,
              "scheme": args.scheme, "marshal_cache": cache_lines}),
    ]
    for row in rows:
        print(json.dumps(row))
    if not args.json:
        print(f"# warm p50 {rows[-1]['p50_ms']} ms vs cold p50 "
              f"{rows[-2]['p50_ms']} ms across {args.keys} keys x "
              f"{args.rounds} rounds (marshal in its own OS process)",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
