#!/usr/bin/env python
"""Host egress hot-path bench: the socket side of the socket⇄HBM pump.

Three tiers, each one JSON line (medians of repeated trials, all trials
disclosed — the deployment core is shared, so single samples lie):

- ``egress/engine``: the native egress engine (`native.egress_encode`,
  framing.cpp) turning a step's delivery matrix into per-user wire
  streams — the ``host_egress_msgs_s`` number BASELINE.md tracks. Same
  shape as bench.py's companion row: 1024 user slots, 16384 frames x
  1 KB, 16 receivers per frame.
- ``egress/wire``: end-to-end host egress — pre-serialized frames fanned
  out to N in-process connections through the full coalescing writer
  (per-peer batch handoff -> adaptive coalesce -> native batch encode ->
  flush), counted at the receivers' transport drain.
- ``egress/writer_small_frames``: single-connection writer throughput on
   1 KB frames (the per-connection coalescing floor).

Usage: python benches/egress_bench.py [--quick]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from pushcdn_tpu import native
from pushcdn_tpu.proto.limiter import Bytes
from pushcdn_tpu.proto.transport.memory import (
    Memory,
    gen_testing_connection_pair,
)

RESULTS: list[dict] = []


def emit(name: str, value: float, unit: str, **extra) -> None:
    row = {"bench": name, "value": round(value, 1), "unit": unit, **extra}
    RESULTS.append(row)
    print(json.dumps(row), flush=True)


# ---------------------------------------------------------------------------
# tier 1: the native egress engine (the host_egress_msgs_s metric)
# ---------------------------------------------------------------------------

def bench_engine(trials: int) -> None:
    if not native.available():
        emit("egress/engine", 0, "skipped", reason="native lib unavailable")
        return
    U, S, F, FANOUT = 1024, 16384, 1024, 16
    rng = np.random.default_rng(1)
    deliver = np.zeros((U, S), bool)
    for f in range(S):
        deliver[rng.integers(0, U, FANOUT), f] = True
    lengths = np.full(S, F, np.int32)
    block = rng.integers(0, 256, (S, F)).astype(np.uint8)
    blocks = [block]

    streams = native.egress_encode(deliver, lengths, blocks)  # warm + pool
    total_msgs = streams.total_msgs
    rates = []
    for _ in range(trials):
        del streams  # return the pooled buffer before re-encoding
        t0 = time.perf_counter()
        streams = native.egress_encode(deliver, lengths, blocks)
        rates.append(total_msgs / (time.perf_counter() - t0))
    emit("egress/engine", statistics.median(rates), "msgs/s",
         users=U, frames=S, frame=F, fanout=FANOUT,
         trials=[round(r, 1) for r in rates],
         max=round(max(rates), 1))


# ---------------------------------------------------------------------------
# tier 2: end-to-end wire egress through the coalescing writer
# ---------------------------------------------------------------------------

async def bench_wire(receivers: int, msgs: int, trials: int) -> None:
    from pushcdn_tpu.proto.transport.base import FrameChunk

    pairs = [await gen_testing_connection_pair() for _ in range(receivers)]
    payload = os.urandom(1024)
    frame = Bytes(payload)

    async def drain(conn, n):
        got = 0
        async with asyncio.timeout(60):
            while got < n:
                for item in await conn.recv_frames(n - got):
                    got += item.remaining if type(item) is FrameChunk else 1
                    item.release()

    rates = []
    batch = 32  # frames handed per peer per wakeup (the routing loops'
    #             per-batch shape at sustained load)
    msgs = (msgs // batch) * batch  # drains must match sends exactly
    for _ in range(trials):
        t0 = time.perf_counter()
        drains = [asyncio.create_task(drain(rx, msgs))
                  for _tx, rx in pairs]
        for _ in range(msgs // batch):
            for tx, _rx in pairs:
                await tx.send_raw_many(
                    [frame.clone() for _ in range(batch)])
            await asyncio.sleep(0)
        await asyncio.gather(*drains)
        rates.append(msgs * receivers / (time.perf_counter() - t0))
    for tx, rx in pairs:
        tx.close()
        rx.close()
    emit("egress/wire", statistics.median(rates), "msgs/s",
         receivers=receivers, msgs_per_receiver=msgs, frame=1024,
         trials=[round(r, 1) for r in rates], max=round(max(rates), 1))


async def bench_writer_small_frames(msgs: int, trials: int) -> None:
    from pushcdn_tpu.proto.transport.base import FrameChunk

    tx, rx = await gen_testing_connection_pair()
    payload = os.urandom(1024)

    async def drain(n):
        got = 0
        async with asyncio.timeout(60):
            while got < n:
                for item in await rx.recv_frames(n - got):
                    got += item.remaining if type(item) is FrameChunk else 1
                    item.release()

    rates = []
    for _ in range(trials):
        t0 = time.perf_counter()
        d = asyncio.create_task(drain(msgs))
        for _ in range(msgs):
            await tx.send_raw(payload)
        await d
        rates.append(msgs / (time.perf_counter() - t0))
    tx.close()
    rx.close()
    emit("egress/writer_small_frames", statistics.median(rates), "msgs/s",
         frame=1024, msgs=msgs,
         trials=[round(r, 1) for r in rates], max=round(max(rates), 1))


async def amain(quick: bool) -> None:
    from pushcdn_tpu.bin.common import tune_gc
    tune_gc()
    bench_engine(trials=3 if quick else 5)
    prev = Memory.set_duplex_window(256 * 1024)
    try:
        await bench_wire(receivers=8, msgs=2_000 if quick else 10_000,
                         trials=3)
        await bench_writer_small_frames(msgs=5_000 if quick else 20_000,
                                        trials=3)
    finally:
        Memory.set_duplex_window(prev)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()
    asyncio.run(amain(args.quick))


if __name__ == "__main__":
    main()
