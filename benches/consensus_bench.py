#!/usr/bin/env python
"""Consensus SLO bench (ISSUE 11): view-driven workload through real
brokers, clean vs churn vs chaos, with per-view SLOs gated by
``scripts/trace_report.py --strict``.

Per scenario: N consensus nodes run V leader-broadcast → vote-direct →
quorum views over an in-process cluster (geo-shaped zipf links), every
message traced (1-in-1) and view-tagged; the span log is aggregated by
``trace_report`` and the scenario's SLO row lands in BENCH_r*.json:

    python benches/consensus_bench.py [--quick] [--out-json BENCH_r16.json]

Scenarios:

- **clean** — no interference; the baseline SLO row.
- **churn** — connect/disconnect storm riding alongside the views (a
  fresh subscriber joins and leaves per view-ish tick).
- **shed_mid_view** (chaos) — a subscribe-spammer trips admission
  shedding (PUSHCDN_SUBSCRIBE_RATE) mid-view; the composition invariant
  is that shed mutations never stall view completion.
- **broker_churn** (chaos) — a second, non-serving broker is stopped
  mid-view and restarted two views later: mesh churn + discovery updates
  while quorum forms. Survivor-lossless by construction, so the strict
  zero-orphan trace gate applies.
- **marshal_restart** (chaos) — the marshal dies mid-view and comes back:
  no new admissions for a beat, but live consensus links keep serving.
- **replay_catchup** (chaos, ISSUE 14) — a third of the nodes hard-drop
  mid-run and rejoin one view later via durable ``subscribe_from``: the
  in-flight view can only reach quorum on votes triggered by replayed
  ``Retained`` proposals, so completing every view proves the
  replay → live handover under real consensus load.

All scenarios assert every view completes (no timeouts) and the chaos
span logs pass ``trace_report --strict`` (zero orphans, zero stalled
views). Provenance (cpus/git/python/jax) is stamped by write_bench_json.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import subprocess
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

TRACE_REPORT = os.path.join(REPO, "scripts", "trace_report.py")

RESULTS = []


def emit(row: dict) -> None:
    RESULTS.append(row)
    print(json.dumps(row), flush=True)


def _pct_ms(x):
    return None if x is None else round(x * 1e3, 3)


async def _run_scenario(name: str, *, num_brokers: int = 1,
                        chaos_factory=None, driver_chaos_factory=None,
                        sidecar_factory=None,
                        env: dict = None, quick: bool = False,
                        span_dir: str = None,
                        require_sidecar_sheds: bool = False,
                        require_replay: bool = False) -> dict:
    """One scenario: cluster up → (sidecar) → consensus run → strict
    trace gate on the scenario's own span log."""
    from pushcdn_tpu.proto import trace as trace_mod
    from pushcdn_tpu.proto.topic import TopicSpace
    from pushcdn_tpu.testing.cluster import Cluster
    from pushcdn_tpu.testing.consensus import ConsensusConfig, run_consensus

    num_nodes = 4 if quick else 6
    num_views = 4 if quick else 12
    cfg = ConsensusConfig(
        num_nodes=num_nodes, num_views=num_views, view_timeout_s=30.0,
        base_latency_s=0.001, tail_latency_s=0.008, jitter_s=0.001,
        loss=0.05, rto_s=0.01, seed=13)

    log_path = os.path.join(span_dir, f"{name}.jsonl")
    prev_env = {}
    for k, v in (env or {}).items():
        prev_env[k] = os.environ.get(k)
        os.environ[k] = v
    prev_log = trace_mod.set_log_path(log_path)
    # the default TEST_TOPIC_SPACE is {0,1}; the sidecars churn/spam on
    # higher topics, and an invalid handshake topic is a rejection
    # (listeners.py topic prune) — so the bench runs a wide space
    cluster = await Cluster(num_brokers=num_brokers,
                            topics=TopicSpace.range(256)).start()
    sidecar_task = None
    stop_sidecar = asyncio.Event()
    try:
        if num_brokers > 1:
            # pin consensus nodes onto broker 0 so chaos on broker 1 is
            # survivor-lossless (the strict zero-orphan gate is honest:
            # no traced frame was ever routed through the victim)
            await cluster.place_on(0)
        chaos = chaos_factory(cluster, cfg) if chaos_factory else None
        if sidecar_factory is not None:
            sidecar_task = asyncio.ensure_future(
                sidecar_factory(cluster, stop_sidecar))
        run = await run_consensus(cluster, cfg, chaos=chaos,
                                  driver_chaos=driver_chaos_factory)
    finally:
        stop_sidecar.set()
        sidecar_result = None
        if sidecar_task is not None:
            try:
                sidecar_result = await asyncio.wait_for(sidecar_task, 10.0)
            except Exception:
                sidecar_task.cancel()
        await cluster.stop()
        trace_mod.set_log_path(prev_log)
        for k, v in prev_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    completion = run.completion_percentiles()
    delivery = run.delivery_percentiles()

    # the SLO gate: per-view aggregation + zero orphans / stalled views
    gate = subprocess.run(
        [sys.executable, TRACE_REPORT, "--strict", "--json", log_path],
        capture_output=True, text=True, timeout=120)
    strict_ok = gate.returncode == 0
    try:
        report = json.loads(gate.stdout)
    except ValueError:
        report = {}

    row = {
        "bench": f"consensus/{name}",
        "nodes": cfg.num_nodes,
        "views": cfg.num_views,
        "completed": run.completed,
        "timeouts": run.timeouts,
        "sheds": run.sheds,
        "votes_sent": run.votes_sent,
        "view_completion_p50_ms": _pct_ms(completion["p50"]),
        "view_completion_p95_ms": _pct_ms(completion["p95"]),
        "view_completion_p99_ms": _pct_ms(completion["p99"]),
        "publish_delivery_p50_ms": _pct_ms(delivery["p50"]),
        "publish_delivery_p99_ms": _pct_ms(delivery["p99"]),
        "replayed_proposals": run.replayed_proposals,
        "trace_strict_ok": strict_ok,
        "trace_complete_chains": report.get("complete_chains"),
        "trace_orphaned_spans": report.get("orphaned_spans"),
        "span_log": os.path.basename(log_path),
    }
    if sidecar_result is not None:
        row["sidecar_sheds"] = sidecar_result
    vr = report.get("views") or {}
    if vr:
        row["trace_view_completion_p99_ms"] = \
            vr.get("completion_ms", {}).get("p99")
        row["trace_stalled_views"] = vr.get("stalled_views")
    if not strict_ok:
        row["trace_strict_stderr"] = gate.stderr.strip()[-500:]
    emit(row)

    assert run.timeouts == 0, \
        f"{name}: {run.timeouts} views timed out (stall)"
    assert run.completed == cfg.num_views, \
        f"{name}: only {run.completed}/{cfg.num_views} views completed"
    assert strict_ok, \
        f"{name}: trace_report --strict failed:\n{gate.stderr}"
    if require_sidecar_sheds:
        assert sidecar_result, \
            f"{name}: the admission layer never shed (sidecar saw 0) — " \
            "the scenario proved nothing"
    if require_replay:
        assert run.replayed_proposals > 0, \
            f"{name}: no Retained proposals were replayed — the rejoin " \
            "never exercised the durable catch-up path"
    return row


# -- scenario wiring ----------------------------------------------------


async def _churn_sidecar(cluster, stop: asyncio.Event):
    """Connect/disconnect storm on a topic the consensus run doesn't
    use: placement, handshakes, and route-state churn ride alongside
    quorum formation."""
    seed = 70_000
    while not stop.is_set():
        c = cluster.client(seed=seed, topics=[5])
        seed += 1
        try:
            await asyncio.wait_for(c.ensure_initialized(), 10.0)
        except Exception:
            pass
        c.close()
        try:
            await asyncio.wait_for(stop.wait(), 0.05)
        except asyncio.TimeoutError:
            continue


async def _shed_sidecar(cluster, stop: asyncio.Event):
    """Hammer one connection with subscribe mutations until admission
    sheds them (typed Error(SHED) notices, never silent drops)."""
    from pushcdn_tpu.proto.error import Error, ErrorKind
    c = cluster.client(seed=71_000, topics=[6])
    sheds = 0
    try:
        await asyncio.wait_for(c.ensure_initialized(), 10.0)
        t = 10
        while not stop.is_set():
            try:
                for _ in range(4):   # burst past the token bucket
                    t += 1
                    await c.subscribe([t % 200 + 10])
                while True:          # drain queued shed notices
                    await asyncio.wait_for(c.receive_messages(), 0.005)
            except asyncio.TimeoutError:
                pass
            except Error as exc:
                if exc.kind == ErrorKind.SHED:
                    sheds += 1
            except Exception:
                pass
            await asyncio.sleep(0)
    finally:
        c.close()
    return sheds


def _broker_churn_chaos(cluster, cfg):
    """Stop the non-serving broker mid-view k, restart it at k+2."""
    kill_at = cfg.num_views // 3
    revive_at = min(kill_at + 2, cfg.num_views - 1)

    async def hook(view: int):
        if view == kill_at:
            await cluster.brokers[1].stop()
        elif view == revive_at:
            await cluster.restart_broker(1)
    return {kill_at: hook, revive_at: hook}


def _replay_catchup_chaos(driver):
    """ISSUE 14 durable-topics scenario: a third of the nodes hard-drop
    mid-run and rejoin one view later via ``subscribe_from`` — the view
    in flight at rejoin time can only reach quorum on votes triggered by
    REPLAYED (``Retained``) proposals, so completing every view proves
    the replay → live handover end to end.

    Orphan hygiene (the strict zero-orphan trace gate stays honest):
    victims are only dropped once their votes for the drop view have
    LANDED at the leader (no traced frame is in flight toward them), and
    the next proposal waits until the broker has reaped their
    connections (no egress span to a corpse). Victims never lead an
    affected view."""
    from pushcdn_tpu.testing.cluster import wait_until

    cfg = driver.cfg
    n = cfg.num_nodes
    drop_at = cfg.num_views // 3
    rejoin_at = drop_at + 1
    leaders = {drop_at % n, rejoin_at % n}
    victims = [i for i in range(n) if i not in leaders][:max(1, n // 3)]

    async def drop_hook(view: int):
        await wait_until(
            lambda: all(i in driver._votes.get(view, set())
                        for i in victims), timeout=15.0)
        for i in victims:
            await driver.drop_node(i)
        want = n - len(victims)
        await wait_until(
            lambda: sum(b.connections.num_users
                        for b in driver.cluster.brokers) <= want,
            timeout=15.0)

    async def rejoin_hook(view: int):
        for i in victims:
            await driver.rejoin_node(i, from_seq=1)

    return {drop_at: drop_hook, rejoin_at: rejoin_hook}


def _marshal_restart_chaos(cluster, cfg):
    kill_at = cfg.num_views // 2

    async def hook(view: int):
        await cluster.marshal.stop()
        await asyncio.sleep(0.05)      # a real outage window
        await cluster.restart_marshal()
    return {kill_at: hook}


async def _replay_io_ab(io_impl: str, quick: bool) -> None:
    """The uring-vs-asyncio A/B row (ISSUE 14 satellite): durable replay
    over REAL loopback TCP. The consensus scenarios above run on the
    Memory transport — an io-impl label there would be a lie — so the
    A/B measures the one consensus-bench path that genuinely crosses
    sockets: N retained proposals streamed to a late joiner via
    ``SubscribeFrom``, timed subscribe → last ``Retained`` frame.
    A kernel that denies io_uring yields a ``skipped`` row, never a
    mislabeled one."""
    from pushcdn_tpu.native import uring as nuring
    from pushcdn_tpu.proto.transport import uring as umod

    n_frames = 256 if quick else 1024
    payload = 1024
    impls = [io_impl] if io_impl in ("asyncio", "uring") \
        else ["asyncio", "uring"]
    prev = {k: os.environ.get(k)
            for k in ("PUSHCDN_RETAIN_TOPICS", "PUSHCDN_RETAIN_COUNT",
                      "PUSHCDN_RETAIN_BYTES", "PUSHCDN_IO_IMPL")}
    os.environ["PUSHCDN_RETAIN_TOPICS"] = "0"
    os.environ["PUSHCDN_RETAIN_COUNT"] = str(n_frames)
    os.environ["PUSHCDN_RETAIN_BYTES"] = str(n_frames * (payload + 64))
    measured = {}
    try:
        for impl in impls:
            if impl == "uring" and not nuring.available():
                emit({"bench": "consensus/replay_io_ab", "io_impl": "uring",
                      "unit": "skipped",
                      "reason": "io_uring unavailable "
                                f"({nuring.probe_errname()})"})
                continue
            umod.set_io_impl(impl)
            dt = await _replay_once(n_frames, payload)
            measured[impl] = dt
            emit({"bench": "consensus/replay_io_ab", "io_impl": impl,
                  "transport": "tcp", "frames": n_frames,
                  "payload_bytes": payload,
                  "replay_ms": round(dt * 1e3, 3),
                  "replay_frames_per_s": round(n_frames / dt, 1)})
        if len(measured) == 2:
            emit({"bench": "consensus/replay_io_ab", "io_impl": "ab",
                  "uring_x": round(measured["asyncio"] / measured["uring"],
                                   3)})
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        umod.set_io_impl(prev.get("PUSHCDN_IO_IMPL") or "auto")


async def _replay_once(n_frames: int, payload: int) -> float:
    """Retain ``n_frames`` broadcasts in one real broker, then time a
    TCP subscriber's ``subscribe_from(0, 1)`` catch-up."""
    import time

    from pushcdn_tpu.broker.test_harness import TestDefinition
    from pushcdn_tpu.proto.message import (KIND_RETAINED, Broadcast,
                                           SubscribeFrom)
    from pushcdn_tpu.testing.cluster import wait_until

    # user 0 publishes on topic 0 but only subscribes to 1 → every frame
    # is retained, none delivered live; user 1 joins cold afterwards
    run = await TestDefinition(connected_users=((1,), ()),
                               tcp_users=True).run()
    try:
        body = b"r" * payload
        for _ in range(n_frames):
            await run.send_message_as(
                run.user(0), Broadcast(topics=[0], message=body))
        await wait_until(
            lambda: run.broker.durable.stats()["ring_entries"]
            .get(0, 0) >= n_frames,
            timeout=30.0)
        late = run.user(1)
        t0 = time.perf_counter()
        await late.remote.send_message(SubscribeFrom(topic=0, seq=1),
                                       flush=True)
        got = 0
        while got < n_frames:
            raw = await asyncio.wait_for(late.remote.recv_raw(), 10.0)
            if (raw.data[0] & 0x7F) == KIND_RETAINED:
                got += 1
            raw.release()
        return time.perf_counter() - t0
    finally:
        await run.shutdown()


async def amain(quick: bool, out_json: str, scenarios,
                io_impl: str = None) -> None:
    span_dir = tempfile.mkdtemp(prefix="consensus-spans-")
    all_scenarios = {
        "clean": dict(),
        "churn": dict(sidecar_factory=_churn_sidecar),
        "shed_mid_view": dict(
            sidecar_factory=_shed_sidecar,
            require_sidecar_sheds=True,
            env={"PUSHCDN_SUBSCRIBE_RATE": "1",
                 "PUSHCDN_SUBSCRIBE_BURST": "2"}),
        "broker_churn": dict(num_brokers=2,
                             chaos_factory=_broker_churn_chaos),
        "marshal_restart": dict(chaos_factory=_marshal_restart_chaos),
        "replay_catchup": dict(
            driver_chaos_factory=_replay_catchup_chaos,
            require_replay=True,
            env={"PUSHCDN_RETAIN_TOPICS": "0"}),
    }
    run_list = scenarios or list(all_scenarios)
    rows = {}
    for name in run_list:
        rows[name] = await _run_scenario(
            name, quick=quick, span_dir=span_dir, **all_scenarios[name])

    if io_impl is not None and (scenarios is None
                                or "replay_catchup" in run_list):
        await _replay_io_ab(io_impl, quick)

    headline = {}
    for key in ("clean", "churn"):
        if key in rows:
            headline[f"{key}_view_p99_ms"] = \
                rows[key]["view_completion_p99_ms"]
            headline[f"{key}_delivery_p99_ms"] = \
                rows[key]["publish_delivery_p99_ms"]
    if "replay_catchup" in rows:
        headline["replayed_proposals"] = \
            rows["replay_catchup"]["replayed_proposals"]
        # its own series: the rejoin view completes on REPLAYED votes
        # (drop + reap + re-auth + catch-up inside one view), which is
        # structurally slower than any live chaos view — folding it into
        # chaos_view_p99_ms_worst would break that series' round-to-round
        # comparability
        headline["replay_catchup_view_p99_ms"] = \
            rows["replay_catchup"]["view_completion_p99_ms"]
    ab = [r for r in RESULTS
          if r.get("bench") == "consensus/replay_io_ab"
          and "uring_x" in r]
    if ab:
        headline["replay_uring_x"] = ab[0]["uring_x"]
    chaos_rows = [r for n, r in rows.items()
                  if n not in ("clean", "churn")]
    if chaos_rows:
        headline["chaos_scenarios"] = len(chaos_rows)
        headline["chaos_strict_ok"] = all(r["trace_strict_ok"]
                                          for r in chaos_rows)
    live_chaos = [r for n, r in rows.items()
                  if n not in ("clean", "churn", "replay_catchup")]
    if live_chaos:
        headline["chaos_view_p99_ms_worst"] = max(
            (r["view_completion_p99_ms"] or 0) for r in live_chaos)
    headline["span_dir"] = span_dir
    print(json.dumps({"headline": headline}), flush=True)

    if out_json:
        from route_bench import write_bench_json
        write_bench_json(out_json, "consensus_slo", headline, RESULTS)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--quick", action="store_true",
                    help="small node/view counts (the CI smoke tier)")
    ap.add_argument("--out-json", default=None,
                    help="merge the consensus_slo section into this "
                         "BENCH_r*.json")
    ap.add_argument("--scenarios", default=None,
                    help="comma-separated subset (default: all)")
    ap.add_argument("--io-impl", default=None,
                    choices=("asyncio", "uring", "both"),
                    help="run the durable-replay io A/B over real TCP "
                         "with this impl (the Memory-transport scenarios "
                         "never touch the io engine, so only this row "
                         "carries an io_impl label; an unavailable "
                         "kernel yields a skipped row)")
    args = ap.parse_args()
    scenarios = args.scenarios.split(",") if args.scenarios else None
    asyncio.run(amain(args.quick, args.out_json, scenarios,
                      io_impl=args.io_impl))


if __name__ == "__main__":
    main()
