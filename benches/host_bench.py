#!/usr/bin/env python
"""Reference-shaped microbenches on the host stack (criterion parity).

Reproduces the shapes of the reference's criterion harnesses so BASELINE.md
can carry our own measured numbers (the reference publishes none):

- transport transfer throughput at 100 B / 1 KB / 100 KB / 10 MB / 100 MB
  frames over Memory and TCP-loopback (cdn-proto/benches/protocols.rs:103-159)
- broker routing latency on the deterministic injection harness: broadcast
  user→2 users and user→2 brokers; direct user→self / user→user /
  user→remote-broker / broker→user, 10 KB messages
  (cdn-broker/benches/broadcast.rs:52-110, benches/direct.rs:79-187)
- end-to-end direct-message echo p50/p99 through marshal+broker+client
  (the BASELINE.json p99 metric's host-side baseline)

Usage: python benches/host_bench.py [--quick] [--profile]
Prints one JSON object per bench line; --profile writes a cProfile dump
next to this file (the reference wires pprof flamegraphs into criterion).
"""

from __future__ import annotations

import argparse
import asyncio
import cProfile
import json
import os
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pushcdn_tpu.broker.test_harness import TestDefinition
from pushcdn_tpu.client import Client, ClientConfig
from pushcdn_tpu.marshal import Marshal, MarshalConfig
from pushcdn_tpu.broker.broker import Broker, BrokerConfig
from pushcdn_tpu.broker.tasks.heartbeat import heartbeat_once
from pushcdn_tpu.proto.crypto.signature import DEFAULT_SCHEME
from pushcdn_tpu.proto.def_ import testing_run_def
from pushcdn_tpu.proto.message import Broadcast, Direct
from pushcdn_tpu.proto.transport import Memory, Quic, Tcp, TcpTls
from pushcdn_tpu.proto.transport.memory import gen_testing_connection_pair

RESULTS: list[dict] = []


def emit(name: str, value: float, unit: str, **extra) -> None:
    row = {"bench": name, "value": round(value, 3), "unit": unit, **extra}
    RESULTS.append(row)
    print(json.dumps(row), flush=True)


# ---------------------------------------------------------------------------
# transport throughput (parity protocols.rs)
# ---------------------------------------------------------------------------

async def bench_transport(proto, endpoint: str, size: int, total_bytes: int,
                          **extra):
    listener = await proto.bind(endpoint)
    ep = endpoint
    port = getattr(listener, "bound_port", None)
    if port:
        ep = f"127.0.0.1:{port}"
    connect = asyncio.create_task(proto.connect(ep))
    server = await (await listener.accept()).finalize()
    client = await connect

    payload = os.urandom(size)
    msg = Direct(recipient=b"", message=payload)
    n = max(1, total_bytes // max(size, 1))

    async def sender():
        for _ in range(n):
            await client.send_message(msg)

    t0 = time.perf_counter()
    send_task = asyncio.create_task(sender())
    for _ in range(n):
        raw = await server.recv_raw()
        raw.release()
    await send_task
    dt = time.perf_counter() - t0
    client.close()
    server.close()
    await listener.close()
    emit(f"transport/{proto.name}/transfer", n * size / dt / 1e6, "MB/s",
         frame_size=size, frames=n, **extra)


# ---------------------------------------------------------------------------
# broker routing latency (parity broadcast.rs / direct.rs, 10 KB)
# ---------------------------------------------------------------------------

async def _routing_case(run, send_entity, message, recv_entities, iters: int):
    lat = []
    for _ in range(iters):
        t0 = time.perf_counter()
        await run.send_message_as(send_entity, message)
        for e in recv_entities:
            raw = await asyncio.wait_for(e.remote.recv_raw(), 5)
            raw.release()
        lat.append((time.perf_counter() - t0) * 1e6)
    return lat


async def bench_routing(iters: int):
    payload = os.urandom(10 * 1024)  # 10 KB parity

    # broadcast user -> 2 subscribed users
    run = await TestDefinition(connected_users=[[0], [0], [0]]).run()
    try:
        lat = await _routing_case(
            run, run.user(0), Broadcast(topics=[0], message=payload),
            [run.user(1), run.user(2)], iters)
        emit("routing/broadcast/user_to_2_users",
             statistics.median(lat), "us_median", p99=_p99(lat))
    finally:
        await run.shutdown()

    # broadcast user -> 2 subscribed brokers
    run = await TestDefinition(connected_users=[[0]],
                               connected_brokers=[([0], []), ([0], [])]).run()
    try:
        lat = await _routing_case(
            run, run.user(0), Broadcast(topics=[0], message=payload),
            [run.peer(0), run.peer(1)], iters)
        emit("routing/broadcast/user_to_2_brokers",
             statistics.median(lat), "us_median", p99=_p99(lat))
    finally:
        await run.shutdown()

    # direct user -> self
    run = await TestDefinition(connected_users=[[0]]).run()
    try:
        lat = await _routing_case(
            run, run.user(0), Direct(recipient=b"user-0", message=payload),
            [run.user(0)], iters)
        emit("routing/direct/user_to_self",
             statistics.median(lat), "us_median", p99=_p99(lat))
    finally:
        await run.shutdown()

    # direct user -> other user (same broker)
    run = await TestDefinition(connected_users=[[0], [0]]).run()
    try:
        lat = await _routing_case(
            run, run.user(0), Direct(recipient=b"user-1", message=payload),
            [run.user(1)], iters)
        emit("routing/direct/user_to_user",
             statistics.median(lat), "us_median", p99=_p99(lat))
    finally:
        await run.shutdown()

    # direct user -> user owned by a remote broker (one forward hop)
    run = await TestDefinition(connected_users=[[0]],
                               connected_brokers=[([], [b"remote-user"])]).run()
    try:
        lat = await _routing_case(
            run, run.user(0), Direct(recipient=b"remote-user", message=payload),
            [run.peer(0)], iters)
        emit("routing/direct/user_to_remote_broker",
             statistics.median(lat), "us_median", p99=_p99(lat))
    finally:
        await run.shutdown()

    # direct broker -> local user
    run = await TestDefinition(connected_users=[[0]],
                               connected_brokers=[([], [])]).run()
    try:
        lat = await _routing_case(
            run, run.peer(0), Direct(recipient=b"user-0", message=payload),
            [run.user(0)], iters)
        emit("routing/direct/broker_to_user",
             statistics.median(lat), "us_median", p99=_p99(lat))
    finally:
        await run.shutdown()


# ---------------------------------------------------------------------------
# end-to-end echo latency (marshal + broker + client; the p99 baseline)
# ---------------------------------------------------------------------------

async def bench_e2e_echo(iters: int):
    db = os.path.join(tempfile.mkdtemp(prefix="pushcdn-bench-"), "d.sqlite")
    rd = testing_run_def()
    broker = await Broker.new(BrokerConfig(
        run_def=rd, keypair=DEFAULT_SCHEME.generate_keypair(seed=1),
        discovery_endpoint=db,
        public_advertise_endpoint="bench-pub", public_bind_endpoint="bench-pub",
        private_advertise_endpoint="bench-priv", private_bind_endpoint="bench-priv",
        heartbeat_interval_s=3600, sync_interval_s=3600,
        whitelist_interval_s=3600))
    await broker.start()
    await heartbeat_once(broker)
    marshal = await Marshal.new(MarshalConfig(
        run_def=rd, discovery_endpoint=db, bind_endpoint="bench-marshal"))
    await marshal.start()
    client = Client(ClientConfig(
        marshal_endpoint="bench-marshal",
        keypair=DEFAULT_SCHEME.generate_keypair(seed=2),
        protocol=Memory, subscribed_topics={0}))
    await client.ensure_initialized()

    payload = os.urandom(10 * 1024)
    lat = []
    for _ in range(iters):
        t0 = time.perf_counter()
        await client.send_direct_message(client.public_key, payload)
        await client.receive_message()
        lat.append((time.perf_counter() - t0) * 1e6)
    emit("e2e/direct_echo_10KB", statistics.median(lat), "us_median",
         p50=round(statistics.median(lat), 1), p99=_p99(lat))
    client.close()
    await marshal.stop()
    await broker.stop()


async def bench_device_echo(iters: int):
    """Device-plane direct-echo latency, both policies (BASELINE.md device-
    latency row): with the depth-1 idle bypass (the default — sparse
    traffic host-routes, so the device plane costs the latency regime
    nothing) and with the bypass disabled (the raw staged step path, the
    floor a device-routed message pays)."""
    from pushcdn_tpu.broker.device_plane import DevicePlaneConfig
    from pushcdn_tpu.testing import Cluster

    for label, bypass in (("bypass", 2), ("staged", 0)):
        cluster = await Cluster(num_brokers=1,
                                device_plane=DevicePlaneConfig(
                                    ring_slots=64, frame_bytes=16384,
                                    extra_lanes=(),
                                    bypass_max_items=bypass)).start()
        try:
            client = cluster.client(seed=77, topics=[0])
            await client.ensure_initialized()
            payload = os.urandom(10 * 1024)
            # warm the path (first step compiles nothing further; warmup
            # ran at broker start, but prime caches anyway)
            for _ in range(5):
                await client.send_direct_message(client.public_key, payload)
                await client.receive_message()
            lat = []
            for _ in range(iters):
                t0 = time.perf_counter()
                await client.send_direct_message(client.public_key, payload)
                await client.receive_message()
                lat.append((time.perf_counter() - t0) * 1e6)
            emit(f"e2e/device_echo_10KB_{label}", statistics.median(lat),
                 "us_median", p99=_p99(lat),
                 steps=cluster.brokers[0].device_plane.steps)
            client.close()
        finally:
            await cluster.stop()


async def bench_device_fanout(tput: int):
    """Sustained broadcast fan-out THROUGH the attached device plane, end
    to end: marshal-auth'd clients publish, frames stage into the ring,
    the routing step runs on whatever accelerator is live (the real TPU
    under axon; CPU elsewhere), the native engine egresses per-user wire
    streams, and all 16 clients fully decode. The only e2e number in the
    suite that exercises the real chip (the 8-shard mesh rows need 8
    devices and therefore run on the virtual CPU mesh)."""
    import jax

    from pushcdn_tpu.broker.device_plane import DevicePlaneConfig
    from pushcdn_tpu.testing import Cluster

    cluster = await Cluster(num_brokers=1,
                            device_plane=DevicePlaneConfig(
                                ring_slots=1024, frame_bytes=2048)).start()
    try:
        clients = [cluster.client(seed=700 + i, topics=[0])
                   for i in range(16)]
        for c in clients:
            await c.ensure_initialized()
        payload = os.urandom(1024)

        async def drain(c, n):
            got = 0
            while got < n:
                got += len(await c.receive_messages())

        # warmup: fill step-shape caches / device buffers
        drains = [asyncio.create_task(drain(c, 400)) for c in clients]
        for _ in range(200):
            await clients[0].send_broadcast_message([0], payload)
            await clients[1].send_broadcast_message([0], payload)
        await asyncio.gather(*drains)

        plane = cluster.brokers[0].device_plane
        sent = tput // 2 * 2  # two publishers: drains must match exactly
        steps0 = plane.steps
        t0 = time.perf_counter()
        drains = [asyncio.create_task(drain(c, sent)) for c in clients]
        for _ in range(sent // 2):
            await clients[0].send_broadcast_message([0], payload)
            await clients[1].send_broadcast_message([0], payload)
        await asyncio.gather(*drains)
        dt = time.perf_counter() - t0
        emit("e2e/device_plane_fanout", sent * 16 / dt, "deliveries/s",
             backend=jax.default_backend(), msgs=sent, frame=1024,
             steps=plane.steps - steps0)
        for c in clients:
            c.close()
    finally:
        await cluster.stop()


def _p99(lat):
    return round(sorted(lat)[max(0, int(len(lat) * 0.99) - 1)], 1)


async def amain(quick: bool):
    sizes = [100, 1024, 100 * 1024, 10 * 1024 * 1024]
    if not quick:
        sizes.append(100 * 1024 * 1024)
    budget = 20 * 1024 * 1024 if quick else 200 * 1024 * 1024
    floor = 1 * 1024 * 1024 if quick else 8 * 1024 * 1024  # enough frames
    # Memory rows run twice: at the reference's 8 KiB duplex window
    # (test-infra parity) and at a production-class 256 KiB window — the
    # parity constant caps large-frame rows at the pipe, not the stack
    for label, window in (("8KiB-parity", None), ("256KiB", 256 * 1024)):
        prev = Memory.set_duplex_window(window) if window else None
        try:
            for size in sizes:
                await bench_transport(Memory,
                                      f"bench-mem-{label}-{size}", size,
                                      min(budget, max(10 * size, floor)),
                                      window=label)
        finally:
            if prev is not None:
                Memory.set_duplex_window(prev)
    for size in sizes:
        await bench_transport(Tcp, "127.0.0.1:0", size,
                              min(budget, max(10 * size, floor)))
    for size in sizes:
        # kernel TCP + TLS: the apples-to-apples baseline for the
        # QUIC-class rows below (those carry TLS 1.3 too; plain TCP does
        # not, so its rows measure an unencrypted stack)
        await bench_transport(TcpTls, "127.0.0.1:0", size,
                              min(budget, max(10 * size, floor)))
    for size in sizes:
        # QUIC-class UDP: same byte budget as TCP — with congestion
        # control the flow needs the full run to leave slow start, and a
        # shorter budget would measure the ramp, not the transport
        await bench_transport(Quic, "127.0.0.1:0", size,
                              min(budget, max(10 * size, floor)))
    await bench_routing(iters=100 if quick else 500)
    await bench_e2e_echo(iters=200 if quick else 1000)
    from pushcdn_tpu.testing.accel_probe import accelerator_reachable
    ok, why = accelerator_reachable()
    if ok:
        await bench_device_echo(iters=100 if quick else 300)
        # wide memory window: models the production TCP edge (same
        # rationale as the configs benches) so the 16-way drain isn't
        # pinched at 8 KiB
        prev = Memory.set_duplex_window(256 * 1024)
        try:
            await bench_device_fanout(tput=1500 if quick else 6000)
        finally:
            Memory.set_duplex_window(prev)
    else:
        emit("e2e/device_skipped", 0, "skipped", reason=why)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--profile", action="store_true",
                    help="write host_bench.prof (pprof-flamegraph parity)")
    args = ap.parse_args()
    if args.profile:
        prof = cProfile.Profile()
        prof.enable()
    asyncio.run(amain(args.quick))
    if args.profile:
        prof.disable()
        out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "host_bench.prof")
        prof.dump_stats(out)
        print(f"# profile written to {out} (view: python -m pstats)",
              file=sys.stderr)


if __name__ == "__main__":
    main()
