#!/usr/bin/env python
"""Straggler degradation curve for the lockstep multi-host pump
(VERDICT r4 #7): two OS processes run the standard two-host deployment
while host 1 injects a blocking delay into every collective tick;
host 0's achieved step cadence and cross-host delivery rate quantify
how much one slow host gates the whole group.

Usage: python benches/straggler_bench.py [--delays 0,20,100] [--msgs 200]
Prints one JSON line per sweep point.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "benches", "_straggler_worker.py")


def run_point(delay_ms: float, msgs: int) -> dict:
    sys.path.insert(0, REPO)
    from pushcdn_tpu.testing.two_host import spawn_worker_pair
    db = os.path.join(tempfile.mkdtemp(prefix="pushcdn-strag-"), "d.sqlite")
    logdir = os.path.dirname(db)
    procs, _base = spawn_worker_pair(
        WORKER, [db, str(delay_ms), str(msgs)], cwd=REPO, pipe=False,
        log_dir=logdir)
    try:
        for p in procs:
            p.communicate(timeout=420)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
            p.communicate(timeout=30)
    outs = [open(os.path.join(logdir, f"rank{r}.log")).read()
            for r in (0, 1)]
    for rank, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0:
            raise RuntimeError(
                f"rank {rank} failed (full log at {logdir}):\n{out[-3000:]}")
    rows = []
    for rank in (0, 1):
        m = re.search(r"rank %d: STRAGGLER delay_ms=\S+ msgs=(\d+) "
                      r"wall=([\d.]+) steps=(\d+) cadence_ms=([\d.]+) "
                      r"rate=([\d.]+)/s" % rank, outs[rank])
        assert m, outs[rank][-2000:]
        rows.append(m)
    # rank 0 drains its LOCAL copies; rank 1's drain is the genuinely
    # cross-host half — report both, extrapolate neither
    return {"delay_ms": delay_ms, "msgs": int(rows[0].group(1)),
            "wall_s": float(rows[0].group(2)),
            "steps": int(rows[0].group(3)),
            "cadence_ms": float(rows[0].group(4)),
            "local_deliveries_per_s": float(rows[0].group(5)),
            "cross_host_deliveries_per_s": float(rows[1].group(5)),
            "cross_host_wall_s": float(rows[1].group(2))}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--delays", default="0,20,100")
    ap.add_argument("--msgs", type=int, default=200)
    args = ap.parse_args()
    for d in (float(x) for x in args.delays.split(",")):
        row = run_point(d, args.msgs)
        print(json.dumps({"bench": "multihost/straggler", **row}),
              flush=True)


if __name__ == "__main__":
    main()
