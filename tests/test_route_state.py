"""Incremental route-state equivalence (ISSUE 7 property suite).

Seeded interleavings of every Connections mutation class — subscribe /
unsubscribe, user add/remove (including same-key eviction), DirectMap
merge + cross-broker eviction, mesh broker add/remove, mesh topic sync,
and the sharded remote-user/remote-broker flavors — are applied to one
``Connections`` while TWO RouteStates track it:

- the **incremental** state refreshes after every op (typed route-log
  deltas applied in place to the native table), and
- a **from-scratch** twin is rebuilt fresh at each checkpoint.

Both must produce IDENTICAL plans: for a probe chunk covering every
topic and every known Direct recipient, the per-(identity, shard)
frame-index fan-out must match exactly. The suite also forces the edge
transitions: delta-log overflow, version gap (trimmed log), slot-capacity
growth, and compaction — asserting the incremental state recovers through
the labeled rebuild fallback and STAYS equivalent afterwards.
"""

import numpy as np
import pytest

from pushcdn_tpu.broker import connections as connections_mod
from pushcdn_tpu.broker.connections import Connections, SubscriptionStatus
from pushcdn_tpu.broker.tasks import cutthrough
from pushcdn_tpu.broker.versioned_map import VersionedMap
from pushcdn_tpu.native import routeplan
from pushcdn_tpu.proto import def_ as def_mod
from pushcdn_tpu.proto import flightrec
from pushcdn_tpu.proto.message import Broadcast, Direct, serialize

pytestmark = pytest.mark.skipif(
    not routeplan.available(),
    reason="native route-plan kernel unavailable (no working g++)")

IDENTITY = "pub:me/priv:me"
PEERS = ["pub:a/priv:a", "pub:b/priv:b", "pub:c/priv:c"]
USERS = [b"user-%d" % i for i in range(12)]
TOPICS = [0, 1]


class _FakeConn:
    """Just enough Connection surface for Connections bookkeeping."""

    def __init__(self):
        self.flightrec = flightrec.FlightRecorder("fake")

    def close(self):
        pass


class _PlanBroker:
    """Minimal broker shim for a control-plane-only RouteState."""

    def __init__(self, identity=IDENTITY, shard_id=0, num_shards=1):
        self.connections = Connections(identity)
        self.connections.shard_id = shard_id
        self.connections.num_shards = num_shards
        self.run_def = def_mod.testing_run_def()
        self.device_plane = None
        self.admission = None


def _sync_payload(owner: str, keys) -> bytes:
    m = VersionedMap(local_identity=owner)
    for k in keys:
        m.insert(bytes(k), owner)
    return VersionedMap.serialize_entries(m.full())


def _topic_payload(owner: str, subs) -> bytes:
    m = VersionedMap(local_identity=owner)
    for topic, on in subs:
        m.insert(int(topic), int(SubscriptionStatus.SUBSCRIBED if on
                                 else SubscriptionStatus.UNSUBSCRIBED))
    return VersionedMap.serialize_entries(m.full())


def _apply_random_op(rng, conns: Connections) -> None:
    roll = int(rng.integers(0, 100))
    user = USERS[int(rng.integers(0, len(USERS)))]
    peer = PEERS[int(rng.integers(0, len(PEERS)))]
    topics = [int(t) for t in
              rng.choice(TOPICS, size=int(rng.integers(1, 3)))]
    if roll < 22:
        conns.add_user(user, _FakeConn(), topics)
    elif roll < 34:
        conns.remove_user(user)
    elif roll < 52:
        if user in conns.users:
            conns.subscribe_user_to(user, topics)
    elif roll < 64:
        conns.unsubscribe_user_from(user, topics)
    elif roll < 72:
        if peer not in conns.brokers:
            conns.add_broker(peer, _FakeConn())
        else:
            conns.remove_broker(peer)
    elif roll < 82:
        # mesh topic sync: the peer (if linked) advertises a random flip
        if peer in conns.brokers:
            conns.apply_topic_sync(peer, _topic_payload(
                peer, [(t, bool(rng.integers(0, 2))) for t in topics]))
    else:
        # DirectMap merge: a peer claims some users (evicts local ones)
        claim = [USERS[int(i)] for i in
                 rng.integers(0, len(USERS), size=2)]
        conns.apply_user_sync(_sync_payload(peer, claim))


def _apply_random_sharded_op(rng, conns: Connections) -> None:
    roll = int(rng.integers(0, 100))
    user = USERS[int(rng.integers(0, len(USERS)))]
    topics = [int(t) for t in
              rng.choice(TOPICS, size=int(rng.integers(1, 3)))]
    if roll < 60:
        _apply_random_op(rng, conns)
    elif roll < 80:
        conns.set_remote_user(user, 1, topics)
    elif roll < 90:
        conns.remove_remote_user(user, 1)
    elif roll < 95:
        conns.set_remote_broker(PEERS[0], 0, topics)
    else:
        conns.remove_remote_broker(PEERS[0])


def _probe_chunk():
    """One chunk touching every topic + every known Direct recipient."""
    frames = []
    for t in TOPICS:
        frames.append(serialize(Broadcast([t], b"probe-t%d" % t)))
    frames.append(serialize(Broadcast(TOPICS, b"probe-all")))
    for u in USERS:
        frames.append(serialize(Direct(u, b"probe-d")))
    buf = bytearray()
    offs, lens = [], []
    for f in frames:
        offs.append(len(buf) + 4)
        lens.append(len(f))
        buf += len(f).to_bytes(4, "big") + f
    return (bytes(buf), np.asarray(offs, np.int64),
            np.asarray(lens, np.int64))


def _plan_map(state: cutthrough.RouteState, chunk, mode: int) -> dict:
    """{(kind, identity, shard): (frame indices...)} for one full plan —
    slot numbering is an implementation detail, identity+shard placement
    is the contract."""
    buf, offs, lens = chunk
    out: dict = {}
    pos, n = 0, len(offs)
    while pos < n:
        consumed, stop, peers, frames = state.planner.plan(
            buf, offs, lens, pos, mode)
        for p, f in zip(peers.tolist(), frames.tolist()):
            if p < state.user_cap:
                key = ("user", state.slot_user[p], state.user_shard[p])
            else:
                b = p - state.user_cap
                key = ("broker", state.slot_broker[b],
                       state.broker_shard[b])
            assert key[1] is not None, "plan emitted a freed slot"
            out.setdefault(key, []).append(f)
        pos += consumed
        if stop == routeplan.STOP_RESIDUAL:
            pos += 1
        assert stop != routeplan.STOP_END or pos >= n
    return {k: tuple(v) for k, v in out.items()}


def _fresh_twin(broker) -> cutthrough.RouteState:
    twin = cutthrough.RouteState(broker, routeplan.RoutePlanner.create())
    assert twin._refresh()
    return twin


def _check_equivalent(inc: cutthrough.RouteState, broker, chunk) -> None:
    assert inc._refresh(), "incremental refresh failed"
    twin = _fresh_twin(broker)
    for mode in (0, 1):
        assert _plan_map(inc, chunk, mode) == _plan_map(twin, chunk, mode)


@pytest.mark.parametrize("seed", range(6))
def test_incremental_equals_rebuild_random_interleavings(seed):
    rng = np.random.default_rng(9000 + seed)
    broker = _PlanBroker()
    inc = cutthrough.RouteState(broker,
                                routeplan.RoutePlanner.create())
    chunk = _probe_chunk()
    assert inc._refresh()
    for step in range(120):
        _apply_random_op(rng, broker.connections)
        if step % 3 == 0:  # refresh often enough to stay on deltas
            assert inc._refresh()
        if step % 10 == 9:
            _check_equivalent(inc, broker, chunk)
    _check_equivalent(inc, broker, chunk)
    # the run must have exercised the incremental path, not hidden
    # rebuilds: only the first build may appear
    assert inc.rebuild_counts == {"first_build": 1}, inc.rebuild_counts
    assert inc.deltas_applied > 50


@pytest.mark.parametrize("seed", range(4))
def test_incremental_equals_rebuild_sharded(seed):
    """2-shard flavor: remote users / remote broker links enter and
    leave the snapshot; shard placement is part of the compared plan."""
    rng = np.random.default_rng(9500 + seed)
    broker = _PlanBroker(shard_id=0, num_shards=2)
    inc = cutthrough.RouteState(broker,
                                routeplan.RoutePlanner.create())
    chunk = _probe_chunk()
    assert inc._refresh()
    for step in range(100):
        _apply_random_sharded_op(rng, broker.connections)
        if step % 2 == 0:
            assert inc._refresh()
        if step % 10 == 9:
            _check_equivalent(inc, broker, chunk)
    _check_equivalent(inc, broker, chunk)
    assert inc.rebuild_counts == {"first_build": 1}, inc.rebuild_counts


def test_delta_overflow_falls_back_and_recovers():
    """More pending deltas than the threshold: one labeled rebuild, then
    the state is equivalent and back on the delta path."""
    rng = np.random.default_rng(42)
    broker = _PlanBroker()
    inc = cutthrough.RouteState(broker, routeplan.RoutePlanner.create())
    chunk = _probe_chunk()
    assert inc._refresh()
    for _ in range(400):  # > max(256, live/2) dirty records, unrefreshed
        _apply_random_op(rng, broker.connections)
    _check_equivalent(inc, broker, chunk)
    assert inc.rebuild_counts.get("delta_overflow") == 1, \
        inc.rebuild_counts
    # back on deltas afterwards
    broker.connections.add_user(b"user-0", _FakeConn(), [0])
    _check_equivalent(inc, broker, chunk)
    assert inc.rebuild_counts.get("delta_overflow") == 1


def test_version_gap_falls_back_and_recovers(monkeypatch):
    """Trimmed route log (consumer fell behind the bound): the cursor
    predates the log start -> one version_gap rebuild, then equivalence."""
    monkeypatch.setattr(connections_mod, "ROUTE_LOG_MAX", 16)
    rng = np.random.default_rng(43)
    broker = _PlanBroker()
    inc = cutthrough.RouteState(broker, routeplan.RoutePlanner.create())
    chunk = _probe_chunk()
    assert inc._refresh()
    for _ in range(60):  # >> 16 records: the log trims past our cursor
        _apply_random_op(rng, broker.connections)
    assert broker.connections.route_log_start > inc.log_seq
    _check_equivalent(inc, broker, chunk)
    assert inc.rebuild_counts.get("version_gap") == 1, inc.rebuild_counts


def test_slot_growth_falls_back_and_recovers():
    """Exhausting the user slot free-list mid-delta triggers the growth
    rebuild (bigger capacity), and equivalence holds across it."""
    broker = _PlanBroker()
    inc = cutthrough.RouteState(broker, routeplan.RoutePlanner.create())
    chunk = _probe_chunk()
    assert inc._refresh()
    cap0 = inc.user_cap
    # connect far more users than the cold-start capacity headroom, in
    # small refreshed batches so every batch rides the delta path until
    # the free list runs dry
    for i in range(cap0 + 40):
        broker.connections.add_user(b"grow-%d" % i, _FakeConn(), [0])
        if i % 7 == 0:
            assert inc._refresh()
    _check_equivalent(inc, broker, chunk)
    assert inc.rebuild_counts.get("growth", 0) >= 1, inc.rebuild_counts
    assert inc.user_cap > cap0


def test_compaction_purges_lazy_garbage(monkeypatch):
    """Sustained subscribe/unsubscribe churn accrues lazy-deleted index
    entries; the periodic compaction check must trigger a labeled rebuild
    that purges them, with equivalence across the transition."""
    monkeypatch.setattr(cutthrough, "_COMPACT_CHECK_EVERY", 4)
    broker = _PlanBroker()
    conns = broker.connections
    for i in range(8):
        conns.add_user(b"user-%d" % i, _FakeConn(), [0])
    inc = cutthrough.RouteState(broker, routeplan.RoutePlanner.create())
    chunk = _probe_chunk()
    assert inc._refresh()
    # drive enough churn that list_entries outgrows 2*live + 1024. The
    # refresh must land BETWEEN the subscribe and the unsubscribe: a
    # sub/unsub pair inside one delta batch coalesces to a no-op mask
    # diff (the recheck-style apply resolves final state) and accrues no
    # garbage at all — itself a feature worth this comment.
    for round_ in range(300):
        for i in range(8):
            conns.subscribe_user_to(b"user-%d" % i, [1])
        assert inc._refresh()
        for i in range(8):
            conns.unsubscribe_user_from(b"user-%d" % i, [1])
        assert inc._refresh()
        if inc.rebuild_counts.get("compaction"):
            break
    assert inc.rebuild_counts.get("compaction", 0) >= 1, \
        (inc.rebuild_counts, inc.planner.stats())
    s = inc.planner.stats()
    assert s["list_entries"] <= 2 * s["live_subs"] + 1024
    _check_equivalent(inc, broker, chunk)


def test_delta_apply_is_o_delta_not_o_users():
    """The acceptance-criterion shape check: one subscribe against a
    10,000-user table must touch O(1) native state — asserted
    structurally (one dirty entity, one update row) and by the apply not
    scaling with the table (time-ratio guard with generous slack)."""
    import time as time_mod
    broker = _PlanBroker()
    conns = broker.connections
    for i in range(10_000):
        conns.add_user(b"u%05d" % i, _FakeConn(), [i % 2])
    inc = cutthrough.RouteState(broker, routeplan.RoutePlanner.create())
    assert inc._refresh()

    def one_delta_seconds() -> float:
        conns.subscribe_user_to(b"u00001", [1])
        t0 = time_mod.perf_counter()
        assert inc._refresh()
        dt = time_mod.perf_counter() - t0
        conns.unsubscribe_user_from(b"u00001", [1])
        assert inc._refresh()
        return dt

    samples = sorted(one_delta_seconds() for _ in range(7))
    # a rebuild at this size costs ~10ms+ (10k-row python loop); a true
    # O(delta) apply is microseconds. 2ms keeps slack for shared-core CI.
    assert samples[len(samples) // 2] < 0.002, samples
    assert inc.rebuild_counts == {"first_build": 1}, inc.rebuild_counts


def test_storm_rebuilds_arm_the_churn_guard(monkeypatch):
    """Review fix: version-gap / delta-overflow rebuilds recur at
    whatever rate EXTERNAL churn sustains (unlike growth/compaction,
    which are self-limiting), so a storm rebuild that never amortized
    must arm the demoted churn guard — the next invalidations route
    scalar (refresh returns False) instead of paying back-to-back
    O(users) rebuilds."""
    monkeypatch.setattr(connections_mod, "ROUTE_LOG_MAX", 16)
    rng = np.random.default_rng(77)
    broker = _PlanBroker()
    inc = cutthrough.RouteState(broker, routeplan.RoutePlanner.create())
    chunk = _probe_chunk()
    assert inc._refresh()
    # storm 1: outrun the log -> one version_gap rebuild (0 frames
    # amortized since first_build -> the guard arms)
    for _ in range(60):
        _apply_random_op(rng, broker.connections)
    assert inc._refresh()
    assert inc.rebuild_counts.get("version_gap") == 1
    assert inc._skip_rebuilds > 0
    # storm 2 while armed: refresh declines the rebuild (scalar fallback)
    for _ in range(60):
        _apply_random_op(rng, broker.connections)
    skips = inc._skip_rebuilds
    assert not inc._refresh()
    assert inc._skip_rebuilds == skips - 1
    assert inc.rebuild_counts.get("version_gap") == 1  # no second rebuild
    # amortization resets the guard: planned frames since the rebuild
    # mean the next storm pays a rebuild again, and equivalence holds
    inc._skip_rebuilds = 0
    inc._frames_since_rebuild = 1 << 20
    _check_equivalent(inc, broker, chunk)
    assert inc.rebuild_counts.get("version_gap") == 2
    assert inc._skip_rebuilds == 0  # amortized: the guard did not re-arm
