"""Wire-format round-trip tests for every message variant.

Parity with the reference's serialization tests
(cdn-proto/src/message.rs:396-457): every variant round-trips, payloads are
preserved exactly, malformed frames raise DESERIALIZE.
"""

import pytest

from pushcdn_tpu.proto import MAX_MESSAGE_SIZE
from pushcdn_tpu.proto.error import Error, ErrorKind
from pushcdn_tpu.proto.message import (
    AuthenticateResponse,
    AuthenticateWithKey,
    AuthenticateWithPermit,
    Broadcast,
    Direct,
    Subscribe,
    TopicSync,
    Unsubscribe,
    UserSync,
    deserialize,
    peek_kind,
    serialize,
)

VARIANTS = [
    AuthenticateWithKey(public_key=b"\x01" * 32, timestamp=1_700_000_000,
                        signature=b"\x02" * 64),
    AuthenticateWithKey(public_key=b"", timestamp=0, signature=b""),
    AuthenticateWithPermit(permit=2**63 + 17),
    AuthenticateResponse(permit=0, context="failed: not whitelisted"),
    AuthenticateResponse(permit=1, context=""),
    AuthenticateResponse(permit=99999, context="broker-0.example:1738"),
    Direct(recipient=b"\xaa" * 48, message=b"hello direct"),
    Direct(recipient=b"", message=b""),
    Broadcast(topics=[0, 1, 7], message=b"hello broadcast"),
    Broadcast(topics=[], message=b"x" * 1000),
    Subscribe([0, 1, 2]),
    Subscribe([]),
    Unsubscribe([255]),
    UserSync(payload=b"\x00\x01\x02 opaque rkyv-ish bytes"),
    TopicSync(payload=b""),
]


@pytest.mark.parametrize("msg", VARIANTS, ids=lambda m: type(m).__name__)
def test_round_trip(msg):
    frame = serialize(msg)
    assert peek_kind(frame) == msg.kind
    out = deserialize(frame)
    assert type(out) is type(msg)
    fields = (msg.__dataclass_fields__ if hasattr(msg, "__dataclass_fields__")
              else msg.__slots__)
    for f in fields:
        a, b = getattr(msg, f), getattr(out, f)
        if isinstance(a, (bytes, memoryview)) or isinstance(b, (bytes, memoryview)):
            assert bytes(a) == bytes(b), f
        else:
            assert a == b, f


def test_payload_is_zero_copy_view():
    msg = Broadcast(topics=[1], message=b"payload")
    frame = serialize(msg)
    out = deserialize(frame)
    assert isinstance(out.message, memoryview)
    assert bytes(out.message) == b"payload"


def test_empty_frame_rejected():
    with pytest.raises(Error) as ei:
        deserialize(b"")
    assert ei.value.kind == ErrorKind.DESERIALIZE


def test_unknown_kind_rejected():
    with pytest.raises(Error) as ei:
        deserialize(b"\xfe\x00\x00")
    assert ei.value.kind == ErrorKind.DESERIALIZE


@pytest.mark.parametrize("frame", [
    b"\x04\xff\xff\xff\xff",          # Direct: recipient length overruns
    b"\x01\x10\x00\x00\x00short",     # AuthWithKey: truncated pubkey
    b"\x02\x01",                      # AuthWithPermit: short
    b"\x06\x05\x00\x00\x01",          # Subscribe: count mismatch
])
def test_truncated_frames_rejected(frame):
    with pytest.raises(Error) as ei:
        deserialize(frame)
    assert ei.value.kind == ErrorKind.DESERIALIZE


def test_direct_large_payload_round_trip():
    payload = bytes(range(256)) * 1024  # 256 KiB
    msg = Direct(recipient=b"k" * 32, message=payload)
    out = deserialize(serialize(msg))
    assert bytes(out.message) == payload


def test_max_size_enforced_on_deserialize(monkeypatch):
    # Shrink the limit so the guard is exercised without a 512 MiB alloc.
    import pushcdn_tpu.proto.message as message_mod
    monkeypatch.setattr(message_mod, "MAX_MESSAGE_SIZE", 64)
    with pytest.raises(Error) as ei:
        deserialize(b"\x08" + b"z" * 100)
    assert ei.value.kind == ErrorKind.EXCEEDED_SIZE


def test_decode_frames_matches_deserialize_fuzz():
    """The batch chunk decoder must agree with the canonical per-frame
    path for every message kind and random shapes (it is the client
    drain's hot loop — a divergence is silent corruption)."""
    import random

    from pushcdn_tpu.proto.message import decode_frames, deserialize_owned

    rng = random.Random(1234)
    msgs = []
    for _ in range(200):
        kind = rng.randrange(6)
        if kind == 0:
            msgs.append(Direct(recipient=rng.randbytes(rng.randrange(0, 64)),
                               message=rng.randbytes(rng.randrange(0, 300))))
        elif kind == 1:
            msgs.append(Broadcast(
                topics=[rng.randrange(256)
                        for _ in range(rng.randrange(0, 5))],
                message=rng.randbytes(rng.randrange(0, 300))))
        elif kind == 2:
            msgs.append(Subscribe([rng.randrange(256)
                                   for _ in range(rng.randrange(0, 4))]))
        elif kind == 3:
            msgs.append(Unsubscribe([rng.randrange(256)]))
        elif kind == 4:
            msgs.append(UserSync(payload=rng.randbytes(rng.randrange(0, 64))))
        else:
            msgs.append(TopicSync(payload=rng.randbytes(rng.randrange(0, 64))))
    frames = [serialize(m) for m in msgs]
    # lay the frames out as one chunk buffer (offset/length spans)
    buf = bytearray()
    offs, lens = [], []
    for f in frames:
        offs.append(len(buf))
        lens.append(len(f))
        buf += f
    decoded = decode_frames(bytes(buf), offs, lens)
    assert len(decoded) == len(msgs)
    for got, f in zip(decoded, frames):
        want = deserialize_owned(f)
        assert type(got) is type(want)
        for field in getattr(want, "__slots__", None) or \
                want.__dataclass_fields__:
            a, b = getattr(got, field), getattr(want, field)
            if isinstance(a, (bytes, bytearray, memoryview)):
                assert bytes(a) == bytes(b), field
            else:
                assert a == b, field


def test_decode_frames_native_vs_python_paths(monkeypatch):
    """When the C batch decoder (native/pydecode.cpp) is available, it
    must produce the same objects AND the same failures as the Python
    loop — they are dual implementations of one spec."""
    import random

    import pushcdn_tpu.proto.message as message_mod
    from pushcdn_tpu.proto.message import decode_frames, deserialize_owned

    rng = random.Random(99)
    frames = []
    for _ in range(100):
        pick = rng.randrange(4)
        if pick == 0:
            frames.append(serialize(Broadcast(
                topics=[rng.randrange(256)
                        for _ in range(rng.randrange(0, 4))],
                message=rng.randbytes(rng.randrange(0, 200)))))
        elif pick == 1:
            frames.append(serialize(Direct(
                recipient=rng.randbytes(rng.randrange(0, 48)),
                message=rng.randbytes(rng.randrange(0, 200)))))
        elif pick == 2:  # cold kind via the fallback
            frames.append(serialize(Subscribe(
                topics=[rng.randrange(256)
                        for _ in range(rng.randrange(0, 4))])))
        else:  # empty-ish hot frames (boundary sizes)
            frames.append(serialize(Broadcast(topics=[], message=b"")))
    buf = bytearray()
    offs, lens = [], []
    for f in frames:
        offs.append(len(buf))
        lens.append(len(f))
        buf += f
    buf = bytes(buf)

    native_out = decode_frames(buf, offs, lens)
    # force the Python loop and compare
    monkeypatch.setattr(message_mod, "_native_decode", None)
    monkeypatch.setattr(message_mod, "_native_decode_tried", True)
    python_out = decode_frames(buf, offs, lens)
    assert len(native_out) == len(python_out) == len(frames)
    for a, b in zip(native_out, python_out):
        assert type(a) is type(b)
        assert a == b

    # malformed hot frames must raise the same Error on both paths
    bad_cases = [
        b"\x05\xff\xff",          # Broadcast claims 65535 topics in 3 B
        b"\x04\xff\xff\xff\x7f",  # Direct recipient overruns frame
    ]
    for bad in bad_cases:
        # pin the Python loop for py_err (decode_frames re-installs the
        # native fn as a side effect of the nat_err call below, so this
        # must be re-pinned every iteration)
        monkeypatch.setattr(message_mod, "_native_decode", None)
        monkeypatch.setattr(message_mod, "_native_decode_tried", True)
        with pytest.raises(Error) as py_err:
            decode_frames(bad, [0], [len(bad)])
        monkeypatch.setattr(message_mod, "_native_decode_tried", False)
        with pytest.raises(Error) as nat_err:
            decode_frames(bad, [0], [len(bad)])
        assert message_mod._native_decode is not None  # native path ran
        assert py_err.value.kind == nat_err.value.kind


def test_decode_frames_zero_copy_views(monkeypatch):
    """ISSUE 8 client-receive residue: zero-copy decode yields memoryview
    payloads over the shared buffer (both C and Python paths), the views
    keep the buffer alive past the chunk's release, and recipients stay
    owned bytes (dict keys)."""
    import gc

    from pushcdn_tpu.proto import message as message_mod
    from pushcdn_tpu.proto.message import decode_frames

    frames = [serialize(Broadcast([0, 1], b"payload-A")),
              serialize(Direct(b"rcpt", b"payload-B" * 100)),
              serialize(Subscribe([3]))]  # cold kind: owned decode
    buf = bytearray()
    offs, lens = [], []
    for f in frames:
        offs.append(len(buf))
        lens.append(len(f))
        buf += f
    buf = bytes(buf)

    for pin_python in (False, True):
        if pin_python:
            monkeypatch.setattr(message_mod, "_native_decode", None)
            monkeypatch.setattr(message_mod, "_native_decode_tried", True)
        else:
            monkeypatch.setattr(message_mod, "_native_decode_tried", False)
        out = decode_frames(buf, offs, lens, 0, zero_copy=True)
        b, d, s = out
        # sub-threshold payloads stay owned copies (ZERO_COPY_MIN: the
        # copy is cheaper than the view AND a retained view would pin
        # the whole chunk); at/above threshold = zero-copy views
        assert type(b.message) is bytes
        assert isinstance(d.message, memoryview)
        assert bytes(b.message) == b"payload-A"
        assert bytes(d.message) == b"payload-B" * 100
        assert type(d.recipient) is bytes and d.recipient == b"rcpt"
        assert s == Subscribe((3,))
        # equality against the owned-decode twin holds across the modes
        owned = decode_frames(buf, offs, lens, 0, zero_copy=False)
        assert out[0] == owned[0] and out[1] == owned[1]
        assert type(owned[1].message) is bytes

    # the views' reference chain keeps the buffer alive
    ref = decode_frames(buf, offs, lens, 0, zero_copy=True)
    del buf
    gc.collect()
    assert bytes(ref[1].message) == b"payload-B" * 100


def test_frame_chunk_decode_remaining_zero_copy():
    """FrameChunk.decode_remaining releases the chunk's pool permit while
    the returned views stay readable (buffer pinned by the views)."""
    from pushcdn_tpu.proto.limiter import MemoryPool
    from pushcdn_tpu.proto.transport.base import FrameChunk

    frames = [serialize(Broadcast([0], b"zc-%d" % i + b"x" * 300))
              for i in range(4)]
    buf = bytearray()
    offs, lens = [], []
    for f in frames:
        offs.append(len(buf))
        lens.append(len(f))
        buf += f
    buf = bytes(buf)
    pool = MemoryPool(1 << 16)
    permit = pool.try_allocate(len(buf))
    chunk = FrameChunk(buf, offs, lens, permit)
    msgs = chunk.decode_remaining()
    assert pool.available == pool.capacity  # permit returned at decode
    assert [bytes(m.message) for m in msgs] == \
        [b"zc-%d" % i + b"x" * 300 for i in range(4)]
    assert all(isinstance(m.message, memoryview) for m in msgs)
