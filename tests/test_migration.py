"""Elastic re-homing invariants (ISSUE 12).

A draining broker actively migrates its users with a typed ``Migrate``
frame; the client performs a make-before-break switch. The invariants
under test, all seeded and asserted against BOTH route implementations:

1. **no delivered-message loss or reorder** for a subscribed topic across
   a live migration (duplicates during the two-home overlap window are
   legal at-least-once handoff artifacts; the de-duplicated stream must
   be the complete, ordered sequence);
2. **a direct sent mid-migration reaches the user at exactly one home**
   — the DirectMap claim/eviction merge race never double-delivers and
   never opens a zero-home window, in the full 2-broker cluster and in
   the 1- and 2-shard worker harness;
3. the drain trail is observable: ``migrate-out`` on the old home's
   flight recorder, ``migrate-in`` on the new one.
"""

import asyncio

import pytest

from pushcdn_tpu.broker import rehome as rehome_mod
from pushcdn_tpu.broker.connections import SubscriptionStatus  # noqa: F401
from pushcdn_tpu.broker.tasks import cutthrough
from pushcdn_tpu.broker.versioned_map import VersionedMap
from pushcdn_tpu.proto import trace as trace_mod
from pushcdn_tpu.proto.error import Error
from pushcdn_tpu.proto.message import (
    Broadcast,
    Direct,
    Migrate,
    deserialize,
    serialize,
)
from pushcdn_tpu.proto.topic import TopicSpace
from pushcdn_tpu.proto.transport.base import FrameChunk
from pushcdn_tpu.testing.cluster import Cluster, wait_until

TOPIC = 1


def _route_impl(impl):
    if impl == "native" and not cutthrough.routeplan.available():
        pytest.skip("native route-plan kernel unavailable")


# ---------------------------------------------------------------------------
# wire format
# ---------------------------------------------------------------------------


def test_migrate_codec_roundtrip():
    for m in (Migrate(target="it0-b1-pub", permit=12345),
              Migrate(target="x" * 300, permit=2 ** 63),
              Migrate(target="no-permit"),  # permit=0: marshal fallback
              Migrate(target="")):
        assert deserialize(serialize(m)) == m


def test_migrate_codec_rejects_truncated():
    frame = serialize(Migrate(target="endpoint", permit=7))
    for cut in (1, len(frame) // 2, len(frame) - 1):
        with pytest.raises(Error):
            deserialize(frame[:cut])


# ---------------------------------------------------------------------------
# live-cluster migration: no loss, no reorder, exactly one home
# ---------------------------------------------------------------------------


def _seq(payload) -> int:
    return int.from_bytes(bytes(payload)[:4], "big")


async def _collect_seqs(client, total: int, out: list):
    """Drain broadcasts/directs into ``out`` (arrival order, raw — dups
    included) until every seq in [0, total) has been seen."""
    seen = set()
    while len(seen) < total:
        for m in await asyncio.wait_for(client.receive_messages(), 20.0):
            if isinstance(m, (Broadcast, Direct)):
                s = _seq(m.message)
                out.append(s)
                seen.add(s)


def _dedup(seqs):
    seen, ordered = set(), []
    for s in seqs:
        if s not in seen:
            seen.add(s)
            ordered.append(s)
    return ordered


async def _two_broker_pair(cluster, sub_topics):
    """Subscriber homed on broker 0, publisher on broker 1."""
    await cluster.place_on(0)
    sub = cluster.client(seed=82_000, topics=sub_topics)
    await asyncio.wait_for(sub.ensure_initialized(), 10.0)
    pk = sub.config.keypair.public_key
    await wait_until(lambda: cluster.brokers[0].connections.has_user(pk))
    await cluster.place_on(1)
    pub = cluster.client(seed=82_001)
    await asyncio.wait_for(pub.ensure_initialized(), 10.0)
    await wait_until(
        lambda: cluster.brokers[1].connections.num_users == 1)
    return sub, pub, pk


@pytest.mark.parametrize("impl", ["native", "python"])
async def test_no_loss_no_reorder_across_migration(impl):
    _route_impl(impl)
    total = 150
    prev_log = trace_mod.set_log_path(None)
    prev_impl = cutthrough.ROUTE_IMPL
    cutthrough.ROUTE_IMPL = impl
    try:
        cluster = await Cluster(num_brokers=2,
                                topics=TopicSpace.range(8)).start()
        try:
            sub, pub, pk = await _two_broker_pair(cluster, [TOPIC])
            b0, b1 = cluster.brokers
            # the publisher's home must know the old home wants TOPIC
            # before the stream starts (interest propagation is async)
            await wait_until(lambda: len(
                b1.connections.get_interested_by_topic([TOPIC], False)[1])
                == 1)
            old_rec = b0.connections.users[pk].connection.flightrec

            got: list = []
            collector = asyncio.create_task(_collect_seqs(sub, total, got))
            try:

                async def publish():
                    for s in range(total):
                        await pub.send_broadcast_message(
                            [TOPIC], s.to_bytes(4, "big") + b"payload")
                        await asyncio.sleep(0.002)

                publisher = asyncio.create_task(publish())
                # drain mid-stream: the subscriber is re-homed while
                # the topic is live
                await asyncio.sleep(0.1)
                summary = await rehome_mod.rehome_users(b0)
                assert summary["signaled"] == 1
                assert summary["orphaned"] == 0
                await asyncio.wait_for(publisher, 30.0)
                await asyncio.wait_for(collector, 30.0)
            finally:
                collector.cancel()

            # THE invariant: de-duplicated arrival order is the complete
            # published sequence — nothing lost, nothing reordered
            assert _dedup(got) == list(range(total)), (
                f"migration lost/reordered the stream: got {len(got)} "
                f"raw, {len(_dedup(got))} unique")

            # the user now lives at exactly one home — the new one
            await wait_until(lambda: b1.connections.has_user(pk))
            await wait_until(lambda: not b0.connections.has_user(pk))
            # flight-recorder trail on both sides of the handoff
            assert any(e == "migrate-out" for _, e, _ in old_rec._events)
            new_rec = b1.connections.users[pk].connection.flightrec
            assert any(e == "migrate-in" for _, e, _ in new_rec._events)
            sub.close()
            pub.close()
        finally:
            await cluster.stop()
    finally:
        cutthrough.ROUTE_IMPL = prev_impl
        trace_mod.set_log_path(prev_log)


@pytest.mark.parametrize("impl", ["native", "python"])
async def test_direct_mid_migration_exactly_one_home(impl):
    """Directs sent while the migration is in flight chase the user
    through the DirectMap CRDT row: every one arrives, none twice."""
    _route_impl(impl)
    total = 120
    prev_log = trace_mod.set_log_path(None)
    prev_impl = cutthrough.ROUTE_IMPL
    cutthrough.ROUTE_IMPL = impl
    try:
        cluster = await Cluster(num_brokers=2,
                                topics=TopicSpace.range(8)).start()
        try:
            sub, pub, pk = await _two_broker_pair(cluster, [TOPIC])
            b0, b1 = cluster.brokers
            # the sender's home must hold the DirectMap row for the
            # recipient (propagated by the strong-consistency UserSync)
            await wait_until(lambda: b1.connections.direct_map.get(pk)
                             == b0.connections.identity)

            got: list = []
            collector = asyncio.create_task(_collect_seqs(sub, total, got))
            try:

                async def send_directs():
                    for s in range(total):
                        await pub.send_direct_message(
                            pk, s.to_bytes(4, "big") + b"direct")
                        await asyncio.sleep(0.002)

                sender = asyncio.create_task(send_directs())
                await asyncio.sleep(0.1)
                summary = await rehome_mod.rehome_users(b0)
                assert summary["signaled"] == 1
                await asyncio.wait_for(sender, 30.0)
                await asyncio.wait_for(collector, 30.0)
            finally:
                collector.cancel()

            # exactly one home: every direct delivered exactly ONCE —
            # no zero-home drop, no two-home double delivery
            assert sorted(got) == list(range(total)), (
                f"mid-migration directs lost or duplicated: {len(got)} "
                f"deliveries of {len(set(got))} unique / {total} sent")
            await wait_until(lambda: b1.connections.has_user(pk))
            sub.close()
            pub.close()
        finally:
            await cluster.stop()
    finally:
        cutthrough.ROUTE_IMPL = prev_impl
        trace_mod.set_log_path(prev_log)


# ---------------------------------------------------------------------------
# DirectMap eviction/merge race in the sharded worker harness
# ---------------------------------------------------------------------------


async def _drain_messages(conn, settle_s: float = 0.05):
    got = []
    while True:
        try:
            items = await asyncio.wait_for(conn.recv_frames(), settle_s)
        except (asyncio.TimeoutError, Exception):
            return got
        for item in items:
            if type(item) is FrameChunk:
                got.extend(deserialize(bytes(mv)) for mv in item.views())
            else:
                got.append(deserialize(bytes(item.data)))
            item.release()


@pytest.mark.parametrize("impl", ["native", "python"])
@pytest.mark.parametrize("num_shards", [1, 2])
async def test_directmap_eviction_race_sharded(impl, num_shards):
    """A peer broker's out-versioning claim lands mid-stream of directs:
    pre-claim directs reach the local connection, post-claim directs are
    forwarded to the claimant, the evicted local record is gone — on the
    1-shard broker and across the worker ring (user on the NON-mesh
    shard, claim relayed over the shard bus)."""
    _route_impl(impl)
    from pushcdn_tpu.testing.shardharness import run_sharded
    prev_impl = cutthrough.ROUTE_IMPL
    cutthrough.ROUTE_IMPL = impl
    try:
        # user-0: migrating recipient on the LAST shard (cross-shard relay
        # when num_shards=2); user-1: direct sender on shard 0; one mesh
        # peer = the new home
        run = await run_sharded([(num_shards - 1, [0]), (0, [])],
                                num_shards=num_shards,
                                connected_brokers=[([0], [])])
        try:
            peer = run.peer(0)
            key = b"user-0"

            def direct_frames(lo, hi):
                return [serialize(Direct(
                    recipient=key, message=s.to_bytes(4, "big") + b"d"))
                    for s in range(lo, hi)]

            sender = run.user(1).remote
            await sender.send_raw_many(direct_frames(0, 10), flush=True)
            await run.settle(40)

            # the migration claim: the peer out-versions our DirectMap row
            # (exactly what the target's add_user produces)
            claim = VersionedMap(local_identity=peer.identifier)
            claim.insert(key, peer.identifier)
            claim.insert(key, peer.identifier)  # version 2 > local 1
            run.brokers[0].connections.apply_user_sync(
                VersionedMap.serialize_entries(claim.full()))
            await run.settle(40)
            # the eviction propagated to every shard
            assert not any(b.connections.has_user(key)
                           for b in run.brokers)
            assert all(b.connections.direct_map.get(key) == peer.identifier
                       for b in run.brokers)

            await sender.send_raw_many(direct_frames(10, 20), flush=True)
            await run.settle(40)

            local = [_seq(m.message)
                     for m in await _drain_messages(run.user(0).remote)
                     if isinstance(m, Direct)]
            chased = [_seq(m.message)
                      for m in await _drain_messages(peer.remote)
                      if isinstance(m, Direct)]
            # exactly one home per direct: the pre-claim batch landed
            # locally, the post-claim batch chased the user to the peer,
            # and no seq appears on both sides
            assert local == list(range(10)), f"pre-claim batch: {local}"
            assert chased == list(range(10, 20)), \
                f"post-claim batch: {chased}"
            assert not set(local) & set(chased)
        finally:
            await run.shutdown()
    finally:
        cutthrough.ROUTE_IMPL = prev_impl


@pytest.mark.parametrize("impl", ["native", "python"])
@pytest.mark.parametrize("num_shards", [1, 2])
async def test_late_forwarded_direct_chases_parting(impl, num_shards):
    """A forwarded direct that lands AFTER the migration claim — the
    sender's DirectMap replica was behind when it chose us as the home —
    must chase the evicted user over the ``parting`` connection instead
    of vanishing into the one-hop rule. This is the stale-replica loss
    window the swarm soak exposed at 500+ concurrent migrations: the
    publisher's broker keeps forwarding to the old home until the
    out-versioned row reaches it, and the old home used to drop every
    such frame the moment its own replica had flipped."""
    _route_impl(impl)
    from pushcdn_tpu.testing.shardharness import run_sharded
    prev_impl = cutthrough.ROUTE_IMPL
    cutthrough.ROUTE_IMPL = impl
    try:
        # recipient on shard 0 — the mesh shard, where broker-origin
        # frames are routed and where ``parting`` must be consulted; one
        # mesh peer plays both the new home and the stale forwarder
        run = await run_sharded([(0, [0])], num_shards=num_shards,
                                connected_brokers=[([0], [])])
        try:
            peer = run.peer(0)
            key = b"user-0"

            # the migration claim: the peer out-versions our DirectMap
            # row, evicting the local user into ``parting``
            claim = VersionedMap(local_identity=peer.identifier)
            claim.insert(key, peer.identifier)
            claim.insert(key, peer.identifier)  # version 2 > local 1
            run.brokers[0].connections.apply_user_sync(
                VersionedMap.serialize_entries(claim.full()))
            await run.settle(40)
            assert not run.brokers[0].connections.has_user(key)
            assert key in run.brokers[0].connections.parting

            # late frames from the stale forwarder: broker-origin, so
            # they arrive with to_user_only semantics and our replica
            # already names the peer as owner
            late = [serialize(Direct(
                recipient=key, message=s.to_bytes(4, "big") + b"late"))
                for s in range(5)]
            await peer.remote.send_raw_many(late, flush=True)
            await run.settle(40)

            got = [_seq(m.message)
                   for m in await _drain_messages(run.user(0).remote)
                   if isinstance(m, Direct)]
            assert got == list(range(5)), \
                f"late forwarded directs lost: {got}"
            # the one-hop rule still holds: nothing bounced back out to
            # the forwarder
            bounced = [m for m in await _drain_messages(peer.remote)
                       if isinstance(m, Direct)]
            assert not bounced, f"late directs re-forwarded: {bounced}"
        finally:
            await run.shutdown()
    finally:
        cutthrough.ROUTE_IMPL = prev_impl


@pytest.mark.parametrize("impl", ["native", "python"])
@pytest.mark.parametrize("num_shards", [1, 2])
async def test_late_broadcast_chases_parting(impl, num_shards,
                                             monkeypatch):
    """Broadcast twin of the stale-replica race — THE swarm-soak loss
    mechanism: a publisher's broker keeps fanning a topic to the old
    home until its TopicSync view of the new home catches up, and the
    old home used to have dropped the user's interest rows the instant
    the eviction landed — a zero-home window for every broadcast routed
    in between. The rows must outlive the eviction through the parting
    grace (delivering to the connection the client is still draining),
    then disappear when the grace expires."""
    _route_impl(impl)
    from pushcdn_tpu.broker import connections as conns_mod
    from pushcdn_tpu.testing.shardharness import run_sharded
    monkeypatch.setattr(conns_mod, "PARTING_GRACE_S", 0.2)
    prev_impl = cutthrough.ROUTE_IMPL
    cutthrough.ROUTE_IMPL = impl
    try:
        run = await run_sharded([(0, [TOPIC])], num_shards=num_shards,
                                connected_brokers=[([TOPIC], [])])
        try:
            peer = run.peer(0)
            key = b"user-0"
            claim = VersionedMap(local_identity=peer.identifier)
            claim.insert(key, peer.identifier)
            claim.insert(key, peer.identifier)  # version 2 > local 1
            run.brokers[0].connections.apply_user_sync(
                VersionedMap.serialize_entries(claim.full()))
            await run.settle(40)
            conns = run.brokers[0].connections
            assert key in conns.parting
            # the chase window: interest survives the eviction
            assert conns.user_topics.get_values_of_key(key)

            late = [serialize(Broadcast(
                topics=[TOPIC], message=s.to_bytes(4, "big") + b"late"))
                for s in range(5)]
            await peer.remote.send_raw_many(late, flush=True)
            await run.settle(40)
            got = [_seq(m.message)
                   for m in await _drain_messages(run.user(0).remote)
                   if isinstance(m, Broadcast)]
            assert got == list(range(5)), f"late broadcasts lost: {got}"

            # ... and the rows are gone once the grace expires
            await asyncio.sleep(0.5)
            assert key not in conns.parting
            assert not conns.user_topics.get_values_of_key(key)
        finally:
            await run.shutdown()
    finally:
        cutthrough.ROUTE_IMPL = prev_impl
