"""Frame-fate conservation ledger (ISSUE 20).

Four planes under test:

- the CLOSED fate taxonomy — a static sweep of the tree proves every
  instrumented call site uses a registered ``(fate, reason)`` pair and
  every registered pair has a call site (a new drop path cannot ship
  uncounted), plus the runtime refusal of unregistered pairs;
- seeded conservation — deterministic harness runs (single broker, mesh
  peer, abrupt teardown, 1/2 shards, python/native route impls) must
  balance the writer-plane identity ``queued == delivered + relayed +
  queue_drops + in_queue`` exactly, with the auditor's quiescence gate
  never flagging a clean run;
- the pumped-path fold — the C-side per-class counters (including the
  appended ``fate_drop_frames`` block) credit ``queued`` and the
  terminal fate in the same delta, so the identity holds with pump
  in-flight invisible by construction;
- the SLO burn engine + client gap detector — bulk loss burns its
  budget while consensus stays green, and delivery-sequence holes are
  detected (and healed) live at the client.
"""

import os
import re

import pytest

from pushcdn_tpu.broker.test_harness import TestDefinition
from pushcdn_tpu.client.client import GapDetector
from pushcdn_tpu.proto import flowclass
from pushcdn_tpu.proto import ledger as ledger_mod
from pushcdn_tpu.proto import metrics as metrics_mod
from pushcdn_tpu.proto.limiter import Bytes
from pushcdn_tpu.proto.message import Broadcast, serialize

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "pushcdn_tpu")


@pytest.fixture(autouse=True)
def _fresh_ledger():
    ledger_mod.reset_for_tests()
    yield
    ledger_mod.reset_for_tests()


def _walk_py_sources():
    for root, _dirs, files in os.walk(PKG):
        if "__pycache__" in root:
            continue
        for f in files:
            if f.endswith(".py"):
                path = os.path.join(root, f)
                with open(path) as fh:
                    yield path, fh.read()


# ---------------------------------------------------------------------------
# taxonomy: closed, exhaustive, and enforced


def test_taxonomy_every_call_site_is_registered():
    """Static sweep: every literal ``record_fate("f", "r", ...)`` and
    every ``ledger_drop_reason = "r"`` assignment in the tree names a
    pair present in TAXONOMY (the runtime check would raise, but a path
    only exercised under rare errors must not hide an unregistered
    reason until production hits it)."""
    call_re = re.compile(r'record_fate\(\s*"(\w+)",\s*"(\w+)"')
    drop_re = re.compile(r'ledger_drop_reason = "(\w+)"')
    seen = set()
    for path, text in _walk_py_sources():
        for fate, reason in call_re.findall(text):
            assert (fate, reason) in ledger_mod.TAXONOMY, \
                f"{path} records unregistered fate {(fate, reason)}"
            seen.add((fate, reason))
        for reason in drop_re.findall(text):
            assert ("dropped", reason) in ledger_mod.TAXONOMY, \
                f"{path} assigns unregistered drop reason {reason!r}"
            seen.add(("dropped", reason))
    assert seen, "the sweep found no instrumented call sites at all"


def test_taxonomy_every_entry_has_a_call_site():
    """The reverse direction: every registered reason string appears as
    a quoted literal somewhere in the tree OUTSIDE the taxonomy
    definition itself — a taxonomy row with no instrumentation is dead
    weight that falsely implies coverage."""
    ledger_py = os.path.join(PKG, "proto", "ledger.py")
    corpus = "".join(text for path, text in _walk_py_sources()
                     if os.path.abspath(path) != ledger_py)
    # the two dequeue fates are recorded through the on_dequeued wrapper
    # in ledger.py; their proof of coverage is the wrapper's call sites
    corpus += "".join(text for _p, text in _walk_py_sources()
                      if "on_dequeued" in text)
    for (fate, reason) in ledger_mod.TAXONOMY:
        if (fate, reason) in (("delivered", "egress"), ("relayed", "mesh")):
            assert re.search(r"on_dequeued\(", corpus), \
                "no on_dequeued call sites — dequeue fates uncovered"
            continue
        assert f'"{reason}"' in corpus, \
            f"taxonomy entry {(fate, reason)} has no call site in the tree"


def test_record_fate_refuses_unregistered_pairs():
    with pytest.raises(ValueError):
        ledger_mod.LEDGER.record_fate("dropped", "cosmic_rays", 0)
    with pytest.raises(ValueError):
        ledger_mod.LEDGER.record_fate("delivered", "no_route", 0)


def test_class_axis_maps_out_of_range_to_none():
    L = ledger_mod.LEDGER
    L.note_queued(flowclass.CLASS_NONE, 3)
    L.note_queued(2, 1)
    assert L.queued[ledger_mod.IDX_NONE] == 3
    assert L.queued[2] == 1


# ---------------------------------------------------------------------------
# seeded conservation: harness runs must balance EXACTLY


def _assert_balanced(note: str):
    """The writer-plane identity, checked the way the auditor checks it
    (derived vs an actual queue walk), plus the quiescence rule: two
    back-to-back ticks on an idle ledger must never flag a clean run."""
    L = ledger_mod.LEDGER
    derived = L.derived_in_queue()
    actual = L.walk_live_queues()
    assert sum(derived) == actual, \
        (f"{note}: queued={L.queued} fates={L.fates} derived={derived} "
         f"actual_walk={actual}")
    assert all(d >= 0 for d in derived), f"{note}: negative balance {derived}"
    for _ in range(3):
        L.check_conservation()
    assert L.violations == 0, f"{note}: clean run flagged a violation"


async def _drain_writers():
    """Yield until every live connection's send queue is empty (writer
    tasks run on this same loop)."""
    import asyncio
    for _ in range(200):
        if ledger_mod.LEDGER.walk_live_queues() == 0:
            return
        await asyncio.sleep(0.01)


@pytest.mark.parametrize("route_impl", ("python", "native"))
async def test_conservation_clean_run_balances(route_impl):
    """Broadcast fan-out to local users + a mesh peer: every queued
    frame lands as delivered/egress or relayed/mesh, the per-link sent
    table matches what went toward the peer, and the identity balances
    to zero in-queue after drain."""
    from pushcdn_tpu.broker.tasks import cutthrough
    if route_impl == "native":
        from pushcdn_tpu.native import routeplan
        if not routeplan.available():
            pytest.skip("native route planner unavailable")
    prev = cutthrough.ROUTE_IMPL
    cutthrough.ROUTE_IMPL = route_impl
    try:
        run = await TestDefinition(
            connected_users=[[0], [0]],
            connected_brokers=[([0], [])],
        ).run()
        try:
            for i in range(10):
                msg = Broadcast(topics=[0], message=b"x%d" % i)
                await run.send_message_as(run.user(0), msg)
                await run.assert_received(run.user(1), msg)
                await run.assert_received(run.peer(0), msg)
            await _drain_writers()
            L = ledger_mod.LEDGER
            _assert_balanced(f"clean run ({route_impl})")
            fates = {k: sum(v) for k, v in L.fates.items()}
            assert fates.get(("delivered", "egress"), 0) >= 20, fates
            assert fates.get(("relayed", "mesh"), 0) >= 10, fates
            # the peer's link table: the 10 relays (plus any control
            # frames) were counted at decision time under its identity
            peer_ident = run.connected_brokers[0].identifier
            assert sum(L.link_sent.get(peer_ident, [])) >= 10, L.link_sent
        finally:
            await run.shutdown()
    finally:
        cutthrough.ROUTE_IMPL = prev


async def test_conservation_abrupt_teardown_counts_drops():
    """Frames queued toward a user whose connection is torn down before
    the writer drains them must take a counted drop fate — the identity
    balances with real loss, not by losing track of it."""
    run = await TestDefinition(connected_users=[[0]]).run()
    try:
        conn = run.broker.connections.get_user_connection(b"user-0")
        assert conn is not None
        # enqueue synchronously, then tear down in the SAME event-loop
        # tick — the writer task never gets to pop these
        for i in range(5):
            conn.send_raw_nowait(Bytes(serialize(
                Broadcast(topics=[0], message=b"doomed%d" % i))), cls=2)
        run.broker.connections.remove_user(b"user-0", reason="test kill")
        await _drain_writers()
        L = ledger_mod.LEDGER
        dropped = sum(n for (fate, _r), row in L.fates.items()
                      for n in row if fate == "dropped")
        assert dropped >= 5, L.fates
        _assert_balanced("abrupt teardown")
    finally:
        await run.shutdown()


@pytest.mark.parametrize("num_shards", (1, 2))
async def test_conservation_sharded_run_balances(num_shards):
    """The sharded twin: a cross-shard broadcast rides the handoff ring
    (relayed/shard_ring — outside the writer identity) and the combined
    in-process ledger still balances exactly."""
    from pushcdn_tpu.testing.shardharness import run_sharded
    run = await run_sharded([(0, [0]), (num_shards - 1, [0])],
                            num_shards=num_shards)
    try:
        raw = Bytes(serialize(Broadcast(topics=[0], message=b"x-shard")))
        await run.user(0).remote.send_raw_many([raw], flush=True)
        await run.settle(40)
        await _drain_writers()
        L = ledger_mod.LEDGER
        _assert_balanced(f"sharded run ({num_shards} shards)")
        delivered = sum(L.fates.get(("delivered", "egress"), [0]))
        assert delivered >= 1, L.fates
        if num_shards == 2:
            assert sum(L.fates.get(("relayed", "shard_ring"),
                                   [0])) >= 1, L.fates
    finally:
        await run.shutdown()


async def test_link_epoch_reset_on_reconnect():
    """A re-formed mesh link starts a fresh per-link conservation epoch:
    stale sent/recv counters from the previous connection (already
    audited while the link was down) must not poison the new balance."""
    run = await TestDefinition(connected_brokers=[([0], [])]).run()
    try:
        ident = run.connected_brokers[0].identifier
        L = ledger_mod.LEDGER
        L.note_link_sent(ident, 0, 7)
        L.note_ingress(0, 3, peer=ident)
        L.note_peer_sheet(ident, {"boot": 1.0, "link_sent": {}})
        assert ident in L.link_sent and ident in L.link_recv
        # same identity reconnects (add_broker evicts + re-adds)
        from pushcdn_tpu.broker.tasks.handlers import broker_receive_loop
        from pushcdn_tpu.proto.transport.memory import (
            gen_testing_connection_pair)
        from pushcdn_tpu.proto.util import AbortOnDropHandle
        import asyncio
        local, remote = await gen_testing_connection_pair(
            run.broker.limiter)
        task = asyncio.create_task(
            broker_receive_loop(run.broker, ident, local))
        run.broker.connections.add_broker(ident, local,
                                          AbortOnDropHandle(task))
        assert ident not in L.link_sent
        assert ident not in L.link_recv
        # and a peer RESTART detected via the boot epoch resets too:
        # the first sheet after a link reset merely anchors (no double
        # reset); a *changed* boot on a later sheet clears the tables
        L.note_peer_sheet(ident, {"boot": 1.5, "link_sent": {}})
        L.note_link_sent(ident, 0, 2)
        L.note_peer_sheet(ident, {"boot": 2.0, "link_sent": {}})
        assert ident not in L.link_sent
        remote.close()
    finally:
        await run.shutdown()


# ---------------------------------------------------------------------------
# pumped-path fold: C counters -> queued + terminal fate in one delta


def _fold(class_frames: dict, drop_frames: dict) -> None:
    metrics_mod.update_native_telemetry({
        "stage": {}, "chain": {}, "class_delay": {},
        "class_frames": class_frames, "class_bytes": {},
        "class_drop_frames": drop_frames,
    })


def test_pump_fold_credits_queued_and_fate_in_same_delta():
    # isolate the module-level high-water trackers
    saved = dict(metrics_mod._native_class_last)
    metrics_mod._native_class_last.clear()
    try:
        _fold({"live": 10, "bulk": 4}, {"bulk": 2})
        L = ledger_mod.LEDGER
        assert L.queued[2] == 10 and L.queued[3] == 6
        assert L.fates[("delivered", "pumped")][2] == 10
        assert L.fates[("delivered", "pumped")][3] == 4
        assert L.fates[("dropped", "pump_peer_poison")][3] == 2
        _assert_balanced("pump fold")
        # re-folding the SAME totals is a no-op (delta, not absolute)
        _fold({"live": 10, "bulk": 4}, {"bulk": 2})
        assert L.queued[2] == 10 and L.queued[3] == 6
        # growth folds only the delta
        _fold({"live": 12, "bulk": 4}, {"bulk": 3})
        assert L.queued[2] == 12
        assert L.fates[("dropped", "pump_peer_poison")][3] == 3
        _assert_balanced("pump fold (delta)")
    finally:
        metrics_mod._native_class_last.clear()
        metrics_mod._native_class_last.update(saved)


def test_native_fate_drop_counters_roundtrip():
    """The C-side test hook bumps the appended fate_drop_frames block and
    parse_telemetry surfaces it per class — the seam the live pump's
    run_dropped() instrumentation writes through."""
    from pushcdn_tpu.native import uring as nuring
    if not nuring.available():
        pytest.skip("native io_uring unavailable")
    ring = nuring.Ring(8)
    try:
        if not ring.enable_telemetry():
            pytest.skip("telemetry shm unavailable")
        assert ring.telemetry_test_count(0, 2, 9) == 0   # class_frames
        assert ring.telemetry_test_count(1, 2, 4) == 0   # fate_drop_frames
        assert ring.telemetry_test_count(1, 3, 1) == 0
        snap = nuring.parse_telemetry(ring.telemetry_snapshot())
        assert snap["class_frames"]["live"] == 9
        assert snap["class_drop_frames"]["live"] == 4
        assert snap["class_drop_frames"]["bulk"] == 1
        # invalid axes refuse
        assert ring.telemetry_test_count(2, 0, 1) < 0
        assert ring.telemetry_test_count(0, 99, 1) < 0
    finally:
        ring.close()


# ---------------------------------------------------------------------------
# SLO burn engine


def test_slo_bulk_burn_fires_while_consensus_stays_green():
    """Seeded loss: bulk drops 1% of its frames against a 0.1% budget
    (burn 10x), consensus delivers everything — the burn gauge must fire
    for bulk on every window and stay zero for consensus."""
    L = ledger_mod.LEDGER
    engine = ledger_mod.SloEngine(L)
    engine.tick(now=1000.0)
    # 10_000 bulk attempts with 100 counted losses; consensus clean
    L.record_fate("delivered", "egress", flowclass.BULK, 9_900)
    L.record_fate("dropped", "send_failed", flowclass.BULK, 100)
    L.record_fate("delivered", "egress", flowclass.CONSENSUS, 5_000)
    engine.tick(now=1030.0)
    for w in engine.windows:
        wl = f"{int(w)}s"
        bulk = ledger_mod.SLO_BURN.labels(slo="loss_bulk", window=wl)
        cons = ledger_mod.SLO_BURN.labels(slo="loss_consensus", window=wl)
        assert bulk.value == pytest.approx(
            (100 / 10_000) / engine.loss_budget[flowclass.BULK]), wl
        assert bulk.value > 1.0, f"bulk burn must fire ({wl})"
        assert cons.value == 0.0, f"consensus must stay green ({wl})"


def test_slo_benign_drops_do_not_burn_budget():
    """no_interest / malformed / retention_evict are not loss — a topic
    nobody wants must not page anyone."""
    L = ledger_mod.LEDGER
    engine = ledger_mod.SloEngine(L)
    engine.tick(now=2000.0)
    L.record_fate("delivered", "egress", flowclass.LIVE, 100)
    L.record_fate("dropped", "no_interest", flowclass.LIVE, 50)
    L.record_fate("dropped", "retention_evict", flowclass.LIVE, 50)
    engine.tick(now=2030.0)
    wl = f"{int(engine.windows[0])}s"
    assert ledger_mod.SLO_BURN.labels(slo="loss_live",
                                       window=wl).value == 0.0


def test_slo_window_bases_age_out():
    """Old samples fall off the horizon: a burst of loss stops burning
    once every window's base has moved past it."""
    L = ledger_mod.LEDGER
    engine = ledger_mod.SloEngine(L)
    engine.tick(now=0.0)
    L.record_fate("delivered", "egress", flowclass.LIVE, 900)
    L.record_fate("dropped", "send_failed", flowclass.LIVE, 100)
    engine.tick(now=1.0)
    wl = f"{int(max(engine.windows))}s"
    assert ledger_mod.SLO_BURN.labels(slo="loss_live",
                                       window=wl).value > 0
    # advance far past the largest window with no new traffic
    horizon = max(engine.windows)
    t = 1.0
    while t < horizon * 2:
        t += horizon / 4
        engine.tick(now=t)
    assert ledger_mod.SLO_BURN.labels(slo="loss_live",
                                       window=wl).value == 0.0


# ---------------------------------------------------------------------------
# client-side live gap detector


def test_gap_detector_anchor_open_heal_duplicate():
    det = GapDetector()
    # late join anchors, never counts a gap
    det.observe("t", 5)
    assert det.events == 0 and det.unique == 1
    # in-order advance
    det.observe("t", 6)
    assert det.events == 0 and det.unique == 2
    # jump opens holes 7,8
    det.observe("t", 9)
    assert det.events == 2 and det.open_gaps == 2
    # late arrival heals one
    det.observe("t", 7)
    assert det.healed == 1 and det.open_gaps == 1
    # replay of a seen seq is a duplicate (legal)
    det.observe("t", 6)
    assert det.duplicates == 1
    assert det.unique == 4          # 5,6,9,7
    assert det.open_gaps == 1       # 8 still missing


def test_gap_detector_streams_are_independent():
    det = GapDetector()
    det.observe("a", 1)
    det.observe("a", 3)             # opens 2 on stream a
    det.observe("b", 100)           # fresh anchor on b — no gap
    assert det.events == 1 and det.open_gaps == 1
    det.observe("b", 101)
    assert det.events == 1


def test_gap_detector_open_set_is_bounded():
    det = GapDetector()
    det.observe("t", 0)
    det.observe("t", det.MAX_OPEN * 3)      # a catastrophic jump
    # events counts every skipped frame; the tracked set stays bounded
    assert det.events == det.MAX_OPEN * 3 - 1
    assert len(det._holes["t"]) <= det.MAX_OPEN


def test_gap_metrics_follow_detector(monkeypatch):
    ev0 = metrics_mod.CLIENT_GAP_EVENTS.value
    he0 = metrics_mod.CLIENT_GAP_HEALED.value
    det = GapDetector()
    det.observe("t", 1)
    det.observe("t", 4)     # opens 2,3
    det.observe("t", 2)     # heals 2
    assert metrics_mod.CLIENT_GAP_EVENTS.value - ev0 == 2
    assert metrics_mod.CLIENT_GAP_HEALED.value - he0 == 1


# ---------------------------------------------------------------------------
# /debug/ledger + auditor surface


async def test_ledger_route_and_auditor_sheet():
    run = await TestDefinition(connected_users=[[0]]).run()
    try:
        msg = Broadcast(topics=[0], message=b"ping")
        await run.send_message_as(run.user(0), msg)
        await run.assert_received(run.user(0), msg)
        await _drain_writers()
        ledger_mod.LEDGER.my_ident = "me"
        doc = ledger_mod.ledger_route({})
        local = doc["local"]
        assert local["ident"] == "me"
        assert local["boot"] == ledger_mod.LEDGER.boot
        assert sum(local["queued"].values()) >= 1
        assert doc["conservation"]["violations"] == 0
        # fates keys render as "fate/reason" and stay inside the taxonomy
        for key in local["fates"]:
            fate, _, reason = key.partition("/")
            assert (fate, reason) in ledger_mod.TAXONOMY
    finally:
        await run.shutdown()
