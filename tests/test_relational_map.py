"""RelationalMap bidirectional-multimap invariants (parity with the tests at
cdn-broker/src/connections/broadcast/relational_map.rs:119-347)."""

import random

from pushcdn_tpu.broker.relational_map import RelationalMap


def test_associate_and_lookup():
    m = RelationalMap()
    m.associate_key_with_values(b"u1", [0, 1])
    m.associate_key_with_values(b"u2", [1, 2])
    assert m.get_values_of_key(b"u1") == {0, 1}
    assert m.get_keys_by_value(1) == {b"u1", b"u2"}
    assert m.get_keys_by_values([0, 2]) == {b"u1", b"u2"}
    assert m.get_keys_by_values([5]) == set()
    assert m.check_invariants()


def test_dissociate():
    m = RelationalMap()
    m.associate_key_with_values(b"u1", [0, 1, 2])
    m.dissociate_key_from_values(b"u1", [1])
    assert m.get_values_of_key(b"u1") == {0, 2}
    assert m.get_keys_by_value(1) == set()
    # dissociating everything drops the key entirely
    m.dissociate_key_from_values(b"u1", [0, 2])
    assert b"u1" not in m
    assert len(m) == 0
    assert m.check_invariants()


def test_remove_key():
    m = RelationalMap()
    m.associate_key_with_values(b"u1", [0, 1])
    m.associate_key_with_values(b"u2", [1])
    gone = m.remove_key(b"u1")
    assert gone == {0, 1}
    assert m.get_keys_by_value(1) == {b"u2"}
    assert m.get_keys_by_value(0) == set()
    assert m.check_invariants()


def test_dissociate_missing_is_noop():
    m = RelationalMap()
    m.dissociate_key_from_values(b"ghost", [1, 2])
    assert m.remove_key(b"ghost") == set()
    assert m.check_invariants()


def test_randomized_invariants():
    rng = random.Random(1234)
    m = RelationalMap()
    keys = [f"k{i}".encode() for i in range(10)]
    for _ in range(2000):
        op = rng.randrange(3)
        key = rng.choice(keys)
        vals = [rng.randrange(8) for _ in range(rng.randrange(1, 4))]
        if op == 0:
            m.associate_key_with_values(key, vals)
        elif op == 1:
            m.dissociate_key_from_values(key, vals)
        else:
            m.remove_key(key)
    assert m.check_invariants()
