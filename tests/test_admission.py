"""Admission control & load shedding (ISSUE 7).

Covers the three tiers end to end against a real in-process broker:
per-tier connection budgets (typed pre-auth refusal), the per-connection
subscribe-rate token bucket (drop + typed notice through the ordered
egress path, identical on the cut-through and scalar impls), and the
surfacing contract — ``cdn_route_shed_total{tier}``, the ``load-shed``
flight-recorder event, and the ``/readyz`` ``admission`` check flipping
false for the shed window then recovering. Plus the client library's
typed ``Error(SHED)`` surfacing (never a silent drop, never a teardown).
"""

import asyncio
import time

import pytest

from pushcdn_tpu.broker.admission import AdmissionControl
from pushcdn_tpu.broker.tasks import cutthrough, listeners
from pushcdn_tpu.broker.test_harness import TestDefinition
from pushcdn_tpu.client import Client, ClientConfig
from pushcdn_tpu.proto import metrics as metrics_mod
from pushcdn_tpu.proto.crypto.signature import DEFAULT_SCHEME
from pushcdn_tpu.proto.error import Error, ErrorKind
from pushcdn_tpu.proto.message import (
    AuthenticateResponse,
    Broadcast,
    Subscribe,
    Unsubscribe,
    deserialize,
    serialize,
)
from pushcdn_tpu.proto.transport.base import FrameChunk
from pushcdn_tpu.proto.transport.memory import gen_testing_connection_pair
from pushcdn_tpu.proto.transport.tcp import Tcp


class _FakeConn:
    """Just enough surface for the token bucket + flight recorder."""

    def __init__(self):
        from pushcdn_tpu.proto import flightrec
        self.flightrec = flightrec.FlightRecorder("fake")


class _FakeBroker:
    def __init__(self, num_users=0, num_brokers=0):
        class _C:
            pass
        self.connections = _C()
        self.connections.num_users = num_users
        self.connections.num_brokers = num_brokers


def _adm(broker=None, **kw) -> AdmissionControl:
    adm = AdmissionControl(broker or _FakeBroker())
    for k, v in kw.items():
        setattr(adm, k, v)
    return adm


# ---------------------------------------------------------------------------
# unit: token bucket + budgets
# ---------------------------------------------------------------------------

def test_token_bucket_burst_then_refuse_then_refill():
    adm = _adm(subscribe_rate=1.0, subscribe_burst=3.0)
    conn = _FakeConn()
    assert all(adm.allow_subscribe(conn) for _ in range(3))
    assert not adm.allow_subscribe(conn)
    # refill: pretend the last update was 2.5 s ago -> 2 whole tokens
    conn._sub_bucket[1] -= 2.5
    assert adm.allow_subscribe(conn)
    assert adm.allow_subscribe(conn)
    assert not adm.allow_subscribe(conn)


def test_token_bucket_disabled_and_connless_always_allow():
    adm = _adm(subscribe_rate=0.0)
    assert adm.allow_subscribe(_FakeConn())
    adm = _adm(subscribe_rate=1.0, subscribe_burst=1.0)
    assert adm.allow_subscribe(None)
    assert adm.allow_subscribe(None)  # no seat to meter: never refuse


def test_connection_budgets_and_ready_window():
    adm = _adm(_FakeBroker(num_users=2, num_brokers=1),
               max_user_conns=2, max_broker_conns=2, ready_window_s=0.2)
    ok, detail = adm.readiness_check()
    assert ok, detail
    reason = adm.admit_user()
    assert reason is not None and "shed" in reason
    assert adm.admit_broker() is None  # broker tier under budget
    ok, detail = adm.readiness_check()
    assert not ok and "user_conn" in detail
    time.sleep(0.25)
    ok, _ = adm.readiness_check()
    assert ok  # window elapsed: back in rotation
    assert adm.summary()["shed_counts"] == {"user_conn": 1}


def test_unconfigured_admission_is_always_ready():
    adm = _adm(max_user_conns=0, max_broker_conns=0, subscribe_rate=0.0)
    assert adm.admit_user() is None
    assert adm.admit_broker() is None
    ok, detail = adm.readiness_check()
    assert ok and "disabled" in detail


# ---------------------------------------------------------------------------
# end to end: subscribe-rate shed through a real broker, both impls
# ---------------------------------------------------------------------------

async def _drain_frames(conn, settle_s=0.1):
    got = []
    while True:
        try:
            items = await asyncio.wait_for(conn.recv_frames(), settle_s)
        except (asyncio.TimeoutError, Exception):
            return got
        for item in items:
            if type(item) is FrameChunk:
                got.extend(bytes(mv) for mv in item.views())
            else:
                got.append(bytes(item.data))
            item.release()


@pytest.mark.parametrize("impl", ["native", "python"])
async def test_subscribe_shed_end_to_end(impl):
    if impl == "native" and not cutthrough.routeplan.available():
        pytest.skip("native route-plan kernel unavailable")
    prev = cutthrough.ROUTE_IMPL
    cutthrough.ROUTE_IMPL = impl
    try:
        run = await TestDefinition(connected_users=[[], [1]]).run()
        adm = run.broker.admission
        adm.subscribe_rate = 0.001  # effectively no refill in-test
        adm.subscribe_burst = 2.0
        adm.ready_window_s = 5.0
        shed0 = metrics_mod.ROUTE_SHED_SUBSCRIBE.value
        try:
            sender = run.user(0).remote
            # 2 allowed (burst), 3 shed; the broadcast AFTER the storm
            # must still deliver — shedding degrades, never disconnects
            frames = [serialize(Subscribe([0]))] * 2 \
                + [serialize(Subscribe([1])), serialize(Unsubscribe([0])),
                   serialize(Subscribe([1]))] \
                + [serialize(Broadcast([1], b"still-alive"))]
            await sender.send_raw_many(frames, flush=True)
            await asyncio.sleep(0.2)

            assert run.broker.connections.has_user(b"user-0")
            # the sheds were NOT applied: user-0 holds only the 2
            # admitted subscriptions (topic 0), never topic 1
            topics = run.broker.connections.user_topics.get_values_of_key(
                b"user-0")
            assert topics == {0}, topics
            # exactly 3 typed notices back to the sender, none silent
            got = [deserialize(f) for f in await _drain_frames(sender)]
            notices = [m for m in got
                       if isinstance(m, AuthenticateResponse)]
            assert len(notices) == 3, got
            assert all(m.permit == 0 and "shed" in m.context
                       for m in notices)
            assert metrics_mod.ROUTE_SHED_SUBSCRIBE.value - shed0 == 3
            ok, detail = adm.readiness_check()
            assert not ok and "subscribe" in detail
            # user-1 (subscribed to 1) still got the broadcast
            got1 = [deserialize(f)
                    for f in await _drain_frames(run.user(1).remote)]
            assert any(isinstance(m, Broadcast)
                       and bytes(m.message) == b"still-alive"
                       for m in got1), got1
            # recovery: age the shed stamps past the window (no sleeps)
            adm.last_shed = {tier: ts - 10.0
                             for tier, ts in adm.last_shed.items()}
            ok, _ = adm.readiness_check()
            assert ok  # recovered once the window passed
        finally:
            await run.shutdown()
    finally:
        cutthrough.ROUTE_IMPL = prev


# ---------------------------------------------------------------------------
# end to end: user connection budget refused pre-auth with a typed reply
# ---------------------------------------------------------------------------

class _FakeUnfinalized:
    def __init__(self, conn):
        self._conn = conn

    async def finalize(self, limiter):
        return self._conn


async def test_user_connection_budget_typed_refusal():
    run = await TestDefinition(connected_users=[[0]]).run()
    adm = run.broker.admission
    adm.max_user_conns = 1  # already at capacity with the injected user
    try:
        local, remote = await gen_testing_connection_pair(
            run.broker.limiter)
        await listeners.handle_user_connection(
            run.broker, _FakeUnfinalized(local))
        # the refusal is typed: permit=0 + the shed reason, pre-auth
        raw = await asyncio.wait_for(remote.recv_raw(), 2.0)
        msg = deserialize(raw.data)
        raw.release()
        assert isinstance(msg, AuthenticateResponse)
        assert msg.permit == 0 and "shed" in msg.context
        assert "PUSHCDN_MAX_CONNS_USER" in msg.context
        ok, detail = adm.readiness_check()
        assert not ok and "user_conn" in detail
        # no second user was registered
        assert run.broker.connections.num_users == 1
    finally:
        await run.shutdown()


# ---------------------------------------------------------------------------
# client library: the typed Error(SHED) surface
# ---------------------------------------------------------------------------

class _StubConn:
    """Minimal Connection stand-in for the client receive paths."""

    is_closed = False

    def __init__(self, messages=None, items=None):
        self._messages = list(messages or [])
        self._items = items

    async def recv_message(self):
        return self._messages.pop(0)

    async def recv_frames(self, n=1024):
        items, self._items = self._items, []
        return items

    def close(self):
        self.is_closed = True


class _StubItem:
    def __init__(self, frame: bytes):
        self.data = frame

    def release(self):
        pass


def _client() -> Client:
    return Client(ClientConfig(
        marshal_endpoint="127.0.0.1:1", protocol=Tcp,
        keypair=DEFAULT_SCHEME.generate_keypair(seed=1)))


async def test_client_receive_message_raises_typed_shed():
    client = _client()
    client._connection = _StubConn(messages=[
        AuthenticateResponse(permit=0, context="shed: subscribe rate")])
    with pytest.raises(Error) as ei:
        await client.receive_message()
    assert ei.value.kind == ErrorKind.SHED
    assert "shed" in str(ei.value)
    # NOT reconnectable, and the connection was NOT torn down (hammering
    # an overloaded broker with re-dials would worsen the overload)
    assert not ei.value.is_reconnectable
    assert client._connection is not None


async def test_client_receive_messages_never_loses_deliveries():
    notice = serialize(AuthenticateResponse(permit=0, context="shed: x"))
    payload = serialize(Broadcast([0], b"real"))
    client = _client()
    client._connection = _StubConn(
        items=[_StubItem(payload), _StubItem(notice)])
    out = await client.receive_messages()
    # the real delivery is returned first...
    assert len(out) == 1 and isinstance(out[0], Broadcast)
    # ...and the shed surfaces as the typed Error on the NEXT call
    with pytest.raises(Error) as ei:
        await client.receive_messages()
    assert ei.value.kind == ErrorKind.SHED


async def test_client_receive_messages_only_notices_raises_immediately():
    notice = serialize(AuthenticateResponse(permit=0, context="shed: y"))
    client = _client()
    client._connection = _StubConn(items=[_StubItem(notice)])
    with pytest.raises(Error) as ei:
        await client.receive_messages()
    assert ei.value.kind == ErrorKind.SHED


async def test_client_resends_verbatim_after_shed():
    """Review fix: a shed may have dropped any recent mutation, so the
    optimistic local topic mirror is untrustworthy afterwards — the
    delta filter must be suspended (requested topics sent verbatim)
    until a reconnect replays the full set, or a retried subscribe
    becomes a permanent silent no-op."""
    client = _client()
    stub = _StubConn(messages=[
        AuthenticateResponse(permit=0, context="shed: subscribe rate")])
    sent = []

    async def send_message(msg, flush=False):
        sent.append(msg)

    stub.send_message = send_message
    client._connection = stub
    # optimistic mirror says topic 5 is subscribed (the broker shed it)
    client._topics.add(5)
    await client.subscribe([5])
    assert sent == []  # pre-shed: the delta filter suppresses the resend
    with pytest.raises(Error) as ei:
        await client.receive_message()
    assert ei.value.kind == ErrorKind.SHED
    # post-shed: the retry goes out verbatim despite the stale mirror
    await client.subscribe([5])
    assert len(sent) == 1 and tuple(sent[0].topics) == (5,), sent
    await client.unsubscribe([7])  # not in the mirror either: still sent
    assert len(sent) == 2 and tuple(sent[1].topics) == (7,), sent
