"""QUIC endpoint plumbing regressions (ISSUE 3 satellites):

- ``connect``/``bind`` iterate ALL ``getaddrinfo`` results instead of
  only the first — the dual-stack-hostname / v6-less-host behavior the
  old ``create_datagram_endpoint`` path had (round-6 review finding).
  Exercised with a mixed-family resolver stub whose FIRST record always
  fails (bogus family / unroutable bind address).
- Event loops without ``add_reader`` (Windows ``ProactorEventLoop``) fall
  back to the datagram-endpoint path with a one-line warning instead of
  crashing the manual non-blocking-socket endpoint. Exercised by faking a
  loop whose public ``add_reader`` raises NotImplementedError (asyncio's
  own selector datagram transport uses the private ``_add_reader``, so
  the fallback still functions under the fake).
"""

import asyncio
import logging
import socket as _socket

import pytest

from pushcdn_tpu.proto.error import Error
from pushcdn_tpu.proto.message import Direct
from pushcdn_tpu.proto.transport import Quic


async def _echo_once(listener, endpoint):
    """connect → accept → one round trip → close. Returns nothing; raises
    on any failure."""
    connect_task = asyncio.create_task(Quic.connect(endpoint))
    unfinalized = await asyncio.wait_for(listener.accept(), 10)
    server_conn = await unfinalized.finalize()
    client_conn = await asyncio.wait_for(connect_task, 10)
    try:
        await client_conn.send_message(Direct(b"srv", b"ping"))
        got = await asyncio.wait_for(server_conn.recv_message(), 10)
        assert isinstance(got, Direct) and bytes(got.message) == b"ping"
        await server_conn.send_message(Direct(b"cli", b"pong"))
        got2 = await asyncio.wait_for(client_conn.recv_message(), 10)
        assert bytes(got2.message) == b"pong"
    finally:
        client_conn.close()
        server_conn.close()


async def test_connect_iterates_mixed_family_resolver():
    """First resolver record is a dead family; connect must fall through
    to the second instead of failing outright."""
    listener = await Quic.bind("127.0.0.1:0")
    try:
        port = listener.bound_port
        loop = asyncio.get_running_loop()
        real_getaddrinfo = loop.getaddrinfo
        calls = []

        async def stub(host, p, **kw):
            infos = await real_getaddrinfo(host, p, **kw)
            calls.append((host, p))
            # a "v6" record on a v6-less host: AF_INET6-shaped row whose
            # socket/connect cannot complete here (family 9999 does not
            # exist, so socket() raises like a kernel without v6 support)
            dead = (9999, _socket.SOCK_DGRAM, 0, "", ("::1", p, 0, 0))
            return [dead] + list(infos)

        loop.getaddrinfo = stub
        try:
            await _echo_once(listener, f"127.0.0.1:{port}")
        finally:
            loop.getaddrinfo = real_getaddrinfo
        assert calls, "resolver stub was never consulted"
    finally:
        await listener.close()


async def test_connect_all_families_dead_raises_typed_error():
    loop = asyncio.get_running_loop()
    real_getaddrinfo = loop.getaddrinfo

    async def stub(host, p, **kw):
        # dead family (socket() raises OSError), then a family/address
        # shape mismatch (connect raises TypeError): BOTH must surface as
        # the typed Error(CONNECTION), never a raw TypeError
        return [(9999, _socket.SOCK_DGRAM, 0, "", ("::1", p, 0, 0)),
                (_socket.AF_INET, _socket.SOCK_DGRAM, 0, "",
                 ("::1", p, 0, 0))]

    loop.getaddrinfo = stub
    try:
        with pytest.raises(Error):
            await Quic.connect("127.0.0.1:1")
    finally:
        loop.getaddrinfo = real_getaddrinfo


async def test_bind_iterates_mixed_family_resolver():
    """First resolver record binds to an address this host doesn't own
    (the v6-record-on-v6-less-host shape); bind must fall through."""
    loop = asyncio.get_running_loop()
    real_getaddrinfo = loop.getaddrinfo

    async def stub(host, p, **kw):
        infos = await real_getaddrinfo(host, p, **kw)
        # TEST-NET-3 address: EADDRNOTAVAIL on any sane host
        dead = (_socket.AF_INET, _socket.SOCK_DGRAM, 0, "",
                ("203.0.113.7", p))
        return [dead] + list(infos)

    loop.getaddrinfo = stub
    try:
        listener = await Quic.bind("127.0.0.1:0")
    finally:
        loop.getaddrinfo = real_getaddrinfo
    try:
        assert listener.bound_port
        await _echo_once(listener, f"127.0.0.1:{listener.bound_port}")
    finally:
        await listener.close()


async def test_proactor_style_loop_falls_back_to_datagram_endpoint(caplog):
    """A loop whose add_reader raises NotImplementedError (the Windows
    ProactorEventLoop behavior) must still carry QUIC traffic via the
    datagram-endpoint fallback, with a one-line warning."""
    loop = asyncio.get_running_loop()

    def no_add_reader(*_a, **_kw):
        raise NotImplementedError("proactor-style loop")

    loop.add_reader = no_add_reader  # instance attr shadows the method
    try:
        with caplog.at_level(logging.WARNING, logger="pushcdn.transport"):
            listener = await Quic.bind("127.0.0.1:0")
            try:
                assert listener._endpoint._transport is not None
                await _echo_once(listener, f"127.0.0.1:{listener.bound_port}")
            finally:
                await listener.close()
        assert any("falling back to the datagram-endpoint" in r.message
                   for r in caplog.records)
    finally:
        del loop.add_reader
