"""MeshDiscovery: membership from mesh topology, durable state delegated
(SURVEY.md §2e: device-mesh topology replaces the broker registry)."""

import os
import tempfile

from pushcdn_tpu.parallel.mesh import (
    MeshDiscovery,
    broker_identifier_for_device,
    make_broker_mesh,
)


def _db():
    return os.path.join(tempfile.mkdtemp(prefix="pushcdn-mesh-"), "d.sqlite")


async def test_membership_from_topology():
    mesh = make_broker_mesh()
    me = broker_identifier_for_device(mesh, 0)
    disc = await MeshDiscovery.new(_db(), identity=me, mesh=mesh)
    others = await disc.get_other_brokers()
    assert len(others) == mesh.devices.size - 1
    assert me not in others
    await disc.close()


async def test_least_connections_uses_host_load_and_liveness():
    mesh = make_broker_mesh()
    disc = await MeshDiscovery.new(
        _db(), identity=broker_identifier_for_device(mesh, 0), mesh=mesh)
    # shard 0 reports load 5; everyone else 0 -> pick shard 1 (lowest index
    # among zero-load shards)
    await disc.perform_heartbeat(5, 60.0)
    pick = await disc.get_with_least_connections()
    assert pick == broker_identifier_for_device(mesh, 1)
    # mark shards dead: they leave membership and placement
    for i in range(1, mesh.devices.size):
        disc.mark_dead(i)
    pick = await disc.get_with_least_connections()
    assert pick == broker_identifier_for_device(mesh, 0)
    assert await disc.get_other_brokers() == []
    await disc.close()


async def test_permits_and_whitelist_delegate():
    mesh = make_broker_mesh()
    b0 = broker_identifier_for_device(mesh, 0)
    disc = await MeshDiscovery.new(_db(), identity=b0, mesh=mesh)
    permit = await disc.issue_permit(b0, 30.0, b"user-key")
    assert permit > 1
    assert await disc.validate_permit(b0, permit) == b"user-key"
    assert await disc.validate_permit(b0, permit) is None  # single-use
    await disc.set_whitelist([b"a"])
    assert await disc.check_whitelist(b"a")
    assert not await disc.check_whitelist(b"b")
    await disc.close()


def test_identifier_order_matches_mesh_order():
    """CRDT tie-breaks must agree between host (string order) and device
    (index order)."""
    mesh = make_broker_mesh()
    idents = [str(broker_identifier_for_device(mesh, i))
              for i in range(mesh.devices.size)]
    assert idents == sorted(idents)
