"""Adversarial fuzzing of the HANDSHAKE STATE MACHINES against a live
marshal + broker (VERDICT r4 #8) — the tier past the codec fuzzers in
``test_fuzz_parsers.py``.

The contract under attack traffic: the servers reject, disconnect, or
time out per the documented auth flow — no unhandled task exceptions, no
leaked tasks, and the cluster KEEPS SERVING legitimate clients after
every barrage. Parity: the reference's handshake validations at
cdn-proto/src/connection/auth/broker.rs:77-151 and marshal.rs:76-141
(Rust's ?-bail chain is the analog of our Error-only guarantee).

Deterministic seeds: failures reproduce.
"""

import asyncio
import gc
import random
import struct

import pytest

from pushcdn_tpu.proto.crypto.signature import DEFAULT_SCHEME, Namespace
from pushcdn_tpu.proto.error import Error
from pushcdn_tpu.proto.message import (
    AuthenticateResponse,
    AuthenticateWithKey,
    AuthenticateWithPermit,
    Broadcast,
    Subscribe,
    serialize,
)
from pushcdn_tpu.proto.transport.memory import Memory
from pushcdn_tpu.testing import Cluster

class _LoopErrors:
    """Collects unhandled task/loop exceptions during a fuzz barrage."""

    def __init__(self):
        self.errors = []
        self._prev = None

    def __enter__(self):
        loop = asyncio.get_running_loop()
        self._prev = loop.get_exception_handler()
        loop.set_exception_handler(
            lambda lo, ctx: self.errors.append(ctx))
        return self

    def __exit__(self, *exc):
        asyncio.get_running_loop().set_exception_handler(self._prev)


async def _settle(baseline_tasks, timeout_s: float = 8.0):
    """Wait until the running task set returns to (a subset of) the
    baseline — fuzz connections must not leak server tasks. The marshal's
    5 s auth timeout is the slowest legitimate cleanup."""
    deadline = asyncio.get_running_loop().time() + timeout_s
    while asyncio.get_running_loop().time() < deadline:
        gc.collect()
        extra = {t for t in asyncio.all_tasks()
                 if not t.done() and t not in baseline_tasks
                 and t is not asyncio.current_task()}
        if not extra:
            return
        await asyncio.sleep(0.2)
    names = sorted(t.get_name() for t in extra)
    raise AssertionError(f"leaked tasks after fuzz barrage: {names}")


async def _expect_reject_or_drop(conn):
    """The server either answers permit=0 or just drops us — both are
    within the documented handshake contract."""
    try:
        got = await asyncio.wait_for(conn.recv_message(), 8)
        assert isinstance(got, AuthenticateResponse)
        assert got.permit == 0
    except (Error, asyncio.TimeoutError):
        pass
    finally:
        conn.close()


async def _assert_still_serving(cluster, seed: int):
    """The real invariant: a legitimate client authenticates and gets an
    echo after the barrage."""
    c = cluster.client(seed=seed, topics=[0])
    await asyncio.wait_for(c.ensure_initialized(), 10)
    await c.send_direct_message(c.public_key, b"alive?")
    got = await asyncio.wait_for(c.receive_message(), 5)
    assert bytes(got.message) == b"alive?"
    c.close()


def _signed_awk(keypair, namespace=Namespace.USER_MARSHAL_AUTH,
                timestamp=None):
    import time as _time
    ts = int(_time.time()) if timestamp is None else timestamp
    sig = DEFAULT_SCHEME.sign(keypair.private_key, namespace,
                              struct.pack("<Q", ts))
    return AuthenticateWithKey(public_key=keypair.public_key,
                               timestamp=ts, signature=sig)


async def test_marshal_handshake_fuzz():
    """Garbage, wrong kinds, wrong namespaces, stale timestamps,
    truncated wire frames, and mid-handshake disconnects against a live
    marshal: every case ends in a reject or clean drop."""
    cluster = await Cluster(num_brokers=1).start()
    try:
        baseline = set(asyncio.all_tasks())
        kp = DEFAULT_SCHEME.generate_keypair(seed=9001)
        rng = random.Random(4242)

        with _LoopErrors() as errs:
            # 1. random byte frames
            for i in range(10):
                conn = await Memory.connect(cluster.marshal_endpoint)
                blob = bytes(rng.getrandbits(8)
                             for _ in range(rng.randrange(1, 200)))
                try:
                    await conn.send_raw(blob, flush=True)
                except Error:
                    pass
                await _expect_reject_or_drop(conn)

            # 2. wrong first message kinds
            for msg in (Subscribe([0]), Broadcast(topics=[0], message=b"x"),
                        AuthenticateWithPermit(permit=7)):
                conn = await Memory.connect(cluster.marshal_endpoint)
                await conn.send_message(msg, flush=True)
                await _expect_reject_or_drop(conn)

            # 3. wrong-namespace signature (signed for broker-broker auth)
            conn = await Memory.connect(cluster.marshal_endpoint)
            await conn.send_message(
                _signed_awk(kp, namespace=Namespace.BROKER_BROKER_AUTH),
                flush=True)
            await _expect_reject_or_drop(conn)

            # 4. stale timestamp (outside the ±5 s window)
            conn = await Memory.connect(cluster.marshal_endpoint)
            await conn.send_message(_signed_awk(kp, timestamp=1000),
                                    flush=True)
            await _expect_reject_or_drop(conn)

            # 5. truncated AWK halves on the wire (mid-frame EOF)
            valid = serialize(_signed_awk(kp))
            for cut in (1, len(valid) // 2, len(valid) - 1):
                conn = await Memory.connect(cluster.marshal_endpoint)
                frame = struct.pack(">I", len(valid)) + valid[:cut]
                await conn._stream.write(frame)  # bypass framing on purpose
                conn.close()  # EOF mid-frame

            # 6. connect-and-vanish (no bytes at all)
            for _ in range(5):
                conn = await Memory.connect(cluster.marshal_endpoint)
                conn.close()

        assert not errs.errors, errs.errors
        await _settle(baseline)
        await _assert_still_serving(cluster, seed=9100)
    finally:
        await cluster.stop()


async def test_broker_permit_fuzz():
    """Permit forgery, truncation, reuse, and mid-handshake disconnects
    against a live broker's user listener."""
    cluster = await Cluster(num_brokers=1).start()
    try:
        baseline = set(asyncio.all_tasks())
        rng = random.Random(2424)
        broker_ep = cluster.brokers[0].config.public_advertise_endpoint

        with _LoopErrors() as errs:
            # 1. permits the marshal never issued (incl. boundary values)
            for permit in (0, 1, 2, 2**31 - 1, 2**63, rng.getrandbits(64)):
                conn = await Memory.connect(broker_ep)
                try:
                    await conn.send_message(
                        AuthenticateWithPermit(permit=permit), flush=True)
                except (Error, struct.error, OverflowError):
                    conn.close()  # unencodable permit: client-side error
                    continue
                await _expect_reject_or_drop(conn)

            # 2. garbage instead of the permit message
            for _ in range(5):
                conn = await Memory.connect(broker_ep)
                blob = bytes(rng.getrandbits(8)
                             for _ in range(rng.randrange(1, 100)))
                try:
                    await conn.send_raw(blob, flush=True)
                except Error:
                    pass
                await _expect_reject_or_drop(conn)

            # 3. a REAL permit redeemed, then garbage instead of the
            # Subscribe that must follow
            from pushcdn_tpu.proto.auth import user as user_auth
            mconn = await Memory.connect(cluster.marshal_endpoint)
            kp = DEFAULT_SCHEME.generate_keypair(seed=9200)
            permit, ep = await user_auth.authenticate_with_marshal(
                mconn, DEFAULT_SCHEME, kp)
            mconn.close()
            conn = await Memory.connect(ep)
            await conn.send_message(AuthenticateWithPermit(permit=permit),
                                    flush=True)
            got = await asyncio.wait_for(conn.recv_message(), 8)
            assert isinstance(got, AuthenticateResponse) and got.permit == 1
            await conn.send_message(Broadcast(topics=[0], message=b"not-sub"),
                                    flush=True)
            # broker must drop us (auth flow violated), not crash
            with pytest.raises((Error, asyncio.TimeoutError)):
                await asyncio.wait_for(conn.recv_message(), 3)
            conn.close()

            # 4. permit single-use: redeeming the same permit again fails
            conn = await Memory.connect(ep)
            await conn.send_message(AuthenticateWithPermit(permit=permit),
                                    flush=True)
            await _expect_reject_or_drop(conn)

            # 5. real permit, disconnect before Subscribe
            mconn = await Memory.connect(cluster.marshal_endpoint)
            kp2 = DEFAULT_SCHEME.generate_keypair(seed=9201)
            permit2, ep2 = await user_auth.authenticate_with_marshal(
                mconn, DEFAULT_SCHEME, kp2)
            mconn.close()
            conn = await Memory.connect(ep2)
            await conn.send_message(AuthenticateWithPermit(permit=permit2),
                                    flush=True)
            conn.close()

        assert not errs.errors, errs.errors
        await _settle(baseline)
        await _assert_still_serving(cluster, seed=9300)
        # no fuzz connection ever became a registered user
        assert cluster.brokers[0].connections.num_users <= 1
    finally:
        await cluster.stop()
