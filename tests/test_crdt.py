"""VersionedMap CRDT semantics tests.

Ports the exact scenarios of the reference's unit tests
(cdn-broker/src/connections/versioned_map.rs:272-377): insert/remove,
conflict resolution by ordered identity, partial diffs, purge; plus codec
round-trips and out-of-order merge convergence.
"""

from pushcdn_tpu.broker.versioned_map import VersionedMap, VersionedValue


def test_insert_get_remove():
    m = VersionedMap(local_identity="b1/priv1")
    m.insert(b"alice", "b1/priv1")
    assert m.get(b"alice") == "b1/priv1"
    assert b"alice" in m
    assert len(m) == 1
    removed = m.remove(b"alice")
    assert removed == "b1/priv1"
    assert m.get(b"alice") is None
    assert len(m) == 0
    # tombstone still present internally for propagation
    assert b"alice" in m.full()


def test_version_bumps_on_reinsert():
    m = VersionedMap(local_identity="a")
    m.insert("k", 1)
    m.insert("k", 2)
    m.remove("k")
    assert m.full()["k"].version == 3


def test_merge_last_writer_wins_by_version():
    a = VersionedMap(local_identity="brokerA")
    b = VersionedMap(local_identity="brokerB")
    a.insert(b"user", "brokerA")
    b.merge(a.diff())
    assert b.get(b"user") == "brokerA"
    # b takes over the user: higher version wins everywhere
    b.insert(b"user", "brokerB")
    changed = a.merge(b.diff())
    assert a.get(b"user") == "brokerB"
    assert [(k, new) for k, _old, new in changed] == [(b"user", "brokerB")]


def test_merge_tie_broken_by_identity():
    """Equal versions: the ordered conflict identity decides, identically on
    both replicas (versioned_map.rs conflict-resolution test)."""
    a = VersionedMap(local_identity="brokerA")
    b = VersionedMap(local_identity="brokerZ")
    a.insert(b"user", "brokerA")   # version 1, identity brokerA
    b.insert(b"user", "brokerZ")   # version 1, identity brokerZ
    delta_a, delta_b = a.diff(), b.diff()
    a.merge(delta_b)
    b.merge(delta_a)
    assert a.get(b"user") == b.get(b"user") == "brokerZ"


def test_merge_idempotent_and_stale_ignored():
    a = VersionedMap(local_identity="A")
    a.insert("k", "v1")
    snapshot = dict(a.full())
    a.insert("k", "v2")
    changed = a.merge(snapshot)  # stale: version 1 < 2
    assert changed == []
    assert a.get("k") == "v2"
    assert a.merge(a.full()) == []  # self-merge is a no-op


def test_partial_diff_only_contains_modifications():
    m = VersionedMap(local_identity="A")
    m.insert("k1", 1)
    m.insert("k2", 2)
    assert set(m.diff().keys()) == {"k1", "k2"}
    assert m.diff() == {}  # cleared
    m.insert("k1", 10)
    m.remove("k2")
    d = m.diff()
    assert set(d.keys()) == {"k1", "k2"}
    assert d["k1"].value == 10
    assert d["k2"].value is None  # tombstone travels in the diff


def test_remove_if_equals():
    m = VersionedMap(local_identity="A")
    m.insert(b"u", "A")
    assert not m.remove_if_equals(b"u", "B")
    assert m.get(b"u") == "A"
    assert m.remove_if_equals(b"u", "A")
    assert m.get(b"u") is None


def test_remove_by_value_no_modify():
    m = VersionedMap(local_identity="A")
    m.insert(b"u1", "B")
    m.insert(b"u2", "B")
    m.insert(b"u3", "C")
    m.diff()  # clear modification tracking
    dropped = m.remove_by_value_no_modify("B")
    assert sorted(dropped) == [b"u1", b"u2"]
    assert m.get(b"u1") is None and b"u1" not in m.full()  # no tombstone
    assert m.diff() == {}  # not marked modified
    assert m.get(b"u3") == "C"


def test_purge_tombstones():
    m = VersionedMap(local_identity="A")
    m.insert("k1", 1)
    m.insert("k2", 2)
    m.remove("k1")
    assert len(m.full()) == 2
    assert m.purge_tombstones() == 1
    assert len(m.full()) == 1
    assert m.get("k2") == 2


def test_out_of_order_delivery_converges():
    """Deltas applied in any order converge (parity: the out-of-order
    topic-sync test, connections/mod.rs:473-526)."""
    src = VersionedMap(local_identity="S")
    deltas = []
    for i in range(5):
        src.insert(b"user", f"owner-{i}")
        deltas.append(src.diff())
    import itertools
    for perm in itertools.permutations(range(5)):
        dst = VersionedMap(local_identity="D")
        for i in perm:
            dst.merge(deltas[i])
        assert dst.get(b"user") == "owner-4"


def test_codec_round_trip():
    m = VersionedMap(local_identity="b1/p1")
    m.insert(b"\x00\xffuser", "b2/p2")
    m.insert(b"other", "b1/p1")
    m.remove(b"other")
    payload = VersionedMap.serialize_entries(m.full())
    out = VersionedMap.deserialize_entries(payload)
    assert out.keys() == m.full().keys()
    for k, vv in m.full().items():
        assert out[k].value == vv.value
        assert out[k].version == vv.version
        assert out[k].identity == vv.identity


def test_codec_int_keys_topic_sync_shape():
    m = VersionedMap(local_identity="b1/p1")
    m.insert(3, 1)   # topic 3 SUBSCRIBED
    m.insert(7, 0)   # topic 7 UNSUBSCRIBED
    out = VersionedMap.deserialize_entries(VersionedMap.serialize_entries(m.full()))
    assert out[3].value == 1 and out[7].value == 0
