"""DevicePlane consistency tests: slot quarantine, snapshot semantics,
churn under traffic, slot-table exhaustion fallback."""

import asyncio

from pushcdn_tpu.parallel.frames import UserSlots
from tests.test_integration import Cluster, wait_until


def test_user_slots_quarantine():
    """unmap() keeps the slot index out of circulation until free_slot()."""
    s = UserSlots(2)
    a = s.assign(b"alice")
    slot = s.unmap(b"alice")
    assert slot == a
    assert s.slot_of(b"alice") is None
    b = s.assign(b"bob")
    assert b != a  # quarantined slot NOT reused
    s.free_slot(a)
    c = s.assign(b"carol")
    assert c == a  # recycled only after explicit free


async def test_churn_during_device_traffic():
    """Users joining/leaving while steps are in flight never lose messages
    for connected users (the snapshot-per-step design)."""
    from pushcdn_tpu.broker.device_plane import DevicePlaneConfig

    cluster = await Cluster(num_brokers=1, device_plane=DevicePlaneConfig(
        num_user_slots=32, ring_slots=64, frame_bytes=1024,
        batch_window_s=0.002, bypass_max_items=0)).start()
    try:
        stable = cluster.client(seed=500, topics=[0])
        await stable.ensure_initialized()
        received = []

        async def drain():
            while True:
                got = await stable.receive_message()
                received.append(bytes(got.message))

        drain_task = asyncio.create_task(drain())
        # churn 5 short-lived clients while the stable one receives
        for i in range(5):
            churner = cluster.client(seed=600 + i, topics=[0])
            await churner.ensure_initialized()
            await churner.send_broadcast_message([0], f"round-{i}".encode())
            await asyncio.sleep(0.02)
            churner.close()
        await wait_until(
            lambda: len([r for r in received if r.startswith(b"round-")]) == 5,
            timeout=10)
        drain_task.cancel()
        device = cluster.brokers[0].device_plane
        assert device.steps >= 1
        assert not device.disabled
        stable.close()
    finally:
        await cluster.stop()


async def test_slot_table_exhaustion_falls_back_to_host():
    """More users than device slots: registration still succeeds and
    broadcasts take the host path (no silent misses)."""
    from pushcdn_tpu.broker.device_plane import DevicePlaneConfig

    cluster = await Cluster(num_brokers=1, device_plane=DevicePlaneConfig(
        num_user_slots=2, ring_slots=16, frame_bytes=1024,
        batch_window_s=0.002)).start()
    try:
        clients = []
        for i in range(4):  # 4 users, 2 slots
            c = cluster.client(seed=700 + i, topics=[0])
            await c.ensure_initialized()
            clients.append(c)
        await wait_until(
            lambda: cluster.brokers[0].connections.num_users == 4)
        device = cluster.brokers[0].device_plane
        assert len(device._unmirrored) == 2

        # a broadcast must reach ALL FOUR users (host path because of the
        # unmirrored users)
        await clients[0].send_broadcast_message([0], b"everyone")
        for c in clients:
            got = await asyncio.wait_for(c.receive_message(), 5)
            assert bytes(got.message) == b"everyone"
        for c in clients:
            c.close()
    finally:
        await cluster.stop()


async def test_idle_bypass_routes_on_host_path():
    """Depth-1 bypass: a lone message hitting a COMPLETELY idle plane is
    host-routed immediately (no step dispatch in the latency path), while
    a burst larger than the bypass budget stages onto the device."""
    from pushcdn_tpu.broker.device_plane import DevicePlaneConfig

    cluster = await Cluster(num_brokers=1, device_plane=DevicePlaneConfig(
        num_user_slots=32, ring_slots=64, frame_bytes=1024,
        batch_window_s=0.002, bypass_max_items=2)).start()
    try:
        c = cluster.client(seed=900, topics=[0])
        await c.ensure_initialized()
        device = cluster.brokers[0].device_plane

        # idle singles: delivered via the host path, zero device steps
        for i in range(3):
            await c.send_direct_message(c.public_key, b"solo %d" % i)
            got = await asyncio.wait_for(c.receive_message(), 10)
            assert bytes(got.message) == b"solo %d" % i
        assert device.steps == 0
        assert device.messages_routed == 0

        # bursts exceed the bypass budget and ride the device; retry a
        # few bursts since the broker's reader may split one across
        # small receive batches that each fit the bypass
        expected = 3
        for _ in range(5):
            await asyncio.gather(*(
                c.send_direct_message(c.public_key, b"burst %d" % i)
                for i in range(16)))
            got = 0
            async with asyncio.timeout(20):
                while got < 16:
                    got += len(await c.receive_messages(16 - got))
            if device.messages_routed > 0:
                break
        assert device.messages_routed > 0
        c.close()
    finally:
        await cluster.stop()


def test_pump_common_helpers():
    """The shared pump machinery (broker/pump_common.py) both planes use."""
    from pushcdn_tpu.broker.pump_common import (
        CoalesceGate, RevCache, effective_users)

    # user-table slice mark: bucket-rounded, clamped, never zero
    assert effective_users(0, 1024) == 64
    assert effective_users(1, 1024) == 64
    assert effective_users(64, 1024) == 64
    assert effective_users(65, 1024) == 128
    assert effective_users(5000, 1024) == 1024
    assert effective_users(10, 32) == 32  # capacity below one bucket

    # coalescing gate: burst-after-idle and saturation step immediately,
    # a recent-step trickle waits one window
    g = CoalesceGate(batch_window_s=0.001, coalesce_min_frames=16)
    assert g.wait_s(1, now=100.0) == 0          # idle: no window
    g.stepped(100.0)
    assert g.wait_s(1, now=100.001) == 0.001    # trickle: coalesce
    assert g.wait_s(16, now=100.001) == 0       # saturated: step now
    assert g.wait_s(0, now=100.001) == 0        # nothing staged
    assert g.wait_s(1, now=100.5) == 0          # idle again

    # revision cache: builds once per revision; None never caches
    cache = RevCache()
    calls = []
    assert cache.get(1, lambda: calls.append(1) or "a") == "a"
    assert cache.get(1, lambda: calls.append(2) or "b") == "a"
    assert cache.get(2, lambda: calls.append(3) or "c") == "c"
    assert calls == [1, 3]
    assert cache.get(None, lambda: calls.append(4) or "w") == "w"
    assert cache.get(2, lambda: calls.append(5) or "x") == "c"


async def test_device_plane_fail_open_to_host_path():
    """A failing device step must not lose acked frames: the staged batch
    re-routes over the host path, the plane disables itself, and the
    broker keeps serving as a plain host broker (fail-open, matching the
    reference's any-core-failure posture)."""
    from pushcdn_tpu.broker.device_plane import DevicePlaneConfig

    cluster = await Cluster(num_brokers=1, device_plane=DevicePlaneConfig(
        num_user_slots=32, ring_slots=64, frame_bytes=1024,
        batch_window_s=0.002, bypass_max_items=0)).start()
    try:
        a = cluster.client(seed=1100, topics=[0])
        b = cluster.client(seed=1101, topics=[0])
        await a.ensure_initialized()
        await b.ensure_initialized()
        device = cluster.brokers[0].device_plane

        # sanity: the plane routes before the failure
        await a.send_broadcast_message([0], b"pre-failure")
        for c in (a, b):
            got = await asyncio.wait_for(c.receive_message(), 10)
            assert bytes(got.message) == b"pre-failure"

        # break the step underneath the pump
        def boom(*args, **kwargs):
            raise RuntimeError("injected device failure")
        device._run_step = boom

        await a.send_broadcast_message([0], b"survives the failure")
        for c in (a, b):
            got = await asyncio.wait_for(c.receive_message(), 10)
            assert bytes(got.message) == b"survives the failure"

        await wait_until(lambda: device.disabled)
        # the broker is now a plain host broker; traffic still flows
        await b.send_direct_message(a.public_key, b"host path onward")
        got = await asyncio.wait_for(a.receive_message(), 10)
        assert bytes(got.message) == b"host path onward"
        a.close()
        b.close()
    finally:
        await cluster.stop()


async def test_ragged_delivery_impl_end_to_end():
    """delivery_impl="ragged": the plane routes through the paged walk
    (compact pairs feed egress directly) and delivers byte-identically —
    broadcasts, a multi-topic union (deduped to one copy), and directs."""
    from pushcdn_tpu.broker.device_plane import DevicePlaneConfig

    cluster = await Cluster(num_brokers=1, device_plane=DevicePlaneConfig(
        num_user_slots=32, ring_slots=64, frame_bytes=1024,
        batch_window_s=0.002, bypass_max_items=0,
        delivery_impl="ragged")).start()
    try:
        device = cluster.brokers[0].device_plane
        assert device.delivery_impl == "ragged"
        stable = cluster.client(seed=520, topics=[0, 1])
        await stable.ensure_initialized()
        received = []

        async def drain():
            while True:
                got = await stable.receive_message()
                received.append(bytes(got.message))

        drain_task = asyncio.create_task(drain())
        sender = cluster.client(seed=521, topics=[])
        await sender.ensure_initialized()
        for i in range(4):
            await sender.send_broadcast_message([0], b"m%d" % i)
        await sender.send_broadcast_message([1], b"t1")
        await sender.send_broadcast_message([0, 1], b"union")  # dedup
        await sender.send_direct_message(stable.public_key, b"direct")
        await wait_until(lambda: len(received) >= 7, timeout=10)
        await asyncio.sleep(0.05)  # a dup would land right behind
        drain_task.cancel()
        assert sorted(received) == sorted(
            [b"m0", b"m1", b"m2", b"m3", b"t1", b"union", b"direct"])
        assert device.ragged_steps >= 1
        assert not device.disabled
        stable.close()
        sender.close()
    finally:
        await cluster.stop()


async def test_ragged_page_pool_exhaustion_falls_back_then_recovers():
    """A too-small page pool: the plane flips to the dense step (never a
    dropped delivery), keeps serving, and once membership shrinks the
    rebuild-retry path restores the paged walk."""
    from pushcdn_tpu.broker.device_plane import DevicePlaneConfig

    cluster = await Cluster(num_brokers=1, device_plane=DevicePlaneConfig(
        num_user_slots=32, ring_slots=64, frame_bytes=1024,
        batch_window_s=0.002, bypass_max_items=0,
        delivery_impl="ragged", ragged_max_pages=2)).start()
    try:
        device = cluster.brokers[0].device_plane
        # two subscribers on different topics exhaust the 1-usable-page
        # pool (page 0 reserved): the second add overflows
        a = cluster.client(seed=530, topics=[0])
        await a.ensure_initialized()
        b = cluster.client(seed=531, topics=[1])
        await b.ensure_initialized()
        await wait_until(lambda: device.delivery_impl == "dense",
                         timeout=5)
        received = []

        async def drain():
            while True:
                got = await a.receive_message()
                received.append(bytes(got.message))

        drain_task = asyncio.create_task(drain())
        sender = cluster.client(seed=532, topics=[])
        await sender.ensure_initialized()
        await sender.send_broadcast_message([0], b"after-fallback")
        await wait_until(lambda: received == [b"after-fallback"],
                         timeout=10)
        assert not device.disabled
        # membership shrinks below the retry mark: the removal's own
        # observer call rebuilds the index and resumes the paged walk
        b.close()
        await wait_until(lambda: device.delivery_impl == "ragged",
                         timeout=10)
        await sender.send_broadcast_message([0], b"after-recovery")
        await wait_until(
            lambda: received == [b"after-fallback", b"after-recovery"],
            timeout=10)
        drain_task.cancel()
        assert not device.disabled
        for c in (a, sender):
            c.close()
    finally:
        await cluster.stop()
