"""Worker for the two-process multi-host PERMANENT-STALL test (run via
subprocess). The kill test (``_multihost_kill_worker.py``) covers a peer
that DIES; this covers the nastier failure VERDICT r5 #6 asked for — a
peer that is alive but never progresses (wedged runtime, livelocked step
thread, GC death spiral): the OS gives no connection-reset signal, so
only the survivor's own collective watchdog can bound detection.

- both ranks prove the device plane end to end (cross-host broadcast),
  then touch a ``ready-<rank>`` sentinel file;
- rank 1 then injects a PERMANENT block into its collective tick (the
  straggler bench's delay injection with an unbounded delay) and sits
  there — the process stays alive, sockets open, heartbeats flowing;
- rank 0 must observe its collective watchdog (``collective_timeout_s``)
  fire, see the group fail CLOSED (disabled, pump task returned —
  no hung collective), fail-fast staging, keep serving its local client
  over the host path, then print ``STALL OK`` and exit 0;
- the parent test kills the stalled rank afterwards and redeploys a
  FRESH two-process group (phase 2) — recovery is redeployment without
  the stalled host, same posture as the kill test.

Usage: _multihost_stall_worker.py <rank> <base_port> <db_path> <tmp_dir>
"""

import asyncio
import os
import sys
import threading
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")  # sitecustomize may override env

rank = int(sys.argv[1])
base = int(sys.argv[2])
db = sys.argv[3]
tmp = sys.argv[4]

# generous heartbeat window, same reasoning as the kill worker: the
# survivor must outlive the collective failure long enough to assert its
# guarantees before the coordination service's posture can matter
jax.distributed.initialize(coordinator_address=f"127.0.0.1:{base}",
                           num_processes=2, process_id=rank,
                           heartbeat_timeout_seconds=600)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pushcdn_tpu.broker.mesh_group import MeshGroupConfig  # noqa: E402
from pushcdn_tpu.proto.crypto.signature import DEFAULT_SCHEME  # noqa: E402
from pushcdn_tpu.proto.message import Broadcast, Direct  # noqa: E402
from pushcdn_tpu.testing.two_host import make_two_host_node  # noqa: E402

CLIENT_SEED = [73_000, 74_000]
WATCHDOG_S = 20.0


async def main() -> None:
    try:
        await _main()
    except BaseException:
        # fail INSIDE the coroutine (see the kill worker): asyncio.run's
        # finally would join the executor and a collective thread stuck in
        # gloo turns an assert failure into a silent hang
        import traceback
        traceback.print_exc()
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(1)


async def _main() -> None:
    node = await make_two_host_node(
        rank, base, db, client_seeds=CLIENT_SEED, broker_seed_base=85,
        mesh_config=MeshGroupConfig(
            num_user_slots=64, ring_slots=64, frame_bytes=2048,
            extra_lanes=(), direct_bucket_slots=4,
            batch_window_s=0.02),
        collective_timeout_s=WATCHDOG_S)
    group, broker, client = node.group, node.broker, node.client
    my_shard = node.my_shard

    await node.directory_rendezvous()

    # prove the device plane is live end to end before the stall
    if rank == 0:
        await client.send_broadcast_message([0], b"pre-stall hello")
    got = await asyncio.wait_for(client.receive_message(), 60)
    assert isinstance(got, Broadcast) and \
        bytes(got.message) == b"pre-stall hello"
    assert broker.connections.num_brokers == 0

    with open(os.path.join(tmp, f"ready-{rank}"), "w") as f:
        f.write("ready")

    if rank == 1:
        # the PERMANENT stall: every collective tick blocks forever from
        # here on. The process stays alive (this is the difference from
        # SIGKILL — no FIN, no connection reset, heartbeat threads keep
        # running); only the survivor's watchdog can detect it.
        stalled = threading.Event()

        def stall_forever(_want_stop):
            stalled.set()
            while True:  # never returns, never raises
                time.sleep(3600)

        group._collective_stop = stall_forever
        # wait out the parent's kill; prove we were genuinely reached
        while not stalled.is_set():
            await asyncio.sleep(0.1)
        print("rank 1: STALLED (alive, wedged in collective)", flush=True)
        await asyncio.sleep(3600)
        return

    # ---- rank 0: survive the peer's livelock -----------------------------
    # the watchdog must fail the group CLOSED within ~collective_timeout_s
    # (plus one tick); poll to 3x the bound before declaring failure
    t0 = time.monotonic()
    while time.monotonic() - t0 < 3 * WATCHDOG_S + 30:
        if group.disabled:
            break
        await asyncio.sleep(0.1)
    assert group.disabled, \
        f"stalled peer never tripped the watchdog within {3 * WATCHDOG_S + 30}s"
    detect_s = time.monotonic() - t0
    print(f"MARK: disabled after {detect_s:.1f}s (watchdog {WATCHDOG_S}s)",
          flush=True)
    # clean halt: the pump task RETURNED (its own last-barrier is bounded
    # by the same watchdog) — no hung collective
    for _ in range(int((WATCHDOG_S + 25) * 10)):
        if group._task is None or group._task.done():
            break
        await asyncio.sleep(0.1)
    assert group._task is None or group._task.done(), \
        "pump still running after disable (hung collective?)"
    print("MARK: pump done", flush=True)

    # staging fail-fasts instead of blackholing
    from pushcdn_tpu.broker.staging import StageResult
    from pushcdn_tpu.proto.limiter import Bytes as _Bytes
    from pushcdn_tpu.proto.message import serialize
    late = Broadcast(topics=[0], message=b"late")
    assert group.try_stage(my_shard, late, _Bytes(serialize(late))) == \
        StageResult.INELIGIBLE
    print("MARK: stage fail-fast", flush=True)

    # host-path service continues for local clients
    own_pk = DEFAULT_SCHEME.generate_keypair(seed=CLIENT_SEED[0]).public_key
    await client.send_direct_message(own_pk, b"still served")
    got = await asyncio.wait_for(client.receive_message(), 30)
    assert isinstance(got, Direct) and bytes(got.message) == b"still served"
    await client.send_broadcast_message([0], b"local fanout works")
    got = await asyncio.wait_for(client.receive_message(), 30)
    assert isinstance(got, Broadcast) and \
        bytes(got.message) == b"local fanout works"
    assert broker.connections.num_users == 1

    client.close()
    await node.marshal.stop()
    await broker.stop()
    print(f"rank {rank}: STALL OK (detected in {detect_s:.1f}s, "
          f"steps={group.steps}, disabled clean)", flush=True)
    # skip jax.distributed.shutdown(): its barrier would gate on the
    # stalled peer forever — hard-exit instead
    os._exit(0)


asyncio.run(main())
