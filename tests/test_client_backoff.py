"""Reconnect-storm resilience (ISSUE 12): full-jitter exponential
backoff, the typed ``Error(SHED)`` retry-after hint that floors it, and
the SQLite discovery store's bounded locked-write retry."""

import asyncio
import random
import sqlite3

import pytest

from pushcdn_tpu.client import client as client_mod
from pushcdn_tpu.client.client import Client, ClientConfig, backoff_delay
from pushcdn_tpu.proto.auth.user import _bail_rejection
from pushcdn_tpu.proto.crypto.signature import DEFAULT_SCHEME
from pushcdn_tpu.proto.discovery import embedded as emb
from pushcdn_tpu.proto.discovery.base import BrokerIdentifier
from pushcdn_tpu.proto.error import Error, ErrorKind, retry_after_hint
from pushcdn_tpu.proto.transport.memory import Memory
from pushcdn_tpu.testing.cluster import Cluster

# ---------------------------------------------------------------------------
# the backoff policy itself
# ---------------------------------------------------------------------------


def test_backoff_is_full_jitter():
    random.seed(1207)
    base, cap = 0.25, 30.0
    for attempt in range(12):
        ceiling = min(cap, base * (2 ** attempt))
        draws = [backoff_delay(attempt, base_s=base, cap_s=cap)
                 for _ in range(200)]
        assert all(0.0 <= d <= ceiling for d in draws)
        # FULL jitter: the whole [0, ceiling) range is drawn from — a
        # "equal jitter" or fixed-delay regression would never go low
        assert min(draws) < 0.2 * ceiling
        assert max(draws) > 0.8 * ceiling


def test_backoff_caps_growth():
    random.seed(7)
    for attempt in (20, 40, 63):
        assert backoff_delay(attempt, base_s=0.25, cap_s=3.0) <= 3.0


def test_backoff_retry_after_is_a_floor():
    random.seed(3)
    # attempt 0 draws from [0, 0.25); a 5 s server hint must dominate
    for _ in range(50):
        assert backoff_delay(0, retry_after_s=5.0) >= 5.0
    # ...but a hint SMALLER than the draw never truncates the jitter
    random.seed(3)
    draws = [backoff_delay(8, retry_after_s=0.001) for _ in range(50)]
    assert max(draws) > 1.0


# ---------------------------------------------------------------------------
# the typed hint, end to end
# ---------------------------------------------------------------------------


def test_retry_after_hint_parsing():
    assert retry_after_hint("shed: budget reached; retry-after=5") == 5.0
    assert retry_after_hint("shed: x; retry-after=2.75 more") == 2.75
    assert retry_after_hint("shed: no hint here") is None
    assert retry_after_hint("retry-after=abc") is None


def test_shed_error_carries_retry_after():
    e = Error(ErrorKind.SHED, "broker shed the connection: shed: user "
                              "connection budget 1 reached; retry-after=5")
    assert e.retry_after_s == 5.0
    # only SHED is a server pacing signal; other kinds never carry one
    e2 = Error(ErrorKind.AUTHENTICATION, "nope; retry-after=5")
    assert e2.retry_after_s is None


def test_bail_rejection_types_sheds():
    with pytest.raises(Error) as ei:
        _bail_rejection("broker", "shed: user connection budget 1 "
                                  "reached; retry-after=5")
    assert ei.value.kind == ErrorKind.SHED
    assert ei.value.retry_after_s == 5.0
    with pytest.raises(Error) as ei:
        _bail_rejection("marshal", "bad signature")
    assert ei.value.kind == ErrorKind.AUTHENTICATION


async def test_connect_shed_surfaces_typed_retry_after(monkeypatch):
    """A broker over its connection budget refuses at connect time with
    ``Error(SHED)`` carrying the readiness window as the retry hint —
    distinguishable from a real auth failure (which must NOT be paced)."""
    monkeypatch.setenv("PUSHCDN_MAX_CONNS_USER", "1")
    monkeypatch.setenv("PUSHCDN_SHED_READY_S", "3")
    cluster = await Cluster(num_brokers=1).start()
    try:
        first = cluster.client(seed=83_000)
        await asyncio.wait_for(first.ensure_initialized(), 10.0)
        second = cluster.client(seed=83_001)
        with pytest.raises(Error) as ei:
            await asyncio.wait_for(second._connect_once(), 10.0)
        assert ei.value.kind == ErrorKind.SHED
        assert ei.value.retry_after_s == 3.0
        first.close()
        second.close()
    finally:
        await cluster.stop()


async def test_reconnect_loop_uses_backoff(monkeypatch):
    """The reconnect loop feeds (attempt, server hint) into the policy —
    attempts count up, and the loop actually sleeps what it drew."""
    delays = []

    def fake_backoff(attempt, retry_after_s=None, **kw):
        delays.append((attempt, retry_after_s))
        return 0.0
    monkeypatch.setattr(client_mod, "backoff_delay", fake_backoff)
    c = Client(ClientConfig(
        marshal_endpoint="nowhere-no-listener",
        keypair=DEFAULT_SCHEME.generate_keypair(seed=83_002),
        protocol=Memory))
    task = asyncio.ensure_future(c._get_connection())
    while len(delays) < 4:
        await asyncio.sleep(0.01)
    task.cancel()
    with pytest.raises(asyncio.CancelledError):
        await task
    assert [a for a, _ in delays[:4]] == [0, 1, 2, 3]


# ---------------------------------------------------------------------------
# SQLite discovery: bounded retry past a write lock
# ---------------------------------------------------------------------------


def _identity(i=0):
    return BrokerIdentifier(f"lock-pub-{i}", f"lock-priv-{i}")


async def test_embedded_retries_past_held_write_lock(tmp_path, monkeypatch):
    """Another process holding BEGIN IMMEDIATE past busy_timeout makes
    every write raise 'database is locked'; the bounded retry schedule
    must ride it out once the lock releases."""
    monkeypatch.setattr(emb, "BUSY_TIMEOUT_MS", 25)
    monkeypatch.setattr(emb, "LOCKED_RETRY_SCHEDULE", (0.05, 0.1, 0.2))
    db = str(tmp_path / "d.sqlite")
    disc = await emb.Embedded.new(db, identity=_identity())
    locker = sqlite3.connect(db)
    try:
        locker.execute("BEGIN IMMEDIATE")  # hold the write lock

        async def release_soon():
            await asyncio.sleep(0.15)  # past busy_timeout + first retries
            locker.execute("COMMIT")

        releaser = asyncio.ensure_future(release_soon())
        await disc.perform_heartbeat(3, 60.0)  # must NOT raise
        await releaser
        others = await disc.get_other_brokers()
        assert others == []  # our own row landed (we are excluded)
    finally:
        locker.close()
        await disc.close()


async def test_embedded_lock_exhaustion_is_typed(tmp_path, monkeypatch):
    """A lock held past the WHOLE schedule surfaces as the typed
    Error(CONNECTION), never a raw sqlite3.OperationalError."""
    monkeypatch.setattr(emb, "BUSY_TIMEOUT_MS", 10)
    monkeypatch.setattr(emb, "LOCKED_RETRY_SCHEDULE", (0.02, 0.04))
    db = str(tmp_path / "d.sqlite")
    disc = await emb.Embedded.new(db, identity=_identity(1))
    locker = sqlite3.connect(db)
    try:
        locker.execute("BEGIN IMMEDIATE")
        with pytest.raises(Error) as ei:
            await disc.perform_heartbeat(1, 60.0)
        assert ei.value.kind == ErrorKind.CONNECTION
        assert "discovery store busy" in ei.value.message
        locker.execute("ROLLBACK")
    finally:
        locker.close()
        await disc.close()


async def test_deregister_removes_broker_row(tmp_path):
    """Drain step 1: a deregistered broker leaves placement immediately
    and idempotently (every shard worker calls it)."""
    db = str(tmp_path / "d.sqlite")
    a = await emb.Embedded.new(db, identity=_identity(0))
    b = await emb.Embedded.new(db, identity=_identity(1))
    await a.perform_heartbeat(0, 60.0)
    await b.perform_heartbeat(5, 60.0)
    assert await a.get_with_least_connections() == _identity(0)
    await a.deregister()
    await a.deregister()  # idempotent
    assert await b.get_other_brokers() == []
    assert await b.get_with_least_connections() == _identity(1)
    await a.close()
    await b.close()
