"""Whole-system integration tests: real marshal + broker(s) + client(s) in
one process over the Memory transport + a shared Embedded (SQLite)
discovery file.

Parity with the reference's ``tests`` crate (tests/src/tests/mod.rs:62-143
fixture; basic_connect.rs, double_connect.rs, subscribe.rs, whitelist.rs):
the Memory protocol's global listener registry stands in for the network
and the shared SQLite file stands in for KeyDB, so multi-node behavior runs
on a laptop with no cluster (SURVEY.md §4 tier 3).
"""

import asyncio

import pytest

from pushcdn_tpu.client import Client, ClientConfig
from pushcdn_tpu.proto.auth import user as user_auth
from pushcdn_tpu.proto.crypto.signature import DEFAULT_SCHEME
from pushcdn_tpu.proto.discovery.embedded import Embedded
from pushcdn_tpu.proto.error import Error
from pushcdn_tpu.proto.message import Broadcast, Direct, Subscribe
from pushcdn_tpu.proto.transport.memory import Memory
from pushcdn_tpu.testing import Cluster, wait_until


async def test_end_to_end_echo():
    """The minimum end-to-end slice (BASELINE.json configs[0]; parity
    basic_connect.rs:16-56): marshal auth → broker → direct-message echo."""
    cluster = await Cluster(num_brokers=1).start()
    try:
        alice = cluster.client(seed=1, topics=[0])
        await alice.ensure_initialized()
        # direct message to self comes straight back
        await alice.send_direct_message(alice.public_key, b"echo?")
        got = await asyncio.wait_for(alice.receive_message(), 5)
        assert isinstance(got, Direct)
        assert bytes(got.message) == b"echo?"
        alice.close()
    finally:
        await cluster.stop()


async def test_broadcast_between_clients():
    cluster = await Cluster(num_brokers=1).start()
    try:
        alice = cluster.client(seed=1, topics=[0])
        bob = cluster.client(seed=2, topics=[0])
        await alice.ensure_initialized()
        await bob.ensure_initialized()
        await alice.send_broadcast_message([0], b"hello everyone")
        got = await asyncio.wait_for(bob.receive_message(), 5)
        assert isinstance(got, Broadcast)
        assert bytes(got.message) == b"hello everyone"
        alice.close()
        bob.close()
    finally:
        await cluster.stop()


async def test_double_connect_same_broker_kicks_old():
    """Parity double_connect.rs same-broker case: the second connection of
    one identity evicts the first."""
    cluster = await Cluster(num_brokers=1).start()
    try:
        c1 = cluster.client(seed=7, topics=[0])
        await c1.ensure_initialized()
        await wait_until(lambda: cluster.brokers[0].connections.num_users == 1)

        c2 = cluster.client(seed=7, topics=[0])  # same identity
        await c2.ensure_initialized()
        await asyncio.sleep(0.1)
        assert cluster.brokers[0].connections.num_users == 1  # old evicted
        assert cluster.brokers[0].connections.has_user(c2.public_key)

        # the new connection works; the old one is dead
        await c2.send_direct_message(c2.public_key, b"still here")
        got = await asyncio.wait_for(c2.receive_message(), 5)
        assert bytes(got.message) == b"still here"
        c1.close()
        c2.close()
    finally:
        await cluster.stop()


async def test_double_connect_across_brokers_kicks_old():
    """Parity double_connect.rs cross-broker case with load steering: the
    same identity lands on broker 1, then broker 0; the user-sync merge
    evicts the stale session ("user connected elsewhere")."""
    cluster = await Cluster(num_brokers=2).start()
    try:
        from pushcdn_tpu.broker.tasks.sync import partial_user_sync

        await cluster.steer_load(0, 100)  # broker0 busy -> marshal picks b1
        await cluster.steer_load(1, 0)
        c1 = cluster.client(seed=9, topics=[0])
        await c1.ensure_initialized()
        await wait_until(lambda: cluster.brokers[1].connections.num_users == 1)

        await cluster.steer_load(0, 0)    # now broker1 busy -> picks b0
        await cluster.steer_load(1, 100)
        c2 = cluster.client(seed=9, topics=[0])
        await c2.ensure_initialized()
        await wait_until(lambda: cluster.brokers[0].connections.num_users == 1)

        # strong consistency pushed the new claim to broker1 on join;
        # give the receive loop a beat, then force one more partial sync
        await asyncio.sleep(0.2)
        await partial_user_sync(cluster.brokers[0])
        await asyncio.sleep(0.2)
        assert cluster.brokers[1].connections.num_users == 0  # evicted
        c1.close()
        c2.close()
    finally:
        await cluster.stop()


async def test_cross_broker_direct_message():
    """Direct message routed one hop between brokers over the mesh."""
    cluster = await Cluster(num_brokers=2).start()
    try:
        await cluster.steer_load(0, 100)
        await cluster.steer_load(1, 0)
        alice = cluster.client(seed=11, topics=[0])
        await alice.ensure_initialized()   # lands on broker 1
        await wait_until(lambda: cluster.brokers[1].connections.num_users == 1)

        await cluster.steer_load(0, 0)
        await cluster.steer_load(1, 100)
        bob = cluster.client(seed=12, topics=[0])
        await bob.ensure_initialized()     # lands on broker 0
        await wait_until(lambda: cluster.brokers[0].connections.num_users == 1)
        await asyncio.sleep(0.2)           # let user-sync claims propagate

        await alice.send_direct_message(bob.public_key, b"across the mesh")
        got = await asyncio.wait_for(bob.receive_message(), 5)
        assert isinstance(got, Direct)
        assert bytes(got.message) == b"across the mesh"
        alice.close()
        bob.close()
    finally:
        await cluster.stop()


async def test_cross_broker_broadcast():
    cluster = await Cluster(num_brokers=2).start()
    try:
        await cluster.steer_load(0, 100)
        await cluster.steer_load(1, 0)
        alice = cluster.client(seed=21, topics=[1])
        await alice.ensure_initialized()   # broker 1
        await wait_until(lambda: cluster.brokers[1].connections.num_users == 1)

        await cluster.steer_load(0, 0)
        await cluster.steer_load(1, 100)
        bob = cluster.client(seed=22, topics=[1])
        await bob.ensure_initialized()     # broker 0
        await wait_until(lambda: cluster.brokers[0].connections.num_users == 1)
        await asyncio.sleep(0.2)           # topic interest propagates

        await bob.send_broadcast_message([1], b"DA proposal")
        got = await asyncio.wait_for(alice.receive_message(), 5)
        assert isinstance(got, Broadcast)
        assert bytes(got.message) == b"DA proposal"
        alice.close()
        bob.close()
    finally:
        await cluster.stop()


async def test_subscribe_delivery_and_invalid_topic_kick():
    """Parity subscribe.rs:20-197: live subscribe changes delivery; an
    invalid topic subscription disconnects the user."""
    cluster = await Cluster(num_brokers=1).start()
    try:
        alice = cluster.client(seed=31, topics=[0])
        bob = cluster.client(seed=32, topics=[])
        await alice.ensure_initialized()
        await bob.ensure_initialized()

        await alice.send_broadcast_message([0], b"one")
        # pin the broker-side order: once alice (a topic-0 subscriber) has
        # her copy back, the broker has already routed "one" — sends return
        # when queued, not when routed, so bob's subscribe could otherwise
        # legally overtake it (same non-guarantee as the reference's queued
        # send_message_raw)
        got = await asyncio.wait_for(alice.receive_message(), 5)
        assert bytes(got.message) == b"one"
        await bob.subscribe([0])
        await asyncio.sleep(0.1)
        await alice.send_broadcast_message([0], b"two")
        got = await asyncio.wait_for(bob.receive_message(), 5)
        assert bytes(got.message) == b"two"  # "one" predates the subscribe

        # invalid topic (42 is not in TestTopic space) => broker kicks bob
        conn = bob._connection
        await conn.send_message(Subscribe([42]), flush=True)
        await asyncio.sleep(0.2)
        assert cluster.brokers[0].connections.num_users == 1  # only alice
        alice.close()
        bob.close()
    finally:
        await cluster.stop()


async def test_whitelist_rejection():
    """Parity whitelist.rs:16-77: a user missing from a non-empty whitelist
    is rejected at the marshal with a reason."""
    cluster = await Cluster(num_brokers=1).start()
    try:
        allowed = DEFAULT_SCHEME.generate_keypair(seed=41)
        denied = DEFAULT_SCHEME.generate_keypair(seed=42)
        admin = await Embedded.new(cluster.db)
        await admin.set_whitelist([allowed.public_key])
        await admin.close()

        # allowed client authenticates fine
        ok = Client(ClientConfig(marshal_endpoint=cluster.marshal_endpoint,
                                 keypair=allowed, protocol=Memory))
        await asyncio.wait_for(ok.ensure_initialized(), 5)
        ok.close()

        # denied identity: drive the marshal handshake directly
        conn = await Memory.connect(cluster.marshal_endpoint)
        with pytest.raises(Error) as ei:
            await asyncio.wait_for(
                user_auth.authenticate_with_marshal(conn, DEFAULT_SCHEME, denied), 5)
        assert "whitelist" in str(ei.value)
        conn.close()
    finally:
        await cluster.stop()


async def test_client_reconnects_after_broker_drop():
    """The elastic client re-dials through the marshal after its connection
    dies (single-flight reconnect, lib.rs:204-258)."""
    cluster = await Cluster(num_brokers=1).start()
    try:
        alice = cluster.client(seed=51, topics=[0])
        await alice.ensure_initialized()
        # kill the broker side of alice's session
        broker = cluster.brokers[0]
        broker.connections.remove_user(alice.public_key, "test kill")
        await asyncio.sleep(0.05)
        # next op either fails once (lazy re-dial on the following call) or
        # transparently reconnects-and-delivers; either way the client heals
        try:
            await alice.send_direct_message(alice.public_key, b"probe")
        except Error:
            pass
        await asyncio.wait_for(alice.ensure_initialized(), 10)
        await alice.send_direct_message(alice.public_key, b"healed")
        while True:  # the probe may or may not have survived the reset
            got = await asyncio.wait_for(alice.receive_message(), 5)
            if bytes(got.message) == b"healed":
                break
        # subscriptions were replayed during re-auth
        assert broker.connections.user_topics.get_values_of_key(
            alice.public_key) == {0}
        alice.close()
    finally:
        await cluster.stop()


async def test_bls_mesh_and_cross_broker_delivery():
    """Regression: broker↔broker mutual auth must be scheme-agnostic — the
    wire field packs ``u16 len || key || identity``, so the 128-byte
    BLS-BN254 keys (production scheme) pass the same-key check just like
    32-byte Ed25519 keys."""
    from pushcdn_tpu.proto.crypto.signature import BlsBn254Scheme

    if not BlsBn254Scheme.available():
        pytest.skip("native BLS library unavailable")
    cluster = await Cluster(num_brokers=2, scheme=BlsBn254Scheme).start()
    try:
        await wait_until(
            lambda: all(b.connections.num_brokers == 1
                        for b in cluster.brokers), timeout=30)
        await cluster.steer_load(0, 100)
        await cluster.steer_load(1, 0)
        alice = cluster.client(seed=71, topics=[0])
        await alice.ensure_initialized()   # broker 1
        await cluster.steer_load(0, 0)
        await cluster.steer_load(1, 100)
        bob = cluster.client(seed=72, topics=[0])
        await bob.ensure_initialized()     # broker 0
        await wait_until(
            lambda: sum(b.connections.num_users for b in cluster.brokers) == 2)
        from pushcdn_tpu.testing import wait_mesh_interest
        await wait_mesh_interest(cluster, topic=0, links=1, timeout=30)
        await alice.send_broadcast_message([0], b"bls mesh works")
        got = await asyncio.wait_for(bob.receive_message(), 10)
        assert bytes(got.message) == b"bls mesh works"
        alice.close()
        bob.close()
    finally:
        await cluster.stop()


async def test_device_plane_routes_broker_traffic():
    """With a DevicePlane attached, eligible messages route through the
    jitted device step (frame ring -> routing_step -> delivery matrix) and
    arrive byte-identical; oversized messages fall back to the host path."""
    from pushcdn_tpu.broker.device_plane import DevicePlaneConfig

    cluster = await Cluster(num_brokers=1, device_plane=DevicePlaneConfig(
        num_user_slots=64, ring_slots=64, frame_bytes=1024,
        batch_window_s=0.005, bypass_max_items=0)).start()
    try:
        alice = cluster.client(seed=61, topics=[0])
        bob = cluster.client(seed=62, topics=[0])
        await alice.ensure_initialized()
        await bob.ensure_initialized()
        device = cluster.brokers[0].device_plane
        assert device is not None

        # broadcast: device-routed to both subscribers
        await alice.send_broadcast_message([0], b"via the device plane")
        got = await asyncio.wait_for(bob.receive_message(), 10)
        assert isinstance(got, Broadcast)
        assert bytes(got.message) == b"via the device plane"
        got2 = await asyncio.wait_for(alice.receive_message(), 10)
        assert bytes(got2.message) == b"via the device plane"

        # direct: device-routed to the local recipient
        await alice.send_direct_message(bob.public_key, b"direct on device")
        got3 = await asyncio.wait_for(bob.receive_message(), 10)
        assert isinstance(got3, Direct)
        assert bytes(got3.message) == b"direct on device"

        await wait_until(lambda: device.messages_routed >= 3)
        assert device.steps >= 1

        # mid-size: too big for the 1 KB base lane, rides the default
        # 16 KB extra lane on device (hard-part #1 size bucketing)
        mid = b"z" * 4096
        routed_before = device.messages_routed
        await alice.send_direct_message(bob.public_key, mid)
        got4 = await asyncio.wait_for(bob.receive_message(), 10)
        assert bytes(got4.message) == mid
        await wait_until(lambda: device.messages_routed == routed_before + 1)

        # oversized beyond every lane: falls back to the host path
        big = b"z" * 30_000  # > the 16 KB widest lane
        routed_before = device.messages_routed
        await alice.send_direct_message(bob.public_key, big)
        got5 = await asyncio.wait_for(bob.receive_message(), 10)
        assert bytes(got5.message) == big
        assert device.messages_routed == routed_before  # host path took it
        alice.close()
        bob.close()
    finally:
        await cluster.stop()


async def test_device_plane_routes_high_topics():
    """Topics ≥ 32 (up to the reference's u8 ceiling) ride the device
    plane via multi-word masks instead of falling back to the host path."""
    from pushcdn_tpu.broker.device_plane import DevicePlaneConfig
    from pushcdn_tpu.proto.topic import TopicSpace

    cluster = await Cluster(
        num_brokers=1,
        device_plane=DevicePlaneConfig(
            num_user_slots=64, ring_slots=64, frame_bytes=1024,
            batch_window_s=0.005, bypass_max_items=0),
        topics=TopicSpace.range(256)).start()
    try:
        alice = cluster.client(seed=71, topics=[200])
        bob = cluster.client(seed=72, topics=[200, 255])
        await alice.ensure_initialized()
        await bob.ensure_initialized()
        device = cluster.brokers[0].device_plane

        await alice.send_broadcast_message([200], b"high topic")
        got = await asyncio.wait_for(bob.receive_message(), 10)
        assert bytes(got.message) == b"high topic"
        got2 = await asyncio.wait_for(alice.receive_message(), 10)
        assert bytes(got2.message) == b"high topic"
        await wait_until(lambda: device.messages_routed >= 2)

        # topic 255 reaches only bob — and still on the device
        routed = device.messages_routed
        await alice.send_broadcast_message([255], b"edge of the space")
        got3 = await asyncio.wait_for(bob.receive_message(), 10)
        assert bytes(got3.message) == b"edge of the space"
        await wait_until(lambda: device.messages_routed == routed + 1)
        alice.close()
        bob.close()
    finally:
        await cluster.stop()


async def test_device_plane_compact_topic_words():
    """topic_words=1 keeps the compact 1-word masks (and 1-D mirrors) for
    ≤32-topic deployments; topics ≥ 32 then fall back to the host path."""
    from pushcdn_tpu.broker.device_plane import DevicePlaneConfig
    from pushcdn_tpu.proto.topic import TopicSpace

    cluster = await Cluster(
        num_brokers=1,
        device_plane=DevicePlaneConfig(
            num_user_slots=32, ring_slots=32, frame_bytes=1024,
            topic_words=1, batch_window_s=0.005, bypass_max_items=0),
        topics=TopicSpace.range(256)).start()
    try:
        alice = cluster.client(seed=81, topics=[3, 40])
        await alice.ensure_initialized()
        device = cluster.brokers[0].device_plane
        assert device._masks.ndim == 1

        await alice.send_broadcast_message([3], b"compact lane")
        got = await asyncio.wait_for(alice.receive_message(), 10)
        assert bytes(got.message) == b"compact lane"
        await wait_until(lambda: device.messages_routed >= 1)

        routed = device.messages_routed
        await alice.send_broadcast_message([40], b"host path")
        got2 = await asyncio.wait_for(alice.receive_message(), 10)
        assert bytes(got2.message) == b"host path"
        assert device.messages_routed == routed  # beyond the 1-word space
        alice.close()
    finally:
        await cluster.stop()
