"""Chaos composition invariants (ISSUE 11): interference degrades
loudly, it never corrupts. (1) Admission shedding tripped mid-view must
not stall consensus view completion — sheds are typed notices to the
offender, not lost frames for everyone else. (2) A shard worker dying
mid-stream must not cost a surviving sibling-shard subscriber one
message or one reorder — cross-shard degradation is counted, local
delivery is untouched. Seeded and deterministic, asserted against BOTH
route implementations (the native cut-through plane and the scalar
loops drive the same egress seams)."""

import asyncio

import numpy as np
import pytest

from pushcdn_tpu.broker.tasks import cutthrough
from pushcdn_tpu.proto import trace as trace_mod
from pushcdn_tpu.proto.error import Error, ErrorKind
from pushcdn_tpu.proto.message import Broadcast, deserialize, serialize
from pushcdn_tpu.proto.topic import TopicSpace
from pushcdn_tpu.proto.transport.base import FrameChunk
from pushcdn_tpu.proto.transport.memory import Memory
from pushcdn_tpu.testing.cluster import Cluster
from pushcdn_tpu.testing.consensus import ConsensusConfig, run_consensus


def _route_impl(impl):
    if impl == "native" and not cutthrough.routeplan.available():
        pytest.skip("native route-plan kernel unavailable")


async def _drain_all(conn, settle_s: float = 0.05):
    got = []
    while True:
        try:
            items = await asyncio.wait_for(conn.recv_frames(), settle_s)
        except (asyncio.TimeoutError, Exception):
            return got
        for item in items:
            if type(item) is FrameChunk:
                got.extend(bytes(mv) for mv in item.views())
            else:
                got.append(bytes(item.data))
            item.release()


# ---------------------------------------------------------------------------
# invariant 1: shed mutations mid-view never stall view completion
# ---------------------------------------------------------------------------


async def _subscribe_spammer(cluster, stop: asyncio.Event) -> int:
    """Burst subscribe mutations past the token bucket until admission
    sheds; count the typed Error(SHED) notices."""
    c = cluster.client(seed=71_000, topics=[6])
    sheds = 0
    try:
        await asyncio.wait_for(c.ensure_initialized(), 10.0)
        t = 0
        while not stop.is_set():
            try:
                for _ in range(4):
                    t += 1
                    await c.subscribe([t % 40 + 10])
                while True:
                    await asyncio.wait_for(c.receive_messages(), 0.005)
            except asyncio.TimeoutError:
                pass
            except Error as exc:
                if exc.kind == ErrorKind.SHED:
                    sheds += 1
            except Exception:
                pass
            await asyncio.sleep(0)
    finally:
        c.close()
    return sheds


@pytest.mark.parametrize("impl", ["native", "python"])
async def test_shed_mid_view_never_stalls_consensus(impl, monkeypatch):
    _route_impl(impl)
    # tiny budget so the spammer trips shedding within the first view
    monkeypatch.setenv("PUSHCDN_SUBSCRIBE_RATE", "1")
    monkeypatch.setenv("PUSHCDN_SUBSCRIBE_BURST", "2")
    prev_log = trace_mod.set_log_path(None)
    prev_impl = cutthrough.ROUTE_IMPL
    cutthrough.ROUTE_IMPL = impl
    try:
        # wide topic space: the spammer's mutation topics must be VALID —
        # an invalid topic is a handshake rejection, not a shed
        cluster = await Cluster(num_brokers=1,
                                topics=TopicSpace.range(64)).start()
        try:
            stop = asyncio.Event()
            spam = asyncio.create_task(_subscribe_spammer(cluster, stop))
            run = await run_consensus(cluster, ConsensusConfig(
                num_nodes=4, num_views=3, view_timeout_s=15.0, seed=21))
            stop.set()
            sheds = await asyncio.wait_for(spam, 15.0)
        finally:
            await cluster.stop()
        assert sheds > 0, \
            "the admission layer never shed — the scenario proved nothing"
        assert run.timeouts == 0, \
            f"shed traffic stalled consensus: {run.timeouts} view timeouts"
        assert run.completed == 3
        # the shed offender's connection was degraded, not killed: every
        # quorum vote still arrived
        assert all(v.votes >= 3 for v in run.views)
    finally:
        cutthrough.ROUTE_IMPL = prev_impl
        trace_mod.set_log_path(prev_log)


# ---------------------------------------------------------------------------
# invariant 2: a shard worker death never reorders a survivor's stream
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("impl", ["native", "python"])
async def test_shard_worker_death_no_survivor_reorder(impl):
    _route_impl(impl)
    from pushcdn_tpu.testing.shardharness import run_sharded
    prev_impl = cutthrough.ROUTE_IMPL
    prev_win = Memory.set_duplex_window(512 * 1024)
    cutthrough.ROUTE_IMPL = impl
    try:
        # user-0: survivor subscriber on shard 0; user-1: subscriber on
        # shard 1 (dies with its worker); user-2: publisher on shard 0
        run = await run_sharded([(0, [0]), (1, [0]), (0, [])],
                                num_shards=2)
        try:
            rng = np.random.default_rng(1311)

            def frame(seq: int) -> bytes:
                tail = bytes(rng.integers(
                    0, 256, int(rng.integers(8, 64)), dtype=np.uint8))
                return serialize(Broadcast(
                    [0], seq.to_bytes(4, "big") + tail))

            sender = run.user(2).remote
            await sender.send_raw_many([frame(s) for s in range(20)],
                                       flush=True)
            await run.settle(40)
            # mid-stream worker death: shard 1 stops draining its rings
            # and its users are gone — the in-process analog of the
            # SIGKILL scripts/local_cluster.py --chaos --shards deals out
            await run.brokers[1].stop()
            await sender.send_raw_many([frame(s) for s in range(20, 40)],
                                       flush=True)
            await run.settle(40)

            got = await _drain_all(run.user(0).remote)
            seqs = []
            for raw in got:
                m = deserialize(raw)
                assert isinstance(m, Broadcast)
                seqs.append(int.from_bytes(bytes(m.message)[:4], "big"))
            assert seqs == list(range(40)), (
                f"survivor lost/reordered: got {len(seqs)}, first miss at "
                f"{next((i for i, s in enumerate(seqs) if s != i), '?')}")
            # the publisher rode out its sibling's death
            assert run.brokers[0].connections.has_user(b"user-2")
            # degradation is COUNTED, never silent: the frames destined
            # for the dead shard show up in shard 0's fallback counters
            # once its ring backs up (ring capacity may absorb them all
            # in a short run, so assert the counters exist, not a floor)
            stats = run.runtimes[0].stats()
            assert "relay_fallbacks" in stats and "relay_shed" in stats
        finally:
            await run.shutdown()
    finally:
        cutthrough.ROUTE_IMPL = prev_impl
        Memory.set_duplex_window(prev_win)
