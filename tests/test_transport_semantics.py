"""Direct contract tests for the round-2/3 transport semantics.

Each test pins a documented contract that would otherwise only fail
indirectly (a stalled broker, a leaked pool permit) rather than as an
assert: ``send_raw_many``'s always-released ownership rule, the limiter's
``try_allocate`` FIFO fairness, the native ``FrameEncoder``'s
capacity-overflow fallback, ``deserialize_owned``'s malformed-frame error
parity with ``deserialize``, and the reader/writer cancel-safety paths
added in round 3.
"""

import asyncio
import struct

import pytest

from pushcdn_tpu import native
from pushcdn_tpu.proto import MAX_MESSAGE_SIZE
from pushcdn_tpu.proto.error import Error, ErrorKind
from pushcdn_tpu.proto.limiter import Bytes, Limiter, MemoryPool
from pushcdn_tpu.proto.message import (
    KIND_BROADCAST,
    KIND_DIRECT,
    Broadcast,
    Direct,
    deserialize,
    deserialize_owned,
    serialize,
)
from pushcdn_tpu.proto.transport import Memory

_LEN = struct.Struct(">I")


async def _pair(endpoint: str, limiter: Limiter = None, client_limiter=None):
    from pushcdn_tpu.proto.limiter import NO_LIMIT
    listener = await Memory.bind(endpoint)
    connect = asyncio.create_task(
        Memory.connect(endpoint, limiter=client_limiter or NO_LIMIT))
    server = await (await listener.accept()).finalize(
        limiter=limiter or NO_LIMIT)
    client = await connect
    return listener, client, server


# ---------------------------------------------------------------------------
# send_raw_many ownership: frames are ALWAYS released by the connection
# ---------------------------------------------------------------------------

async def test_send_raw_many_on_poisoned_connection_releases_exactly_once():
    pool = MemoryPool(64 * 1024)
    listener, client, server = await _pair("sem-poisoned")
    # poison the client connection by killing the peer and forcing a write
    server.close()
    await client.send_raw(serialize(Direct(recipient=b"r", message=b"x")))
    for _ in range(200):
        if client.is_closed:
            break
        await asyncio.sleep(0.01)
    frames = [Bytes(b"p" * 128, None) for _ in range(4)]
    permits = [await pool.allocate(128) for _ in range(4)]
    for f, p in zip(frames, permits):
        f._permit = p
    with pytest.raises(Error):
        await client.send_raw_many(frames)
    # released exactly once: pool back to capacity, refcounts at zero
    assert pool.available == 64 * 1024
    assert all(f._refs[0] == 0 for f in frames)
    client.close()
    await listener.close()


async def test_send_raw_many_cancelled_while_blocked_releases():
    # bounded per-connection queue: the put blocks, cancellation must
    # release every frame in the never-inserted batch. The accepted side is
    # never finalized, so nothing drains the 8 KiB duplex window and the
    # client writer genuinely stalls mid-flush.
    pool = MemoryPool(64 * 1024)
    lim = Limiter(per_connection_queue=1)
    listener = await Memory.bind("sem-cancelled")
    connect = asyncio.create_task(Memory.connect("sem-cancelled",
                                                 limiter=lim))
    _unfinalized = await listener.accept()
    client = await connect
    # top the queue up across ticks: the writer takes one frame and blocks
    # mid-flush on the full window, then the bounded queue stays full
    for _ in range(5):
        try:
            while True:
                client.send_raw_nowait(Bytes(b"z" * 8192, None))
        except asyncio.QueueFull:
            pass
        await asyncio.sleep(0.01)
    frames = [Bytes(b"q" * 64, await pool.allocate(64)) for _ in range(5)]
    task = asyncio.create_task(client.send_raw_many(frames))
    await asyncio.sleep(0.05)
    assert not task.done()  # genuinely blocked on the bounded queue
    task.cancel()
    with pytest.raises(asyncio.CancelledError):
        await task
    assert pool.available == 64 * 1024
    assert all(f._refs[0] == 0 for f in frames)
    client.close()
    await listener.close()


# ---------------------------------------------------------------------------
# try_allocate FIFO fairness
# ---------------------------------------------------------------------------

async def test_try_allocate_never_jumps_a_waiter():
    pool = MemoryPool(100)
    held = await pool.allocate(80)
    waiter = asyncio.create_task(pool.allocate(60))
    await asyncio.sleep(0.01)
    assert not waiter.done()
    # 10 bytes ARE available, but granting them would jump the FIFO waiter
    assert pool.try_allocate(10) is None
    held.release()
    permit = await waiter
    assert pool.available == 40
    # with no waiters, try_allocate takes the sync fast path
    fast = pool.try_allocate(40)
    assert fast is not None
    permit.release()
    fast.release()
    assert pool.available == 100


# ---------------------------------------------------------------------------
# FrameEncoder capacity-overflow fallback
# ---------------------------------------------------------------------------

def test_frame_encoder_overflow_returns_none():
    enc = native.FrameEncoder.create(capacity=256)
    if enc is None:
        pytest.skip("native library unavailable")
    ok = enc.encode([b"a" * 32, b"b" * 32])
    assert ok is not None and len(ok) == 72
    ok.release()
    # total (4+200)*2 > 256: must refuse, not truncate
    assert enc.encode([b"c" * 200, b"d" * 200]) is None


async def test_writer_falls_back_when_batch_exceeds_encoder_capacity():
    # a queued batch far beyond the native encoder capacity must still
    # arrive intact via the Python coalescing fallback
    listener, client, server = await _pair("sem-encoder-overflow")
    payloads = [serialize(Broadcast(topics=[0], message=bytes([i]) * 3000))
                for i in range(128)]
    await client.send_raw_many([Bytes(p, None) for p in payloads])
    got = []
    while len(got) < 128:
        raws = await asyncio.wait_for(server.recv_raw_many(), 5)
        got.extend(bytes(r.data) for r in raws)
        for r in raws:
            r.release()
    assert got == payloads
    client.close()
    server.close()
    await listener.close()


# ---------------------------------------------------------------------------
# deserialize_owned malformed-frame parity
# ---------------------------------------------------------------------------

def test_deserialize_owned_truncated_raises_error_not_struct_error():
    # 1-4 byte truncated Direct/Broadcast frames: the fast path must raise
    # the same Error(DESERIALIZE) the two-step path does — the broker's
    # malformed-frame disconnect policy catches Error only
    for frame in (bytes([KIND_DIRECT]), bytes([KIND_DIRECT, 0, 0]),
                  bytes([KIND_BROADCAST]), bytes([KIND_BROADCAST, 1])):
        with pytest.raises(Error) as ei:
            deserialize_owned(frame)
        assert ei.value.kind == ErrorKind.DESERIALIZE
        with pytest.raises(Error):
            deserialize(frame)


def test_deserialize_owned_oversize_parity():
    frame = bytes([KIND_DIRECT]) + b"\x00" * (MAX_MESSAGE_SIZE + 4)
    with pytest.raises(Error) as ei:
        deserialize_owned(frame)
    assert ei.value.kind == ErrorKind.EXCEEDED_SIZE


def test_deserialize_owned_matches_deserialize_on_valid_frames():
    for msg in (Direct(recipient=b"rcpt", message=b"payload"),
                Broadcast(topics=[1, 7], message=b"payload2")):
        frame = serialize(msg)
        owned = deserialize_owned(frame)
        two_step = deserialize(frame)
        assert type(owned) is type(two_step)
        assert bytes(owned.message) == bytes(two_step.message)


# ---------------------------------------------------------------------------
# recv error interleaving + cancel safety (round-3 paths)
# ---------------------------------------------------------------------------

async def test_recv_raw_many_delivers_frames_before_surfacing_error():
    listener, client, server = await _pair("sem-err-interleave")
    for i in range(3):
        await client.send_message(Direct(recipient=b"r", message=bytes([i])))
    # wait until the frames are parsed server-side, then kill the link
    await asyncio.sleep(0.05)
    client.close()
    got = 0
    with pytest.raises(Error):
        while True:
            raws = await asyncio.wait_for(server.recv_raw_many(), 5)
            got += len(raws)
            for r in raws:
                r.release()
    assert got == 3  # queued frames delivered before the poison surfaced
    server.close()
    await listener.close()


async def test_flush_sender_not_stranded_by_close():
    # a flush=True sender whose entry was dequeued must not await forever
    # when close() cancels the writer mid-flush; the accepted side is never
    # finalized, so the 64 KiB frame blocks in the 8 KiB duplex window
    listener = await Memory.bind("sem-flush-cancel")
    connect = asyncio.create_task(Memory.connect("sem-flush-cancel"))
    _unfinalized = await listener.accept()
    client = await connect
    blocker = asyncio.create_task(
        client.send_raw(b"w" * (64 * 1024), flush=True))
    await asyncio.sleep(0.05)
    assert not blocker.done()  # writer is mid-flush
    client.close()
    with pytest.raises((asyncio.CancelledError, Error)):
        await asyncio.wait_for(blocker, 5)
    await listener.close()


async def test_close_with_queued_bare_frame_returns_pool_bytes():
    # the reader's depth-1 fast path queues bare Bytes; close() must drain
    # them back into the pool like list batches
    pool_lim = Limiter(global_pool_bytes=32 * 1024)
    listener, client, server = await _pair("sem-bare-drain",
                                           limiter=pool_lim)
    await client.send_message(Direct(recipient=b"r", message=b"m" * 512))
    await asyncio.sleep(0.05)  # parsed and queued, never received
    server.close()
    await asyncio.sleep(0.05)
    assert pool_lim.pool.available == 32 * 1024
    client.close()
    await listener.close()


async def test_send_encoded_nowait_bounded_queue_fails_fast():
    """The device-plane egress handoff must FAIL (QueueFull), never block,
    when a slow consumer's bounded send queue is full — that failure is
    what triggers the sender-side removal policy, so one stalled client
    cannot stall the pump."""
    import asyncio

    from pushcdn_tpu.proto.limiter import Limiter
    from pushcdn_tpu.proto.transport.memory import (
        gen_testing_connection_pair,
    )

    a, b = await gen_testing_connection_pair(
        Limiter(None, per_connection_queue=2))
    try:
        # the peer never reads and the writer stalls on the tiny duplex
        # window, so entries pile up in the bounded send queue
        big = b"\x00" * 64 * 1024
        for _ in range(8):
            try:
                a.send_encoded_nowait(
                    len(big).to_bytes(4, "big") + big)
            except asyncio.QueueFull:
                break
            await asyncio.sleep(0)
        else:
            raise AssertionError("bounded queue never filled")
    finally:
        a.close()
        b.close()


async def test_bounded_queue_send_order_is_fifo_under_saturation():
    """Bounded connections take the awaited ``q.put`` path (no
    put_nowait fast path): a saturated sequential sender's frames
    transmit in send order, and a putter blocked on a full queue makes
    progress as the writer drains (liveness). asyncio.Queue gives no
    hard slot reservation against a RACING second sender, so this pins
    ordering/liveness for the saturated path, not a global FIFO across
    concurrent senders."""
    lim = Limiter(per_connection_queue=2)
    listener = await Memory.bind("sem-fifo-order")
    connect = asyncio.create_task(Memory.connect("sem-fifo-order",
                                                 limiter=lim))
    server = await (await listener.accept()).finalize()
    client = await connect

    n = 40
    sent = [b"frame-%03d" % i for i in range(n)]

    async def sender():
        for payload in sent:
            await client.send_raw(payload)

    task = asyncio.create_task(sender())
    got = []
    async with asyncio.timeout(10):
        while len(got) < n:
            raw = await server.recv_raw()
            got.append(bytes(raw.data))
            raw.release()
            # stall the drain a tick so the bounded queue saturates and
            # blocked puts interleave with freed slots
            await asyncio.sleep(0)
    await task
    assert got == sent  # exact send order, no slot-stealing reorder
    client.close()
    server.close()
    await listener.close()
