"""Message-lifecycle tracing (ISSUE 4): wire codec roundtrips, sampler
determinism, span emission, and the zero-cost contract for untraced
frames."""

import asyncio

from pushcdn_tpu.proto import trace as trace_mod
from pushcdn_tpu.proto.error import Error
from pushcdn_tpu.proto.message import (
    Broadcast,
    Direct,
    TracedBroadcast,
    TracedDirect,
    decode_frames,
    deserialize,
    deserialize_owned,
    materialize,
    serialize,
    with_trace,
)


def test_traced_codec_roundtrip_broadcast():
    tr = (0xDEADBEEF12345678, 1_700_000_000_000_000_000)
    msg = TracedBroadcast([3, 7], b"payload", tr)
    frame = serialize(msg)
    # flagged kind byte + 16-byte block, otherwise the ordinary layout
    assert frame[0] == 0x85
    assert len(frame) == len(serialize(Broadcast([3, 7], b"payload"))) + 16
    out = deserialize(frame)
    assert type(out) is TracedBroadcast
    assert isinstance(out, Broadcast)  # routing treats it as a Broadcast
    assert out.trace == tr
    assert out.topics == (3, 7) and bytes(out.message) == b"payload"
    owned = deserialize_owned(frame)
    assert owned.trace == tr and type(owned.message) is bytes


def test_traced_codec_roundtrip_direct():
    tr = (42, 99)
    frame = serialize(TracedDirect(b"rcpt", b"hello", tr))
    assert frame[0] == 0x84
    out = deserialize_owned(frame)
    assert type(out) is TracedDirect and isinstance(out, Direct)
    assert out.trace == tr and out.recipient == b"rcpt"
    assert bytes(out.message) == b"hello"


def test_untraced_frames_are_byte_identical_and_pay_nothing():
    for msg in (Broadcast([1], b"x"), Direct(b"r", b"y")):
        frame = serialize(msg)
        assert not frame[0] & 0x80
        out = deserialize(frame)
        assert out.trace is None  # class attribute: no per-instance cost
        assert type(out) in (Broadcast, Direct)


def test_materialize_preserves_trace():
    tr = (7, 8)
    frame = serialize(TracedBroadcast([1], b"z", tr))
    view_msg = deserialize(memoryview(frame))
    assert isinstance(view_msg.message, memoryview)
    owned = materialize(view_msg)
    assert owned.trace == tr and type(owned.message) is bytes


def test_decode_frames_handles_traced_mid_batch():
    tr = (11, 22)
    frames = [serialize(Broadcast([0], b"a")),
              serialize(TracedBroadcast([0], b"b", tr)),
              serialize(Direct(b"r", b"c"))]
    buf = bytearray()
    offs, lens = [], []
    for f in frames:
        offs.append(len(buf) + 4)
        lens.append(len(f))
        buf += len(f).to_bytes(4, "big") + f
    out = decode_frames(bytes(buf), offs, lens)
    assert [m.trace for m in out] == [None, tr, None]
    assert bytes(out[1].message) == b"b"


def test_view_tagged_codec_roundtrip():
    # ISSUE 11: an optional u32 view tag rides the high bit of origin_ns
    # (reserved: wall-clock ns stays below 2**63 until 2262). View-less
    # traces keep the 16-byte block byte-for-byte.
    tr3 = (0xDEADBEEF12345678, 1_700_000_000_000_000_000, 42)
    tr2 = tr3[:2]
    for mk in (lambda t: TracedBroadcast([3], b"p", t),
               lambda t: TracedDirect(b"r", b"p", t)):
        f3, f2 = serialize(mk(tr3)), serialize(mk(tr2))
        assert len(f3) == len(f2) + 4
        for dec in (deserialize, deserialize_owned):
            assert dec(f3).trace == tr3
            assert dec(f2).trace == tr2
    # view 0 is a real view, distinct from "no view"
    f0 = serialize(TracedBroadcast([3], b"p", (1, 2, 0)))
    assert deserialize(f0).trace == (1, 2, 0)


def test_view_tagged_stamp_strip_and_emit():
    frame = serialize(Broadcast([5], b"q"))
    tr = (99, 1_700_000_000_000_000_000, 7)
    stamped = trace_mod.stamp_frame(frame, tr)
    plain, got = trace_mod.strip_frame(stamped)
    assert plain == frame and got == tr
    trace_mod.emit("delivery", tr, "view-tag")
    hop, tid, origin, _, detail = trace_mod.recent[-1]
    assert (hop, tid, origin, detail) == ("delivery", 99, tr[1], "view-tag")


def test_sampler_view_tags_sampled_traces():
    s = trace_mod.Sampler(every=1)
    assert len(s.next_trace()) == 2
    s.view = 12
    tr = s.next_trace()
    assert len(tr) == 3 and tr[2] == 12
    s.pending = 77  # forced post-connect trace carries the view too
    tr = s.next_trace()
    assert tr[0] == 77 and tr[2] == 12
    s.view = None
    assert len(s.next_trace()) == 2


def test_truncated_trace_block_is_deserialize_error():
    import pytest
    frame = serialize(TracedBroadcast([0], b"p", (1, 2)))
    with pytest.raises(Error):
        deserialize(frame[:10])  # cut inside the trace block


def test_with_trace_only_wraps_hot_kinds():
    from pushcdn_tpu.proto.message import Subscribe
    tr = (1, 2)
    assert with_trace(Broadcast([0], b"x"), tr).trace == tr
    assert with_trace(Direct(b"r", b"x"), tr).trace == tr
    sub = Subscribe([0])
    assert with_trace(sub, tr) is sub


def test_stamp_strip_frame_roundtrip():
    frame = serialize(Broadcast([5], b"q"))
    tr = (123456, 789)
    stamped = trace_mod.stamp_frame(frame, tr)
    assert stamped[0] == frame[0] | 0x80
    plain, got = trace_mod.strip_frame(stamped)
    assert plain == frame and got == tr
    plain2, got2 = trace_mod.strip_frame(frame)
    assert plain2 == frame and got2 is None


def test_sampler_is_deterministic_one_in_n():
    s = trace_mod.Sampler(every=8)
    picks = [s.next_trace() is not None for _ in range(32)]
    assert sum(picks) == 4
    assert [i for i, p in enumerate(picks) if p] == [7, 15, 23, 31]


def test_sampler_pending_forces_first_publish():
    s = trace_mod.Sampler(every=1_000_000)
    s.pending = 0xABC
    tr = s.next_trace()
    assert tr is not None and tr[0] == 0xABC
    assert s.next_trace() is None  # back to ordinary sampling


def test_sampler_disabled_never_traces():
    s = trace_mod.Sampler(every=0)
    assert all(s.next_trace() is None for _ in range(10))


def test_emit_observes_hop_histogram_and_recent():
    import time
    before = trace_mod._HOP_CHILDREN["ingress"].total
    tr = (trace_mod._next_id(), time.time_ns() - 5_000_000)  # 5 ms ago
    trace_mod.emit("ingress", tr, "unit-test")
    child = trace_mod._HOP_CHILDREN["ingress"]
    assert child.total == before + 1
    hop, tid, origin, now, detail = trace_mod.recent[-1]
    assert hop == "ingress" and tid == tr[0] and detail == "unit-test"
    assert now >= origin


async def test_traced_publish_spans_through_in_process_broker():
    """A traced Broadcast through a real (in-process, Memory-transport)
    broker emits ingress/plan/egress spans and forwards the traced wire
    frame VERBATIM to subscribers."""
    from pushcdn_tpu.broker.test_harness import TestDefinition
    from pushcdn_tpu.proto.transport.base import FrameChunk

    run = await TestDefinition(connected_users=[[], [0]]).run()
    try:
        tr = trace_mod.new_trace()
        traced = trace_mod.stamp_frame(serialize(Broadcast([0], b"tp")), tr)
        trace_mod.recent.clear()
        await run.user(0).remote.send_raw(traced, flush=True)
        got = []
        async with asyncio.timeout(5):
            while not got:
                for item in await run.user(1).remote.recv_frames():
                    if type(item) is FrameChunk:
                        got.extend(bytes(v) for v in item.views())
                    else:
                        got.append(bytes(item.data))
                    item.release()
        assert got == [traced]  # flag + block intact on the wire
        hops = {h for h, tid, *_ in trace_mod.recent if tid == tr[0]}
        assert {"ingress", "plan", "egress"} <= hops
    finally:
        await run.shutdown()
