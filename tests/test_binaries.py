"""Binary smoke tier: the production CLIs must actually wire the flags
they advertise. Runs the broker binary WITH --device-plane as a real OS
process over TCP, authenticates a client through the marshal binary, and
proves a burst routed on-device by scraping the broker's /metrics
endpoint (cdn_device_messages_routed > 0) — CLI → plane → metrics, full
circle. (The reference's process-compose tier is scripts/local_cluster.py;
this is the always-on pytest slice of it.)"""

import asyncio
import os
import socket
import subprocess
import sys
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_ports(n: int) -> list:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _spawn(name: str, *args: str) -> subprocess.Popen:
    from pushcdn_tpu.bin.common import spawn_binary
    return spawn_binary(name, *args,
                        env_extra={"JAX_PLATFORMS":
                                   os.environ.get("JAX_PLATFORMS", "cpu")})


async def test_broker_binary_device_plane_end_to_end(tmp_path):
    db = str(tmp_path / "cdn.sqlite")
    pub, priv, metrics, marshal_p = _free_ports(4)
    procs = []
    try:
        procs.append(_spawn(
            "broker", "--discovery-endpoint", db,
            "--public-advertise-endpoint", f"127.0.0.1:{pub}",
            "--public-bind-endpoint", f"127.0.0.1:{pub}",
            "--private-advertise-endpoint", f"127.0.0.1:{priv}",
            "--private-bind-endpoint", f"127.0.0.1:{priv}",
            "--metrics-bind-endpoint", f"127.0.0.1:{metrics}",
            "--user-transport", "tcp", "--device-plane",
            "--device-ring-slots", "64"))
        procs.append(_spawn(
            "marshal", "--discovery-endpoint", db,
            "--bind-endpoint", f"127.0.0.1:{marshal_p}",
            "--user-transport", "tcp"))

        from pushcdn_tpu.client import Client, ClientConfig
        from pushcdn_tpu.proto.crypto.signature import DEFAULT_SCHEME
        from pushcdn_tpu.proto.transport import Tcp

        client = Client(ClientConfig(
            marshal_endpoint=f"127.0.0.1:{marshal_p}",
            keypair=DEFAULT_SCHEME.generate_keypair(seed=4242),
            protocol=Tcp, subscribed_topics={0}))
        async with asyncio.timeout(45):  # binaries cold-start + register
            await client.ensure_initialized()

        # a pipelined burst beats the idle bypass and rides the device
        # (budgets stay under conftest's 120 s whole-test cap)
        for _ in range(3):
            await asyncio.gather(*(
                client.send_broadcast_message([0], b"cli burst %d" % i)
                for i in range(16)))
            got = 0
            # generous: under full-suite load on a single core the CLI
            # broker's first staged step can contend with other tests'
            # processes (observed flake at 15 s)
            async with asyncio.timeout(40):
                while got < 16:
                    got += len(await client.receive_messages(16 - got))
            text = await asyncio.to_thread(
                lambda: urllib.request.urlopen(
                    f"http://127.0.0.1:{metrics}/metrics",
                    timeout=5).read().decode())
            routed = [l for l in text.splitlines()
                      if l.startswith("cdn_device_messages_routed ")]
            if routed and float(routed[0].split()[-1]) > 0:
                break
        else:
            raise AssertionError(
                f"device plane never routed via the CLI broker:\n{text}")
        client.close()
        for p in procs:
            assert p.poll() is None, "a binary died during the test"
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def test_every_binary_parses_help():
    """All six CLIs must at least import and build their parsers — the
    load binaries (bad_*) have no other automated exercise as modules."""
    for name in ("broker", "marshal", "client",
                 "bad_broker", "bad_connector", "bad_sender"):
        p = _spawn(name, "--help")
        out, _ = p.communicate(timeout=60)
        assert p.returncode == 0, f"{name} --help failed:\n{out}"
        assert "usage" in out.lower(), out[:200]
