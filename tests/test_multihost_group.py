"""MultiHostBrokerGroup unit tier on the single-process degenerate case
(process_count == 1 ⇒ every shard is local): the partitioned slot space,
the discovery user-slot directory lifecycle, same-host cross-shard
reconnect kicks, and the lockstep pump routing real traffic — all
without subprocesses (the two-OS-process deployment test covers the
cross-host paths)."""

import asyncio

from pushcdn_tpu.broker.mesh_group import MeshGroupConfig
from pushcdn_tpu.broker.multihost_group import (
    MultiHostBrokerGroup,
    PartitionedUserSlots,
)
from pushcdn_tpu.parallel.mesh import make_broker_mesh
from pushcdn_tpu.proto.discovery.embedded import Embedded
from pushcdn_tpu.proto.error import Error


def test_partitioned_slots_owner_by_construction():
    slots = PartitionedUserSlots(64, num_shards=4, local_shards=[1, 3])
    a = slots.assign_in_shard(b"alice", 1)
    b = slots.assign_in_shard(b"bob", 3)
    assert a // slots.slots_per_shard == 1
    assert b // slots.slots_per_shard == 3
    # re-claim at the same shard returns the same slot
    assert slots.assign_in_shard(b"alice", 1) == a
    # freed slots return to their OWN shard's range
    slots.unmap(b"alice")
    slots.free_slot(a)
    assert slots.assign_in_shard(b"carol", 1) == a
    # a non-local shard has no free list
    try:
        slots.assign_in_shard(b"dave", 0)
        raise AssertionError("non-local shard must not allocate")
    except Error:
        pass
    # exhaustion of one shard's range is typed, not silent
    K = slots.slots_per_shard
    for i in range(K - 1):  # carol already holds one
        slots.assign_in_shard(b"u%d" % i, 1)
    try:
        slots.assign_in_shard(b"overflow", 1)
        raise AssertionError("full range must bail")
    except Error:
        pass


async def test_single_process_group_routes_and_directory(tmp_path):
    import jax

    db = str(tmp_path / "d.sqlite")
    mesh = make_broker_mesh(4, devices=jax.devices("cpu")[:4])
    group = MultiHostBrokerGroup(
        mesh,
        MeshGroupConfig(num_user_slots=32, ring_slots=8, frame_bytes=512,
                        extra_lanes=(), direct_bucket_slots=4,
                        batch_window_s=0.02),
        discovery=await Embedded.new(db),
        directory_refresh_s=0.1)
    assert group.local_shards == [0, 1, 2, 3]

    class FakeUserConnection:
        def __init__(self):
            self.streams = []

        def send_encoded_nowait(self, data, owner=None, cls=2, nframes=0):
            self.streams.append(bytes(data))

    class FakeConnections:
        """Mirrors the real Connections contract the group depends on:
        remove_user fires the observer's on_user_removed synchronously
        (that is what releases the old slot during a kick), and egress
        looks sessions up via get_user_connection."""

        def __init__(self):
            self.removed = []
            self.users = {}
            self.observer = None

        def has_user(self, pk):
            return bytes(pk) in self.users

        def get_user_connection(self, pk):
            return self.users.get(bytes(pk))

        def remove_user(self, pk, reason=""):
            self.removed.append((bytes(pk), reason))
            self.users.pop(bytes(pk), None)
            if self.observer is not None:
                self.observer.on_user_removed(bytes(pk))

    class FakeBroker:
        def __init__(self, ident):
            self.identity = ident
            self.connections = FakeConnections()
            self.host_links_kick = asyncio.Event()

        def update_metrics(self):
            pass

    brokers = [FakeBroker("mhg-b0"), FakeBroker("mhg-b2")]
    # attach without the Broker class: the group only needs connections +
    # identity + host_links_kick
    planes = [group.attach(brokers[0], 0), group.attach(brokers[1], 2)]
    for fb, plane in zip(brokers, planes):
        fb.connections.observer = plane
    try:
        await group.ensure_started()

        # claims land in the claiming shard's range and publish to the
        # directory on refresh (sessions register like real connections)
        alice_conn, bob_conn = FakeUserConnection(), FakeUserConnection()
        brokers[0].connections.users[b"alice-pk"] = alice_conn
        group.claim_user(0, b"alice-pk", [0])
        brokers[1].connections.users[b"bob-pk"] = bob_conn
        group.claim_user(2, b"bob-pk", [0])
        slot_a = group.slots.slot_of(b"alice-pk")
        assert slot_a // group.slots_per_shard == 0
        for _ in range(50):
            d = await group.discovery.get_user_slots()
            if b"alice-pk" in d and b"bob-pk" in d:
                break
            await asyncio.sleep(0.05)
        else:
            raise AssertionError("directory never converged")

        # directs resolve the owner statically from the slot
        info = group._direct_route_info(b"bob-pk")
        assert info is not None and info[1] == 2

        # the lockstep pump ROUTES: a broadcast staged at shard 0 lands
        # at both subscribers' sessions as pre-framed egress streams
        from pushcdn_tpu.broker.staging import StageResult
        from pushcdn_tpu.proto.limiter import Bytes
        from pushcdn_tpu.proto.message import Broadcast, serialize
        wire = serialize(Broadcast(topics=[0], message=b"lockstep!"))
        res = planes[0].try_stage(Broadcast(topics=[0], message=b"lockstep!"),
                                  Bytes(wire))
        assert res == StageResult.STAGED
        for _ in range(100):
            if alice_conn.streams and bob_conn.streams:
                break
            await asyncio.sleep(0.05)
        else:
            raise AssertionError("lockstep pump never delivered")
        # the stream is the wire frame, u32-BE length-prefixed
        for conn in (alice_conn, bob_conn):
            frame = conn.streams[0]
            assert frame[4:] == wire and                 int.from_bytes(frame[:4], "big") == len(wire)
        assert group.steps >= 1 and group.messages_routed >= 2

        # same-host cross-shard reconnect: the old session is kicked
        # (observer releases its slot) and the claim moves to shard 2's
        # range in ONE call, exactly like a real reconnect
        brokers[1].connections.users[b"alice-pk"] = FakeUserConnection()
        group.claim_user(2, b"alice-pk", [0])
        assert (b"alice-pk", "user connected elsewhere") in \
            brokers[0].connections.removed
        new_slot = group.slots.slot_of(b"alice-pk")
        assert new_slot // group.slots_per_shard == 2

        # release drops the directory entry (we own the claim)
        group.release_user(2, b"bob-pk")
        for _ in range(50):
            if b"bob-pk" not in await group.discovery.get_user_slots():
                break
            await asyncio.sleep(0.05)
        else:
            raise AssertionError("release never dropped the claim")

        assert not group.disabled

        # partial retirement: one of the host's brokers stops — the
        # collective keeps running (other local brokers depend on it)
        await group.on_shard_stopped(0)
        assert group._task is not None and not group._stop_requested
        assert not group.disabled
        # shard 2 still routes: a direct to bob from shard 2 delivers
        bob_conn2 = FakeUserConnection()
        brokers[1].connections.users[b"bob-pk"] = bob_conn2
        group.claim_user(2, b"bob-pk", [0])
        wire2 = serialize(Broadcast(topics=[0], message=b"after partial"))
        assert planes[1].try_stage(
            Broadcast(topics=[0], message=b"after partial"),
            Bytes(wire2)) == StageResult.STAGED
        for _ in range(100):
            if bob_conn2.streams:
                break
            await asyncio.sleep(0.05)
        else:
            raise AssertionError("group stopped routing after a partial "
                                 "host retirement")
    finally:
        await group.on_shard_stopped(0)
        await group.on_shard_stopped(2)
        await group.discovery.close()
