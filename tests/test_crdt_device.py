"""Property-test: the vectorized device CRDT merge is equivalent to the
host ``VersionedMap`` (SURVEY.md §7 stage 4: "property-test equivalence
against the Python CRDT")."""

import random

import jax.numpy as jnp
import numpy as np

from pushcdn_tpu.broker.versioned_map import VersionedMap, VersionedValue
from pushcdn_tpu.parallel.crdt import (
    ABSENT,
    CrdtState,
    empty_state,
    eviction_mask,
    local_claim,
    local_release,
    merge,
    merge_all_gathered,
)

N = 64


def _host_to_device(m: VersionedMap, n: int = N) -> CrdtState:
    owners = np.full(n, ABSENT, np.int32)
    versions = np.zeros(n, np.uint32)
    identities = np.full(n, ABSENT, np.int32)
    for k, vv in m.full().items():
        owners[k] = ABSENT if vv.value is None else vv.value
        versions[k] = vv.version
        identities[k] = vv.identity
    return CrdtState(jnp.asarray(owners), jnp.asarray(versions),
                     jnp.asarray(identities))


def _random_map(rng, ident: int, steps: int) -> VersionedMap:
    m = VersionedMap(local_identity=ident)
    for _ in range(steps):
        k = rng.randrange(N)
        if rng.random() < 0.25:
            m.remove(k)
        else:
            m.insert(k, rng.randrange(8))
    return m


def test_merge_equivalence_randomized():
    rng = random.Random(42)
    for trial in range(20):
        a = _random_map(rng, ident=rng.randrange(8), steps=rng.randrange(1, 80))
        b = _random_map(rng, ident=rng.randrange(8), steps=rng.randrange(1, 80))

        dev_a, dev_b = _host_to_device(a), _host_to_device(b)
        merged_dev, changed = merge(dev_a, dev_b)

        host = a  # merge b into a
        host_changed = host.merge(b.full())

        expect = _host_to_device(host)
        np.testing.assert_array_equal(np.asarray(merged_dev.owners),
                                      np.asarray(expect.owners), err_msg=f"trial {trial}")
        np.testing.assert_array_equal(np.asarray(merged_dev.versions),
                                      np.asarray(expect.versions))
        np.testing.assert_array_equal(np.asarray(merged_dev.identities),
                                      np.asarray(expect.identities))
        # changed slots where live value changed must match host report
        host_changed_slots = sorted(k for k, old, new in host_changed)
        dev_changed_slots = sorted(np.nonzero(np.asarray(changed))[0].tolist())
        assert dev_changed_slots == host_changed_slots


def test_merge_commutative_and_idempotent():
    rng = random.Random(7)
    a = _host_to_device(_random_map(rng, 1, 50))
    b = _host_to_device(_random_map(rng, 2, 50))
    ab, _ = merge(a, b)
    ba, _ = merge(b, a)
    for x, y in zip(ab, ba):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    aa, changed = merge(ab, ab)
    assert not np.asarray(changed).any()


def test_claim_release_and_eviction_mask():
    state = empty_state(8)
    mask = jnp.asarray([True, True, False, False, False, False, False, False])
    state = local_claim(state, mask, jnp.int32(3))
    assert np.asarray(state.owners)[:2].tolist() == [3, 3]
    assert np.asarray(state.versions)[:2].tolist() == [1, 1]

    # peer 5 claims slot 0 with a higher version -> we must evict slot 0
    peer = empty_state(8)
    peer_mask = jnp.asarray([True] + [False] * 7)
    peer = local_claim(peer, peer_mask, jnp.int32(5))
    peer = local_claim(peer, peer_mask, jnp.int32(5))  # version 2 > our 1

    merged, changed = merge(state, peer)
    locally_connected = mask
    evict = eviction_mask(changed, merged.owners, locally_connected, jnp.int32(3))
    assert np.asarray(evict).tolist() == [True] + [False] * 7

    # releasing slot 1 (still ours) tombstones it
    rel_mask = jnp.asarray([False, True] + [False] * 6)
    merged = local_release(merged, rel_mask, jnp.int32(3))
    assert int(merged.owners[1]) == ABSENT
    assert int(merged.versions[1]) == 2


def test_merge_all_gathered_matches_sequential():
    rng = random.Random(99)
    local = _host_to_device(_random_map(rng, 0, 40))
    peers = [_host_to_device(_random_map(rng, i + 1, 40)) for i in range(4)]
    gathered = CrdtState(
        owners=jnp.stack([p.owners for p in peers]),
        versions=jnp.stack([p.versions for p in peers]),
        identities=jnp.stack([p.identities for p in peers]),
    )
    folded, changed_any = merge_all_gathered(local, gathered)
    seq = local
    changed_seq = np.zeros(N, bool)
    for p in peers:
        seq, ch = merge(seq, p)
        changed_seq |= np.asarray(ch)
    for x, y in zip(folded, seq):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    np.testing.assert_array_equal(np.asarray(changed_any), changed_seq)
