"""scripts/bench_series.py: cross-round merge of BENCH_r*.json into
BENCH_SERIES.md, metric direction inference, and the --gate regression
exit codes (>10% the wrong way vs the previous round fails)."""

import importlib.util
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "bench_series.py")

_spec = importlib.util.spec_from_file_location("bench_series", SCRIPT)
bench_series = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench_series)


def _round(path, rnd, section, headline, provenance=None):
    with open(path, "w") as fh:
        json.dump({"round": rnd,
                   section: {"headline": headline, "rows": [],
                             "provenance": provenance or {}}}, fh)


def test_direction_inference():
    assert bench_series.direction("route_cutthrough_msgs_s") == 1
    assert bench_series.direction("churn_forward_ratio") == 1
    assert bench_series.direction("million_users") == 1
    assert bench_series.direction("broadcast_msgs_sec_chip") == 1
    assert bench_series.direction("clean_view_p99_ms") == -1
    assert bench_series.direction("million_rss_mib") == -1
    assert bench_series.direction("million_max_loop_lag_ms") == -1
    assert bench_series.direction("million_storm_catchup_s") == -1
    # counts with no better/worse reading are tracked but never gated
    assert bench_series.direction("chaos_scenarios") == 0


def test_merge_and_markdown(tmp_path):
    _round(tmp_path / "BENCH_r1.json", 1, "route", {"fwd_msgs_s": 100.0})
    _round(tmp_path / "BENCH_r2.json", 2, "route",
           {"fwd_msgs_s": 120.0, "plan_p99_ms": 3.0})
    rounds = bench_series.load_rounds(str(tmp_path))
    assert rounds == {1: {"route": {"fwd_msgs_s": 100.0}},
                      2: {"route": {"fwd_msgs_s": 120.0,
                                    "plan_p99_ms": 3.0}}}
    md = bench_series.render_markdown(rounds)
    assert "## route" in md
    assert "`fwd_msgs_s`" in md and "120" in md
    assert "`plan_p99_ms`" in md


def test_legacy_schema_folds_in(tmp_path):
    (tmp_path / "BENCH_r1.json").write_text(json.dumps(
        {"n": 1, "cmd": "bench.py", "rc": 0, "tail": "",
         "parsed": {"metric": "broadcast msgs/sec/chip",
                    "value": 42.0, "unit": "msgs/s"}}))
    rounds = bench_series.load_rounds(str(tmp_path))
    assert rounds == {1: {"legacy": {"broadcast_msgs_sec_chip": 42.0}}}


def test_gate_flags_regression_only(tmp_path):
    # throughput -15% and latency +50%: both the wrong way
    _round(tmp_path / "BENCH_r1.json", 1, "route",
           {"fwd_msgs_s": 100.0, "plan_p99_ms": 2.0})
    _round(tmp_path / "BENCH_r2.json", 2, "route",
           {"fwd_msgs_s": 85.0, "plan_p99_ms": 3.0})
    rounds = bench_series.load_rounds(str(tmp_path))
    failed = {(s, m) for s, m, *_ in bench_series.gate(rounds, 0.10)}
    assert failed == {("route", "fwd_msgs_s"), ("route", "plan_p99_ms")}
    # a looser threshold forgives the -15% but not the +50%
    failed = {(s, m) for s, m, *_ in bench_series.gate(rounds, 0.20)}
    assert failed == {("route", "plan_p99_ms")}


def test_gate_improvement_and_new_metrics_pass(tmp_path):
    _round(tmp_path / "BENCH_r1.json", 1, "route", {"fwd_msgs_s": 100.0})
    _round(tmp_path / "BENCH_r2.json", 2, "route",
           {"fwd_msgs_s": 150.0, "brand_new_p99_ms": 9.0})
    rounds = bench_series.load_rounds(str(tmp_path))
    assert bench_series.gate(rounds, 0.10) == []


def test_gate_skips_round_gaps(tmp_path):
    # the metric last appeared two rounds ago: compare against THAT round,
    # not the adjacent one that dropped the section
    _round(tmp_path / "BENCH_r1.json", 1, "route", {"fwd_msgs_s": 100.0})
    _round(tmp_path / "BENCH_r2.json", 2, "other", {"auth_ms": 1.0})
    _round(tmp_path / "BENCH_r3.json", 3, "route", {"fwd_msgs_s": 50.0})
    rounds = bench_series.load_rounds(str(tmp_path))
    fails = bench_series.gate(rounds, 0.10)
    assert [(f[0], f[1], f[2]) for f in fails] == [("route", "fwd_msgs_s", 1)]


def test_gate_waives_cross_host_comparisons(tmp_path):
    """A regression vs a round recorded on a different host (or one that
    predates provenance) is waived — tracked in ``waived``, not a
    failure — while same-fingerprint regressions still gate."""
    host_a = {"platform": "Linux-A", "cpus": 8}
    host_b = {"platform": "Linux-B", "cpus": 1}
    # r1 has no provenance (legacy), r2 on host A, r3 on host B
    _round(tmp_path / "BENCH_r1.json", 1, "other", {"auth_ms": 1.0})
    _round(tmp_path / "BENCH_r2.json", 2, "route",
           {"fwd_msgs_s": 100.0}, provenance=host_a)
    _round(tmp_path / "BENCH_r3.json", 3, "route",
           {"fwd_msgs_s": 50.0}, provenance=host_b)
    rounds = bench_series.load_rounds(str(tmp_path))
    fps = bench_series.load_fingerprints(str(tmp_path))
    waived = []
    assert bench_series.gate(rounds, 0.10, fps, waived) == []
    assert [(w[0], w[1], w[2]) for w in waived] == [("route",
                                                     "fwd_msgs_s", 2)]

    # same host again: the gate re-engages against the host-B baseline
    _round(tmp_path / "BENCH_r4.json", 4, "route",
           {"fwd_msgs_s": 25.0}, provenance=host_b)
    rounds = bench_series.load_rounds(str(tmp_path))
    fps = bench_series.load_fingerprints(str(tmp_path))
    fails = bench_series.gate(rounds, 0.10, fps, [])
    assert [(f[0], f[1], f[2]) for f in fails] == [("route",
                                                    "fwd_msgs_s", 3)]

    # without fingerprints the cross-host pair still gates (legacy call)
    (tmp_path / "BENCH_r4.json").unlink()
    rounds = bench_series.load_rounds(str(tmp_path))
    assert bench_series.gate(rounds, 0.10) != []


def test_cli_gate_exit_codes(tmp_path):
    _round(tmp_path / "BENCH_r1.json", 1, "route", {"fwd_msgs_s": 100.0})
    _round(tmp_path / "BENCH_r2.json", 2, "route", {"fwd_msgs_s": 10.0})
    proc = subprocess.run(
        [sys.executable, SCRIPT, "--root", str(tmp_path), "--gate"],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "GATE FAIL" in proc.stdout
    assert (tmp_path / "BENCH_SERIES.md").exists()

    (tmp_path / "BENCH_r2.json").unlink()
    _round(tmp_path / "BENCH_r2.json", 2, "route", {"fwd_msgs_s": 101.0})
    proc = subprocess.run(
        [sys.executable, SCRIPT, "--root", str(tmp_path), "--gate"],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "gate OK" in proc.stdout


def test_repo_series_is_current():
    """The committed BENCH_SERIES.md matches what the committed
    BENCH_r*.json files produce — regenerating must be a no-op."""
    rounds = bench_series.load_rounds(REPO)
    assert rounds, "repo has no BENCH_r*.json?"
    committed = open(os.path.join(REPO, "BENCH_SERIES.md")).read()
    assert committed == bench_series.render_markdown(rounds)
