"""Worker for the two-process multi-host DEPLOYMENT test (run via
subprocess, not pytest).

Each of two OS processes hosts 4 virtual CPU devices and runs a REAL
slice of the system — jax.distributed runtime, the global 8-shard broker
mesh, its own marshal (stateless, parity: many marshals per deployment),
one TCP broker attached to a local mesh shard (``form_mesh=False``: no
host broker links ever form), and one TCP client authenticated through
its marshal. Asserts the VERDICT deployment criterion end to end:

- a broadcast published by host 0's client is delivered to host 1's
  client purely over the device mesh (zero host broker links on both
  sides, checked);
- a direct message from host 1's client to host 0's client routes
  cross-host after the discovery user-slot directory propagates;
- both brokers report ``connections.num_brokers == 0`` throughout.

Usage: _multihost_worker.py <rank> <base_port> <discovery_db_path>
"""

import asyncio
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")  # sitecustomize may override env

rank = int(sys.argv[1])
base = int(sys.argv[2])
db = sys.argv[3]

jax.distributed.initialize(coordinator_address=f"127.0.0.1:{base}",
                           num_processes=2, process_id=rank)
assert jax.process_count() == 2

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pushcdn_tpu.proto.crypto.signature import DEFAULT_SCHEME  # noqa: E402
from pushcdn_tpu.proto.message import Broadcast, Direct  # noqa: E402
from pushcdn_tpu.testing.two_host import make_two_host_node  # noqa: E402

# deterministic client identities: each host can derive the OTHER's key
CLIENT_SEED = [61_000, 62_000]


async def main() -> None:
    node = await make_two_host_node(
        rank, base, db, client_seeds=CLIENT_SEED, broker_seed_base=50)
    group, broker, client = node.group, node.broker, node.client
    my_shard = node.my_shard

    # rendezvous: wait until the user-slot directory shows BOTH clients
    # (this also phase-syncs the two processes)
    await node.directory_rendezvous()

    # ---- cross-host broadcast (the VERDICT 'Done' criterion) -------------
    if rank == 0:
        await client.send_broadcast_message([0], b"cross-host hello")
    got = await asyncio.wait_for(client.receive_message(), 30)
    assert isinstance(got, Broadcast), got
    assert bytes(got.message) == b"cross-host hello"
    assert broker.connections.num_brokers == 0  # zero host broker links

    # ---- cross-host direct (via the slot directory) ----------------------
    peer_pk = DEFAULT_SCHEME.generate_keypair(
        seed=CLIENT_SEED[1 - rank]).public_key
    # directs are fire-and-forget (reference parity): wait until THIS
    # host's directory mirror has the peer's slot before sending, or the
    # frame legitimately drops as unroutable
    for _ in range(100):
        if group._direct_route_info(bytes(peer_pk)) is not None:
            break
        await asyncio.sleep(0.1)
    else:
        raise AssertionError("peer slot never reached the local mirror")
    if rank == 1:
        await client.send_direct_message(peer_pk, b"direct across hosts")
        # host 0 answers so BOTH directions are proven
        got = await asyncio.wait_for(client.receive_message(), 30)
        assert isinstance(got, Direct)
        assert bytes(got.message) == b"ack from host 0"
    else:
        got = await asyncio.wait_for(client.receive_message(), 30)
        assert isinstance(got, Direct), got
        assert bytes(got.message) == b"direct across hosts"
        await client.send_direct_message(peer_pk, b"ack from host 0")

    assert broker.connections.num_brokers == 0
    assert group.steps > 0
    assert not group.disabled

    # end-of-test rendezvous: neither host may stop the collective pump
    # until BOTH have seen their final deliveries (the directory doubles
    # as the phase barrier)
    await node.publish_marker(b"done-%d" % rank)
    await node.await_markers([b"done-0", b"done-1"])

    client.close()
    await node.marshal.stop()
    if rank == 0:
        await broker.stop()   # triggers the collective stop barrier
    else:
        # peer retirement must stop the collective HERE too (same barrier
        # iteration) and flip disabled, so staging fail-fasts instead of
        # ACKing frames into rings nothing will ever drain
        for _ in range(200):
            if group.disabled:
                break
            await asyncio.sleep(0.05)
        assert group.disabled, "peer retirement never disabled the group"
        from pushcdn_tpu.broker.staging import StageResult
        from pushcdn_tpu.proto.limiter import Bytes as _Bytes
        from pushcdn_tpu.proto.message import serialize
        late = Broadcast(topics=[0], message=b"late")
        raw = _Bytes(serialize(late))
        assert group.try_stage(my_shard, late, raw) == \
            StageResult.INELIGIBLE
        await broker.stop()
    await group.discovery.close()
    jax.distributed.shutdown()
    print(f"rank {rank}: MULTIHOST OK (steps={group.steps}, "
          f"routed={group.messages_routed}, host_links=0)")


asyncio.run(main())
