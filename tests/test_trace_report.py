"""scripts/trace_report.py: chain assembly over synthetic multi-process
span JSONL — complete chains, orphans, duplicate spans, clock-skewed
hops — plus the CLI's strict-gate exit codes."""

import importlib.util
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "trace_report.py")

_spec = importlib.util.spec_from_file_location("trace_report", SCRIPT)
trace_report = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(trace_report)

MS = 1_000_000  # ns


def _span(tid, hop, origin_ns, at_ms, detail=""):
    t_ns = origin_ns + int(at_ms * MS)
    return {"hop": hop, "trace_id": tid, "origin_ns": origin_ns,
            "t_ns": t_ns, "lat_s": at_ms / 1e3, "detail": detail}


def _write(path, spans):
    with open(path, "w") as fh:
        for s in spans:
            fh.write(json.dumps(s) + "\n")


def _synthetic_dir(tmp_path):
    origin = 1_700_000_000_000_000_000
    # chain A: complete, slow-ish (e2e 9ms), spread over three "process"
    # files exactly like a local_cluster run
    a_client = [_span(1, "publish", origin, 0.1),
                _span(1, "delivery", origin, 9.0)]
    a_broker = [_span(1, "ingress", origin, 2.0),
                _span(1, "plan", origin, 2.4),
                _span(1, "egress", origin, 3.0)]
    a_marshal = [_span(1, "auth", origin, 1.0)]
    # chain B: complete + fast, with a clock-SKEWED delivery (receiver
    # clock behind the origin: negative latency)
    b = [_span(2, "publish", origin, 0.1),
         _span(2, "ingress", origin, 0.5),
         _span(2, "plan", origin, 0.6),
         _span(2, "egress", origin, 0.9),
         _span(2, "delivery", origin, -1.5)]
    # chain C: ORPHANED — publish + broker hops, delivery never happened
    c = [_span(3, "publish", origin, 0.1),
         _span(3, "ingress", origin, 0.4),
         _span(3, "plan", origin, 0.5)]
    # duplicates: chain A's ingress span shipped twice (same t_ns)
    dup = [a_broker[0], a_broker[0]]
    _write(tmp_path / "client.jsonl", a_client + b)
    _write(tmp_path / "broker0.jsonl", a_broker + c + dup)
    _write(tmp_path / "marshal.jsonl", a_marshal)
    (tmp_path / "garbled.jsonl").write_text('{"not a span"}\nnot json\n')
    return tmp_path


def test_chain_assembly_orphans_dupes_skew(tmp_path):
    _synthetic_dir(tmp_path)
    spans, dups = trace_report.load_spans([str(tmp_path)])
    assert dups == 2  # dup list re-ships a span already in a_broker
    report = trace_report.build_report(spans, duplicates=dups, top=5)
    assert report["trace_ids"] == 3
    assert report["complete_chains"] == 2
    assert report["incomplete_chains"] == 1
    assert report["orphaned_spans"] == 3  # chain C's spans
    assert report["skewed_hops"] == 1     # chain B's delivery
    assert report["duplicates_dropped"] == 2
    # per-hop stats exist for every hop present, in canonical order
    assert list(report["per_hop"]) == ["auth", "publish", "ingress",
                                       "plan", "egress", "delivery"]
    assert report["per_hop"]["delivery"]["count"] == 2
    # skew clamps to 0, so p50 over [0, 9ms] is one of the two
    assert report["per_hop"]["delivery"]["max_ms"] == 9.0
    # slowest chain is A, broken down hop by hop in time order
    slowest = report["slowest"][0]
    assert slowest["trace_id"] == f"{1:016x}"
    assert slowest["e2e_ms"] == 9.0
    hops = [h["hop"] for h in slowest["hops"]]
    assert hops == ["publish", "auth", "ingress", "plan", "egress",
                    "delivery"]
    # dt of the ingress hop = 2.0ms - 1.0ms (after auth)
    ingress = slowest["hops"][2]
    assert abs(ingress["dt_ms"] - 1.0) < 1e-6


def test_format_report_is_readable(tmp_path):
    _synthetic_dir(tmp_path)
    spans, dups = trace_report.load_spans([str(tmp_path)])
    text = trace_report.format_report(
        trace_report.build_report(spans, duplicates=dups))
    assert "2 complete" in text
    assert "1 incomplete" in text
    assert "p99 ms" in text
    assert "slowest complete chains" in text


def test_cli_strict_gate(tmp_path):
    _synthetic_dir(tmp_path)
    # non-strict: complete chains exist -> 0
    proc = subprocess.run(
        [sys.executable, SCRIPT, "--json", str(tmp_path)],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    report = json.loads(proc.stdout)
    assert report["complete_chains"] == 2
    # strict: the orphaned chain fails the gate
    proc = subprocess.run(
        [sys.executable, SCRIPT, "--strict", str(tmp_path)],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 1
    assert "orphaned" in proc.stderr


def test_cli_fails_without_any_complete_chain(tmp_path):
    _write(tmp_path / "only.jsonl",
           [_span(9, "publish", 1_700_000_000_000_000_000, 0.1)])
    proc = subprocess.run(
        [sys.executable, SCRIPT, str(tmp_path)],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 1
    assert "no complete chain" in proc.stderr
