"""scripts/trace_report.py: chain assembly over synthetic multi-process
span JSONL — complete chains, orphans, duplicate spans, clock-skewed
hops — plus the CLI's strict-gate exit codes."""

import importlib.util
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "trace_report.py")

_spec = importlib.util.spec_from_file_location("trace_report", SCRIPT)
trace_report = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(trace_report)

MS = 1_000_000  # ns


def _span(tid, hop, origin_ns, at_ms, detail=""):
    t_ns = origin_ns + int(at_ms * MS)
    return {"hop": hop, "trace_id": tid, "origin_ns": origin_ns,
            "t_ns": t_ns, "lat_s": at_ms / 1e3, "detail": detail}


def _write(path, spans):
    with open(path, "w") as fh:
        for s in spans:
            fh.write(json.dumps(s) + "\n")


def _synthetic_dir(tmp_path):
    origin = 1_700_000_000_000_000_000
    # chain A: complete, slow-ish (e2e 9ms), spread over three "process"
    # files exactly like a local_cluster run
    a_client = [_span(1, "publish", origin, 0.1),
                _span(1, "delivery", origin, 9.0)]
    a_broker = [_span(1, "ingress", origin, 2.0),
                _span(1, "plan", origin, 2.4),
                _span(1, "egress", origin, 3.0)]
    a_marshal = [_span(1, "auth", origin, 1.0)]
    # chain B: complete + fast, with a clock-SKEWED delivery (receiver
    # clock behind the origin: negative latency)
    b = [_span(2, "publish", origin, 0.1),
         _span(2, "ingress", origin, 0.5),
         _span(2, "plan", origin, 0.6),
         _span(2, "egress", origin, 0.9),
         _span(2, "delivery", origin, -1.5)]
    # chain C: ORPHANED — publish + broker hops, delivery never happened
    c = [_span(3, "publish", origin, 0.1),
         _span(3, "ingress", origin, 0.4),
         _span(3, "plan", origin, 0.5)]
    # duplicates: chain A's ingress span shipped twice (same t_ns)
    dup = [a_broker[0], a_broker[0]]
    _write(tmp_path / "client.jsonl", a_client + b)
    _write(tmp_path / "broker0.jsonl", a_broker + c + dup)
    _write(tmp_path / "marshal.jsonl", a_marshal)
    (tmp_path / "garbled.jsonl").write_text('{"not a span"}\nnot json\n')
    return tmp_path


def test_chain_assembly_orphans_dupes_skew(tmp_path):
    _synthetic_dir(tmp_path)
    spans, dups = trace_report.load_spans([str(tmp_path)])
    assert dups == 2  # dup list re-ships a span already in a_broker
    report = trace_report.build_report(spans, duplicates=dups, top=5)
    assert report["trace_ids"] == 3
    assert report["complete_chains"] == 2
    assert report["incomplete_chains"] == 1
    assert report["orphaned_spans"] == 3  # chain C's spans
    assert report["skewed_hops"] == 1     # chain B's delivery
    assert report["duplicates_dropped"] == 2
    # per-hop stats exist for every hop present, in canonical order
    assert list(report["per_hop"]) == ["auth", "publish", "ingress",
                                       "plan", "egress", "delivery"]
    assert report["per_hop"]["delivery"]["count"] == 2
    # skew clamps to 0, so p50 over [0, 9ms] is one of the two
    assert report["per_hop"]["delivery"]["max_ms"] == 9.0
    # slowest chain is A, broken down hop by hop in time order
    slowest = report["slowest"][0]
    assert slowest["trace_id"] == f"{1:016x}"
    assert slowest["e2e_ms"] == 9.0
    hops = [h["hop"] for h in slowest["hops"]]
    assert hops == ["publish", "auth", "ingress", "plan", "egress",
                    "delivery"]
    # dt of the ingress hop = 2.0ms - 1.0ms (after auth)
    ingress = slowest["hops"][2]
    assert abs(ingress["dt_ms"] - 1.0) < 1e-6


def test_format_report_is_readable(tmp_path):
    _synthetic_dir(tmp_path)
    spans, dups = trace_report.load_spans([str(tmp_path)])
    text = trace_report.format_report(
        trace_report.build_report(spans, duplicates=dups))
    assert "2 complete" in text
    assert "1 incomplete" in text
    assert "p99 ms" in text
    assert "slowest complete chains" in text


def test_cli_strict_gate(tmp_path):
    _synthetic_dir(tmp_path)
    # non-strict: complete chains exist -> 0
    proc = subprocess.run(
        [sys.executable, SCRIPT, "--json", str(tmp_path)],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    report = json.loads(proc.stdout)
    assert report["complete_chains"] == 2
    # strict: the orphaned chain fails the gate
    proc = subprocess.run(
        [sys.executable, SCRIPT, "--strict", str(tmp_path)],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 1
    assert "orphaned" in proc.stderr


def test_cli_fails_without_any_complete_chain(tmp_path):
    _write(tmp_path / "only.jsonl",
           [_span(9, "publish", 1_700_000_000_000_000_000, 0.1)])
    proc = subprocess.run(
        [sys.executable, SCRIPT, str(tmp_path)],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 1
    assert "no complete chain" in proc.stderr


# -- per-view aggregation (ISSUE 11) ------------------------------------


def _view_span(tid, hop, origin_ns, at_ms, view):
    s = _span(tid, hop, origin_ns, at_ms)
    s["view"] = view
    return s


def _chain(tid, origin_ns, view, base_ms, hops=("publish", "ingress",
                                                "plan", "egress",
                                                "delivery")):
    return [_view_span(tid, hop, origin_ns, base_ms + i * 0.2, view)
            for i, hop in enumerate(hops)]


def test_view_report_aggregates_completion_and_slowest(tmp_path):
    origin = 1_700_000_000_000_000_000
    spans = []
    # view 0: two complete chains, slow (completion ~5ms)
    spans += _chain(10, origin, 0, 0.1)
    spans += _chain(11, origin, 0, 4.2)
    # view 1: one complete chain, fast
    spans += _chain(12, origin, 1, 0.1)
    # untagged chain rides along and stays OUT of the view section
    spans += _chain(13, origin, None, 0.1)[0:5]
    for s in spans:
        if s.get("view") is None:
            s.pop("view", None)
    _write(tmp_path / "s.jsonl", spans)
    loaded, _ = trace_report.load_spans([str(tmp_path)])
    vr = trace_report.build_view_report(loaded)
    assert vr["views"] == 2
    assert vr["stalled_views"] == 0
    assert vr["incomplete_view_chains"] == 0
    assert vr["per_view"][0]["chains"] == 2
    assert vr["per_view"][0]["complete"] == 2
    # slowest view is 0 (its last delivery lands latest)
    assert vr["slowest_views"][0] == 0
    assert vr["completion_ms"]["max"] >= vr["completion_ms"]["p50"]
    # no tags at all -> no view section
    assert trace_report.build_view_report(
        [s for s in loaded if "view" not in s]) is None


def test_view_strict_gate_catches_stall_and_orphan(tmp_path):
    origin = 1_700_000_000_000_000_000
    good = _chain(20, origin, 0, 0.1)
    # view 1 stalled: publish happened, nothing ever delivered
    stalled = [_view_span(21, "publish", origin, 0.1, 1),
               _view_span(21, "ingress", origin, 0.3, 1)]
    _write(tmp_path / "s.jsonl", good + stalled)
    proc = subprocess.run(
        [sys.executable, SCRIPT, "--strict", str(tmp_path)],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 1
    # the chain-level orphan gate fires first; the stalled view is the
    # same defect seen at view granularity
    assert "orphaned" in proc.stderr or "stalled" in proc.stderr

    # all chains complete but one view never delivers -> the VIEW gate
    # is what fails
    v0 = _chain(30, origin, 0, 0.1)
    v1_publish_only = _chain(31, origin, 1, 0.1,
                             hops=("publish", "ingress", "plan", "egress",
                                   "delivery"))
    # strip view 1's delivery span but keep the chain complete via an
    # untagged delivery (same trace id, no view key): chain gate passes,
    # stalled-view gate fires
    for s in v1_publish_only:
        if s["hop"] == "delivery":
            s.pop("view")
    _write(tmp_path / "s.jsonl", v0 + v1_publish_only)
    proc = subprocess.run(
        [sys.executable, SCRIPT, "--strict", str(tmp_path)],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 1
    assert "stalled views" in proc.stderr or "incomplete view" in proc.stderr


def test_view_report_renders_in_text_output(tmp_path):
    origin = 1_700_000_000_000_000_000
    _write(tmp_path / "s.jsonl",
           _chain(40, origin, 0, 0.1) + _chain(41, origin, 1, 0.3))
    proc = subprocess.run(
        [sys.executable, SCRIPT, "--strict", str(tmp_path)],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    assert "views: 2 tagged" in proc.stdout
    assert "view completion ms" in proc.stdout


def test_auth_only_connection_is_not_an_orphan(tmp_path):
    origin = 1_700_000_000_000_000_000
    spans = _chain(50, origin, None, 0.1)
    for s in spans:
        s.pop("view", None)
    # a churny subscriber: authenticated, never published
    spans.append(_span(51, "auth", origin, 0.8, detail="marshal-verify"))
    _write(tmp_path / "s.jsonl", spans)
    loaded, _ = trace_report.load_spans([str(tmp_path)])
    report = trace_report.build_report(loaded)
    assert report["complete_chains"] == 1
    assert report["incomplete_chains"] == 0
    assert report["orphaned_spans"] == 0
    assert report["auth_only_chains"] == 1
    # and the strict CLI gate passes
    proc = subprocess.run(
        [sys.executable, SCRIPT, "--strict", str(tmp_path)],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
