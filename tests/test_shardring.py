"""Property tests for the cross-shard shared-memory handoff ring
(ISSUE 6 satellite): wraparound, torn-write detection, ring-full
fallback accounting, lease-ordered slot reclamation, and the worker
runtime's counted drop-to-relay degradation."""

import asyncio
import struct

import numpy as np
import pytest

from pushcdn_tpu.broker import shardring as sr


@pytest.fixture
def ring():
    name = sr.create_ring(16 * 1024)
    w = sr.RingWriter(name, 16 * 1024)
    r = sr.RingReader(name, 16 * 1024)
    try:
        yield w, r
    finally:
        w.close()
        r.close()
        sr.unlink_ring(name)


def test_roundtrip_frames_and_peers(ring):
    w, r = ring
    assert w.try_push([b"alpha", b"bravo!"],
                      [(sr.KIND_USER, b"user-1", [0, 1]),
                       (sr.KIND_BROKER, b"pub:1/priv:1", [1])])
    recs = r.drain()
    assert len(recs) == 1
    rec = recs[0]
    assert rec.peers == [(sr.KIND_USER, b"user-1", [0, 1]),
                         (sr.KIND_BROKER, b"pub:1/priv:1", [1])]
    # streams are u32-BE length-delimited wire bytes
    assert bytes(rec.stream_for([0, 1])) == \
        b"\x00\x00\x00\x05alpha\x00\x00\x00\x06bravo!"
    assert bytes(rec.stream_for([1])) == b"\x00\x00\x00\x06bravo!"
    # contiguous index run -> zero-copy view of the shm payload
    assert isinstance(rec.stream_for([0, 1]), memoryview)
    rec.release()
    assert r.tail == r.head


def test_prefixed_frames_copied_verbatim(ring):
    w, r = ring
    wire = b"\x00\x00\x00\x03abc"
    assert w.try_push([wire], [(sr.KIND_USER, b"u", [0])], prefixed=True)
    rec = r.drain()[0]
    assert bytes(rec.stream_for([0])) == wire
    rec.release()


def test_non_contiguous_index_gathers(ring):
    w, r = ring
    assert w.try_push([b"a", b"b", b"c"], [(sr.KIND_USER, b"u", [0, 2])])
    rec = r.drain()[0]
    data = rec.stream_for([0, 2])
    assert not isinstance(data, memoryview)
    assert bytes(data) == b"\x00\x00\x00\x01a\x00\x00\x00\x01c"
    rec.release()


def test_permuted_index_span_gathers_in_idx_order(ring):
    """REGRESSION: an index list that is a same-span PERMUTATION — the
    shape _flush_shards produces when a peer shares frames first indexed
    by an earlier peer in the batch — must gather in idx order. A
    span-length-only contiguity check took the zero-copy path and
    silently delivered the frames in table order instead."""
    w, r = ring
    assert w.try_push([b"f0", b"f1", b"f2", b"f3"],
                      [(sr.KIND_USER, b"a", [0, 1]),
                       (sr.KIND_USER, b"b", [0, 2, 1, 3])])
    rec = r.drain()[0]
    data = rec.stream_for([0, 2, 1, 3])
    assert not isinstance(data, memoryview)
    assert bytes(data) == (b"\x00\x00\x00\x02f0\x00\x00\x00\x02f2"
                           b"\x00\x00\x00\x02f1\x00\x00\x00\x02f3")
    # strictly consecutive runs still ride zero-copy
    assert isinstance(rec.stream_for([0, 1]), memoryview)
    rec.release()


def test_poisoned_ring_rejects_pushes(ring):
    """Once the consumer abandons a ring (a record that never commits),
    the header poison flag makes every further push fail over to the
    counted relay — a stalled-then-resumed producer must not keep
    feeding a ring nobody drains."""
    w, r = ring
    assert w.try_push([b"a"], [(sr.KIND_USER, b"u", [0])])
    r.poison()
    assert w.poisoned
    dropped = w.dropped
    assert not w.try_push([b"b"], [(sr.KIND_USER, b"u", [0])])
    assert w.dropped == dropped + 1


def test_poison_landing_mid_push_reports_failure(ring):
    """The producer re-checks the poison flag AFTER committing: a stall
    spanning the consumer's abandon window must not count a path=ring
    delivery for a record nobody will ever drain."""
    w, r = ring
    checks = []

    class _MidPushPoisoned(type(w)):
        @property
        def poisoned(self):
            checks.append(1)
            # clean at the entry check, poisoned by the post-commit
            # re-check — the consumer abandoned while we were writing
            return len(checks) > 1

    w.__class__ = _MidPushPoisoned
    assert not w.try_push([b"x"], [(sr.KIND_USER, b"u", [0])])
    assert w.dropped == 1
    assert w.records_pushed == 0


def test_wraparound_many_records(ring):
    """Thousands of pushes through a small ring: every record survives the
    wrap (PAD records at the boundary), sequences stay intact, and the
    ring fully reclaims."""
    w, r = ring
    rng = np.random.default_rng(11)
    sent, got = [], []
    pending = 0
    for i in range(3000):
        payload = bytes(rng.integers(0, 256, int(rng.integers(1, 900)),
                                     dtype=np.uint8))
        while not w.try_push([payload], [(sr.KIND_USER, b"u", [0])]):
            recs = r.drain(8)
            assert recs, "ring full but nothing drainable"
            for rec in recs:
                got.append(bytes(rec.stream_for([0]))[4:])
                rec.release()
        sent.append(payload)
        pending += 1
        if pending % 7 == 0:
            for rec in r.drain(3):
                got.append(bytes(rec.stream_for([0]))[4:])
                rec.release()
    for rec in r.drain(100000):
        got.append(bytes(rec.stream_for([0]))[4:])
        rec.release()
    assert got == sent
    assert r.tail == r.head
    assert r.torn_reads == 0


def test_ring_full_counts_drops(ring):
    w, r = ring
    big = b"z" * 5000
    pushed = 0
    while w.try_push([big], [(sr.KIND_USER, b"u", [0])]):
        pushed += 1
    assert pushed >= 2
    assert w.dropped == 1
    assert not w.try_push([big], [(sr.KIND_USER, b"u", [0])])
    assert w.dropped == 2
    # draining frees the space again
    for rec in r.drain(100):
        rec.release()
    assert w.try_push([big], [(sr.KIND_USER, b"u", [0])])


def test_torn_write_detected_and_recovered(ring):
    """A record whose commit word hasn't landed (simulated mid-write
    state) stops the drain and is counted; once the commit appears the
    record drains normally."""
    w, r = ring
    assert w.try_push([b"first"], [(sr.KIND_USER, b"u", [0])])
    pos = sr.HEADER_BYTES + (r._cursor % r.capacity)
    saved = bytes(r.buf[pos + 4:pos + 8])
    r.buf[pos + 4:pos + 8] = b"\x00\x00\x00\x00"  # wipe the commit word
    assert r.drain() == []
    assert r.torn_reads == 1
    assert r.drain() == []
    assert r.torn_reads == 2
    r.buf[pos + 4:pos + 8] = saved  # "writer finishes" the record
    recs = r.drain()
    assert len(recs) == 1
    assert bytes(recs[0].stream_for([0])) == b"\x00\x00\x00\x05first"
    recs[0].release()


def test_corrupt_length_detected(ring):
    w, r = ring
    assert w.try_push([b"x"], [(sr.KIND_USER, b"u", [0])])
    pos = sr.HEADER_BYTES + (r._cursor % r.capacity)
    r.buf[pos:pos + 4] = struct.pack("<I", r.capacity + 8)  # absurd length
    assert r.drain() == []
    assert r.torn_reads == 1


def test_lease_pins_slot_until_last_holder_drops(ring):
    """Slot reclamation is in-order and waits for every pending flush's
    lease — the PreEncoded.owner contract."""
    w, r = ring
    assert w.try_push([b"one"], [(sr.KIND_USER, b"u", [0])])
    assert w.try_push([b"two"], [(sr.KIND_USER, b"u", [0])])
    rec1, rec2 = r.drain()
    lease1 = rec1.lease()
    rec1.release()
    rec2.release()  # rec2 done FIRST: reclamation must still wait on rec1
    assert r.tail == 0
    del lease1
    assert r.tail == r.head


def test_notify_socket_signals_every_push():
    """EVERY push sends a wakeup byte: an empty->nonempty-only scheme
    races the consumer's lease-deferred tail (a push while the oldest
    slot is still pinned by a pending flush would never re-notify, and
    the consumer would sleep forever on a nonempty ring)."""
    rx, tx = sr.notify_pair()
    name = sr.create_ring(8192)
    try:
        w = sr.RingWriter(name, 8192, notify_sock=tx)
        r = sr.RingReader(name, 8192)
        assert w.try_push([b"a"], [(sr.KIND_USER, b"u", [0])])
        assert rx.recv(16) == b"\x01"
        assert w.try_push([b"b"], [(sr.KIND_USER, b"u", [0])])
        assert rx.recv(16) == b"\x01"
        with pytest.raises(BlockingIOError):
            rx.recv(16)
        for rec in r.drain():
            rec.release()
        assert w.try_push([b"c"], [(sr.KIND_USER, b"u", [0])])
        assert rx.recv(16) == b"\x01"
        w.close()
        r.close()
    finally:
        rx.close()
        tx.close()
        sr.unlink_ring(name)


# ---------------------------------------------------------------------------
# runtime-level: ring-full falls back to the counted control-plane relay
# ---------------------------------------------------------------------------

async def test_runtime_ring_full_falls_back_to_relay():
    from pushcdn_tpu.broker import sharding

    class _Conns:
        num_shards = 2
        shard_id = 0
        shard_notifier = None

    class _Broker:
        connections = _Conns()

    name = sr.create_ring(4096)
    rx, tx = sr.notify_pair()
    try:
        w = sr.RingWriter(name, 4096, notify_sock=tx)
        rt = sharding.ShardRuntime(_Broker(), 0, 2, {1: w}, {}, None)
        relayed = []

        class _Bus:
            def publish(self, origin, event):
                relayed.append((origin, event))
        rt.set_bus(_Bus())
        big = b"q" * 1200
        # fill the ring, then the next handoff must relay (counted), and
        # subsequent handoffs stay on the relay path until drained+acked
        n_ring = 0
        while True:
            before = rt.relay_fallbacks
            rt.handoff(1, [big], [(sr.KIND_USER, b"u", [0])])
            if rt.relay_fallbacks > before:
                break
            n_ring += 1
        assert n_ring >= 1
        assert w.dropped == 1
        assert len(relayed) == 1
        origin, event = relayed[0]
        assert event[0] == "relay" and event[1] == 1
        kind, ident, stream, n = event[2][0]
        assert (kind, ident, n) == (sr.KIND_USER, b"u", 1)
        assert stream == len(big).to_bytes(4, "big") + big
        # still degraded: next handoff relays too (order barrier holds
        # until the consumer drains AND acks)
        rt.handoff(1, [b"tail"], [(sr.KIND_USER, b"u", [0])])
        assert len(relayed) == 2
        # drain + ack -> ring usable again
        r = sr.RingReader(name, 4096)
        for rec in r.drain(1000):
            rec.release()
        rt.apply_event(1, ("relay_ack", 0, rt._relay_epoch[1]))
        assert not rt._relay_unacked[1]  # ack released the byte budget
        before = rt.relay_fallbacks
        rt.handoff(1, [b"back"], [(sr.KIND_USER, b"u", [0])])
        assert rt.relay_fallbacks == before  # rode the ring again
        # doubly-degraded shedding: with the relay budget exhausted and
        # the ring full, further handoffs are DROPPED with a counter —
        # bounded degradation, never unbounded control-plane queues
        while w.try_push([big], [(sr.KIND_USER, b"u", [0])]):
            pass  # refill the ring
        rt._RELAY_MAX_BYTES = 2000
        rt.handoff(1, [big], [(sr.KIND_USER, b"u", [0])])  # relays (1204B)
        shed_before = rt.relay_shed
        rt.handoff(1, [big], [(sr.KIND_USER, b"u", [0])])  # over budget
        assert rt.relay_shed == shed_before + 1
        r.close()
        w.close()
    finally:
        rx.close()
        tx.close()
        sr.unlink_ring(name)


# ---------------------------------------------------------------------------
# supervisor helpers: shard-label injection, hub write-buffer bound
# ---------------------------------------------------------------------------

def test_inject_shard_label_handles_spaced_label_values():
    """Label values may legally contain spaces; the injector must find
    the sample-name boundary at the closing '}', not the first space."""
    from pushcdn_tpu.broker.sharding import _inject_shard_label
    text = ("# HELP cdn_x help text\n"
            'cdn_x{path="GET /metrics",code="200"} 3\n'
            "cdn_plain 1\n"
            "cdn_empty{} 2")
    out = _inject_shard_label(text, 1).splitlines()
    assert out[0] == "# HELP cdn_x help text"
    assert out[1] == 'cdn_x{shard="1",path="GET /metrics",code="200"} 3'
    assert out[2] == 'cdn_plain{shard="1"} 1'
    assert out[3] == 'cdn_empty{shard="1"} 2'


def test_hub_send_disconnects_wedged_worker():
    """A worker that stops draining its control socket is cut loose once
    its buffered hub traffic passes HUB_MAX_BUFFER — bounded parent
    memory instead of unbounded broadcast-delta accumulation."""
    from pushcdn_tpu.broker import sharding

    class _Transport:
        def __init__(self, size):
            self._size = size
            self.aborted = False

        def get_write_buffer_size(self):
            return self._size

        def abort(self):
            self.aborted = True

    class _Writer:
        def __init__(self, buffered):
            self.transport = _Transport(buffered)
            self.frames = []

        def write(self, frame):
            self.frames.append(frame)

    sup = sharding.ShardSupervisor.__new__(sharding.ShardSupervisor)
    sup.hub_disconnects = 0
    sup._hub_buffer_cap = sharding.HUB_MAX_BUFFER
    healthy = _Writer(0)
    wedged = _Writer(sharding.HUB_MAX_BUFFER)
    writers = {0: healthy, 1: wedged}
    sup._hub_send(writers, 0, b"delta")
    sup._hub_send(writers, 1, b"delta")
    assert healthy.frames == [b"delta"]
    # abort, not close: close() would flush-wait on the very peer that
    # isn't draining, so the disconnect would never actually land
    assert wedged.frames == [] and wedged.transport.aborted
    assert 1 not in writers and 0 in writers
    assert sup.hub_disconnects == 1
    sup._hub_send(writers, 1, b"delta")  # gone: a no-op, not a crash
