"""The shard_map compat shim must key the check kwarg on the function's
SIGNATURE, not on ``hasattr(jax, 'shard_map')`` — the jax 0.5.x window
ships a top-level ``jax.shard_map`` that still takes ``check_rep``, and
the old hasattr shim passed it ``check_vma`` (ISSUE 3 satellite)."""

import types

import pytest

from pushcdn_tpu.parallel import jax_compat


def _fake_jax(shard_map_fn, version=None):
    mod = types.SimpleNamespace()
    if shard_map_fn is not None:
        mod.shard_map = shard_map_fn
    if version is not None:
        mod.__version_info__ = version
    return mod


def test_modern_signature_picks_check_vma():
    def modern(f, mesh=None, in_specs=None, out_specs=None,
               check_vma=True):
        return ("modern", check_vma)

    fn, kw = jax_compat._resolve(_fake_jax(modern))
    assert fn is modern and kw == "check_vma"


def test_05x_window_top_level_name_still_takes_check_rep():
    """jax.shard_map exists but with the OLD kwarg: the hasattr shim
    misfired here; signature inspection must pick check_rep."""
    def window(f, mesh=None, in_specs=None, out_specs=None,
               check_rep=True):
        return ("window", check_rep)

    fn, kw = jax_compat._resolve(_fake_jax(window))
    assert fn is window and kw == "check_rep"


def test_opaque_kwargs_wrapper_uses_version_tuple():
    def wrapped(f, **kwargs):
        return ("wrapped", kwargs)

    fn, kw = jax_compat._resolve(_fake_jax(wrapped, version=(0, 5, 3)))
    assert fn is wrapped and kw == "check_rep"
    fn, kw = jax_compat._resolve(_fake_jax(wrapped, version=(0, 6, 0)))
    assert fn is wrapped and kw == "check_vma"


def test_missing_top_level_falls_back_to_experimental():
    fn, kw = jax_compat._resolve(_fake_jax(None))
    assert kw == "check_rep"
    # whatever jax ships here, the fallback import must have succeeded
    assert callable(fn)


def test_installed_jax_resolves_consistently():
    """On the image's real jax, the resolved kwarg must actually be
    accepted by the resolved function's signature (the property the old
    shim violated on 0.5.x)."""
    import inspect
    fn, kw = jax_compat._SHARD_MAP, jax_compat._CHECK_KW
    try:
        params = inspect.signature(fn).parameters
    except (TypeError, ValueError):
        pytest.skip("installed shard_map signature not inspectable")
    assert kw in params or any(
        p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values())
