"""Redis discovery logic, driven through a faithful in-memory fake client
(the real ``redis`` package isn't in this environment; `Redis.new` is
gated). Covers the same semantics the Embedded tests cover: heartbeat
TTLs, least-connections incl. outstanding permits, single-use permit
redemption, scoped vs global permits, whitelist (parity
cdn-proto/src/discovery/redis.rs:38-327)."""

import fnmatch

import pytest

from pushcdn_tpu.proto.discovery.base import BrokerIdentifier
from pushcdn_tpu.proto.discovery.redis import Redis
from pushcdn_tpu.proto.error import Error


class FakeRedis:
    """The subset of redis.asyncio the discovery client uses, with a
    manually-advanced clock for TTL behavior."""

    def __init__(self):
        self.kv = {}        # key -> (value_bytes, expires_at | None)
        self.sets = {}      # key -> set[bytes]
        self.now = 0.0

    def advance(self, seconds: float) -> None:
        self.now += seconds

    def _live(self, key):
        ent = self.kv.get(key)
        if ent is None:
            return None
        value, exp = ent
        if exp is not None and self.now >= exp:
            del self.kv[key]
            return None
        return value

    # -- commands ----------------------------------------------------------
    async def set(self, key, value, ex=None, nx=False):
        if nx and self._live(key) is not None:
            return None
        if isinstance(value, int):
            value = str(value).encode()
        elif isinstance(value, str):
            value = value.encode()
        self.kv[key] = (value, self.now + ex if ex is not None else None)
        return True

    async def get(self, key):
        return self._live(key)

    async def getdel(self, key):
        value = self._live(key)
        self.kv.pop(key, None)
        return value

    async def delete(self, *keys):
        for k in keys:
            self.kv.pop(k, None)
            self.sets.pop(k, None)

    async def sadd(self, key, *members):
        self.sets.setdefault(key, set()).update(members)

    async def scard(self, key):
        return len(self.sets.get(key, ()))

    async def sismember(self, key, member):
        return member in self.sets.get(key, set())

    async def mget(self, keys):
        return [self._live(k) for k in keys]

    async def scan_iter(self, match="*"):
        for key in list(self.kv):
            if self._live(key) is not None and fnmatch.fnmatch(key, match):
                yield key

    def pipeline(self, transaction=False):
        return FakePipeline(self)

    async def aclose(self):
        pass


class FakePipeline:
    def __init__(self, fake):
        self.fake = fake
        self.ops = []

    def __getattr__(self, name):
        def queue(*args, **kwargs):
            self.ops.append((name, args, kwargs))
            return self
        return queue

    async def execute(self):
        out = []
        for name, args, kwargs in self.ops:
            out.append(await getattr(self.fake, name)(*args, **kwargs))
        return out


B1 = BrokerIdentifier("b1-pub", "b1-priv")
B2 = BrokerIdentifier("b2-pub", "b2-priv")


def make(fake, ident, global_permits=False):
    return Redis(fake, ident, global_permits=global_permits)


async def test_heartbeat_membership_and_ttl():
    fake = FakeRedis()
    r1, r2 = make(fake, B1), make(fake, B2)
    await r1.perform_heartbeat(3, heartbeat_expiry_s=60)
    await r2.perform_heartbeat(5, heartbeat_expiry_s=60)
    others = await r1.get_other_brokers()
    assert others == [B2]
    # TTL: a broker that stops heartbeating ages out (redis.rs:93-99)
    fake.advance(61)
    await r1.perform_heartbeat(3, heartbeat_expiry_s=60)
    assert await r1.get_other_brokers() == []


async def test_least_connections_counts_permits():
    fake = FakeRedis()
    r1, r2 = make(fake, B1), make(fake, B2)
    await r1.perform_heartbeat(2, 60)
    await r2.perform_heartbeat(1, 60)
    marshal = make(fake, None)
    assert await marshal.get_with_least_connections() == B2
    # two outstanding permits for b2 outweigh b1's one extra connection
    await marshal.issue_permit(B2, 30, b"user-a")
    await marshal.issue_permit(B2, 30, b"user-b")
    assert await marshal.get_with_least_connections() == B1


async def test_permit_single_use_and_scoping():
    fake = FakeRedis()
    marshal = make(fake, None)
    permit = await marshal.issue_permit(B1, 30, b"alice")
    assert permit > 1  # 0/1 are reserved response codes (message.rs:338)
    broker = make(fake, B1)
    # wrong broker: rejected (and consumed — GETDEL semantics)
    p2 = await marshal.issue_permit(B1, 30, b"bob")
    assert await broker.validate_permit(B2, p2) is None
    # right broker: returns the public key, single-use
    assert await broker.validate_permit(B1, permit) == b"alice"
    assert await broker.validate_permit(B1, permit) is None


async def test_global_permits_flag():
    fake = FakeRedis()
    marshal = make(fake, None)
    permit = await marshal.issue_permit(B1, 30, b"carol")
    broker = make(fake, B2, global_permits=True)
    # with global permits any broker may redeem (discovery/redis.rs:219-226)
    assert await broker.validate_permit(B2, permit) == b"carol"


async def test_permit_expiry():
    fake = FakeRedis()
    marshal = make(fake, None)
    permit = await marshal.issue_permit(B1, 30, b"dave")
    fake.advance(31)
    broker = make(fake, B1)
    assert await broker.validate_permit(B1, permit) is None


async def test_whitelist():
    fake = FakeRedis()
    r = make(fake, None)
    # empty whitelist admits everyone
    assert await r.check_whitelist(b"anyone")
    await r.set_whitelist([b"alice", b"bob"])
    assert await r.check_whitelist(b"alice")
    assert not await r.check_whitelist(b"mallory")
    # replacing the list drops old entries atomically
    await r.set_whitelist([b"carol"])
    assert not await r.check_whitelist(b"alice")
    assert await r.check_whitelist(b"carol")


async def test_no_brokers_is_an_error():
    fake = FakeRedis()
    marshal = make(fake, None)
    with pytest.raises(Error):
        await marshal.get_with_least_connections()


async def test_user_slot_directory_roundtrip_and_newest_wins():
    """The multi-host user-slot directory over Redis: publish/read/drop,
    TTL aging, and the newest-claim-wins conflict rule (a loser host's
    TTL republication must not overwrite the winner's newer claim)."""
    fake = FakeRedis()
    d = make(fake, None)
    await d.publish_user_slots({b"alice": (3, 100.0)}, ttl_s=30)
    assert await d.get_user_slots() == {b"alice": (3, 100.0)}

    # stale republication (older ts) loses; newer claim wins
    await d.publish_user_slots({b"alice": (9, 50.0)}, ttl_s=30)
    assert (await d.get_user_slots())[b"alice"] == (3, 100.0)
    await d.publish_user_slots({b"alice": (7, 200.0)}, ttl_s=30)
    assert (await d.get_user_slots())[b"alice"] == (7, 200.0)

    # TTL expiry ages claims out like broker heartbeats
    fake.advance(31)
    assert await d.get_user_slots() == {}

    # explicit drop on release
    await d.publish_user_slots({b"bob": (1, 1.0), b"carol": (2, 2.0)},
                               ttl_s=30)
    await d.drop_user_slots([b"bob"])
    assert await d.get_user_slots() == {b"carol": (2, 2.0)}


# ---------------------------------------------------------------------------
# Real-server tier (VERDICT r4 #5): the full discovery contract against an
# ACTUAL redis-compatible server — TTL expiry via real time, GETDEL
# single-use atomicity, least-connections with live permits. Skipped when
# the image ships neither a server binary nor the redis client package
# (this environment ships neither and installing is disallowed); the tier
# runs unmodified wherever both exist.
# Parity target: cdn-proto/src/discovery/redis.rs:86-167.
# ---------------------------------------------------------------------------

def _find_redis_server():
    import shutil
    for name in ("redis-server", "valkey-server", "keydb-server"):
        path = shutil.which(name)
        if path:
            return path
    return None


def _have_redis_client():
    try:
        import redis.asyncio  # noqa: F401
        return True
    except ImportError:
        return False


_SERVER = _find_redis_server()
needs_real_redis = pytest.mark.skipif(
    _SERVER is None or not _have_redis_client(),
    reason="real-server tier: no redis-compatible server binary and/or "
           "no 'redis' client package in this image (install forbidden); "
           "runs unmodified where both exist")


@pytest.fixture
def real_redis():
    """Spawn a throwaway real server on a free port, yield its URL.
    Synchronous on purpose: the repo's conftest runs async TESTS without
    pytest-asyncio, so fixtures must not be async generators."""
    import socket
    import subprocess
    import time as _time
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = subprocess.Popen(
        [_SERVER, "--port", str(port), "--save", "", "--appendonly", "no"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        deadline = _time.time() + 10
        while _time.time() < deadline:
            try:
                with socket.create_connection(("127.0.0.1", port),
                                              timeout=1) as c:
                    c.sendall(b"PING\r\n")
                    if c.recv(7).startswith(b"+PONG"):
                        break
            except OSError:
                pass
            _time.sleep(0.05)
        else:
            raise RuntimeError("redis-server never became ready")
        yield f"redis://127.0.0.1:{port}"
    finally:
        proc.terminate()
        proc.wait(timeout=10)


@needs_real_redis
async def test_real_server_heartbeat_ttl_expiry(real_redis):
    """Membership expires by the SERVER's clock, not ours: a broker that
    stops heartbeating vanishes after its TTL."""
    import asyncio
    d = await Redis.new(real_redis, identity=B1)
    try:
        await d.perform_heartbeat(3, heartbeat_expiry_s=1.0)
        others = await Redis.new(real_redis, identity=B2)
        await others.perform_heartbeat(5, heartbeat_expiry_s=30.0)
        assert {str(b) for b in await d.get_other_brokers()} | {str(B1)} \
            >= {str(B1), str(B2)}
        await asyncio.sleep(1.5)  # B1's TTL lapses on the server
        alive = {str(b) for b in await others.get_other_brokers()}
        assert str(B1) not in alive
        await others.close()
    finally:
        await d.close()


@needs_real_redis
async def test_real_server_permit_getdel_single_use(real_redis):
    """GETDEL atomicity: N concurrent redemptions of one permit yield
    exactly one winner."""
    import asyncio
    d = await Redis.new(real_redis, identity=B1)
    try:
        await d.perform_heartbeat(0, heartbeat_expiry_s=30.0)
        permit = await d.issue_permit(B1, 30.0, b"alice")
        results = await asyncio.gather(*(
            d.validate_permit(B1, permit) for _ in range(8)))
        winners = [r for r in results if r == b"alice"]
        assert len(winners) == 1, results
        assert all(r is None for r in results if r != b"alice")
    finally:
        await d.close()


@needs_real_redis
async def test_real_server_least_connections_with_live_permits(real_redis):
    """Outstanding permits count toward load, so the marshal spreads
    storms across brokers before connections even land."""
    d1 = await Redis.new(real_redis, identity=B1)
    d2 = await Redis.new(real_redis, identity=B2)
    try:
        await d1.perform_heartbeat(2, heartbeat_expiry_s=30.0)
        await d2.perform_heartbeat(2, heartbeat_expiry_s=30.0)
        # load equal: 3 permits against B1 must tip selection to B2
        for i in range(3):
            await d1.issue_permit(B1, 30.0, b"user%d" % i)
        chosen = await d1.get_with_least_connections()
        assert str(chosen) == str(B2)
    finally:
        await d1.close()
        await d2.close()


@needs_real_redis
async def test_real_server_permit_ttl_expiry(real_redis):
    """An unredeemed permit lapses by the server's clock."""
    import asyncio
    d = await Redis.new(real_redis, identity=B1)
    try:
        await d.perform_heartbeat(0, heartbeat_expiry_s=30.0)
        permit = await d.issue_permit(B1, 1.0, b"bob")
        await asyncio.sleep(1.5)
        assert await d.validate_permit(B1, permit) is None
    finally:
        await d.close()
