"""CI tier for the deploy story (VERDICT r5 #8): run the ACTUAL
``scripts/local_cluster.py`` — the parity analog of the reference's
process-compose.yaml — as a subprocess: discovery SQLite + marshal + two
brokers + an echo client, each its OWN OS process over real TCP, and
assert the end-to-end echo plus a clean shutdown. Until now that script
was documentation-exercised only; this makes the deploy recipe a tested
artifact.

Skip gates: ``PUSHCDN_SKIP_CLUSTER_TEST=1`` opts out (constrained CI
images), and the test self-skips where loopback TCP listeners are
unavailable. Runtime ~15-25 s (the client echoes on a 1 s interval).
"""

import json
import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "local_cluster.py")
CONSENSUS_BENCH = os.path.join(REPO, "benches", "consensus_bench.py")


def _loopback_available() -> bool:
    try:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
        return True
    except OSError:
        return False


@pytest.mark.skipif(os.environ.get("PUSHCDN_SKIP_CLUSTER_TEST") == "1",
                    reason="PUSHCDN_SKIP_CLUSTER_TEST=1")
@pytest.mark.skipif(not _loopback_available(),
                    reason="no loopback TCP in this sandbox")
def test_local_cluster_end_to_end_echo_and_clean_shutdown(tmp_path):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")  # never touch an accelerator
    trace_dir = str(tmp_path / "spans")
    proc = subprocess.run(
        [sys.executable, SCRIPT, "--duration", "10", "--base-port", "0",
         "--trace-log", trace_dir],
        env=env, capture_output=True, text=True, timeout=180)
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, f"local_cluster failed:\n{out[-6000:]}"
    assert "OK: end-to-end echo through real processes" in out, out[-6000:]
    # ISSUE 4: one complete lifecycle span chain (auth + publish ->
    # ingress -> plan -> egress -> delivery on ONE trace id) assembled
    # from the per-process JSONL span logs
    assert "trace chain complete" in out, out[-6000:]
    # ISSUE 5: the observability plane, proven end to end by the runner —
    # readiness false before broker0's listeners bind...
    assert "readiness pre-bind: 503 not-ready" in out, out[-6000:]
    # ...every process (2 brokers, marshal, client) serving /healthz +
    # /readyz with the check schema...
    assert "health OK (4 processes" in out, out[-6000:]
    # ...broker /debug/topology reflecting the actual mesh...
    assert "topology OK" in out, out[-6000:]
    # ...trace_report --strict: per-hop p50/p99 for a complete chain with
    # zero orphaned spans...
    assert "trace report OK" in out, out[-6000:]
    assert "0 orphaned spans" in out, out[-6000:]
    # ...and readiness flipping false during drain BEFORE listeners close
    assert "drain readiness flip observed" in out, out[-6000:]
    # clean shutdown: the runner SIGINTs every component and exits 0 —
    # a component that survives SIGINT is killed and would have left
    # "FAIL" markers; assert none
    assert "FAIL" not in out, out[-6000:]


@pytest.mark.skipif(os.environ.get("PUSHCDN_SKIP_CLUSTER_TEST") == "1",
                    reason="PUSHCDN_SKIP_CLUSTER_TEST=1")
@pytest.mark.skipif(not _loopback_available(),
                    reason="no loopback TCP in this sandbox")
def test_local_cluster_io_impl_auto(tmp_path):
    """ISSUE 13 + 17: the same real-process cluster with ``--io-impl
    auto --pump auto`` — every component resolves the host I/O engine
    (io_uring where the kernel allows, honest demotion otherwise), the
    fused data-plane pump engages and natively pumps real frames (or
    skips loudly when the composition can't engage), the echo still
    completes, and ``trace_report --strict`` still sees complete span
    chains with zero orphans: traced frames escalate off the pump and
    chain exactly as before."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    trace_dir = str(tmp_path / "spans")
    proc = subprocess.run(
        [sys.executable, SCRIPT, "--duration", "10", "--base-port", "0",
         "--io-impl", "auto", "--pump", "auto", "--trace-log", trace_dir],
        env=env, capture_output=True, text=True, timeout=180)
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, f"io-impl cluster failed:\n{out[-6000:]}"
    assert "[cluster] io-impl: auto" in out, out[-6000:]
    assert "[cluster] pump: auto" in out, out[-6000:]
    from pushcdn_tpu.native import pump as npump
    from pushcdn_tpu.native import routeplan
    from pushcdn_tpu.native import uring as nuring
    if nuring.available() and routeplan.available() and npump.available():
        assert "pump OK" in out, out[-6000:]
    else:
        assert "pump skipped" in out, out[-6000:]
    assert "OK: end-to-end echo through real processes" in out, out[-6000:]
    assert "trace chain complete" in out, out[-6000:]
    assert "trace report OK" in out, out[-6000:]
    assert "0 orphaned spans" in out, out[-6000:]
    assert "FAIL" not in out, out[-6000:]


@pytest.mark.skipif(os.environ.get("PUSHCDN_SKIP_CLUSTER_TEST") == "1",
                    reason="PUSHCDN_SKIP_CLUSTER_TEST=1")
@pytest.mark.skipif(not _loopback_available(),
                    reason="no loopback TCP in this sandbox")
def test_local_cluster_collector():
    """ISSUE 19: the one-pane collector against a REAL cluster —
    ``--collector`` drives ``scripts/cdn_top.py --once --record
    --bundle`` over every process's metrics endpoint and the runner
    asserts the rendered pane covers every process, the recorded
    timeline headline saw all processes up, and the postmortem bundle
    holds every process's raw metrics + each broker's topology +
    manifest. With ``--pump auto`` on a uring-capable kernel the bundled
    broker metrics must carry nonzero ``cdn_pump_stage_seconds`` samples
    for all four native stages (plan/submit/wire/total) — the shm
    telemetry block observed from C end to end; on a demoted host the
    stage sub-check skips loudly inside the runner."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, SCRIPT, "--duration", "10", "--base-port", "0",
         "--io-impl", "auto", "--pump", "auto", "--collector"],
        env=env, capture_output=True, text=True, timeout=240)
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, f"collector cluster failed:\n{out[-6000:]}"
    assert "collector OK" in out, out[-6000:]
    from pushcdn_tpu.native import pump as npump
    from pushcdn_tpu.native import routeplan
    from pushcdn_tpu.native import uring as nuring
    if nuring.available() and routeplan.available() and npump.available():
        # pumped run: the stage histograms were asserted nonzero for all
        # four stages inside check_collector
        assert "pump stages all nonzero" in out, out[-6000:]
    else:
        assert "pump-stage check skipped" in out, out[-6000:]
    assert "OK: end-to-end echo through real processes" in out, out[-6000:]
    assert "FAIL" not in out, out[-6000:]


@pytest.mark.skipif(os.environ.get("PUSHCDN_SKIP_CLUSTER_TEST") == "1",
                    reason="PUSHCDN_SKIP_CLUSTER_TEST=1")
@pytest.mark.skipif(not _loopback_available(),
                    reason="no loopback TCP in this sandbox")
def test_local_cluster_conservation_audit():
    """ISSUE 20: the mesh-wide conservation audit against a REAL
    cluster — ``--audit`` drives ``scripts/cdn_top.py --audit --once``
    over both brokers' /debug/ledger endpoints. The clean leg must merge
    to zero conservation violations and zero unattributed deficit; the
    chaos leg SIGKILLs broker1 mid-stream and requires every frame the
    survivor committed toward it to surface as ATTRIBUTED deficit (never
    silent loss), then a clean balance again once the respawned
    incarnation's fresh link epoch propagates."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, SCRIPT, "--duration", "25", "--base-port", "0",
         "--audit"],
        env=env, capture_output=True, text=True, timeout=240)
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, f"audit cluster failed:\n{out[-6000:]}"
    assert "audit OK (clean): [audit] violations=0 " \
           "unattributed_deficit=0" in out, out[-6000:]
    assert "fully attributed to the dead broker1" in out, out[-6000:]
    assert "audit OK (post-respawn): [audit] violations=0 " \
           "unattributed_deficit=0 attributed_deficit=0" in out, out[-6000:]
    assert "OK: end-to-end echo through real processes" in out, out[-6000:]
    assert "FAIL" not in out, out[-6000:]


@pytest.mark.skipif(os.environ.get("PUSHCDN_SKIP_CLUSTER_TEST") == "1",
                    reason="PUSHCDN_SKIP_CLUSTER_TEST=1")
@pytest.mark.skipif(not _loopback_available(),
                    reason="no loopback TCP in this sandbox")
def test_local_cluster_load_shed():
    """ISSUE 7: forced subscribe-rate overload against a REAL broker —
    the shed reaches the client as a typed Error (never a silent drop),
    the broker flips /readyz 503 with the ``admission`` check failing and
    records the ``load-shed`` flight-recorder event, then recovers to
    /readyz 200 once the storm stops."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, SCRIPT, "--duration", "12", "--base-port", "0",
         "--churn"],
        env=env, capture_output=True, text=True, timeout=180)
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, f"churn local_cluster failed:\n{out[-6000:]}"
    assert "OK: end-to-end echo through real processes" in out, out[-6000:]
    # the shed response reached the client as a typed Error(SHED)
    assert "typed shed Error observed by the client" in out, out[-6000:]
    # /readyz flipped 503 with the admission check failing...
    assert "load shed observed" in out, out[-6000:]
    # ...the flight recorder captured the shed event...
    assert "shed flight-recorder event recorded" in out, out[-6000:]
    # ...and the broker re-entered rotation after the storm
    assert "load shed recovered" in out, out[-6000:]
    assert "FAIL" not in out, out[-6000:]


@pytest.mark.skipif(os.environ.get("PUSHCDN_SKIP_CLUSTER_TEST") == "1",
                    reason="PUSHCDN_SKIP_CLUSTER_TEST=1")
@pytest.mark.skipif(not _loopback_available(),
                    reason="no loopback TCP in this sandbox")
def test_local_cluster_sharded_broker(tmp_path):
    """ISSUE 6: the same cluster with broker0 sharded across 2 worker OS
    processes (fd-handoff accept distribution, so the two clients land on
    different workers deterministically). Asserts the aggregated
    observability plane answers for the whole shard group, the handoff
    rings carried real cross-shard directs, and trace_report --strict
    sees complete span chains with zero orphans THROUGH the cross-shard
    hop."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    trace_dir = str(tmp_path / "spans")
    proc = subprocess.run(
        [sys.executable, SCRIPT, "--duration", "25", "--base-port", "0",
         "--shards", "2", "--trace-log", trace_dir],
        env=env, capture_output=True, text=True, timeout=240)
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, f"sharded local_cluster failed:\n{out[-6000:]}"
    assert "OK: end-to-end echo through real processes" in out, out[-6000:]
    # the aggregated parent endpoint serves health for 5 processes
    # (2 brokers + marshal + 2 clients), with broker0 fronting its workers
    assert "health OK (5 processes" in out, out[-6000:]
    assert "topology OK" in out, out[-6000:]
    # users landed on BOTH workers and the rings carried their directs
    assert "shard plane OK: 2 workers" in out, out[-6000:]
    # complete lifecycle chains (client2 -> worker1 -> ring -> worker0 ->
    # client1 among them), zero orphaned spans under --strict
    assert "trace chain complete" in out, out[-6000:]
    assert "trace report OK" in out, out[-6000:]
    assert "0 orphaned spans" in out, out[-6000:]
    assert "drain readiness flip observed" in out, out[-6000:]
    assert "FAIL" not in out, out[-6000:]


@pytest.mark.skipif(os.environ.get("PUSHCDN_SKIP_CLUSTER_TEST") == "1",
                    reason="PUSHCDN_SKIP_CLUSTER_TEST=1")
@pytest.mark.skipif(not _loopback_available(),
                    reason="no loopback TCP in this sandbox")
def test_local_cluster_chaos_broker_kill_smoke():
    """ISSUE 11 (tier-1 smoke): ONE chaos event — SIGKILL the broker
    serving the echo client — against real processes: the elastic client
    re-load-balances through the marshal and echoes again, the survivor
    logs the peer removal, and the respawned victim re-forms the mesh.
    The full three-event suite is the ``slow``-marked test below."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, SCRIPT, "--duration", "10", "--base-port", "0",
         "--chaos", "--chaos-events", "broker"],
        env=env, capture_output=True, text=True, timeout=240)
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, f"chaos local_cluster failed:\n{out[-6000:]}"
    assert "SIGKILL broker" in out, out[-6000:]
    assert "echo resumed after" in out, out[-6000:]
    assert "peer-loss correlation" in out, out[-6000:]
    assert "mesh re-formed after" in out, out[-6000:]
    assert "all chaos events rode out" in out, out[-6000:]
    assert "FAIL" not in out, out[-6000:]


@pytest.mark.slow
@pytest.mark.skipif(os.environ.get("PUSHCDN_SKIP_CLUSTER_TEST") == "1",
                    reason="PUSHCDN_SKIP_CLUSTER_TEST=1")
@pytest.mark.skipif(not _loopback_available(),
                    reason="no loopback TCP in this sandbox")
def test_local_cluster_chaos_full_suite():
    """ISSUE 11 (slow tier): every scripted chaos event — broker SIGKILL,
    marshal loss (control/data decoupling), and a discovery outage held
    past the store's busy timeout (heartbeat failures land in the
    supervised-task flight recorder; admissions refuse then recover)."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, SCRIPT, "--duration", "20", "--base-port", "0",
         "--chaos"],
        env=env, capture_output=True, text=True, timeout=400)
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, f"chaos local_cluster failed:\n{out[-8000:]}"
    assert "echo resumed after" in out, out[-8000:]
    assert "new admissions refused while the marshal is down" in out
    assert "established data plane kept echoing" in out, out[-8000:]
    assert "new admissions refused during the discovery outage" in out
    assert "admissions recovered after the discovery outage" in out
    assert "heartbeat task-died event recorded" in out, out[-8000:]
    assert "all chaos events rode out" in out, out[-8000:]
    assert "FAIL" not in out, out[-8000:]


@pytest.mark.skipif(os.environ.get("PUSHCDN_SKIP_CLUSTER_TEST") == "1",
                    reason="PUSHCDN_SKIP_CLUSTER_TEST=1")
def test_consensus_bench_quick_smoke():
    """ISSUE 11: the consensus SLO bench's clean scenario in --quick mode
    (in-process cluster, ~1 s): every view completes, the strict
    per-view trace gate passes with zero orphans, and the SLO row
    carries the percentile schema BENCH_r*.json records."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, CONSENSUS_BENCH, "--quick",
         "--scenarios", "clean"],
        env=env, capture_output=True, text=True, timeout=120)
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, f"consensus_bench failed:\n{out[-4000:]}"
    rows = [json.loads(line) for line in proc.stdout.splitlines()
            if line.startswith("{")]
    clean = next(r for r in rows if r.get("bench") == "consensus/clean")
    assert clean["completed"] == clean["views"] and clean["timeouts"] == 0
    assert clean["trace_strict_ok"] is True
    assert clean["trace_orphaned_spans"] == 0
    assert clean["view_completion_p99_ms"] > 0
    assert clean["publish_delivery_p99_ms"] > 0


@pytest.mark.skipif(os.environ.get("PUSHCDN_SKIP_CLUSTER_TEST") == "1",
                    reason="PUSHCDN_SKIP_CLUSTER_TEST=1")
@pytest.mark.skipif(not _loopback_available(),
                    reason="no loopback TCP in this sandbox")
def test_local_cluster_rehome(tmp_path):
    """ISSUE 12: operator-triggered elastic drain against REAL broker
    processes — GET /drain actively re-homes the echo client to the
    surviving broker via a typed Migrate frame (make-before-break, no
    marshal round-trip), topology shows the move, the drained broker
    latches 503 ``draining`` while still serving, the echo keeps flowing
    on the new home, and trace_report --strict still sees complete span
    chains with zero orphans THROUGH the migration."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    trace_dir = str(tmp_path / "spans")
    proc = subprocess.run(
        [sys.executable, SCRIPT, "--duration", "10", "--base-port", "0",
         "--rehome", "--trace-log", trace_dir],
        env=env, capture_output=True, text=True, timeout=180)
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, f"rehome local_cluster failed:\n{out[-6000:]}"
    assert "rehome drain summary" in out, out[-6000:]
    assert "'orphaned': 0" in out, out[-6000:]
    assert "rehome OK" in out, out[-6000:]
    assert "echo alive on the new home" in out, out[-6000:]
    assert "trace report OK" in out, out[-6000:]
    assert "0 orphaned spans" in out, out[-6000:]
    assert "FAIL" not in out, out[-6000:]


@pytest.mark.skipif(os.environ.get("PUSHCDN_SKIP_CLUSTER_TEST") == "1",
                    reason="PUSHCDN_SKIP_CLUSTER_TEST=1")
@pytest.mark.skipif(not _loopback_available(),
                    reason="no loopback TCP in this sandbox")
def test_local_cluster_replay(tmp_path):
    """ISSUE 14: durable-topics catch-up against REAL broker processes —
    publish on a retained topic, one frame delivered live, the
    subscriber killed, more frames published into the ring, then a fresh
    client rejoins with ``subscribe_from(topic, 1)`` and receives the
    full history as an in-order ``Retained`` run followed by live
    delivery (no gap, no dup); trace_report --strict still sees zero
    orphans across the run."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    trace_dir = str(tmp_path / "spans")
    proc = subprocess.run(
        [sys.executable, SCRIPT, "--duration", "10", "--base-port", "0",
         "--replay", "--trace-log", trace_dir],
        env=env, capture_output=True, text=True, timeout=180)
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, f"replay local_cluster failed:\n{out[-6000:]}"
    assert "replay phase 1: live frame delivered" in out, out[-6000:]
    assert "retained frames replayed in order" in out, out[-6000:]
    assert "replay OK: retained 1..5 then live" in out, out[-6000:]
    assert "trace report OK" in out, out[-6000:]
    assert "0 orphaned spans" in out, out[-6000:]
    assert "FAIL" not in out, out[-6000:]


@pytest.mark.slow
@pytest.mark.skipif(os.environ.get("PUSHCDN_SKIP_CLUSTER_TEST") == "1",
                    reason="PUSHCDN_SKIP_CLUSTER_TEST=1")
@pytest.mark.skipif(not _loopback_available(),
                    reason="no loopback TCP in this sandbox")
def test_swarm_soak_quick():
    """ISSUE 12 (slow tier): the multi-process swarm soak in --quick
    size — client-pack workers over real TCP, a live join -> drain ->
    leave -> rejoin cycle and a reconnect storm, with the elastic
    invariant measured (zero delivered-message gaps, zero reorders,
    zero orphans)."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "benches", "swarm_bench.py"),
         "--quick"],
        env=env, capture_output=True, text=True, timeout=500)
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, f"swarm_bench failed:\n{out[-6000:]}"
    assert "rehome OK" in out, out[-6000:]
    assert "storm OK" in out, out[-6000:]
    assert "loss check (live gap detector): open gaps 0" in out, out[-6000:]
    assert "reorders 0" in out, out[-6000:]
    assert "[swarm] OK" in out, out[-6000:]
