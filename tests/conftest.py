"""Test configuration.

Multi-device sharding tests run on a virtual 8-device CPU mesh (no
multi-chip TPU hardware is available in CI): force the host platform and 8
virtual devices BEFORE jax initializes. This mirrors the reference's trick
of standing in for the network with its Memory transport — we stand in for
a TPU pod with virtual CPU devices (SURVEY.md §4).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The axon TPU plugin's sitecustomize overwrites jax_platforms to
# "axon,cpu" regardless of the env var; force CPU before any backend
# initializes so tests run on the virtual 8-device mesh, not the tunnel.
jax.config.update("jax_platforms", "cpu")

import asyncio  # noqa: E402
import inspect  # noqa: E402

import pytest  # noqa: E402


def pytest_configure(config):
    # tier-1 CI runs `-m 'not slow'`; register the marker so the long
    # tiers (full chaos suite, big soak runs) deselect cleanly instead
    # of tripping unknown-marker warnings
    config.addinivalue_line(
        "markers", "slow: long-running tier excluded from tier-1 CI "
        "(run explicitly with -m slow)")


# Run `async def` tests on a fresh event loop (no pytest-asyncio needed).
@pytest.hookimpl(tryfirst=True)
def pytest_pyfunc_call(pyfuncitem):
    fn = pyfuncitem.function
    if inspect.iscoroutinefunction(fn):
        kwargs = {k: pyfuncitem.funcargs[k]
                  for k in pyfuncitem._fixtureinfo.argnames}
        asyncio.run(asyncio.wait_for(fn(**kwargs), timeout=120))
        return True
    return None
