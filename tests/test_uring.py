"""io_uring host data plane (ISSUE 13).

Four tiers:

1. **Capability/CI probe** — this container ships a uring-capable kernel;
   the probe must report usable (skip ONLY on a genuine kernel denial,
   ENOSYS/EPERM), so a toolchain regression can never silently demote the
   whole suite to asyncio and still show green.
2. **Selection/fallback** — ``auto`` demotes to asyncio with exactly one
   warning when the kernel denies; explicit ``--io-impl uring`` raises
   instead of mislabeling.
3. **Seeded equivalence** — the same deterministic message mix through a
   REAL broker over real loopback TCP must produce byte-identical
   per-peer delivery sequences for every (io impl x route impl) config,
   on 1 broker and on a 2-shard worker group, with the byte pools
   balanced afterwards (zero leaked permits).
4. **Fault tier** — short writes (residue re-pump vs mid-chain poison),
   peer reset mid-transfer, stalled-peer backpressure at the TX
   watermark, engine teardown with in-flight SQEs, and MSG_ZEROCOPY
   lease reclamation deferred to the kernel's NOTIF completion.
"""

import asyncio
import errno
import gc
import logging
import os
import socket

import pytest

from pushcdn_tpu.broker.tasks import cutthrough
from pushcdn_tpu.broker.test_harness import TestDefinition
from pushcdn_tpu.native import pump as npump
from pushcdn_tpu.native import routeplan
from pushcdn_tpu.native import uring as nuring
from pushcdn_tpu.proto.limiter import NO_LIMIT, Limiter
from pushcdn_tpu.proto.message import Broadcast, Direct
from pushcdn_tpu.proto.transport import pump as pump_mod
from pushcdn_tpu.proto.transport import uring as umod

_URING_OK = nuring.available()
_PUMP_OK = _URING_OK and routeplan.available() and npump.available()

requires_uring = pytest.mark.skipif(
    not _URING_OK,
    reason=f"io_uring unavailable ({nuring.probe_errname()})")
requires_zc = pytest.mark.skipif(
    not (_URING_OK and nuring.zerocopy_supported()),
    reason="MSG_ZEROCOPY sends unsupported by this kernel's io_uring")
requires_pump = pytest.mark.skipif(
    not _PUMP_OK,
    reason="fused pump needs io_uring + the native route-plan kernel")


@pytest.fixture(autouse=True)
def _io_impl_state():
    """Save/restore the process-global io-impl selection (env + resolved
    cache + warn-once latches) and the route-impl toggle, and shut every
    engine down after each test — fd/lease hygiene across the suite."""
    saved_env = {k: os.environ.get(k)
                 for k in ("PUSHCDN_IO_IMPL", "PUSHCDN_IO_URING",
                           "PUSHCDN_URING_ZC_MIN", "PUSHCDN_PUMP")}
    saved = (umod._resolved, umod._warned_demote, umod._warned_tls,
             cutthrough.ROUTE_IMPL)
    saved_pump = (pump_mod.PUMP_IMPL, pump_mod._warned_demote)
    yield
    umod.UringEngine.shutdown()
    for k, v in saved_env.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    (umod._resolved, umod._warned_demote, umod._warned_tls,
     cutthrough.ROUTE_IMPL) = saved
    (pump_mod.PUMP_IMPL, pump_mod._warned_demote) = saved_pump


# ---------------------------------------------------------------------------
# tier 1: capability probe (the CI assertion for this container)
# ---------------------------------------------------------------------------

def test_probe_reports_capability_on_this_container():
    cap = nuring.probe()
    if cap < 0:
        # only a genuine kernel denial may skip; anything else (a binding
        # bug, a build failure) must FAIL so the suite can't silently run
        # asyncio-only while claiming coverage
        assert -cap in (errno.ENOSYS, errno.EPERM), (
            f"io_uring probe failed with unexpected "
            f"{nuring.probe_errname()} ({cap})")
        pytest.skip(f"kernel denies io_uring ({nuring.probe_errname()})")
    assert cap & 1, f"probe bitmask {cap} lacks the usable bit"
    assert nuring.available()


# ---------------------------------------------------------------------------
# tier 2: selection and graceful fallback
# ---------------------------------------------------------------------------

def test_configured_io_impl_env_parsing(monkeypatch):
    monkeypatch.delenv("PUSHCDN_IO_IMPL", raising=False)
    monkeypatch.delenv("PUSHCDN_IO_URING", raising=False)
    assert umod.configured_io_impl() == "asyncio"  # opt-in this round
    monkeypatch.setenv("PUSHCDN_IO_IMPL", "uring")
    assert umod.configured_io_impl() == "uring"
    monkeypatch.setenv("PUSHCDN_IO_IMPL", "bogus")
    assert umod.configured_io_impl() == "asyncio"
    monkeypatch.delenv("PUSHCDN_IO_IMPL")
    monkeypatch.setenv("PUSHCDN_IO_URING", "1")  # legacy spelling
    assert umod.configured_io_impl() == "uring"
    monkeypatch.setenv("PUSHCDN_IO_URING", "auto")
    assert umod.configured_io_impl() == "auto"
    with pytest.raises(ValueError):
        umod.set_io_impl("epoll")


def _deny_kernel(monkeypatch):
    monkeypatch.setattr(nuring, "available", lambda: False)
    monkeypatch.setattr(nuring, "probe", lambda: -errno.ENOSYS)
    monkeypatch.setattr(nuring, "probe_errname", lambda: "ENOSYS")
    monkeypatch.setattr(umod, "_resolved", None)
    monkeypatch.setattr(umod, "_warned_demote", False)


def test_auto_demotes_to_asyncio_with_one_warning(monkeypatch, caplog):
    _deny_kernel(monkeypatch)
    monkeypatch.setenv("PUSHCDN_IO_IMPL", "auto")
    with caplog.at_level(logging.WARNING, logger="pushcdn.uring"):
        assert umod.resolve_io_impl() == "asyncio"
        monkeypatch.setattr(umod, "_resolved", None)  # force re-resolve
        assert umod.resolve_io_impl() == "asyncio"
    warnings = [r for r in caplog.records if "demoted to" in r.message]
    assert len(warnings) == 1, "demotion must warn exactly once"
    assert "ENOSYS" in warnings[0].getMessage()


def test_explicit_uring_raises_when_kernel_denies(monkeypatch):
    _deny_kernel(monkeypatch)
    monkeypatch.setenv("PUSHCDN_IO_IMPL", "uring")
    with pytest.raises(nuring.RingError) as ei:
        umod.resolve_io_impl()
    assert "ENOSYS" in str(ei.value)


@requires_uring
def test_resolve_selects_uring_when_requested(monkeypatch):
    monkeypatch.setattr(umod, "_resolved", None)
    monkeypatch.setenv("PUSHCDN_IO_IMPL", "uring")
    assert umod.resolve_io_impl() == "uring"


# ---------------------------------------------------------------------------
# tier 3: seeded delivery equivalence through a real broker
# ---------------------------------------------------------------------------

# user-0 is the sender; the topic layout gives every message class a
# target: topic-2 fans out, topic-3 is single-owner, directs hit 1 and 2
_USER_TOPICS = ((1, 2), (2,), (1, 3))
_SCENARIO_SEED = 0xC0FFEE


def _scenario_messages():
    """Deterministic mix spanning every TX path: tiny coalesced sends,
    mid-size frames, >64 KiB entries that skip coalescing, and ~200 KiB
    frames that exercise the chunked owner flush."""
    import random
    rng = random.Random(_SCENARIO_SEED)
    sizes = (5, 700, 9_000, 70_000, 200_000)
    msgs = []
    for i in range(20):
        payload = rng.randbytes(sizes[i % len(sizes)])
        if i % 2:
            msgs.append(Broadcast(topics=[rng.choice((1, 2, 3))],
                                  message=payload))
        else:
            msgs.append(Direct(recipient=f"user-{rng.choice((1, 2))}".encode(),
                               message=payload))
    return msgs


async def _drain_sequence(entity, quiet=0.4):
    """Everything the entity receives, in order, as (len, digest) pairs
    (full-byte identity without holding megabytes per config)."""
    import hashlib
    seq = []
    while True:
        try:
            raw = await asyncio.wait_for(entity.remote.recv_raw(), quiet)
        except (asyncio.TimeoutError, Exception):
            return seq
        data = bytes(raw.data) if hasattr(raw, "data") else bytes(raw)
        seq.append((len(data), hashlib.sha256(data).hexdigest()))
        if hasattr(raw, "release"):
            raw.release()


def _assert_pool_balanced(limiter, what):
    gc.collect()
    pool = getattr(limiter, "pool", None)
    if pool is not None:
        assert pool.available == pool.capacity, (
            f"{what}: {pool.capacity - pool.available} pooled bytes "
            f"leaked (permit imbalance)")


def _pump_summary(broker):
    state = getattr(broker, "_route_state", None)
    ps = getattr(state, "_pump_state", None)
    if ps is None or ps.closed:
        return None
    return ps.summary()


async def _run_one_shard(io_impl, route_impl, msgs, pump="off"):
    umod.set_io_impl(io_impl)
    cutthrough.ROUTE_IMPL = route_impl
    pump_mod.set_pump_impl(pump)
    run = await TestDefinition(connected_users=_USER_TOPICS,
                               tcp_users=True).run()
    try:
        if io_impl == "uring":
            assert umod.resolve_io_impl() == "uring"
            assert isinstance(run.tcp_listener, umod.UringListener)
        for i, m in enumerate(msgs):
            await run.send_message_as(run.user(0), m)
            if i == 0:
                # one idle gap: pump engagement completes at the first
                # TX-idle transition, so the remaining mix exercises the
                # engaged path (a no-op for the non-pump legs)
                await asyncio.sleep(0.15)
        seqs = await asyncio.gather(
            *[_drain_sequence(u) for u in run.connected_users])
        summary = _pump_summary(run.broker)
    finally:
        await run.shutdown()
    _assert_pool_balanced(run.broker.limiter,
                          f"1-shard {io_impl}/{route_impl}/pump={pump}")
    return ({u.public_key: s
             for u, s in zip(run.connected_users, seqs)}, summary)


async def _run_two_shards(io_impl, route_impl, msgs, pump="off"):
    from pushcdn_tpu.testing.shardharness import run_sharded
    umod.set_io_impl(io_impl)
    cutthrough.ROUTE_IMPL = route_impl
    pump_mod.set_pump_impl(pump)
    # sender on worker 0, receivers split across workers: topic-2 fanout
    # and the directs both cross the shard ring
    run = await run_sharded(
        [(0, _USER_TOPICS[0]), (1, _USER_TOPICS[1]), (1, _USER_TOPICS[2])],
        num_shards=2, tcp_users=True)
    try:
        for i, m in enumerate(msgs):
            await run.user(0).remote.send_message(m, flush=True)
            if i == 0:
                await asyncio.sleep(0.15)
        seqs = await asyncio.gather(
            *[_drain_sequence(u) for u, _ in run.connected_users])
        summaries = [s for s in map(_pump_summary, run.brokers)
                     if s is not None]
    finally:
        await run.shutdown()
    for broker in run.brokers:
        _assert_pool_balanced(broker.limiter,
                              f"2-shard {io_impl}/{route_impl}/pump={pump}")
    return ({u.public_key: s
             for (u, _), s in zip(run.connected_users, seqs)}, summaries)


def _io_impls():
    return ("asyncio", "uring") if _URING_OK else ("asyncio",)


def _equivalence_configs():
    """(io impl, route impl, pump) legs: the io x route grid with the
    pump off, plus — when the composition can engage here — the fused
    pump leg on top of uring+native."""
    configs = [(io_impl, route_impl, "off")
               for io_impl in _io_impls()
               for route_impl in ("python", "native")]
    if _PUMP_OK:
        configs.append(("uring", "native", "auto"))
    return configs


async def test_delivery_equivalence_one_shard():
    """Byte-identical per-peer delivery SEQUENCES across io x route x
    pump impls through one real broker over loopback TCP."""
    msgs = _scenario_messages()
    baseline = None
    for io_impl, route_impl, pump in _equivalence_configs():
        got, summary = await _run_one_shard(io_impl, route_impl, msgs,
                                            pump=pump)
        if baseline is None:
            baseline = got
            # the scenario must actually deliver: every receiver saw
            # traffic (a silent broker would vacuously "match")
            assert all(len(s) > 0 for s in got.values()), got
        assert got == baseline, (
            f"delivery diverged under {io_impl}/{route_impl}/pump={pump}")
        if pump == "auto":
            # non-vacuous: the pump leg must have actually pumped
            assert summary is not None and summary["pump_frames"] > 0, (
                f"pump leg never sent a frame natively: {summary}")
    if not _URING_OK:
        pytest.skip("asyncio-only equivalence (io_uring unavailable)")


async def test_delivery_equivalence_two_shards():
    """The same contract across a 2-worker shard group: the cross-shard
    handoff ring must be invisible to the io-impl and pump A/Bs. Both
    shards share one loop engine, so exactly one RouteState owns the
    pump — the other's frames take the residual path, which the
    equivalence assertion covers for free."""
    msgs = _scenario_messages()
    baseline = None
    for io_impl, route_impl, pump in _equivalence_configs():
        got, summaries = await _run_two_shards(io_impl, route_impl, msgs,
                                               pump=pump)
        if baseline is None:
            baseline = got
            assert all(len(s) > 0 for s in got.values()), got
        assert got == baseline, (
            f"sharded delivery diverged under "
            f"{io_impl}/{route_impl}/pump={pump}")
        if pump == "auto":
            assert sum(s["pump_frames"] for s in summaries) > 0, (
                f"no shard pumped natively: {summaries}")
    if not _URING_OK:
        pytest.skip("asyncio-only equivalence (io_uring unavailable)")


# ---------------------------------------------------------------------------
# tier 4: fault injection on the raw stream layer
# ---------------------------------------------------------------------------

async def _stream_pair(bufsize=None, raw_peer=False):
    """A connected UringStream pair over a socketpair (deterministic
    loopback, no listener). ``bufsize`` shrinks the kernel socket
    buffers so the TX queue watermark is reachable with modest writes.
    ``raw_peer`` leaves side B a plain socket — a genuinely STALLED
    peer (a peer UringStream's multishot recv would keep absorbing a
    CQE burst into its RX deque before the pause-cancel lands)."""
    eng = umod.UringEngine.current()
    a, b = socket.socketpair()
    for s in (a, b):
        s.setblocking(False)
        if bufsize:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, bufsize)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, bufsize)
    sb = b if raw_peer else umod.UringStream(b, eng)
    return umod.UringStream(a, eng), sb, eng


async def _sock_read_exactly(sock, n):
    loop = asyncio.get_running_loop()
    parts = []
    got = 0
    while got < n:
        chunk = await loop.sock_recv(sock, min(256 * 1024, n - got))
        if not chunk:
            raise AssertionError(f"EOF after {got}/{n} bytes")
        parts.append(chunk)
        got += len(chunk)
    return b"".join(parts)


@requires_uring
async def test_stream_roundtrip_all_tx_paths():
    """Coalesced, non-coalesced, vectored, and chunk-boundary writes all
    arrive byte-exact and in order."""
    sa, sb, _eng = await _stream_pair()
    try:
        chunks = [b"a" * 5, b"b" * 700, b"c" * 70_000,
                  bytearray(b"d" * 300), memoryview(b"e" * 9000),
                  b"f" * 200_000]
        total = b"".join(bytes(c) for c in chunks)
        for c in chunks[:3]:
            await sa.write(c)
        await sa.writev(chunks[3:])
        got = await sb.read_exactly(len(total))
        assert got == total
    finally:
        await sa.close()
        await sb.close()


@requires_uring
async def test_peer_stall_parks_writer_at_watermark_then_resumes():
    """A stalled peer must park write() once the TX queue crosses
    _TX_HIGH (backpressure, not unbounded buffering), and a draining
    peer must release it — with every byte intact."""
    sa, peer, _eng = await _stream_pair(bufsize=16 * 1024, raw_peer=True)
    try:
        payload = os.urandom(2 * 1024 * 1024)
        writer = asyncio.ensure_future(sa.write(payload))
        await asyncio.sleep(0.2)
        assert not writer.done(), "writer should park against the stall"
        assert sa._tx_bytes > umod._TX_HIGH
        got = await _sock_read_exactly(peer, len(payload))
        await asyncio.wait_for(writer, 10)
        assert got == payload
    finally:
        await sa.close()
        peer.close()


@requires_uring
async def test_peer_reset_mid_transfer_fails_writer():
    """Aborting the peer while a chain is in flight surfaces a
    connection error on the parked writer instead of hanging."""
    sa, peer, _eng = await _stream_pair(bufsize=16 * 1024, raw_peer=True)
    try:
        writer = asyncio.ensure_future(sa.write(os.urandom(4 * 1024 * 1024)))
        await asyncio.sleep(0.1)
        assert not writer.done()
        peer.close()  # unread data pending -> in-flight sends fail
        with pytest.raises(OSError):
            await asyncio.wait_for(writer, 10)
        with pytest.raises(OSError):
            await sa.write(b"after-reset")
    finally:
        sa.abort()
        sa._sock.close()


@requires_uring
async def test_short_send_residue_repumped():
    """A short-but-successful LONE send completion re-pumps the residue
    (the WAITALL backstop) — simulated by acking fewer bytes than the
    queued entry, then letting the real kernel send the remainder."""
    sa, sb, _eng = await _stream_pair()
    try:
        sa._tx_flight = 1           # pretend a 1-entry chain is in flight
        sa._queue_tx(b"A" * 100, None)
        sa._on_send_cqe(60)         # kernel "sent" 60 of 100
        got = await sb.read_exactly(40)
        assert got == b"A" * 40     # exactly the residue, nothing else
    finally:
        await sa.close()
        await sb.close()


@requires_uring
async def test_short_send_mid_chain_poisons_stream():
    """A short completion with more of the chain still in flight means
    the wire now holds a torn frame — the stream must poison (EIO), not
    resume framing at a garbage offset."""
    sa, sb, _eng = await _stream_pair()
    try:
        sa._tx_flight = 2           # two linked entries "in the kernel"
        sa._queue_tx(b"B" * 100, None)
        sa._queue_tx(b"C" * 200_000, None)
        sa._on_send_cqe(60)         # first link short, second still live
        with pytest.raises(OSError) as ei:
            await sa.write(b"after-poison")
        assert ei.value.errno == errno.EIO
    finally:
        sa.abort()
        await sb.close()


@requires_uring
async def test_engine_teardown_with_inflight_sqes():
    """Engine shutdown with queued + in-flight sends: pending ops are
    failed (EBADF), both stream directions error cleanly, and the
    pending table holds no leaked entries."""
    sa, peer, eng = await _stream_pair(bufsize=16 * 1024, raw_peer=True)
    rx_a, rx_b, _ = await _stream_pair()  # an idle armed-recv pair
    writer = asyncio.ensure_future(sa.write(os.urandom(2 * 1024 * 1024)))
    await asyncio.sleep(0.1)
    assert not writer.done()
    umod.UringEngine.shutdown(asyncio.get_running_loop())
    with pytest.raises(OSError):
        await asyncio.wait_for(writer, 10)
    assert eng.closed
    assert not eng._pending, "teardown leaked pending ops"
    with pytest.raises(OSError):
        await rx_b.read_some(1)  # armed recv died with the engine
    with pytest.raises(OSError):
        await sa.write(b"x")
    for s in (sa._sock, rx_a._sock, rx_b._sock):
        s.close()
    peer.close()


@requires_zc
async def test_zc_lease_released_exactly_once_after_notif():
    """MSG_ZEROCOPY defers the owner-lease drop to the kernel's NOTIF
    completion: the lease survives the send CQE, releases exactly once,
    and the pending table ends with zero anchored sends."""
    os.environ["PUSHCDN_URING_ZC_MIN"] = "1024"  # before engine creation
    umod.set_io_impl("uring")
    from pushcdn_tpu.proto.transport.tcp import Tcp  # ZC needs real TCP

    class FakeLease:
        released = 0

        def __del__(self):
            FakeLease.released += 1

    listener = await Tcp.bind("127.0.0.1:0")
    conn = None
    server = None
    try:
        accept_t = asyncio.create_task(listener.accept())
        conn = await Tcp.connect(f"127.0.0.1:{listener.bound_port}")
        server = await (await accept_t).finalize()
        eng = umod.UringEngine.current()
        assert eng.zc_ok, "ZC not armed despite supported kernel"

        import struct
        payload = b"Q" * 50_000
        pre = struct.pack(">I", len(payload)) + payload
        lease = FakeLease()
        await conn.send_encoded(pre, owner=lease, flush=True)
        del lease
        raw = await asyncio.wait_for(server.recv_raw(), 10)
        got = bytes(raw.data) if hasattr(raw, "data") else bytes(raw)
        if hasattr(raw, "release"):
            raw.release()
        assert got == payload

        # NOTIF may trail the send CQE — drain until the kernel reports
        # it is done with the pages
        for _ in range(200):
            if eng.zc_sends > 0 and eng.zc_notifs >= eng.zc_sends:
                break
            await asyncio.sleep(0.01)
        assert eng.zc_sends > 0, "ZC path not exercised"
        assert eng.zc_notifs == eng.zc_sends
        gc.collect()
        assert FakeLease.released == 1, (
            f"lease released {FakeLease.released} times")
        assert not any(isinstance(e, umod._Send)
                       for e in eng._pending.values()), (
            "send entries leaked in the pending table")
    finally:
        if conn is not None:
            conn.close()
        if server is not None:
            server.close()
        await listener.close()


@requires_uring
async def test_pool_permit_balance_over_uring_links():
    """A bounded byte pool drains back to full capacity after traffic
    over uring links in both directions — no permit leaks from the
    provided-buffer recv path or the owner-anchored send path."""
    umod.set_io_impl("uring")
    from pushcdn_tpu.proto.transport.tcp import Tcp
    cap = 1 << 20
    limiter = Limiter(global_pool_bytes=cap, per_connection_queue=64)
    listener = await Tcp.bind("127.0.0.1:0")
    conn = None
    server = None
    try:
        accept_t = asyncio.create_task(listener.accept())
        conn = await Tcp.connect(f"127.0.0.1:{listener.bound_port}",
                                 limiter=limiter)
        server = await (await accept_t).finalize(limiter)
        for size in (100, 9_000, 70_000, 200_000):
            await conn.send_raw(b"x" * size, flush=True)
            raw = await asyncio.wait_for(server.recv_raw(), 10)
            assert len(raw.data) == size
            raw.release()
            await server.send_raw(b"y" * size, flush=True)
            raw = await asyncio.wait_for(conn.recv_raw(), 10)
            assert len(raw.data) == size
            raw.release()
    finally:
        if conn is not None:
            conn.close()
        if server is not None:
            server.close()
        await listener.close()
    await asyncio.sleep(0.05)  # let close-path releases land
    _assert_pool_balanced(limiter, "uring link pool")


@requires_uring
async def test_listener_survives_reset_client():
    """The multishot accept keeps serving after a client RSTs right at
    the handshake: the dead connection errors in isolation and the next
    connect still lands and carries traffic."""
    import struct
    umod.set_io_impl("uring")
    listener = umod.uring_bind("127.0.0.1", 0)
    opened = []
    try:
        port = listener.bound_port
        loop = asyncio.get_running_loop()
        # a connect that goes away with an RST immediately
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                     struct.pack("ii", 1, 0))
        s.setblocking(False)
        await loop.sock_connect(s, ("127.0.0.1", port))
        s.close()
        await asyncio.sleep(0.05)
        # a real connect must still be accepted and usable
        conn_t = asyncio.create_task(
            umod.uring_connect("127.0.0.1", port, NO_LIMIT, "t"))
        client = await asyncio.wait_for(conn_t, 10)
        opened.append(client)
        await client.send_raw(b"alive", flush=True)
        # the dead connect may occupy the first accept slot; the live
        # one must show up within the next few
        for _ in range(3):
            unf = await asyncio.wait_for(listener.accept(), 10)
            server = await unf.finalize()
            opened.append(server)
            try:
                raw = await asyncio.wait_for(server.recv_raw(), 1)
            except Exception:
                continue  # the RST'd connection — isolated, not fatal
            assert bytes(raw.data) == b"alive"
            raw.release()
            break
        else:
            raise AssertionError("live connect never accepted")
    finally:
        for c in opened:
            c.close()
        await listener.close()


# ---------------------------------------------------------------------------
# tier 5: fused data-plane pump faults (ISSUE 17)
# ---------------------------------------------------------------------------
#
# Binding-level tests drive NativePump directly over a socketpair with
# injected CQEs (deterministic chain accounting, no kernel timing);
# product-level tests run a real broker over loopback TCP with the pump
# engaged and break things mid-fan-out.

def _pump_rig(topics=((1,), (2,))):
    """RoutePlanner + raw Ring + NativePump with one engaged peer per
    entry in ``topics`` (user slots 0..n-1), plus the peer sockets."""
    import struct

    import numpy as np

    planner = routeplan.RoutePlanner.create()
    assert planner is not None
    user_cap, broker_cap = max(4, len(topics)), 2
    peer_masks = np.zeros((user_cap + broker_cap, routeplan.MASK_WORDS),
                          np.uint64)
    valid_topics = sorted({t for ts in topics for t in ts})
    for slot, ts in enumerate(topics):
        peer_masks[slot] = routeplan.topic_mask(list(ts))
    assert planner.build(user_cap, broker_cap,
                         routeplan.topic_mask(valid_topics), peer_masks,
                         [], np.zeros(0, np.int32))
    ring = nuring.Ring(256)
    pump = npump.NativePump.create(ring, max_peers=8, chunk_slots=4)
    assert pump is not None
    socks = []
    slot_map = np.full(user_cap + broker_cap, -1, np.int32)
    for slot in range(len(topics)):
        a, b = socket.socketpair()
        a.setblocking(False)
        b.setblocking(False)
        pid = pump.add_peer(a.fileno())
        assert pid >= 0
        slot_map[slot] = pid
        socks.append((a, b, pid))
    pump.set_slots(slot_map)

    def chunk(frame_topics):
        from pushcdn_tpu.proto.message import Broadcast, serialize
        frames = [serialize(Broadcast((t,), b"payload-%d" % i))
                  for i, t in enumerate(frame_topics)]
        buf = b"".join(struct.pack(">I", len(f)) + f for f in frames)
        offs, lens, o = [], [], 0
        for f in frames:
            offs.append(o + 4)
            lens.append(len(f))
            o += 4 + len(f)
        import numpy as _np
        return buf, _np.asarray(offs, _np.int64), _np.asarray(lens, _np.int64)

    return planner, ring, pump, socks, chunk


def _rig_teardown(ring, pump, socks):
    pump.destroy()
    ring.close()
    for pair in socks:
        for s in pair[:2]:
            try:
                s.close()
            except OSError:
                pass


@requires_pump
def test_pump_short_lone_tail_repumps_residue():
    """A short-but-successful CQE on the LAST link of a chain re-pumps
    the residue from the advanced offset (the MSG_WAITALL backstop) —
    the run stays queued, a fresh SQE is prepped at the next drain, and
    the chunk slot releases only when every byte is accounted."""
    planner, ring, pump, socks, chunk = _pump_rig(topics=((1,),))
    try:
        buf, offs, lens = chunk([1, 1, 1])
        consumed, stop, rp, rf, meta = pump.route_chunk(
            planner._handle, buf, offs, lens, 0, 1)
        assert consumed == 3 and len(rp) == 0
        assert meta[npump.META_SQES] == 1  # one contiguous run
        run_len = int(offs[2] + lens[2] - (offs[0] - 4))
        # never submit: the injected CQEs are the only completions
        _c, ev, _n = pump.inject_cqe(socks[0][2], run_len - 7), [], 0
        st = pump.stats()
        assert st["short_repump"] == 1
        assert not pump.take_released(), "slot freed before bytes done"
        cqes, events, n_prepped = pump.drain()
        assert n_prepped == 1, "residue chain not re-prepped"
        pump.inject_cqe(socks[0][2], 7)
        released = pump.take_released()
        assert released == [int(meta[npump.META_CHUNK_SLOT])]
        assert pump.stats()["errors"] == 0
        assert pump.peer_stats(socks[0][2])["err"] == 0
    finally:
        _rig_teardown(ring, pump, socks)


@requires_pump
def test_pump_short_mid_chain_poisons_peer():
    """A short completion with more links of the chain still in flight
    means the wire holds a torn frame: the peer must poison (EV_PEER_ERROR
    with EIO), queued runs drop, the chunk slot still releases, and later
    chunks escalate that peer's frames as peer_error residuals."""
    import errno as _errno
    planner, ring, pump, socks, chunk = _pump_rig(topics=((1,), (2,)))
    try:
        # frames: topic1, topic2, topic1 -> peer0 gets TWO runs (a
        # 2-link chain), peer1 one run
        buf, offs, lens = chunk([1, 2, 1])
        consumed, stop, rp, rf, meta = pump.route_chunk(
            planner._handle, buf, offs, lens, 0, 1)
        assert consumed == 3 and len(rp) == 0
        p0 = socks[0][2]
        assert pump.peer_stats(p0)["inflight"] == 2
        first_run = int(lens[0]) + 4
        events = pump.inject_cqe(p0, first_run - 3)  # short, chain live
        assert [e[0] for e in events] == [npump.EV_PEER_ERROR]
        assert events[0][1] == p0
        assert abs(events[0][2]) == _errno.EIO
        assert pump.stats()["errors"] == 1
        # the still-in-flight second link drains as a trailing CQE
        events = pump.inject_cqe(p0, -_errno.ECANCELED)
        assert npump.EV_PEER_QUIESCED in [e[0] for e in events]
        # peer1's clean run completes; only then is the chunk slot free
        p1 = socks[1][2]
        pump.inject_cqe(p1, int(lens[1]) + 4)
        assert pump.take_released() == [int(meta[npump.META_CHUNK_SLOT])]
        # frames for the poisoned peer now escalate as residuals
        consumed, stop, rp, rf, meta = pump.route_chunk(
            planner._handle, buf, offs, lens, 0, 1)
        assert meta[npump.META_RESID_ERROR] == 2
        assert sorted(set(rp.tolist())) == [0]
        assert pump.peer_stats(p0)["err"] != 0
    finally:
        _rig_teardown(ring, pump, socks)


async def _pump_broker(receivers, topics=(0,)):
    """A real broker over loopback TCP with the pump engaged: returns
    (run, sender, pump_state) after a warmup wave has landed so every
    receiver is natively engaged."""
    from pushcdn_tpu.proto.message import serialize

    umod.set_io_impl("uring")
    cutthrough.ROUTE_IMPL = "native"
    pump_mod.set_pump_impl("auto")
    run = await TestDefinition(
        connected_users=[[]] + [list(topics)] * receivers,
        tcp_users=True).run()
    sender = run.user(0).remote
    warm = serialize(Broadcast(list(topics), b"warm"))
    for _ in range(3):
        await sender.send_raw_many([warm] * 8)
        await asyncio.sleep(0.15)
    state = run.broker._route_state
    assert state is not None
    ps = state._pump_state
    assert ps is not None and not ps.closed, "pump never engaged"
    assert len(ps.bindings) >= receivers, ps.summary()
    return run, sender, ps


async def _drain_payloads(user, quiet=0.4):
    """Every frame the user receives until the link goes quiet, decoded
    payload-first so tests can assert ordering by content."""
    from pushcdn_tpu.proto.transport.base import FrameChunk
    out = []
    while True:
        try:
            raw = await asyncio.wait_for(user.remote.recv_raw(), quiet)
        except (asyncio.TimeoutError, Exception):
            return out
        if type(raw) is FrameChunk:
            for i in range(raw.remaining):
                o, ln = raw.offs[i], raw.lens[i]
                out.append(bytes(memoryview(raw.buf)[o:o + ln]))
        else:
            out.append(bytes(raw.data) if hasattr(raw, "data")
                       else bytes(raw))
        if hasattr(raw, "release"):
            raw.release()


@requires_pump
async def test_pump_peer_reset_during_pumped_fanout():
    """One receiver RSTs mid-fan-out while its pumped chain is in
    flight: the broker must survive, disengage (never force-disconnect —
    the Python path owns that decision), keep delivering to the healthy
    receivers, and leave the pools balanced."""
    import struct
    from pushcdn_tpu.proto.message import serialize

    run, sender, ps = await _pump_broker(receivers=3)
    try:
        victim = run.connected_users[1]
        vsock = victim.remote._stream._sock
        # stop reading + RST on close: in-flight pumped sends error
        vsock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                         struct.pack("ii", 1, 0))
        frame = serialize(Broadcast([0], os.urandom(9_000)))
        await sender.send_raw_many([frame] * 32)
        vsock.close()
        await sender.send_raw_many([frame] * 32)
        await asyncio.sleep(0.3)
        # the healthy receivers got every post-warmup frame
        for u in (run.connected_users[2], run.connected_users[3]):
            got = [p for p in await _drain_payloads(u) if len(p) > 5_000]
            assert len(got) == 64, f"healthy receiver lost frames: {len(got)}"
        assert not ps.closed, "whole pump died with one peer"
        summary = ps.summary()
        assert summary["pump_frames"] > 0
    finally:
        await run.shutdown()
    _assert_pool_balanced(run.broker.limiter, "pump peer-reset")


@requires_pump
async def test_pump_fence_race_with_concurrent_python_enqueue():
    """A frame entering a pumped peer's Python writer queue fences the
    peer synchronously: frames planned while the queue is non-empty
    divert to the residual path (counted, ordered behind the queue), and
    the fence lifts once both sides drain — after which the pump engages
    again."""
    from pushcdn_tpu.proto.message import serialize

    run, sender, ps = await _pump_broker(receivers=2)
    try:
        key = run.connected_users[1].public_key
        conn = run.broker.connections.get_user_connection(key)
        assert conn is not None
        marker = serialize(Broadcast([0], b"MARKER" * 10))
        wave = [serialize(Broadcast([0], b"wave-%03d" % i))
                for i in range(24)]
        fenced_before = ps.escalations.get("fenced", 0)
        # hold the writer mutex so the queued marker CANNOT drain: the
        # fence provably overlaps the wave's plan call
        async with conn._write_mutex:
            await conn.send_raw(marker)     # enqueue -> synchronous fence
            assert any(b.fenced for b in ps.bindings.values())
            await sender.send_raw_many(wave)
            await asyncio.sleep(0.25)        # wave planned while fenced
        assert ps.escalations.get("fenced", 0) > fenced_before, (
            "wave never hit the fence escalation path")
        await asyncio.sleep(0.2)
        got = await _drain_payloads(run.connected_users[1])
        wave_tags = [p[p.find(b"wave-"):p.find(b"wave-") + 8]
                     for p in got if b"wave-" in p]
        assert wave_tags == sorted(wave_tags), "fenced frames reordered"
        assert len(wave_tags) == 24
        assert any(b"MARKER" in p for p in got)
        # fence lifted and the pump re-engages for the next wave
        assert not any(b.fenced for b in ps.bindings.values())
        pumped_before = ps.pump_frames
        await sender.send_raw_many(wave)
        await asyncio.sleep(0.3)
        assert ps.pump_frames > pumped_before, "peer never unfenced"
    finally:
        await run.shutdown()
    _assert_pool_balanced(run.broker.limiter, "pump fence race")


@requires_pump
async def test_pump_lease_balance_after_teardown_in_flight():
    """Shutdown with pumped runs still referencing chunk slots: the
    parked leases must release on teardown — zero pooled bytes leaked."""
    from pushcdn_tpu.proto.message import serialize

    run, sender, ps = await _pump_broker(receivers=2)
    try:
        frame = serialize(Broadcast([0], os.urandom(4_000)))
        await sender.send_raw_many([frame] * 48)
        # no drain, no sleep: chunk slots are still referenced when the
        # shutdown path starts tearing the engine down
    finally:
        await run.shutdown()
        umod.UringEngine.shutdown(asyncio.get_running_loop())
    assert ps.closed and not ps.leases, "parked leases survived teardown"
    _assert_pool_balanced(run.broker.limiter, "pump teardown in flight")


def test_pump_demotion_warning_names_failed_layer(monkeypatch, caplog):
    """``resolve_pump`` must name the dead layer in ONE warning — an
    operator reading the log learns WHICH leg of the composition failed,
    and repeat probes stay silent (count, don't spam)."""
    monkeypatch.setattr(pump_mod, "_warned_demote", False)
    pump_mod.set_pump_impl("auto")

    # io impl resolved to asyncio (kernel fine, selection says no)
    umod.set_io_impl("asyncio")
    with caplog.at_level(logging.WARNING, logger=pump_mod.logger.name):
        ok, why = pump_mod.resolve_pump()
        ok2, _ = pump_mod.resolve_pump()  # second probe: silent
    assert not ok and not ok2
    assert "asyncio" in why or "io_uring unavailable" in why
    warnings = [r for r in caplog.records if "pump demoted" in r.message]
    assert len(warnings) == 1, "demotion must warn exactly once"
    assert why in warnings[0].message

    # dead route-plan kernel: the warning names THAT layer
    caplog.clear()
    pump_mod.set_pump_impl("auto")  # resets the warn-once latch
    monkeypatch.setattr(pump_mod.routeplan, "available", lambda: False)
    with caplog.at_level(logging.WARNING, logger=pump_mod.logger.name):
        ok, why = pump_mod.resolve_pump()
    assert not ok and "route-plan kernel unavailable" in why

    # explicit off is a decision, not a demotion: no warning at all
    caplog.clear()
    pump_mod.set_pump_impl("off")
    with caplog.at_level(logging.WARNING, logger=pump_mod.logger.name):
        ok, why = pump_mod.resolve_pump()
    assert not ok and "disabled" in why
    assert not [r for r in caplog.records if "demoted" in r.message]


# ---------------------------------------------------------------------------
# tier 6: native-path telemetry (ISSUE 19)
# ---------------------------------------------------------------------------
#
# The shm telemetry block is written from C on the pump's hot path and
# read by /metrics through a seqlock snapshot. These tests pin the three
# contracts the exposition rests on: log2 bucketing (exact boundaries),
# torn-read safety under a concurrent writer, and counter monotonicity
# across pump disengage and engine teardown (the carry fold).


@requires_uring
def test_telemetry_log2_bucket_boundaries():
    """Bucket k holds durations in [2^(k-1), 2^k) ns — i.e. the bucket
    index of ``ns`` is ``ns.bit_length()`` capped at 63, with 0 in
    bucket 0. Exact count/sum bookkeeping, weighted observes, and
    out-of-range histogram indices rejected."""
    from collections import Counter as _Counter

    ring = nuring.Ring(64)
    try:
        assert ring.enable_telemetry()
        assert ring.telemetry_enabled
        assert ring.enable_telemetry()  # idempotent
        cases = [0, 1, 2, 3, 4, 7, 8, 1023, 1024, 1025,
                 2**32 - 1, 2**32, 2**62, 2**63 + 11]
        for ns in cases:
            assert ring.telemetry_test_observe(0, 0, ns) == 0
        # weighted observe: one duration covering n frames adds n to
        # count, n*ns to sum, n to the single bucket
        assert ring.telemetry_test_observe(0, 0, 1024, n=5) == 0
        # invalid indices/kinds must be rejected, not clamped
        assert ring.telemetry_test_observe(0, nuring.TM_STAGES, 1) < 0
        assert ring.telemetry_test_observe(1, nuring.TM_CHAIN, 1) < 0
        assert ring.telemetry_test_observe(2, nuring.TM_CLASSES, 1) < 0
        assert ring.telemetry_test_observe(3, 0, 1) < 0

        snap = nuring.parse_telemetry(ring.telemetry_snapshot())
        h = snap["stage"]["plan"]
        assert h["count"] == len(cases) + 5
        assert h["sum_ns"] == sum(cases) + 5 * 1024
        expect = _Counter(min(ns.bit_length(), 63) for ns in cases)
        expect[(1024).bit_length()] += 5
        for k in range(nuring.TM_BUCKETS):
            assert h["buckets"][k] == expect.get(k, 0), f"bucket {k}"
        # nothing leaked into the neighbouring histograms
        assert snap["stage"]["submit"]["count"] == 0
        assert snap["chain"]["enter"]["count"] == 0
    finally:
        ring.close()


@requires_uring
def test_telemetry_snapshot_consistent_under_concurrent_writer():
    """Seqlock torn-read safety: a writer thread hammers weighted
    observations (seeded n) into one histogram while the reader
    snapshots. Every snapshot must be internally consistent — with a
    fixed duration, sum_ns == ns * count and all samples in one bucket;
    a torn copy would break one of those identities — and counts must
    be monotone across snapshots."""
    import random
    import threading
    import time as _time

    ring = nuring.Ring(64)
    try:
        assert ring.enable_telemetry()
        rng = random.Random(1119)
        ns = 1 << 20  # bucket 21
        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                ring.telemetry_test_observe(2, 3, ns, n=rng.randrange(1, 8))

        writer = threading.Thread(target=hammer)
        writer.start()
        try:
            prev, good = 0, 0
            deadline = _time.monotonic() + 10.0
            while good < 150 and _time.monotonic() < deadline:
                words = ring.telemetry_snapshot()
                if words is None:
                    continue  # writer never went quiet in the spin window
                h = nuring.parse_telemetry(words)["class_delay"]["bulk"]
                assert h["sum_ns"] == ns * h["count"], "torn sum"
                assert sum(h["buckets"]) == h["count"], "torn buckets"
                assert h["buckets"][21] == h["count"], "sample strayed"
                assert h["count"] >= prev, "count went backwards"
                prev = h["count"]
                good += 1
        finally:
            stop.set()
            writer.join()
        assert good >= 150, f"reader starved: {good} consistent snapshots"
        assert prev > 0, "writer never landed an observation"
    finally:
        ring.close()


@requires_pump
def test_pump_stage_telemetry_and_class_accounting_injected():
    """Binding-level stage/class accounting over injected CQEs (no
    kernel timing): one pumped chunk must stamp all four stages, fold
    the frames into the planner's class (BULK via set_classes), account
    the peer row by fd, and SURVIVE pump disengage — the telemetry
    block belongs to the ring, not the pump."""
    from pushcdn_tpu.proto import flowclass

    planner, ring, pump, socks, chunk = _pump_rig(topics=((1,),))
    try:
        assert ring.enable_telemetry()
        # topic 1 -> bulk; everything else keeps the live default
        assert planner.set_classes(
            flowclass.compile_table(overrides={1: flowclass.BULK}))
        buf, offs, lens = chunk([1, 1, 1])
        consumed, stop_r, rp, rf, meta = pump.route_chunk(
            planner._handle, buf, offs, lens, 0, 1)
        assert consumed == 3 and len(rp) == 0
        assert list(pump.frame_classes[:3]) == [flowclass.BULK] * 3
        run_len = int(offs[2] + lens[2] - (offs[0] - 4))
        pump.inject_cqe(socks[0][2], run_len)
        assert pump.take_released(), "run did not complete"

        snap = nuring.parse_telemetry(ring.telemetry_snapshot())
        for stage in nuring.STAGE_NAMES:
            assert snap["stage"][stage]["count"] >= 1, stage
        assert snap["class_delay"]["bulk"]["count"] == 3
        assert snap["class_frames"]["bulk"] == 3
        assert snap["class_bytes"]["bulk"] == run_len
        assert snap["class_frames"]["live"] == 0
        rows = {p["fd"]: p for p in snap["peers"]}
        fd = socks[0][0].fileno()
        assert rows[fd]["frames"] == 3 and rows[fd]["bytes"] == run_len

        # disengage: destroying the pump must not reset the counters
        pump.destroy()
        after = nuring.parse_telemetry(ring.telemetry_snapshot())
        assert after["class_frames"]["bulk"] == 3
        for stage in nuring.STAGE_NAMES:
            assert after["stage"][stage]["count"] \
                == snap["stage"][stage]["count"], stage
    finally:
        if not pump.closed:
            pump.destroy()
        ring.close()
        for pair in socks:
            for s in pair[:2]:
                try:
                    s.close()
                except OSError:
                    pass


@requires_uring
async def test_telemetry_totals_monotone_across_engine_teardown():
    """Engine teardown folds the ring's final snapshot into the
    module-level carry BEFORE the ring closes, so ``telemetry_totals``
    (and with it every rendered series) stays monotone across engine
    recreate — the lease-balance discipline, applied to counters."""
    from pushcdn_tpu.proto import metrics as metrics_mod

    saved_carry = umod._TELEM_CARRY
    umod._TELEM_CARRY = None
    try:
        eng = umod.UringEngine.current()
        assert eng.ring.enable_telemetry()
        assert eng.ring.telemetry_test_observe(0, 3, 1 << 21, n=5) == 0
        t1 = umod.telemetry_totals()
        assert t1 is not None and t1["stage"]["total"]["count"] == 5

        umod.UringEngine.shutdown()  # close() folds into the carry
        t2 = umod.telemetry_totals()
        assert t2["stage"]["total"]["count"] == 5, "teardown lost samples"

        # a fresh engine keeps the series monotone on top of the carry
        eng2 = umod.UringEngine.current()
        assert eng2.ring.enable_telemetry()
        assert eng2.ring.telemetry_test_observe(0, 3, 1 << 21, n=2) == 0
        t3 = umod.telemetry_totals()
        assert t3["stage"]["total"]["count"] == 7
        assert t3["stage"]["total"]["sum_ns"] == 7 * (1 << 21)

        # and the /metrics exposition publishes the aggregated family
        metrics_mod.update_native_telemetry(t3)
        body = metrics_mod.PUMP_STAGE_SECONDS.render()
        assert 'cdn_pump_stage_seconds_count{stage="total"} 7' in body
    finally:
        umod._TELEM_CARRY = saved_carry
