"""Consensus-shaped workload driver (ISSUE 11): view lifecycle, zipf
geography, view-tagged span capture, and the chaos composition
invariants (shed-mid-view must not stall; shard-worker kill must not
reorder a surviving peer)."""

import asyncio
import json
import os

from pushcdn_tpu.proto import trace as trace_mod
from pushcdn_tpu.testing.cluster import Cluster
from pushcdn_tpu.testing.consensus import (
    ConsensusConfig,
    ConsensusDriver,
    encode_proposal,
    encode_vote,
    percentile,
    run_consensus,
)


def test_config_zipf_latency_tail():
    cfg = ConsensusConfig(num_nodes=8, base_latency_s=0.01,
                          tail_latency_s=0.08, zipf_alpha=1.0)
    lats = [cfg.node_latency_s(i) for i in range(8)]
    # node 0 carries the full tail; the tail decays monotonically to base
    assert lats[0] == 0.09
    assert all(a >= b for a, b in zip(lats, lats[1:]))
    assert abs(lats[-1] - 0.02) < 1e-9
    # unshaped config keeps the plain Memory protocol (no pump tasks)
    from pushcdn_tpu.proto.transport.memory import Memory
    assert ConsensusConfig().node_protocol(0) is Memory
    assert cfg.node_protocol(0) is not Memory


def test_quorum_default_is_two_thirds_plus_one():
    assert ConsensusConfig(num_nodes=4).effective_quorum() == 3
    assert ConsensusConfig(num_nodes=10).effective_quorum() == 7
    assert ConsensusConfig(num_nodes=3, quorum=5).effective_quorum() == 3


def test_payload_codecs_are_sized_and_parseable():
    p = encode_proposal(7, 256)
    assert len(p) == 256 and p[:1] == b"P"
    v = encode_vote(7, 3, 64)
    assert len(v) == 64 and v[:1] == b"V"
    assert percentile([], 0.5) is None
    assert percentile([1.0, 2.0, 3.0, 4.0], 0.5) == 2.0


async def test_consensus_views_complete_clean(tmp_path):
    log = str(tmp_path / "spans.jsonl")
    prev = trace_mod.set_log_path(log)
    cluster = await Cluster(num_brokers=1).start()
    try:
        run = await run_consensus(cluster, ConsensusConfig(
            num_nodes=4, num_views=3, view_timeout_s=10.0, seed=1))
        assert run.completed == 3 and run.timeouts == 0
        assert run.proposals_sent == 3
        # quorum is 3 of 4: at least quorum votes counted per view
        assert all(v.votes >= 3 for v in run.views)
        pct = run.completion_percentiles()
        assert pct["p50"] is not None and pct["p50"] > 0
    finally:
        await cluster.stop()
        trace_mod.set_log_path(prev)
    # the span log carries the view tag on every consensus hop
    views = set()
    for line in open(log):
        rec = json.loads(line)
        if "view" in rec:
            views.add(rec["view"])
    assert views == {0, 1, 2}


async def test_consensus_zipf_tail_slows_but_does_not_stall(tmp_path):
    prev = trace_mod.set_log_path(None)
    cluster = await Cluster(num_brokers=1).start()
    try:
        run = await run_consensus(cluster, ConsensusConfig(
            num_nodes=4, num_views=2, view_timeout_s=10.0,
            base_latency_s=0.002, tail_latency_s=0.03, loss=0.2,
            rto_s=0.01, seed=9))
        assert run.completed == 2
        # quorum formation waits on real shaped links: completion can't
        # be faster than the base one-way latency
        assert min(v.completion_s for v in run.views) >= 0.002
    finally:
        await cluster.stop()
        trace_mod.set_log_path(prev)


async def test_leader_rotates_per_view():
    cluster = await Cluster(num_brokers=1).start()
    try:
        driver = ConsensusDriver(cluster, ConsensusConfig(num_nodes=3,
                                                          num_views=4))
        assert [driver.leader_of(v) for v in range(4)] == [0, 1, 2, 0]
    finally:
        await cluster.stop()
