"""Ragged paged delivery (ISSUE 8): page-packing property tests and the
seeded device-vs-host equivalence suite.

The equivalence chain asserted here: for seeded broadcast/direct/control/
garbage mixes (uniform AND zipf-skewed topic popularity, empty-fan-out
edges included) the ragged kernel's delivery decisions — jnp twin AND
Pallas kernel in interpreter mode, CPU backend — are identical to the
dense ``delivery_matrix_reference`` and to a scalar host cut-through twin
(the interest-set + direct-ownership routing rule the broker's host path
implements).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from pushcdn_tpu.ops.delivery_kernel import delivery_matrix_reference  # noqa: E402
from pushcdn_tpu.ops.ragged_delivery import (  # noqa: E402
    PAGE,
    RaggedInterest,
    ragged_delivery_pallas,
    ragged_delivery_reference,
    ragged_pairs,
    ragged_pairs_grouped,
    ragged_to_dense,
)
from pushcdn_tpu.proto.message import KIND_BROADCAST, KIND_DIRECT  # noqa: E402


def host_cutthrough_reference(user_masks, local, frame_tmask, kind, dest,
                              valid):
    """The host cut-through's routing rule as scalar Python: per frame,
    broadcast delivery = interest-set membership (mask AND), direct
    delivery = addressed slot iff locally owned. The executable spec the
    device kernels must match (the broker's dict-based path implements
    exactly this per message)."""
    U = len(user_masks)
    N = len(kind)
    deliver = np.zeros((U, N), bool)
    multiword = np.ndim(user_masks) == 2
    for n in range(N):
        if not valid[n]:
            continue
        if kind[n] == KIND_BROADCAST:
            for u in range(U):
                if not local[u]:
                    continue
                if multiword:
                    hit = bool((user_masks[u] & frame_tmask[n]).any())
                else:
                    hit = bool(user_masks[u] & frame_tmask[n])
                if hit:
                    deliver[u, n] = True
        elif kind[n] == KIND_DIRECT:
            d = int(dest[n])
            if 0 <= d < U and local[d]:
                deliver[d, n] = True
    return deliver


def _mix(seed: int, U: int, N: int, T: int, popularity: str,
         topic_words: int = 1):
    """One seeded broadcast/direct/control/garbage mix + matching
    interest, in both host (numpy) and index (RaggedInterest) form."""
    from pushcdn_tpu.parallel.frames import mask_mirror_shape, split_mask

    rng = np.random.default_rng(seed)
    if popularity == "zipf":
        p = 1.0 / np.arange(1, T + 1)
        p /= p.sum()
    else:
        p = np.full(T, 1.0 / T)
    # interest: most users subscribe to a few topics; some users idle
    # (empty masks), some unowned (local=False)
    masks_int = []
    W = topic_words
    masks = np.zeros(mask_mirror_shape(U, W), np.uint32)
    for u in range(U):
        k = int(rng.integers(0, 4))  # 0 topics = empty-fan-out edge
        m = 0
        for t in rng.choice(T, size=k, p=p):
            m |= 1 << int(t)
        masks_int.append(m)
        masks[u] = m if W == 1 else split_mask(m, W)
    local = rng.random(U) < 0.8
    ri = RaggedInterest(T, max_pages=1024)
    for u in range(U):
        ri.set_mask(u, masks_int[u])
    assert not ri.overflowed

    # frames: broadcasts (single + multi topic), directs (incl. repeated
    # and garbage dests), control kinds, garbage kinds, invalid slots
    # with poisoned metadata
    kind = rng.choice([0, KIND_BROADCAST, KIND_BROADCAST, KIND_DIRECT, 6,
                       9, 77], N).astype(np.int32)
    tmask_ints = np.zeros(N, object)
    for n in range(N):
        if kind[n] == KIND_BROADCAST:
            m = 1 << int(rng.choice(T, p=p))
            if rng.random() < 0.3:  # multi-topic (union path)
                m |= 1 << int(rng.choice(T, p=p))
            if rng.random() < 0.1:
                m = 0  # no-topic broadcast: empty fan-out
            tmask_ints[n] = m
        else:
            tmask_ints[n] = 0
    tmask = np.zeros(mask_mirror_shape(N, W), np.uint32)
    for n in range(N):
        tmask[n] = tmask_ints[n] if W == 1 else split_mask(
            int(tmask_ints[n]), W)
    dest = np.where(kind == KIND_DIRECT,
                    rng.integers(-3, U + 5, N), -1).astype(np.int32)
    valid = rng.random(N) < 0.85
    # poison invalid slots' metadata: must never deliver
    inv = np.nonzero(~valid)[0]
    if len(inv):
        row = np.uint32(0xFFFFFFFF)
        tmask[inv[0]] = row
        kind[inv[0]] = KIND_BROADCAST
    kz = np.where(valid, kind, 0).astype(np.int32)
    return ri, masks, local, tmask, kind, kz, dest, valid


@pytest.mark.parametrize("popularity", ["uniform", "zipf"])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_equivalence_ragged_vs_dense_vs_host(seed, popularity):
    """jnp twin == Pallas(interpret) == dense reference == scalar host
    cut-through on seeded mixed traffic."""
    U, N, T = 150, 64, 24
    ri, masks, local, tmask, kind, kz, dest, valid = _mix(
        seed, U, N, T, popularity)
    walk = ri.pack(kz, tmask, dest, valid)
    assert not walk.spilled

    host = host_cutthrough_reference(masks, local, tmask, kind, dest,
                                     valid)
    dense = np.asarray(delivery_matrix_reference(
        jnp.asarray(masks), jnp.asarray(local), jnp.asarray(tmask),
        jnp.asarray(kz), jnp.asarray(dest)))
    np.testing.assert_array_equal(dense, host)

    args = (jnp.asarray(walk.pages), jnp.asarray(walk.walk_page),
            jnp.asarray(walk.walk_frame), jnp.asarray(local),
            jnp.asarray(masks), jnp.asarray(tmask), jnp.asarray(kz),
            jnp.asarray(dest))
    out_ref, cnt_ref = ragged_delivery_reference(*args)
    got = ragged_to_dense(np.asarray(out_ref), walk.walk_frame, U, N)
    np.testing.assert_array_equal(got, host)

    out_pal, cnt_pal = ragged_delivery_pallas(*args, interpret=True)
    np.testing.assert_array_equal(np.asarray(out_pal), np.asarray(out_ref))
    np.testing.assert_array_equal(np.asarray(cnt_pal), np.asarray(cnt_ref))

    # both pair extractors produce the host pair set, dup-free, grouped
    pu, pf = ragged_pairs(np.asarray(out_ref), walk.walk_frame,
                          num_users=U)
    gu, gf = ragged_pairs_grouped(np.asarray(out_ref), walk, num_users=U)
    want = set(zip(*np.nonzero(host)))
    assert set(zip(pu.tolist(), pf.tolist())) == want
    assert set(zip(gu.tolist(), gf.tolist())) == want
    assert len(gu) == len(want)  # dup-free
    for users in (pu, gu):  # per-user contiguity (egress run shape)
        if len(users):
            changes = int((np.diff(users) != 0).sum())
            assert changes + 1 == len(np.unique(users))
    ri.release_transient()


def test_equivalence_multiword_masks():
    """The full 256-topic space (8xu32 masks) through the same chain."""
    U, N, T = 80, 48, 256
    ri, masks, local, tmask, kind, kz, dest, valid = _mix(
        7, U, N, T, "zipf", topic_words=8)
    walk = ri.pack(kz, tmask, dest, valid)
    host = host_cutthrough_reference(masks, local, tmask, kind, dest,
                                     valid)
    args = (jnp.asarray(walk.pages), jnp.asarray(walk.walk_page),
            jnp.asarray(walk.walk_frame), jnp.asarray(local),
            jnp.asarray(masks), jnp.asarray(tmask), jnp.asarray(kz),
            jnp.asarray(dest))
    out_ref, _ = ragged_delivery_reference(*args)
    np.testing.assert_array_equal(
        ragged_to_dense(np.asarray(out_ref), walk.walk_frame, U, N), host)
    out_pal, _ = ragged_delivery_pallas(*args, interpret=True)
    np.testing.assert_array_equal(np.asarray(out_pal), np.asarray(out_ref))
    ri.release_transient()


def test_ragged_routing_step_matches_dense_step():
    """routing_step_ragged_single delivers the dense jitted step's exact
    decisions (the bench.py --delivery-impl ragged contract)."""
    from pushcdn_tpu.parallel.crdt import CrdtState
    from pushcdn_tpu.parallel.frames import FrameRing
    from pushcdn_tpu.parallel.router import (
        IngressBatch,
        RouterState,
        routing_step_ragged_single,
        routing_step_single,
    )

    U, S = 32, 16
    rng = np.random.default_rng(5)
    masks = rng.integers(0, 2**8, U).astype(np.uint32)
    owners = np.where(rng.random(U) < 0.7, 0, 3).astype(np.int32)
    state = RouterState(
        CrdtState(jnp.asarray(owners), jnp.asarray(np.ones(U, np.uint32)),
                  jnp.asarray(owners)), jnp.asarray(masks))
    ri = RaggedInterest(8, max_pages=64)
    for u in range(U):
        ri.set_mask(u, int(masks[u]) & 0xFF)
    ring = FrameRing(slots=S, frame_bytes=64)
    ring.push_broadcast(b"t0", 0b1)
    ring.push_broadcast(b"t27", 0b1000)
    ring.push_direct(b"d", 4)
    ring.push_direct(b"d2", 4)  # repeated dest: the shared-page dup edge
    b = ring.take_batch()
    kz = np.where(b.valid, b.kind, 0).astype(np.int32)
    walk = ri.pack(kz, b.topic_mask, b.dest, b.valid)
    batch = IngressBatch(
        jnp.asarray(b.bytes_), jnp.asarray(b.kind), jnp.asarray(b.length),
        jnp.asarray(b.topic_mask), jnp.asarray(b.dest),
        jnp.asarray(b.valid))
    res = routing_step_ragged_single(
        state, batch, jnp.asarray(walk.pages), jnp.asarray(walk.walk_page),
        jnp.asarray(walk.walk_frame))
    dense = routing_step_single(state, batch)
    np.testing.assert_array_equal(
        ragged_to_dense(np.asarray(res.out_user), walk.walk_frame, U, S),
        np.asarray(dense.deliver))
    ri.release_transient()


# ---------------------------------------------------------------------------
# page-packing property tests
# ---------------------------------------------------------------------------


def test_incremental_index_matches_bruteforce_under_churn():
    """Seeded subscribe/unsubscribe churn: after every mutation the
    per-topic pages hold exactly the brute-force membership."""
    rng = np.random.default_rng(42)
    T, U = 16, 64
    ri = RaggedInterest(T, max_pages=256)
    truth = {u: 0 for u in range(U)}
    for _ in range(600):
        u = int(rng.integers(0, U))
        m = int(rng.integers(0, 1 << T))
        ri.set_mask(u, m)
        truth[u] = m
        if rng.random() < 0.05:  # occasional full check
            for t in range(T):
                want = sorted(u for u, mm in truth.items()
                              if mm & (1 << t))
                got = sorted(ri.topic_receivers(t).tolist())
                assert got == want, (t, got, want)
    for t in range(T):
        want = sorted(u for u, mm in truth.items() if mm & (1 << t))
        assert sorted(ri.topic_receivers(t).tolist()) == want


def test_pool_wraparound_reuses_pages_without_leaks():
    """Repeated pack/release cycles with transient unions + directs: the
    free-page count returns to baseline every tick and recycled pages
    never leak a previous tick's candidates."""
    T = 8
    ri = RaggedInterest(T, max_pages=32)
    for u in range(40):
        ri.set_mask(u, 0b01 if u % 2 else 0b10)
    free0 = ri.free_pages
    kind = np.asarray([KIND_BROADCAST, KIND_DIRECT, KIND_DIRECT],
                      np.int32)
    tmask = np.asarray([0b11, 0, 0], np.uint32)  # union of both topics
    dest = np.asarray([-1, 3, 5], np.int32)
    valid = np.ones(3, bool)
    for tick in range(50):
        walk = ri.pack(kind, tmask, dest, valid)
        assert not walk.spilled
        # union page content is exactly the dedup'd membership
        out, _ = ragged_delivery_reference(
            jnp.asarray(walk.pages), jnp.asarray(walk.walk_page),
            jnp.asarray(walk.walk_frame),
            jnp.asarray(np.ones(40, bool)),
            jnp.asarray(np.asarray(
                [0b01 if u % 2 else 0b10 for u in range(40)], np.uint32)),
            jnp.asarray(tmask), jnp.asarray(kind), jnp.asarray(dest))
        d = ragged_to_dense(np.asarray(out), walk.walk_frame, 40, 3)
        assert d[:, 0].sum() == 40      # union reaches everyone, once
        assert d[3, 1] and d[5, 2]
        assert d.sum() == 42
        ri.release_transient()
        assert ri.free_pages == free0, f"page leak at tick {tick}"


def test_transient_overflow_spills_frames_not_corruption():
    """A pool too small for the tick's unions: the un-carryable frames
    come back in ``spilled`` (the caller's dense/host fallback), nothing
    else is disturbed, and after release the pool recovers."""
    T = 8
    ri = RaggedInterest(T, max_pages=4)  # page 0 + three usable
    for u in range(6):
        ri.set_mask(u, 0b01)  # one topic page
    assert ri.free_pages == 2
    kind = np.full(4, KIND_BROADCAST, np.int32)
    tmask = np.asarray([0b01, 0b11, 0b11, 0b01], np.uint32)
    valid = np.ones(4, bool)
    dest = np.full(4, -1, np.int32)
    # frame 1's union takes the last free pages? only one union is
    # memoized; add a direct to exhaust the remaining page
    kind[3] = KIND_DIRECT
    dest[3] = 2
    tmask[3] = 0
    walk = ri.pack(kind, tmask, dest, valid)
    # single-topic frames never spill (live pages); the union (1 page)
    # and the direct page both fit the 2 free pages -> no spill yet
    assert not walk.spilled
    ri.release_transient()
    # now ask for THREE distinct unions: only 2 free pages -> spill
    tmask2 = np.asarray([0b011, 0b101, 0b110], np.uint32)
    kind2 = np.full(3, KIND_BROADCAST, np.int32)
    ri.set_mask(6, 0b100)  # third topic page? pool full ->
    walk2 = ri.pack(kind2, tmask2, np.full(3, -1, np.int32),
                    np.ones(3, bool))
    assert walk2.spilled, "transient exhaustion must spill"
    spilled = set(walk2.spilled)
    # non-spilled frames still walked correctly
    kept = [n for n in range(3) if n not in spilled]
    assert all(walk2.walk_frame[:walk2.n_walk] != s for s in spilled)
    assert len(kept) >= 1
    ri.release_transient()


def test_persistent_overflow_flags_and_rebuild_recovers():
    """Subscription growth past the pool: ``overflowed`` latches (the
    device plane's dense-fallback trigger); after churn shrinks the
    membership, ``rebuild()`` restores a usable index."""
    ri = RaggedInterest(4, max_pages=3)  # page 0 + two usable
    for u in range(2 * PAGE):  # fills two pages of topic 0
        ri.set_mask(u, 0b1)
    assert not ri.overflowed
    ri.set_mask(999, 0b10)  # needs a third page
    assert ri.overflowed
    # shrink and rebuild
    for u in range(PAGE, 2 * PAGE):
        ri.set_mask(u, 0)
    assert ri.rebuild()
    assert not ri.overflowed
    assert sorted(ri.topic_receivers(0).tolist()) == list(range(PAGE))
    assert ri.topic_receivers(1).tolist() == [999]


def test_empty_frames_pack_no_walk_entries():
    """Frames with zero fan-out (no subscribers, mask 0, invalid slots,
    control kinds, garbage dests) contribute nothing to the walk."""
    ri = RaggedInterest(8, max_pages=16)
    ri.set_mask(0, 0b1)
    kind = np.asarray([KIND_BROADCAST, KIND_BROADCAST, 6, KIND_DIRECT,
                       KIND_BROADCAST], np.int32)
    tmask = np.asarray([0b10, 0, 0b1, 0, 0b1], np.uint32)  # t1: nobody
    dest = np.asarray([-1, -1, -1, -2, -1], np.int32)      # garbage dest
    valid = np.asarray([True, True, True, True, False])    # last invalid
    walk = ri.pack(kind, tmask, dest, valid)
    # only the t1-broadcast frame walks (its topic page list is empty ->
    # actually zero entries too); nothing else is eligible
    assert walk.n_walk == 0
    assert not walk.spilled
    # and the walk still evaluates cleanly (padded null-page entries)
    out, cnt = ragged_delivery_reference(
        jnp.asarray(walk.pages), jnp.asarray(walk.walk_page),
        jnp.asarray(walk.walk_frame), jnp.asarray(np.ones(4, bool)),
        jnp.asarray(np.asarray([0b1, 0, 0, 0], np.uint32)),
        jnp.asarray(tmask), jnp.asarray(np.where(valid, kind, 0)),
        jnp.asarray(dest))
    assert int(np.asarray(cnt).sum()) == 0
    ri.release_transient()
