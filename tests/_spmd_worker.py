"""Worker for the two-process SPMD test (run via subprocess, not pytest).

Each of two OS processes hosts 4 virtual CPU devices, joins the
jax.distributed runtime, builds the SAME global 8-shard broker mesh, and
executes ONE jitted lane step collectively — the real multi-host
contract (pushcdn_tpu/parallel/multihost.py), not the single-process
8-device pretend version. Asserts, per process:

- the runtime really is 2 processes x 4 local devices;
- the broker-axis ring crosses DCN exactly twice;
- frames published on the OTHER process's shards deliver to THIS
  process's users (cross-process fan-out through the all_gather);
- every shard's direct frame lands exactly once at its owner shard
  (all_to_all across the process boundary);
- the CRDT converges: claims seeded only on remote shards appear in
  this process's merged owner table.

Usage: _spmd_worker.py <rank> <coordinator_port>
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")  # sitecustomize may override env

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

rank = int(sys.argv[1])
port = int(sys.argv[2])

jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                           num_processes=2, process_id=rank)

assert jax.process_count() == 2, jax.process_count()
assert jax.local_device_count() == 4, jax.local_device_count()
assert jax.device_count() == 8, jax.device_count()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pushcdn_tpu.parallel.crdt import ABSENT, CrdtState  # noqa: E402
from pushcdn_tpu.parallel.frames import DirectBuckets, FrameRing  # noqa: E402
from pushcdn_tpu.parallel.multihost import (  # noqa: E402
    dcn_crossings,
    local_shard_indices,
    pod_broker_mesh,
)
from pushcdn_tpu.parallel.router import (  # noqa: E402
    BROKER_AXIS,
    DirectIngress,
    IngressBatch,
    RouterState,
    make_mesh_lane_step,
)

N = 8      # global shards
U = 16     # user slots per shard

mesh = pod_broker_mesh(N)
assert dcn_crossings(mesh) == 2, dcn_crossings(mesh)
local = local_shard_indices(mesh)
expected_local = list(range(4)) if rank == 0 else list(range(4, 8))
assert local == expected_local, (rank, local)

step = make_mesh_lane_step(mesh)


def garr(host_array):
    """Global sharded array from identical per-process host data."""
    return jax.make_array_from_callback(
        host_array.shape, NamedSharding(mesh, P(BROKER_AXIS)),
        lambda idx: host_array[idx])


# CRDT seed: shard i claims user slot i — each claim exists ONLY on its
# origin shard's row, so convergence requires the cross-process merge.
owners = np.full((N, U), ABSENT, np.int32)
versions = np.zeros((N, U), np.uint32)
ids = np.full((N, U), ABSENT, np.int32)
masks = np.zeros((N, U), np.uint32)
for i in range(N):
    owners[i, i] = i
    versions[i, i] = 1
    ids[i, i] = i
    masks[i, i] = 0b1

state = RouterState(
    CrdtState(garr(owners), garr(versions), garr(ids)), garr(masks))

# one broadcast frame per shard (topic bit 0), one direct frame per shard
# addressed to user slot (i+1) % N — owned by the NEXT shard, so rank 0's
# shard 3 sends across the process boundary to rank 1's shard 4, etc.
ring_parts = []
for i in range(N):
    r = FrameRing(slots=4, frame_bytes=64)
    r.push_broadcast(b"from-%d" % i, 0b1)
    ring_parts.append(r.take_batch())
S = ring_parts[0].kind.shape[0]
batch = IngressBatch(
    garr(np.stack([p.bytes_ for p in ring_parts])),
    garr(np.stack([p.kind for p in ring_parts])),
    garr(np.stack([p.length for p in ring_parts])),
    garr(np.stack([p.topic_mask for p in ring_parts])),
    garr(np.stack([p.dest for p in ring_parts])),
    garr(np.stack([p.valid for p in ring_parts])))

dparts = []
for i in range(N):
    d = DirectBuckets(N, capacity=2, frame_bytes=128)
    d.push((i + 1) % N, b"direct-%d" % i, dest_slot=(i + 1) % N)
    dparts.append(d.take_batch())
direct = DirectIngress(
    garr(np.stack([p.bytes_ for p in dparts])),
    garr(np.stack([p.length for p in dparts])),
    garr(np.stack([p.dest for p in dparts])),
    garr(np.stack([p.valid for p in dparts])))

out = step(state, (batch,), (direct,))

# ---- global invariants (replicated scalars, addressable everywhere) ----
lane_total = int(jnp.sum(out.lanes[0].deliver))
assert lane_total == N * N, lane_total          # every frame -> every user
direct_total = int(jnp.sum(out.direct_lanes[0].deliver))
assert direct_total == N, direct_total          # one landing per frame

# ---- per-process (cross-process) assertions ----------------------------
remote = set(range(N)) - set(local)
for shard in out.lanes[0].deliver.addressable_shards:
    b = shard.index[0].start  # this device's broker index
    dm = np.asarray(shard.data)[0]  # [U, N*S] (users x gathered frames)
    # frames are gathered as src*S + slot; count deliveries whose source
    # shard lives on the OTHER process
    from_remote = sum(int(dm[:, src * S].sum()) for src in remote)
    assert from_remote == len(remote), (b, from_remote)

for shard in out.state.crdt.owners.addressable_shards:
    merged = np.asarray(shard.data)[0]  # [U]
    for i in range(N):
        assert merged[i] == i, (i, merged[:N])  # remote claims arrived

# direct: this process's shards each received exactly the one frame
# addressed to them, sent by the PREVIOUS shard (cross-process for the
# boundary shards 0 and 4)
for shard in out.direct_lanes[0].deliver.addressable_shards:
    b = shard.index[0].start
    dm = np.asarray(shard.data)[0]
    assert int(dm.sum()) == 1, (b, dm.sum())

jax.distributed.shutdown()
print(f"rank {rank}: SPMD OK (process_count=2, dcn_crossings=2, "
      f"cross-process deliveries + CRDT convergence verified)")
