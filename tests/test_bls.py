"""Native BLS-over-BN254 signature scheme tests.

Capability parity with the reference's signature tests
(cdn-proto/src/crypto/signature.rs:177-219 — namespace separation and
round trips for its jellyfish BLS-over-BN254 scheme) plus the pairing
library's own invariants (bilinearity self-test) and an end-to-end
marshal-auth flow running entirely on BLS keys.
"""

import asyncio

import pytest

from pushcdn_tpu.native import bls
from pushcdn_tpu.proto.crypto.signature import (
    BlsBn254Scheme,
    Ed25519Scheme,
    Namespace,
)

pytestmark = pytest.mark.skipif(
    not bls.available(), reason="native BLS library failed to compile")


def test_pairing_self_test():
    """The library's internal invariants: e(G2,G1) != 1, bilinearity
    e(aQ,bP) == e(Q,P)^ab, keygen/sign/verify round trip, tamper
    rejection. rc pinpoints the failed invariant."""
    assert bls.self_test() == 0


def test_deterministic_keygen():
    kp1 = BlsBn254Scheme.generate_keypair(seed=7)
    kp2 = BlsBn254Scheme.generate_keypair(seed=7)
    kp3 = BlsBn254Scheme.generate_keypair(seed=8)
    assert kp1 == kp2
    assert kp1.public_key != kp3.public_key
    assert len(kp1.private_key) == 32
    assert len(kp1.public_key) == 128   # G2 affine, uncompressed
    random_kp = BlsBn254Scheme.generate_keypair()
    assert random_kp.public_key != kp1.public_key


def test_sign_verify_roundtrip():
    kp = BlsBn254Scheme.generate_keypair(seed=1)
    msg = b"the message"
    sig = BlsBn254Scheme.sign(kp.private_key, Namespace.USER_MARSHAL_AUTH, msg)
    assert len(sig) == 64               # G1 affine, uncompressed
    assert BlsBn254Scheme.verify(kp.public_key, Namespace.USER_MARSHAL_AUTH,
                                 msg, sig)


def test_namespace_separation():
    """A signature for the marshal must not verify for broker-broker auth
    (parity signature.rs:177-219)."""
    kp = BlsBn254Scheme.generate_keypair(seed=2)
    msg = b"1700000000"
    sig = BlsBn254Scheme.sign(kp.private_key, Namespace.USER_MARSHAL_AUTH, msg)
    assert BlsBn254Scheme.verify(kp.public_key, Namespace.USER_MARSHAL_AUTH,
                                 msg, sig)
    assert not BlsBn254Scheme.verify(kp.public_key,
                                     Namespace.BROKER_BROKER_AUTH, msg, sig)


def test_tamper_rejection():
    kp = BlsBn254Scheme.generate_keypair(seed=3)
    other = BlsBn254Scheme.generate_keypair(seed=4)
    msg = b"payload"
    sig = BlsBn254Scheme.sign(kp.private_key, Namespace.USER_MARSHAL_AUTH, msg)
    ns = Namespace.USER_MARSHAL_AUTH
    assert not BlsBn254Scheme.verify(kp.public_key, ns, b"payloaD", sig)
    assert not BlsBn254Scheme.verify(other.public_key, ns, msg, sig)
    flipped = bytearray(sig)
    flipped[10] ^= 1
    assert not BlsBn254Scheme.verify(kp.public_key, ns, msg, bytes(flipped))


def test_malformed_inputs_rejected_without_crash():
    kp = BlsBn254Scheme.generate_keypair(seed=5)
    ns = Namespace.USER_MARSHAL_AUTH
    sig = BlsBn254Scheme.sign(kp.private_key, ns, b"m")
    assert not BlsBn254Scheme.verify(b"", ns, b"m", sig)
    assert not BlsBn254Scheme.verify(kp.public_key, ns, b"m", b"short")
    # non-canonical field elements (>= p) must be rejected
    assert not BlsBn254Scheme.verify(b"\xff" * 128, ns, b"m", sig)
    assert not BlsBn254Scheme.verify(kp.public_key, ns, b"m", b"\xff" * 64)
    # all-zero encodings (the infinity encoding) are invalid
    assert not BlsBn254Scheme.verify(b"\x00" * 128, ns, b"m", sig)
    assert not BlsBn254Scheme.verify(kp.public_key, ns, b"m", b"\x00" * 64)
    # an Ed25519 signature is not a BLS signature
    ed = Ed25519Scheme.generate_keypair(seed=6)
    ed_sig = Ed25519Scheme.sign(ed.private_key, ns, b"m")
    assert not BlsBn254Scheme.verify(kp.public_key, ns, b"m", ed_sig)


def test_distinct_messages_distinct_signatures():
    kp = BlsBn254Scheme.generate_keypair(seed=9)
    ns = Namespace.USER_MARSHAL_AUTH
    sigs = {BlsBn254Scheme.sign(kp.private_key, ns, b"m%d" % i)
            for i in range(8)}
    assert len(sigs) == 8  # deterministic per message, distinct across them


async def test_end_to_end_cluster_on_bls():
    """Whole-system flow with BLS everywhere: marshal verifies a BLS
    user signature, broker↔broker mutual auth signs with BLS, and a
    direct-message echo completes (parity basic_connect.rs over the
    reference's production scheme shape)."""
    from pushcdn_tpu.proto.message import Direct
    from pushcdn_tpu.testing import Cluster

    cluster = await Cluster(num_brokers=2, scheme=BlsBn254Scheme).start()
    try:
        client = cluster.client(seed=21_000, topics=[0])
        await client.ensure_initialized()
        await client.send_direct_message(client.public_key, b"bls echo")
        got = await asyncio.wait_for(client.receive_message(), 10)
        assert isinstance(got, Direct)
        assert bytes(got.message) == b"bls echo"
        client.close()
    finally:
        await cluster.stop()


def test_cached_verify_matches_plain_cold_and_warm():
    """The per-pk line-table cache (the marshal's repeat-connector hot
    path) must be invisible semantically: cold (miss+record), warm
    (table replay), tampered, wrong-key, and malformed inputs all agree
    with the uncached pairing loop, and the counters actually move."""
    bls.pk_cache_clear()
    kp = BlsBn254Scheme.generate_keypair(seed=700)
    other = BlsBn254Scheme.generate_keypair(seed=701)
    ns = Namespace.USER_MARSHAL_AUTH
    msg = b"repeat connector"
    from pushcdn_tpu.proto.crypto.signature import _namespaced
    raw = _namespaced(ns, msg)
    sig = BlsBn254Scheme.sign(kp.private_key, ns, msg)
    for _ in range(3):  # miss, then hits
        assert bls.verify_cached(kp.public_key, raw, sig) \
            == bls.verify(kp.public_key, raw, sig) is True
    for bad_pk, bad_raw, bad_sig in [
            (kp.public_key, raw + b"x", sig),
            (other.public_key, raw, sig),
            (kp.public_key, raw, sig[:-1] + bytes([sig[-1] ^ 1])),
            (b"\xff" * 128, raw, sig),
            (kp.public_key, raw, b"\x00" * 64)]:
        assert bls.verify_cached(bad_pk, bad_raw, bad_sig) \
            == bls.verify(bad_pk, bad_raw, bad_sig) is False
    stats = bls.pk_cache_stats()
    assert stats["hits"] >= 2 and stats["misses"] >= 1
    assert stats["entries"] >= 1
    # the documented memory bound: ~17 KB per cached table
    assert stats["bytes"] <= stats["entries"] * 18 * 1024


def test_cache_eviction_and_repopulation():
    """At capacity 2 a third key evicts the least-recently-used table;
    the evicted key repopulates transparently and still verifies —
    the Python twin of the in-library evict/repopulate self-test."""
    saved = bls.pk_cache_stats()["capacity"]
    bls.pk_cache_clear()
    bls.pk_cache_configure(2)
    try:
        ns = Namespace.USER_MARSHAL_AUTH
        kps, sigs = [], []
        for i in range(3):
            kp = BlsBn254Scheme.generate_keypair(seed=710 + i)
            kps.append(kp)
            sigs.append(BlsBn254Scheme.sign(kp.private_key, ns, b"evict"))
        for kp, sig in zip(kps, sigs):
            assert BlsBn254Scheme.verify(kp.public_key, ns, b"evict", sig)
            assert BlsBn254Scheme.verify(kp.public_key, ns, b"evict", sig)
        stats = bls.pk_cache_stats()
        assert stats["evictions"] >= 1
        assert stats["entries"] == 2 == stats["capacity"]
        # key 0 was evicted; a repopulating verify must still accept,
        # and a tampered message must still reject through the fresh table
        assert BlsBn254Scheme.verify(kps[0].public_key, ns, b"evict",
                                     sigs[0])
        assert not BlsBn254Scheme.verify(kps[0].public_key, ns, b"evicT",
                                         sigs[0])
    finally:
        bls.pk_cache_clear()
        bls.pk_cache_configure(saved)


def test_cache_disabled_still_verifies():
    """Capacity 0 = cache off: the cached entrypoints take the plain
    path (PUSHCDN_BLS_PK_CACHE=0 deployments) with unchanged results."""
    saved = bls.pk_cache_stats()["capacity"]
    bls.pk_cache_clear()
    bls.pk_cache_configure(0)
    try:
        ns = Namespace.USER_MARSHAL_AUTH
        kp = BlsBn254Scheme.generate_keypair(seed=720)
        sig = BlsBn254Scheme.sign(kp.private_key, ns, b"off")
        assert BlsBn254Scheme.verify(kp.public_key, ns, b"off", sig)
        assert not BlsBn254Scheme.verify(kp.public_key, ns, b"ofF", sig)
        assert BlsBn254Scheme.verify_batch(
            [(kp.public_key, ns, b"off", sig)] * 2)
        assert bls.pk_cache_stats()["entries"] == 0  # nothing was cached
    finally:
        bls.pk_cache_configure(saved)


def test_batch_cached_matches_uncached():
    """The fused multi-table batch walk agrees with the plain per-item
    Miller-loop batch on honest and forged inputs, warm or cold."""
    import os as _os
    bls.pk_cache_clear()
    ns = Namespace.USER_MARSHAL_AUTH
    from pushcdn_tpu.proto.crypto.signature import _namespaced
    items = []
    for i in range(5):
        kp = BlsBn254Scheme.generate_keypair(seed=730 + i)
        msg = b"fused %d" % i
        sig = BlsBn254Scheme.sign(kp.private_key, ns, msg)
        items.append((kp.public_key, _namespaced(ns, msg), sig))
    seed = _os.urandom(32)
    for _ in range(2):  # cold tables, then warm
        assert bls.verify_batch(items, seed, cached=True) \
            == bls.verify_batch(items, seed, cached=False) is True
    forged = list(items)
    forged[2] = (forged[2][0], forged[2][1],
                 forged[3][2])  # someone else's signature
    assert bls.verify_batch(forged, seed, cached=True) \
        == bls.verify_batch(forged, seed, cached=False) is False


def test_batch_verify_all_valid():
    ns = Namespace.USER_MARSHAL_AUTH
    items = []
    for i in range(5):
        kp = BlsBn254Scheme.generate_keypair(seed=400 + i)
        msg = b"storm auth %d" % i
        sig = BlsBn254Scheme.sign(kp.private_key, ns, msg)
        items.append((kp.public_key, ns, msg, sig))
    assert BlsBn254Scheme.verify_batch(items)


def test_batch_verify_rejects_one_forgery():
    ns = Namespace.USER_MARSHAL_AUTH
    items = []
    for i in range(4):
        kp = BlsBn254Scheme.generate_keypair(seed=500 + i)
        msg = b"storm auth %d" % i
        sig = BlsBn254Scheme.sign(kp.private_key, ns, msg)
        items.append([kp.public_key, ns, msg, sig])
    # swap two signatures: each is individually valid for the OTHER
    # message, so only a real pairing check catches it
    items[1][3], items[2][3] = items[2][3], items[1][3]
    assert not BlsBn254Scheme.verify_batch(
        [tuple(it) for it in items])


def test_batch_verify_matches_single_semantics():
    ns = Namespace.USER_MARSHAL_AUTH
    kp = BlsBn254Scheme.generate_keypair(seed=600)
    msg = b"solo"
    sig = BlsBn254Scheme.sign(kp.private_key, ns, msg)
    assert BlsBn254Scheme.verify_batch([(kp.public_key, ns, msg, sig)])
    assert BlsBn254Scheme.verify_batch([])  # vacuous truth
    bad = bytearray(sig)
    bad[7] ^= 1
    assert not BlsBn254Scheme.verify_batch(
        [(kp.public_key, ns, msg, bytes(bad))])


async def test_marshal_batches_storm_verifications():
    """Under a connection storm the marshal amortizes pairing checks via
    the micro-batching verifier (crypto/batch.py): concurrent auths share
    one batched verification, and a forged item in a batch neither passes
    nor denies service to the honest co-batched users."""
    from pushcdn_tpu.testing import Cluster

    cluster = await Cluster(num_brokers=1, scheme=BlsBn254Scheme).start()
    try:
        clients = [cluster.client(seed=95_000 + i, topics=[0])
                   for i in range(10)]
        await asyncio.gather(*(c.ensure_initialized() for c in clients))
        bv = cluster.marshal.batch_verifier
        # 10 auths fired in one gather: the first verifies solo and the
        # rest overlap its ~2 ms pairing, so at least one real batch forms
        assert bv.batches >= 1, (bv.batches, bv.singles)
        assert bv.batched_items >= 2  # real amortization happened
        # everyone actually authenticated end to end
        await clients[0].send_broadcast_message([0], b"storm ok")
        for c in clients:
            got = await asyncio.wait_for(c.receive_message(), 10)
            assert bytes(got.message) == b"storm ok"
        for c in clients:
            c.close()
    finally:
        await cluster.stop()


async def test_batch_verifier_isolates_forgery():
    from pushcdn_tpu.proto.crypto.batch import BatchVerifier
    from pushcdn_tpu.proto.crypto.signature import Namespace

    bv = BatchVerifier(BlsBn254Scheme, max_batch=8)
    ns = Namespace.USER_MARSHAL_AUTH
    async def one(seed, forge):
        kp = BlsBn254Scheme.generate_keypair(seed=seed)
        msg = b"storm %d" % seed
        sig = BlsBn254Scheme.sign(kp.private_key, ns, msg)
        if forge:
            sig = bytes(sig[:-1]) + bytes([sig[-1] ^ 1])
        return await bv.verify(kp.public_key, ns, msg, sig)
    results = await asyncio.gather(
        one(1, False), one(2, True), one(3, False), one(4, False))
    assert results == [True, False, True, True]
    # adaptive batching: the first verified solo, 2-4 batched behind it
    assert bv.batches == 1 and bv.batched_items == 3


@pytest.mark.parametrize("offload", [False, True])
async def test_batch_verifier_offload_modes(offload):
    """Both offload policies (inline single-core path and the to_thread
    multi-core path) verify honest items, reject forgeries, and still
    form batches behind an in-flight verification."""
    from pushcdn_tpu.proto.crypto.batch import BatchVerifier
    from pushcdn_tpu.proto.crypto.signature import Namespace

    bv = BatchVerifier(BlsBn254Scheme, max_batch=8, offload=offload)
    ns = Namespace.USER_MARSHAL_AUTH

    async def one(seed, forge):
        kp = BlsBn254Scheme.generate_keypair(seed=seed)
        msg = b"mode %d" % seed
        sig = BlsBn254Scheme.sign(kp.private_key, ns, msg)
        if forge:
            sig = bytes(sig[:-1]) + bytes([sig[-1] ^ 1])
        return await bv.verify(kp.public_key, ns, msg, sig)

    results = await asyncio.gather(
        one(11, False), one(12, False), one(13, True), one(14, False))
    assert results == [True, True, False, True]
    # every waiter resolved (no future left hanging by either path) and
    # the batch window stayed alive across the policy's yield/handoff
    assert bv.batches >= 1
    assert bv.singles + bv.batched_items == 4
