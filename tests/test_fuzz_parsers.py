"""Adversarial-input fuzzing for every parser that touches wire bytes.

The reference's parsers sit behind Rust's memory safety plus capnp's
traversal limits; here the equivalent guarantee is that random or
truncated bytes NEVER escape as anything but the documented
``Error(DESERIALIZE)`` (or a clean drop, for datagram transports) — no
IndexError/struct.error/UnboundLocalError leaking from the hot parsing
paths, no hangs, no unbounded allocation.

Deterministic seeds: failures reproduce.
"""

import random

from pushcdn_tpu.proto.error import Error
from pushcdn_tpu.proto.message import (
    decode_frames,
    deserialize_owned,
    serialize,
)
from pushcdn_tpu.proto.transport.base import _py_scan_frames


def _random_blobs(seed, n, max_len=512):
    rng = random.Random(seed)
    out = []
    for _ in range(n):
        k = rng.randrange(0, max_len)
        out.append(bytes(rng.getrandbits(8) for _ in range(k)))
    return out


def test_deserialize_survives_random_bytes():
    ok = rejected = 0
    for blob in _random_blobs(1, 400):
        try:
            deserialize_owned(blob)
            ok += 1
        except Error:
            rejected += 1
    # every input either decodes or raises the documented Error — any
    # other exception fails the test by propagating
    assert ok + rejected == 400 and rejected > 0


def test_deserialize_survives_mutated_valid_frames():
    from pushcdn_tpu.proto.message import (
        AuthenticateResponse,
        AuthenticateWithKey,
        Broadcast,
        Direct,
        Subscribe,
    )
    rng = random.Random(2)
    frames = [
        serialize(Direct(recipient=b"r" * 32, message=b"m" * 100)),
        serialize(Broadcast(topics=[1, 2, 3], message=b"b" * 100)),
        serialize(Subscribe(topics=[0, 7])),
        serialize(AuthenticateWithKey(public_key=b"k" * 32, timestamp=5,
                                      signature=b"s" * 64)),
        serialize(AuthenticateResponse(permit=9, context="ctx")),
    ]
    for _ in range(2000):
        base = bytearray(rng.choice(frames))
        op = rng.randrange(3)
        if op == 0 and base:          # flip a byte
            i = rng.randrange(len(base))
            base[i] ^= 1 << rng.randrange(8)
        elif op == 1:                 # truncate
            base = base[:rng.randrange(len(base) + 1)]
        else:                         # extend with garbage
            base += bytes(rng.getrandbits(8) for _ in range(rng.randrange(16)))
        try:
            deserialize_owned(bytes(base))
        except Error:
            pass  # the documented failure mode


def test_decode_frames_survives_corrupt_offsets_payloads():
    rng = random.Random(3)
    for blob in _random_blobs(4, 200, max_len=256):
        if not blob:
            continue
        # offsets/lengths that stay in range but cut frames arbitrarily
        offs, lens = [], []
        pos = 0
        while pos < len(blob):
            n = rng.randrange(1, 64)
            n = min(n, len(blob) - pos)
            offs.append(pos)
            lens.append(n)
            pos += n
        try:
            out = decode_frames(blob, offs, lens)
            assert len(out) == len(offs)
        except Error:
            pass


def test_scan_frames_survives_random_streams():
    for blob in _random_blobs(5, 300, max_len=600):
        offs, lens, consumed, err = _py_scan_frames(blob, 4096)
        assert 0 <= consumed <= len(blob)
        for o, ln in zip(offs, lens):
            assert o + ln <= len(blob)
    # native scanner agrees on the same inputs (when available)
    from pushcdn_tpu import native
    if native.available():
        for blob in _random_blobs(6, 300, max_len=600):
            py = _py_scan_frames(blob, 4096)
            nat = native.scan_frames(blob, 4096)
            if nat is not None:
                pairs, n_consumed, n_err = nat
                assert ([p[0] for p in pairs], [p[1] for p in pairs],
                        n_consumed, bool(n_err)) \
                    == (list(py[0]), list(py[1]), py[2], bool(py[3]))


async def test_quic_on_packet_survives_random_datagrams():
    """The QUIC-class packet handler is the UDP attack surface: random
    type/body datagrams must never raise out of on_packet or wedge the
    stream's timers."""
    from pushcdn_tpu.proto.transport.quic import _UdpStream

    rng = random.Random(7)
    stream = _UdpStream(1, lambda pkt: None)
    try:
        for _ in range(3000):
            ptype = rng.randrange(0, 16)          # includes unknown types
            body = bytes(rng.getrandbits(8)
                         for _ in range(rng.randrange(0, 64)))
            stream.on_packet(ptype, body)
        # nothing escaped on_packet; random garbage may legitimately have
        # included an RST datagram (type byte in range), which poisons the
        # stream by DESIGN — any other error class would be a parser leak
        assert stream._error is None or \
            isinstance(stream._error, ConnectionResetError)
    finally:
        stream.abort()


def test_versioned_map_codec_survives_hostile_payloads():
    """The CRDT sync codec is broker-to-broker wire surface: random blobs
    and a nested-tuple recursion bomb must both surface as the documented
    Error(DESERIALIZE) (the capnp-traversal-limit analog), never
    RecursionError or a raw struct/index error."""
    import struct as _struct

    import pushcdn_tpu.broker.versioned_map as vm
    from pushcdn_tpu.broker.versioned_map import VersionedMap

    rng = random.Random(11)
    rejected = 0
    for _ in range(500):
        blob = bytes(rng.getrandbits(8) for _ in range(rng.randrange(0, 300)))
        try:
            VersionedMap.deserialize_entries(blob)
        except Error:
            rejected += 1
    assert rejected > 0

    nest = b"".join(bytes([vm._T_TUPLE]) + _struct.pack("<I", 1)
                    for _ in range(100_000))
    bomb = _struct.pack("<I", 1) + nest
    try:
        VersionedMap.deserialize_entries(bomb)
        raise AssertionError("tuple bomb decoded")
    except Error:
        pass  # the documented failure mode — bounded traversal
