"""Device-router tests: single-chip semantics, 8-shard mesh routing,
eviction propagation, and Pallas-kernel equivalence."""

import jax
import jax.numpy as jnp
import numpy as np

from pushcdn_tpu.ops.delivery_kernel import (
    delivery_matrix_pallas,
    delivery_matrix_reference,
)
from pushcdn_tpu.parallel.crdt import ABSENT, CrdtState, local_claim
from pushcdn_tpu.parallel.frames import FrameRing
from pushcdn_tpu.parallel.mesh import make_broker_mesh
from pushcdn_tpu.parallel.router import (
    BROKER_AXIS,
    IngressBatch,
    RouterState,
    empty_router_state,
    make_mesh_lane_step,
    make_mesh_routing_step,
    routing_step_lanes_single,
    routing_step_single,
)
from pushcdn_tpu.proto.message import KIND_BROADCAST, KIND_DIRECT

U, S, F = 16, 8, 64


def _batch_from_ring(ring: FrameRing) -> IngressBatch:
    b = ring.take_batch()
    return IngressBatch(
        jnp.asarray(b.bytes_), jnp.asarray(b.kind), jnp.asarray(b.length),
        jnp.asarray(b.topic_mask.astype(np.uint32)), jnp.asarray(b.dest),
        jnp.asarray(b.valid))


def _claim(state: RouterState, slot: int, broker: int,
           topic_mask: int) -> RouterState:
    mask = jnp.zeros(U, bool).at[slot].set(True)
    return RouterState(
        local_claim(state.crdt, mask, jnp.int32(broker)),
        state.topic_masks.at[slot].set(topic_mask))


def test_single_chip_broadcast_and_direct():
    state = empty_router_state(U)
    state = _claim(state, 0, 0, 0b01)   # user 0: topic 0
    state = _claim(state, 1, 0, 0b10)   # user 1: topic 1
    ring = FrameRing(slots=S, frame_bytes=F)
    assert ring.push_broadcast(b"topic0 msg", topic_mask=0b01)
    assert ring.push_direct(b"direct to 1", dest_slot=1)
    res = routing_step_single(state, _batch_from_ring(ring))
    d = np.asarray(res.deliver)
    assert d[0, 0] and not d[0, 1]      # user0 gets the broadcast only
    assert d[1, 1] and not d[1, 0]      # user1 gets the direct only
    assert not np.asarray(res.evictions).any()
    # frame bytes surfaced for the egress pump
    assert bytes(np.asarray(res.gathered_bytes)[0][:10]) == b"topic0 msg"


def test_single_chip_unowned_user_gets_nothing():
    state = empty_router_state(U)
    state = _claim(state, 0, 3, 0b01)   # owned by broker 3, we are broker 0
    ring = FrameRing(slots=S, frame_bytes=F)
    ring.push_broadcast(b"x", topic_mask=0b01)
    ring.push_direct(b"y", dest_slot=0)
    res = routing_step_single(state, _batch_from_ring(ring))
    assert not np.asarray(res.deliver).any()  # delivery-iff-owner


def test_invalid_slots_never_deliver():
    state = _claim(empty_router_state(U), 0, 0, 0xFFFFFFFF)
    ring = FrameRing(slots=S, frame_bytes=F)
    ring.push_broadcast(b"real", topic_mask=0b1)
    batch = _batch_from_ring(ring)
    # poison the metadata of an EMPTY slot: must still not deliver
    batch = batch._replace(
        topic_mask=batch.topic_mask.at[5].set(0xFFFFFFFF),
        kind=batch.kind.at[5].set(KIND_BROADCAST))
    res = routing_step_single(state, batch)
    assert np.asarray(res.deliver)[0].sum() == 1  # only the real frame


def test_mesh_routing_8_shards():
    """Each of 8 broker shards owns one user on topic 0; a broadcast from
    every shard reaches every user exactly once; a direct lands only at its
    owner (the multichip fan-out path over the virtual CPU mesh)."""
    mesh = make_broker_mesh()
    B = mesh.devices.size
    assert B == 8, "conftest must provide 8 virtual CPU devices"
    step = make_mesh_routing_step(mesh)

    owners = np.full((B, U), ABSENT, np.int32)
    versions = np.zeros((B, U), np.uint32)
    ids = np.full((B, U), ABSENT, np.int32)
    masks = np.zeros((B, U), np.uint32)
    for i in range(B):
        owners[i, i] = i; versions[i, i] = 1; ids[i, i] = i; masks[i, i] = 0b1
    state = RouterState(
        CrdtState(jnp.asarray(owners), jnp.asarray(versions), jnp.asarray(ids)),
        jnp.asarray(masks))

    parts = []
    for i in range(B):
        ring = FrameRing(slots=S, frame_bytes=F)
        ring.push_broadcast(f"from-{i}".encode(), topic_mask=0b1)
        if i == 2:
            ring.push_direct(b"direct to user 5", dest_slot=5)
        parts.append(ring.take_batch())
    batch = IngressBatch(
        jnp.asarray(np.stack([x.bytes_ for x in parts])),
        jnp.asarray(np.stack([x.kind for x in parts])),
        jnp.asarray(np.stack([x.length for x in parts])),
        jnp.asarray(np.stack([x.topic_mask for x in parts]).astype(np.uint32)),
        jnp.asarray(np.stack([x.dest for x in parts])),
        jnp.asarray(np.stack([x.valid for x in parts])))

    out = step(state, batch)
    d = np.asarray(out.deliver)  # [B, U, B*S]
    for b in range(B):
        expected = B + (1 if b == 5 else 0)  # all broadcasts (+1 direct)
        assert d[b, b].sum() == expected, (b, int(d[b, b].sum()))
        # no shard delivers to users it doesn't own
        others = [u for u in range(U) if u != b]
        assert d[b][others].sum() == 0


def test_mesh_eviction_on_ownership_change():
    """Shard 0 and shard 1 both claim user 0; shard 1's claim dominates
    (higher version) → shard 0 reports the eviction, parity with
    apply_user_sync's kick (connections/mod.rs:154-162)."""
    mesh = make_broker_mesh()
    B = mesh.devices.size
    step = make_mesh_routing_step(mesh)

    owners = np.full((B, U), ABSENT, np.int32)
    versions = np.zeros((B, U), np.uint32)
    ids = np.full((B, U), ABSENT, np.int32)
    masks = np.zeros((B, U), np.uint32)
    owners[0, 0], versions[0, 0], ids[0, 0] = 0, 1, 0   # shard0 claim v1
    owners[1, 0], versions[1, 0], ids[1, 0] = 1, 2, 1   # shard1 claim v2
    state = RouterState(
        CrdtState(jnp.asarray(owners), jnp.asarray(versions), jnp.asarray(ids)),
        jnp.asarray(masks))
    empty = FrameRing(slots=S, frame_bytes=F).take_batch()
    batch = IngressBatch(*[jnp.asarray(np.stack([getattr(empty, f)] * B))
                           for f in ("bytes_", "kind", "length")],
                         jnp.asarray(np.stack([empty.topic_mask] * B).astype(np.uint32)),
                         jnp.asarray(np.stack([empty.dest] * B)),
                         jnp.asarray(np.stack([empty.valid] * B)))
    out = step(state, batch)
    ev = np.asarray(out.evictions)   # [B, U]
    assert ev[0, 0]                  # shard 0 must kick its local session
    assert not ev[1:, :].any()
    merged_owners = np.asarray(out.state.crdt.owners)
    assert (merged_owners[:, 0] == 1).all()  # everyone converged on shard 1


def test_mask_rides_ownership_handoff():
    """When a dominating ownership claim is adopted, the claimant's topic
    mask is adopted with it — stale masks after a handoff would misroute
    broadcasts (merge_all_gathered_with_payload's whole purpose)."""
    mesh = make_broker_mesh()
    B = mesh.devices.size
    step = make_mesh_routing_step(mesh)

    owners = np.full((B, U), ABSENT, np.int32)
    versions = np.zeros((B, U), np.uint32)
    ids = np.full((B, U), ABSENT, np.int32)
    masks = np.zeros((B, U), np.uint32)
    # every shard has a STALE view: user 0 owned by shard 0 with mask 0b01
    owners[:, 0] = 0; versions[:, 0] = 1; ids[:, 0] = 0; masks[:, 0] = 0b01
    # shard 1 takes user 0 over with a NEW mask 0b10 (version 2 dominates)
    owners[1, 0], versions[1, 0], ids[1, 0], masks[1, 0] = 1, 2, 1, 0b10
    state = RouterState(
        CrdtState(jnp.asarray(owners), jnp.asarray(versions), jnp.asarray(ids)),
        jnp.asarray(masks))

    # a broadcast on topic 1 (mask 0b10) from shard 3
    parts = []
    for i in range(B):
        ring = FrameRing(slots=S, frame_bytes=F)
        if i == 3:
            ring.push_broadcast(b"new-topic msg", topic_mask=0b10)
        parts.append(ring.take_batch())
    batch = IngressBatch(
        jnp.asarray(np.stack([x.bytes_ for x in parts])),
        jnp.asarray(np.stack([x.kind for x in parts])),
        jnp.asarray(np.stack([x.length for x in parts])),
        jnp.asarray(np.stack([x.topic_mask for x in parts]).astype(np.uint32)),
        jnp.asarray(np.stack([x.dest for x in parts])),
        jnp.asarray(np.stack([x.valid for x in parts])))
    out = step(state, batch)
    # every shard converged on the new mask...
    np.testing.assert_array_equal(np.asarray(out.state.topic_masks)[:, 0],
                                  np.full(B, 0b10, np.uint32))
    # ...and the new owner (shard 1) delivered the topic-1 broadcast using
    # the adopted mask, in the SAME step as the handoff
    d = np.asarray(out.deliver)
    assert d[1, 0].sum() == 1
    assert d[0, 0].sum() == 0  # the old owner no longer delivers


def test_pallas_kernel_matches_reference():
    rng = np.random.default_rng(0)
    Uk, Nk = 64, 256
    user_masks = jnp.asarray(rng.integers(0, 2**16, Uk).astype(np.uint32))
    local = jnp.asarray(rng.random(Uk) < 0.5)
    tmask = jnp.asarray(rng.integers(0, 2**16, Nk).astype(np.uint32))
    kind = jnp.asarray(rng.choice([0, KIND_BROADCAST, KIND_DIRECT], Nk).astype(np.int32))
    dest = jnp.asarray(rng.integers(-1, Uk, Nk).astype(np.int32))
    ref = delivery_matrix_reference(user_masks, local, tmask, kind, dest)
    pal = delivery_matrix_pallas(user_masks, local, tmask, kind, dest,
                                 interpret=True)
    np.testing.assert_array_equal(np.asarray(pal), np.asarray(ref))


def test_mesh_direct_all_to_all():
    """The one-hop direct path: frames staged into per-destination-shard
    buckets cross the mesh with ONE all_to_all and deliver only at the
    owner (SURVEY.md §2e: point-to-point collective keyed by owner shard),
    never riding the broadcast all_gather."""
    from pushcdn_tpu.parallel.frames import DirectBuckets
    from pushcdn_tpu.parallel.router import DirectIngress

    mesh = make_broker_mesh()
    B = mesh.devices.size
    C = 4
    step = make_mesh_routing_step(mesh, with_direct=True)

    # shard i owns user slot i, topic mask irrelevant here
    owners = np.full((B, U), ABSENT, np.int32)
    versions = np.zeros((B, U), np.uint32)
    ids = np.full((B, U), ABSENT, np.int32)
    masks = np.zeros((B, U), np.uint32)
    for i in range(B):
        owners[i, i] = i; versions[i, i] = 1; ids[i, i] = i
    state = RouterState(
        CrdtState(jnp.asarray(owners), jnp.asarray(versions), jnp.asarray(ids)),
        jnp.asarray(masks))

    # empty broadcast ingress; shard 2 sends directs to users 5 and 7
    # (owned by shards 5 and 7), shard 6 sends to user 0
    parts = [FrameRing(slots=S, frame_bytes=F).take_batch() for _ in range(B)]
    batch = IngressBatch(
        jnp.asarray(np.stack([x.bytes_ for x in parts])),
        jnp.asarray(np.stack([x.kind for x in parts])),
        jnp.asarray(np.stack([x.length for x in parts])),
        jnp.asarray(np.stack([x.topic_mask for x in parts]).astype(np.uint32)),
        jnp.asarray(np.stack([x.dest for x in parts])),
        jnp.asarray(np.stack([x.valid for x in parts])))

    buckets = [DirectBuckets(B, capacity=C, frame_bytes=F) for _ in range(B)]
    assert buckets[2].push(5, b"to user 5", dest_slot=5)
    assert buckets[2].push(7, b"to user 7", dest_slot=7)
    assert buckets[6].push(0, b"to user 0", dest_slot=0)
    parts_d = [b.take_batch() for b in buckets]
    direct = DirectIngress(
        jnp.asarray(np.stack([x.bytes_ for x in parts_d])),
        jnp.asarray(np.stack([x.length for x in parts_d])),
        jnp.asarray(np.stack([x.dest for x in parts_d])),
        jnp.asarray(np.stack([x.valid for x in parts_d])))

    out = step(state, batch, direct)
    assert np.asarray(out.deliver).sum() == 0       # nothing on the broadcast path
    dd = np.asarray(out.direct_deliver)             # [B, U, B*C]
    db = np.asarray(out.direct_bytes)               # [B, B*C, F]
    dl = np.asarray(out.direct_length)
    # exactly the three deliveries, each at its owner shard only
    assert dd.sum() == 3
    for shard, user, payload in [(5, 5, b"to user 5"), (7, 7, b"to user 7"),
                                 (0, 0, b"to user 0")]:
        hits = np.nonzero(dd[shard, user])[0]
        assert len(hits) == 1, (shard, user, hits)
        f = hits[0]
        assert db[shard, f, :dl[shard, f]].tobytes() == payload
        # no other shard delivers this frame
        assert dd[:, user].sum() == 1

    # bucket overflow is per-link backpressure
    small = DirectBuckets(B, capacity=1, frame_bytes=F)
    assert small.push(3, b"x", 3)
    assert not small.push(3, b"y", 3)   # that link is full
    assert small.push(4, b"z", 4)       # other links unaffected


def test_lane_step_single_and_mesh():
    """Size-bucketed lanes (hard-part #1): one step routes several
    independently-shaped rings with ONE shared CRDT merge — single-chip
    and over the 8-shard mesh with a direct all_to_all lane."""
    state = empty_router_state(U)
    state = _claim(state, 0, 0, 0b1)
    small = FrameRing(slots=8, frame_bytes=64)
    small.push_broadcast(b"small", 0b1)
    big = FrameRing(slots=4, frame_bytes=512)
    big.push_broadcast(b"B" * 300, 0b1)
    big.push_direct(b"D" * 200, dest_slot=0)
    res = routing_step_lanes_single(
        state, (_batch_from_ring(small), _batch_from_ring(big)))
    assert np.asarray(res.lanes[0].deliver)[0].sum() == 1
    assert np.asarray(res.lanes[1].deliver)[0].sum() == 2
    assert bytes(np.asarray(res.lanes[1].gathered_bytes)[0][:3]) == b"BBB"

    n = 8
    mesh = make_broker_mesh(n)
    step = make_mesh_lane_step(mesh)
    owners = np.full((n, U), ABSENT, np.int32)
    versions = np.zeros((n, U), np.uint32)
    ids = np.full((n, U), ABSENT, np.int32)
    masks = np.zeros((n, U), np.uint32)
    for i in range(n):
        owners[i, i] = i
        versions[i, i] = 1
        ids[i, i] = i
        masks[i, i] = 0b1
    state = RouterState(
        CrdtState(jnp.asarray(owners), jnp.asarray(versions),
                  jnp.asarray(ids)), jnp.asarray(masks))

    def stack_rings(make_ring):
        parts = []
        for i in range(n):
            parts.append(make_ring(i).take_batch())
        return IngressBatch(
            jnp.asarray(np.stack([p.bytes_ for p in parts])),
            jnp.asarray(np.stack([p.kind for p in parts])),
            jnp.asarray(np.stack([p.length for p in parts])),
            jnp.asarray(np.stack([p.topic_mask for p in parts])),
            jnp.asarray(np.stack([p.dest for p in parts])),
            jnp.asarray(np.stack([p.valid for p in parts])))

    def small_ring(i):
        r = FrameRing(slots=4, frame_bytes=64)
        r.push_broadcast(b"s%d" % i, 0b1)
        return r

    def big_ring(i):
        r = FrameRing(slots=2, frame_bytes=512)
        r.push_broadcast(b"L" * 400, 0b1)
        return r

    from pushcdn_tpu.parallel.frames import DirectBuckets
    from pushcdn_tpu.parallel.router import DirectIngress
    dparts = []
    for i in range(n):
        d = DirectBuckets(n, capacity=2, frame_bytes=256)
        d.push((i + 1) % n, b"d%d" % i, dest_slot=(i + 1) % n)
        dparts.append(d.take_batch())
    direct = DirectIngress(
        jnp.asarray(np.stack([p.bytes_ for p in dparts])),
        jnp.asarray(np.stack([p.length for p in dparts])),
        jnp.asarray(np.stack([p.dest for p in dparts])),
        jnp.asarray(np.stack([p.valid for p in dparts])))

    out = step(state, (stack_rings(small_ring), stack_rings(big_ring)),
               (direct,))
    # each shard's broadcast (per lane) reaches every owned user once
    assert np.asarray(out.lanes[0].deliver).sum() == n * n
    assert np.asarray(out.lanes[1].deliver).sum() == n * n
    # each all_to_all direct frame lands exactly once at its owner shard
    assert np.asarray(out.direct_lanes[0].deliver).sum() == n
    # CRDT converged identically on every shard
    merged = np.asarray(out.state.crdt.owners)
    assert (merged[0] == merged).all()


def test_liveness_mask_dead_shard():
    """Hard-part #3 (dynamic membership on a static mesh): a shard marked
    dead contributes no deliveries, and slots it owned are tombstoned by an
    identical deterministic release on every live shard."""
    import jax.numpy as jnp
    from pushcdn_tpu.parallel.router import make_mesh_lane_step

    n = 8
    dead = 3
    mesh = make_broker_mesh(n)
    step = make_mesh_lane_step(mesh)
    owners = np.full((n, U), ABSENT, np.int32)
    versions = np.zeros((n, U), np.uint32)
    ids = np.full((n, U), ABSENT, np.int32)
    masks = np.zeros((n, U), np.uint32)
    for i in range(n):
        owners[i, i] = i
        versions[i, i] = 1
        ids[i, i] = i
        masks[i, i] = 0b1
    state = RouterState(
        CrdtState(jnp.asarray(owners), jnp.asarray(versions),
                  jnp.asarray(ids)), jnp.asarray(masks))
    parts = []
    for i in range(n):
        r = FrameRing(slots=4, frame_bytes=64)
        r.push_broadcast(b"from %d" % i, 0b1)
        parts.append(r.take_batch())
    batch = IngressBatch(
        jnp.asarray(np.stack([p.bytes_ for p in parts])),
        jnp.asarray(np.stack([p.kind for p in parts])),
        jnp.asarray(np.stack([p.length for p in parts])),
        jnp.asarray(np.stack([p.topic_mask for p in parts])),
        jnp.asarray(np.stack([p.dest for p in parts])),
        jnp.asarray(np.stack([p.valid for p in parts])))
    live = np.ones(n, bool)
    live[dead] = False
    out = step(state, (batch,), (),
               jnp.asarray(np.broadcast_to(live, (n, n))))
    deliver = np.asarray(out.lanes[0].deliver)
    # the dead shard's broadcast delivers nowhere; everyone else's reaches
    # the n-1 live owned users (the dead shard's user slot was released)
    merged_owners = np.asarray(out.state.crdt.owners)
    assert (merged_owners[0] == merged_owners).all()  # still convergent
    assert (merged_owners[:, dead] == ABSENT).all()   # tombstoned
    # per shard: slots delivered = live users x live frames
    for shard in range(n):
        d = deliver[shard]
        # frames are ordered [src_shard * slots + slot]
        dead_frame_cols = d[:, dead * 4:(dead + 1) * 4]
        assert not dead_frame_cols.any(), "dead shard's frames delivered"
    total = deliver.sum()
    assert total == (n - 1) * (n - 1), total  # 7 live frames x 7 live users
    # released slots' masks were cleared with the claim
    assert (np.asarray(out.state.topic_masks)[:, dead] == 0).all()


def test_multiword_topic_masks():
    """8×u32 masks cover the reference's full u8 topic space: delivery on
    topics ≥ 32, Pallas kernel ≡ jnp reference at W=8, and masks riding
    the lane step."""
    from pushcdn_tpu.parallel.frames import (
        TOPIC_WORDS_FULL, mask_of_topics, split_mask)

    rng = np.random.default_rng(7)
    Uw, Nw, W = 16, 256, TOPIC_WORDS_FULL
    umask = rng.integers(0, 2**32, (Uw, W), dtype=np.uint32)
    tmask = rng.integers(0, 2**32, (Nw, W), dtype=np.uint32)
    local = rng.random(Uw) < 0.7
    kind = rng.choice([0, KIND_BROADCAST, KIND_DIRECT], Nw).astype(np.int32)
    dest = rng.integers(-1, Uw, Nw).astype(np.int32)
    ref = delivery_matrix_reference(
        jnp.asarray(umask), jnp.asarray(local), jnp.asarray(tmask),
        jnp.asarray(kind), jnp.asarray(dest))
    pal = delivery_matrix_pallas(
        jnp.asarray(umask), jnp.asarray(local), jnp.asarray(tmask),
        jnp.asarray(kind), jnp.asarray(dest), interpret=True)
    assert (np.asarray(ref) == np.asarray(pal)).all()

    # semantic check on a high topic through the full lane step
    state = empty_router_state(U, topic_words=W)
    mask200 = mask_of_topics([200], W)
    claim = jnp.zeros(U, bool).at[0].set(True)
    from pushcdn_tpu.parallel.crdt import local_claim
    state = RouterState(
        local_claim(state.crdt, claim, jnp.int32(0)),
        state.topic_masks.at[0].set(jnp.asarray(split_mask(mask200, W))))
    ring = FrameRing(slots=8, frame_bytes=64, topic_words=W)
    ring.push_broadcast(b"topic 200", topic_mask=mask200)
    ring.push_broadcast(b"topic 7", topic_mask=mask_of_topics([7], W))
    res = routing_step_lanes_single(state, (_batch_from_ring(ring),))
    d = np.asarray(res.lanes[0].deliver)
    assert d[0, 0] and not d[0, 1]  # subscribed to 200, not to 7


def _seeded_mesh_inputs(n=8, seed=0, with_direct=True):
    """Stacked state + traffic for an n-shard mesh (helper for the fused
    one-collective tests)."""
    from pushcdn_tpu.parallel.frames import DirectBuckets
    from pushcdn_tpu.parallel.router import DirectIngress

    rng = np.random.default_rng(seed)
    owners = np.full((n, U), ABSENT, np.int32)
    versions = np.zeros((n, U), np.uint32)
    ids = np.full((n, U), ABSENT, np.int32)
    masks = np.zeros((n, U), np.uint32)
    for i in range(n):
        owners[i, i] = i
        versions[i, i] = 1
        ids[i, i] = i
        masks[i, i] = rng.integers(1, 8)
    state = RouterState(
        CrdtState(jnp.asarray(owners), jnp.asarray(versions),
                  jnp.asarray(ids)), jnp.asarray(masks))
    parts = []
    for i in range(n):
        ring = FrameRing(slots=S, frame_bytes=F)
        for j in range(int(rng.integers(1, 4))):
            ring.push_broadcast(b"b%d-%d" % (i, j),
                                int(rng.integers(1, 8)))
        parts.append(ring.take_batch())
    batch = IngressBatch(
        *[jnp.asarray(np.stack([getattr(p, f) for p in parts]))
          for f in ("bytes_", "kind", "length", "topic_mask", "dest",
                    "valid")])
    direct = None
    if with_direct:
        dparts = []
        for i in range(n):
            d = DirectBuckets(n, capacity=4, frame_bytes=F)
            d.push((i + 1) % n, b"d%d" % i, dest_slot=(i + 1) % n)
            d.push((i + 3) % n, b"e%d" % i, dest_slot=(i + 3) % n)
            dparts.append(d.take_batch())
        direct = DirectIngress(
            *[jnp.asarray(np.stack([getattr(p, f) for p in dparts]))
              for f in ("bytes_", "length", "dest", "valid")])
    return state, batch, direct


def test_fused_tick_matches_per_array_and_counts_one_collective():
    """ISSUE 8 tentpole: the fused mesh tick (one packed all_gather) is
    bit-identical to the per-array collective schedule, and the lowered
    program contains EXACTLY one collective op (vs a dozen-plus for the
    per-array form) — the counted one-collective-per-tick invariant."""
    import jax

    from pushcdn_tpu.parallel import router as router_mod
    from pushcdn_tpu.parallel.router import count_collectives

    n = 8
    mesh = make_broker_mesh(n)
    state, batch, direct = _seeded_mesh_inputs(n, seed=3)
    live = jnp.ones((n, n), bool)

    step_f = make_mesh_lane_step(mesh, fused=True)
    step_u = make_mesh_lane_step(mesh, fused=False)
    out_f = step_f(state, (batch,), (direct,), live)
    out_u = step_u(state, (batch,), (direct,), live)
    for get in (lambda o: o.lanes[0].deliver,
                lambda o: o.lanes[0].gathered_bytes,
                lambda o: o.lanes[0].gathered_length,
                lambda o: o.direct_lanes[0].deliver,
                lambda o: o.direct_lanes[0].gathered_bytes,
                lambda o: o.state.crdt.owners,
                lambda o: o.state.topic_masks,
                lambda o: o.evictions):
        np.testing.assert_array_equal(np.asarray(get(out_f)),
                                      np.asarray(get(out_u)))

    # lowered-program collective count: fused == 1, per-array >> 1
    low_f = jax.jit(step_f).lower(state, (batch,), (direct,),
                                  live).as_text()
    low_u = jax.jit(step_u).lower(state, (batch,), (direct,),
                                  live).as_text()
    assert count_collectives(low_f) == 1, low_f.count("all_gather")
    assert count_collectives(low_u) > 1

    # trace-time counter agrees: tracing a fresh fused program adds
    # exactly one collective call site
    before = router_mod.trace_collectives()
    state2, batch2, direct2 = _seeded_mesh_inputs(n, seed=4)
    step_f2 = make_mesh_lane_step(mesh, fused=True, gather_bytes=False)
    step_f2(state2, (batch2,), (direct2,), live)
    assert router_mod.trace_collectives() - before == 1


def test_fused_tick_liveness_and_eviction_equivalence():
    """Dead-shard masking and ownership-eviction semantics survive the
    fused packing unchanged."""
    n = 8
    mesh = make_broker_mesh(n)
    state, batch, direct = _seeded_mesh_inputs(n, seed=9)
    # shard 2 and 5 dead; shard 1 re-claims user 0 at a higher version
    owners = np.asarray(state.crdt.owners).copy()
    versions = np.asarray(state.crdt.versions).copy()
    ids = np.asarray(state.crdt.identities).copy()
    owners[1, 0], versions[1, 0], ids[1, 0] = 1, 5, 1
    state = RouterState(
        CrdtState(jnp.asarray(owners), jnp.asarray(versions),
                  jnp.asarray(ids)), state.topic_masks)
    live = np.ones((n, n), bool)
    live[:, 2] = False
    live[:, 5] = False
    live = jnp.asarray(live)
    out_f = make_mesh_lane_step(mesh, fused=True)(
        state, (batch,), (direct,), live)
    out_u = make_mesh_lane_step(mesh, fused=False)(
        state, (batch,), (direct,), live)
    for get in (lambda o: o.lanes[0].deliver,
                lambda o: o.direct_lanes[0].deliver,
                lambda o: o.state.crdt.owners,
                lambda o: o.state.crdt.versions,
                lambda o: o.state.topic_masks,
                lambda o: o.evictions):
        np.testing.assert_array_equal(np.asarray(get(out_f)),
                                      np.asarray(get(out_u)))
    # the dead shards' slots tombstoned, eviction reported at shard 0
    merged = np.asarray(out_f.state.crdt.owners)
    assert (merged[:, 2] == ABSENT).all()
    assert (merged[:, 5] == ABSENT).all()
    assert np.asarray(out_f.evictions)[0, 0]
