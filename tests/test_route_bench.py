"""CI tier for the cut-through routing bench (ISSUE 3): run the ACTUAL
``benches/route_bench.py`` in smoke mode as a subprocess — the same
tested-artifact treatment ``tests/test_local_cluster.py`` gives the
deploy recipe. Asserts the JSON rows parse, both implementations emit a
plan-tier row, and the end-to-end forward tier routed real traffic.

The ≥2x acceptance ratio is a BENCH number (recorded in BASELINE.md), not
a CI gate: shared-core CI machines throttle unpredictably, and a perf
assertion here would flake. What IS asserted: the native tier ran (when
the kernel compiles here) and produced a sane positive rate.

Runtime: sub-second warm; a cold .build pays one g++ run (~2-5 s), still
inside the ≤10 s smoke budget.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "benches", "route_bench.py")


def test_route_bench_smoke(tmp_path):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    # the _r99 suffix pins the artifact's round stamp via the filename
    # (the real producer path) — asserting the bare-name fallback
    # constant went stale every PR round
    out_json = str(tmp_path / "BENCH_r99.json")
    proc = subprocess.run(
        [sys.executable, SCRIPT, "--quick", "--churn-rows",
         "--out-json", out_json],
        env=env, capture_output=True, text=True, timeout=240)
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, f"route_bench failed:\n{out[-4000:]}"
    rows = [json.loads(line) for line in proc.stdout.splitlines()
            if line.startswith("{")]
    by_bench: dict = {}
    for r in rows:
        by_bench.setdefault(r["bench"], []).append(r)
    assert "route/plan" in by_bench, rows
    assert "route/forward" in by_bench, rows
    plan_impls = {r["impl"] for r in by_bench["route/plan"]}
    assert "python" in plan_impls, rows
    for r in by_bench["route/forward"]:
        assert r["value"] > 0, r
    # when the native kernel compiled here, its rows must be present and
    # positive (the A/B exists); a host without a working g++ degrades
    from pushcdn_tpu.native import routeplan
    if routeplan.available():
        assert "native" in plan_impls, rows
        native_plan = [r for r in by_bench["route/plan"]
                       if r["impl"] == "native"][0]
        assert native_plan["unit"] == "msgs/s" and native_plan["value"] > 0
        assert any(r.get("tier") == "plan" for r in
                   by_bench.get("route/ratio", [])), rows
    # ISSUE 4: the trace-overhead A/B rows (tracing off vs on at the
    # default 1/1024 sampling) must be present and positive — the ≤2%
    # budget itself is a BENCH number (BASELINE.md), not a CI gate
    assert "route/trace_overhead" in by_bench, rows
    tr_rows = {r.get("trace"): r for r in by_bench["route/trace_overhead"]
               if r["unit"] == "msgs/s"}
    if not any(r["unit"] == "skipped"
               for r in by_bench["route/trace_overhead"]):
        assert {"off", "on"} <= set(tr_rows), rows
        assert tr_rows["off"]["value"] > 0 and tr_rows["on"]["value"] > 0
        assert tr_rows["on"].get("sample") == 1024
        assert any(r.get("tier") == "on-vs-off"
                   for r in by_bench["route/trace_overhead"])
    # ISSUE 5: the whole-plane (profiler + tracing + e2e histogram)
    # overhead A/B and the e2e percentile rows
    assert "route/profiler_overhead" in by_bench, rows
    if not any(r["unit"] == "skipped"
               for r in by_bench["route/profiler_overhead"]):
        planes = {r.get("plane") for r in by_bench["route/profiler_overhead"]
                  if r["unit"] == "msgs/s"}
        assert {"off", "on"} <= planes, rows
        assert "route/e2e_latency" in by_bench, rows
        e2e_tiers = {r["tier"] for r in by_bench["route/e2e_latency"]}
        assert {"p50", "p99"} <= e2e_tiers, rows
    # ISSUE 7: the sustained-churn A/B (incremental deltas vs the
    # rebuild-guard baseline) and the synthetic 1M-subscription harness.
    # The ≥2x ratio is a BENCH number (BASELINE.md), not a CI gate —
    # asserted here: both modes ran, the incremental mode actually
    # applied deltas in place, the baseline actually rebuilt, and the
    # harness stayed inside its memory ceiling with the loop-lag check
    # green.
    assert "route/churn_forward" in by_bench, rows
    if not any(r["unit"] == "skipped"
               for r in by_bench["route/churn_forward"]):
        churn_rows = {r.get("mode"): r
                      for r in by_bench["route/churn_forward"]
                      if r["unit"] == "msgs/s"}
        assert {"incremental", "rebuild"} <= set(churn_rows), rows
        inc, reb = churn_rows["incremental"], churn_rows["rebuild"]
        assert inc["value"] > 0 and reb["value"] > 0
        assert inc["deltas_applied"] > 0, inc
        assert "incremental_disabled" in reb["rebuilds"], reb
        assert any(r.get("tier") == "incremental-vs-rebuild"
                   for r in by_bench["route/churn_forward"]), rows
        assert "route/million" in by_bench, rows
        million = {r["tier"]: r for r in by_bench["route/million"]}
        assert {"build", "churn", "reconnect_storm", "memory"} \
            <= set(million), rows
        assert million["churn"]["deltas_applied"] > 0
        mem = million["memory"]
        assert mem["value"] <= mem["ceiling_mib"], mem
        assert mem["loop_lag_green"] is True, mem
    # ISSUE 6: the multi-process shard-scaling tier (real broker binary
    # with --shards N over TCP). Flat ratios are legal on a 1-core CI
    # host — asserted here: the rows exist, parse, and carry the honest
    # cpu-count label; the scaling figure itself is a BENCH number.
    assert "route/shard_forward" in by_bench, rows
    shard_rows = {r["shards"]: r for r in by_bench["route/shard_forward"]
                  if r["unit"] == "msgs/s"}
    if not any(r["unit"] == "skipped"
               for r in by_bench["route/shard_forward"]):
        assert {1, 2} <= set(shard_rows), rows
        for r in shard_rows.values():
            assert r["value"] > 0 and r["cpus"] >= 1 \
                and r["backend"] == "cpu", r
        assert any(r.get("tier") == "shards2-vs-1"
                   for r in by_bench["route/shard_forward"]), rows
    # ISSUE 8: the device data plane rows — dense-vs-ragged A/B on the
    # CPU twin (uniform AND zipf popularity, honestly labeled) and the
    # one-collective fused mesh tick (dryrun). The ragged-ahead-at-skew
    # figure is a BENCH number (BASELINE.md); asserted here: both impls
    # ran per popularity (or a labeled skip), labels are honest, and the
    # fused tick counted EXACTLY one collective.
    assert "device/delivery" in by_bench, rows
    dl = [r for r in by_bench["device/delivery"] if r["unit"] == "msgs/s"]
    if dl:
        pairs_seen = {(r["impl"], r["popularity"]) for r in dl}
        for pop in ("uniform", "zipf"):
            assert {("dense", pop), ("ragged", pop)} <= pairs_seen, rows
        for r in dl:
            assert r["value"] > 0 and r["backend"] == "cpu" \
                and r["mode"] == "cpu-twin", r
        # both ordering contracts measured and labeled (strict = the
        # DevicePlane default, per-topic = the relaxed fast path)
        orders = {r.get("order") for r in dl if r["impl"] == "ragged"}
        assert {"strict", "per-topic"} <= orders, rows
        tiers = {r.get("tier") for r in by_bench["device/delivery"]}
        assert "ragged-vs-dense-zipf" in tiers, rows
    # the Pallas row is either a real interpreter measurement or a
    # labeled skip — never a mislabeled A/B
    pal = [r for r in by_bench["device/delivery"]
           if r.get("impl") == "ragged-pallas-interpret"]
    for r in pal:
        assert r["unit"] == "skipped" or "NOT a chip measurement" \
            in r.get("note", ""), r
    assert "device/mesh_tick" in by_bench, rows
    mt = {r["impl"]: r for r in by_bench["device/mesh_tick"]
          if r["unit"] == "ticks/s"}
    if not any(r["unit"] == "skipped"
               for r in by_bench["device/mesh_tick"]):
        assert {"fused", "per-array"} <= set(mt), rows
        assert mt["fused"]["collectives"] == 1, mt["fused"]
        assert mt["per-array"]["collectives"] > 1, mt["per-array"]
        for r in mt.values():
            assert r["mode"] == "dryrun" and r["backend"] == "cpu", r
        assert mt["fused"]["deliveries"] == mt["per-array"]["deliveries"]
    # ISSUE 8 satellite: the 8-receiver row through the real client
    # decode (zero-copy receive_messages path)
    assert "route/forward_decoded" in by_bench, rows
    for r in by_bench["route/forward_decoded"]:
        if r["unit"] == "msgs/s":
            assert r["value"] > 0 and r["decode"] == "receive_messages", r

    # ISSUE 17: the fused-pump rows — either a real pump-off vs pump-auto
    # A/B (forward rate + interpreter-transition attribution + hit ratio)
    # or a loudly-skipped row naming the dead layer; never a mislabeled
    # A/B. The speedup figure itself is a BENCH number, not a CI gate.
    assert "route/pump_forward" in by_bench, rows
    pump_fwd = by_bench["route/pump_forward"]
    if any(r["unit"] == "skipped" for r in pump_fwd):
        assert all(r.get("reason") for r in pump_fwd
                   if r["unit"] == "skipped"), pump_fwd
    else:
        legs = {r.get("pump"): r for r in pump_fwd if r["unit"] == "msgs/s"}
        assert {"off", "on"} <= set(legs), rows
        for r in legs.values():
            assert r["value"] > 0 and r["io_impl"] == "uring" \
                and r["route_impl"] == "native", r
        assert "route/pump_attribution" in by_bench, rows
        attr = by_bench["route/pump_attribution"]
        trans = {r.get("pump"): r for r in attr
                 if r["unit"] == "transitions/kmsg"}
        assert {"off", "on"} <= set(trans), attr
        hit = [r for r in attr if r["unit"] == "hit-ratio"]
        assert hit and hit[0]["pump_frames"] > 0, attr
        assert any(r.get("tier") == "forward_tcp"
                   for r in by_bench.get("route/pump_ratio", [])), rows

    # ISSUE 5 satellite: the machine-readable bench artifact was written
    # with the headline block (the BENCH_r10.json producer)
    with open(out_json) as fh:
        doc = json.load(fh)
    assert doc["round"] == 99
    assert "route_bench" in doc
    assert isinstance(doc["route_bench"]["rows"], list)
    assert "headline" in doc["route_bench"]
