"""C++ framing kernel tests: compiled-on-demand, equivalent to the Python
paths (native/framing.cpp via ctypes)."""

import struct

import numpy as np
import pytest

from pushcdn_tpu import native
from pushcdn_tpu.parallel.frames import FrameRing
from pushcdn_tpu.proto.message import KIND_BROADCAST, KIND_DIRECT

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native lib failed to compile")


def test_pack_frames_matches_python_ring():
    payloads = [b"alpha", b"beta" * 10, b"", b"x" * 64]
    kinds = [KIND_BROADCAST, KIND_DIRECT, KIND_BROADCAST, KIND_DIRECT]
    tmasks = [0b1, 0, 0b10, 0]
    dests = [-1, 5, -1, 7]

    ring_native = FrameRing(slots=8, frame_bytes=64)
    n = ring_native.push_batch(payloads, kinds, tmasks, dests)
    assert n == 4
    native_batch = ring_native.take_batch()

    ring_py = FrameRing(slots=8, frame_bytes=64)
    for p, k, t, d in zip(payloads, kinds, tmasks, dests):
        if k == KIND_BROADCAST:
            ring_py.push_broadcast(p, t)
        else:
            ring_py.push_direct(p, d)
    py_batch = ring_py.take_batch()

    np.testing.assert_array_equal(native_batch.bytes_, py_batch.bytes_)
    np.testing.assert_array_equal(native_batch.kind, py_batch.kind)
    np.testing.assert_array_equal(native_batch.length, py_batch.length)
    np.testing.assert_array_equal(native_batch.topic_mask, py_batch.topic_mask)
    np.testing.assert_array_equal(native_batch.dest, py_batch.dest)
    np.testing.assert_array_equal(native_batch.valid, py_batch.valid)


def test_push_batch_rejects_oversized_payload_up_front():
    ring = FrameRing(slots=8, frame_bytes=16)
    with pytest.raises(ValueError, match="host path"):
        ring.push_batch([b"ok", b"z" * 17], [5, 5], [1, 1], [-1, -1])
    # nothing was partially packed
    assert ring.free_slots == 8


def test_push_batch_rejects_length_mismatch():
    ring = FrameRing(slots=8, frame_bytes=16)
    with pytest.raises(ValueError, match="mismatch"):
        ring.push_batch([b"a", b"b"], [5], [1, 1], [-1, -1])


def test_push_batch_ring_full_means_requeue():
    ring = FrameRing(slots=2, frame_bytes=16)
    n = ring.push_batch([b"a", b"b", b"c"], [5] * 3, [1] * 3, [-1] * 3)
    assert n == 2  # unambiguous: ring full, re-queue the rest
    batch = ring.take_batch()
    assert batch.num_valid == 2


def test_scan_frames_roundtrip_with_encode():
    payloads = [b"one", b"two two", b"", b"\x00" * 100]
    stream = native.encode_frames(payloads)
    # matches the transport's hand-rolled framing exactly
    expect = b"".join(struct.pack(">I", len(p)) + p for p in payloads)
    assert stream == expect

    frames, consumed, error = native.scan_frames(stream, max_frame_len=1024)
    assert not error
    assert consumed == len(stream)
    assert [stream[o:o + l] for o, l in frames] == payloads


def test_scan_partial_frame_waits():
    stream = native.encode_frames([b"complete"]) + b"\x00\x00\x00\x08part"
    frames, consumed, error = native.scan_frames(stream, max_frame_len=1024)
    assert not error
    assert len(frames) == 1
    assert consumed == len(native.encode_frames([b"complete"]))


def test_scan_flags_oversized_frame():
    stream = struct.pack(">I", 10_000) + b"x" * 10
    frames, consumed, error = native.scan_frames(stream, max_frame_len=1000)
    assert error
    assert frames == []


# ---------------------------------------------------------------------------
# egress engine (pushcdn_egress_count / _fill via native.egress_encode)
# ---------------------------------------------------------------------------

def _egress_reference(deliver, lengths, blocks):
    """Per-user wire streams, the obvious way: concat u32-BE len ‖ payload
    for every delivered frame in frame order."""
    import numpy as np
    U, N = deliver.shape
    rows = blocks[0].shape[0]
    out = {}
    for u in range(U):
        stream = bytearray()
        count = 0
        for n in range(N):
            if deliver[u, n]:
                ln = int(lengths[n])
                payload = bytes(blocks[n // rows][n % rows, :ln])
                stream += struct.pack(">I", ln) + payload
                count += 1
        if count:
            out[u] = (bytes(stream), count)
    return out


def test_egress_encode_matches_reference():
    import numpy as np
    rng = np.random.default_rng(7)
    U, B, S, F = 16, 4, 9, 64  # S*B = 36: exercises the non-multiple-of-8 tail
    blocks = [rng.integers(0, 256, (S, F), dtype=np.uint8) for _ in range(B)]
    N = B * S
    lengths = rng.integers(0, F + 1, N).astype(np.int32)
    deliver = rng.random((U, N)) < 0.3
    deliver[:, lengths == 0] = False  # empty slots never deliver
    streams = native.egress_encode(deliver, lengths, blocks)
    if streams is None:
        pytest.skip("native library unavailable")
    ref = _egress_reference(deliver, lengths, blocks)
    assert sorted(streams.users) == sorted(ref)
    for u in streams.users:
        assert bytes(streams.stream(u)) == ref[u][0]
        assert int(streams.msgs[u]) == ref[u][1]
    assert streams.total_msgs == sum(c for _, c in ref.values())


def test_egress_encode_empty_matrix():
    import numpy as np
    deliver = np.zeros((8, 16), bool)
    lengths = np.zeros(16, np.int32)
    blocks = [np.zeros((8, 32), np.uint8), np.zeros((8, 32), np.uint8)]
    streams = native.egress_encode(deliver, lengths, blocks)
    if streams is None:
        pytest.skip("native library unavailable")
    assert streams.users == []
    assert streams.total_msgs == 0


def test_egress_encode_dense_single_user():
    import numpy as np
    F = 16
    block = np.arange(3 * F, dtype=np.uint8).reshape(3, F)
    lengths = np.array([F, 5, 0], np.int32)
    deliver = np.array([[True, True, False], [False, False, False]])
    streams = native.egress_encode(deliver, lengths, [block])
    if streams is None:
        pytest.skip("native library unavailable")
    assert streams.users == [0]
    expect = (struct.pack(">I", F) + bytes(block[0]) +
              struct.pack(">I", 5) + bytes(block[1, :5]))
    assert bytes(streams.stream(0)) == expect


def test_push_batch_multiword_mask_expansion_and_memo():
    """Multi-word topic masks expand to the exact little-endian u32 words
    through the memoized row cache — uniform, mixed, and out-of-range
    (truncating, matching the old per-word shift loop) mask batches."""
    W = 8
    ring = FrameRing(slots=16, frame_bytes=32, topic_words=W)
    big = (1 << 200) | (1 << 37) | 0b101     # spans words 0, 1, and 6
    over = (1 << (32 * W)) | 0b11            # bit above the topic space
    neg = -1                                 # pathological caller input
    masks = [big, big, over, neg, 0b1]       # uniform run + mixed tail
    n = ring.push_batch([b"m"] * 5, [KIND_BROADCAST] * 5, masks, [-1] * 5)
    assert n == 5
    batch = ring.take_batch()
    allbits = (1 << (32 * W)) - 1
    for i, m in enumerate(masks):
        expect = [(int(m) & allbits) >> (32 * w) & 0xFFFFFFFF
                  for w in range(W)]
        assert list(batch.topic_mask[i]) == expect, (i, m)

    # uniform-mask fast path fills every row identically
    ring2 = FrameRing(slots=16, frame_bytes=32, topic_words=W)
    assert ring2.push_batch([b"u"] * 6, [KIND_BROADCAST] * 6,
                            [big] * 6, [-1] * 6) == 6
    b2 = ring2.take_batch()
    rows = b2.topic_mask[:6]
    assert (rows == rows[0]).all()
    assert list(rows[0]) == [(big >> (32 * w)) & 0xFFFFFFFF
                             for w in range(W)]
