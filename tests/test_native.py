"""C++ framing kernel tests: compiled-on-demand, equivalent to the Python
paths (native/framing.cpp via ctypes)."""

import struct

import numpy as np
import pytest

from pushcdn_tpu import native
from pushcdn_tpu.parallel.frames import FrameRing
from pushcdn_tpu.proto.message import KIND_BROADCAST, KIND_DIRECT

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native lib failed to compile")


def test_pack_frames_matches_python_ring():
    payloads = [b"alpha", b"beta" * 10, b"", b"x" * 64]
    kinds = [KIND_BROADCAST, KIND_DIRECT, KIND_BROADCAST, KIND_DIRECT]
    tmasks = [0b1, 0, 0b10, 0]
    dests = [-1, 5, -1, 7]

    ring_native = FrameRing(slots=8, frame_bytes=64)
    n = ring_native.push_batch(payloads, kinds, tmasks, dests)
    assert n == 4
    native_batch = ring_native.take_batch()

    ring_py = FrameRing(slots=8, frame_bytes=64)
    for p, k, t, d in zip(payloads, kinds, tmasks, dests):
        if k == KIND_BROADCAST:
            ring_py.push_broadcast(p, t)
        else:
            ring_py.push_direct(p, d)
    py_batch = ring_py.take_batch()

    np.testing.assert_array_equal(native_batch.bytes_, py_batch.bytes_)
    np.testing.assert_array_equal(native_batch.kind, py_batch.kind)
    np.testing.assert_array_equal(native_batch.length, py_batch.length)
    np.testing.assert_array_equal(native_batch.topic_mask, py_batch.topic_mask)
    np.testing.assert_array_equal(native_batch.dest, py_batch.dest)
    np.testing.assert_array_equal(native_batch.valid, py_batch.valid)


def test_push_batch_rejects_oversized_payload_up_front():
    ring = FrameRing(slots=8, frame_bytes=16)
    with pytest.raises(ValueError, match="host path"):
        ring.push_batch([b"ok", b"z" * 17], [5, 5], [1, 1], [-1, -1])
    # nothing was partially packed
    assert ring.free_slots == 8


def test_push_batch_rejects_length_mismatch():
    ring = FrameRing(slots=8, frame_bytes=16)
    with pytest.raises(ValueError, match="mismatch"):
        ring.push_batch([b"a", b"b"], [5], [1, 1], [-1, -1])


def test_push_batch_ring_full_means_requeue():
    ring = FrameRing(slots=2, frame_bytes=16)
    n = ring.push_batch([b"a", b"b", b"c"], [5] * 3, [1] * 3, [-1] * 3)
    assert n == 2  # unambiguous: ring full, re-queue the rest
    batch = ring.take_batch()
    assert batch.num_valid == 2


def test_scan_frames_roundtrip_with_encode():
    payloads = [b"one", b"two two", b"", b"\x00" * 100]
    stream = native.encode_frames(payloads)
    # matches the transport's hand-rolled framing exactly
    expect = b"".join(struct.pack(">I", len(p)) + p for p in payloads)
    assert stream == expect

    frames, consumed, error = native.scan_frames(stream, max_frame_len=1024)
    assert not error
    assert consumed == len(stream)
    assert [stream[o:o + l] for o, l in frames] == payloads


def test_scan_partial_frame_waits():
    stream = native.encode_frames([b"complete"]) + b"\x00\x00\x00\x08part"
    frames, consumed, error = native.scan_frames(stream, max_frame_len=1024)
    assert not error
    assert len(frames) == 1
    assert consumed == len(native.encode_frames([b"complete"]))


def test_scan_flags_oversized_frame():
    stream = struct.pack(">I", 10_000) + b"x" * 10
    frames, consumed, error = native.scan_frames(stream, max_frame_len=1000)
    assert error
    assert frames == []
