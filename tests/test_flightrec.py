"""Per-connection flight recorder (ISSUE 4): ring semantics, abnormal-
disconnect dumps into the diagnostics log, and the /debug/flightrec
endpoint."""

import asyncio
import logging

from pushcdn_tpu.proto import flightrec


def test_ring_is_bounded_and_ordered():
    rec = flightrec.FlightRecorder("unit", capacity=4)
    for i in range(10):
        rec.record("ev", i)
    assert len(rec) == 4
    trail = rec.trail()
    assert "ev  6" in trail and "ev  9" in trail and "ev  5" not in trail
    assert "flight recorder [unit]" in trail


def test_abnormal_arms_and_maybe_dump_disarms(caplog):
    rec = flightrec.FlightRecorder("unit-2")
    rec.record("connect")
    assert not rec.maybe_dump("clean close")  # unarmed: silent
    rec.record("error", "boom", abnormal=True)
    with caplog.at_level(logging.WARNING, logger="pushcdn.flightrec"):
        assert rec.maybe_dump("io error")
        assert not rec.maybe_dump("second teardown path")  # disarmed
    assert "abnormal disconnect (io error)" in caplog.text
    assert "boom" in caplog.text and "connect" in caplog.text


def test_render_all_lists_live_recorders():
    rec = flightrec.FlightRecorder("render-me")
    rec.record("hello")
    body = flightrec.render_all()
    assert "flight recorder [render-me]" in body
    assert "hello" in body


async def test_malformed_frame_dumps_trail_with_trigger(caplog):
    """The chaos-tier contract: a user feeding the broker garbage is
    disconnected AND the broker logs that connection's flight-recorder
    trail containing the triggering event."""
    from pushcdn_tpu.broker.test_harness import TestDefinition

    run = await TestDefinition(connected_users=[[0]]).run()
    try:
        with caplog.at_level(logging.WARNING, logger="pushcdn.flightrec"):
            try:
                await run.user(0).remote.send_raw(b"\xfegarbage", flush=True)
            except Exception:
                pass  # broker may kill the link before the flush settles
            async with asyncio.timeout(5):
                while run.broker.connections.num_users:
                    await asyncio.sleep(0.02)
            await asyncio.sleep(0.05)
        assert "abnormal disconnect" in caplog.text
        assert "malformed-frame" in caplog.text
        assert "connect" in caplog.text  # the trail shows the life before
    finally:
        await run.shutdown()


async def test_connection_poison_records_and_dumps(caplog):
    """An I/O failure (not a clean FIN) arms the recorder and the poison
    path dumps immediately (nobody may ever tear this handle down)."""
    from pushcdn_tpu.proto.transport.memory import Memory

    listener = await Memory.bind("flightrec-test")
    try:
        accept_task = asyncio.create_task(listener.accept())
        conn = await Memory.connect("flightrec-test")
        server_side = await (await accept_task).finalize()
        with caplog.at_level(logging.WARNING, logger="pushcdn.flightrec"):
            # oversized announced frame: the reader poisons with
            # EXCEEDED_SIZE, which is NOT a clean peer-close
            from pushcdn_tpu.proto import MAX_MESSAGE_SIZE
            bogus = (MAX_MESSAGE_SIZE + 1).to_bytes(4, "big")
            await conn._stream.write(bogus)
            async with asyncio.timeout(5):
                while server_side._error is None:
                    await asyncio.sleep(0.01)
        assert "abnormal disconnect" in caplog.text
        conn.close()
        server_side.close()
    finally:
        await listener.close()
