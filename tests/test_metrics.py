"""Metrics endpoint tests: /metrics prometheus text, /tasks introspection
(parity metrics.rs:18-78 + the tokio-console aux subsystem), plus the
ISSUE 4 registry upgrade: labels, mutator thread-safety under scrapes
racing live updates, build info, the new gauges, /debug/flightrec, and
the supervised-task helper."""

import asyncio
import threading

from pushcdn_tpu.proto import metrics as metrics_mod


async def _get(port: int, path: str) -> tuple[int, str]:
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"GET {path} HTTP/1.1\r\nHost: x\r\n\r\n".encode())
    await writer.drain()
    data = await reader.read(-1)
    writer.close()
    head, _, body = data.partition(b"\r\n\r\n")
    status = int(head.split(b" ")[1])
    return status, body.decode()


async def test_metrics_endpoint_serves_prometheus_text():
    server = await metrics_mod.serve_metrics("127.0.0.1:0")
    port = server.sockets[0].getsockname()[1]
    try:
        metrics_mod.BYTES_SENT.inc(1234)
        status, body = await _get(port, "/metrics")
        assert status == 200
        assert "# TYPE cdn_bytes_sent counter" in body
        assert "cdn_num_users_connected" in body or True  # broker gauges load lazily
        assert "cdn_message_latency_seconds_bucket" in body
    finally:
        server.close()
        await server.wait_closed()


async def test_tasks_endpoint_lists_live_tasks():
    server = await metrics_mod.serve_metrics("127.0.0.1:0")
    port = server.sockets[0].getsockname()[1]

    async def parked():
        await asyncio.sleep(30)

    task = asyncio.create_task(parked(), name="test-parked-task")
    try:
        status, body = await _get(port, "/tasks")
        assert status == 200
        assert "test-parked-task" in body
        assert "[pending]" in body
    finally:
        task.cancel()
        server.close()
        await server.wait_closed()


async def test_unknown_path_404():
    server = await metrics_mod.serve_metrics("127.0.0.1:0")
    port = server.sockets[0].getsockname()[1]
    try:
        status, _ = await _get(port, "/nope")
        assert status == 404
    finally:
        server.close()
        await server.wait_closed()


# ---------------------------------------------------------------------------
# ISSUE 4: labeled registry
# ---------------------------------------------------------------------------

def test_labeled_counter_children_and_total_line():
    c = metrics_mod.Counter("cdn_test_labeled_counter", "t", labels=("k",))
    c.labels(k="a").inc(3)
    c.labels(k="b").inc(4)
    c.inc(1)  # direct parent inc stays legal (unlabeled series)
    body = c.render()
    assert 'cdn_test_labeled_counter{k="a"} 3' in body
    assert 'cdn_test_labeled_counter{k="b"} 4' in body
    assert "cdn_test_labeled_counter 8" in body  # bare total = own + sum
    # children are cached: same object on re-lookup (hot paths hold them)
    assert c.labels(k="a") is c.labels(k="a")
    metrics_mod._REGISTRY.pop("cdn_test_labeled_counter")


def test_labeled_histogram_renders_per_series_buckets():
    h = metrics_mod.Histogram("cdn_test_labeled_hist", "t",
                              buckets=(0.1, 1.0), labels=("hop",))
    h.labels(hop="x").observe(0.05)
    h.labels(hop="x").observe(0.5)
    body = h.render()
    assert 'cdn_test_labeled_hist_bucket{hop="x",le="0.1"} 1' in body
    assert 'cdn_test_labeled_hist_bucket{hop="x",le="+Inf"} 2' in body
    assert 'cdn_test_labeled_hist_count{hop="x"} 2' in body
    metrics_mod._REGISTRY.pop("cdn_test_labeled_hist")


def test_labels_require_declared_names():
    import pytest
    g = metrics_mod.Gauge("cdn_test_label_names", "t", labels=("a",))
    with pytest.raises(KeyError):
        g.labels(b="x")
    with pytest.raises(KeyError):
        g.labels(a="x", b="y")
    metrics_mod._REGISTRY.pop("cdn_test_label_names")


def test_label_values_are_escaped():
    g = metrics_mod.Gauge("cdn_test_label_escape", "t", labels=("v",))
    g.labels(v='say "hi"\nthere').set(1)
    body = g.render()
    assert '\\"hi\\"' in body and "\\n" in body
    metrics_mod._REGISTRY.pop("cdn_test_label_escape")


def test_histogram_observe_is_thread_safe():
    """The satellite fix: off-loop observers (native callers, bench
    threads) must not lose samples in the sum/bucket read-modify-write."""
    h = metrics_mod.Histogram("cdn_test_threaded_hist", "t",
                              buckets=(0.5,))
    N, T = 5_000, 4

    def pound():
        for _ in range(N):
            h.observe(0.25)

    threads = [threading.Thread(target=pound) for _ in range(T)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert h.total == N * T
    assert h.counts[0] == N * T
    assert abs(h.sum - 0.25 * N * T) < 1e-6
    metrics_mod._REGISTRY.pop("cdn_test_threaded_hist")


async def test_concurrent_scrapes_racing_live_updates():
    """Many concurrent /metrics scrapes while counters and histograms are
    updated from the loop AND from a thread: every scrape parses, and
    every histogram snapshot is internally consistent (cumulative buckets
    never exceed the +Inf count)."""
    server = await metrics_mod.serve_metrics("127.0.0.1:0")
    port = server.sockets[0].getsockname()[1]
    stop = threading.Event()

    def pound():
        while not stop.is_set():
            metrics_mod.LATENCY.observe(0.001)
            metrics_mod.BYTES_SENT.labels(transport="test").inc(7)

    thread = threading.Thread(target=pound)
    thread.start()
    try:
        async def hammer():
            for _ in range(5):
                metrics_mod.LATENCY.observe(0.01)
                status, body = await _get(port, "/metrics")
                assert status == 200
                # internal consistency of the racing histogram snapshot
                lines = [ln for ln in body.splitlines()
                         if ln.startswith("cdn_message_latency_seconds")]
                inf = [ln for ln in lines if 'le="+Inf"' in ln]
                count = [ln for ln in lines
                         if ln.startswith("cdn_message_latency_seconds_count")]
                assert inf and count
                assert float(inf[0].rsplit(" ", 1)[1]) == \
                    float(count[0].rsplit(" ", 1)[1])
                cums = [float(ln.rsplit(" ", 1)[1]) for ln in lines
                        if "_bucket" in ln]
                assert cums == sorted(cums)  # cumulative: non-decreasing

        await asyncio.gather(*[hammer() for _ in range(8)])
    finally:
        stop.set()
        thread.join()
        server.close()
        await server.wait_closed()


# ---------------------------------------------------------------------------
# ISSUE 4: new observability surfaces
# ---------------------------------------------------------------------------

async def test_scrape_exposes_build_info_and_new_gauges():
    server = await metrics_mod.serve_metrics("127.0.0.1:0")
    port = server.sockets[0].getsockname()[1]
    try:
        status, body = await _get(port, "/metrics")
        assert status == 200
        assert "cdn_build_info{" in body
        assert 'version="' in body and "device_kind=" in body
        assert 'cdn_writer_queue_depth{stat="sum"}' in body
        assert 'cdn_writer_queue_depth{stat="max"}' in body
        assert "cdn_event_loop_lag_seconds" in body
        assert 'cdn_pool_bytes{state="in_use"}' in body
        assert "cdn_trace_hop_seconds" in body
        assert 'cdn_route_batch_frames{path="cutthrough"}' in body
        assert 'cdn_bls_pk_cache{stat="hits"}' in body
        assert 'cdn_egress_frames{peer="user"}' in body
        # ISSUE 5 families: e2e SLO histogram, native-seam attribution,
        # task-profiler samples
        assert "cdn_e2e_latency_seconds_bucket" in body
        assert 'cdn_native_seconds{kernel="route_plan"}' in body
        assert 'cdn_native_seconds{kernel="egress_encode"}' in body
        assert 'cdn_native_seconds{kernel="bls_verify"}' in body
        assert "cdn_task_samples" in body
    finally:
        server.close()
        await server.wait_closed()


async def test_writer_queue_gauge_tracks_live_connections():
    from pushcdn_tpu.proto.transport.memory import (
        gen_testing_connection_pair,
    )
    a, b = await gen_testing_connection_pair()
    try:
        metrics_mod._refresh_writer_queues()
        base = metrics_mod.WRITER_QUEUE_DEPTH.labels(stat="sum").value
        # park frames in the send queue by never letting the writer run
        # (enqueue without awaiting the loop)
        for _ in range(5):
            a._send_q.put_nowait((b"x", None))
        metrics_mod._refresh_writer_queues()
        assert metrics_mod.WRITER_QUEUE_DEPTH.labels(
            stat="sum").value >= base + 5
        assert metrics_mod.WRITER_QUEUE_DEPTH.labels(stat="max").value >= 5
        while not a._send_q.empty():
            a._send_q.get_nowait()
    finally:
        a.close()
        b.close()


async def test_debug_flightrec_endpoint():
    from pushcdn_tpu.proto import flightrec
    rec = flightrec.FlightRecorder("endpoint-test-rec")
    rec.record("unit-event", "detail-42")
    server = await metrics_mod.serve_metrics("127.0.0.1:0")
    port = server.sockets[0].getsockname()[1]
    try:
        status, body = await _get(port, "/debug/flightrec")
        assert status == 200
        assert "endpoint-test-rec" in body
        assert "unit-event" in body and "detail-42" in body
    finally:
        server.close()
        await server.wait_closed()


async def test_supervised_task_restarts_after_exception():
    runs = []

    async def flaky():
        runs.append(1)
        if len(runs) < 3:
            raise RuntimeError("boom")
        await asyncio.sleep(30)  # healthy: park

    task = asyncio.create_task(
        metrics_mod.supervised(flaky, "flaky-test", restart_delay_s=0.01))
    try:
        async with asyncio.timeout(5):
            while len(runs) < 3:
                await asyncio.sleep(0.01)
    finally:
        task.cancel()
    assert len(runs) >= 3  # died twice, restarted each time


async def test_loop_lag_sampler_reports_stall():
    import time
    task = asyncio.create_task(metrics_mod._loop_lag_sampler(0.1))
    try:
        await asyncio.sleep(0.15)  # sampler running, mid-interval
        time.sleep(0.3)            # hog the loop synchronously
        # let several on-time wakeups land AFTER the stall: the peak must
        # survive them until a scrape publishes-and-resets it
        await asyncio.sleep(0.25)
        metrics_mod._refresh_loop_lag()  # what a /metrics render runs
        assert metrics_mod.EVENT_LOOP_LAG.value >= 0.05
        metrics_mod._refresh_loop_lag()  # next scrape: peak was reset
        assert metrics_mod.EVENT_LOOP_LAG.value < 0.05
    finally:
        task.cancel()


async def test_pump_metrics_exposed_after_pumped_traffic():
    """ISSUE 17 observability: after a fused-pump run the exposition
    carries ``cdn_route_batch_frames{path="pump"}`` with the natively
    pumped frame count and ``cdn_pump_escalations{reason="fenced"}``
    for the frames diverted by a Python-queue fence."""
    import os

    import pytest

    from pushcdn_tpu.broker.tasks import cutthrough
    from pushcdn_tpu.broker.test_harness import TestDefinition
    from pushcdn_tpu.native import pump as npump
    from pushcdn_tpu.native import uring as nuring
    from pushcdn_tpu.proto.message import Broadcast, serialize
    from pushcdn_tpu.proto.transport import pump as pump_mod
    from pushcdn_tpu.proto.transport import uring as umod

    if not (nuring.available() and npump.available()
            and cutthrough.routeplan.available()):
        pytest.skip("fused pump unavailable on this host")

    saved_env = os.environ.get("PUSHCDN_PUMP")
    saved = (umod._resolved, umod._warned_demote, cutthrough.ROUTE_IMPL,
             pump_mod.PUMP_IMPL, pump_mod._warned_demote)
    umod.set_io_impl("uring")
    cutthrough.ROUTE_IMPL = "native"
    pump_mod.set_pump_impl("auto")
    try:
        run = await TestDefinition(
            connected_users=[[], [0], [0]], tcp_users=True,
            metrics_bind_endpoint="127.0.0.1:0").run()
        try:
            port = run.broker._metrics_server.sockets[0].getsockname()[1]
            sender = run.user(0).remote
            frame = serialize(Broadcast([0], b"pump-metrics"))
            for _ in range(3):  # waves with idle gaps: pump engages
                await sender.send_raw_many([frame] * 16)
                await asyncio.sleep(0.15)
            ps = run.broker._route_state._pump_state
            assert ps is not None and ps.summary()["pump_frames"] > 0
            # force a deterministic "fenced" escalation: a Python-queued
            # frame fences the peer while a pumped wave is planned
            key = run.connected_users[1].public_key
            conn = run.broker.connections.get_user_connection(key)
            async with conn._write_mutex:
                await conn.send_raw(serialize(Broadcast([0], b"mark")))
                await sender.send_raw_many([frame] * 16)
                await asyncio.sleep(0.2)
            status, body = await _get(port, "/metrics")
            assert status == 200
        finally:
            await run.shutdown()
            umod.UringEngine.shutdown()
    finally:
        if saved_env is None:
            os.environ.pop("PUSHCDN_PUMP", None)
        else:
            os.environ["PUSHCDN_PUMP"] = saved_env
        (umod._resolved, umod._warned_demote, cutthrough.ROUTE_IMPL,
         pump_mod.PUMP_IMPL, pump_mod._warned_demote) = saved

    pump_line = [ln for ln in body.splitlines()
                 if ln.startswith('cdn_route_batch_frames{path="pump"}')]
    assert pump_line, "pump path missing from cdn_route_batch_frames"
    assert float(pump_line[0].split()[-1]) > 0
    fenced = [ln for ln in body.splitlines()
              if ln.startswith('cdn_pump_escalations{reason="fenced"}')]
    assert fenced, "fenced escalation series missing"
    assert float(fenced[0].split()[-1]) > 0
    assert "# TYPE cdn_pump_escalations counter" in body


# ---------------------------------------------------------------------------
# ISSUE 19 acceptance: per-class writer-queue delay separation
# ---------------------------------------------------------------------------

def _delay_hist_state(child):
    return list(child.counts), child.total


def _delay_p99_delta(child, before):
    """p99 upper bound over the (before -> now) window of a fixed-bucket
    histogram child: the le edge of the bucket the 99th-percentile
    sample landed in (+Inf window -> inf)."""
    import math

    b_counts, b_total = before
    deltas = [a - b for a, b in zip(child.counts, b_counts)]
    total = child.total - b_total
    if total == 0:
        return 0.0
    rank = math.ceil(0.99 * total)
    cum = 0
    for i, c in enumerate(deltas):
        cum += c
        if cum >= rank:
            return child.buckets[i] if i < len(child.buckets) \
                else float("inf")
    return float("inf")


async def test_writer_queue_delay_separates_bulk_flood_from_consensus():
    """``cdn_writer_queue_delay_seconds{class}`` must separate a
    bulk-replay flood from concurrent consensus traffic on the SAME
    link: a replay burst queues thousands of frames at once, so its
    tail waits behind its own serialization (the writer drains <=512
    entries per wakeup), while the sparse consensus frame enqueued in
    the same loop tick rides the first batch out — seeded sizes, a
    bandwidth-throttled stream, and bulk p99 >> consensus p99."""
    import random

    from pushcdn_tpu.proto.transport.memory import (
        Memory,
        gen_testing_connection_pair,
    )

    rng = random.Random(1911)
    # window large enough that the duplex buffer never backpressures:
    # the only bandwidth limit is the throttle below, so the measured
    # delays are the burst's own serialization time, deterministically
    prev_win = Memory.set_duplex_window(64 * 1024 * 1024)
    a, b = await gen_testing_connection_pair()
    sec_per_byte = 4e-8  # ~25 MB/s link

    orig_write = a._stream.write

    async def throttled_write(data, *owner):
        await orig_write(data, *owner)
        await asyncio.sleep(len(data) * sec_per_byte)

    a._stream.write = throttled_write

    cons_child = metrics_mod.WRITER_QUEUE_DELAY_CLS[1]
    bulk_child = metrics_mod.WRITER_QUEUE_DELAY_CLS[3]
    cons_before = _delay_hist_state(cons_child)
    bulk_before = _delay_hist_state(bulk_child)
    try:
        for _ in range(3):
            # consensus request in flight when the replay burst lands:
            # enqueued in the SAME tick, ahead of the flood
            await a.send_raw(b"consensus-vote", cls=1)
            flood = rng.randrange(1200, 1400)
            payload = bytes(4096)
            for _ in range(flood):
                a.send_raw_nowait(payload, cls=3)
            # settle the round: a flushed control frame resolves only
            # after the flood fully serialized, so the next round's
            # consensus frame meets an IDLE writer, not the tail flush
            # of this one (control is not a measured class here)
            async with asyncio.timeout(30):
                await a.send_raw(b"round-sync", cls=0, flush=True)
        cons_p99 = _delay_p99_delta(cons_child, cons_before)
        bulk_p99 = _delay_p99_delta(bulk_child, bulk_before)
        cons_n = cons_child.total - cons_before[1]
        bulk_n = bulk_child.total - bulk_before[1]
        assert cons_n == 3 and bulk_n >= 3600, (cons_n, bulk_n)
        assert bulk_p99 != float("inf"), "bulk delay blew the 5s bucket"
        assert cons_p99 <= 0.01, f"consensus p99 {cons_p99} not sparse"
        assert bulk_p99 >= 10 * max(cons_p99, 1e-3), (
            f"classes not separated: bulk p99 {bulk_p99} vs "
            f"consensus p99 {cons_p99}")
    finally:
        a._stream.write = orig_write
        a.close()
        b.close()
        Memory.set_duplex_window(prev_win)
