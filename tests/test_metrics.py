"""Metrics endpoint tests: /metrics prometheus text, /tasks introspection
(parity metrics.rs:18-78 + the tokio-console aux subsystem)."""

import asyncio

from pushcdn_tpu.proto import metrics as metrics_mod


async def _get(port: int, path: str) -> tuple[int, str]:
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"GET {path} HTTP/1.1\r\nHost: x\r\n\r\n".encode())
    await writer.drain()
    data = await reader.read(-1)
    writer.close()
    head, _, body = data.partition(b"\r\n\r\n")
    status = int(head.split(b" ")[1])
    return status, body.decode()


async def test_metrics_endpoint_serves_prometheus_text():
    server = await metrics_mod.serve_metrics("127.0.0.1:0")
    port = server.sockets[0].getsockname()[1]
    try:
        metrics_mod.BYTES_SENT.inc(1234)
        status, body = await _get(port, "/metrics")
        assert status == 200
        assert "# TYPE cdn_bytes_sent counter" in body
        assert "cdn_num_users_connected" in body or True  # broker gauges load lazily
        assert "cdn_message_latency_seconds_bucket" in body
    finally:
        server.close()
        await server.wait_closed()


async def test_tasks_endpoint_lists_live_tasks():
    server = await metrics_mod.serve_metrics("127.0.0.1:0")
    port = server.sockets[0].getsockname()[1]

    async def parked():
        await asyncio.sleep(30)

    task = asyncio.create_task(parked(), name="test-parked-task")
    try:
        status, body = await _get(port, "/tasks")
        assert status == 200
        assert "test-parked-task" in body
        assert "[pending]" in body
    finally:
        task.cancel()
        server.close()
        await server.wait_closed()


async def test_unknown_path_404():
    server = await metrics_mod.serve_metrics("127.0.0.1:0")
    port = server.sockets[0].getsockname()[1]
    try:
        status, _ = await _get(port, "/nope")
        assert status == 404
    finally:
        server.close()
        await server.wait_closed()
