"""CI lint gate (ISSUE 4 satellite): run ``ruff check`` over the package,
tests, benches and scripts with the repo's ruff.toml baseline, so new
instrumentation code lands lint-clean.

The container image may not ship ruff (it is not pip-installable here);
in that case the test SKIPS with an explicit reason rather than
vacuously passing — the gate engages wherever ruff exists.
"""

import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ruff_cmd():
    exe = shutil.which("ruff")
    if exe:
        return [exe]
    try:
        import ruff  # noqa: F401
        return [sys.executable, "-m", "ruff"]
    except ImportError:
        return None


# the directories the gate covers — every new observability file (ISSUE 5:
# proto/health.py, scripts/trace_report.py, tests/test_health.py,
# tests/test_trace_report.py) lives inside them and is asserted present
# below so a future move out of the linted tree fails loudly
RUFF_SCOPE = ["pushcdn_tpu", "tests", "benches", "scripts", "bench.py"]

ISSUE5_FILES = [
    "pushcdn_tpu/proto/health.py",
    "scripts/trace_report.py",
    "tests/test_health.py",
    "tests/test_trace_report.py",
]


ISSUE13_FILES = [
    # the io_uring host data plane (ISSUE 13): native layer, ctypes
    # binding, transport engine, syscall-attribution interposer binding,
    # and the equivalence/fault suite
    "pushcdn_tpu/proto/transport/uring.py",
    "pushcdn_tpu/native/uring.py",
    "pushcdn_tpu/native/syscount.py",
    "pushcdn_tpu/testing/routebench.py",
    "tests/test_uring.py",
]


def test_issue5_files_inside_lint_scope():
    for rel in ISSUE5_FILES:
        assert os.path.exists(os.path.join(REPO, rel)), rel
        assert any(rel == scope or rel.startswith(scope + "/")
                   for scope in RUFF_SCOPE), \
            f"{rel} is outside the ruff gate's scope {RUFF_SCOPE}"


ISSUE14_FILES = [
    # durable topics (ISSUE 14): retention rings + replay subscribe +
    # wildcard namespace, the seeded handover/lease suite, and the
    # consensus replay_catchup scenario wiring
    "pushcdn_tpu/broker/retention.py",
    "pushcdn_tpu/proto/topic.py",
    "tests/test_retention.py",
    "benches/consensus_bench.py",
]


def test_issue14_files_inside_lint_scope():
    for rel in ISSUE14_FILES:
        assert os.path.exists(os.path.join(REPO, rel)), rel
        assert any(rel == scope or rel.startswith(scope + "/")
                   for scope in RUFF_SCOPE), \
            f"{rel} is outside the ruff gate's scope {RUFF_SCOPE}"


def test_issue13_files_inside_lint_scope():
    for rel in ISSUE13_FILES:
        assert os.path.exists(os.path.join(REPO, rel)), rel
        assert any(rel == scope or rel.startswith(scope + "/")
                   for scope in RUFF_SCOPE), \
            f"{rel} is outside the ruff gate's scope {RUFF_SCOPE}"


ISSUE17_FILES = [
    # the fused data-plane pump (ISSUE 17): native composition kernel,
    # ctypes binding, policy plane, and the fault/equivalence suites
    "native/pump.cpp",
    "pushcdn_tpu/native/pump.py",
    "pushcdn_tpu/proto/transport/pump.py",
    "tests/test_uring.py",
    "tests/test_route_cutthrough.py",
]


def test_issue17_files_inside_lint_scope():
    for rel in ISSUE17_FILES:
        assert os.path.exists(os.path.join(REPO, rel)), rel
        if rel.endswith(".cpp"):
            continue  # native sources sit outside the ruff gate
        assert any(rel == scope or rel.startswith(scope + "/")
                   for scope in RUFF_SCOPE), \
            f"{rel} is outside the ruff gate's scope {RUFF_SCOPE}"


ISSUE19_FILES = [
    # native-path telemetry + flow accounting + collector (ISSUE 19):
    # shm telemetry block (C), class taxonomy, metrics families, the
    # one-pane collector, and the telemetry/class test surfaces
    "native/io_uring.cpp",
    "native/pump.cpp",
    "pushcdn_tpu/proto/flowclass.py",
    "pushcdn_tpu/proto/metrics.py",
    "pushcdn_tpu/native/uring.py",
    "scripts/cdn_top.py",
    "tests/test_uring.py",
    "tests/test_route_cutthrough.py",
]


def test_issue19_files_inside_lint_scope():
    for rel in ISSUE19_FILES:
        assert os.path.exists(os.path.join(REPO, rel)), rel
        if rel.endswith(".cpp"):
            continue  # native sources sit outside the ruff gate
        assert any(rel == scope or rel.startswith(scope + "/")
                   for scope in RUFF_SCOPE), \
            f"{rel} is outside the ruff gate's scope {RUFF_SCOPE}"


ISSUE20_FILES = [
    # frame-fate conservation ledger (ISSUE 20): fate taxonomy + per-link
    # counters + auditor + SLO burn engine, the wire/class rule, the
    # instrumented terminal paths, mesh-wide audit tooling, and the
    # client-side gap detector
    "native/io_uring.cpp",
    "native/pump.cpp",
    "pushcdn_tpu/proto/ledger.py",
    "pushcdn_tpu/proto/flowclass.py",
    "pushcdn_tpu/proto/metrics.py",
    "pushcdn_tpu/proto/transport/base.py",
    "pushcdn_tpu/native/uring.py",
    "pushcdn_tpu/broker/broker.py",
    "pushcdn_tpu/broker/connections.py",
    "pushcdn_tpu/broker/sharding.py",
    "pushcdn_tpu/broker/admission.py",
    "pushcdn_tpu/broker/retention.py",
    "pushcdn_tpu/broker/tasks/handlers.py",
    "pushcdn_tpu/broker/tasks/cutthrough.py",
    "pushcdn_tpu/broker/tasks/senders.py",
    "pushcdn_tpu/broker/tasks/sync.py",
    "pushcdn_tpu/client/client.py",
    "pushcdn_tpu/testing/clientpack.py",
    "pushcdn_tpu/bin/broker.py",
    "scripts/cdn_top.py",
    "scripts/local_cluster.py",
    "tests/test_ledger.py",
]


def test_issue20_files_inside_lint_scope():
    for rel in ISSUE20_FILES:
        assert os.path.exists(os.path.join(REPO, rel)), rel
        if rel.endswith(".cpp"):
            continue  # native sources sit outside the ruff gate
        assert any(rel == scope or rel.startswith(scope + "/")
                   for scope in RUFF_SCOPE), \
            f"{rel} is outside the ruff gate's scope {RUFF_SCOPE}"


def test_ruff_check_clean():
    cmd = _ruff_cmd()
    if cmd is None:
        pytest.skip("ruff not installed in this image; lint gate inactive")
    proc = subprocess.run(
        [*cmd, "check", *RUFF_SCOPE],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, \
        f"ruff check found issues:\n{proc.stdout}\n{proc.stderr}"
