"""CI lint gate (ISSUE 4 satellite): run ``ruff check`` over the package,
tests, benches and scripts with the repo's ruff.toml baseline, so new
instrumentation code lands lint-clean.

The container image may not ship ruff (it is not pip-installable here);
in that case the test SKIPS with an explicit reason rather than
vacuously passing — the gate engages wherever ruff exists.
"""

import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ruff_cmd():
    exe = shutil.which("ruff")
    if exe:
        return [exe]
    try:
        import ruff  # noqa: F401
        return [sys.executable, "-m", "ruff"]
    except ImportError:
        return None


def test_ruff_check_clean():
    cmd = _ruff_cmd()
    if cmd is None:
        pytest.skip("ruff not installed in this image; lint gate inactive")
    proc = subprocess.run(
        [*cmd, "check", "pushcdn_tpu", "tests", "benches", "scripts",
         "bench.py"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, \
        f"ruff check found issues:\n{proc.stdout}\n{proc.stderr}"
