"""Worker for the two-process multi-host KILL test (run via subprocess).

Same deployment shape as ``_multihost_worker.py`` (jax.distributed, global
8-shard mesh, one TCP broker + marshal + client per OS process, zero host
broker links), but the scenario is a mid-stream host death:

- both ranks prove the device plane end to end (cross-host broadcast),
  then touch a ``ready-<rank>`` sentinel file;
- the parent SIGKILLs rank 1;
- rank 0 (the survivor, also the jax coordinator) must observe the
  collective fail, see the group disable itself CLEANLY (pump task
  finished — no hung collective), and keep serving its local client over
  the host path (direct echo + local broadcast), then print ``KILL OK``.

Parity: the reference self-heals its host mesh from any peer death within
one heartbeat tick (cdn-broker/src/tasks/broker/heartbeat.rs:69-107); an
SPMD collective group cannot self-heal mid-world (every step needs every
process), so the contract here is fail-CLOSED on the device plane,
fail-OPEN for local host-path service, and recovery by redeployment (the
parent test's phase 2 — jax.distributed's world is static, so "the
restarted host rejoins" happens at deployment granularity).

Usage: _multihost_kill_worker.py <rank> <base_port> <db_path> <tmp_dir>
"""

import asyncio
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")  # sitecustomize may override env

rank = int(sys.argv[1])
base = int(sys.argv[2])
db = sys.argv[3]
tmp = sys.argv[4]

# a generous heartbeat window: when the peer is SIGKILLed, the
# coordination service's error-poller TERMINATES surviving processes
# (client.h LOG(FATAL) — jax's by-design SPMD restart posture). The
# survivor needs to outlive the GLOO collective failure long enough to
# assert its clean-halt and host-path-service guarantees and exit on its
# own terms.
jax.distributed.initialize(coordinator_address=f"127.0.0.1:{base}",
                           num_processes=2, process_id=rank,
                           heartbeat_timeout_seconds=600)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pushcdn_tpu.proto.crypto.signature import DEFAULT_SCHEME  # noqa: E402
from pushcdn_tpu.proto.message import Broadcast, Direct  # noqa: E402
from pushcdn_tpu.testing.two_host import make_two_host_node  # noqa: E402

CLIENT_SEED = [71_000, 72_000]


async def main() -> None:
    try:
        await _main()
    except BaseException:
        # fail INSIDE the coroutine: asyncio.run's finally would join the
        # default executor, and a collective thread stuck in gloo would
        # turn any assert failure into a silent minutes-long hang
        import traceback
        traceback.print_exc()
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(1)


async def _main() -> None:
    node = await make_two_host_node(
        rank, base, db, client_seeds=CLIENT_SEED, broker_seed_base=80)
    group, broker, client = node.group, node.broker, node.client
    my_shard = node.my_shard

    # rendezvous via the user-slot directory
    await node.directory_rendezvous()

    # prove the device plane is live end to end before the kill
    if rank == 0:
        await client.send_broadcast_message([0], b"pre-kill hello")
    got = await asyncio.wait_for(client.receive_message(), 60)
    assert isinstance(got, Broadcast) and bytes(got.message) == b"pre-kill hello"
    assert broker.connections.num_brokers == 0

    with open(os.path.join(tmp, f"ready-{rank}"), "w") as f:
        f.write("ready")

    if rank == 1:
        # sit in the collective pump until the parent SIGKILLs us
        await asyncio.sleep(3600)
        return

    # ---- rank 0: survive the peer's death --------------------------------
    # the next collective step must FAIL (dead peer), the pump must exit
    # cleanly, and the group must disable itself
    for _ in range(1500):  # up to 150 s: gloo/coordination detection time
        if group.disabled:
            break
        await asyncio.sleep(0.1)
    assert group.disabled, "peer death never disabled the group"
    print("MARK: disabled", flush=True)
    # clean halt: the pump task RETURNED (no hung collective). When the
    # STEP (rather than the stop-barrier) is what caught the death, the
    # pump still runs its bounded last-barrier (<= collective_timeout_s)
    # before returning — poll past that bound.
    for _ in range(450):
        if group._task is None or group._task.done():
            break
        await asyncio.sleep(0.1)
    assert group._task is None or group._task.done(), \
        "pump still running after disable (hung collective?)"
    print("MARK: pump done", flush=True)

    # staging now fail-fasts instead of blackholing
    from pushcdn_tpu.broker.staging import StageResult
    from pushcdn_tpu.proto.limiter import Bytes as _Bytes
    from pushcdn_tpu.proto.message import serialize
    late = Broadcast(topics=[0], message=b"late")
    assert group.try_stage(my_shard, late, _Bytes(serialize(late))) == \
        StageResult.INELIGIBLE
    print("MARK: stage fail-fast", flush=True)

    # the survivor KEEPS SERVING local clients over the host path
    own_pk = DEFAULT_SCHEME.generate_keypair(seed=CLIENT_SEED[0]).public_key
    print("MARK: sending direct", flush=True)
    await client.send_direct_message(own_pk, b"still served")
    print("MARK: direct sent", flush=True)
    got = await asyncio.wait_for(client.receive_message(), 30)
    assert isinstance(got, Direct) and bytes(got.message) == b"still served"
    await client.send_broadcast_message([0], b"local fanout works")
    got = await asyncio.wait_for(client.receive_message(), 30)
    assert isinstance(got, Broadcast) and \
        bytes(got.message) == b"local fanout works"
    assert broker.connections.num_users == 1

    client.close()
    await node.marshal.stop()
    await broker.stop()
    print(f"rank {rank}: KILL OK (steps={group.steps}, disabled clean)",
          flush=True)
    # skip jax.distributed.shutdown(): its barrier would wait forever for
    # the killed peer (and so would the atexit hook) — hard-exit instead
    os._exit(0)


asyncio.run(main())
