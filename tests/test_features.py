"""Feature-flag and hook tests: message hooks (skip/process/disconnect),
global permits, strong-consistency off, mesh self-healing after a broker
death (the reference's cargo-feature behaviors as runtime flags,
SURVEY.md §5 config system)."""

import asyncio
import dataclasses

import pytest

from pushcdn_tpu.broker.tasks.heartbeat import heartbeat_once
from pushcdn_tpu.broker.test_harness import TestDefinition
from pushcdn_tpu.proto.def_ import HookResult
from pushcdn_tpu.proto.discovery.base import BrokerIdentifier
from pushcdn_tpu.proto.discovery.embedded import Embedded
from pushcdn_tpu.proto.message import Broadcast, Direct
from tests.test_integration import Cluster, wait_until


# ---------------------------------------------------------------------------
# message hooks (parity MessageHookDef, def.rs:70-97)
# ---------------------------------------------------------------------------

async def test_hook_skip_drops_silently():
    run = await TestDefinition(connected_users=[[0], [0]]).run()
    try:
        def hook(_sender, message):
            if isinstance(message, Broadcast) and bytes(message.message) == b"censored":
                return HookResult.SKIP
            return HookResult.PROCESS
        run.broker.run_def.user_def.hook = hook

        await run.send_message_as(run.user(0), Broadcast(topics=[0], message=b"censored"))
        await run.assert_silence(run.user(1))
        await run.send_message_as(run.user(0), Broadcast(topics=[0], message=b"fine"))
        await run.assert_received(run.user(1), Broadcast(topics=[0], message=b"fine"))
    finally:
        await run.shutdown()


async def test_hook_disconnect_kicks_sender():
    run = await TestDefinition(connected_users=[[0], [0]]).run()
    try:
        def hook(_sender, message):
            if isinstance(message, Direct) and bytes(message.message) == b"forbidden":
                return HookResult.DISCONNECT
            return HookResult.PROCESS
        run.broker.run_def.user_def.hook = hook

        await run.send_message_as(run.user(0), Direct(recipient=b"user-1", message=b"forbidden"))
        await asyncio.sleep(0.1)
        assert not run.broker.connections.has_user(b"user-0")
        assert run.broker.connections.has_user(b"user-1")
        await run.assert_silence(run.user(1))
    finally:
        run.broker.run_def.user_def.hook = lambda s, m: HookResult.PROCESS
        await run.shutdown()


# ---------------------------------------------------------------------------
# global permits (parity the `global-permits` cargo feature)
# ---------------------------------------------------------------------------

async def test_global_permits_flag():
    """Off (default): a permit issued for broker A is refused at broker B.
    On: any broker accepts it."""
    db = "/tmp/test-global-permits.sqlite"
    import os
    if os.path.exists(db):
        os.unlink(db)
    a = BrokerIdentifier("a-pub", "a-priv")
    b = BrokerIdentifier("b-pub", "b-priv")

    strict = await Embedded.new(db, identity=a, global_permits=False)
    permit = await strict.issue_permit(a, 30.0, b"alice")
    assert await strict.validate_permit(b, permit) is None   # wrong broker
    assert await strict.validate_permit(a, permit) == b"alice"
    await strict.close()

    os.unlink(db)
    loose = await Embedded.new(db, identity=a, global_permits=True)
    permit = await loose.issue_permit(a, 30.0, b"alice")
    assert await loose.validate_permit(b, permit) == b"alice"  # any broker
    await loose.close()


# ---------------------------------------------------------------------------
# strong consistency off: syncs only at the periodic tick
# ---------------------------------------------------------------------------

async def test_strong_consistency_off_defers_sync():
    cluster = Cluster(num_brokers=2)
    cluster.run_def = dataclasses.replace(cluster.run_def,
                                          strong_consistency=False)
    await cluster.start()
    try:
        await cluster.steer_load(0, 100)
        await cluster.steer_load(1, 0)
        alice = cluster.client(seed=801, topics=[0])
        await alice.ensure_initialized()   # lands on broker 1
        await wait_until(lambda: cluster.brokers[1].connections.num_users == 1)
        await asyncio.sleep(0.1)
        # broker 0 has NOT heard about alice (no immediate push)
        assert cluster.brokers[0].connections.get_broker_identifier_of_user(
            alice.public_key) is None
        # the periodic sync tick (driven manually here) propagates it
        from pushcdn_tpu.broker.tasks.sync import partial_user_sync
        await partial_user_sync(cluster.brokers[1])
        await wait_until(lambda: cluster.brokers[0].connections
                         .get_broker_identifier_of_user(alice.public_key)
                         is not None)
        alice.close()
    finally:
        await cluster.stop()


# ---------------------------------------------------------------------------
# mesh self-healing (SURVEY.md §5 failure detection)
# ---------------------------------------------------------------------------

async def test_mesh_self_heals_after_broker_death():
    """Kill one broker: peers drop the link on I/O failure, discovery ages
    it out, and traffic keeps flowing through the survivor."""
    cluster = await Cluster(num_brokers=2).start()
    try:
        assert cluster.brokers[0].connections.num_brokers == 1
        # broker 1 dies
        await cluster.brokers[1].stop()
        # survivor detects on next send: force a sync -> send fails ->
        # removal (the EOF path may have already removed it)
        from pushcdn_tpu.broker.tasks.sync import full_user_sync
        peers = cluster.brokers[0].connections.all_broker_identifiers()
        if peers:
            await full_user_sync(cluster.brokers[0], peers[0])
        await wait_until(lambda: cluster.brokers[0].connections.num_brokers == 0)

        # clients still work through the survivor (marshal re-steers: the
        # dead broker's heartbeat ages out; here we steer directly)
        await cluster.steer_load(0, 0)
        c = cluster.client(seed=901, topics=[0])
        await c.ensure_initialized()
        await c.send_direct_message(c.public_key, b"still alive")
        got = await asyncio.wait_for(c.receive_message(), 5)
        assert bytes(got.message) == b"still alive"
        c.close()
        cluster.brokers.pop()  # stopped already
    finally:
        await cluster.stop()


async def test_mesh_reforms_on_heartbeat():
    """A restarted/rediscovered peer is re-dialed at the next heartbeat
    tick (heartbeat.rs:69-107 self-healing)."""
    cluster = await Cluster(num_brokers=2).start()
    try:
        b0, b1 = cluster.brokers
        # sever the link from both sides
        ident1 = str(b1.identity)
        b0.connections.remove_broker(ident1, "test sever")
        b1.connections.remove_broker(str(b0.identity), "test sever")
        assert b0.connections.num_brokers == 0
        # next heartbeat round re-dials (dedup rule picks one side)
        await heartbeat_once(b0)
        await heartbeat_once(b1)
        await wait_until(lambda: b0.connections.num_brokers == 1
                         and b1.connections.num_brokers == 1)
    finally:
        await cluster.stop()


async def test_marshal_death_and_replacement():
    """The marshal is stateless (parity cdn-marshal: horizontally
    scalable, handlers.rs soft-closes after every auth): killing it must
    not disturb already-connected clients (they only ever used it to get
    a permit), new connects must fail while it is down, and a REPLACEMENT
    marshal on the same discovery store must serve new auths immediately —
    including a re-auth from a client whose connection was torn down."""
    from pushcdn_tpu.marshal import Marshal, MarshalConfig

    cluster = await Cluster(num_brokers=1).start()
    try:
        alive = cluster.client(seed=7101, topics=[0])
        await alive.ensure_initialized()

        # marshal dies
        await cluster.marshal.stop()

        # existing session unaffected: the broker link never involved it
        await alive.send_broadcast_message([0], b"marshal-less")
        got = await asyncio.wait_for(alive.receive_message(), 10)
        assert bytes(got.message) == b"marshal-less"

        # a new client cannot authenticate while the marshal is down
        orphan = cluster.client(seed=7102, topics=[0])
        with pytest.raises(asyncio.TimeoutError):
            await asyncio.wait_for(orphan.ensure_initialized(), 1.0)

        # replacement marshal: same discovery store, same endpoint name
        cluster.marshal = await Marshal.new(MarshalConfig(
            run_def=cluster.run_def,
            discovery_endpoint=cluster.db,
            bind_endpoint=cluster.marshal_endpoint,
        ))
        await cluster.marshal.start()

        # the orphan's single-flight retry loop (2 s cadence) finds the
        # replacement and completes the full permit handshake
        await asyncio.wait_for(orphan.ensure_initialized(), 15)
        await orphan.send_broadcast_message([0], b"via replacement")
        for c in (alive, orphan):
            got = await asyncio.wait_for(c.receive_message(), 10)
            assert bytes(got.message) == b"via replacement"

        # a torn-down session re-auths through the NEW marshal too
        alive._disconnect_on_error()
        await alive.ensure_initialized()
        await alive.send_direct_message(alive.public_key, b"re-authed")
        got = await asyncio.wait_for(alive.receive_message(), 10)
        assert bytes(got.message) == b"re-authed"

        alive.close()
        orphan.close()
    finally:
        await cluster.stop()


async def test_broker_restart_same_identity_rejoins_and_resyncs():
    """Broker state is soft by design (SURVEY §5: no checkpointing —
    rebuilt from discovery + full CRDT syncs on reconnect). A broker that
    dies and comes back under the SAME identity must rejoin the mesh on a
    heartbeat tick, receive/serve full syncs, and have its reconnected
    users reachable from the surviving broker's DirectMap."""
    from pushcdn_tpu.broker.tasks.sync import full_user_sync

    cluster = await Cluster(num_brokers=2).start()
    try:
        await cluster.place_on(0)
        alice = cluster.client(seed=7301, topics=[0])
        await alice.ensure_initialized()
        await cluster.place_on(1)
        bob = cluster.client(seed=7302, topics=[0])
        await bob.ensure_initialized()
        await wait_until(
            lambda: cluster.brokers[1].connections.num_users == 1)

        # broker 1 dies; the survivor notices on the next send (it may
        # already have noticed via the closing stream's EOF, so the list
        # can legitimately be empty by the time we look)
        await cluster.brokers[1].stop()
        peers = cluster.brokers[0].connections.all_broker_identifiers()
        if peers:
            await full_user_sync(cluster.brokers[0], peers[0])
        await wait_until(
            lambda: cluster.brokers[0].connections.num_brokers == 0)
        bob._disconnect_on_error()  # his session died with the broker

        # restart under the SAME endpoints + deployment keypair
        restarted = await cluster.restart_broker(1)

        # mesh reforms on the next heartbeat round (>=-identifier dedup)
        await heartbeat_once(cluster.brokers[0])
        await heartbeat_once(restarted)
        await wait_until(
            lambda: cluster.brokers[0].connections.num_brokers == 1
            and restarted.connections.num_brokers == 1)

        # bob reconnects; the marshal steers him onto the restarted broker
        await cluster.place_on(1)
        await bob.ensure_initialized()
        await wait_until(lambda: restarted.connections.num_users == 1)

        # strong-consistency push (broker default) syncs bob's ownership;
        # wait for the claim to land in the SURVIVOR's DirectMap before
        # routing (the push crosses the mesh link asynchronously)
        bob_pk = bytes(bob.public_key)
        await wait_until(
            lambda: cluster.brokers[0].connections
            .get_broker_identifier_of_user(bob_pk) is not None)
        await alice.send_direct_message(bob.public_key, b"after restart")
        got = await asyncio.wait_for(bob.receive_message(), 10)
        assert bytes(got.message) == b"after restart"
        # and broadcast fan-out crosses the reformed link both ways
        await bob.send_broadcast_message([0], b"mesh is back")
        for c in (alice, bob):
            got = await asyncio.wait_for(c.receive_message(), 10)
            assert bytes(got.message) == b"mesh is back"
        alice.close()
        bob.close()
    finally:
        await cluster.stop()


async def test_mixed_schemes_per_edge():
    """The RunDef wires each edge's signature scheme independently
    (parity def.rs:62-66 ConnectionDef: scheme x transport per edge): a
    deployment can run cheap Ed25519 on the user edge while the broker
    mesh authenticates with BLS-BN254. Pins that neither auth path
    assumes the other edge's scheme (key sizes differ: 32-byte Ed25519
    vs 128-byte BLS G2), with the broadcast genuinely crossing the
    BLS-authenticated mesh link."""
    from pushcdn_tpu.proto.crypto.signature import (
        BlsBn254Scheme,
        Ed25519Scheme,
    )
    from pushcdn_tpu.proto.def_ import ConnectionDef
    from pushcdn_tpu.proto.transport import Memory
    from pushcdn_tpu.testing import wait_mesh_interest

    if not BlsBn254Scheme.available():
        pytest.skip("native BLS library unavailable")

    cluster = Cluster(num_brokers=2, scheme=Ed25519Scheme)
    cluster.run_def = dataclasses.replace(
        cluster.run_def,
        broker_def=ConnectionDef(protocol=Memory, scheme=BlsBn254Scheme))
    cluster.broker_keypair = BlsBn254Scheme.generate_keypair(seed=7400)
    await cluster.start()
    clients = []
    try:
        await wait_until(lambda: all(
            b.connections.num_brokers == 1 for b in cluster.brokers),
            timeout=30)  # BLS mutual auth: hundreds of ms per link
        for i in range(2):
            await cluster.place_on(i)  # one client per broker
            c = cluster.client(seed=7410 + i, topics=[0])
            await c.ensure_initialized()
            await wait_until(
                lambda i=i: cluster.brokers[i].connections.num_users == 1)
            clients.append(c)
        # cross-broker fan-out requires propagated topic interest
        await wait_mesh_interest(cluster, topic=0, links=1, timeout=30)
        await clients[0].send_broadcast_message([0], b"mixed edges")
        for c in clients:
            got = await asyncio.wait_for(c.receive_message(), 10)
            assert bytes(got.message) == b"mixed edges"
    finally:
        for c in clients:
            c.close()
        await cluster.stop()
