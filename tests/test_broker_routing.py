"""Deterministic broker routing tests on the injection harness.

Parity with cdn-broker/src/tests/broadcast.rs:26-167 and
tests/direct.rs:27-173: exact delivery sets, absence of duplicates, and the
loop-prevention rules.
"""

import pytest

from pushcdn_tpu.broker.test_harness import TestDefinition
from pushcdn_tpu.proto.message import Broadcast, Direct

# topics: TestTopic.GLOBAL=0, TestTopic.DA=1


async def test_broadcast_from_user():
    """User broadcast reaches subscribed users AND subscribed brokers;
    unsubscribed entities get nothing (broadcast.rs user-origin case)."""
    run = await TestDefinition(
        connected_users=[[0], [0], [1]],
        connected_brokers=[([0], []), ([1], [])],
    ).run()
    try:
        msg = Broadcast(topics=[0], message=b"hello global")
        await run.send_message_as(run.user(0), msg)
        await run.assert_received(run.user(0), msg)   # sender is subscribed
        await run.assert_received(run.user(1), msg)
        await run.assert_received(run.peer(0), msg)   # subscribed peer
        await run.assert_silence(run.user(2))          # wrong topic
        await run.assert_silence(run.peer(1))          # wrong topic
    finally:
        await run.shutdown()


async def test_broadcast_from_broker_loop_prevention():
    """Broker-originated broadcast goes to local users ONLY — never
    re-forwarded to other brokers (to_users_only, handler.rs:156-161)."""
    run = await TestDefinition(
        connected_users=[[0], [1]],
        connected_brokers=[([0], []), ([0], [])],
    ).run()
    try:
        msg = Broadcast(topics=[0], message=b"from peer")
        await run.send_message_as(run.peer(0), msg)
        await run.assert_received(run.user(0), msg)
        await run.assert_silence(run.user(1))   # wrong topic
        await run.assert_silence(run.peer(1))   # loop prevention
        await run.assert_silence(run.peer(0))   # not echoed back
    finally:
        await run.shutdown()


async def test_direct_user_to_self():
    run = await TestDefinition(connected_users=[[0]]).run()
    try:
        msg = Direct(recipient=b"user-0", message=b"note to self")
        await run.send_message_as(run.user(0), msg)
        await run.assert_received(run.user(0), msg)
    finally:
        await run.shutdown()


async def test_direct_user_to_user_same_broker():
    run = await TestDefinition(connected_users=[[0], [0]],
                               connected_brokers=[([], [])]).run()
    try:
        msg = Direct(recipient=b"user-1", message=b"hi neighbor")
        await run.send_message_as(run.user(0), msg)
        await run.assert_received(run.user(1), msg)
        await run.assert_silence(run.user(0))
        await run.assert_silence(run.peer(0))  # local delivery: no broker hop
    finally:
        await run.shutdown()


async def test_direct_user_to_remote_broker():
    """Recipient owned by a peer broker: exactly one forward to that peer
    (direct.rs user→remote-broker case)."""
    run = await TestDefinition(
        connected_users=[[0]],
        connected_brokers=[([], [b"remote-user"]), ([], [])],
    ).run()
    try:
        msg = Direct(recipient=b"remote-user", message=b"cross-broker")
        await run.send_message_as(run.user(0), msg)
        await run.assert_received(run.peer(0), msg)  # the owner
        await run.assert_silence(run.peer(1))         # nobody else
        await run.assert_silence(run.user(0))
    finally:
        await run.shutdown()


async def test_direct_from_broker_delivered_locally_only():
    """A Direct arriving FROM a peer broker is delivered to our local user
    (to_user_only) — and never bounced to another broker
    (direct.rs broker→user + broker→user-not-returned cases)."""
    run = await TestDefinition(
        connected_users=[[0]],
        connected_brokers=[([], []), ([], [b"foreign-user"])],
    ).run()
    try:
        # delivered: we own user-0
        msg = Direct(recipient=b"user-0", message=b"inbound")
        await run.send_message_as(run.peer(0), msg)
        await run.assert_received(run.user(0), msg)

        # NOT re-forwarded: foreign-user is owned by peer(1), but a
        # broker-originated Direct must never take a second broker hop
        msg2 = Direct(recipient=b"foreign-user", message=b"should stop here")
        await run.send_message_as(run.peer(0), msg2)
        await run.assert_silence(run.peer(1))
        await run.assert_silence(run.user(0))
    finally:
        await run.shutdown()


async def test_unknown_recipient_dropped():
    run = await TestDefinition(connected_users=[[0]]).run()
    try:
        await run.send_message_as(
            run.user(0), Direct(recipient=b"ghost", message=b"anyone?"))
        await run.assert_silence(run.user(0))
    finally:
        await run.shutdown()


async def test_subscribe_unsubscribe_live():
    """Subscriptions applied mid-connection change routing (parity
    subscribe-delivery aspects of tests/subscribe.rs)."""
    from pushcdn_tpu.proto.message import Subscribe, Unsubscribe
    run = await TestDefinition(connected_users=[[0], []]).run()
    try:
        msg = Broadcast(topics=[1], message=b"DA block")
        await run.send_message_as(run.user(0), msg)
        await run.assert_silence(run.user(1))  # not yet subscribed

        await run.send_message_as(run.user(1), Subscribe([1]))
        import asyncio
        await asyncio.sleep(0.05)  # let the receive loop apply it
        await run.send_message_as(run.user(0), msg)
        await run.assert_received(run.user(1), msg)

        await run.send_message_as(run.user(1), Unsubscribe([1]))
        await asyncio.sleep(0.05)
        await run.send_message_as(run.user(0), msg)
        await run.assert_silence(run.user(1))
    finally:
        await run.shutdown()


async def test_malformed_frame_disconnects_user():
    run = await TestDefinition(connected_users=[[0], [0]]).run()
    try:
        await run.user(0).remote.send_raw(b"\xfe garbage frame", flush=True)
        import asyncio
        await asyncio.sleep(0.1)
        assert not run.broker.connections.has_user(b"user-0")
        assert run.broker.connections.has_user(b"user-1")
    finally:
        await run.shutdown()
