"""Generic transport conformance test, instantiated per transport.

Parity with the reference's shared `test_connection::<P>()`
(cdn-proto/src/connection/protocols/mod.rs:396-481, instantiated by
tcp.rs:175-194, tcp_tls.rs:256-275, memory.rs:206-222):
bind → connect → accept → finalize → bidirectional send/recv → soft-close.
"""

import asyncio

import pytest

from pushcdn_tpu.proto.error import Error
from pushcdn_tpu.proto.limiter import Limiter
from pushcdn_tpu.proto.message import Broadcast, Direct, deserialize
from pushcdn_tpu.proto.transport import Memory, Quic, Tcp, TcpTls
from pushcdn_tpu.proto.transport.memory import gen_testing_connection_pair

TRANSPORTS = [
    pytest.param(Memory, "test-conformance-mem", id="memory"),
    pytest.param(Tcp, "127.0.0.1:0", id="tcp"),
    pytest.param(TcpTls, "127.0.0.1:0", id="tcp_tls"),
    pytest.param(Quic, "127.0.0.1:0", id="quic"),
]


def _endpoint_of(listener, requested):
    port = getattr(listener, "bound_port", None)
    if port:
        return f"127.0.0.1:{port}"
    return requested


@pytest.mark.parametrize("proto,endpoint", TRANSPORTS)
async def test_connection_conformance(proto, endpoint):
    listener = await proto.bind(endpoint)
    try:
        ep = _endpoint_of(listener, endpoint)
        connect_task = asyncio.create_task(proto.connect(ep))
        unfinalized = await asyncio.wait_for(listener.accept(), 10)
        server_conn = await unfinalized.finalize()
        client_conn = await asyncio.wait_for(connect_task, 10)

        # client -> server
        msg = Direct(recipient=b"server-key", message=b"ping" * 100)
        await client_conn.send_message(msg)
        got = await asyncio.wait_for(server_conn.recv_message(), 10)
        assert isinstance(got, Direct)
        assert bytes(got.message) == b"ping" * 100

        # server -> client
        await server_conn.send_message(Broadcast(topics=[3], message=b"pong"))
        got2 = await asyncio.wait_for(client_conn.recv_message(), 10)
        assert isinstance(got2, Broadcast)
        assert got2.topics == (3,)
        assert bytes(got2.message) == b"pong"

        # soft close: peer sees clean EOF as a connection error on recv
        await client_conn.soft_close()
        with pytest.raises(Error):
            await asyncio.wait_for(server_conn.recv_message(), 10)
        server_conn.close()
    finally:
        await listener.close()


@pytest.mark.parametrize("proto,endpoint", TRANSPORTS)
async def test_large_frame(proto, endpoint):
    listener = await proto.bind(endpoint)
    try:
        ep = _endpoint_of(listener, endpoint)
        connect_task = asyncio.create_task(proto.connect(ep))
        server_conn = await (await asyncio.wait_for(listener.accept(), 10)).finalize()
        client_conn = await asyncio.wait_for(connect_task, 10)
        payload = bytes(range(256)) * 4096  # 1 MiB
        await client_conn.send_message(Direct(recipient=b"k", message=payload))
        got = await asyncio.wait_for(server_conn.recv_message(), 30)
        assert bytes(got.message) == payload
        client_conn.close()
        server_conn.close()
    finally:
        await listener.close()


async def test_memory_pair_helper():
    a, b = await gen_testing_connection_pair()
    await a.send_message(Direct(recipient=b"x", message=b"hi"))
    got = await asyncio.wait_for(b.recv_message(), 5)
    assert bytes(got.message) == b"hi"
    a.close()
    b.close()


async def test_connect_to_unbound_memory_endpoint_fails():
    with pytest.raises(Error):
        await Memory.connect("nobody-home")


async def test_send_raw_forwarding_preserves_frame():
    """The broker forwards raw frames verbatim (deserialize once per hop,
    payload bytes shared) — check raw passthrough equals re-serialization."""
    a, b = await gen_testing_connection_pair()
    c, d = await gen_testing_connection_pair()
    await a.send_message(Broadcast(topics=[1, 2], message=b"fanout-payload"))
    raw = await asyncio.wait_for(b.recv_raw(), 5)
    # forward the exact bytes to another peer, as the broker hot path does
    await c.send_raw(raw.clone())
    raw.release()
    got = deserialize((await asyncio.wait_for(d.recv_raw(), 5)).data)
    assert isinstance(got, Broadcast)
    assert bytes(got.message) == b"fanout-payload"
    for conn in (a, b, c, d):
        conn.close()


async def test_limiter_backpressure_blocks_reader():
    """With a tiny pool, a second frame must wait until the first's Bytes is
    released (parity: 'block the reader, not the router')."""
    limiter = Limiter(global_pool_bytes=1500)
    a, b = await gen_testing_connection_pair(limiter)
    payload = b"z" * 1000
    await a.send_message(Direct(recipient=b"", message=payload))
    await a.send_message(Direct(recipient=b"", message=payload))
    first = await asyncio.wait_for(b.recv_raw(), 5)
    # second frame needs ~1005 bytes but only ~495 remain: reader must stall
    await asyncio.sleep(0.1)
    assert limiter.pool.available < 1005
    pending = asyncio.create_task(b.recv_raw())
    await asyncio.sleep(0.1)
    assert not pending.done()
    first.release()  # frees pool -> reader resumes
    second = await asyncio.wait_for(pending, 5)
    assert len(second.data) > 1000
    second.release()
    a.close()
    b.close()


async def test_quic_msgsize_clamp_and_resegment():
    """A post-negotiation path-MTU decrease (EMSGSIZE outside the probe
    grace window) clamps the MTU to the floor AND re-segments unacked
    data so retransmissions fit; during the grace window (probe bounce)
    it is a no-op."""
    import time as _time
    from pushcdn_tpu.proto.transport.quic import (
        MTU_PAYLOAD, _UdpStream)

    sent = []
    stream = _UdpStream(1, sent.append)
    try:
        # pretend probing negotiated a jumbo path and the window has grown
        # (nothing ACKs in this fixture; without the bump the congestion
        # window would block the 40 KB write)
        stream._mtu = 16000
        stream._cwnd = 1e6
        await stream.write(b"x" * 40000)
        big_segs = dict(stream._unacked)
        assert any(len(s[0]) > MTU_PAYLOAD for s in big_segs.values())

        # 1) within the grace window: ignored (probe bounce)
        stream._last_probe_sent = _time.monotonic()
        stream.on_msgsize_error()
        assert stream._mtu == 16000
        assert stream._unacked == big_segs

        # 2) outside the window: clamp + re-segment
        stream._last_probe_sent = 0.0
        stream.on_msgsize_error()
        assert stream._mtu == MTU_PAYLOAD
        assert all(len(s[0]) <= MTU_PAYLOAD
                   for s in stream._unacked.values())
        # byte coverage is identical after the re-split
        covered = sorted((off, off + len(s[0]))
                         for off, s in stream._unacked.items())
        assert covered[0][0] == 0
        for (a0, a1), (b0, _) in zip(covered, covered[1:]):
            assert a1 == b0, "gap or overlap after resegmentation"
        assert covered[-1][1] == 40000
        assert list(stream._send_order) == [c[0] for c in covered]
        # idempotent at the floor
        stream.on_msgsize_error()
        assert stream._mtu == MTU_PAYLOAD
    finally:
        stream.abort()


async def test_quic_recovers_from_datagram_loss():
    """The QUIC-class ARQ must deliver in-order bytes through a lossy
    path: two streams wired back-to-back through a channel that drops
    every 5th datagram in each direction."""
    from pushcdn_tpu.proto.transport.quic import _UdpStream

    drop = {"a": 0, "b": 0}
    a = b = None

    # header is 9 bytes: type(1) + conn_id(8); on_packet takes (type, body)
    def wire(key, get_peer):
        def send(pkt: bytes) -> None:
            drop[key] += 1
            if drop[key] % 5 == 0:
                return
            peer = get_peer()
            if peer is not None:
                asyncio.get_running_loop().call_soon(
                    peer.on_packet, pkt[0], pkt[9:])
        return send

    a = _UdpStream(1, wire("a", lambda: b))
    b = _UdpStream(1, wire("b", lambda: a))
    # pin the floor MTU: probing this lossless-looking fake wire up to
    # 64 KB would fit the whole payload in one segment and leave nothing
    # for the loss-recovery dynamics this test exists to observe
    a._prober.cancel()
    b._prober.cancel()
    try:
        payload = bytes(range(256)) * 200  # 51200 B
        await a.write(payload)
        got = bytearray()
        peak_cwnd = 0.0
        async with asyncio.timeout(30):
            while len(got) < len(payload):
                got += await b.read_some(65536)
                if a._ssthresh != float("inf"):
                    # only sample AFTER the first loss cut — the initial
                    # window already exceeds the floor, so pre-loss
                    # samples would make the regrowth assert vacuous
                    peak_cwnd = max(peak_cwnd, a._cwnd)
        assert bytes(got) == payload
        # recovery must not leave the window collapsed: through 20% loss
        # the congestion controller has both cut (ssthresh finite — losses
        # were seen) and RAMPED back up past its post-loss floor of 2
        # segments at some point during the transfer. (The END-state cwnd
        # is deliberately not asserted: with a deterministic every-5th
        # dropper a tail loss legally leaves cwnd at the floor — that IS
        # NewReno — and which datagram the tail loss lands on is pure
        # drop-counter phase.)
        assert a._ssthresh != float("inf")
        assert peak_cwnd > 2.0 * a._mtu
        # and the reverse direction too
        await b.write(b"pong" * 1000)
        back = bytearray()
        async with asyncio.timeout(30):
            while len(back) < 4000:
                back += await a.read_some(65536)
        assert bytes(back) == b"pong" * 1000
    finally:
        a.abort()
        b.abort()


async def test_quic_pacer_handles_segment_larger_than_cwnd():
    """Pace-deadlock regression: after MTU probing settles (~64 KB
    segments) a fresh connection's cwnd (16 x 1200 B) is SMALLER than one
    segment; the pacing bucket must still be fillable or the first jumbo
    write hangs forever."""
    from pushcdn_tpu.proto.transport.quic import _OFF, _UdpStream

    sent: list[bytes] = []
    a = _UdpStream(9, sent.append)
    try:
        a._mtu = 65000          # probed-up path
        a._srtt = 0.05          # pacing active (above the loopback floor)
        a._rttvar = 0.0

        async def acker():
            seen = 0
            while a._next_off < 4 * 65000:
                if a._next_off > seen:
                    seen = a._next_off
                    a.on_packet(4, _OFF.pack(seen))   # ACK everything sent
                await asyncio.sleep(0.005)
            a.on_packet(4, _OFF.pack(a._next_off))

        t = asyncio.create_task(acker())
        async with asyncio.timeout(10):
            await a.write(b"z" * (4 * 65000))
        await t
        assert a._acked == 4 * 65000
    finally:
        a.abort()


async def test_quic_congestion_controller_state_machine():
    """NewReno unit check against a hand-driven ACK sequence: slow-start
    growth, 3-dup-ACK halving + fast retransmit, partial-ACK retransmit
    during recovery, full-ACK deflation, and RTO collapse to 2 segments."""
    from pushcdn_tpu.proto.transport.quic import (
        _OFF, _UdpStream, MTU_PAYLOAD, CWND_INITIAL_SEGS)

    sent: list[bytes] = []
    s = _UdpStream(7, sent.append)
    try:
        mtu = s._mtu
        assert s._cwnd == CWND_INITIAL_SEGS * MTU_PAYLOAD
        await s.write(b"x" * (8 * mtu))       # 8 segments in flight
        base = len(sent)
        cw0 = s._cwnd

        # slow start: ACK of 2 segments grows cwnd by the acked bytes
        s.on_packet(4, _OFF.pack(2 * mtu))    # 4 == _ACK
        assert s._cwnd == cw0 + 2 * mtu
        assert s._srtt is not None            # RTT estimator seeded

        # 3 duplicate ACKs: fast retransmit of the earliest hole + halve
        for _ in range(3):
            s.on_packet(4, _OFF.pack(2 * mtu))
        assert s._in_recovery
        assert s._ssthresh == max(s._inflight() / 2.0, 2.0 * mtu)
        assert len(sent) == base + 1          # exactly one fast retransmit
        retx_off = _OFF.unpack_from(sent[-1], 9)[0]
        assert retx_off == 2 * mtu

        # partial ACK (below the recovery point): retransmit next hole
        s.on_packet(4, _OFF.pack(3 * mtu))
        assert s._in_recovery
        assert len(sent) == base + 2
        assert _OFF.unpack_from(sent[-1], 9)[0] == 3 * mtu

        # full ACK: exit recovery, deflate to ssthresh
        s.on_packet(4, _OFF.pack(8 * mtu))
        assert not s._in_recovery
        assert s._cwnd == max(s._ssthresh, 2.0 * mtu)

        # RTO expiry: collapse to 2 segments, ssthresh = half the flight
        s._cwnd = 8.0 * mtu                   # room for the whole write
        await s.write(b"y" * (4 * mtu))
        s._rto = 0.0                          # force immediate expiry
        await asyncio.sleep(0.1)              # timer loop fires
        assert s._cwnd == 2.0 * mtu
        assert s._ssthresh >= 2.0 * mtu
    finally:
        s.abort()


async def test_quic_wire_carries_no_plaintext():
    """The QUIC-class transport is TLS 1.3-secured (parity quinn+rustls,
    quic.rs:37-146): capture every datagram either side transmits and
    assert the application payload never appears in cleartext."""
    import os as _os
    from pushcdn_tpu.proto.transport import quic as quic_mod

    captured: list[bytes] = []
    orig_tx = quic_mod._UdpStream._tx

    def capturing_tx(self, ptype, body):
        captured.append(bytes(body))
        orig_tx(self, ptype, body)

    quic_mod._UdpStream._tx = capturing_tx
    try:
        listener = await Quic.bind("127.0.0.1:0")
        ep = f"127.0.0.1:{listener.bound_port}"
        connect_task = asyncio.create_task(Quic.connect(ep))
        server = await (await asyncio.wait_for(listener.accept(), 10)) \
            .finalize()
        client = await connect_task
        marker = _os.urandom(64)  # incompressible, unmistakable
        payload = marker * 128    # 8 KB spanning many segments
        await client.send_message(Direct(recipient=b"r", message=payload))
        echoed = await asyncio.wait_for(server.recv_message(), 10)
        assert bytes(echoed.message) == payload
        await server.send_message(Direct(recipient=b"r", message=payload))
        echoed = await asyncio.wait_for(client.recv_message(), 10)
        assert bytes(echoed.message) == payload
        client.close()
        server.close()
        await listener.close()
    finally:
        quic_mod._UdpStream._tx = orig_tx
    assert captured, "capture hook never fired"
    blob = b"\x00".join(captured)
    assert marker not in blob, "plaintext payload leaked onto the wire"


async def test_quic_tls_handshake_survives_datagram_loss():
    """TLS rides the ARQ: the handshake and encrypted traffic must complete
    over a wire dropping every 4th datagram in each direction."""
    from pushcdn_tpu.proto.crypto.tls import LOCAL_SAN, local_certificate
    from pushcdn_tpu.proto.transport.quic import _UdpStream
    from pushcdn_tpu.proto.transport.tls_stream import TlsStream

    drop = {"a": 0, "b": 0}
    a = b = None

    def wire(key, get_peer):
        def send(pkt: bytes) -> None:
            drop[key] += 1
            if drop[key] % 4 == 0:
                return
            peer = get_peer()
            if peer is not None:
                asyncio.get_running_loop().call_soon(
                    peer.on_packet, pkt[0], pkt[9:])
        return send

    a = _UdpStream(7, wire("a", lambda: b))
    b = _UdpStream(7, wire("b", lambda: a))
    try:
        cert = local_certificate()
        async with asyncio.timeout(30):
            server_task = asyncio.create_task(
                TlsStream.wrap_server(b, cert.server_context()))
            tls_a = await TlsStream.wrap_client(
                a, cert.client_context(), LOCAL_SAN)
            tls_b = await server_task
            await tls_a.write(b"secret-over-lossy-wire" * 100)
            got = bytearray()
            while len(got) < 2200:
                got += await tls_b.read_some(65536)
        assert bytes(got) == b"secret-over-lossy-wire" * 100
    finally:
        a.abort()
        b.abort()


@pytest.mark.parametrize("proto,endpoint", TRANSPORTS)
async def test_coalesced_writes_preserve_frame_boundaries(proto, endpoint):
    """The writer coalesces whole queued runs into single flushes (and the
    adaptive window makes that the steady state under load): a mixed burst
    of sizes — sub-byte, odd, exactly at and beyond the coalesce limit —
    queued in one breath must arrive intact, in order, on every
    transport."""
    from pushcdn_tpu.proto.transport.base import Connection

    listener = await proto.bind(endpoint)
    try:
        ep = _endpoint_of(listener, endpoint)
        connect_task = asyncio.create_task(proto.connect(ep))
        server = await (await asyncio.wait_for(listener.accept(), 10)) \
            .finalize()
        client = await asyncio.wait_for(connect_task, 10)

        limit = Connection._BATCH_COALESCE_LIMIT
        sizes = [1, 7, 100, 1024, 4096, limit - 4, limit, limit + 1,
                 3 * limit, 5, 64, limit - 1, 2, 9000, 1]
        frames = [bytes([i % 251]) * s for i, s in enumerate(sizes)]
        # no awaits between sends: everything lands in the send queue in
        # one breath, so the writer drains it as coalesced batches
        for f in frames:
            await client.send_raw(f)
        got = []
        async with asyncio.timeout(30):
            while len(got) < len(frames):
                got.extend(b.data if isinstance(b.data, bytes)
                           else bytes(b.data)
                           for b in await server.recv_raw_many())
        assert [len(g) for g in got] == sizes
        assert got == frames
        client.close()
        server.close()
    finally:
        await listener.close()


class _TornStream:
    """RawStream wrapper that forwards writes in ragged sub-writes and, at
    a chosen write index, tears one mid-buffer (half flushed, then an
    error) — the fault the writer's poison path must turn into a clean
    connection error, never a mid-frame resync."""

    def __init__(self, inner, tear_at_write: int):
        self._inner = inner
        self._writes = 0
        self._tear_at = tear_at_write

    def __getattr__(self, name):
        return getattr(self._inner, name)

    async def write(self, data) -> None:
        view = memoryview(data)
        self._writes += 1
        if self._writes == self._tear_at:
            await self._inner.write(view[:max(1, len(view) // 2)])
            raise ConnectionResetError("torn write (fault injection)")
        # ragged forwarding: split every write into unaligned pieces so
        # coalesced flushes never map 1:1 onto reader chunks
        step = 1237
        for off in range(0, len(view), step):
            await self._inner.write(view[off:off + step])

    async def writev(self, bufs) -> None:
        for b in bufs:
            await self.write(b)


async def test_torn_write_poisons_cleanly_and_keeps_whole_frames():
    """Fault injection on the coalesced write path: frames flushed before
    the tear arrive whole; the tear poisons the sender; the receiver gets
    every fully-flushed frame and then a clean CONNECTION error — no
    partial frame is ever delivered as data."""
    from pushcdn_tpu.proto.transport.base import Connection
    from pushcdn_tpu.proto.transport.memory import _BoundedBuffer, _PipeStream

    a_to_b = _BoundedBuffer(256 * 1024)
    b_to_a = _BoundedBuffer(256 * 1024)
    torn = _TornStream(_PipeStream(rx=b_to_a, tx=a_to_b), tear_at_write=3)
    sender = Connection(torn, label="torn")
    receiver = Connection(_PipeStream(rx=a_to_b, tx=b_to_a), label="rx")

    payloads = [bytes([i]) * (512 + i) for i in range(40)]
    # waves with a yield between them: each wave coalesces into its own
    # flush (write #1, #2, ...) so the tear at write 3 lands mid-stream;
    # the poison surfaces on a later wave's send
    with pytest.raises(Error):
        for wave in range(4):
            for p in payloads[wave * 10:(wave + 1) * 10]:
                await sender.send_raw(p)
            await asyncio.sleep(0.05)
    # data-before-FIN: everything fully flushed before the tear is still
    # deliverable; after the prefix the receiver sees the clean error
    got = []
    with pytest.raises(Error):
        async with asyncio.timeout(10):
            while True:
                for b in await receiver.recv_raw_many():
                    got.append(bytes(b.data))
                    b.release()
    # every delivered frame is exactly one sent frame, in order (the torn
    # flush's half-frame must not surface as data)
    assert 0 < len(got) < len(payloads)
    assert got == payloads[:len(got)]
    assert sender.is_closed
    sender.close()
    receiver.close()


async def test_quic_batched_receive_coalesces_acks():
    """A burst of in-order datagrams processed in ONE endpoint drain
    (begin/end_rx_batch) must produce exactly one coalesced ACK covering
    the lot — not one per datagram — while a drain containing a hole
    still emits the (capped) duplicate ACKs fast retransmit needs."""
    from pushcdn_tpu.proto.transport.quic import (
        _DATA, _OFF, _UdpStream, DUP_ACK_FAST_RETX)

    sent: list[bytes] = []
    s = _UdpStream(5, sent.append)
    try:
        seg = b"d" * 1000
        # --- in-order burst in one drain: exactly one ACK out ---
        base_acks = sum(1 for p in sent if p[0] == 4)
        s.begin_rx_batch()
        for i in range(16):
            s.on_packet(_DATA, _OFF.pack(i * 1000) + seg)
        assert sum(1 for p in sent if p[0] == 4) == base_acks  # deferred
        s.end_rx_batch()
        acks = [p for p in sent if p[0] == 4]
        assert len(acks) == base_acks + 1
        assert _OFF.unpack_from(acks[-1], 9)[0] == 16 * 1000

        # --- a drain with a hole: dup ACKs preserved, capped ---
        pre = len([p for p in sent if p[0] == 4])
        s.begin_rx_batch()
        for i in range(20, 30):  # offsets past the hole at 16000
            s.on_packet(_DATA, _OFF.pack(i * 1000) + seg)
        s.end_rx_batch()
        dup_acks = [p for p in sent if p[0] == 4][pre:]
        assert 1 <= len(dup_acks) <= DUP_ACK_FAST_RETX
        assert all(_OFF.unpack_from(p, 9)[0] == 16 * 1000
                   for p in dup_acks)

        # --- duplicates of delivered data: one re-ACK per drain ---
        pre = len([p for p in sent if p[0] == 4])
        s.begin_rx_batch()
        for i in range(4):
            s.on_packet(_DATA, _OFF.pack(i * 1000) + seg)
        s.end_rx_batch()
        assert len([p for p in sent if p[0] == 4]) == pre + 1
    finally:
        s.abort()


async def test_quic_batched_lossy_path_recovers():
    """Loss recovery through BATCHED drains: the wire delivers packets in
    endpoint-style batches (begin/end_rx_batch around each group) and
    drops every 5th datagram; in-order delivery and both directions must
    still complete — the coalesced-ACK rules preserve the ARQ's recovery
    dynamics."""
    from pushcdn_tpu.proto.transport.quic import _UdpStream

    drop = {"a": 0, "b": 0}
    a = b = None
    pending: dict = {"a": [], "b": []}

    def wire(key, get_peer):
        def send(pkt: bytes) -> None:
            drop[key] += 1
            if drop[key] % 5 == 0:
                return
            pending[key].append(pkt)
            if len(pending[key]) == 1:
                asyncio.get_running_loop().call_soon(deliver, key, get_peer)
        return send

    def deliver(key, get_peer):
        peer = get_peer()
        batch, pending[key] = pending[key], []
        if peer is None or not batch:
            return
        peer.begin_rx_batch()
        try:
            for pkt in batch:
                peer.on_packet(pkt[0], pkt[9:])
        finally:
            peer.end_rx_batch()

    a = _UdpStream(1, wire("a", lambda: b))
    b = _UdpStream(1, wire("b", lambda: a))
    a._prober.cancel()
    b._prober.cancel()
    try:
        payload = bytes(range(256)) * 200  # 51200 B
        await a.write(payload)
        got = bytearray()
        async with asyncio.timeout(30):
            while len(got) < len(payload):
                got += await b.read_some(65536)
        assert bytes(got) == payload
        await b.write(b"pong" * 1000)
        back = bytearray()
        async with asyncio.timeout(30):
            while len(back) < 4000:
                back += await a.read_some(65536)
        assert bytes(back) == b"pong" * 1000
    finally:
        a.abort()
        b.abort()


async def test_abandoned_poisoned_connection_returns_permits():
    """ADVICE r5 backstop: a poisoned connection whose handle is dropped
    WITHOUT close() must still return its queued frames' pool permits
    (weakref finalizer) — a crashed handler cannot leak the pool dry."""
    import gc

    limiter = Limiter(global_pool_bytes=100_000)
    a, b = await gen_testing_connection_pair(limiter)
    payload = b"x" * 10_000
    for _ in range(4):
        await a.send_message(Direct(recipient=b"", message=payload))
    # let the frames land in b's receive queue (permits held)
    await asyncio.sleep(0.2)
    assert limiter.pool.available < 100_000
    # poison b (peer abort), then abandon the handle without close()
    a.close()
    await asyncio.sleep(0.2)
    del b
    for _ in range(3):
        gc.collect()
        await asyncio.sleep(0.05)
    assert limiter.pool.available == 100_000


async def test_quic_ack_delay_keeps_rtt_honest():
    """ACKs carry the time the receiver held them (QUIC's ack_delay): a
    timer-delayed ACK must not inflate the sender's RTT estimator, and a
    hostile/corrupt delay field is clamped so it can't zero it either."""
    from pushcdn_tpu.proto.transport.quic import (
        _ACK_DELAY, _DATA, _OFF, _UdpStream, ACK_DELAY_S,
    )

    sent = []
    a = _UdpStream(1, sent.append)
    b = None

    def to_b(pkt: bytes) -> None:
        if b is not None:
            b.on_packet(pkt[0], pkt[9:])

    try:
        # --- wire format: receiver stamps held time on timer-fired ACKs ---
        acks = []
        b = _UdpStream(1, acks.append)
        b.on_packet(_DATA, _OFF.pack(0) + b"x" * 100)
        # held ~30 ms, then the delayed-ACK timer fires
        await asyncio.sleep(0.03)
        async with asyncio.timeout(5):
            while not any(p[0] == 4 for p in acks):  # _ACK type byte = 4
                await asyncio.sleep(0.005)
        ack_pkts = [p for p in acks if p[0] == 4]
        assert ack_pkts, acks
        body = ack_pkts[-1][9:]
        assert len(body) >= _OFF.size + _ACK_DELAY.size
        delay_us = _ACK_DELAY.unpack_from(body, _OFF.size)[0]
        # the stamp reflects the ~20-30 ms hold, not zero
        assert delay_us >= 10_000, delay_us

        # --- sender side: the held time is subtracted from the sample ---
        a._unacked[0] = [b"y" * 100, __import__("time").monotonic() - 0.040, 0]
        a._send_order.append(0)
        a._next_off = 100
        a.on_packet(4, _OFF.pack(100) + _ACK_DELAY.pack(35_000))
        # raw sample ~40 ms minus reported 35 ms -> ~5 ms, far below raw
        assert a._srtt is not None and a._srtt < 0.02, a._srtt

        # --- clamp: a hostile delay can't pin the estimator to the floor ---
        c = _UdpStream(1, lambda pkt: None)
        c._unacked[0] = [b"z" * 100, __import__("time").monotonic() - 0.500, 0]
        c._send_order.append(0)
        c._next_off = 100
        c.on_packet(4, _OFF.pack(100) + _ACK_DELAY.pack(0xFFFFFFFF))
        # raw ~500 ms minus the CLAMPED delay (2*ACK_DELAY_S) stays large
        assert c._srtt is not None and c._srtt >= 0.5 - 2.5 * ACK_DELAY_S
        c.abort()
    finally:
        a.abort()
        if b is not None:
            b.abort()


# -- geo-shaped memory links (ISSUE 11) ---------------------------------


async def test_shaped_memory_adds_pipelined_latency_and_keeps_order():
    from pushcdn_tpu.proto.transport.memory import LinkShape, shaped_memory

    listener = await Memory.bind("shaped-lat")
    try:
        Shaped = shaped_memory(LinkShape(latency_s=0.03, seed=1))
        connect = asyncio.create_task(Shaped.connect("shaped-lat"))
        server = await (await listener.accept()).finalize()
        client = await connect
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        for i in range(16):
            await client.send_message(Direct(recipient=b"r",
                                             message=b"m%d" % i))
        msgs = [await server.recv_message() for _ in range(16)]
        dt = loop.time() - t0
        # ordered, and the burst pays the one-way latency once (pipelined),
        # not per message
        assert [bytes(m.message) for m in msgs] == \
            [b"m%d" % i for i in range(16)]
        assert 0.03 <= dt < 0.4, dt
        client.close()
        server.close()
    finally:
        await listener.close()


async def test_shaped_memory_loss_is_deterministic_delay_not_corruption():
    from pushcdn_tpu.proto.transport.memory import LinkShape, shaped_memory

    listener = await Memory.bind("shaped-loss")
    try:
        # heavy loss: every chunk still arrives intact and in order (the
        # reliable stream models loss as an RTO penalty, never a drop)
        Shaped = shaped_memory(LinkShape(latency_s=0.001, loss=0.8,
                                         rto_s=0.005, seed=42))
        connect = asyncio.create_task(Shaped.connect("shaped-loss"))
        server = await (await listener.accept()).finalize()
        client = await connect
        payloads = [bytes([i]) * (i + 1) for i in range(24)]
        for p in payloads:
            await client.send_message(Broadcast(topics=[0], message=p))
        got = [bytes((await server.recv_message()).message)
               for _ in range(24)]
        assert got == payloads
        # and the reverse direction is shaped too
        await server.send_message(Direct(recipient=b"c", message=b"pong"))
        back = await client.recv_message()
        assert bytes(back.message) == b"pong"
        client.close()
        server.close()
    finally:
        await listener.close()
