"""Multi-host tests: the single-process degenerate case AND the real
thing — two OS processes joined via jax.distributed executing one global
lane step collectively (parity with the reference's whole-system tier,
tests/src/tests/mod.rs:62-143, which is what backs its multi-node
claims)."""

import functools
import os
import socket
import subprocess
import sys

import jax
import pytest

from pushcdn_tpu.parallel.mesh import make_broker_mesh
from pushcdn_tpu.parallel.multihost import (
    dcn_crossings,
    initialize,
    local_shard_indices,
    pod_broker_mesh,
)


_PROBE = r"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")
rank, port = int(sys.argv[1]), sys.argv[2]
jax.distributed.initialize(f"127.0.0.1:{port}", 2, rank,
                           local_device_ids=[0])
out = jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")(
    jax.numpy.ones((1,)))
assert float(out[0]) == 2.0
print("PROBE OK")
"""


@functools.lru_cache(None)
def _cpu_multiprocess_collectives():
    """(ok, reason): can this jaxlib run cross-process collectives on the
    CPU backend? Older jaxlibs raise 'Multiprocess computations aren't
    implemented on the CPU backend' — the two-process tiers skip there
    (image capability, not a code path; they run unmodified wherever the
    runtime supports it)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = [subprocess.Popen([sys.executable, "-c", _PROBE, str(rank),
                               str(port)], env=env, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
             for rank in (0, 1)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=120)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        return False, "two-process collective probe timed out"
    if all(p.returncode == 0 for p in procs):
        return True, ""
    tail = "; ".join(o.strip().rsplit("\n", 1)[-1] for o in outs if o)
    return False, f"jaxlib cannot run multiprocess CPU collectives ({tail})"


def _require_two_process_runtime():
    ok, reason = _cpu_multiprocess_collectives()
    if not ok:
        pytest.skip(reason)


def test_single_host_owns_all_shards():
    initialize()  # no-op off-pod
    mesh = pod_broker_mesh(8)
    assert local_shard_indices(mesh) == list(range(8))
    # one host ⇒ the ring never crosses DCN
    assert dcn_crossings(mesh) == 0
    assert mesh.devices.size == 8


def test_pod_mesh_matches_plain_mesh():
    assert [d.id for d in pod_broker_mesh(4).devices.flat] == \
        [d.id for d in make_broker_mesh(4).devices.flat]


def test_two_process_spmd_lane_step():
    """Two separate OS processes (4 virtual CPU devices each) join the
    jax.distributed runtime, build the same global 8-shard mesh, and run
    ONE collective lane step. Each worker asserts jax.process_count()==2,
    dcn_crossings==2, cross-process broadcast/direct delivery, and CRDT
    convergence of claims seeded only on the other process's shards (see
    tests/_spmd_worker.py). This is the multi-node evidence the
    single-process 8-device dryrun cannot provide."""
    _require_two_process_runtime()
    with socket.socket() as s:  # a free coordinator port
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    worker = os.path.join(os.path.dirname(__file__), "_spmd_worker.py")
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = [
        subprocess.Popen([sys.executable, worker, str(rank), str(port)],
                         env=env, stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True)
        for rank in (0, 1)
    ]
    outputs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outputs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    for rank, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        assert f"rank {rank}: SPMD OK" in out, out


def test_two_process_multihost_deployment():
    """The REAL multi-host deployment (VERDICT r3 item 2): two OS
    processes each run marshal + TCP broker + TCP client over one global
    8-shard mesh (MultiHostBrokerGroup). A broadcast published on host 0
    reaches host 1's client, a direct crosses back via the discovery
    user-slot directory, and both brokers hold ZERO host broker links
    throughout (see tests/_multihost_worker.py)."""
    _require_two_process_runtime()
    import tempfile
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        base = s.getsockname()[1]
    db = os.path.join(tempfile.mkdtemp(prefix="pushcdn-mh-"), "d.sqlite")
    worker = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(rank), str(base), db],
            env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        for rank in (0, 1)
    ]
    outputs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outputs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    for rank, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        assert f"rank {rank}: MULTIHOST OK" in out, out


def test_two_process_stall_and_redeploy():
    """VERDICT r5 #6, the non-SIGKILL twin of the kill test: one host of a
    live two-host group PERMANENTLY STALLS (alive, sockets open, heartbeats
    flowing — a wedged runtime, not a death, so no connection reset ever
    arrives). The survivor's collective watchdog must fail the group
    CLOSED in bounded time, host-path service must continue, and a fresh
    group must redeploy without the stalled host (phase 2). See
    ``tests/_multihost_stall_worker.py``."""
    _require_two_process_runtime()
    import signal
    import tempfile
    import time as _time

    tmp = tempfile.mkdtemp(prefix="pushcdn-stall-")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        base = s.getsockname()[1]
    db = os.path.join(tmp, "d.sqlite")
    worker = os.path.join(os.path.dirname(__file__),
                          "_multihost_stall_worker.py")
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(rank), str(base), db, tmp],
            env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        for rank in (0, 1)
    ]
    try:
        # wait for both readiness sentinels (device plane proven live,
        # rank 1 about to wedge itself)
        deadline = _time.time() + 240
        while _time.time() < deadline:
            if all(os.path.exists(os.path.join(tmp, f"ready-{r}"))
                   for r in (0, 1)):
                break
            for p in procs:
                if p.poll() is not None:
                    out, _ = p.communicate()
                    raise AssertionError(f"worker died pre-stall:\n{out}")
            _time.sleep(0.2)
        else:
            raise AssertionError("workers never reached readiness")

        # rank 1 stalls ITSELF (no signal sent — the stalled process must
        # stay alive for the whole detection window; that's the scenario)
        try:
            out0, _ = procs[0].communicate(timeout=240)
        except subprocess.TimeoutExpired:
            procs[0].kill()
            out0, _ = procs[0].communicate(timeout=30)
            raise AssertionError(
                f"survivor hung past the watchdog; output:\n{out0}")
        assert procs[0].returncode == 0, f"survivor failed:\n{out0}"
        assert "rank 0: STALL OK" in out0, out0
        # the stalled rank must still be ALIVE (that is the point): it
        # never exited on its own
        assert procs[1].poll() is None, \
            "stalled rank exited by itself — scenario degraded to a death"
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGKILL)
                p.communicate(timeout=30)

    # ---- phase 2: a fresh group redeploys WITHOUT the stalled host -------
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        base2 = s.getsockname()[1]
    db2 = os.path.join(tmp, "d2.sqlite")
    worker2 = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")
    procs2 = [
        subprocess.Popen(
            [sys.executable, worker2, str(rank), str(base2), db2],
            env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        for rank in (0, 1)
    ]
    outputs = []
    try:
        for p in procs2:
            out, _ = p.communicate(timeout=300)
            outputs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs2:
            p.kill()
        raise
    for rank, (p, out) in enumerate(zip(procs2, outputs)):
        assert p.returncode == 0, f"redeploy rank {rank} failed:\n{out}"
        assert f"rank {rank}: MULTIHOST OK" in out, out


def test_two_process_kill_and_redeploy():
    """VERDICT r4 #6: SIGKILL one host of a live two-host group mid-stream.

    Phase 1 (``tests/_multihost_kill_worker.py``): both hosts prove the
    device plane end to end, then rank 1 is SIGKILLed. The survivor must
    observe the collective fail, disable the group CLEANLY (pump task
    finished — no hung collective), fail-fast staging, and keep serving
    its local client over the host path.

    Phase 2: a fresh two-process deployment on a new coordinator port and
    discovery db forms and serves cross-host traffic (the standard
    ``_multihost_worker.py`` pair). jax.distributed's world is static, so
    "the restarted host rejoins" is a redeployment — the parity analog of
    the reference's same-identity broker restart at deployment
    granularity (heartbeat.rs:69-107 self-heal)."""
    _require_two_process_runtime()
    import signal
    import tempfile
    import time as _time

    tmp = tempfile.mkdtemp(prefix="pushcdn-kill-")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        base = s.getsockname()[1]
    db = os.path.join(tmp, "d.sqlite")
    worker = os.path.join(os.path.dirname(__file__),
                          "_multihost_kill_worker.py")
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(rank), str(base), db, tmp],
            env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        for rank in (0, 1)
    ]
    try:
        # wait for both readiness sentinels (device plane proven live)
        deadline = _time.time() + 240
        while _time.time() < deadline:
            if all(os.path.exists(os.path.join(tmp, f"ready-{r}"))
                   for r in (0, 1)):
                break
            for p in procs:
                if p.poll() is not None:
                    out, _ = p.communicate()
                    raise AssertionError(f"worker died pre-kill:\n{out}")
            _time.sleep(0.2)
        else:
            raise AssertionError("workers never reached readiness")

        procs[1].send_signal(signal.SIGKILL)
        try:
            out0, _ = procs[0].communicate(timeout=240)
        except subprocess.TimeoutExpired:
            procs[0].kill()
            out0, _ = procs[0].communicate(timeout=30)
            raise AssertionError(
                f"survivor hung past the watchdog; output:\n{out0}")
        assert procs[0].returncode == 0, f"survivor failed:\n{out0}"
        assert "rank 0: KILL OK" in out0, out0
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate(timeout=30)

    # ---- phase 2: redeployment heals the deployment ----------------------
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        base2 = s.getsockname()[1]
    db2 = os.path.join(tmp, "d2.sqlite")
    worker2 = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")
    procs2 = [
        subprocess.Popen(
            [sys.executable, worker2, str(rank), str(base2), db2],
            env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        for rank in (0, 1)
    ]
    outputs = []
    try:
        for p in procs2:
            out, _ = p.communicate(timeout=300)
            outputs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs2:
            p.kill()
        raise
    for rank, (p, out) in enumerate(zip(procs2, outputs)):
        assert p.returncode == 0, f"redeploy rank {rank} failed:\n{out}"
        assert f"rank {rank}: MULTIHOST OK" in out, out
