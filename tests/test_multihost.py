"""Multi-host helpers (single-process degenerate case: one host owns every
shard; the SPMD contract itself is exercised by the shard_map routing
tests, whose per-shard program is identical on a pod)."""

import jax

from pushcdn_tpu.parallel.mesh import make_broker_mesh
from pushcdn_tpu.parallel.multihost import (
    dcn_crossings,
    initialize,
    local_shard_indices,
    pod_broker_mesh,
)


def test_single_host_owns_all_shards():
    initialize()  # no-op off-pod
    mesh = pod_broker_mesh(8)
    assert local_shard_indices(mesh) == list(range(8))
    # one host ⇒ the ring never crosses DCN
    assert dcn_crossings(mesh) == 0
    assert mesh.devices.size == 8


def test_pod_mesh_matches_plain_mesh():
    assert [d.id for d in pod_broker_mesh(4).devices.flat] == \
        [d.id for d in make_broker_mesh(4).devices.flat]
