"""Automated chaos tier: broker churn + connection storm + 9 MB firehose
against a live in-process cluster, asserting ZERO loss for survivors.

The reference ships this tier as manual load binaries — bad-broker
(cdn-broker/src/binaries/bad-broker.rs:57-97: joins the mesh, dies,
rejoins, forever), bad-connector (cdn-client: connect/disconnect churn)
and bad-sender (9 MB message firehose) — run by hand against a cluster.
Here the same three antagonists run INSIDE one pytest for ~8 s while a
survivor publisher streams sequenced messages, and afterwards every
survivor subscriber must hold the complete, in-order sequence: churn of
an unrelated broker, auth-storm load on the marshal, and giant frames
sharing every pipe must not cost one message between healthy peers.
"""

import asyncio
import os

from pushcdn_tpu.broker.broker import Broker, BrokerConfig
from pushcdn_tpu.broker.tasks.heartbeat import heartbeat_once
from pushcdn_tpu.proto.message import Broadcast
from pushcdn_tpu.testing import Cluster, wait_mesh_interest, wait_until

CHAOS_SECONDS = 8.0
SEQ_MSGS = 300          # survivor stream: steady sequenced broadcasts
FIREHOSE_BYTES = 9 * 1024 * 1024  # parity: bad-sender's 9 MB default


async def _churn_bad_broker(cluster: Cluster, stop: asyncio.Event,
                            stats: dict) -> None:
    """bad-broker.rs parity: join the mesh, live briefly, die without
    goodbye, rejoin — forever (until the window closes)."""
    i = 0
    while not stop.is_set():
        pub = f"chaos{cluster.uid}-bad-pub-{i}"
        priv = f"chaos{cluster.uid}-bad-priv-{i}"
        bad = await Broker.new(BrokerConfig(
            run_def=cluster.run_def, keypair=cluster.broker_keypair,
            discovery_endpoint=cluster.db,
            public_advertise_endpoint=pub, public_bind_endpoint=pub,
            private_advertise_endpoint=priv, private_bind_endpoint=priv,
            heartbeat_interval_s=3600, sync_interval_s=3600,
            whitelist_interval_s=3600))
        await bad.start()
        await heartbeat_once(bad)          # dial into the mesh
        for b in cluster.brokers:
            await heartbeat_once(b)        # survivors learn of it
        await asyncio.sleep(0.4)           # live briefly under load
        await bad.stop()                   # die (no goodbye protocol)
        stats["churn_cycles"] = i = i + 1
        await asyncio.sleep(0.1)


async def _connection_storm(cluster: Cluster, stop: asyncio.Event,
                            stats: dict) -> None:
    """bad-connector parity: authenticate through the marshal, hold the
    session a moment, vanish; repeat as fast as the marshal allows."""
    seed = 0
    while not stop.is_set():
        seed += 1
        c = cluster.client(seed=80_000 + seed, topics=[3])
        try:
            async with asyncio.timeout(5):
                await c.ensure_initialized()
            stats["storm_ok"] = stats.get("storm_ok", 0) + 1
        except Exception:
            # a storm connect landing on the dying broker IS the chaos
            stats["storm_fail"] = stats.get("storm_fail", 0) + 1
        finally:
            c.close()
        await asyncio.sleep(0)


async def _firehose(sender, sink, stop: asyncio.Event,
                    stats: dict) -> None:
    """bad-sender parity: 9 MB broadcasts, back to back, on their own
    topic so the survivor stream shares pipes but not subscriptions.
    Both clients were connected to SURVIVOR brokers before churn began;
    transient resets (chaos is chaos) reconnect and continue."""
    blob = os.urandom(FIREHOSE_BYTES)
    while not stop.is_set():
        try:
            await sender.send_broadcast_message([5], blob)
            got = await asyncio.wait_for(sink.receive_message(), 10)
            assert len(bytes(got.message)) == FIREHOSE_BYTES
            stats["firehose_msgs"] = stats.get("firehose_msgs", 0) + 1
        except (Exception, asyncio.TimeoutError):
            stats["firehose_resets"] = stats.get("firehose_resets", 0) + 1
            await asyncio.sleep(0.2)


async def test_chaos_survivors_lose_nothing():
    from pushcdn_tpu.proto.topic import TopicSpace
    cluster = await Cluster(num_brokers=3,
                            topics=TopicSpace.range(8)).start()
    try:
        # survivors: 6 subscribed clients, 2 per broker, all on topic 0
        survivors = []
        for i in range(6):
            await cluster.place_on(i % 3)
            c = cluster.client(seed=70_000 + i, topics=[0])
            await c.ensure_initialized()
            survivors.append(c)
        await wait_until(
            lambda: sum(b.connections.num_users
                        for b in cluster.brokers) == 6)
        await wait_mesh_interest(cluster, topic=0, links=2)

        # firehose clients connect BEFORE churn begins so they live on
        # survivor brokers (a load-0 churn broker wins placement ties)
        fh_sender = cluster.client(seed=90_001, topics=[])
        await cluster.place_on(2)
        fh_sink = cluster.client(seed=90_002, topics=[5])
        await fh_sender.ensure_initialized()
        await fh_sink.ensure_initialized()
        # every broker must be able to route topic 5 (local user or an
        # interested mesh link) before the first giant frame flies
        await wait_until(
            lambda: all(any(b.connections
                            .get_interested_by_topic([5], False)[j]
                            for j in (0, 1))
                        for b in cluster.brokers), timeout=30)

        publisher = survivors[0]
        received = [[] for _ in survivors]

        async def drain(idx: int) -> None:
            while len(received[idx]) < SEQ_MSGS:
                for m in await survivors[idx].receive_messages():
                    assert isinstance(m, Broadcast)
                    received[idx].append(
                        int.from_bytes(bytes(m.message)[:4], "big"))

        stop = asyncio.Event()
        stats: dict = {}
        chaos = [
            asyncio.create_task(_churn_bad_broker(cluster, stop, stats)),
            asyncio.create_task(_connection_storm(cluster, stop, stats)),
            asyncio.create_task(_firehose(fh_sender, fh_sink, stop,
                                          stats)),
        ]
        drains = [asyncio.create_task(drain(i))
                  for i in range(len(survivors))]

        try:
            # the survivor stream: sequenced broadcasts over the window
            interval = CHAOS_SECONDS / SEQ_MSGS
            payload_tail = os.urandom(512)
            for seq in range(SEQ_MSGS):
                await publisher.send_broadcast_message(
                    [0], seq.to_bytes(4, "big") + payload_tail)
                await asyncio.sleep(interval)

            async with asyncio.timeout(60):
                await asyncio.gather(*drains)
        finally:
            # a failing drain must not leave churn running into teardown
            stop.set()
            for t in drains:
                t.cancel()
        chaos_results = await asyncio.gather(*chaos, return_exceptions=True)
        for r in chaos_results:
            assert not isinstance(r, BaseException) \
                or isinstance(r, asyncio.CancelledError), r

        # ---- the zero-loss assertion ---------------------------------
        for idx, seqs in enumerate(received):
            assert seqs == list(range(SEQ_MSGS)), (
                f"survivor {idx} lost/reordered messages: "
                f"got {len(seqs)}, first miss at "
                f"{next((i for i, s in enumerate(seqs) if s != i), '?')}")

        # chaos actually happened
        assert stats.get("churn_cycles", 0) >= 2, stats
        assert stats.get("storm_ok", 0) >= 10, stats
        assert stats.get("firehose_msgs", 0) >= 3, stats

        # ---- convergence: the dead broker aged out of the mesh -------
        for b in cluster.brokers:
            await heartbeat_once(b)
        await wait_until(
            lambda: all(b.connections.num_brokers == 2
                        for b in cluster.brokers), timeout=30)
        for c in survivors:
            c.close()
        fh_sender.close()
        fh_sink.close()
    finally:
        await cluster.stop()
