"""Memory pool / limiter unit tests (parity limiter/pool.rs behavior)."""

import asyncio

import pytest

from pushcdn_tpu.proto.error import Error, ErrorKind
from pushcdn_tpu.proto.limiter import Bytes, MemoryPool


async def test_allocate_and_release():
    pool = MemoryPool(1000)
    p = await pool.allocate(600)
    assert pool.available == 400
    p.release()
    assert pool.available == 1000
    # double release is a no-op
    p.release()
    assert pool.available == 1000


async def test_oversized_allocation_errors_not_deadlocks():
    pool = MemoryPool(100)
    with pytest.raises(Error) as ei:
        await pool.allocate(101)
    assert ei.value.kind == ErrorKind.EXCEEDED_SIZE


async def test_blocking_until_release_fifo():
    pool = MemoryPool(100)
    p1 = await pool.allocate(80)
    big = asyncio.create_task(pool.allocate(60))
    await asyncio.sleep(0.05)
    assert not big.done()
    # FIFO fairness: a small allocation queued behind the big one must not
    # starve it even though it would fit right now.
    small = asyncio.create_task(pool.allocate(10))
    await asyncio.sleep(0.05)
    assert not small.done()
    p1.release()
    p_big = await asyncio.wait_for(big, 5)
    p_small = await asyncio.wait_for(small, 5)
    assert pool.available == 100 - 60 - 10
    p_big.release()
    p_small.release()


async def test_cancelled_waiter_does_not_leak():
    pool = MemoryPool(100)
    p1 = await pool.allocate(100)
    waiter = asyncio.create_task(pool.allocate(50))
    await asyncio.sleep(0.05)
    waiter.cancel()
    with pytest.raises(asyncio.CancelledError):
        await waiter
    p1.release()
    assert pool.available == 100


async def test_bytes_refcounted_fanout_release():
    """Permit returns to the pool only when the LAST clone releases —
    exactly the reference's fan-out lifetime (pool.rs:7-14)."""
    pool = MemoryPool(1000)
    permit = await pool.allocate(500)
    b = Bytes(b"x" * 500, permit)
    clones = [b.clone() for _ in range(7)]
    b.release()
    for c in clones[:-1]:
        c.release()
    assert pool.available == 500  # still held by the final clone
    clones[-1].release()
    assert pool.available == 1000


async def test_latency_sample_recorded():
    pool = MemoryPool(100)
    p = await pool.allocate(10)
    await asyncio.sleep(0.01)
    p.release()
    assert pool.latency_samples and pool.latency_samples[0] >= 0.009
