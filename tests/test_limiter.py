"""Memory pool / limiter unit tests (parity limiter/pool.rs behavior)."""

import asyncio

import pytest

from pushcdn_tpu.proto.error import Error, ErrorKind
from pushcdn_tpu.proto.limiter import Bytes, MemoryPool


async def test_allocate_and_release():
    pool = MemoryPool(1000)
    p = await pool.allocate(600)
    assert pool.available == 400
    p.release()
    assert pool.available == 1000
    # double release is a no-op
    p.release()
    assert pool.available == 1000


async def test_oversized_allocation_errors_not_deadlocks():
    pool = MemoryPool(100)
    with pytest.raises(Error) as ei:
        await pool.allocate(101)
    assert ei.value.kind == ErrorKind.EXCEEDED_SIZE


async def test_blocking_until_release_fifo():
    pool = MemoryPool(100)
    p1 = await pool.allocate(80)
    big = asyncio.create_task(pool.allocate(60))
    await asyncio.sleep(0.05)
    assert not big.done()
    # FIFO fairness: a small allocation queued behind the big one must not
    # starve it even though it would fit right now.
    small = asyncio.create_task(pool.allocate(10))
    await asyncio.sleep(0.05)
    assert not small.done()
    p1.release()
    p_big = await asyncio.wait_for(big, 5)
    p_small = await asyncio.wait_for(small, 5)
    assert pool.available == 100 - 60 - 10
    p_big.release()
    p_small.release()


async def test_cancelled_waiter_does_not_leak():
    pool = MemoryPool(100)
    p1 = await pool.allocate(100)
    waiter = asyncio.create_task(pool.allocate(50))
    await asyncio.sleep(0.05)
    waiter.cancel()
    with pytest.raises(asyncio.CancelledError):
        await waiter
    p1.release()
    assert pool.available == 100


async def test_bytes_refcounted_fanout_release():
    """Permit returns to the pool only when the LAST clone releases —
    exactly the reference's fan-out lifetime (pool.rs:7-14)."""
    pool = MemoryPool(1000)
    permit = await pool.allocate(500)
    b = Bytes(b"x" * 500, permit)
    clones = [b.clone() for _ in range(7)]
    b.release()
    for c in clones[:-1]:
        c.release()
    assert pool.available == 500  # still held by the final clone
    clones[-1].release()
    assert pool.available == 1000


async def test_latency_sample_recorded():
    pool = MemoryPool(100)
    p = await pool.allocate(10)
    await asyncio.sleep(0.01)
    p.release()
    assert pool.latency_samples and pool.latency_samples[0] >= 0.009


# ---------------------------------------------------------------------------
# batch-release invariants under the coalescing egress paths
# ---------------------------------------------------------------------------

async def _permit_frames(pool, n, size):
    frames = []
    for i in range(n):
        permit = await pool.allocate(size)
        frames.append(Bytes(bytes([i % 251]) * size, permit))
    return frames


async def test_batched_send_releases_every_clone():
    """send_raw_many hands a whole fan-out batch to the writer as ONE
    entry; after the coalesced flush every clone's permit must be back in
    the pool (no per-frame path may be skipped by batching)."""
    from pushcdn_tpu.proto.limiter import Limiter
    from pushcdn_tpu.proto.transport.memory import gen_testing_connection_pair

    limiter = Limiter(global_pool_bytes=100_000)
    a, b = await gen_testing_connection_pair()
    pool = limiter.pool
    frames = await _permit_frames(pool, 20, 1000)
    assert pool.available == 80_000
    clones = [f.clone() for f in frames]
    await a.send_raw_many(clones, flush=True)  # flush ⇒ writer done
    for f in frames:
        f.release()
    assert pool.available == 100_000  # originals + flushed clones
    got = 0
    while got < 20:
        got += len(await asyncio.wait_for(b.recv_raw_many(), 5))
    a.close()
    b.close()


async def test_pre_encoded_batch_releases_at_encode_time():
    """The routing loops' pre-encode path copies the batch into one owned
    buffer, so the frames' permits free at ENCODE time (before the wire
    flush) — and the receiver still sees every frame intact."""
    import pytest as _pytest
    from pushcdn_tpu.broker.tasks.senders import pre_encode_frames
    from pushcdn_tpu.proto.transport.memory import gen_testing_connection_pair

    pool = MemoryPool(100_000)
    frames = await _permit_frames(pool, 10, 2000)
    encoded = pre_encode_frames(frames)
    if encoded is None:
        _pytest.skip("native batch encoder unavailable in this image")
    for f in frames:
        f.release()
    assert pool.available == 100_000  # permits home before any flush
    a, b = await gen_testing_connection_pair()
    await a.send_encoded(encoded, flush=True)
    got = []
    while len(got) < 10:
        got.extend(await asyncio.wait_for(b.recv_raw_many(), 5))
    assert [len(g.data) for g in got] == [2000] * 10
    assert all(bytes(g.data) == bytes([i % 251]) * 2000
               for i, g in enumerate(got))
    for g in got:
        g.release()
    a.close()
    b.close()


async def test_close_with_queued_batches_returns_permits():
    """A connection torn down with un-flushed coalesced batches queued
    must hand every clone's permit back via the drain (the writer never
    ran for them)."""
    from pushcdn_tpu.proto.transport.base import Connection
    from pushcdn_tpu.proto.transport.memory import _BoundedBuffer, _PipeStream

    pool = MemoryPool(50_000)
    # a pipe nobody reads from, with a tiny window: the writer jams
    tx = _BoundedBuffer(64)
    rx = _BoundedBuffer(64)
    conn = Connection(_PipeStream(rx=rx, tx=tx), label="jammed")
    frames = await _permit_frames(pool, 10, 1000)
    await conn.send_raw_many([f.clone() for f in frames])
    await conn.send_raw_many([f.clone() for f in frames])
    await asyncio.sleep(0.05)  # writer picks up batch 1 and jams mid-flush
    conn.close()
    await asyncio.sleep(0.05)
    for f in frames:
        f.release()
    # whatever the jammed writer held was cancelled + released; the
    # queued second batch drained synchronously in close()
    assert pool.available == 50_000
