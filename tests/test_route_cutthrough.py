"""Batch-vs-scalar routing equivalence (ISSUE 3 property test).

Drives IDENTICAL seeded frame mixes (broadcast / direct / control /
garbage) through both ``--route-impl`` paths — the native cut-through
plane and the scalar receive loops — on identical broker topologies, and
asserts:

- identical per-peer delivery SEQUENCES (payload lists, order included:
  per-(sender→receiver) order is part of the cut-through contract);
- identical disconnect decisions (malformed frames, invalid-topic
  subscribes, kind-policy violations);
- permit balance: the broker's byte pool refills completely once every
  receiver has drained and released (no leaked chunk permits, no leaked
  egress leases).

The mixes deliberately include the frames that force the plan to stop and
resume (Subscribe before a Broadcast on the just-subscribed topic, sync
payloads, truncated/garbage frames), because that residual seam is where
batch and scalar semantics could drift.
"""

import asyncio
import gc

import numpy as np
import pytest

from pushcdn_tpu.broker.tasks import cutthrough
from pushcdn_tpu.broker.test_harness import TestDefinition
from pushcdn_tpu.broker.versioned_map import VersionedMap
from pushcdn_tpu.proto.message import (
    AuthenticateWithPermit,
    Broadcast,
    Direct,
    Subscribe,
    TopicSync,
    Unsubscribe,
    UserSync,
    serialize,
)
from pushcdn_tpu.proto.transport.base import FrameChunk
from pushcdn_tpu.proto.transport.memory import Memory

pytestmark = pytest.mark.skipif(
    not cutthrough.routeplan.available(),
    reason="native route-plan kernel unavailable (no working g++)")

# topology shared by every mix: sender is user 0 / peer 0; receivers are
# users 1-4 (topics {0}, {0}, {1}, {}) and peer brokers (topic sets below)
USER_TOPICS = [[], [0], [0], [1], []]
BROKER_DEFS = [([0], [b"remote-user"]), ([1], [])]
KNOWN_DIRECTS = [b"user-1", b"user-2", b"user-3", b"user-4",
                 b"remote-user", b"nobody-home"]


def _sync_payload(ident: str) -> bytes:
    m = VersionedMap(local_identity=ident)
    m.insert(b"synced-user", ident)
    return VersionedMap.serialize_entries(m.full())


def _gen_frames(rng: np.random.Generator, n: int, as_user: bool):
    """A seeded mix of wire frames. Returns (frames, may_disconnect)."""
    frames = []
    for _ in range(n):
        roll = rng.integers(0, 100)
        payload = bytes(rng.integers(0, 256, int(rng.integers(1, 64)),
                                     dtype=np.uint8))
        if roll < 55:
            # broadcasts, sometimes with invalid (7) or duplicate topics
            topics = [int(t) for t in rng.choice(
                [0, 1, 7], size=int(rng.integers(1, 4)))]
            frames.append(serialize(Broadcast(topics, payload)))
        elif roll < 80:
            rcpt = KNOWN_DIRECTS[int(rng.integers(0, len(KNOWN_DIRECTS)))]
            frames.append(serialize(Direct(rcpt, payload)))
        elif roll < 88:
            topics = [int(t) for t in rng.choice(
                [0, 1, 7] if not as_user else [0, 1],
                size=int(rng.integers(1, 3)))]
            frames.append(serialize(Subscribe(topics)))
        elif roll < 93:
            frames.append(serialize(Unsubscribe([0])))
        elif roll < 96:
            frames.append(serialize(UserSync(_sync_payload(
                "testbrokerpub-0:0/testbrokerpriv-0:0"))))
        elif roll < 98:
            frames.append(serialize(TopicSync(_sync_payload(
                "testbrokerpub-0:0/testbrokerpriv-0:0"))))
        elif roll < 99:
            frames.append(serialize(AuthenticateWithPermit(permit=7)))
        else:
            frames.append(b"\xfe" + payload)  # garbage: unknown kind
    return frames


async def _drain_all(conn, settle_s: float = 0.05):
    """Collect every delivered frame (as bytes) until silence."""
    got = []
    while True:
        try:
            items = await asyncio.wait_for(conn.recv_frames(), settle_s)
        except (asyncio.TimeoutError, Exception):
            return got
        for item in items:
            if type(item) is FrameChunk:
                got.extend(bytes(mv) for mv in item.views())
            else:
                got.append(bytes(item.data))
            item.release()


async def _run_mix(impl: str, frames, as_user: bool, chunked: bool):
    """Run one mix through one implementation. Returns (per-peer delivery
    lists, sender-still-connected, pool-balanced)."""
    prev_impl = cutthrough.ROUTE_IMPL
    prev_win = Memory.set_duplex_window(512 * 1024)
    cutthrough.ROUTE_IMPL = impl
    try:
        run = await TestDefinition(connected_users=USER_TOPICS,
                                   connected_brokers=BROKER_DEFS).run()
        try:
            sender = (run.user(0) if as_user else run.peer(0)).remote
            try:
                if chunked:
                    # one batch ⇒ arrives as FrameChunk(s): the plan path
                    await sender.send_raw_many(list(frames), flush=True)
                else:
                    # flushed singles ⇒ depth-1 Bytes: the residual path
                    for f in frames:
                        await sender.send_raw(f, flush=True)
            except Exception:
                pass  # peer disconnected us mid-send: a legal outcome
            await asyncio.sleep(0.15)

            deliveries = {}
            for i in range(1, len(USER_TOPICS)):
                deliveries[f"user-{i}"] = await _drain_all(
                    run.user(i).remote)
            for j in range(len(BROKER_DEFS)):
                if not (not as_user and j == 0):  # skip the sender itself
                    deliveries[f"peer-{j}"] = await _drain_all(
                        run.peer(j).remote)
            if as_user:
                deliveries["user-0"] = await _drain_all(run.user(0).remote)

            if as_user:
                alive = run.broker.connections.has_user(b"user-0")
            else:
                alive = run.broker.connections.has_broker(
                    run.peer(0).identifier)

            # permit balance: everything drained+released above; the pool
            # must refill (leases release via refcount/GC)
            pool = run.broker.limiter.pool
            balanced = True
            if pool is not None:
                for _ in range(10):
                    gc.collect()
                    if pool.available == pool.capacity:
                        break
                    await asyncio.sleep(0.02)
                balanced = pool.available == pool.capacity
            return deliveries, alive, balanced
        finally:
            await run.shutdown()
    finally:
        cutthrough.ROUTE_IMPL = prev_impl
        Memory.set_duplex_window(prev_win)


@pytest.mark.parametrize("seed", range(8))
async def test_user_mix_equivalence(seed):
    rng = np.random.default_rng(1000 + seed)
    frames = _gen_frames(rng, 60, as_user=True)
    d_native, alive_n, bal_n = await _run_mix("native", frames,
                                              as_user=True, chunked=True)
    d_python, alive_p, bal_p = await _run_mix("python", frames,
                                              as_user=True, chunked=True)
    assert alive_n == alive_p, f"seed {seed}: disconnect decisions differ"
    assert d_native == d_python, f"seed {seed}: delivery sets differ"
    assert bal_n and bal_p, f"seed {seed}: pool permits leaked"


@pytest.mark.parametrize("seed", range(4))
async def test_broker_mix_equivalence(seed):
    rng = np.random.default_rng(2000 + seed)
    frames = _gen_frames(rng, 60, as_user=False)
    d_native, alive_n, bal_n = await _run_mix("native", frames,
                                              as_user=False, chunked=True)
    d_python, alive_p, bal_p = await _run_mix("python", frames,
                                              as_user=False, chunked=True)
    assert alive_n == alive_p, f"seed {seed}: link-drop decisions differ"
    assert d_native == d_python, f"seed {seed}: delivery sets differ"
    assert bal_n and bal_p, f"seed {seed}: pool permits leaked"


async def test_subscribe_then_broadcast_same_chunk():
    """The residual seam: a Subscribe and a Broadcast on the just-
    subscribed topic land in ONE chunk — the plan must stop, apply the
    subscription, rebuild, and deliver the broadcast back to the sender
    (scalar parity with test_broadcast_from_user's self-delivery)."""
    frames = [serialize(Subscribe([1])),
              serialize(Broadcast([1], b"fresh-topic")),
              serialize(Unsubscribe([1])),
              serialize(Broadcast([1], b"after-unsub"))]
    for impl in ("native", "python"):
        deliveries, alive, balanced = await _run_mix(
            impl, frames, as_user=True, chunked=True)
        assert alive and balanced, impl
        assert deliveries["user-0"] == [
            serialize(Broadcast((1,), b"fresh-topic"))], (impl, deliveries)
        # user-3 is subscribed to topic 1 throughout: gets both broadcasts
        assert deliveries["user-3"] == [
            serialize(Broadcast((1,), b"fresh-topic")),
            serialize(Broadcast((1,), b"after-unsub"))], (impl, deliveries)


async def test_traced_frame_mid_chunk_equivalence():
    """ISSUE 4 trace propagation: a traced Broadcast mid-chunk stops the
    plan on the kind-tag flag bit and takes the instrumented scalar path,
    while the rest of the chunk stays batched. Both implementations must
    produce identical per-peer delivery sequences (the traced wire frame
    forwarded VERBATIM), the broker must emit the ingress/plan/egress
    span chain, and the native run must still cut through the untraced
    neighbors."""
    from pushcdn_tpu.proto import metrics as metrics_mod
    from pushcdn_tpu.proto import trace as trace_lib

    tr = trace_lib.new_trace()
    traced = trace_lib.stamp_frame(
        serialize(Broadcast([0], b"traced-payload")), tr)
    frames = ([serialize(Broadcast([0], b"pre-%d" % i)) for i in range(6)]
              + [traced]
              + [serialize(Broadcast([0], b"post-%d" % i))
                 for i in range(6)])

    results = {}
    for impl in ("native", "python"):
        cut0 = metrics_mod.ROUTE_CUTTHROUGH_FRAMES.value
        res0 = metrics_mod.ROUTE_RESIDUAL_FRAMES.value
        trace_lib.recent.clear()
        deliveries, alive, balanced = await _run_mix(
            impl, frames, as_user=True, chunked=True)
        assert alive and balanced, impl
        hops = {h for h, tid, *_ in trace_lib.recent if tid == tr[0]}
        assert {"ingress", "plan", "egress"} <= hops, (impl, hops)
        results[impl] = deliveries
        if impl == "native":
            # the 12 untraced neighbors cut through; exactly the traced
            # frame went residual
            assert metrics_mod.ROUTE_CUTTHROUGH_FRAMES.value - cut0 >= 12
            assert metrics_mod.ROUTE_RESIDUAL_FRAMES.value - res0 == 1
    assert results["native"] == results["python"]
    # topic-0 subscribers received the traced frame VERBATIM (flag +
    # trace block intact), in arrival order
    for peer in ("user-1", "user-2"):
        got = results["native"][peer]
        assert got[6] == traced, peer
        assert len(got) == 13


# ---------------------------------------------------------------------------
# ISSUE 6: 1-shard vs N-shard equivalence — the cross-shard handoff must
# be semantically invisible (identical per-peer delivery SEQUENCES per
# connection, identical disconnect decisions, balanced pool permits on
# EVERY shard's byte pool)
# ---------------------------------------------------------------------------

async def _run_sharded_mix(impl: str, frames, as_user: bool,
                           num_shards: int = 2):
    """The sharded twin of ``_run_mix``: same topology, users spread
    round-robin across worker shards (sender user-0 / peer-0 on shard 0),
    every frame batch sent as one chunk."""
    from pushcdn_tpu.testing.shardharness import run_sharded
    prev_impl = cutthrough.ROUTE_IMPL
    prev_win = Memory.set_duplex_window(512 * 1024)
    cutthrough.ROUTE_IMPL = impl
    try:
        run = await run_sharded(
            [(i % num_shards, topics)
             for i, topics in enumerate(USER_TOPICS)],
            num_shards=num_shards, connected_brokers=BROKER_DEFS)
        try:
            sender = (run.user(0) if as_user else run.peer(0)).remote
            try:
                await sender.send_raw_many(list(frames), flush=True)
            except Exception:
                pass  # disconnected mid-send: a legal outcome
            await asyncio.sleep(0.15)
            await run.settle(40)

            deliveries = {}
            for i in range(1, len(USER_TOPICS)):
                deliveries[f"user-{i}"] = await _drain_all(
                    run.user(i).remote)
            for j in range(len(BROKER_DEFS)):
                if not (not as_user and j == 0):
                    deliveries[f"peer-{j}"] = await _drain_all(
                        run.peer(j).remote)
            if as_user:
                deliveries["user-0"] = await _drain_all(run.user(0).remote)

            shard0 = run.brokers[0]
            if as_user:
                alive = shard0.connections.has_user(b"user-0")
            else:
                alive = shard0.connections.has_broker(
                    run.peer(0).identifier)

            balanced = True
            for broker in run.brokers:
                pool = broker.limiter.pool
                if pool is None:
                    continue
                for _ in range(20):
                    gc.collect()
                    if pool.available == pool.capacity:
                        break
                    await asyncio.sleep(0.02)
                balanced = balanced and pool.available == pool.capacity
            return deliveries, alive, balanced
        finally:
            await run.shutdown()
    finally:
        cutthrough.ROUTE_IMPL = prev_impl
        Memory.set_duplex_window(prev_win)


@pytest.mark.parametrize("seed", range(4))
async def test_sharded_user_mix_equivalence(seed):
    """Seeded user-origin mixes through a 2-shard group vs the 1-shard
    broker: identical per-peer delivery sequences, disconnects, permit
    balance — with the sender's fan-out crossing the handoff rings for
    the odd-shard receivers."""
    rng = np.random.default_rng(5000 + seed)
    frames = _gen_frames(rng, 50, as_user=True)
    d_shard, alive_s, bal_s = await _run_sharded_mix("native", frames,
                                                     as_user=True)
    d_single, alive_1, bal_1 = await _run_mix("native", frames,
                                              as_user=True, chunked=True)
    assert alive_s == alive_1, f"seed {seed}: disconnect decisions differ"
    assert d_shard == d_single, f"seed {seed}: delivery sequences differ"
    assert bal_s and bal_1, f"seed {seed}: pool permits leaked"


@pytest.mark.parametrize("seed", range(2))
async def test_sharded_broker_mix_equivalence(seed):
    """Broker-origin (mesh) mixes arrive on shard 0 and must reach
    sibling-shard users over the rings with local-users-only semantics
    intact (no loop, no mesh re-forward)."""
    rng = np.random.default_rng(6000 + seed)
    frames = _gen_frames(rng, 50, as_user=False)
    d_shard, alive_s, bal_s = await _run_sharded_mix("native", frames,
                                                     as_user=False)
    d_single, alive_1, bal_1 = await _run_mix("native", frames,
                                              as_user=False, chunked=True)
    assert alive_s == alive_1, f"seed {seed}: link-drop decisions differ"
    assert d_shard == d_single, f"seed {seed}: delivery sequences differ"
    assert bal_s and bal_1, f"seed {seed}: pool permits leaked"


async def test_sharded_scalar_impl_equivalence():
    """The scalar loops drive the same shard-egress seam (EgressBatch
    ``to_shard``): a python-impl sharded run must match the 1-shard run
    too — the handoff isn't a cut-through-only feature."""
    rng = np.random.default_rng(7000)
    frames = _gen_frames(rng, 40, as_user=True)
    d_shard, alive_s, bal_s = await _run_sharded_mix("python", frames,
                                                     as_user=True)
    d_single, alive_1, bal_1 = await _run_mix("python", frames,
                                              as_user=True, chunked=True)
    assert alive_s == alive_1
    assert d_shard == d_single
    assert bal_s and bal_1


async def test_sharded_subscribe_propagates_to_sibling():
    """A Subscribe on one shard must reach sibling snapshots (versioned
    delta via the bus) before later traffic routes: sender on shard 0
    subscribes, a sibling-shard user broadcasts, sender receives."""
    from pushcdn_tpu.testing.shardharness import run_sharded
    prev = Memory.set_duplex_window(512 * 1024)
    try:
        run = await run_sharded([(0, []), (1, [])], num_shards=2)
        try:
            await run.user(0).remote.send_raw(
                serialize(Subscribe([1])), flush=True)
            await run.settle(30)
            await run.user(1).remote.send_raw(
                serialize(Broadcast([1], b"cross-shard-pub")), flush=True)
            await run.settle(40)
            got = await _drain_all(run.user(0).remote)
            assert got == [serialize(Broadcast((1,), b"cross-shard-pub"))]
        finally:
            await run.shutdown()
    finally:
        Memory.set_duplex_window(prev)


def _gen_churn_frames(rng: np.random.Generator, n: int):
    """A control-frame-heavy mix (ISSUE 7): the regime where incremental
    deltas vs full rebuilds could diverge — every hot frame is planned
    against a snapshot that just absorbed a mutation."""
    frames = []
    for _ in range(n):
        roll = rng.integers(0, 100)
        payload = bytes(rng.integers(0, 256, int(rng.integers(1, 48)),
                                     dtype=np.uint8))
        if roll < 30:
            topics = [int(t) for t in rng.choice(
                [0, 1], size=int(rng.integers(1, 3)))]
            frames.append(serialize(Broadcast(topics, payload)))
        elif roll < 45:
            rcpt = KNOWN_DIRECTS[int(rng.integers(0, len(KNOWN_DIRECTS)))]
            frames.append(serialize(Direct(rcpt, payload)))
        elif roll < 70:
            frames.append(serialize(Subscribe(
                [int(t) for t in rng.choice([0, 1],
                                            size=int(rng.integers(1, 3)))])))
        elif roll < 90:
            frames.append(serialize(Unsubscribe([int(rng.integers(0, 2))])))
        elif roll < 97:
            frames.append(serialize(UserSync(_sync_payload(
                "testbrokerpub-0:0/testbrokerpriv-0:0"))))
        else:
            frames.append(serialize(TopicSync(_sync_payload(
                "testbrokerpub-0:0/testbrokerpriv-0:0"))))
    return frames


async def _run_mix_incremental(incremental: bool, frames, as_user: bool):
    """_run_mix with the native impl's maintenance mode forced: True =
    in-place deltas (ISSUE 7 default), False = the rebuild-per-
    invalidation baseline (churn guard armed)."""
    prev = cutthrough.ROUTE_INCREMENTAL
    cutthrough.ROUTE_INCREMENTAL = incremental
    try:
        return await _run_mix("native", frames, as_user=as_user,
                              chunked=True)
    finally:
        cutthrough.ROUTE_INCREMENTAL = prev


@pytest.mark.parametrize("seed", range(4))
async def test_churn_mix_incremental_vs_rebuild_vs_python(seed):
    """ISSUE 7: under subscribe-churn-heavy mixes, the incremental delta
    path, the full-rebuild baseline, and the scalar loops must produce
    identical per-peer delivery SEQUENCES and disconnect decisions."""
    rng = np.random.default_rng(8000 + seed)
    frames = _gen_churn_frames(rng, 70)
    d_inc, alive_i, bal_i = await _run_mix_incremental(True, frames,
                                                       as_user=True)
    d_reb, alive_r, bal_r = await _run_mix_incremental(False, frames,
                                                       as_user=True)
    d_py, alive_p, bal_p = await _run_mix("python", frames,
                                          as_user=True, chunked=True)
    assert alive_i == alive_r == alive_p, f"seed {seed}: disconnects differ"
    assert d_inc == d_reb == d_py, f"seed {seed}: delivery sequences differ"
    assert bal_i and bal_r and bal_p, f"seed {seed}: pool permits leaked"


@pytest.mark.parametrize("seed", range(2))
async def test_churn_mix_sharded_incremental(seed):
    """The 2-shard flavor: sibling-shard deltas (shard_notifier stream)
    keep every worker's incremental snapshot converged — same sequences
    as the 1-shard rebuild baseline."""
    rng = np.random.default_rng(8500 + seed)
    frames = _gen_churn_frames(rng, 50)
    d_shard, alive_s, bal_s = await _run_sharded_mix("native", frames,
                                                     as_user=True)
    d_single, alive_1, bal_1 = await _run_mix_incremental(False, frames,
                                                          as_user=True)
    assert alive_s == alive_1, f"seed {seed}: disconnect decisions differ"
    assert d_shard == d_single, f"seed {seed}: delivery sequences differ"
    assert bal_s and bal_1, f"seed {seed}: pool permits leaked"


async def test_depth1_singles_equivalence():
    """Flushed singles ride the depth-1 Bytes path through the cut-through
    drain; decisions must still match the scalar loops."""
    rng = np.random.default_rng(77)
    frames = _gen_frames(rng, 25, as_user=True)
    d_native, alive_n, bal_n = await _run_mix("native", frames,
                                              as_user=True, chunked=False)
    d_python, alive_p, bal_p = await _run_mix("python", frames,
                                              as_user=True, chunked=False)
    assert alive_n == alive_p
    assert d_native == d_python
    assert bal_n and bal_p


def test_sharded_route_direct_directmap_precedence():
    """The sharded scalar route_direct must give the DirectMap owner the
    same precedence the unsharded path (and the cut-through plan's dmap)
    does: a user the mesh already re-homed to another broker is FORWARDED
    even while the local eviction delta is still in flight — delivering
    to the stale local connection would diverge from the N==1 decision."""
    from pushcdn_tpu.broker.tasks.handlers import EgressBatch, route_direct

    class _Raw:
        def clone(self):
            return self

        def release(self):
            pass

    class _Conns:
        num_shards = 2
        identity = "pub:me/priv:me"

        def __init__(self):
            self.users = {}
            self.remote_user_shard = {}
            self.brokers = {}
            self.remote_broker_shard = {}
            self.direct = {}
            self.parting = {}

        def get_broker_identifier_of_user(self, key):
            return self.direct.get(key)

    class _Broker:
        def __init__(self):
            self.connections = _Conns()

    other = "pub:other/priv:other"

    # re-homed user with a stale local connection: forward to the owner
    broker = _Broker()
    broker.connections.users[b"u"] = object()
    broker.connections.brokers[other] = object()
    broker.connections.direct[b"u"] = other
    egress = EgressBatch(broker)
    route_direct(broker, b"u", _Raw(), to_user_only=False, egress=egress)
    assert list(egress.brokers) == [other]
    assert not egress.users and not egress.shards

    # same state, broker-origin frame: one-hop rule drops it
    egress = EgressBatch(broker)
    route_direct(broker, b"u", _Raw(), to_user_only=True, egress=egress)
    assert not egress.brokers and not egress.users and not egress.shards

    # owner is this box: local connection delivers
    broker = _Broker()
    broker.connections.users[b"u"] = object()
    broker.connections.direct[b"u"] = _Conns.identity
    egress = EgressBatch(broker)
    route_direct(broker, b"u", _Raw(), to_user_only=False, egress=egress)
    assert list(egress.users) == [b"u"] and not egress.shards

    # sibling-shard user (no DirectMap entry off shard 0): ride the ring
    broker = _Broker()
    broker.connections.remote_user_shard[b"u"] = 1
    egress = EgressBatch(broker)
    route_direct(broker, b"u", _Raw(), to_user_only=False, egress=egress)
    assert list(egress.shards) == [1] and not egress.users

    # re-homed user whose mesh link lives on shard 0: ring to the link
    broker = _Broker()
    broker.connections.direct[b"u"] = other
    broker.connections.remote_broker_shard[other] = 0
    egress = EgressBatch(broker)
    route_direct(broker, b"u", _Raw(), to_user_only=False, egress=egress)
    assert list(egress.shards) == [0] and not egress.brokers


# ---------------------------------------------------------------------------
# ISSUE 17: fused-pump equivalence — the same seeded mixes over REAL
# loopback TCP, python-scalar vs native cut-through vs the engaged pump.
# The pump's escalation taxonomy (control / traced / garbage / durable)
# must be semantically invisible: per-peer delivery sequences, disconnect
# decisions, and pool balance all byte-identical to the reference legs.
# ---------------------------------------------------------------------------

from pushcdn_tpu.native import pump as _npump  # noqa: E402
from pushcdn_tpu.native import uring as _nuring  # noqa: E402
from pushcdn_tpu.proto.transport import pump as _pump_mod  # noqa: E402
from pushcdn_tpu.proto.transport import uring as _umod  # noqa: E402

_PUMP_OK = _nuring.available() and _npump.available()
requires_pump = pytest.mark.skipif(
    not _PUMP_OK,
    reason="fused pump needs io_uring + the native route-plan kernel")

# legs: scalar reference, cut-through reference, fused pump
_PUMP_LEGS = (("asyncio", "python", "off"),
              ("uring", "native", "off"),
              ("uring", "native", "auto"))


def _gen_pump_frames(rng: np.random.Generator, n: int, popularity: str):
    """A seeded mix covering every pump escalation class: broadcasts
    (uniform or zipf topic popularity), directs, control (sub/unsub),
    traced frames, and trailing garbage. The warmup prefix guarantees
    the pump leg engages before the interesting frames arrive."""
    from pushcdn_tpu.proto import trace as trace_lib

    if popularity == "zipf":
        # heavy head on topic 0, thin tail on topic 1
        topic_p = np.array([0.85, 0.15])
    else:
        topic_p = np.array([0.5, 0.5])

    def pick_topics(k):
        return [int(t) for t in rng.choice([0, 1], size=k, p=topic_p)]

    frames = [serialize(Broadcast([0], b"warm-%02d" % i)) for i in range(8)]
    for _ in range(n):
        roll = rng.integers(0, 100)
        payload = bytes(rng.integers(0, 256, int(rng.integers(1, 256)),
                                     dtype=np.uint8))
        if roll < 60:
            frames.append(serialize(Broadcast(
                pick_topics(int(rng.integers(1, 3))), payload)))
        elif roll < 78:
            rcpt = KNOWN_DIRECTS[int(rng.integers(0, len(KNOWN_DIRECTS)))]
            frames.append(serialize(Direct(rcpt, payload)))
        elif roll < 86:
            frames.append(serialize(Subscribe(pick_topics(1))))
        elif roll < 92:
            frames.append(serialize(Unsubscribe([0])))
        elif roll < 97:
            tr = trace_lib.new_trace()
            frames.append(trace_lib.stamp_frame(
                serialize(Broadcast(pick_topics(1), payload)), tr))
        else:
            frames.append(b"\xfe" + payload)  # garbage: unknown kind
    return frames


async def _drain_tcp(user, quiet=0.3):
    """Every frame a TCP user receives until silence, as full bytes."""
    got = []
    while True:
        try:
            raw = await asyncio.wait_for(user.remote.recv_raw(), quiet)
        except (asyncio.TimeoutError, Exception):
            return got
        if type(raw) is FrameChunk:
            got.extend(bytes(mv) for mv in raw.views())
        elif hasattr(raw, "data"):
            got.append(bytes(raw.data))
        else:
            got.append(bytes(raw))
        if hasattr(raw, "release"):
            raw.release()


async def _run_mix_pump(io_impl, route_impl, pump, frames, retain=None):
    """One mix through one (io, route, pump) leg over loopback TCP.
    Returns (deliveries, sender-alive, balanced, pump-summary)."""
    import os as _os

    prev_impl = cutthrough.ROUTE_IMPL
    saved = (_umod._resolved, _umod._warned_demote,
             _pump_mod.PUMP_IMPL, _pump_mod._warned_demote)
    prev_retain = _os.environ.get("PUSHCDN_RETAIN_TOPICS")
    _umod.set_io_impl(io_impl)
    cutthrough.ROUTE_IMPL = route_impl
    _pump_mod.set_pump_impl(pump)
    if retain is not None:
        _os.environ["PUSHCDN_RETAIN_TOPICS"] = retain
    else:
        _os.environ.pop("PUSHCDN_RETAIN_TOPICS", None)
    try:
        run = await TestDefinition(connected_users=USER_TOPICS,
                                   connected_brokers=BROKER_DEFS,
                                   tcp_users=True).run()
        try:
            sender = run.user(0).remote
            try:
                # warmup wave first, then an idle gap: the pump leg
                # engages before the seeded mix arrives (a no-op for the
                # reference legs — deliveries stay identical)
                await sender.send_raw_many(list(frames[:8]), flush=True)
                await asyncio.sleep(0.2)
                await sender.send_raw_many(list(frames[8:]), flush=True)
            except Exception:
                pass  # disconnected mid-send: a legal outcome
            await asyncio.sleep(0.3)

            deliveries = {}
            for i in range(1, len(USER_TOPICS)):
                deliveries[f"user-{i}"] = await _drain_tcp(run.user(i))
            for j in range(len(BROKER_DEFS)):
                deliveries[f"peer-{j}"] = await _drain_all(
                    run.peer(j).remote)
            deliveries["user-0"] = await _drain_tcp(run.user(0))
            alive = run.broker.connections.has_user(b"user-0")
            summary = None
            state = getattr(run.broker, "_route_state", None)
            ps = getattr(state, "_pump_state", None)
            if ps is not None and not ps.closed:
                summary = ps.summary()
            pool = run.broker.limiter.pool
            balanced = True
            if pool is not None and retain is None:
                # with retention on, the rings legitimately park leases
                # until broker close — balance is checked post-shutdown
                for _ in range(20):
                    gc.collect()
                    if pool.available == pool.capacity:
                        break
                    await asyncio.sleep(0.02)
                balanced = pool.available == pool.capacity
            return deliveries, alive, balanced, summary
        finally:
            await run.shutdown()
            pool = run.broker.limiter.pool
            if retain is not None and pool is not None:
                for _ in range(20):
                    gc.collect()
                    if pool.available == pool.capacity:
                        break
                    await asyncio.sleep(0.02)
                assert pool.available == pool.capacity, (
                    "retained leases leaked past broker close")
    finally:
        _umod.UringEngine.shutdown()
        cutthrough.ROUTE_IMPL = prev_impl
        (_umod._resolved, _umod._warned_demote,
         _pump_mod.PUMP_IMPL, _pump_mod._warned_demote) = saved
        if prev_retain is None:
            _os.environ.pop("PUSHCDN_RETAIN_TOPICS", None)
        else:
            _os.environ["PUSHCDN_RETAIN_TOPICS"] = prev_retain


@requires_pump
@pytest.mark.parametrize("popularity", ("uniform", "zipf"))
@pytest.mark.parametrize("seed", range(3))
async def test_pump_mix_equivalence(seed, popularity):
    rng = np.random.default_rng(17_000 + seed
                                + (500 if popularity == "zipf" else 0))
    frames = _gen_pump_frames(rng, 48, popularity)
    baseline = base_alive = None
    for io_impl, route_impl, pump in _PUMP_LEGS:
        d, alive, balanced, summary = await _run_mix_pump(
            io_impl, route_impl, pump, frames)
        assert balanced, (
            f"seed {seed}/{popularity}: permits leaked under "
            f"{io_impl}/{route_impl}/pump={pump}")
        if baseline is None:
            baseline, base_alive = d, alive
            assert any(len(v) > 0 for v in d.values()), d
        assert alive == base_alive, (
            f"seed {seed}/{popularity}: disconnect decisions differ "
            f"under {io_impl}/{route_impl}/pump={pump}")
        assert d == baseline, (
            f"seed {seed}/{popularity}: delivery diverged under "
            f"{io_impl}/{route_impl}/pump={pump}")
        if pump == "auto":
            assert summary is not None and summary["pump_frames"] > 0, (
                f"pump leg never pumped: {summary}")


@requires_pump
async def test_pump_mix_equivalence_durable():
    """The durable escalation class: with topic 0 retained, the pump
    must hand retained broadcasts to the retention ring exactly like the
    scalar path — identical live deliveries, identical retained rings,
    and the pump still engaged for the rest of the mix."""
    rng = np.random.default_rng(17_900)
    frames = _gen_pump_frames(rng, 48, "uniform")
    baseline = results = None
    retained = {}
    for io_impl, route_impl, pump in _PUMP_LEGS:
        d, alive, balanced, summary = await _run_mix_pump(
            io_impl, route_impl, pump, frames, retain="0")
        assert balanced, f"{io_impl}/{route_impl}/pump={pump}"
        if baseline is None:
            baseline = d
            assert any(len(v) > 0 for v in d.values()), d
        assert d == baseline, (
            f"durable delivery diverged under "
            f"{io_impl}/{route_impl}/pump={pump}")
        if pump == "auto":
            assert summary is not None and summary["pump_frames"] > 0, (
                f"durable pump leg never pumped: {summary}")


# ---------------------------------------------------------------------------
# ISSUE 19: class-accounting equivalence — the scalar (python) and
# native route planes must fold IDENTICAL per-class frame/byte deltas
# into cdn_class_frames / cdn_class_bytes for the same seeded mix.
# Topic names bind the taxonomy (consensus.* -> topic 0, bulk.* ->
# topic 1) through the same PUSHCDN_TOPIC_NAMES path production uses,
# so both the installed scalar table and the planner's mirror see it.
# ---------------------------------------------------------------------------

from pushcdn_tpu.proto import flowclass as _flowclass  # noqa: E402
from pushcdn_tpu.proto import metrics as _metrics_mod  # noqa: E402

# control excluded: protocol/gossip traffic is timer-driven, so its
# counts are not a deterministic function of the mix
_ACCOUNTED_CLASSES = (1, 2, 3)  # consensus, live, bulk


def _class_counter_snapshot():
    return [fam[i].value
            for fam in (_metrics_mod.CLASS_FRAMES_OUT,
                        _metrics_mod.CLASS_FRAMES_IN,
                        _metrics_mod.CLASS_BYTES_OUT,
                        _metrics_mod.CLASS_BYTES_IN)
            for i in _ACCOUNTED_CLASSES]


async def _run_mix_accounted(impl, frames, as_user, chunked):
    before = _class_counter_snapshot()
    d, alive, bal = await _run_mix(impl, frames, as_user=as_user,
                                   chunked=chunked)
    after = _class_counter_snapshot()
    return d, bal, [a - b for a, b in zip(after, before)]


@pytest.mark.parametrize("seed,chunked", [(0, True), (1, True), (2, False)])
async def test_class_accounting_equivalence(seed, chunked):
    import os as _os

    rng = np.random.default_rng(5000 + seed)
    frames = _gen_frames(rng, 60, as_user=True)
    saved_names = _os.environ.get("PUSHCDN_TOPIC_NAMES")
    _os.environ["PUSHCDN_TOPIC_NAMES"] = \
        "consensus.votes=0,bulk.replay=1"
    try:
        d_n, bal_n, acct_n = await _run_mix_accounted(
            "native", frames, as_user=True, chunked=chunked)
        d_p, bal_p, acct_p = await _run_mix_accounted(
            "python", frames, as_user=True, chunked=chunked)
        assert d_n == d_p, f"seed {seed}: delivery sets differ"
        assert bal_n and bal_p, f"seed {seed}: pool permits leaked"
        assert acct_n == acct_p, (
            f"seed {seed}: per-class accounting diverged\n"
            f"  native: {acct_n}\n  python: {acct_p}")
        # the mix must actually move the classed topics, or this test
        # proves nothing: topic 0 (consensus) and topic 1 (bulk) both
        # have subscribers in USER_TOPICS
        frames_out = dict(zip(_ACCOUNTED_CLASSES, acct_n[:3]))
        assert frames_out[1] > 0, "no consensus egress accounted"
        assert frames_out[3] > 0, "no bulk egress accounted"
    finally:
        if saved_names is None:
            _os.environ.pop("PUSHCDN_TOPIC_NAMES", None)
        else:
            _os.environ["PUSHCDN_TOPIC_NAMES"] = saved_names
        _flowclass.install_table(_flowclass.compile_table())
