"""Durable topics (ISSUE 14): retention rings, replay subscribe,
last-value cache, and wildcard interest.

Five tiers:

1. **Wire** — ``SubscribeFrom``/``Retained`` round-trips (serialize /
   deserialize / materialize) and the sequence sentinels.
2. **Namespace** — hierarchical name binding, wildcard compilation
   (``*`` = exactly one segment, final ``*`` = one-or-more), and live
   watches.
3. **Rings** — count/bytes/age eviction, LVC survival past eviction,
   snapshot addressing, and the pool-lease discipline: retained leases
   NEVER deadlock pool-permit reclamation (the reclaimer materializes
   oldest-first, synchronously), and the pooled clamp bounds idle leases
   to a quarter of the pool.
4. **Handover** — the acceptance property: a drop/rejoin via
   ``SubscribeFrom`` receives the retained prefix then the live tail
   with NO gap and NO duplicate, across both route impls and on a
   2-shard worker group (cross-shard replay handoff + owner-drainer
   ordering), with the byte pools balanced after shutdown.
5. **Wildcards** — a pattern subscription compiles onto the interest
   mask BIT-IDENTICALLY to the equivalent explicit topic set (native
   plan fan-out compared frame by frame), and stays identical as
   bindings come and go.
"""

import asyncio
import gc

import numpy as np
import pytest

from pushcdn_tpu.broker.tasks import cutthrough
from pushcdn_tpu.broker.test_harness import TestDefinition
from pushcdn_tpu.native import routeplan
from pushcdn_tpu.proto.error import Error
from pushcdn_tpu.proto.limiter import Bytes, Limiter
from pushcdn_tpu.proto.message import (
    SEQ_LAST,
    SEQ_LIVE,
    Broadcast,
    Retained,
    Subscribe,
    SubscribeFrom,
    deserialize,
    deserialize_owned,
    serialize,
)
from pushcdn_tpu.proto.topic import TopicNamespace, TopicSpace

ROUTE_IMPLS = [
    "python",
    pytest.param("native", marks=pytest.mark.skipif(
        not routeplan.available(),
        reason="native route-plan kernel unavailable")),
]


@pytest.fixture(autouse=True)
def _route_impl_state():
    saved = cutthrough.ROUTE_IMPL
    yield
    cutthrough.ROUTE_IMPL = saved


# ---------------------------------------------------------------------------
# tier 1: wire round-trips
# ---------------------------------------------------------------------------

def test_subscribe_from_round_trip():
    for msg in (SubscribeFrom(topic=7, seq=0),
                SubscribeFrom(topic=0, seq=SEQ_LAST),
                SubscribeFrom(topic=3, seq=SEQ_LIVE, pattern="a.b.*"),
                SubscribeFrom(topic=255, seq=2**63, pattern="x")):
        raw = serialize(msg)
        for decode in (deserialize, deserialize_owned):
            got = decode(raw)
            assert isinstance(got, SubscribeFrom)
            assert (got.topic, got.seq, got.pattern) == \
                (msg.topic, msg.seq, msg.pattern)


def test_retained_round_trip():
    for payload in (b"", b"x", b"y" * 70_000):
        raw = serialize(Retained(topic=9, seq=41, payload=payload))
        got = deserialize(raw)
        assert isinstance(got, Retained)
        assert (got.topic, got.seq, bytes(got.payload)) == (9, 41, payload)
        owned = deserialize_owned(raw)
        assert bytes(owned.payload) == payload
        assert not isinstance(owned.payload, memoryview)


def test_malformed_durable_frames_raise():
    for bad in (bytes([11]), bytes([11, 3, 0, 0]),     # truncated seq
                bytes([12, 3, 1, 2, 3])):              # truncated seq
        with pytest.raises(Error):
            deserialize(bad)


# ---------------------------------------------------------------------------
# tier 2: hierarchical namespace
# ---------------------------------------------------------------------------

def test_namespace_bind_and_conflicts():
    ns = TopicNamespace(TopicSpace.range(4))
    assert ns.bind("a.b", 2) == 2
    assert ns.bind("a.b") == 2          # idempotent re-bind
    assert ns.bind("a.c") == 0          # auto-alloc: smallest free valid
    assert ns.bind("a.d") == 1
    with pytest.raises(ValueError):
        ns.bind("a.b", 3)               # conflicting re-bind
    with pytest.raises(ValueError):
        ns.bind("other", 2)             # topic already bound
    with pytest.raises(ValueError):
        ns.bind("oob", 9)               # outside the space
    with pytest.raises(ValueError):
        ns.bind(".leading")
    assert ns.bind("last") == 3
    with pytest.raises(ValueError):
        ns.bind("overflow")             # space exhausted
    ns.unbind("a.b")
    assert ns.topic_of("a.b") is None
    assert ns.bind("fresh") == 2        # freed topic is reusable


def test_namespace_wildcard_match_semantics():
    ns = TopicNamespace(TopicSpace.range(16))
    t = {n: ns.bind(n) for n in (
        "c.view.1", "c.view.2", "c.view.2.retry", "c.vote.1", "c", "d.x")}
    # mid `*` matches exactly one segment
    assert ns.match("c.*.1") == tuple(sorted(
        (t["c.view.1"], t["c.vote.1"])))
    # final `*` matches one-or-more trailing segments
    assert ns.match("c.view.*") == tuple(sorted(
        (t["c.view.1"], t["c.view.2"], t["c.view.2.retry"])))
    assert ns.match("c.*") == tuple(sorted(
        (t["c.view.1"], t["c.view.2"], t["c.view.2.retry"],
         t["c.vote.1"])))               # NOT bare "c" (one-or-more)
    assert ns.match("c") == (t["c"],)   # plain name = its own pattern
    assert ns.match("*") == tuple(sorted(t.values()))
    assert ns.match("nope.*") == ()


def test_namespace_watch_lifecycle():
    ns = TopicNamespace(TopicSpace.range(8))
    added, removed = [], []
    h = ns.watch("a.*", on_add=lambda n, t: added.append((n, t)),
                 on_remove=lambda n, t: removed.append((n, t)))
    ta = ns.bind("a.one")
    ns.bind("b.one")                    # no match, no event
    assert added == [("a.one", ta)]
    ns.unbind("a.one")
    assert removed == [("a.one", ta)]
    ns.unwatch(h)
    ns.bind("a.two")
    assert added == [("a.one", ta)]     # no events after unwatch


# ---------------------------------------------------------------------------
# tier 3: rings, LVC, leases
# ---------------------------------------------------------------------------

class _FakeBroker:
    """Just enough broker surface for a standalone DurableTopics."""

    def __init__(self, pool_bytes=None, topics=TopicSpace.range(8)):
        from pushcdn_tpu.broker.connections import Connections
        from pushcdn_tpu.proto.def_ import testing_run_def
        self.connections = Connections("pub:me/priv:me")
        self.run_def = testing_run_def(topics=topics)
        self.limiter = Limiter(global_pool_bytes=pool_bytes)
        self.shard_runtime = None
        self.durable = None


def _durable(**kw):
    from pushcdn_tpu.broker.retention import DurableTopics
    broker = _FakeBroker(pool_bytes=kw.pop("pool_bytes", None))
    d = DurableTopics(broker, **kw)
    broker.durable = d
    return d


def test_ring_count_eviction_and_snapshot():
    d = _durable(topics=[0], max_count=4)
    for i in range(10):
        d._retain([0], b"p%d" % i, None)
    assert [e.seq for e in d.snapshot(0, 0)] == [7, 8, 9, 10]
    assert [bytes(e.payload) for e in d.snapshot(0, 9)] == [b"p8", b"p9"]
    assert d.snapshot(0, SEQ_LIVE) == []
    assert d.evicted_entries == 6
    assert d.stats()["next_seq"][0] == 11


def test_ring_bytes_eviction():
    d = _durable(topics=[0], max_count=1000, max_bytes=100)
    for i in range(10):
        d._retain([0], bytes(40), None)
    # at most 100 bytes retained => 2 entries of 40
    assert len(d.snapshot(0, 0)) == 2
    assert d._rings[0].nbytes <= 100


def test_ring_age_eviction():
    d = _durable(topics=[0], max_age_s=0.03)
    d._retain([0], b"old", None)
    import time
    time.sleep(0.05)
    d._retain([0], b"new", None)
    assert [bytes(e.payload) for e in d.snapshot(0, 0)] == [b"new"]
    # the LVC entry survives aging out of the ring
    d._rings[0].entries.clear  # (no-op sanity: snapshot already evicted)
    time.sleep(0.05)
    assert d.snapshot(0, 0) == []
    assert bytes(d.snapshot(0, SEQ_LAST)[0].payload) == b"new"


def test_lvc_survives_eviction_and_tracks_latest():
    d = _durable(topics=[0, 1], max_count=2)
    for i in range(6):
        d._retain([0], b"v%d" % i, None)
    last = d.snapshot(0, SEQ_LAST)
    assert len(last) == 1 and bytes(last[0].payload) == b"v5"
    assert last[0].seq == 6
    assert d.snapshot(1, SEQ_LAST) == []   # untouched topic: no LVC


async def test_retained_leases_never_deadlock_pool_reclaim():
    """The acceptance property for the lease discipline: retention holds
    pooled leases, the pool is then exhausted by a new allocation, and
    the allocation MUST complete (reclaimer materializes retention's
    leases synchronously) instead of deadlocking."""
    d = _durable(topics=[0], pool_bytes=1024, max_count=1000,
                 max_bytes=1 << 20)
    pool = d.broker.limiter.pool
    # seed the ring with leased entries: ~200 pooled bytes held by
    # retention (under the 256-byte pooled clamp)
    for i in range(4):
        b = Bytes(bytes([i]) * 50, await pool.allocate(50))
        d._retain([0], b.data, b)
        b.release()                     # retention's clone keeps the lease
    held = d.stats()["pooled_bytes"]
    assert held == 200, held
    assert pool.available == 1024 - 200
    # exhaust: this allocation needs more than is free -> without the
    # reclaimer it would block forever on retention's idle leases
    permit = await asyncio.wait_for(pool.allocate(1000), timeout=2)
    permit.release()
    assert d.pool_reclaims >= 1
    assert d.materialized_entries >= 1
    assert d.stats()["pooled_bytes"] < held
    # materialization preserved every payload
    assert [bytes(e.payload) for e in d.snapshot(0, 0)] == \
        [bytes([i]) * 50 for i in range(4)]


async def test_pooled_clamp_bounds_idle_leases():
    """Retention may pin at most a quarter of the pool: pushing more
    leased bytes than the budget materializes oldest-first inline."""
    d = _durable(topics=[0], pool_bytes=400, max_count=1000,
                 max_bytes=1 << 20)
    pool = d.broker.limiter.pool
    for i in range(8):                  # 8 x 50 = 400 leased bytes offered
        b = Bytes(bytes([i]) * 50, await pool.allocate(50))
        d._retain([0], b.data, b)
        b.release()
    assert d.stats()["pooled_bytes"] <= 100   # capacity // 4
    assert d.materialized_entries >= 6
    assert pool.available >= 300
    assert [bytes(e.payload) for e in d.snapshot(0, 0)] == \
        [bytes([i]) * 50 for i in range(8)]   # nothing lost, only copied


def test_close_releases_everything():
    d = _durable(topics=[0, 1], max_count=100)
    for i in range(5):
        d._retain([0, 1], b"x%d" % i, None)
    d.close()
    assert d.stats()["pooled_bytes"] == 0
    assert all(n == 0 for n in d.stats()["ring_entries"].values())


# ---------------------------------------------------------------------------
# tier 4: replay -> live handover (the acceptance property)
# ---------------------------------------------------------------------------

def _pool_balanced(broker, what):
    gc.collect()
    pool = broker.limiter.pool
    if pool is not None:
        assert pool.available == pool.capacity, (
            f"{what}: {pool.capacity - pool.available} pooled bytes leaked")


async def _drain_stream(entity, quiet=0.4):
    """Everything the entity receives, in order, as typed messages."""
    out = []
    while True:
        try:
            raw = await asyncio.wait_for(entity.remote.recv_raw(), quiet)
        except (asyncio.TimeoutError, Exception):
            return out
        msg = deserialize(raw.data)
        if isinstance(msg, Retained):
            out.append(("retained", msg.seq, bytes(msg.payload)))
        elif isinstance(msg, Broadcast):
            out.append(("live", None, bytes(msg.message)))
        else:
            out.append((type(msg).__name__, None, None))
        if hasattr(raw, "release"):
            raw.release()


def _assert_handover(stream, published, what):
    """The gap/dup-free contract: the receiver's stream is a run of
    Retained frames followed by a run of live Broadcasts, and the
    concatenated payloads equal the FULL publish history exactly once,
    in order. (Where the split lands depends on scheduling; that it is a
    clean, complete, duplicate-free splice does not.)"""
    kinds = [k for k, _, _ in stream]
    split = kinds.index("live") if "live" in kinds else len(stream)
    assert all(k == "retained" for k in kinds[:split]), (what, kinds)
    assert all(k == "live" for k in kinds[split:]), (what, kinds)
    replay_seqs = [s for _, s, _ in stream[:split]]
    assert replay_seqs == list(range(1, split + 1)), (what, replay_seqs)
    payloads = [p for _, _, p in stream]
    assert payloads == published, (
        f"{what}: handover gap/dup — got {payloads}, want {published}")


@pytest.mark.parametrize("impl", ROUTE_IMPLS)
@pytest.mark.parametrize("seed", [0, 1])
async def test_replay_live_handover_one_shard(impl, seed, monkeypatch):
    monkeypatch.setenv("PUSHCDN_RETAIN_TOPICS", "0")
    cutthrough.ROUTE_IMPL = impl
    rng = np.random.default_rng(1400 + seed)
    k1, k2 = int(rng.integers(3, 9)), int(rng.integers(3, 9))
    published = [b"m%03d" % i for i in range(k1 + k2)]
    run = await TestDefinition(connected_users=((1,), ())).run()
    try:
        assert run.broker.durable.enabled
        sender, rx = run.user(0), run.user(1)
        for p in published[:k1]:
            await run.send_message_as(sender, Broadcast([0], p))
        await asyncio.sleep(0.1)        # phase 1 fully retained
        # phase 2: rejoin AND keep publishing, interleaved
        await rx.remote.send_message(SubscribeFrom(topic=0, seq=1),
                                     flush=True)
        for p in published[k1:]:
            await run.send_message_as(sender, Broadcast([0], p))
        stream = await _drain_stream(rx)
        _assert_handover(stream, published, f"1-shard/{impl}/s{seed}")
        assert run.broker.durable.replayed_frames >= k1
    finally:
        await run.shutdown()
    _pool_balanced(run.broker, f"1-shard/{impl}/s{seed}")


@pytest.mark.parametrize("impl", ROUTE_IMPLS)
@pytest.mark.parametrize("topic", [0, 1])
async def test_replay_live_handover_two_shards(impl, topic, monkeypatch):
    """2-shard flavor. ``topic`` selects the owner shard (topic % 2):
    topic 0 is owned by the receiver's shard, topic 1 by the sender's —
    the latter exercises the cross-shard replay handoff ring AND the
    owner-drainer live path in one run."""
    from pushcdn_tpu.testing.shardharness import run_sharded
    monkeypatch.setenv("PUSHCDN_RETAIN_TOPICS", "0,1")
    cutthrough.ROUTE_IMPL = impl
    published = [b"s%03d" % i for i in range(10)]
    run = await run_sharded([(0, ()), (1, (topic,))], num_shards=2)
    try:
        assert all(b.durable.enabled for b in run.brokers)
        rx, sender = run.user(0), run.user(1)
        for p in published[:5]:
            await sender.remote.send_message(Broadcast([topic], p),
                                             flush=True)
        await run.settle()
        await rx.remote.send_message(SubscribeFrom(topic=topic, seq=1),
                                     flush=True)
        for p in published[5:]:
            await sender.remote.send_message(Broadcast([topic], p),
                                             flush=True)
        await run.settle()
        stream = await _drain_stream(rx)
        _assert_handover(stream, published, f"2-shard/{impl}/t{topic}")
        owner = run.brokers[topic % 2]
        assert owner.durable.replayed_frames >= 5
        assert owner.durable.stats()["ring_entries"][topic] == 10
    finally:
        await run.shutdown()
    for i, b in enumerate(run.brokers):
        _pool_balanced(b, f"2-shard/{impl}/t{topic} shard{i}")


@pytest.mark.parametrize("impl", ROUTE_IMPLS)
async def test_seq_last_and_live_sentinels_through_broker(impl,
                                                          monkeypatch):
    monkeypatch.setenv("PUSHCDN_RETAIN_TOPICS", "0")
    cutthrough.ROUTE_IMPL = impl
    run = await TestDefinition(connected_users=((1,), (), ())).run()
    try:
        sender, lvc_rx, live_rx = run.user(0), run.user(1), run.user(2)
        for i in range(4):
            await run.send_message_as(sender, Broadcast([0], b"b%d" % i))
        await asyncio.sleep(0.1)
        await lvc_rx.remote.send_message(
            SubscribeFrom(topic=0, seq=SEQ_LAST), flush=True)
        await live_rx.remote.send_message(
            SubscribeFrom(topic=0, seq=SEQ_LIVE), flush=True)
        await asyncio.sleep(0.1)
        await run.send_message_as(sender, Broadcast([0], b"tail"))
        lvc = await _drain_stream(lvc_rx)
        live = await _drain_stream(live_rx)
        # LVC: exactly the newest retained entry, then the live tail
        assert lvc == [("retained", 4, b"b3"), ("live", None, b"tail")]
        # SEQ_LIVE: no replay at all
        assert live == [("live", None, b"tail")]
    finally:
        await run.shutdown()


async def test_subscribe_from_unknown_topic_disconnects(monkeypatch):
    monkeypatch.setenv("PUSHCDN_RETAIN_TOPICS", "0")
    run = await TestDefinition(connected_users=((0,),)).run()
    try:
        u = run.user(0)
        await u.remote.send_message(SubscribeFrom(topic=77, seq=0),
                                    flush=True)
        await asyncio.sleep(0.1)
        assert not run.broker.connections.has_user(u.public_key)
    finally:
        await run.shutdown()


async def test_pool_pressure_through_broker(monkeypatch):
    """Integration twin of the lease test: a SMALL pool, retention on,
    and a publish volume well past pool capacity — every frame must
    still deliver (no allocate ever deadlocks on retention's leases)
    and the pool must balance after shutdown."""
    monkeypatch.setenv("PUSHCDN_RETAIN_TOPICS", "0")
    monkeypatch.setenv("PUSHCDN_RETAIN_BYTES", str(1 << 20))
    for impl in ("python", "native") if routeplan.available() \
            else ("python",):
        cutthrough.ROUTE_IMPL = impl
        run = await TestDefinition(connected_users=((1,), (0,)),
                                   pool_bytes=64 * 1024).run()
        try:
            sender, rx = run.user(0), run.user(1)
            payload = bytes(2048)
            # drain concurrently: the pool pressure must come from
            # retention's idle leases plus transient in-flight frames,
            # not from an intentionally wedged receiver queue
            drain = asyncio.create_task(_drain_stream(rx, quiet=1.0))
            for _ in range(64):         # 128 KiB through a 64 KiB pool
                await run.send_message_as(sender,
                                          Broadcast([0], payload))
            got = await asyncio.wait_for(drain, timeout=30)
            assert len([g for g in got if g[0] == "live"]) == 64
            assert run.broker.durable.stats()["ring_entries"][0] == 64
        finally:
            await run.shutdown()
        _pool_balanced(run.broker, f"pool-pressure/{impl}")


# ---------------------------------------------------------------------------
# tier 5: wildcard interest == explicit interest, bit-identically
# ---------------------------------------------------------------------------

def _plan_fanout(broker, frames):
    """{identity: (frame indices...)} for one native plan over
    ``frames`` (mirrors test_route_state's contract comparison)."""
    state = cutthrough.RouteState(broker, routeplan.RoutePlanner.create())
    assert state._refresh()
    buf = bytearray()
    offs, lens = [], []
    for f in frames:
        offs.append(len(buf) + 4)
        lens.append(len(f))
        buf += len(f).to_bytes(4, "big") + f
    offs = np.asarray(offs, np.int64)
    lens = np.asarray(lens, np.int64)
    out: dict = {}
    pos, n = 0, len(lens)
    while pos < n:
        consumed, stop, peers, fidx = state.planner.plan(
            bytes(buf), offs, lens, pos, 0)
        for p, f in zip(peers.tolist(), fidx.tolist()):
            key = (state.slot_user[p] if p < state.user_cap
                   else state.slot_broker[p - state.user_cap])
            out.setdefault(key, []).append(f)
        pos += consumed
        if stop == routeplan.STOP_RESIDUAL:
            pos += 1
    return {k: tuple(v) for k, v in out.items()}


@pytest.mark.skipif(not routeplan.available(),
                    reason="native route-plan kernel unavailable")
async def test_wildcard_plan_bit_identical_to_explicit(monkeypatch):
    """A wildcard subscriber and an explicit-set subscriber must be
    indistinguishable to the route plane: the native plan's fan-out for
    every probe topic is identical for both users, before AND after
    incremental bind/unbind churn."""
    monkeypatch.setenv("PUSHCDN_RETAIN_TOPICS", "1,2")
    run = await TestDefinition(connected_users=((), ()),
                               topics=TopicSpace.range(8)).run()
    try:
        broker = run.broker
        ns = broker.durable.namespace
        for name, t in (("c.view.1", 1), ("c.view.2", 2), ("other.x", 3)):
            ns.bind(name, t)
        wild, expl = run.user(0), run.user(1)
        await wild.remote.send_message(
            SubscribeFrom(topic=0, seq=SEQ_LIVE, pattern="c.view.*"),
            flush=True)
        await expl.remote.send_message(Subscribe([1, 2]), flush=True)
        await asyncio.sleep(0.1)
        conns = broker.connections
        assert (set(conns.user_topics.get_values_of_key(wild.public_key)) ==
                set(conns.user_topics.get_values_of_key(expl.public_key)) ==
                {1, 2})
        probe = [serialize(Broadcast([t], b"probe%d" % t))
                 for t in range(8)]

        def check(what):
            fan = _plan_fanout(broker, probe)
            assert fan.get(wild.public_key) == fan.get(expl.public_key), (
                what, fan)
        check("initial")
        # incremental: a NEW binding covered by the pattern must reach the
        # wildcard user through the watch; mirror it explicitly on the twin
        ns.bind("c.view.9", 4)
        conns.subscribe_user_to(expl.public_key, [4])
        await asyncio.sleep(0.05)
        assert 4 in set(conns.user_topics.get_values_of_key(wild.public_key))
        check("after bind")
        ns.unbind("c.view.1")
        conns.unsubscribe_user_from(expl.public_key, [1])
        check("after unbind")
        # and the delivered traffic agrees with the plan
        await run.send_message_as(run.user(1), Broadcast([4], b"hit"))
        got = await _drain_stream(wild)
        assert (("live", None, b"hit") in got), got
    finally:
        await run.shutdown()


async def test_wildcard_pattern_with_replay(monkeypatch):
    """A pattern + a real from-seq: every durable topic the pattern
    covers replays its ring, then live frames follow."""
    monkeypatch.setenv("PUSHCDN_RETAIN_TOPICS", "1,2")
    run = await TestDefinition(connected_users=((1, 2), ()),
                               topics=TopicSpace.range(8)).run()
    try:
        ns = run.broker.durable.namespace
        ns.bind("v.1", 1)
        ns.bind("v.2", 2)
        sender, rx = run.user(0), run.user(1)
        await run.send_message_as(sender, Broadcast([1], b"one"))
        await run.send_message_as(sender, Broadcast([2], b"two"))
        await asyncio.sleep(0.1)
        await rx.remote.send_message(
            SubscribeFrom(topic=0, seq=1, pattern="v.*"), flush=True)
        got = await _drain_stream(rx)
        replayed = {(s, p) for k, s, p in got if k == "retained"}
        assert replayed == {(1, b"one"), (1, b"two")}, got
    finally:
        await run.shutdown()
