"""Connections state-plane tests: interest queries, sync generation and
application, cross-broker double-connect eviction, topic-sync convergence
(parity cdn-broker/src/connections/mod.rs:390-527)."""

import asyncio

from pushcdn_tpu.broker.connections import Connections, SubscriptionStatus
from pushcdn_tpu.proto.transport.memory import gen_testing_connection_pair

B1 = "pub1:1/priv1:1"
B2 = "pub2:1/priv2:1"


async def _user(conns: Connections, key: bytes, topics):
    local, remote = await gen_testing_connection_pair()
    conns.add_user(key, local, list(topics))
    return remote


async def _broker(conns: Connections, ident: str):
    local, remote = await gen_testing_connection_pair()
    conns.add_broker(ident, local)
    return remote


async def test_interest_queries_and_loop_prevention():
    c = Connections(B1)
    await _user(c, b"u1", [0])
    await _user(c, b"u2", [0, 1])
    await _broker(c, B2)
    c.subscribe_broker_to(B2, [1])

    users, brokers = c.get_interested_by_topic([0], to_users_only=False)
    assert sorted(users) == [b"u1", b"u2"] and brokers == []
    users, brokers = c.get_interested_by_topic([1], to_users_only=False)
    assert users == [b"u2"] and brokers == [B2]
    # to_users_only=True: the broker-originated loop-prevention rule
    users, brokers = c.get_interested_by_topic([1], to_users_only=True)
    assert users == [b"u2"] and brokers == []


async def test_direct_map_claims_and_release():
    c = Connections(B1)
    await _user(c, b"alice", [])
    assert c.get_broker_identifier_of_user(b"alice") == B1
    c.remove_user(b"alice")
    assert c.get_broker_identifier_of_user(b"alice") is None


async def test_user_sync_round_trip_and_eviction():
    """B1's claim propagates to B2; B2 taking the user over evicts it from
    B1 on the next sync — the cross-broker double-connect kick."""
    c1, c2 = Connections(B1), Connections(B2)
    await _broker(c1, B2)
    await _broker(c2, B1)

    await _user(c1, b"alice", [0])
    payload = c1.get_partial_user_sync()
    assert payload is not None
    c2.apply_user_sync(payload)
    assert c2.get_broker_identifier_of_user(b"alice") == B1

    # alice reconnects at B2: claim bumps version
    await _user(c2, b"alice", [0])
    payload2 = c2.get_partial_user_sync()
    evicted = c1.apply_user_sync(payload2)
    assert evicted == [b"alice"]
    assert not c1.has_user(b"alice")
    assert c1.get_broker_identifier_of_user(b"alice") == B2


async def test_full_user_sync_on_join():
    c1 = Connections(B1)
    for i in range(5):
        await _user(c1, f"user{i}".encode(), [])
    c2 = Connections(B2)
    c2.apply_user_sync(c1.get_full_user_sync())
    for i in range(5):
        assert c2.get_broker_identifier_of_user(f"user{i}".encode()) == B1


async def test_topic_sync_updates_broker_interest():
    c1, c2 = Connections(B1), Connections(B2)
    await _broker(c2, B1)

    await _user(c1, b"u", [0, 1])
    payload = c1.get_partial_topic_sync()
    assert payload is not None
    c2.apply_topic_sync(B1, payload)
    _users, brokers = c2.get_interested_by_topic([0], to_users_only=False)
    assert brokers == [B1]

    # unsubscribe: u drops topic 0 -> next delta flips it off
    c1.unsubscribe_user_from(b"u", [0])
    payload2 = c1.get_partial_topic_sync()
    assert payload2 is not None
    c2.apply_topic_sync(B1, payload2)
    _users, brokers = c2.get_interested_by_topic([0], to_users_only=False)
    assert brokers == []
    _users, brokers = c2.get_interested_by_topic([1], to_users_only=False)
    assert brokers == [B1]


async def test_topic_sync_out_of_order_convergence():
    """Deltas applied out of order still converge (parity
    connections/mod.rs:473-526)."""
    c1 = Connections(B1)
    await _user(c1, b"u", [0])
    d1 = c1.get_partial_topic_sync()
    c1.unsubscribe_user_from(b"u", [0])
    d2 = c1.get_partial_topic_sync()
    c1.subscribe_user_to(b"u", [0])
    d3 = c1.get_partial_topic_sync()

    for order in ([d1, d2, d3], [d3, d1, d2], [d2, d3, d1]):
        c2 = Connections(B2)
        await _broker(c2, B1)
        for d in order:
            if d:
                c2.apply_topic_sync(B1, d)
        _u, brokers = c2.get_interested_by_topic([0], to_users_only=False)
        assert brokers == [B1], order


async def test_remove_broker_forgets_routed_users():
    c1 = Connections(B1)
    await _broker(c1, B2)
    c1.apply_user_sync(
        _seed_user_sync(B2, [b"remote-user-1", b"remote-user-2"]))
    assert c1.get_broker_identifier_of_user(b"remote-user-1") == B2
    c1.remove_broker(B2)
    assert c1.get_broker_identifier_of_user(b"remote-user-1") is None
    # forgetting is local-only: nothing queued for the next partial sync
    assert c1.get_partial_user_sync() is None


async def test_same_broker_double_connect_evicts_old():
    c = Connections(B1)
    r1 = await _user(c, b"alice", [0])
    r2 = await _user(c, b"alice", [1])  # reconnect, same broker
    assert c.num_users == 1
    assert c.user_topics.get_values_of_key(b"alice") == {1}
    del r1, r2


def _seed_user_sync(owner: str, users):
    """Hand-build a user-sync payload as if from a peer broker (the trick
    the reference harness uses, cdn-broker/src/tests/mod.rs:356-382)."""
    from pushcdn_tpu.broker.versioned_map import VersionedMap
    m = VersionedMap(local_identity=owner)
    for u in users:
        m.insert(u, owner)
    return VersionedMap.serialize_entries(m.full())
