"""ISSUE 5 observability plane: /healthz + /readyz semantics (state
transitions, drain-before-listener-close, ready-flip flight-recorder
events), the routed metrics HTTP server (405/404/400, no substring
misrouting), /debug/topology schema over an in-process mesh, and the
per-task sampling profiler's attribution."""

import asyncio
import json

import pytest

from pushcdn_tpu.proto import flightrec, health
from pushcdn_tpu.proto import metrics as metrics_mod


async def _get(port: int, path: str, method: str = "GET",
               accept: str = "") -> tuple:
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    req = f"{method} {path} HTTP/1.1\r\nHost: x\r\n"
    if accept:
        req += f"Accept: {accept}\r\n"
    writer.write((req + "\r\n").encode())
    await writer.drain()
    data = await reader.read(-1)
    writer.close()
    head, _, body = data.partition(b"\r\n\r\n")
    return int(head.split(b" ")[1]), body.decode()


async def _serve():
    server = await metrics_mod.serve_metrics("127.0.0.1:0")
    return server, server.sockets[0].getsockname()[1]


# ---------------------------------------------------------------------------
# routed HTTP server (the satellite bugfix the tentpole builds on)
# ---------------------------------------------------------------------------

async def test_non_get_rejected_405():
    server, port = await _serve()
    try:
        status, _ = await _get(port, "/metrics", method="POST")
        assert status == 405
        status, _ = await _get(port, "/healthz", method="DELETE")
        assert status == 405
    finally:
        server.close()
        await server.wait_closed()


async def test_query_string_cannot_misroute():
    """The latent bug: a request merely CONTAINING /debug/flightrec used
    to be served the flightrec body. The parsed route table dispatches on
    the actual path."""
    server, port = await _serve()
    try:
        status, body = await _get(port, "/metrics?q=/debug/flightrec")
        assert status == 200
        assert "# TYPE cdn_bytes_sent counter" in body
        assert "flight recorder" not in body
        # and an unknown path that merely mentions a route is 404
        status, _ = await _get(port, "/nope/metrics")
        assert status == 404
    finally:
        server.close()
        await server.wait_closed()


async def test_flightrec_limit_query_caps_body():
    rec = flightrec.FlightRecorder("limit-test-rec")
    for i in range(20):
        rec.record("evt", f"n{i}")
    server, port = await _serve()
    try:
        status, body = await _get(port, "/debug/flightrec?limit=3")
        assert status == 200
        # only the most recent events of this recorder survive the cap
        assert "n19" in body
        assert "n0" not in body
        status, full = await _get(port, "/debug/flightrec")
        assert "n0" in full  # default limit is generous
    finally:
        server.close()
        await server.wait_closed()


async def test_openmetrics_negotiation_carries_exemplars():
    metrics_mod.E2E_LATENCY.observe(0.002,
                                    exemplar={"trace_id": "feedface01"})
    server, port = await _serve()
    try:
        status, body = await _get(port, "/metrics",
                                  accept="application/openmetrics-text")
        assert status == 200
        assert body.rstrip().endswith("# EOF")
        assert '# {trace_id="feedface01"}' in body
        # OM mandates the _total suffix on counter SAMPLES (family name
        # in TYPE stays bare) — a strict parser rejects bare counters
        assert "# TYPE cdn_bytes_sent counter" in body
        assert "\ncdn_bytes_sent_total " in body
        # plain scrapes stay strict prometheus 0.0.4: no exemplars, no
        # suffix migration
        _, plain = await _get(port, "/metrics")
        assert "trace_id=" not in plain
        assert "# EOF" not in plain
        assert "cdn_bytes_sent_total" not in plain
    finally:
        server.close()
        await server.wait_closed()


# ---------------------------------------------------------------------------
# /healthz + /readyz
# ---------------------------------------------------------------------------

async def test_healthz_reports_builtin_checks():
    server, port = await _serve()
    try:
        status, body = await _get(port, "/healthz")
        assert status == 200
        doc = json.loads(body)
        assert doc["status"] == "ok"
        assert doc["checks"]["loop-lag"]["ok"] is True
        assert doc["checks"]["samplers"]["ok"] is True
    finally:
        server.close()
        await server.wait_closed()


async def test_readyz_drain_latch_and_ready_flip_event():
    server, port = await _serve()
    try:
        status, _ = await _get(port, "/readyz")
        assert status == 200
        before = len(flightrec.task_recorder())
        health.set_draining("unit-test drain")
        status, body = await _get(port, "/readyz")
        assert status == 503
        doc = json.loads(body)
        assert doc["draining"] is True
        assert doc["checks"]["draining"]["ok"] is False
        # the flip was recorded the moment set_draining ran
        assert len(flightrec.task_recorder()) > before
        trail = flightrec.task_recorder().trail()
        assert "ready-flip" in trail and "draining: unit-test drain" in trail
    finally:
        health.clear_draining()
        server.close()
        await server.wait_closed()


async def test_failing_check_name_lands_in_ready_flip():
    health.register_readiness("unit-fails", lambda: (False, "on purpose"))
    server, port = await _serve()
    try:
        status, body = await _get(port, "/readyz")
        assert status == 503
        assert json.loads(body)["checks"]["unit-fails"]["ok"] is False
        trail = flightrec.task_recorder().trail()
        assert "unit-fails" in trail
        # recovery transitions back to ready
        health.register_readiness("unit-fails", lambda: (True, "fixed"))
        status, _ = await _get(port, "/readyz")
        assert status == 200
    finally:
        health.unregister("unit-fails")
        server.close()
        await server.wait_closed()


async def test_raising_check_reports_unhealthy_not_500():
    def boom():
        raise RuntimeError("check exploded")
    health.register_readiness("unit-boom", boom)
    server, port = await _serve()
    try:
        status, body = await _get(port, "/readyz")
        assert status == 503
        assert "check exploded" in body
    finally:
        health.unregister("unit-boom")
        server.close()
        await server.wait_closed()


# ---------------------------------------------------------------------------
# broker readiness lifecycle (discovery down -> not ready -> recovers;
# drain flips readiness BEFORE the listeners close)
# ---------------------------------------------------------------------------

async def test_broker_readiness_transitions():
    from pushcdn_tpu.broker.test_harness import TestDefinition
    run = await TestDefinition(
        connected_users=[[0]],
        connected_brokers=[([0], [b"remote-user"])],
        metrics_bind_endpoint="127.0.0.1:0").run()
    broker = run.broker
    port = broker._metrics_server.sockets[0].getsockname()[1]
    try:
        status, body = await _get(port, "/readyz")
        assert status == 200, body
        doc = json.loads(body)
        assert set(doc["checks"]) >= {"listeners", "discovery", "mesh"}

        # discovery down: expire the cached probe, make the active one fail
        real = broker.discovery.get_other_brokers

        async def dead():
            raise OSError("discovery store unreachable")

        broker.discovery.get_other_brokers = dead
        broker._discovery_probe_at = None
        status, body = await _get(port, "/readyz")
        assert status == 503
        assert json.loads(body)["checks"]["discovery"]["ok"] is False

        # recovers once the store answers again (cache expired manually —
        # production pays at most one probe per TTL)
        broker.discovery.get_other_brokers = real
        broker._discovery_probe_at = None
        status, _ = await _get(port, "/readyz")
        assert status == 200

        # drain: readiness flips false while the listeners are STILL up
        broker.begin_drain("test drain")
        status, body = await _get(port, "/readyz")
        assert status == 503
        assert json.loads(body)["draining"] is True
        assert broker.listeners_bound  # nothing closed yet
    finally:
        await run.shutdown()
    assert health.draining() is None  # stop() cleans the global latch


async def test_broker_mesh_check_solo_vs_partitioned():
    from pushcdn_tpu.broker.test_harness import TestDefinition
    run = await TestDefinition(metrics_bind_endpoint="127.0.0.1:0").run()
    broker = run.broker
    try:
        # no peers connected, discovery says nobody else exists: solo is
        # intentional => ready
        broker.last_peer_count = 0
        ok, detail = broker._check_mesh()
        assert ok and "solo" in detail
        # discovery reports peers we can't reach: NOT ready
        broker.last_peer_count = 3
        ok, detail = broker._check_mesh()
        assert not ok and "3" in detail
    finally:
        await run.shutdown()


# ---------------------------------------------------------------------------
# /debug/topology
# ---------------------------------------------------------------------------

async def test_topology_dump_schema_over_mesh():
    from pushcdn_tpu.broker.test_harness import TestDefinition
    run = await TestDefinition(
        connected_users=[[0], [1]],
        connected_brokers=[([0], [b"remote-user"])],
        metrics_bind_endpoint="127.0.0.1:0").run()
    broker = run.broker
    port = broker._metrics_server.sockets[0].getsockname()[1]
    try:
        status, body = await _get(port, "/debug/topology")
        assert status == 200
        topo = json.loads(body)
        for key in ("identity", "draining", "interest_version", "num_users",
                    "num_brokers", "peers", "users", "users_truncated",
                    "interest", "cutthrough"):
            assert key in topo, f"topology schema drift: missing {key}"
        assert topo["num_users"] == 2
        assert topo["num_brokers"] == 1
        [peer] = topo["peers"]
        assert peer["id"] == run.peer(0).identifier
        assert peer["topics"] == 1
        assert {"writer_queue_depth", "bytes_in_flight"} <= set(peer)
        assert {u["topics"] for u in topo["users"]} == {1}
        card = topo["interest"]["topic_cardinality"]
        assert card == {"0": 1, "1": 1}
        # 2 local users + 1 remote user owned by the peer
        assert topo["interest"]["direct_map_size"] == 3
    finally:
        await run.shutdown()
    # unregistered on stop: the route 404s for the next owner
    server, port = await _serve()
    try:
        status, _ = await _get(port, "/debug/topology")
        assert status == 404
    finally:
        server.close()
        await server.wait_closed()


# ---------------------------------------------------------------------------
# per-task sampling profiler
# ---------------------------------------------------------------------------

async def test_profiler_attributes_hot_task_family():
    async def hot():
        while True:
            await asyncio.sleep(0.001)

    # two instances of one family (trailing ids strip to one label)
    tasks = [asyncio.create_task(hot(), name=f"deliberately-hot-task-{i:04x}")
             for i in range(2)]
    profiler = asyncio.create_task(metrics_mod._task_profiler(0.02))
    try:
        await asyncio.sleep(0.25)
    finally:
        profiler.cancel()
        for t in tasks:
            t.cancel()
    child = metrics_mod.TASK_SAMPLES.labels(task="deliberately-hot-task")
    # ~12 ticks x 2 tasks; generous floor for slow CI
    assert child.value >= 6
    rendered = metrics_mod.TASK_SAMPLES.render()
    assert 'cdn_task_samples{task="deliberately-hot-task"}' in rendered


def test_task_family_normalization():
    f = metrics_mod._task_family
    assert f("Task-123") == "Task"
    assert f("user-receive-7f3a2b") == "user-receive"
    assert f("heartbeat") == "heartbeat"
    assert f("dial-0xdeadbeef") == "dial"
    assert f("42") == "anonymous"


def test_native_seconds_children_render():
    body = metrics_mod.NATIVE_SECONDS.render()
    for kernel in ("route_plan", "egress_encode", "bls_verify"):
        assert f'cdn_native_seconds{{kernel="{kernel}"}}' in body


async def test_profiler_cardinality_cap_folds_to_other():
    saved = dict(metrics_mod._family_children)
    try:
        metrics_mod._family_children.clear()
        for i in range(metrics_mod._MAX_TASK_FAMILIES):
            metrics_mod._family_child(f"fam{i}x")  # 'x' so digits survive
        over = metrics_mod._family_child("one-family-too-many")
        assert over is metrics_mod._family_children["other"]
    finally:
        metrics_mod._family_children.clear()
        metrics_mod._family_children.update(saved)


@pytest.mark.parametrize("path", ["/healthz", "/readyz"])
async def test_health_endpoints_never_import_jax(path):
    """Same rule as cdn_build_info: probing health must not initialize
    (or newly import) jax — the render path is pure stdlib."""
    import sys
    had_jax = "jax" in sys.modules
    server, port = await _serve()
    try:
        await _get(port, path)
    finally:
        server.close()
        await server.wait_closed()
    assert ("jax" in sys.modules) == had_jax


async def test_topology_reports_pump_state():
    """ISSUE 17 observability: with the fused pump engaged, the
    ``/debug/topology`` cut-through block carries the pump summary —
    engaged peers, natively pumped frames, and the escalation
    taxonomy — so an operator can see WHY frames left the native path."""
    from pushcdn_tpu.broker.tasks import cutthrough
    from pushcdn_tpu.broker.test_harness import TestDefinition
    from pushcdn_tpu.native import pump as npump
    from pushcdn_tpu.native import uring as nuring
    from pushcdn_tpu.proto.message import Broadcast, serialize
    from pushcdn_tpu.proto.transport import pump as pump_mod
    from pushcdn_tpu.proto.transport import uring as umod

    if not (nuring.available() and npump.available()
            and cutthrough.routeplan.available()):
        pytest.skip("fused pump unavailable on this host")

    saved = (umod._resolved, umod._warned_demote, cutthrough.ROUTE_IMPL,
             pump_mod.PUMP_IMPL, pump_mod._warned_demote)
    umod.set_io_impl("uring")
    cutthrough.ROUTE_IMPL = "native"
    pump_mod.set_pump_impl("auto")
    try:
        run = await TestDefinition(
            connected_users=[[], [0], [0]], tcp_users=True,
            metrics_bind_endpoint="127.0.0.1:0").run()
        try:
            port = run.broker._metrics_server.sockets[0].getsockname()[1]
            sender = run.user(0).remote
            frame = serialize(Broadcast([0], b"topology-pump"))
            for _ in range(3):
                await sender.send_raw_many([frame] * 16)
                await asyncio.sleep(0.15)
            status, body = await _get(port, "/debug/topology")
            assert status == 200
            topo = json.loads(body)
        finally:
            await run.shutdown()
            umod.UringEngine.shutdown()
    finally:
        (umod._resolved, umod._warned_demote, cutthrough.ROUTE_IMPL,
         pump_mod.PUMP_IMPL, pump_mod._warned_demote) = saved

    pump = topo["cutthrough"]["pump"]
    assert pump is not None, "pump engaged but absent from topology"
    assert pump["engaged_peers"] >= 2, pump
    assert pump["pump_frames"] > 0, pump
    assert isinstance(pump["escalations"], dict)
    assert "native" in pump and "parked_leases" in pump
