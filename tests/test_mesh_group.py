"""MeshBrokerGroup integration: inter-broker traffic rides the device mesh
step (all_gather over the virtual CPU mesh) with NO host broker links —
the north-star path (BASELINE.json config 4 shape) in miniature."""

import asyncio

import numpy as np

from pushcdn_tpu.broker.mesh_group import MeshBrokerGroup, MeshGroupConfig
from pushcdn_tpu.parallel.mesh import make_broker_mesh
from pushcdn_tpu.proto.message import Broadcast, Direct
from pushcdn_tpu.testing.mesh_cluster import MeshCluster
from tests.test_integration import wait_until



async def test_cross_shard_broadcast_over_mesh_only():
    """4 shards, no host broker links: a broadcast reaches subscribers on
    every shard purely via the device mesh all_gather."""
    cluster = await MeshCluster(num_shards=4).start(form_host_mesh=False)
    try:
        clients = []
        for shard in range(4):
            clients.append(await cluster.place_client(
                seed=100 + shard, shard=shard, topics=[0]))
        # sanity: NO host broker links exist
        for b in cluster.brokers:
            assert b.connections.num_brokers == 0

        await clients[0].send_broadcast_message([0], b"over the mesh")
        for c in clients:
            got = await asyncio.wait_for(c.receive_message(), 10)
            assert isinstance(got, Broadcast)
            assert bytes(got.message) == b"over the mesh"
        assert cluster.group.steps >= 1
        assert cluster.group.messages_routed >= 4
        for c in clients:
            c.close()
    finally:
        await cluster.stop()


async def test_cross_shard_direct_over_mesh_only():
    cluster = await MeshCluster(num_shards=4).start(form_host_mesh=False)
    try:
        alice = await cluster.place_client(seed=200, shard=0, topics=[0])
        bob = await cluster.place_client(seed=201, shard=3, topics=[0])
        for b in cluster.brokers:
            assert b.connections.num_brokers == 0

        await alice.send_direct_message(bob.public_key, b"shard 0 -> shard 3")
        got = await asyncio.wait_for(bob.receive_message(), 10)
        assert isinstance(got, Direct)
        assert bytes(got.message) == b"shard 0 -> shard 3"
        # exactly-once: nothing else arrives
        with_timeout = asyncio.create_task(bob.receive_message())
        await asyncio.sleep(0.3)
        assert not with_timeout.done()
        with_timeout.cancel()
        alice.close()
        bob.close()
    finally:
        await cluster.stop()


async def test_cross_shard_traffic_with_gathered_bytes():
    """The multi-host configuration (gather_frame_bytes=True): frame bytes
    ride the step's collectives and egress decodes from the DEVICE-gathered
    tensors. The all_to_all direct output differs per shard — regression
    for pairing shard j's delivery mask with shard 0's received bytes."""
    cluster = await MeshCluster(
        num_shards=4, gather_frame_bytes=True).start(form_host_mesh=False)
    try:
        alice = await cluster.place_client(seed=210, shard=0, topics=[0])
        bob = await cluster.place_client(seed=211, shard=3, topics=[0])
        carol = await cluster.place_client(seed=212, shard=1, topics=[0])

        await alice.send_direct_message(bob.public_key, b"gathered 0 -> 3")
        got = await asyncio.wait_for(bob.receive_message(), 10)
        assert isinstance(got, Direct)
        assert bytes(got.message) == b"gathered 0 -> 3"

        await carol.send_broadcast_message([0], b"gathered bcast")
        for c in (alice, bob, carol):
            got = await asyncio.wait_for(c.receive_message(), 10)
            assert isinstance(got, Broadcast)
            assert bytes(got.message) == b"gathered bcast"
        for c in (alice, bob, carol):
            c.close()
    finally:
        await cluster.stop()


async def test_in_group_double_connect_kick():
    """The same identity connecting at a second shard kicks the first
    session immediately (authoritative in-group claim)."""
    cluster = await MeshCluster(num_shards=2).start(form_host_mesh=False)
    try:
        c1 = await cluster.place_client(seed=300, shard=0, topics=[0])
        c2 = await cluster.place_client(seed=300, shard=1, topics=[0])
        await wait_until(
            lambda: not cluster.brokers[0].connections.has_user(c1.public_key))
        assert cluster.brokers[1].connections.has_user(c2.public_key)
        # the surviving session still receives device-routed traffic
        await c2.send_direct_message(c2.public_key, b"still routed")
        got = await asyncio.wait_for(c2.receive_message(), 10)
        assert bytes(got.message) == b"still routed"
        c1.close()
        c2.close()
    finally:
        await cluster.stop()


async def test_mesh_group_host_fallback_on_step_failure():
    """If the device step blows up, staged frames re-route over the host
    links and the group disables itself (fail-open)."""
    cluster = await MeshCluster(num_shards=2).start(form_host_mesh=True)
    try:
        alice = await cluster.place_client(seed=400, shard=0, topics=[1])
        bob = await cluster.place_client(seed=401, shard=1, topics=[1])
        # host links exist as backup
        assert all(b.connections.num_brokers == 1 for b in cluster.brokers)

        # sabotage the device step
        def boom(*_a, **_k):
            raise RuntimeError("injected step failure")
        cluster.group.step_fn = boom

        await alice.send_broadcast_message([1], b"survives the failure")
        got = await asyncio.wait_for(bob.receive_message(), 10)
        assert bytes(got.message) == b"survives the failure"
        assert cluster.group.disabled
        # subsequent traffic flows purely on the host plane
        await alice.send_broadcast_message([1], b"host plane now")
        got2 = await asyncio.wait_for(bob.receive_message(), 10)
        assert bytes(got2.message) == b"host plane now"
        alice.close()
        bob.close()
    finally:
        await cluster.stop()


async def test_staged_broadcast_still_forwards_to_out_of_group_broker():
    """Mixed deployment: a broadcast staged on the mesh must STILL be
    forwarded over host links to interested brokers OUTSIDE the group."""
    from pushcdn_tpu.proto.transport.memory import gen_testing_connection_pair

    cluster = await MeshCluster(num_shards=2).start(form_host_mesh=False)
    try:
        alice = await cluster.place_client(seed=500, shard=0, topics=[0])
        # attach an out-of-group broker to shard 0 over a host link, with
        # interest in topic 0 (harness-style injection)
        ext_ident = "external-pub:1/external-priv:1"
        local, remote = await gen_testing_connection_pair()
        cluster.brokers[0].connections.add_broker(ext_ident, local)
        cluster.brokers[0].connections.subscribe_broker_to(ext_ident, [0])

        await alice.send_broadcast_message([0], b"reach outside too")
        # the device plane delivers alice's copy...
        got = await asyncio.wait_for(alice.receive_message(), 10)
        assert bytes(got.message) == b"reach outside too"
        # ...AND the external broker got a host-forwarded copy
        raw = await asyncio.wait_for(remote.recv_raw(), 10)
        from pushcdn_tpu.proto.message import deserialize
        ext_msg = deserialize(raw.data)
        assert isinstance(ext_msg, Broadcast)
        assert bytes(ext_msg.message) == b"reach outside too"
        raw.release()
        remote.close()
        alice.close()
    finally:
        await cluster.stop()


async def test_overflow_traffic_triggers_host_links_in_mesh_only_mode():
    """Mesh-only deployment (no host links formed up-front): traffic the
    device plane can't carry — here an oversized frame — must flag
    overflow, kick the heartbeat into dialing host links, and then flow
    cross-shard over those links instead of being silently lost."""
    cluster = await MeshCluster(num_shards=2).start(form_host_mesh=False)
    try:
        alice = await cluster.place_client(seed=600, shard=0, topics=[1])
        bob = await cluster.place_client(seed=601, shard=1, topics=[1])
        for b in cluster.brokers:
            assert b.connections.num_brokers == 0

        big = b"x" * 4096  # frame_bytes=1024 ⇒ ineligible for the mesh step
        await alice.send_broadcast_message([1], big)
        await wait_until(lambda: cluster.group.overflow_seen)
        # the kicked heartbeat forms host links promptly
        await wait_until(
            lambda: all(b.connections.num_brokers >= 1
                        for b in cluster.brokers))
        # with links up, oversized traffic crosses shards on the host plane
        await alice.send_broadcast_message([1], big + b"2")
        got = await asyncio.wait_for(bob.receive_message(), 10)
        assert bytes(got.message) == big + b"2"
        # and eligible traffic still rides the device mesh, exactly once
        await alice.send_broadcast_message([1], b"small still on mesh")
        got2 = await asyncio.wait_for(bob.receive_message(), 10)
        assert bytes(got2.message) == b"small still on mesh"
        pending = asyncio.create_task(bob.receive_message())
        await asyncio.sleep(0.3)
        assert not pending.done()  # no duplicate via host + mesh
        pending.cancel()
        alice.close()
        bob.close()
    finally:
        await cluster.stop()


async def test_size_bucketed_lanes_carry_large_frames_on_mesh():
    """Hard-part #1: with an extra 16 KB lane configured, frames too big
    for the base 1 KB lane still cross shards on the device mesh (no host
    links exist to fall back to), while small frames ride the base lane —
    each delivered exactly once."""
    cluster = await MeshCluster(
        num_shards=2, extra_lanes=((16384, 8, 4),),
    ).start(form_host_mesh=False)
    try:
        alice = await cluster.place_client(seed=700, shard=0, topics=[1])
        bob = await cluster.place_client(seed=701, shard=1, topics=[1])
        for b in cluster.brokers:
            assert b.connections.num_brokers == 0  # mesh-only

        big = b"L" * 8000   # > base lane (1 KB), fits the 16 KB lane
        await alice.send_broadcast_message([1], big)
        got = await asyncio.wait_for(bob.receive_message(), 10)
        assert bytes(got.message) == big
        assert not cluster.group.overflow_seen  # the lane carried it

        # direct frames use the lane buckets the same way
        await alice.send_direct_message(bob.public_key, b"D" * 4000)
        got2 = await asyncio.wait_for(bob.receive_message(), 10)
        assert bytes(got2.message) == b"D" * 4000

        await alice.send_broadcast_message([1], b"small lane")
        got3 = await asyncio.wait_for(bob.receive_message(), 10)
        assert bytes(got3.message) == b"small lane"

        pending = asyncio.create_task(bob.receive_message())
        await asyncio.sleep(0.3)
        assert not pending.done()  # exactly-once across lanes
        pending.cancel()
        alice.close()
        bob.close()
    finally:
        await cluster.stop()


async def test_shard_departure_survivors_keep_routing():
    """Hard-part #3 at the group level: one shard of a 3-shard mesh-only
    group stops; the static device mesh stays up, the stopped shard is
    masked dead, and the survivors keep exchanging traffic over the mesh
    with no host links and no group disable."""
    cluster = await MeshCluster(num_shards=3).start(form_host_mesh=False)
    try:
        alice = await cluster.place_client(seed=800, shard=0, topics=[0])
        bob = await cluster.place_client(seed=801, shard=1, topics=[0])
        carol = await cluster.place_client(seed=802, shard=2, topics=[0])

        await alice.send_broadcast_message([0], b"all three")
        for c in (alice, bob, carol):
            got = await asyncio.wait_for(c.receive_message(), 10)
            assert bytes(got.message) == b"all three"

        # shard 2 departs (its client goes with it)
        carol.close()
        await cluster.brokers[2].stop()
        assert not cluster.group._liveness[2]
        assert not cluster.group.disabled

        await alice.send_broadcast_message([0], b"survivors")
        for c in (alice, bob):
            got = await asyncio.wait_for(c.receive_message(), 10)
            assert bytes(got.message) == b"survivors"
        await alice.send_direct_message(bob.public_key, b"still one hop")
        got = await asyncio.wait_for(bob.receive_message(), 10)
        assert bytes(got.message) == b"still one hop"
        for b in cluster.brokers[:2]:
            assert b.connections.num_brokers == 0  # still mesh-only
        alice.close()
        bob.close()
    finally:
        await cluster.stop()


async def test_dead_shard_sweep_releases_slots():
    """on_shard_stopped must release every slot the dead shard still owned
    (a crashed broker fires no per-user removals): directs to its users
    then overflow to the host path instead of being staged at a ghost, and
    the slot table doesn't leak."""
    mesh = make_broker_mesh(2)
    group = MeshBrokerGroup(mesh, MeshGroupConfig(
        num_user_slots=8, ring_slots=4, frame_bytes=512, extra_lanes=()))
    group._liveness[:] = True
    group.claim_user(0, b"alice-key", [0])
    group.claim_user(1, b"bob-key", [0])
    assert len(group.slots) == 2

    # shard 1 "crashes": declared dead without per-user removals
    await group.on_shard_stopped(1)
    assert group.slots.slot_of(b"bob-key") is None  # mapping swept
    assert group.slots.slot_of(b"alice-key") is not None  # survivor intact
    assert not group._liveness[1]
    # swept slot is quarantined until the next step, then reusable
    assert len(group._quarantine) == 1


async def test_mid_session_subscribe_over_mesh():
    """A subscription added AFTER connect must reach the device mirrors
    (update_mask) and start delivering cross-shard broadcasts; an
    unsubscribe stops them."""
    cluster = await MeshCluster(num_shards=2).start(form_host_mesh=False)
    try:
        pub = await cluster.place_client(seed=950, shard=0, topics=[0])
        sub = await cluster.place_client(seed=951, shard=1, topics=[])

        # not subscribed yet: only the publisher (topic 0) receives
        await pub.send_broadcast_message([1], b"before subscribe")
        pending = asyncio.create_task(sub.receive_message())
        await asyncio.sleep(0.3)
        assert not pending.done(), "unsubscribed client received a broadcast"

        await sub.subscribe([1])
        await wait_until(lambda: bool(
            cluster.group._masks[
                cluster.group.slots.slot_of(sub.public_key)].any()))
        await pub.send_broadcast_message([1], b"after subscribe")
        got = await asyncio.wait_for(pending, 10)
        assert bytes(got.message) == b"after subscribe"

        await sub.unsubscribe([1])
        await wait_until(lambda: not
            cluster.group._masks[
                cluster.group.slots.slot_of(sub.public_key)].any())
        await pub.send_broadcast_message([1], b"after unsubscribe")
        late = asyncio.create_task(sub.receive_message())
        await asyncio.sleep(0.3)
        assert not late.done(), "unsubscribed client still receives"
        late.cancel()
        pub.close()
        sub.close()
    finally:
        await cluster.stop()


async def test_mesh_chaos_shard_death_under_load():
    """Device-mesh chaos tier: a shard dies MID-STREAM while a publisher
    keeps sending; survivors receive every message published after the
    death settles, and the group neither disables nor leaks the dead
    shard's slots."""
    cluster = await MeshCluster(num_shards=4, ring_slots=32).start(
        form_host_mesh=False)
    try:
        pub = await cluster.place_client(seed=980, shard=0, topics=[0])
        doomed = await cluster.place_client(seed=981, shard=2, topics=[0])
        survivors = [pub,
                     await cluster.place_client(seed=982, shard=1,
                                                topics=[0]),
                     await cluster.place_client(seed=983, shard=3,
                                                topics=[0])]
        received = [[] for _ in survivors]

        async def drain(idx):
            while True:
                for m in await survivors[idx].receive_messages():
                    received[idx].append(bytes(m.message))

        drains = [asyncio.create_task(drain(i))
                  for i in range(len(survivors))]
        stop_stream = asyncio.Event()
        sent = []

        async def stream():
            seq = 0
            while not stop_stream.is_set():
                payload = b"chaos-%06d" % seq
                await pub.send_broadcast_message([0], payload)
                sent.append(payload)
                seq += 1
                await asyncio.sleep(0.01)

        try:
            streamer = asyncio.create_task(stream())
            await asyncio.sleep(0.3)             # traffic flowing
            doomed.close()                       # client gone...
            await cluster.brokers[2].stop()      # ...and its shard dies
            await asyncio.sleep(0.5)             # group sweeps + settles
            # every message sent AFTER the death must reach all survivors
            post_death_from = len(sent)
            await asyncio.sleep(1.0)
            stop_stream.set()
            await streamer
            post = sent[post_death_from:]
            assert post, "stream never progressed after the shard death"

            def converged():
                for t in drains:  # surface a dead drain's real exception
                    if t.done():
                        t.result()
                return all(set(post) <= set(r) for r in received)

            await wait_until(converged, timeout=20)
        finally:
            stop_stream.set()
            for t in drains:
                t.cancel()
        assert not cluster.group.disabled
        # the doomed user's slot is gone after the (graceful) teardown;
        # the CRASH-path sweep is pinned separately by
        # test_dead_shard_sweep_releases_slots
        assert cluster.group.slots.slot_of(doomed.public_key) is None
        for c in survivors:
            c.close()
    finally:
        await cluster.stop()


async def test_mesh_tick_is_one_collective():
    """ISSUE 8: the group's default (fused) tick traces exactly ONE
    collective — the counted one-collective-per-tick invariant, observed
    at the running group (router.trace_collectives delta captured around
    the compiled step)."""
    cluster = await MeshCluster(num_shards=4).start(form_host_mesh=False)
    try:
        assert cluster.group.config.fused_collective
        a = await cluster.place_client(seed=900, shard=0, topics=[0])
        b = await cluster.place_client(seed=901, shard=2, topics=[0])
        await a.send_broadcast_message([0], b"tick")
        got = await asyncio.wait_for(b.receive_message(), 10)
        assert bytes(got.message) == b"tick"
        assert cluster.group.collectives_last_trace == 1, \
            cluster.group.collectives_last_trace
        a.close()
        b.close()
    finally:
        await cluster.stop()
