"""Generate a CA certificate + key pair on disk.

Parity with the reference's scripts/gen-ca.bash: multi-process deployments
over TCP+TLS need every broker/marshal to present leaf certs derived from
the SAME CA (a process-local auto-generated CA only works single-process).
Run this once per deployment and pass the paths via --ca-cert-path /
--ca-key-path to every binary.

Usage: python scripts/gen_ca.py [outdir]    (default ./ca)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pushcdn_tpu.proto.crypto.tls import _generate_ca  # noqa: E402


def main() -> None:
    outdir = sys.argv[1] if len(sys.argv) > 1 else "ca"
    os.makedirs(outdir, exist_ok=True)
    cert_pem, key_pem = _generate_ca()
    cert_path = os.path.join(outdir, "ca_cert.pem")
    key_path = os.path.join(outdir, "ca_key.pem")
    with open(cert_path, "wb") as f:
        f.write(cert_pem)
    with open(key_path, "wb") as f:
        f.write(key_pem)
    os.chmod(key_path, 0o600)
    print(f"wrote {cert_path} and {key_path}")
    print("pass --ca-cert-path/--ca-key-path to pushcdn-broker and "
          "pushcdn-marshal")


if __name__ == "__main__":
    main()
