#!/usr/bin/env python
"""Cross-round bench series: merge every ``BENCH_r*.json`` in the repo
root into ``BENCH_SERIES.md`` and (optionally) gate on regressions.

Each PR round leaves one ``BENCH_r<N>.json`` behind (written by
``benches/route_bench.py::write_bench_json``: per-section ``headline``
scalars + rows + provenance). This tool is the longitudinal view — the
same headline metric tracked round over round, so a perf regression is a
visible diff in BENCH_SERIES.md instead of an archaeology project:

    python scripts/bench_series.py                  # rewrite BENCH_SERIES.md
    python scripts/bench_series.py --gate           # exit 1 on >10% regression
    python scripts/bench_series.py --gate --threshold 0.25

The gate compares the LATEST round's metrics against the most recent
earlier round that carries the same metric (sections come and go as PRs
focus on different subsystems; a missing metric is not a regression).
Direction is inferred from the metric name — latency/footprint suffixes
(``_ms``/``_us``/``p99``/``lag``/``rss``…) are lower-is-better,
throughput suffixes (``msgs_s``/``ticks_s``/``ratio``/``ops``…) are
higher-is-better — and metrics with no inferable direction are tracked
in the table but never gated.

Absolute numbers are only comparable on the same host: the gate checks
the per-section provenance host fingerprint (platform + cpu count,
recorded since r13) and WAIVES — loudly, not silently — any comparison
whose baseline ran on a different host or predates provenance. The next
round on the same host re-engages the gate against the fresh baseline.

Legacy rounds (r01–r05 predate sections) are folded in as a ``legacy``
section from their single parsed metric line.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")

# direction inference on whole ``_``-separated tokens (substring matching
# is too greedy: ``chaos_scenarios`` contains ``_s``). Higher-better wins
# a conflict — ``msgs_s`` is a rate, not a time.
HIGHER_PARTS = {"msgs", "ops", "ratio", "users", "subs", "sheds",
                "chains", "delivered", "ticks", "frames", "throughput"}
LOWER_PARTS = {"ms", "us", "ns", "s", "p50", "p95", "p99", "lag",
               "overhead", "rss", "staleness", "bytes", "orphans",
               "orphaned", "stalled", "catchup", "latency"}


def direction(metric: str) -> int:
    """+1 higher-is-better, -1 lower-is-better, 0 unknown (not gated)."""
    parts = set(re.split(r"[^a-z0-9]+", metric.lower()))
    if parts & HIGHER_PARTS:
        return 1
    if parts & LOWER_PARTS:
        return -1
    return 0


def _slug(text: str) -> str:
    return re.sub(r"_+", "_", re.sub(r"\W", "_", text)).strip("_")


def load_rounds(root: str) -> dict:
    """{round: {section: {metric: value}}} from every BENCH_r*.json."""
    rounds = {}
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        m = ROUND_RE.search(path)
        if not m:
            continue
        rnd = int(m.group(1))
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except (OSError, ValueError) as exc:
            print(f"[series] skipping unreadable {path}: {exc}",
                  file=sys.stderr)
            continue
        sections = {}
        if "round" in doc:                       # modern: per-section headline
            for name, body in doc.items():
                if name == "round" or not isinstance(body, dict):
                    continue
                headline = body.get("headline") or {}
                metrics = {k: v for k, v in headline.items()
                           if isinstance(v, (int, float))
                           and not isinstance(v, bool)}
                if metrics:
                    sections[name] = metrics
        else:                                    # legacy r01–r05 schema
            parsed = doc.get("parsed") or {}
            metric, value = parsed.get("metric"), parsed.get("value")
            if metric and isinstance(value, (int, float)):
                sections["legacy"] = {_slug(metric): value}
        if sections:
            rounds[rnd] = sections
    return rounds


def _fingerprint(prov) -> "tuple | None":
    """Host identity a throughput number is only comparable within:
    (platform, cpus) from a section's provenance, or None when the round
    predates provenance recording (pre-r13) or left it empty."""
    if not isinstance(prov, dict):
        return None
    platform, cpus = prov.get("platform"), prov.get("cpus")
    if platform is None and cpus is None:
        return None
    return (platform, cpus)


def load_fingerprints(root: str) -> dict:
    """{round: {section: fingerprint-or-None}} — the per-section host
    identity alongside :func:`load_rounds` (legacy rounds get None)."""
    fps = {}
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        m = ROUND_RE.search(path)
        if not m:
            continue
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            continue
        if "round" not in doc:
            continue
        for name, body in doc.items():
            if name == "round" or not isinstance(body, dict):
                continue
            fps.setdefault(int(m.group(1)), {})[name] = \
                _fingerprint(body.get("provenance"))
    return fps


def _fmt(value) -> str:
    if value is None:
        return "—"
    if isinstance(value, float):
        return f"{value:,.4g}" if abs(value) < 1000 else f"{value:,.0f}"
    return f"{value:,}"


def render_markdown(rounds: dict) -> str:
    order = sorted(rounds)
    out = ["# Bench series", "",
           "Headline metrics per PR round, merged from `BENCH_r*.json` by",
           "`scripts/bench_series.py` (regenerate with no args; `--gate`",
           "fails CI on a >10% regression vs the previous round carrying",
           "the metric). Direction: ↑ higher-is-better, ↓ lower-is-better,",
           "· untracked.", ""]
    sections = sorted({s for secs in rounds.values() for s in secs})
    for section in sections:
        present = [r for r in order if section in rounds[r]]
        metrics = sorted({m for r in present for m in rounds[r][section]})
        out.append(f"## {section}")
        out.append("")
        head = "| metric | " + " | ".join(f"r{r}" for r in present) + " |"
        out.append(head)
        out.append("|" + "---|" * (len(present) + 1))
        for metric in metrics:
            arrow = {1: "↑", -1: "↓", 0: "·"}[direction(metric)]
            cells = [_fmt(rounds[r][section].get(metric)) for r in present]
            out.append(f"| {arrow} `{metric}` | " + " | ".join(cells) + " |")
        out.append("")
    return "\n".join(out)


def gate(rounds: dict, threshold: float, fingerprints: dict = None,
         waived: list = None) -> list:
    """Regressions of the latest round vs the nearest earlier round that
    carries the same metric: [(section, metric, prev_round, prev, cur,
    pct_worse), ...].

    When ``fingerprints`` (from :func:`load_fingerprints`) is given, a
    metric whose baseline round ran on a different host — or predates
    provenance recording while the latest round carries it — is NOT
    gated: absolute throughput/latency across hosts is noise, not a
    regression. Would-be failures land in ``waived`` (if provided) so
    the re-baseline is loud, and the next same-host round re-engages the
    gate automatically against the freshly recorded numbers."""
    if len(rounds) < 2:
        return []
    order = sorted(rounds)
    latest = order[-1]
    failures = []
    for section, metrics in rounds[latest].items():
        for metric, cur in metrics.items():
            sign = direction(metric)
            if sign == 0:
                continue
            prev_round = prev = None
            for r in reversed(order[:-1]):
                candidate = rounds[r].get(section, {}).get(metric)
                if candidate is not None:
                    prev_round, prev = r, candidate
                    break
            if prev is None or prev == 0:
                continue
            # pct_worse > 0 means the metric moved the wrong way
            change = (cur - prev) / abs(prev)
            pct_worse = -change if sign > 0 else change
            if pct_worse <= threshold:
                continue
            if fingerprints is not None:
                fp_prev = fingerprints.get(prev_round, {}).get(section)
                fp_cur = fingerprints.get(latest, {}).get(section)
                if fp_prev != fp_cur:
                    if waived is not None:
                        waived.append((section, metric, prev_round, prev,
                                       cur, pct_worse, fp_prev, fp_cur))
                    continue
            failures.append((section, metric, prev_round, prev, cur,
                             pct_worse))
    return failures


def reduce_timeline(path: str) -> "dict | None":
    """Reduce a ``cdn_top.py --record`` JSONL timeline into one headline
    dict: per-sample cluster scalars collapse to the mean (rates/ratios),
    the max (worst-case delays, lags, cumulative sheds), or the min
    (process-up/ready counts — a flapping process must show). Returns
    None when the file holds no usable samples."""
    samples = []
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    doc = json.loads(line)
                except ValueError:
                    continue
                head = doc.get("headline")
                if isinstance(head, dict):
                    samples.append((doc.get("t"), head))
    except OSError as exc:
        print(f"[series] cannot read timeline {path}: {exc}",
              file=sys.stderr)
        return None
    if not samples:
        return None
    keys = sorted({k for _, h in samples for k in h
                   if isinstance(h.get(k), (int, float))
                   and not isinstance(h.get(k), bool)})
    out = {}
    for key in keys:
        vals = [h[key] for _, h in samples if isinstance(
            h.get(key), (int, float)) and not isinstance(h.get(key), bool)]
        if not vals:
            continue
        parts = set(re.split(r"[^a-z0-9]+", key.lower()))
        if parts & {"p99", "p95", "lag", "sheds", "max"}:
            out[key] = max(vals)
        elif parts & {"procs", "up", "ready"}:
            out[key] = min(vals)
        else:
            out[key] = sum(vals) / len(vals)
    out["timeline_samples"] = len(samples)
    times = [t for t, _ in samples if isinstance(t, (int, float))]
    if len(times) >= 2:
        out["timeline_span_s"] = max(times) - min(times)
    return out


def ingest_timeline(root: str, path: str, rnd: int, section: str) -> bool:
    """Merge a reduced timeline into ``BENCH_r<rnd>.json`` as a section
    (headline + provenance), creating the round file if absent."""
    headline = reduce_timeline(path)
    if headline is None:
        print(f"[series] timeline {path} holds no samples", file=sys.stderr)
        return False
    bench_path = os.path.join(root, f"BENCH_r{rnd:02d}.json")
    doc = {"round": rnd}
    if os.path.exists(bench_path):
        try:
            with open(bench_path) as fh:
                doc = json.load(fh)
        except (OSError, ValueError) as exc:
            print(f"[series] cannot merge into {bench_path}: {exc}",
                  file=sys.stderr)
            return False
    try:
        sys.path.insert(0, REPO)
        from pushcdn_tpu.testing.provenance import provenance
        prov = provenance()
    except Exception:
        prov = {}
    doc[section] = {"headline": headline, "provenance": prov,
                    "source": os.path.basename(path)}
    with open(bench_path, "w") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
    print(f"[series] ingested {len(headline)} timeline metrics into "
          f"{bench_path} section {section!r}")
    return True


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--root", default=REPO,
                    help="directory holding BENCH_r*.json (default: repo)")
    ap.add_argument("--out", default=None,
                    help="output markdown (default: <root>/BENCH_SERIES.md)")
    ap.add_argument("--gate", action="store_true",
                    help="exit 1 if the latest round regressed >threshold "
                         "vs the previous round carrying the metric")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="gate threshold as a fraction (default 0.10)")
    ap.add_argument("--ingest-timeline", metavar="JSONL", default=None,
                    help="reduce a scripts/cdn_top.py --record timeline "
                         "into a BENCH_r<round>.json section before "
                         "rendering the series")
    ap.add_argument("--round", type=int, default=None,
                    help="round number for --ingest-timeline")
    ap.add_argument("--section", default="cluster_top",
                    help="section name for --ingest-timeline "
                         "(default cluster_top)")
    args = ap.parse_args()

    if args.ingest_timeline:
        if args.round is None:
            print("[series] --ingest-timeline needs --round",
                  file=sys.stderr)
            return 1
        if not ingest_timeline(args.root, args.ingest_timeline, args.round,
                               args.section):
            return 1

    rounds = load_rounds(args.root)
    if not rounds:
        print("[series] no BENCH_r*.json found", file=sys.stderr)
        return 1
    out_path = args.out or os.path.join(args.root, "BENCH_SERIES.md")
    with open(out_path, "w") as fh:
        fh.write(render_markdown(rounds))
    print(f"[series] wrote {out_path} "
          f"({len(rounds)} rounds: r{min(rounds)}..r{max(rounds)})")

    if args.gate:
        waived = []
        failures = gate(rounds, args.threshold, load_fingerprints(args.root),
                        waived)
        for (section, metric, prev_round, prev, cur, pct,
             fp_prev, fp_cur) in waived:
            print(f"[series] gate WAIVED {section}.{metric}: "
                  f"r{prev_round}={_fmt(prev)} -> r{max(rounds)}={_fmt(cur)} "
                  f"({pct:+.1%}) — host fingerprint changed "
                  f"({fp_prev or 'unrecorded'} -> {fp_cur or 'unrecorded'}); "
                  f"cross-host absolutes are not gated")
        for section, metric, prev_round, prev, cur, pct in failures:
            print(f"[series] GATE FAIL {section}.{metric}: "
                  f"r{prev_round}={_fmt(prev)} -> r{max(rounds)}={_fmt(cur)} "
                  f"({pct:+.1%} worse; threshold {args.threshold:.0%})")
        if failures:
            return 1
        print(f"[series] gate OK: no metric regressed "
              f">{args.threshold:.0%} vs its previous round on the "
              f"same host")
    return 0


if __name__ == "__main__":
    sys.exit(main())
