#!/usr/bin/env python
"""One-pane cluster collector (ISSUE 19 tentpole 4).

Polls every process's ``/metrics`` + ``/debug/topology`` (+ ``/readyz``)
and renders a live cluster view: per-process byte rates and writer-queue
depth, per-broker routed-frame rates with the pump hit ratio and its
escalation split, per-class flow rates and head-of-line queue delays,
retention/replay state, sheds, and the native pump stage latencies —
the numbers the scheduling work (ROADMAP item 4) and the mega-soak
(item 5) read from one place instead of N scrape targets.

Endpoints come from the ``local_cluster`` port layout or an explicit
list:

    python scripts/cdn_top.py --base-port 21700            # local_cluster
    python scripts/cdn_top.py --endpoints broker0=127.0.0.1:21800,marshal=127.0.0.1:21840

Modes:

    (default)        live pane, repainted every --interval seconds
    --once           two quick polls (rates need a delta), one render, exit
    --record F       append one JSONL sample per poll ({"t", "headline",
                     "procs"}) — reduce into a BENCH_r<N>.json section
                     with ``scripts/bench_series.py --ingest-timeline``
    --bundle DIR     capture a postmortem archive (every process's raw
                     metrics, health, topology, flightrec trails +
                     manifest) into DIR/bundle-<stamp>/ — on demand with
                     --once, and automatically when any /readyz flips
                     unready in watch mode (once per failure episode)
    --audit          frame-fate conservation audit (ISSUE 20): merge every
                     process's ``/debug/ledger`` into one cluster balance
                     sheet — per-process queued/fate/violation totals and
                     the per-link (sender-claimed sent vs receiver-counted
                     recv) deficits. Deficits toward peers absent from the
                     scrape set are ATTRIBUTED to that peer's death;
                     deficits between two live processes after drain are
                     unattributed loss. With --once: one fetch, one
                     report, exit 0 only when zero conservation
                     violations and zero unattributed deficit.

Exit code: 0 on a clean run, 1 when --once could not reach ANY endpoint
(or, with --audit --once, when the mesh balance sheet does not balance).
"""

from __future__ import annotations

import argparse
import json
import math
import os
import re
import sys
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# local_cluster.py metrics layout (keep in sync): each broker parent owns
# a 20-port block so per-shard worker endpoints (parent + 1 + shard)
# never collide with the next component
CLUSTER_LAYOUT = {"broker0": 100, "broker1": 120, "marshal": 140,
                  "client": 141, "client2": 142}


# ---------------------------------------------------------------------------
# scraping


def http_get(endpoint: str, path: str, timeout: float = 2.0):
    """(status, body) or None when nothing answers."""
    try:
        with urllib.request.urlopen(
                f"http://{endpoint}{path}", timeout=timeout) as resp:
            return resp.status, resp.read().decode(errors="replace")
    except urllib.error.HTTPError as exc:
        try:
            return exc.code, exc.read().decode(errors="replace")
        except OSError:
            return exc.code, ""
    except (urllib.error.URLError, OSError, TimeoutError, ValueError):
        return None


_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+([^\s]+)')
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_metrics(text: str) -> dict:
    """Prometheus text -> {sample_name: {labels_tuple: float}} where
    labels_tuple is a sorted tuple of (key, value) pairs. Histogram
    component samples (_bucket/_sum/_count) keep their suffixed names."""
    out: dict = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        name, rawlab, rawval = m.groups()
        try:
            val = float(rawval)
        except ValueError:
            continue
        labels = tuple(sorted(
            (k, v.replace('\\"', '"').replace("\\\\", "\\")
                 .replace("\\n", "\n"))
            for k, v in _LABEL_RE.findall(rawlab or "")))
        out.setdefault(name, {})[labels] = val
    return out


def scrape(name: str, endpoint: str) -> dict:
    """One process sample: raw metrics text + parsed families + readiness
    + (brokers) topology. Unreachable -> {"up": False}."""
    res = http_get(endpoint, "/metrics")
    if res is None or res[0] != 200:
        return {"name": name, "endpoint": endpoint, "up": False}
    sample = {"name": name, "endpoint": endpoint, "up": True,
              "t": time.monotonic(), "raw": res[1],
              "metrics": parse_metrics(res[1])}
    ready = http_get(endpoint, "/readyz")
    sample["ready"] = None if ready is None else ready[0] == 200
    sample["ready_body"] = None if ready is None else ready[1]
    topo = http_get(endpoint, "/debug/topology")
    if topo is not None and topo[0] == 200:
        try:
            sample["topology"] = json.loads(topo[1])
        except ValueError:
            pass
    return sample


# ---------------------------------------------------------------------------
# derivation


def sum_family(metrics: dict, name: str, **match) -> float:
    """Sum of a family's samples whose labels include every match pair."""
    total = 0.0
    for labels, val in (metrics.get(name) or {}).items():
        d = dict(labels)
        if all(d.get(k) == v for k, v in match.items()):
            total += val
    return total


def label_values(metrics: dict, name: str, label: str, **match) -> dict:
    """{label_value: summed value} over a family, filtered by match."""
    out: dict = {}
    for labels, val in (metrics.get(name) or {}).items():
        d = dict(labels)
        if all(d.get(k) == v for k, v in match.items()):
            key = d.get(label)
            if key is not None:
                out[key] = out.get(key, 0.0) + val
    return out


def hist_quantile(metrics: dict, name: str, q: float, base=None,
                  **match) -> float:
    """Quantile (seconds) from a cumulative-bucket histogram family,
    optionally over the DELTA vs a previous sample's parsed metrics
    (``base``) so watch mode shows the recent window, not all time.
    Returns NaN when the (delta) histogram is empty."""
    def buckets(src):
        rows = []
        for labels, val in (src.get(name + "_bucket") or {}).items():
            d = dict(labels)
            if not all(d.get(k) == v for k, v in match.items()):
                continue
            le = d.get("le")
            if le is None:
                continue
            rows.append((math.inf if le == "+Inf" else float(le), val))
        merged: dict = {}
        for le, val in rows:
            merged[le] = merged.get(le, 0.0) + val
        return dict(sorted(merged.items()))

    cur = buckets(metrics)
    if not cur:
        return math.nan
    prev = buckets(base) if base else {}
    deltas = [(le, cur[le] - prev.get(le, 0.0)) for le in cur]
    total = deltas[-1][1]
    if total <= 0:
        return math.nan
    target = q * total
    lo = 0.0
    for le, cum in deltas:
        if cum >= target:
            if le is math.inf:
                return lo  # open-ended bucket: report its lower bound
            prev_cum = 0.0
            for ple, pcum in deltas:
                if ple >= le:
                    break
                lo, prev_cum = ple, pcum
            span = cum - prev_cum
            frac = (target - prev_cum) / span if span > 0 else 1.0
            return lo + (le - lo) * frac
    return deltas[-1][0]


def _rate(cur: dict, prev: dict, name: str, dt: float, **match) -> float:
    if not prev or dt <= 0:
        return 0.0
    d = sum_family(cur, name, **match) - sum_family(prev, name, **match)
    return max(0.0, d) / dt


CLASSES = ("control", "consensus", "live", "bulk")


def derive(cur: dict, prev: dict) -> dict:
    """One process's view row from its current (and previous) sample."""
    if not cur.get("up"):
        return {"up": False}
    m = cur["metrics"]
    pm = (prev or {}).get("metrics") or {}
    dt = cur["t"] - prev["t"] if prev and prev.get("up") else 0.0
    row = {
        "up": True,
        "ready": cur.get("ready"),
        "in_mb_s": _rate(m, pm, "cdn_bytes_received", dt) / 1e6,
        "out_mb_s": _rate(m, pm, "cdn_bytes_sent", dt) / 1e6,
        "queue_depth_sum": sum_family(m, "cdn_writer_queue_depth",
                                      stat="sum"),
        "queue_depth_max": sum_family(m, "cdn_writer_queue_depth",
                                      stat="max"),
        "loop_lag_ms": sum_family(m, "cdn_event_loop_lag_seconds") * 1e3,
    }
    # routed-frame rates by path + pump ratio (brokers; zero elsewhere)
    paths = label_values(m, "cdn_route_batch_frames", "path")
    if paths:
        prev_paths = label_values(pm, "cdn_route_batch_frames", "path")
        deltas = {p: max(0.0, v - prev_paths.get(p, 0.0))
                  for p, v in paths.items()}
        routed = sum(deltas.values())
        row["routed_f_s"] = routed / dt if dt > 0 else 0.0
        row["pump_hit_pct"] = (100.0 * deltas.get("pump", 0.0) / routed
                               if routed > 0 else None)
        row["path_split"] = {p: v / dt if dt > 0 else 0.0
                             for p, v in deltas.items() if v > 0}
    esc = label_values(m, "cdn_pump_escalations", "reason")
    if esc:
        prev_esc = label_values(pm, "cdn_pump_escalations", "reason")
        row["escalations"] = {
            r: int(v - prev_esc.get(r, 0.0)) for r, v in esc.items()
            if v - prev_esc.get(r, 0.0) > 0}
    # per-class flow + head-of-line delay
    classes = {}
    for cls in CLASSES:
        out_f = _rate(m, pm, "cdn_class_frames", dt,
                      **{"class": cls, "dir": "out"})
        out_b = _rate(m, pm, "cdn_class_bytes", dt,
                      **{"class": cls, "dir": "out"})
        in_f = _rate(m, pm, "cdn_class_frames", dt,
                     **{"class": cls, "dir": "in"})
        p50 = hist_quantile(m, "cdn_writer_queue_delay_seconds", 0.50,
                            base=pm, **{"class": cls})
        p99 = hist_quantile(m, "cdn_writer_queue_delay_seconds", 0.99,
                            base=pm, **{"class": cls})
        if out_f or in_f or not math.isnan(p50):
            classes[cls] = {"out_f_s": out_f, "out_mb_s": out_b / 1e6,
                            "in_f_s": in_f,
                            "delay_p50_ms":
                                None if math.isnan(p50) else p50 * 1e3,
                            "delay_p99_ms":
                                None if math.isnan(p99) else p99 * 1e3}
    if classes:
        row["classes"] = classes
    # native pump stages (delta-window quantiles; counts all-time)
    stages = {}
    for stage in ("plan", "submit", "wire", "total"):
        count = sum_family(m, "cdn_pump_stage_seconds_count", stage=stage)
        if count > 0:
            p50 = hist_quantile(m, "cdn_pump_stage_seconds", 0.50,
                                base=pm, stage=stage)
            p99 = hist_quantile(m, "cdn_pump_stage_seconds", 0.99,
                                base=pm, stage=stage)
            stages[stage] = {
                "count": int(count),
                "p50_us": None if math.isnan(p50) else p50 * 1e6,
                "p99_us": None if math.isnan(p99) else p99 * 1e6}
    if stages:
        row["pump_stages"] = stages
    # retention / replay
    ring_bytes = sum_family(m, "cdn_retention_ring_bytes")
    ring_entries = sum_family(m, "cdn_retention_ring_entries")
    if ring_bytes or ring_entries:
        row["retention"] = {
            "topics": len(m.get("cdn_retention_ring_entries") or {}),
            "bytes": ring_bytes, "entries": ring_entries,
            "evictions": {k: int(v) for k, v in label_values(
                m, "cdn_retention_evictions", "reason").items()},
        }
    lags = label_values(m, "cdn_replay_lag_entries", "subscriber")
    lags = {k: v for k, v in lags.items() if v > 0}
    if lags:
        worst = max(lags, key=lags.get)
        row["replay_lag"] = {"max": int(lags[worst]), "subscriber": worst,
                             "subscribers": len(lags)}
    sheds = sum_family(m, "cdn_route_shed_total")
    if sheds:
        row["sheds"] = int(sheds)
    topo = cur.get("topology")
    if topo:
        shards = topo.get("shards")
        if shards:
            row["shards"] = len(shards)
        peers = topo.get("peers") or topo.get("brokers")
        if isinstance(peers, (list, dict)):
            row["mesh_peers"] = len(peers)
    return row


def headline(rows: dict) -> dict:
    """Cluster-level scalars from the per-process rows (the --record
    timeline's reducible surface: every value numeric or absent)."""
    up = [r for r in rows.values() if r.get("up")]
    head = {
        "procs": len(rows),
        "procs_up": len(up),
        "procs_ready": sum(1 for r in up if r.get("ready")),
        "out_mb_s": sum(r.get("out_mb_s", 0.0) for r in up),
        "routed_f_s": sum(r.get("routed_f_s", 0.0) for r in up),
        "sheds": sum(r.get("sheds", 0) for r in up),
    }
    ratios = [r["pump_hit_pct"] for r in up
              if r.get("pump_hit_pct") is not None]
    if ratios:
        head["pump_hit_pct"] = min(ratios)
    for cls in ("consensus", "bulk"):
        p99s = [r["classes"][cls]["delay_p99_ms"] for r in up
                if cls in r.get("classes", {})
                and r["classes"][cls]["delay_p99_ms"] is not None]
        if p99s:
            head[f"{cls}_delay_p99_ms"] = max(p99s)
    lags = [r["replay_lag"]["max"] for r in up if "replay_lag" in r]
    if lags:
        head["replay_lag_max"] = max(lags)
    return head


# ---------------------------------------------------------------------------
# rendering


def _fmt(v, unit="", digits=1):
    if v is None:
        return "—"
    if isinstance(v, float):
        if math.isnan(v):
            return "—"
        return f"{v:,.{digits}f}{unit}"
    return f"{v:,}{unit}"


def render(rows: dict, head: dict, poll: int, dt: float) -> str:
    out = [f"cdn_top — {head['procs_up']}/{head['procs']} up, "
           f"{head['procs_ready']} ready | poll {poll} (window {dt:.1f}s) "
           f"| out {_fmt(head['out_mb_s'])} MB/s, routed "
           f"{_fmt(head['routed_f_s'], ' f/s', 0)}"
           + (f", pump {_fmt(head['pump_hit_pct'], '%')}"
              if "pump_hit_pct" in head else "")]
    out.append("")
    out.append(f"{'PROC':<10} {'UP':<4} {'RDY':<4} {'IN MB/s':>8} "
               f"{'OUT MB/s':>9} {'QDEPTH s/m':>11} {'LAG ms':>7}")
    for name in sorted(rows):
        r = rows[name]
        if not r.get("up"):
            out.append(f"{name:<10} down")
            continue
        rdy = {True: "ok", False: "FAIL", None: "—"}[r.get("ready")]
        out.append(
            f"{name:<10} {'ok':<4} {rdy:<4} {_fmt(r['in_mb_s'], '', 2):>8} "
            f"{_fmt(r['out_mb_s'], '', 2):>9} "
            f"{int(r['queue_depth_sum']):>6}/{int(r['queue_depth_max']):<4} "
            f"{_fmt(r['loop_lag_ms'], '', 1):>7}")
    for name in sorted(rows):
        r = rows[name]
        if not r.get("up") or "routed_f_s" not in r:
            continue
        split = " | ".join(f"{p} {_fmt(v, ' f/s', 0)}"
                           for p, v in sorted(
                               (r.get("path_split") or {}).items()))
        shard = f", {r['shards']} shards" if "shards" in r else ""
        out.append("")
        out.append(f"{name}: routed {_fmt(r['routed_f_s'], ' f/s', 0)}"
                   + (f" (pump {_fmt(r['pump_hit_pct'], '%')})"
                      if r.get("pump_hit_pct") is not None else "")
                   + shard + (f" [{split}]" if split else ""))
        if r.get("escalations"):
            esc = " ".join(f"{k}={v}" for k, v in
                           sorted(r["escalations"].items()))
            out.append(f"  escalations (window): {esc}")
        if r.get("classes"):
            out.append(f"  {'class':<10} {'out f/s':>9} {'out MB/s':>9} "
                       f"{'in f/s':>8} {'delay p50/p99 ms':>18}")
            for cls in CLASSES:
                c = r["classes"].get(cls)
                if c is None:
                    continue
                out.append(
                    f"  {cls:<10} {_fmt(c['out_f_s'], '', 0):>9} "
                    f"{_fmt(c['out_mb_s'], '', 2):>9} "
                    f"{_fmt(c['in_f_s'], '', 0):>8} "
                    f"{_fmt(c['delay_p50_ms'], '', 3):>9}/"
                    f"{_fmt(c['delay_p99_ms'], '', 3)}")
        if r.get("pump_stages"):
            st = "  ".join(
                f"{s} {_fmt(v['p50_us'], '', 0)}/{_fmt(v['p99_us'], '', 0)}us"
                f" (n={v['count']})"
                for s, v in r["pump_stages"].items())
            out.append(f"  pump stages p50/p99: {st}")
        if r.get("retention"):
            ret = r["retention"]
            ev = " ".join(f"{k}={v}" for k, v in
                          sorted(ret["evictions"].items()))
            out.append(f"  retention: {ret['topics']} topics, "
                       f"{_fmt(ret['bytes'] / 1e6, ' MB', 2)}, "
                       f"{int(ret['entries'])} entries"
                       + (f" | evictions {ev}" if ev else ""))
        if r.get("replay_lag"):
            lag = r["replay_lag"]
            out.append(f"  replay lag: max {lag['max']} entries "
                       f"({lag['subscriber']}; "
                       f"{lag['subscribers']} replaying)")
        if r.get("sheds"):
            out.append(f"  sheds (all-time): {r['sheds']}")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# conservation audit (ISSUE 20)


def fetch_ledger(endpoint: str):
    """One process's /debug/ledger document, or None."""
    res = http_get(endpoint, "/debug/ledger", timeout=3.0)
    if res is None or res[0] != 200:
        return None
    try:
        return json.loads(res[1])
    except ValueError:
        return None


def _sheet_total(table: dict) -> int:
    return sum(int(v) for v in (table or {}).values())


def merge_audit(ledgers: dict) -> dict:
    """Merge per-process /debug/ledger docs into one cluster balance
    sheet. ``ledgers`` maps process name -> doc (or None when the
    endpoint had no ledger — e.g. the marshal or a client)."""
    procs = {}
    ident_to_name = {}
    for name, doc in ledgers.items():
        if not doc:
            continue
        local = doc.get("local") or {}
        ident = str(local.get("ident") or "") or name
        ident_to_name[ident] = name
        fates = local.get("fates") or {}
        by_fate = {"delivered": 0, "relayed": 0, "dropped": 0}
        drop_reasons = {}
        for key, row in fates.items():
            fate, _, reason = key.partition("/")
            if fate in by_fate:
                by_fate[fate] += _sheet_total(row)
            if fate == "dropped":
                drop_reasons[reason] = (drop_reasons.get(reason, 0)
                                        + _sheet_total(row))
        procs[name] = {
            "ident": ident,
            "queued": _sheet_total(local.get("queued")),
            "ingress": _sheet_total(local.get("ingress")),
            **by_fate,
            "drop_reasons": drop_reasons,
            "in_queue": _sheet_total(local.get("in_queue_derived")),
            "violations": int(local.get("violations") or 0),
        }
    links = []
    for name, doc in ledgers.items():
        if not doc:
            continue
        local = doc.get("local") or {}
        src = str(local.get("ident") or "") or name
        for dst, sent in (local.get("link_sent") or {}).items():
            dst_name = ident_to_name.get(dst)
            alive = dst_name is not None
            recv = {}
            if alive:
                dst_local = ledgers[dst_name].get("local") or {}
                recv = (dst_local.get("link_recv") or {}).get(src) or {}
            for cls, s in sorted(sent.items()):
                s = int(s)
                r = int(recv.get(cls, 0))
                if s == 0 and r == 0:
                    continue
                links.append({"src": src, "dst": dst, "class": cls,
                              "sent": s, "recv": r, "deficit": s - r,
                              "dst_alive": alive})
    unattributed = sum(l["deficit"] for l in links
                       if l["dst_alive"] and l["deficit"] > 0)
    attributed = sum(l["deficit"] for l in links
                     if not l["dst_alive"] and l["deficit"] > 0)
    return {
        "procs": procs,
        "links": links,
        "violations": sum(p["violations"] for p in procs.values()),
        "unattributed_deficit": unattributed,
        "attributed_deficit": attributed,
    }


def render_audit(audit: dict) -> str:
    """The cluster balance sheet, one screen. The final ``[audit]``
    summary line is the machine-readable verdict local_cluster asserts
    against."""
    out = [f"cdn_top audit — {len(audit['procs'])} ledgers, "
           f"{audit['violations']} conservation violations"]
    out.append("")
    out.append(f"{'PROC':<12} {'QUEUED':>9} {'DELIV':>9} {'RELAY':>9} "
               f"{'DROP':>7} {'IN-Q':>6} {'VIOL':>5}")
    for name in sorted(audit["procs"]):
        p = audit["procs"][name]
        out.append(f"{name:<12} {p['queued']:>9,} {p['delivered']:>9,} "
                   f"{p['relayed']:>9,} {p['dropped']:>7,} "
                   f"{p['in_queue']:>6,} {p['violations']:>5}")
        if p["drop_reasons"]:
            reasons = " ".join(f"{k}={v}" for k, v in
                               sorted(p["drop_reasons"].items()))
            out.append(f"{'':<12}   drops: {reasons}")
    residual = [l for l in audit["links"] if l["deficit"] != 0]
    if residual:
        out.append("")
        out.append("links with residual deficit (sender claim - "
                   "receiver count):")
        for l in residual:
            state = ("peer dead — attributed" if not l["dst_alive"]
                     else "peer alive — in-flight or LOSS")
            out.append(f"  {l['src']} -> {l['dst']} [{l['class']}]: "
                       f"sent {l['sent']:,} recv {l['recv']:,} "
                       f"deficit {l['deficit']:,} ({state})")
    out.append("")
    out.append(f"[audit] violations={audit['violations']} "
               f"unattributed_deficit={audit['unattributed_deficit']} "
               f"attributed_deficit={audit['attributed_deficit']}")
    return "\n".join(out)


def run_audit(args, endpoints: dict) -> int:
    """--audit driver: fetch + merge + render, once or on an interval.
    Exit 0 (with --once) only when the mesh balances."""
    while True:
        ledgers = {n: fetch_ledger(ep) for n, ep in endpoints.items()}
        if not any(ledgers.values()):
            print("[cdn_top] no endpoint served /debug/ledger",
                  file=sys.stderr)
            return 1
        audit = merge_audit(ledgers)
        print(render_audit(audit))
        if args.record:
            with open(args.record, "a") as fh:
                fh.write(json.dumps({"t": time.time(), "audit": audit})
                         + "\n")
        if args.once:
            ok = (audit["violations"] == 0
                  and audit["unattributed_deficit"] == 0)
            return 0 if ok else 1
        time.sleep(args.interval)


# ---------------------------------------------------------------------------
# bundle


def capture_bundle(out_dir: str, endpoints: dict, reason: str) -> str:
    """Postmortem archive: every process's raw observability surface in
    one directory — what you attach to the incident, captured while the
    cluster is still in the failed state."""
    stamp = time.strftime("%Y%m%d-%H%M%S")
    bdir = os.path.join(out_dir, f"bundle-{stamp}")
    os.makedirs(bdir, exist_ok=True)
    manifest = {"captured_at": time.time(), "reason": reason, "procs": {}}
    for name, endpoint in endpoints.items():
        entry = {"endpoint": endpoint, "files": []}
        for path, fname, binary_ok in (
                ("/metrics", f"{name}.metrics.txt", True),
                ("/healthz", f"{name}.healthz.json", False),
                ("/readyz", f"{name}.readyz.json", False),
                ("/debug/topology", f"{name}.topology.json", False),
                ("/debug/flightrec?limit=2000",
                 f"{name}.flightrec.json", False)):
            res = http_get(endpoint, path, timeout=3.0)
            if res is None:
                continue
            status, body = res
            if status != 200 and path.startswith("/debug"):
                continue  # marshal/client have no topology: skip quietly
            with open(os.path.join(bdir, fname), "w") as fh:
                fh.write(body)
            entry["files"].append({"file": fname, "path": path,
                                   "status": status})
        manifest["procs"][name] = entry
    with open(os.path.join(bdir, "manifest.json"), "w") as fh:
        json.dump(manifest, fh, indent=2)
    return bdir


# ---------------------------------------------------------------------------
# main


def discover_endpoints(args) -> dict:
    """{name: host:port} from --endpoints, or probed from the
    local_cluster layout at --base-port (only answering ports join —
    per-shard worker endpoints at broker parent + 1 + shard included)."""
    if args.endpoints:
        out = {}
        for item in args.endpoints.split(","):
            item = item.strip()
            if not item:
                continue
            name, _, ep = item.partition("=")
            if not ep:
                raise SystemExit(f"--endpoints entry {item!r} is not "
                                 f"name=host:port")
            out[name] = ep
        return out
    if args.base_port is None:
        raise SystemExit("need --base-port or --endpoints")
    bp = args.base_port
    out = {}
    for name, off in CLUSTER_LAYOUT.items():
        ep = f"{args.host}:{bp + off}"
        if http_get(ep, "/healthz", timeout=0.5) is not None:
            out[name] = ep
        if name.startswith("broker"):
            # sharded parents re-serve workers' metrics aggregated, but
            # the per-worker endpoints answer too — surface them when up
            for shard in range(args.shards):
                wep = f"{args.host}:{bp + off + 1 + shard}"
                if http_get(wep, "/healthz", timeout=0.3) is not None:
                    out[f"{name}/s{shard}"] = wep
    return out


def main() -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.split("\n", 1)[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--base-port", type=int, default=None,
                    help="local_cluster --base-port to derive the "
                         "metrics layout from (probed; silent ports "
                         "are skipped)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--shards", type=int, default=0,
                    help="also probe per-shard worker metrics endpoints "
                         "(broker parent port + 1 + shard)")
    ap.add_argument("--endpoints", default=None,
                    help="explicit name=host:port[,name=host:port...] "
                         "(bypasses layout discovery)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="poll / repaint interval (watch mode) and the "
                         "rate window for --once (default 2s)")
    ap.add_argument("--once", action="store_true",
                    help="two polls, one render, exit")
    ap.add_argument("--duration", type=float, default=None,
                    help="watch-mode time budget in seconds "
                         "(default: until interrupted)")
    ap.add_argument("--record", metavar="FILE", default=None,
                    help="append one JSONL timeline sample per poll")
    ap.add_argument("--bundle", metavar="DIR", default=None,
                    help="postmortem archive dir: captured on --once, "
                         "and on any /readyz failure in watch mode")
    ap.add_argument("--no-clear", action="store_true",
                    help="don't ANSI-clear between repaints (log-friendly)")
    ap.add_argument("--audit", action="store_true",
                    help="conservation audit: merge /debug/ledger across "
                         "processes into one cluster balance sheet "
                         "(--once exits 0 only when it balances)")
    args = ap.parse_args()

    endpoints = discover_endpoints(args)
    if not endpoints:
        print("[cdn_top] no endpoints answered", file=sys.stderr)
        return 1
    print(f"[cdn_top] watching {len(endpoints)} endpoints: "
          f"{', '.join(sorted(endpoints))}", file=sys.stderr)

    if args.audit:
        try:
            return run_audit(args, endpoints)
        except KeyboardInterrupt:
            return 0

    prev: dict = {}
    poll = 0
    bundle_armed = True  # one capture per failure episode
    deadline = (time.monotonic() + args.duration
                if args.duration is not None else None)
    try:
        while True:
            cur = {n: scrape(n, ep) for n, ep in endpoints.items()}
            poll += 1
            rows = {n: derive(cur[n], prev.get(n)) for n in cur}
            head = headline(rows)
            dt = args.interval
            ups = [n for n in cur if cur[n].get("up")
                   and prev.get(n, {}).get("up")]
            if ups:
                dt = cur[ups[0]]["t"] - prev[ups[0]]["t"]
            if poll > 1 or args.once:
                text = render(rows, head, poll, dt)
                if not (args.once or args.no_clear):
                    sys.stdout.write("\x1b[2J\x1b[H")
                print(text)
                if args.record:
                    with open(args.record, "a") as fh:
                        fh.write(json.dumps(
                            {"t": time.time(), "headline": head,
                             "procs": rows}) + "\n")
            unready = [n for n in cur
                       if cur[n].get("up") and cur[n].get("ready") is False]
            down = [n for n in cur if not cur[n].get("up")]
            if args.bundle and poll > 1:
                if (unready or down) and bundle_armed:
                    bdir = capture_bundle(
                        args.bundle, endpoints,
                        f"readyz failed: {unready or down}")
                    print(f"[cdn_top] bundle captured -> {bdir} "
                          f"(unready={unready}, down={down})",
                          file=sys.stderr)
                    bundle_armed = False
                elif not (unready or down):
                    bundle_armed = True
            if args.once:
                if poll == 1:
                    prev = cur
                    time.sleep(min(args.interval, 2.0))
                    continue
                if args.bundle:
                    bdir = capture_bundle(args.bundle, endpoints,
                                          "on-demand (--once --bundle)")
                    print(f"[cdn_top] bundle captured -> {bdir}",
                          file=sys.stderr)
                return 0 if any(c.get("up") for c in cur.values()) else 1
            prev = cur
            if deadline is not None and time.monotonic() >= deadline:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
