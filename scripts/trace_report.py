#!/usr/bin/env python
"""Offline lifecycle-trace aggregator: merge multi-process
``PUSHCDN_TRACE_LOG`` JSONL span files, assemble per-trace-id chains, and
report where the latency goes.

    python scripts/trace_report.py [--top N] [--json] PATH [PATH...]

``PATH`` is a span JSONL file or a directory of them (``*.jsonl``, the
layout ``scripts/local_cluster.py --trace-log DIR`` writes). The report
shows:

- per-hop latency from the trace origin: p50 / p95 / p99 / max — the
  transfer-level attribution ("RPC Considered Harmful") that per-message
  averages hide;
- the top-N slowest COMPLETE chains (publish → … → delivery), each with
  its hop-by-hop breakdown;
- orphaned / incomplete chain counts (a chain missing its delivery span
  means the message died in flight — or the receiver never logged),
  duplicate spans dropped, and clock-skewed hops (a hop timestamped
  before its predecessor: cross-machine clock skew, clamped to 0 in the
  stats and counted so the reader knows the numbers are floor values).

Exit status: 0 when at least one complete chain exists and ``--strict``
is off; with ``--strict``, nonzero on ANY orphaned span or incomplete
chain (the CI gate ``scripts/local_cluster.py`` runs).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Tuple

# chain-order canonical hops (auth precedes publish chronologically: the
# connection trace originates at dial time and the marshal stamps auth
# before the client's first publish reuses the id)
HOPS = ("auth", "publish", "ingress", "plan", "egress", "delivery")
REQUIRED = frozenset(("publish", "ingress", "plan", "egress", "delivery"))


def load_spans(paths: List[str]) -> Tuple[List[dict], int]:
    """Read span records from files/directories; returns
    ``(spans, duplicates_dropped)``. Duplicates — same (trace_id, hop,
    t_ns), e.g. a log shipped twice — are dropped here so every
    downstream count is over unique spans."""
    files: List[str] = []
    for path in paths:
        if os.path.isdir(path):
            files.extend(sorted(glob.glob(os.path.join(path, "*.jsonl"))))
        else:
            files.append(path)
    spans: List[dict] = []
    seen = set()
    duplicates = 0
    for path in files:
        try:
            fh = open(path)
        except OSError as exc:
            print(f"trace_report: cannot read {path}: {exc}",
                  file=sys.stderr)
            continue
        with fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                    rec["origin_ns"]  # build_report dereferences it too
                    key = (rec["trace_id"], rec["hop"], rec["t_ns"])
                except (ValueError, KeyError, TypeError):
                    continue  # torn/garbled line: skip, never crash
                if key in seen:
                    duplicates += 1
                    continue
                seen.add(key)
                rec.setdefault("detail", "")
                spans.append(rec)
    return spans, duplicates


def _pct(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[idx]


def build_view_report(spans: List[dict], top: int = 3):
    """Aggregate view-tagged spans (consensus workloads, ISSUE 11) into
    per-view SLOs: view completion time (first origin → last delivery),
    per-view chain completeness, and a per-hop breakdown of the slowest
    views. Returns ``None`` when no span carries a view tag."""
    by_view: Dict[int, List[dict]] = {}
    for rec in spans:
        view = rec.get("view")
        if view is None:
            continue
        by_view.setdefault(view, []).append(rec)
    if not by_view:
        return None

    per_view = {}
    completions: List[float] = []
    stalled = 0
    incomplete_total = 0
    for view, recs in sorted(by_view.items()):
        by_id: Dict[int, List[dict]] = {}
        for rec in recs:
            by_id.setdefault(rec["trace_id"], []).append(rec)
        complete = 0
        incomplete = 0
        for recs_of_id in by_id.values():
            if REQUIRED <= {r["hop"] for r in recs_of_id}:
                complete += 1
            else:
                incomplete += 1
        deliveries = [r["t_ns"] for r in recs if r["hop"] == "delivery"]
        start_ns = min(r["origin_ns"] for r in recs)
        completion_ms = (max(deliveries) - start_ns) / 1e6 \
            if deliveries else None
        hop_p95 = {}
        for hop in HOPS:
            vals = sorted(max(r["t_ns"] - r["origin_ns"], 0) / 1e6
                          for r in recs if r["hop"] == hop)
            if vals:
                hop_p95[hop] = round(_pct(vals, 0.95), 3)
        per_view[view] = {
            "chains": len(by_id),
            "complete": complete,
            "incomplete": incomplete,
            "completion_ms": (round(completion_ms, 3)
                              if completion_ms is not None else None),
            "stalled": not deliveries,
            "hop_p95_ms": hop_p95,
        }
        if completion_ms is not None:
            completions.append(completion_ms)
        else:
            stalled += 1
        incomplete_total += incomplete

    completions.sort()
    slowest = sorted(
        (v for v in per_view if per_view[v]["completion_ms"] is not None),
        key=lambda v: per_view[v]["completion_ms"], reverse=True)[:max(top, 0)]
    return {
        "views": len(per_view),
        "stalled_views": stalled,
        "incomplete_view_chains": incomplete_total,
        "completion_ms": {
            "p50": round(_pct(completions, 0.50), 3),
            "p95": round(_pct(completions, 0.95), 3),
            "p99": round(_pct(completions, 0.99), 3),
            "max": round(completions[-1], 3) if completions else 0.0,
        },
        "per_view": per_view,
        "slowest_views": slowest,
    }


def build_report(spans: List[dict], duplicates: int = 0,
                 top: int = 5) -> dict:
    """Assemble chains and stats from (deduplicated) span records."""
    by_id: Dict[int, List[dict]] = {}
    for rec in spans:
        by_id.setdefault(rec["trace_id"], []).append(rec)

    per_hop: Dict[str, List[float]] = {}
    skewed = 0
    complete: List[dict] = []
    incomplete = 0
    orphaned_spans = 0
    auth_only = 0
    for tid, recs in by_id.items():
        recs.sort(key=lambda r: r["t_ns"])
        hops = {r["hop"] for r in recs}
        if hops == {"auth"}:
            # a connection that authenticated but never published: its
            # trace id was never reused by a message, so there is no
            # message lifecycle to be incomplete — counted separately,
            # not as an orphan (churny subscribers would otherwise fail
            # the strict gate without a single lost message)
            auth_only += 1
            for r in recs:
                lat = (r["t_ns"] - r["origin_ns"]) / 1e9
                if lat < 0:
                    skewed += 1
                    lat = 0.0
                per_hop.setdefault(r["hop"], []).append(lat)
            continue
        # per-hop latency from the carried origin (floor at 0: a receiver
        # whose clock runs behind the origin's reports negative latency —
        # counted as skew, clamped in the stats)
        for r in recs:
            lat = (r["t_ns"] - r["origin_ns"]) / 1e9
            if lat < 0:
                skewed += 1
                lat = 0.0
            per_hop.setdefault(r["hop"], []).append(lat)
        if REQUIRED <= hops:
            delivery = max((r for r in recs if r["hop"] == "delivery"),
                           key=lambda r: r["t_ns"])
            complete.append({
                "trace_id": tid,
                "e2e_ms": max(delivery["t_ns"] - delivery["origin_ns"], 0)
                / 1e6,
                "recs": recs,
            })
        else:
            incomplete += 1
            orphaned_spans += len(recs)

    hop_stats = {}
    for hop, vals in per_hop.items():
        vals.sort()
        hop_stats[hop] = {
            "count": len(vals),
            "p50_ms": round(_pct(vals, 0.50) * 1e3, 3),
            "p95_ms": round(_pct(vals, 0.95) * 1e3, 3),
            "p99_ms": round(_pct(vals, 0.99) * 1e3, 3),
            "max_ms": round(vals[-1] * 1e3, 3),
        }

    complete.sort(key=lambda c: c["e2e_ms"], reverse=True)
    slowest = []
    for chain in complete[:max(top, 0)]:
        prev_t = None
        breakdown = []
        for r in chain["recs"]:
            dt = 0.0 if prev_t is None else (r["t_ns"] - prev_t) / 1e6
            breakdown.append({
                "hop": r["hop"],
                "at_ms": round(max(r["t_ns"] - r["origin_ns"], 0) / 1e6, 3),
                "dt_ms": round(max(dt, 0.0), 3),
                "skewed": dt < 0,
                "detail": r.get("detail", ""),
            })
            prev_t = r["t_ns"]
        slowest.append({"trace_id": f"{chain['trace_id']:016x}",
                        "e2e_ms": round(chain["e2e_ms"], 3),
                        "hops": breakdown})

    return {
        "spans": len(spans),
        "duplicates_dropped": duplicates,
        "trace_ids": len(by_id),
        "complete_chains": len(complete),
        "incomplete_chains": incomplete,
        "orphaned_spans": orphaned_spans,
        "auth_only_chains": auth_only,
        "skewed_hops": skewed,
        "per_hop": {hop: hop_stats[hop] for hop in HOPS
                    if hop in hop_stats},
        "slowest": slowest,
        "views": build_view_report(spans, top=min(top, 3)),
    }


def format_report(report: dict) -> str:
    out = [
        f"{report['spans']} spans / {report['trace_ids']} trace ids "
        f"({report['duplicates_dropped']} duplicates dropped, "
        f"{report['skewed_hops']} clock-skewed hops)",
        f"chains: {report['complete_chains']} complete, "
        f"{report['incomplete_chains']} incomplete "
        f"({report['orphaned_spans']} orphaned spans, "
        f"{report.get('auth_only_chains', 0)} auth-only connections)",
        "",
        f"{'hop':<10} {'count':>6} {'p50 ms':>9} {'p95 ms':>9} "
        f"{'p99 ms':>9} {'max ms':>9}",
    ]
    for hop, s in report["per_hop"].items():
        out.append(f"{hop:<10} {s['count']:>6} {s['p50_ms']:>9.3f} "
                   f"{s['p95_ms']:>9.3f} {s['p99_ms']:>9.3f} "
                   f"{s['max_ms']:>9.3f}")
    if report["slowest"]:
        out.append("")
        out.append(f"top {len(report['slowest'])} slowest complete chains:")
        for chain in report["slowest"]:
            out.append(f"  trace {chain['trace_id']}  "
                       f"e2e {chain['e2e_ms']:.3f} ms")
            for h in chain["hops"]:
                skew = "  [skewed]" if h["skewed"] else ""
                detail = f"  ({h['detail']})" if h["detail"] else ""
                out.append(f"    {h['hop']:<10} +{h['dt_ms']:8.3f} ms  "
                           f"@{h['at_ms']:8.3f} ms{detail}{skew}")
    vr = report.get("views")
    if vr:
        c = vr["completion_ms"]
        out.append("")
        out.append(f"views: {vr['views']} tagged "
                   f"({vr['stalled_views']} stalled, "
                   f"{vr['incomplete_view_chains']} incomplete view chains)")
        out.append(f"view completion ms: p50 {c['p50']:.3f}  "
                   f"p95 {c['p95']:.3f}  p99 {c['p99']:.3f}  "
                   f"max {c['max']:.3f}")
        for v in vr["slowest_views"]:
            s = vr["per_view"][v]
            hops = "  ".join(f"{h}@{ms:.2f}"
                             for h, ms in s["hop_p95_ms"].items())
            out.append(f"  view {v}: {s['completion_ms']:.3f} ms, "
                       f"{s['complete']}/{s['chains']} chains  [{hops}]")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="merge PUSHCDN_TRACE_LOG JSONL files and attribute "
                    "per-hop latency")
    ap.add_argument("paths", nargs="+",
                    help="span .jsonl files or directories of them")
    ap.add_argument("--top", type=int, default=5,
                    help="how many slowest chains to break down")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--strict", action="store_true",
                    help="exit nonzero on any orphaned span or incomplete "
                         "chain (the CI gate)")
    args = ap.parse_args(argv)
    spans, duplicates = load_spans(args.paths)
    report = build_report(spans, duplicates=duplicates, top=args.top)
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print(format_report(report))
    if report["complete_chains"] == 0:
        print("trace_report: FAIL: no complete chain", file=sys.stderr)
        return 1
    if args.strict and (report["orphaned_spans"]
                        or report["incomplete_chains"]):
        print("trace_report: FAIL (strict): "
              f"{report['incomplete_chains']} incomplete chains / "
              f"{report['orphaned_spans']} orphaned spans",
              file=sys.stderr)
        return 1
    vr = report.get("views")
    if args.strict and vr and (vr["stalled_views"]
                               or vr["incomplete_view_chains"]):
        # view-level gates (ISSUE 11): a view with zero deliveries is a
        # stall; a view-tagged chain missing hops is an in-view orphan
        print("trace_report: FAIL (strict): "
              f"{vr['stalled_views']} stalled views / "
              f"{vr['incomplete_view_chains']} incomplete view chains",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
