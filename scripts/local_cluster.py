#!/usr/bin/env python
"""Local cluster runner (parity with the reference's process-compose.yaml:
discovery store + marshal + 2 brokers + an echo client, each a real OS
process over TCP; SQLite stands in for KeyDB).

    python scripts/local_cluster.py [--duration 30] [--topology]

Beyond the end-to-end echo, the run proves the observability plane
(ISSUE 5) end to end:

- every process serves ``/healthz`` + ``/readyz`` (readiness is observed
  FALSE before broker0's listeners bind, TRUE once the cluster is up, and
  FALSE again during drain — before the listeners close);
- broker ``/debug/topology`` reflects the actual mesh (each broker sees
  the other as its one peer; the client appears as a user exactly once);
- ``scripts/trace_report.py --strict`` over the per-process span logs
  reports per-hop p50/p99 for a complete publish→delivery chain with zero
  orphaned spans (with ``--trace-log``).

``--chaos`` adds scripted failure injection after the baseline checks:
a broker SIGKILL (with ``--shards``, a shard-*worker* SIGKILL that
fail-fasts the whole sharded box), a marshal loss, and a discovery-store
outage — each asserted against its composition invariant (echo rides out
control-plane loss; survivors dump the abnormal-disconnect trail; new
admissions are refused, never silently dropped; everything recovers on
respawn/release).

Exits nonzero if any component dies early, the client fails to echo, or
any observability or chaos check fails.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


sys.path.insert(0, REPO)
from pushcdn_tpu.bin.common import spawn_binary  # noqa: E402

# brokers keep serving (readiness already 503) this long after SIGINT —
# the window the drain check probes
DRAIN_GRACE_S = 2.0


def spawn(name: str, *args: str, env_extra=None,
          log_path=None) -> subprocess.Popen:
    """Brokers and the marshal pass ``log_path``: nothing drains their
    pipes while they run (only the client's stdout is read live), and a
    chatty ``--shards`` broker — parent plus workers sharing one fd —
    wedges once the 64 KiB pipe buffer fills; a log file avoids the
    wedge while keeping crash output for the died-early diagnostic."""
    proc = spawn_binary(name, *args, env_extra=env_extra,
                        log_path=log_path)
    print(f"[cluster] {name} up (pid {proc.pid})")
    return proc


def http_get(port: int, path: str, timeout: float = 2.0):
    """(status, body_str) from a process's observability endpoint; None
    when nothing answers (connection refused / timeout)."""
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=timeout) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as exc:  # 4xx/5xx still carry a body
        return exc.code, exc.read().decode()
    except (urllib.error.URLError, OSError, TimeoutError):
        return None


def wait_http(port: int, path: str, wait_s: float = 8.0):
    """Poll until the endpoint answers at all; returns (status, body)."""
    deadline = time.time() + wait_s
    while time.time() < deadline:
        res = http_get(port, path, timeout=1.0)
        if res is not None:
            return res
        time.sleep(0.05)
    return None


def check_readiness_before_bind(port: int) -> bool:
    """broker0 starts its metrics endpoint BEFORE binding listeners (and
    holds the bind for PUSHCDN_BIND_DELAY_S): the first /readyz answer
    must be 503 with the listeners check failing."""
    res = wait_http(port, "/readyz")
    if res is None:
        print("[cluster] FAIL: broker0 /readyz never answered during startup")
        return False
    status, body = res
    if status != 503:
        print(f"[cluster] FAIL: pre-bind /readyz was {status}, wanted 503 "
              f"(body {body[:200]})")
        return False
    try:
        doc = json.loads(body)
        # sharded brokers aggregate worker checks as "shardN:listeners";
        # an unreachable worker ("shardN:reachable" false) is the same
        # not-ready-before-bind state observed earlier in startup
        relevant = [c["ok"] for name, c in doc["checks"].items()
                    if name.rsplit(":", 1)[-1] in ("listeners",
                                                   "reachable")]
        listeners_ok = bool(relevant) and all(relevant)
    except (ValueError, KeyError):
        print(f"[cluster] FAIL: pre-bind /readyz body unparseable: {body[:200]}")
        return False
    if listeners_ok:
        print("[cluster] FAIL: pre-bind /readyz 503 but listeners check ok?")
        return False
    print("[cluster] readiness pre-bind: 503 not-ready (listeners unbound) "
          "as expected")
    return True


def check_health(ports: dict) -> bool:
    """/healthz + /readyz on every process: 200s with the check schema."""
    for name, port in ports.items():
        for path in ("/healthz", "/readyz"):
            res = None
            deadline = time.time() + 10.0
            while time.time() < deadline:  # readiness may lag startup
                res = http_get(port, path)
                if res is not None and res[0] == 200:
                    break
                time.sleep(0.2)
            if res is None:
                print(f"[cluster] FAIL: {name} {path} unreachable")
                return False
            status, body = res
            try:
                doc = json.loads(body)
                checks = doc["checks"]
                assert isinstance(checks, dict)
                for c in checks.values():
                    assert isinstance(c["ok"], bool)
                    assert "detail" in c
            except (ValueError, KeyError, AssertionError):
                print(f"[cluster] FAIL: {name} {path} schema drift: "
                      f"{body[:300]}")
                return False
            if status != 200:
                print(f"[cluster] FAIL: {name} {path} = {status} "
                      f"({body[:300]})")
                return False
    print(f"[cluster] health OK ({len(ports)} processes serve "
          "/healthz + /readyz)")
    return True


TOPOLOGY_KEYS = ("identity", "draining", "interest_version", "num_users",
                 "num_brokers", "peers", "users", "interest", "cutthrough")


def fetch_topology(port: int):
    res = http_get(port, "/debug/topology")
    if res is None or res[0] != 200:
        return None
    try:
        return json.loads(res[1])
    except ValueError:
        return None


def check_topology(broker_ports: dict, expected_users: int = 1) -> bool:
    """Each broker's /debug/topology must reflect the real mesh: the other
    broker as its one peer, and every client as a user exactly once."""
    topos = {}
    for name, port in broker_ports.items():
        deadline = time.time() + 10.0
        topo = None
        while time.time() < deadline:
            topo = fetch_topology(port)
            if topo is not None and topo.get("num_brokers", 0) >= 1:
                break
            time.sleep(0.2)
        if topo is None:
            print(f"[cluster] FAIL: {name} /debug/topology unreachable")
            return False
        missing = [k for k in TOPOLOGY_KEYS if k not in topo]
        if missing:
            print(f"[cluster] FAIL: {name} topology schema drift: "
                  f"missing {missing}")
            return False
        topos[name] = topo
    idents = {name: t["identity"] for name, t in topos.items()}
    for name, topo in topos.items():
        peer_ids = [p["id"] for p in topo["peers"]]
        expected = [i for n, i in idents.items() if n != name]
        if sorted(peer_ids) != sorted(expected):
            print(f"[cluster] FAIL: {name} mesh mismatch: peers={peer_ids} "
                  f"expected={expected}")
            return False
    total_users = sum(t["num_users"] for t in topos.values())
    if total_users != expected_users:
        print(f"[cluster] FAIL: expected exactly {expected_users} connected "
              f"user(s) across the mesh, saw {total_users}")
        return False
    print(f"[cluster] topology OK (mesh verified: each broker sees the "
          f"other; {total_users} user(s) connected)")
    return True


def check_pump(broker_ports: dict) -> bool:
    """``--pump auto``: poll each broker's topology until the fused
    data-plane pump reports engaged peers AND natively pumped frames
    (the echo client keeps publishing in the background, so frames keep
    arriving while we poll), or report an honest skip when the
    composition cannot engage on this host — never a silent demotion."""
    deadline = time.time() + 12.0
    engaged = {}
    while time.time() < deadline:
        for name, port in broker_ports.items():
            topo = fetch_topology(port)
            ps = ((topo or {}).get("cutthrough") or {}).get("pump")
            if ps:
                engaged[name] = ps
                if ps.get("pump_frames", 0) > 0:
                    print(f"[cluster] pump OK ({name}: engaged_peers="
                          f"{ps['engaged_peers']}, pump_frames="
                          f"{ps['pump_frames']}, escalated="
                          f"{sum(ps.get('escalations', {}).values())})")
                    return True
        time.sleep(0.3)
    if engaged:
        print(f"[cluster] FAIL: pump engaged but never pumped a frame: "
              f"{engaged}")
        return False
    print("[cluster] pump skipped (composition not engaged on this host: "
          "io_uring or the native route planner unavailable)")
    return True


def check_collector(metrics_ports: dict, broker_ports: dict,
                    logdir: str) -> bool:
    """``--collector``: drive ``scripts/cdn_top.py --once --record
    --bundle`` against the live cluster and verify the one-pane plane
    end to end — the collector reaches every process, the recorded
    timeline carries a reducible headline, and the postmortem bundle
    holds every process's raw metrics plus each broker's topology. When
    the fused pump is live (pumped frames visible in topology), the
    bundled broker metrics must also show nonzero
    ``cdn_pump_stage_seconds`` samples for all four stages; otherwise
    that sub-check skips loudly (never a silent pass on an
    asyncio-demoted host)."""
    record = os.path.join(logdir, "cdn_top_timeline.jsonl")
    bundle_root = os.path.join(logdir, "bundles")
    eps = ",".join(f"{n}=127.0.0.1:{p}" for n, p in metrics_ports.items())
    cmd = [sys.executable, os.path.join(REPO, "scripts", "cdn_top.py"),
           "--endpoints", eps, "--once", "--interval", "1.0",
           "--record", record, "--bundle", bundle_root]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=90)
    except subprocess.TimeoutExpired:
        print("[cluster] FAIL: cdn_top --once --bundle timed out")
        return False
    if proc.returncode != 0:
        print(f"[cluster] FAIL: cdn_top rc={proc.returncode}\n"
              f"{proc.stdout[-1500:]}\n{proc.stderr[-1500:]}")
        return False
    # the rendered pane reached stdout (one line per process at minimum)
    for name in metrics_ports:
        if name not in proc.stdout:
            print(f"[cluster] FAIL: cdn_top pane missing process {name}:\n"
                  f"{proc.stdout[-1500:]}")
            return False
    # recorded timeline: >=1 sample whose headline saw every process up
    try:
        with open(record) as fh:
            samples = [json.loads(ln) for ln in fh if ln.strip()]
    except (OSError, ValueError) as exc:
        print(f"[cluster] FAIL: cdn_top --record unreadable: {exc}")
        return False
    if not samples or samples[-1]["headline"].get("procs_up", 0) \
            != len(metrics_ports):
        print(f"[cluster] FAIL: timeline headline incomplete: "
              f"{samples[-1]['headline'] if samples else 'no samples'}")
        return False
    # bundle: every process's metrics + every broker's topology + manifest
    bundles = sorted(os.path.join(bundle_root, d)
                     for d in os.listdir(bundle_root)
                     if d.startswith("bundle-")) if \
        os.path.isdir(bundle_root) else []
    if not bundles:
        print("[cluster] FAIL: cdn_top --bundle wrote no bundle dir")
        return False
    bdir = bundles[-1]
    missing = [f"{n}.metrics.txt" for n in metrics_ports
               if not os.path.exists(os.path.join(bdir,
                                                  f"{n}.metrics.txt"))]
    missing += [f"{n}.topology.json" for n in broker_ports
                if not os.path.exists(os.path.join(
                    bdir, f"{n}.topology.json"))]
    if not os.path.exists(os.path.join(bdir, "manifest.json")):
        missing.append("manifest.json")
    if missing:
        print(f"[cluster] FAIL: bundle {bdir} missing {missing}")
        return False
    # pump stage telemetry: required exactly when the pump really pumped
    pumped = False
    for name, port in broker_ports.items():
        topo = fetch_topology(port)
        ps = ((topo or {}).get("cutthrough") or {}).get("pump")
        if ps and ps.get("pump_frames", 0) > 0:
            pumped = True
    if pumped:
        stages_seen = set()
        for name in broker_ports:
            with open(os.path.join(bdir, f"{name}.metrics.txt")) as fh:
                text = fh.read()
            for m in re.finditer(
                    r'cdn_pump_stage_seconds_count\{stage="(\w+)"\} '
                    r'(\d+)', text):
                if int(m.group(2)) > 0:
                    stages_seen.add(m.group(1))
        want = {"plan", "submit", "wire", "total"}
        if stages_seen != want:
            print(f"[cluster] FAIL: pump live but bundle shows stage "
                  f"samples only for {sorted(stages_seen)} "
                  f"(want {sorted(want)})")
            return False
        print(f"[cluster] collector OK (bundle {os.path.basename(bdir)}: "
              f"{len(metrics_ports)} metrics + {len(broker_ports)} "
              f"topologies; pump stages all nonzero)")
    else:
        print(f"[cluster] collector OK (bundle {os.path.basename(bdir)}: "
              f"{len(metrics_ports)} metrics + {len(broker_ports)} "
              f"topologies; pump-stage check skipped — pump not engaged "
              f"on this host)")
    return True


def _audit_once(metrics_ports: dict, logdir: str):
    """One ``cdn_top --audit --once`` sweep against the brokers' ledger
    endpoints. Returns ``(rc, output, summary)`` where ``summary`` is the
    machine-readable ``[audit] violations=... unattributed_deficit=...
    attributed_deficit=...`` verdict line."""
    eps = ",".join(f"{n}=127.0.0.1:{p}" for n, p in metrics_ports.items())
    cmd = [sys.executable, os.path.join(REPO, "scripts", "cdn_top.py"),
           "--endpoints", eps, "--audit", "--once",
           "--record", os.path.join(logdir, "audit_timeline.jsonl")]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=60)
    except subprocess.TimeoutExpired:
        return -1, "cdn_top --audit timed out", ""
    summary = next((ln for ln in proc.stdout.splitlines()
                    if ln.startswith("[audit]")), "")
    return proc.returncode, proc.stdout + proc.stderr, summary


def _audit_until_balanced(metrics_ports: dict, logdir: str, label: str,
                          deadline_s: float = 30.0) -> bool:
    """Re-run the mesh audit until it balances: decision-time link
    counters legitimately lead the receiver's ingress count while frames
    are in flight, so a clean balance is an eventually-quiescent property
    — but one that MUST arrive within the deadline."""
    deadline = time.time() + deadline_s
    while True:
        rc, out, summary = _audit_once(metrics_ports, logdir)
        if rc == 0 and "violations=0" in summary \
                and "unattributed_deficit=0" in summary:
            print(f"[cluster] audit OK ({label}): {summary}")
            return True
        if time.time() >= deadline:
            print(f"[cluster] FAIL: conservation audit ({label}) never "
                  f"balanced (rc={rc}): {summary or '(no verdict line)'}\n"
                  f"{out[-2000:]}")
            return False
        time.sleep(1.0)


def check_audit(metrics_ports: dict, broker_ports: dict,
                logdir: str) -> bool:
    """``--audit`` clean leg: merge every broker's /debug/ledger into one
    cluster balance sheet (scripts/cdn_top.py --audit --once) and require
    zero conservation violations and zero unattributed mesh deficit —
    every frame either reached a terminal fate or is visibly in flight."""
    audit_ports = {k: v for k, v in metrics_ports.items()
                   if k in broker_ports}   # only brokers serve ledgers
    return _audit_until_balanced(audit_ports, logdir, "clean")


def check_audit_chaos(procs, replace_proc, spawn_broker,
                      metrics_ports: dict, broker_ports: dict,
                      logdir: str) -> bool:
    """``--audit`` chaos leg: SIGKILL broker1 mid-stream and prove the
    balance sheet stays honest — every frame the survivor committed
    toward the dead peer shows up as ATTRIBUTED deficit (charged to the
    dead incarnation), never as silent unattributed loss; after the
    respawn, the link-epoch reset returns the mesh to a clean balance."""
    victim = "broker1"
    audit_ports = {k: v for k, v in metrics_ports.items()
                   if k in broker_ports and k != victim}
    proc = _proc_of(procs, victim)
    print(f"[cluster] audit chaos: SIGKILL {victim} mid-stream")
    proc.kill()
    proc.wait(timeout=10)

    ok = True
    # the survivor notices the dead link (EOF => failure-is-removal),
    # drains its queue with counted drop fates, and the merged audit must
    # balance with the dead peer's whole residual attributed to it
    attributed = None
    deadline = time.time() + 30.0
    while True:
        rc, out, summary = _audit_once(audit_ports, logdir)
        m = re.search(r" attributed_deficit=(\d+)", summary)
        if rc == 0 and "violations=0" in summary \
                and "unattributed_deficit=0" in summary and m:
            attributed = int(m.group(1))
            break
        if time.time() >= deadline:
            print(f"[cluster] FAIL: post-kill audit never balanced "
                  f"(rc={rc}): {summary or '(no verdict line)'}\n"
                  f"{out[-2000:]}")
            return False
        time.sleep(1.0)
    if attributed > 0:
        print(f"[cluster] audit chaos: {attributed} undelivered frame(s) "
              f"fully attributed to the dead {victim}")
    else:
        print(f"[cluster] FAIL: {victim}'s link carried no accounted "
              "frames — the attribution leg proved nothing")
        ok = False

    # respawn the victim; the fresh incarnation reuses its canonical
    # identity, so the re-formed link's epoch reset (plus the boot stamp
    # in its first LedgerSync) must converge the mesh back to clean
    replace_proc(victim, spawn_broker(int(victim[-1])))

    def mesh_reformed() -> bool:
        for port in broker_ports.values():
            topo = fetch_topology(port)
            if topo is None or topo.get("num_brokers", 0) != 1:
                return False
        return True

    deadline = time.time() + 60.0
    while time.time() < deadline and not mesh_reformed():
        time.sleep(0.3)
    if not mesh_reformed():
        print(f"[cluster] FAIL: mesh never re-formed after the audit "
              f"chaos {victim} kill")
        return False
    full_ports = {k: v for k, v in metrics_ports.items()
                  if k in broker_ports}
    ok = _audit_until_balanced(full_ports, logdir, "post-respawn") and ok
    return ok


def check_shard_plane(port: int, num_shards: int) -> bool:
    """Sharded broker0: the merged topology must show users spread across
    2+ worker shards and the handoff rings having carried records — the
    proof the cross-shard zero-copy hop ran for real."""
    deadline = time.time() + 15.0
    last = None
    while time.time() < deadline:
        topo = fetch_topology(port)
        if topo is not None:
            last = topo
            shards = topo.get("shards") or {}
            user_shards = {u.get("shard") for u in topo.get("users", [])}
            ring_records = 0
            for stats in shards.values():
                for r in ((stats or {}).get("rings") or {}).get(
                        "in", {}).values():
                    ring_records += r.get("records", 0)
            if len(shards) == num_shards and len(user_shards) >= 2 \
                    and ring_records > 0:
                print(f"[cluster] shard plane OK: {len(shards)} workers, "
                      f"users on shards {sorted(user_shards)}, "
                      f"{ring_records} cross-shard ring records drained")
                return True
        time.sleep(0.3)
    print(f"[cluster] FAIL: shard plane never showed cross-shard traffic "
          f"(last topology: {json.dumps(last)[:600]})")
    return False


def render_merged_topology(broker_ports: dict) -> None:
    """One merged cluster view from every broker's /debug/topology."""
    print("[cluster] ---- merged topology ----")
    for name, port in sorted(broker_ports.items()):
        topo = fetch_topology(port)
        if topo is None:
            print(f"  {name}: <unreachable>")
            continue
        cut = topo.get("cutthrough") or {}
        print(f"  {name} [{topo['identity']}] users={topo['num_users']} "
              f"brokers={topo['num_brokers']} "
              f"interest_v={topo['interest_version']} "
              f"draining={topo['draining']}")
        for p in topo["peers"]:
            print(f"    peer {p['id']}: queue={p['writer_queue_depth']} "
                  f"in-flight={p['bytes_in_flight']}B topics={p['topics']}")
        for u in topo["users"]:
            print(f"    user {u['key']}: topics={u['topics']} "
                  f"queue={u['writer_queue_depth']}")
        if cut:
            print(f"    cut-through: usable={cut.get('usable')} "
                  f"age={cut.get('snapshot_age_s')}s "
                  f"churn-skips={cut.get('churn_guard_skips_left')}")
    print("[cluster] ---- end topology ----")


# readiness stays 503 this long after the last shed (the window the
# --churn check polls; generous so the observation can't race the flip)
SHED_READY_S = 6.0


def check_load_shed(marshal_port: int, broker_ports: dict) -> bool:
    """--churn (ISSUE 7): force subscribe-rate overload through a real
    broker via the REAL client library and verify the whole shed surface
    — the client's typed ``Error(SHED)``, ``/readyz`` flipping 503 with
    the ``admission`` check failing, the ``load-shed`` flight-recorder
    event, then recovery back to 200 once the storm stops. The churn
    client stays CONNECTED until the flight-recorder check passes (the
    trail lives on its connection's recorder)."""
    import asyncio

    from pushcdn_tpu.bin.common import keypair_from_seed
    from pushcdn_tpu.client import Client, ClientConfig
    from pushcdn_tpu.proto.error import Error, ErrorKind
    from pushcdn_tpu.proto.transport.tcp import Tcp

    def admission_failing(body: str) -> bool:
        try:
            doc = json.loads(body)
            return any(name.rsplit(":", 1)[-1] == "admission"
                       and not c["ok"]
                       for name, c in doc.get("checks", {}).items())
        except (ValueError, KeyError, TypeError):
            return False

    async def drive() -> bool:
        client = Client(ClientConfig(
            marshal_endpoint=f"127.0.0.1:{marshal_port}",
            keypair=keypair_from_seed(99),
            protocol=Tcp, subscribed_topics=set()))
        try:
            async with asyncio.timeout(20):
                await client.ensure_initialized()
            shed = False
            for _ in range(60):
                await client.subscribe([1])
                await client.unsubscribe([1])
                try:  # drain any pending shed notice quickly
                    async with asyncio.timeout(0.02):
                        await client.receive_message()
                except (TimeoutError, asyncio.TimeoutError):
                    continue
                except Error as exc:
                    if exc.kind != ErrorKind.SHED:
                        raise
                    shed = True
                    break
            if not shed:
                try:  # notices may still be in flight: one longer read
                    async with asyncio.timeout(3.0):
                        await client.receive_message()
                except (TimeoutError, asyncio.TimeoutError):
                    pass
                except Error as exc:
                    shed = exc.kind == ErrorKind.SHED
            if not shed:
                print("[cluster] FAIL: churn client never received the "
                      "typed Error(shed)")
                return False
            print("[cluster] typed shed Error observed by the client "
                  "(Error kind=shed for over-rate subscribe)")

            shed_broker = None
            deadline = time.time() + SHED_READY_S
            while time.time() < deadline and shed_broker is None:
                for name, port in broker_ports.items():
                    res = http_get(port, "/readyz")
                    if res is not None and res[0] == 503 \
                            and admission_failing(res[1]):
                        shed_broker = (name, port)
                        break
                await asyncio.sleep(0.1)
            if shed_broker is None:
                print("[cluster] FAIL: no broker flipped /readyz on the "
                      "shed")
                return False
            name, port = shed_broker
            print(f"[cluster] load shed observed: {name} /readyz 503 "
                  "(admission check failing)")

            res = http_get(port, "/debug/flightrec?limit=400")
            if res is None or res[0] != 200 or "load-shed" not in res[1]:
                print(f"[cluster] FAIL: {name} /debug/flightrec has no "
                      f"load-shed event ({(res or ('?', ''))[1][:300]})")
                return False
            print(f"[cluster] shed flight-recorder event recorded on "
                  f"{name}")

            deadline = time.time() + SHED_READY_S + 8.0
            while time.time() < deadline:
                res = http_get(port, "/readyz")
                if res is not None and res[0] == 200:
                    print(f"[cluster] load shed recovered: {name} "
                          "/readyz 200 after the storm stopped")
                    return True
                await asyncio.sleep(0.2)
            print(f"[cluster] FAIL: {name} never recovered /readyz 200 "
                  "after the churn stopped")
            return False
        finally:
            client.close()

    return asyncio.run(drive())


def check_replay(marshal_port: int, broker_ports: dict) -> bool:
    """--replay (ISSUE 14): durable catch-up through REAL processes —
    publish on a retained topic, see one frame live, KILL the subscriber,
    publish more into the ring, then rejoin on a fresh client with
    ``subscribe_from(topic, 1)`` and assert every frame comes back as an
    in-order ``Retained`` run followed by live delivery.

    Retention is broker-local (seqs are per-broker), so the rejoining
    client must land on a broker whose ring is complete: the marshal owns
    placement, so we redial with fresh seeds until /debug/topology shows
    co-location with the publisher (2 brokers — a couple of draws). The
    replay clients run untraced: a broadcast retained with zero live
    subscribers has no delivery span by design, and the strict
    zero-orphan gate must stay meaningful for the echo traffic."""
    import asyncio

    from pushcdn_tpu.bin.common import keypair_from_seed
    from pushcdn_tpu.client import Client, ClientConfig
    from pushcdn_tpu.proto.message import Broadcast, Retained
    from pushcdn_tpu.proto.transport.tcp import Tcp
    from pushcdn_tpu.proto.util import mnemonic

    K = 5
    TOPIC = 1  # the echo client broadcasts on 0; topic 1's ring is ours

    def mk(seed: int) -> Client:
        c = Client(ClientConfig(
            marshal_endpoint=f"127.0.0.1:{marshal_port}",
            keypair=keypair_from_seed(seed), protocol=Tcp,
            subscribed_topics=set()))
        c._sampler.every = 0
        return c

    def home_of(key: bytes):
        wanted = mnemonic(key)
        for name, port in broker_ports.items():
            res = http_get(port, "/debug/topology")
            if res is None or res[0] != 200:
                continue
            try:
                topo = json.loads(res[1])
            except ValueError:
                continue
            if any(u.get("key") == wanted for u in topo.get("users", ())):
                return name
        return None

    async def recv_stream(c: Client, want: int, deadline_s: float):
        out = []
        loop = asyncio.get_running_loop()
        deadline = loop.time() + deadline_s
        while len(out) < want and loop.time() < deadline:
            try:
                async with asyncio.timeout(
                        max(0.05, deadline - loop.time())):
                    msgs = await c.receive_messages()
            except (TimeoutError, asyncio.TimeoutError):
                break
            for m in msgs:
                if isinstance(m, Retained):
                    out.append(("retained", m.seq, bytes(m.payload)))
                elif isinstance(m, Broadcast):
                    out.append(("live", None, bytes(m.message)))
        return out

    async def drive() -> bool:
        pub = mk(96)
        sub = mk(97)
        rejoin = None
        try:
            async with asyncio.timeout(20):
                await pub.ensure_initialized()
            async with asyncio.timeout(20):
                await sub.ensure_initialized()
            await sub.subscribe([TOPIC])
            await asyncio.sleep(0.8)   # interest propagates via the mesh
            await pub.send_broadcast_message([TOPIC], b"replay-0")
            first = await recv_stream(sub, 1, 10.0)
            if first != [("live", None, b"replay-0")]:
                print(f"[cluster] FAIL: pre-kill subscriber saw {first!r}")
                return False
            print("[cluster] replay phase 1: live frame delivered, "
                  "killing the subscriber")
            sub.close()
            await asyncio.sleep(0.5)   # the broker reaps the connection
            for i in range(1, K):
                await pub.send_broadcast_message(
                    [TOPIC], f"replay-{i}".encode())
            pub_home = home_of(pub.public_key)
            # rejoin CO-LOCATED with the publisher (complete ring)
            for seed in range(98, 110):
                rejoin = mk(seed)
                try:
                    async with asyncio.timeout(20):
                        await rejoin.ensure_initialized()
                except (TimeoutError, asyncio.TimeoutError):
                    rejoin.close()
                    rejoin = None
                    continue
                if pub_home is None or home_of(
                        rejoin.public_key) == pub_home:
                    break
                rejoin.close()
                rejoin = None
            if rejoin is None:
                print("[cluster] FAIL: could not co-locate the rejoin "
                      "client with the publisher")
                return False
            await rejoin.subscribe_from(TOPIC, 1)
            got = await recv_stream(rejoin, K, 15.0)
            want = [("retained", i + 1, f"replay-{i}".encode())
                    for i in range(K)]
            if got != want:
                print(f"[cluster] FAIL: replay stream {got!r} != {want!r}")
                return False
            print(f"[cluster] replay phase 2: {K} retained frames "
                  "replayed in order (seqs 1..%d)" % K)
            await pub.send_broadcast_message([TOPIC], b"replay-live")
            tail = await recv_stream(rejoin, 1, 10.0)
            if tail != [("live", None, b"replay-live")]:
                print(f"[cluster] FAIL: post-replay live frame missing "
                      f"({tail!r})")
                return False
            print("[cluster] replay OK: retained 1..%d then live, "
                  "no gap, no dup" % K)
            return True
        finally:
            pub.close()
            sub.close()
            if rejoin is not None:
                rejoin.close()

    return asyncio.run(drive())


# ---------------------------------------------------------------------------
# scripted chaos (--chaos): kill real processes mid-run and assert the
# composition invariants — the data plane rides out control-plane loss,
# survivors converge, and every event leaves a flight-recorder trail
# ---------------------------------------------------------------------------


class EchoWatch:
    """Watch the echo client's merged stdout for FRESH lines without
    blocking. Reads the raw fd (the startup loop's buffered reader is
    done by chaos time): anything already pipelined is drained first, so
    a match proves the data plane worked AFTER the chaos event."""

    def __init__(self, proc: subprocess.Popen):
        self.proc = proc
        self.fd = proc.stdout.fileno()

    def _read_chunk(self) -> str:
        import select
        r, _, _ = select.select([self.fd], [], [], 0.25)
        if not r:
            return ""
        try:
            chunk = os.read(self.fd, 65536)
        except OSError:
            return ""
        return chunk.decode(errors="replace")

    def drain(self, settle_s: float = 0.3) -> None:
        deadline = time.time() + settle_s
        while time.time() < deadline:
            self._read_chunk()

    def wait_fresh(self, needle: str, wait_s: float) -> bool:
        buf = ""
        deadline = time.time() + wait_s
        while time.time() < deadline:
            if self.proc.poll() is not None:
                print("[chaos] FAIL: echo client process died")
                return False
            buf += self._read_chunk()
            if needle in buf:
                return True
        return False


def try_connect(marshal_port: int, seed: int, timeout_s: float) -> bool:
    """One in-process client connect attempt through the real marshal —
    the probe for 'can NEW work be admitted right now?'."""
    import asyncio

    from pushcdn_tpu.bin.common import keypair_from_seed
    from pushcdn_tpu.client import Client, ClientConfig
    from pushcdn_tpu.proto.transport.tcp import Tcp

    async def drive() -> bool:
        client = Client(ClientConfig(
            marshal_endpoint=f"127.0.0.1:{marshal_port}",
            keypair=keypair_from_seed(seed),
            protocol=Tcp, subscribed_topics=set()))
        try:
            async with asyncio.timeout(timeout_s):
                await client.ensure_initialized()
            return True
        except Exception:
            return False
        finally:
            client.close()

    return asyncio.run(drive())


def _log_gained(path: str, offset: int, needle: str, wait_s: float) -> bool:
    """True once ``needle`` appears in ``path`` PAST ``offset`` — the
    flight-recorder correlation check (dumps land in the survivor's log
    after the event, never before it)."""
    deadline = time.time() + wait_s
    while time.time() < deadline:
        try:
            with open(path, errors="replace") as fh:
                fh.seek(offset)
                if needle in fh.read():
                    return True
        except OSError:
            pass
        time.sleep(0.3)
    return False


def _log_size(path: str) -> int:
    try:
        return os.path.getsize(path)
    except OSError:
        return 0


def check_chaos(procs: list, replace_proc, spawn_broker, spawn_marshal,
                watch: "EchoWatch", broker_ports: dict, metrics_ports: dict,
                marshal_port: int, db: str, logdir: str, shards: int,
                events=("broker", "marshal", "discovery")) -> bool:
    """Scripted chaos events against the live cluster, each asserted
    against its composition invariant:

    1. **broker SIGKILL** (or, with ``--shards``, SIGKILL of one shard
       *worker*, which fail-fasts the whole sharded box): the elastic
       client re-load-balances through the marshal and echoes again; the
       surviving broker's flight recorder dumps the abnormal peer
       disconnect; the victim respawns and the mesh re-forms.
    2. **marshal loss**: NEW admissions fail, but the established data
       plane keeps echoing (control/data decoupling); the respawned
       marshal admits again.
    3. **discovery outage**: the store's write lock is held hostage, so
       permit minting (and heartbeats) fail — new admissions are refused
       while the outage lasts, heartbeat failures land in the process
       flight recorder (``task-died heartbeat``), and everything recovers
       on release. (The embedded store's writes are synchronous, so
       in-flight echoes can stall with it — the invariant asserted is
       refuse-then-recover, not zero-jitter.)
    """
    ok = True
    if "broker" in events:
        ok = _chaos_broker_kill(procs, replace_proc, spawn_broker, watch,
                                broker_ports, metrics_ports, logdir,
                                shards) and ok
    if "marshal" in events:
        ok = _chaos_marshal_loss(procs, replace_proc, spawn_marshal, watch,
                                 marshal_port) and ok
    if "discovery" in events:
        ok = _chaos_discovery_outage(watch, broker_ports, marshal_port,
                                     db) and ok
    if ok:
        print("[chaos] OK: all chaos events rode out with invariants held")
    return ok


def _proc_of(procs: list, name: str) -> subprocess.Popen:
    return next(p for n, p in procs if n == name)


def _chaos_broker_kill(procs, replace_proc, spawn_broker,
                       watch: "EchoWatch", broker_ports: dict,
                       metrics_ports: dict, logdir: str,
                       shards: int) -> bool:
    ok = True
    if shards > 1:
        victim = "broker0"
        topo = fetch_topology(metrics_ports[victim])
        worker = ((topo or {}).get("shards") or {}).get("1") or {}
        pid = worker.get("pid")
        if not pid:
            print("[chaos] FAIL: no shard-worker pid in broker0 topology")
            return False
        survivor = "broker1"
        surv_log0 = _log_size(os.path.join(logdir, f"{survivor}.log"))
        print(f"[chaos] SIGKILL shard-1 worker (pid {pid}) of {victim}")
        os.kill(pid, signal.SIGKILL)
        # fail-fast supervisor: ANY dead worker takes the whole box down
        proc = _proc_of(procs, victim)
        deadline = time.time() + 20.0
        while time.time() < deadline and proc.poll() is None:
            time.sleep(0.1)
        if proc.poll() is None:
            print("[chaos] FAIL: sharded broker0 survived a dead worker "
                  "(fail-fast supervisor broken)")
            ok = False
    else:
        # kill whichever broker is serving the echo client — the sharpest
        # version of the event (the reconnect path MUST run)
        users = {}
        for name, port in broker_ports.items():
            topo = fetch_topology(port)
            users[name] = (topo or {}).get("num_users", 0)
        victim = max(users, key=lambda n: users[n])
        survivor = next(n for n in broker_ports if n != victim)
        surv_log0 = _log_size(os.path.join(logdir, f"{survivor}.log"))
        print(f"[chaos] SIGKILL {victim} (serving {users[victim]} user(s))")
        watch.drain()
        proc = _proc_of(procs, victim)
        proc.kill()
        proc.wait(timeout=10)

    watch.drain()
    if not watch.wait_fresh("recv direct", 45.0):
        print(f"[chaos] FAIL: client never echoed again after {victim} "
              "was killed")
        ok = False
    else:
        print(f"[chaos] echo resumed after {victim} kill (client "
              "re-load-balanced through the marshal)")
    # a SIGKILLed peer reads as a clean FIN on the survivor (failure-is-
    # removal, sender.rs semantics): the correlation trail is the removal
    # diagnostic ("broker X removed (...); forgot N routed users"), which
    # the connection's flight recorder also carries as a "removed" event
    if not _log_gained(os.path.join(logdir, f"{survivor}.log"), surv_log0,
                       "; forgot", 20.0):
        print(f"[chaos] FAIL: {survivor} never logged the dead peer's "
              "removal")
        ok = False
    else:
        print(f"[chaos] peer-loss correlation: {survivor} recorded the "
              "dead peer's removal")

    # respawn the victim and wait for the mesh to re-form
    idx = int(victim[-1])
    replace_proc(victim, spawn_broker(idx))

    def mesh_reformed() -> bool:
        for port in broker_ports.values():
            topo = fetch_topology(port)
            if topo is None or topo.get("num_brokers", 0) != 1:
                return False
        return True

    deadline = time.time() + 60.0
    while time.time() < deadline and not mesh_reformed():
        time.sleep(0.3)
    if not mesh_reformed():
        print(f"[chaos] FAIL: mesh never re-formed after {victim} respawn")
        ok = False
    else:
        print(f"[chaos] mesh re-formed after {victim} respawn")
    return ok


def _chaos_marshal_loss(procs, replace_proc, spawn_marshal,
                        watch: "EchoWatch", marshal_port: int) -> bool:
    ok = True
    print("[chaos] SIGKILL marshal")
    proc = _proc_of(procs, "marshal")
    proc.kill()
    proc.wait(timeout=10)
    if try_connect(marshal_port, seed=201, timeout_s=4.0):
        print("[chaos] FAIL: a new client connected with the marshal dead")
        ok = False
    else:
        print("[chaos] new admissions refused while the marshal is down")
    watch.drain()
    if not watch.wait_fresh("recv direct", 20.0):
        print("[chaos] FAIL: established data plane stalled during "
              "marshal loss")
        ok = False
    else:
        print("[chaos] established data plane kept echoing through "
              "marshal loss")
    replace_proc("marshal", spawn_marshal())
    if not try_connect(marshal_port, seed=202, timeout_s=25.0):
        print("[chaos] FAIL: new client could not connect after the "
              "marshal respawn")
        ok = False
    else:
        print("[chaos] marshal respawned; new admissions flow again")
    return ok


def _chaos_discovery_outage(watch: "EchoWatch", broker_ports: dict,
                            marshal_port: int, db: str) -> bool:
    import sqlite3

    ok = True
    print("[chaos] discovery outage: holding the store's write lock")
    lock = sqlite3.connect(db, isolation_level=None)
    try:
        lock.execute("PRAGMA busy_timeout=1000")
        lock.execute("BEGIN IMMEDIATE")
        outage_t0 = time.time()
        if try_connect(marshal_port, seed=203, timeout_s=4.0):
            print("[chaos] FAIL: a new client was admitted during the "
                  "discovery outage (permit mint should have failed)")
            ok = False
        else:
            print("[chaos] new admissions refused during the discovery "
                  "outage")
        # hold the lock PAST the store's 5 s busy timeout so at least one
        # broker heartbeat actually fails (a shorter outage just delays
        # the write, and the failure trail would never exist)
        remaining = 8.0 - (time.time() - outage_t0)
        if remaining > 0:
            time.sleep(remaining)
    finally:
        try:
            lock.rollback()
        finally:
            lock.close()
    if not try_connect(marshal_port, seed=204, timeout_s=25.0):
        print("[chaos] FAIL: admissions never recovered after the "
              "discovery outage")
        ok = False
    else:
        print("[chaos] admissions recovered after the discovery outage")
    watch.drain()
    if not watch.wait_fresh("recv direct", 20.0):
        print("[chaos] FAIL: echo never resumed after the discovery outage")
        ok = False
    # heartbeat failures during the outage are supervised-task deaths —
    # the correlation trail lives in the brokers' process flight recorder
    flightrec_seen = False
    deadline = time.time() + 10.0
    while time.time() < deadline and not flightrec_seen:
        for port in broker_ports.values():
            res = http_get(port, "/debug/flightrec?limit=400")
            if res is not None and res[0] == 200 \
                    and "task-died" in res[1] and "heartbeat" in res[1]:
                flightrec_seen = True
                break
        time.sleep(0.3)
    if not flightrec_seen:
        print("[chaos] FAIL: no broker recorded the heartbeat failure in "
              "its flight recorder during the outage")
        ok = False
    else:
        print("[chaos] flight-recorder correlation: heartbeat task-died "
              "event recorded during the outage")
    return ok


def check_rehome(broker_ports: dict, watch: "EchoWatch") -> bool:
    """ISSUE 12: operator-triggered elastic drain against REAL brokers.
    ``GET /drain`` on the broker homing the echo client must actively
    re-home every user (typed Migrate frames, make-before-break): the
    user count moves to the surviving broker, the drained broker latches
    /readyz 503 ``draining`` while still serving, and the echo keeps
    flowing on the new home."""
    homes = {}
    for name, port in broker_ports.items():
        topo = fetch_topology(port)
        if topo is None:
            print(f"[cluster] FAIL: {name} topology unreachable pre-rehome")
            return False
        homes[name] = topo["num_users"]
    target = max(homes, key=lambda n: homes[n])
    if homes[target] == 0:
        print("[cluster] FAIL: no broker homes any user pre-rehome")
        return False
    survivor = next(n for n in broker_ports if n != target)
    users_moving = homes[target]
    watch.drain()
    res = http_get(broker_ports[target], "/drain", timeout=30.0)
    if res is None or res[0] != 200:
        print(f"[cluster] FAIL: {target} /drain did not answer: {res}")
        return False
    try:
        summary = json.loads(res[1])
    except ValueError:
        print(f"[cluster] FAIL: /drain body unparseable: {res[1][:200]}")
        return False
    print(f"[cluster] rehome drain summary from {target}: {summary}")
    if summary.get("signaled", 0) < users_moving or summary.get("orphaned"):
        print("[cluster] FAIL: drain signaled too few users or left "
              "orphans")
        return False
    deadline = time.time() + 20.0
    moved = False
    while time.time() < deadline:
        t_old = fetch_topology(broker_ports[target])
        t_new = fetch_topology(broker_ports[survivor])
        if t_old and t_new and t_old["num_users"] == 0 \
                and t_new["num_users"] >= homes[survivor] + users_moving:
            moved = True
            break
        time.sleep(0.2)
    if not moved:
        print(f"[cluster] FAIL: users never moved {target} -> {survivor}")
        return False
    res = http_get(broker_ports[target], "/readyz")
    if res is None or res[0] != 503:
        print(f"[cluster] FAIL: drained {target} still reports ready: {res}")
        return False
    # the data plane survived the migration: a FRESH direct echo arrives
    # through the new home (the client re-homed without a marshal trip)
    if not watch.wait_fresh("recv direct", 15.0):
        print("[cluster] FAIL: echo stalled after re-home")
        return False
    print(f"[cluster] rehome OK: {users_moving} user(s) re-homed "
          f"{target} -> {survivor}, echo alive on the new home")
    return True


def check_drain(name: str, proc: subprocess.Popen, port: int) -> bool:
    """SIGINT the process and verify /readyz flips to 503 (draining)
    BEFORE the listeners close — the process keeps answering through the
    drain grace window."""
    proc.send_signal(signal.SIGINT)
    deadline = time.time() + DRAIN_GRACE_S + 3.0
    while time.time() < deadline:
        res = http_get(port, "/readyz", timeout=0.5)
        if res is None:
            if proc.poll() is not None:
                print(f"[cluster] FAIL: {name} exited before its drain "
                      "readiness flip was observable")
                return False
            time.sleep(0.05)
            continue
        status, body = res
        drain_latched = False
        if status == 503:
            try:
                drain_latched = json.loads(body)["draining"] is True
            except (ValueError, KeyError):
                drain_latched = False
        if drain_latched:
            print(f"[cluster] drain readiness flip observed on {name} "
                  "(503 draining while still serving)")
            proc.wait(timeout=DRAIN_GRACE_S + 10)
            return True
        time.sleep(0.05)
    print(f"[cluster] FAIL: {name} never reported draining on /readyz")
    return False


def run_trace_report(trace_dir: str, wait_s: float = 10.0) -> bool:
    """The CI gate: merge the span logs and require per-hop stats for at
    least one complete chain with zero orphans (retried briefly — the
    broker's last spans land moments after the client prints its echo)."""
    script = os.path.join(REPO, "scripts", "trace_report.py")
    deadline = time.time() + wait_s
    proc = None
    while True:
        proc = subprocess.run(
            [sys.executable, script, "--strict", "--json", trace_dir],
            capture_output=True, text=True, timeout=60)
        if proc.returncode == 0 or time.time() >= deadline:
            break
        time.sleep(0.3)
    if proc.returncode != 0:
        print(f"[cluster] FAIL: trace_report strict gate:\n"
              f"{proc.stdout[-1500:]}\n{proc.stderr[-500:]}")
        return False
    report = json.loads(proc.stdout)
    hops = report["per_hop"]
    print(f"[cluster] trace report OK: {report['complete_chains']} complete "
          f"chain(s), {report['orphaned_spans']} orphaned spans; "
          "per-hop p50/p99 ms: "
          + " ".join(f"{hop}={s['p50_ms']}/{s['p99_ms']}"
                     for hop, s in hops.items()))
    return True


def check_trace_chain(trace_dir: str, wait_s: float = 5.0) -> bool:
    """Assemble the per-process JSONL span logs and verify at least one
    trace id produced the COMPLETE lifecycle chain: auth (marshal) +
    publish → ingress → plan → egress (broker) → delivery (client).
    Retries briefly: the broker's egress span lands microseconds after
    the client prints its echo, and we read the files right then."""
    import glob
    import json as json_mod
    need = {"auth", "publish", "ingress", "plan", "egress", "delivery"}
    deadline = time.time() + wait_s
    hops_by_id: dict = {}
    while True:
        hops_by_id = {}
        for path in glob.glob(os.path.join(trace_dir, "*.jsonl")):
            with open(path) as fh:
                for line in fh:
                    try:
                        rec = json_mod.loads(line)
                    except ValueError:
                        continue
                    hops_by_id.setdefault(rec["trace_id"],
                                          set()).add(rec["hop"])
        for tid, hops in hops_by_id.items():
            if need <= hops:
                print(f"[cluster] trace chain complete: id={tid:x} "
                      f"hops={sorted(hops)}")
                return True
        if time.time() >= deadline:
            break
        time.sleep(0.2)
    print(f"[cluster] FAIL: no complete trace chain "
          f"(saw {[(hex(t), sorted(h)) for t, h in hops_by_id.items()]})")
    return False


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--duration", type=float, default=20.0)
    ap.add_argument("--base-port", type=int, default=21700,
                    help="0 picks a free contiguous range (CI runs that "
                         "must not collide with other suites)")
    ap.add_argument("--device-plane", action="store_true",
                    help="brokers route eligible traffic on the attached "
                         "device (single-shard planes)")
    ap.add_argument("--topology", action="store_true",
                    help="render one merged cluster view from every "
                         "broker's /debug/topology once the mesh is up")
    ap.add_argument("--trace-log", metavar="DIR", default=None,
                    help="write per-process lifecycle-trace span JSONL "
                         "under DIR, verify one complete span chain, and "
                         "run scripts/trace_report.py --strict over it")
    ap.add_argument("--churn", action="store_true",
                    help="force subscribe-rate overload (ISSUE 7): brokers "
                         "run with a tiny PUSHCDN_SUBSCRIBE_RATE, a churn "
                         "client drives an over-rate storm, and the run "
                         "verifies the typed shed Error, the /readyz "
                         "admission flip + flight-recorder event, and "
                         "recovery")
    ap.add_argument("--replay", action="store_true",
                    help="durable-topics check (ISSUE 14): brokers retain "
                         "topic 1; publish, kill the subscriber, rejoin "
                         "with subscribe_from and assert the in-order "
                         "Retained catch-up + live handover")
    ap.add_argument("--rehome", action="store_true",
                    help="elastic drain (ISSUE 12): GET /drain on the "
                         "broker homing the echo client, verify every "
                         "user is actively re-homed to the survivor via "
                         "typed Migrate frames (topology moves, drained "
                         "broker latches 503 draining, echo keeps "
                         "flowing on the new home)")
    ap.add_argument("--shards", type=int, default=1,
                    help="run broker0 with a sharded data plane (N worker "
                         "processes); spawns a second client so directs "
                         "cross the shard boundary, and asserts the "
                         "handoff rings carried them")
    ap.add_argument("--collector", action="store_true",
                    help="drive scripts/cdn_top.py --once --record "
                         "--bundle against the live cluster and verify "
                         "the pane, timeline, and postmortem bundle "
                         "(ISSUE 19)")
    ap.add_argument("--audit", action="store_true",
                    help="drive scripts/cdn_top.py --audit --once against "
                         "the live mesh (ISSUE 20): clean leg requires "
                         "zero conservation violations and zero "
                         "unattributed deficit; a broker-SIGKILL chaos "
                         "leg requires the dead peer's undelivered frames "
                         "fully attributed, then a clean balance again "
                         "after the respawn (forces the scalar data "
                         "plane: PUSHCDN_PUMP=off)")
    ap.add_argument("--chaos", action="store_true",
                    help="scripted chaos events after the baseline checks: "
                         "broker SIGKILL (a shard-worker kill under "
                         "--shards), marshal loss, and a discovery outage "
                         "— each asserted against its composition "
                         "invariant and correlated in the flight recorder")
    ap.add_argument("--io-impl", choices=("auto", "uring", "asyncio"),
                    default=None,
                    help="host I/O engine for every spawned component "
                         "(exported as PUSHCDN_IO_IMPL; auto demotes to "
                         "asyncio with a warning when the kernel denies "
                         "io_uring)")
    ap.add_argument("--pump", choices=("auto", "off"), default=None,
                    help="fused native data-plane pump for every broker "
                         "(exported as PUSHCDN_PUMP; auto engages when "
                         "io_uring + the native planner are both live, "
                         "with an honest skip otherwise)")
    ap.add_argument("--chaos-events", default="broker,marshal,discovery",
                    metavar="LIST",
                    help="comma-separated subset of chaos events to run "
                         "(broker, marshal, discovery); the CI smoke tier "
                         "runs one event to stay fast")
    args = ap.parse_args()

    if args.io_impl:
        # every spawned component inherits the selection (and a --shards
        # broker's workers inherit it transitively)
        os.environ["PUSHCDN_IO_IMPL"] = args.io_impl
        print(f"[cluster] io-impl: {args.io_impl}")

    if args.pump:
        os.environ["PUSHCDN_PUMP"] = args.pump
        print(f"[cluster] pump: {args.pump}")

    if args.audit:
        # pumped frames move below the Python per-link tables (the C
        # counters are fd-keyed, not peer-identity-resolvable yet), so
        # the conservation audit legs pin the scalar data plane
        os.environ["PUSHCDN_PUMP"] = "off"
        if args.pump == "auto":
            print("[cluster] --audit overrides --pump auto: per-link "
                  "ledger tables are scalar-plane only")

    if args.trace_log:
        os.makedirs(args.trace_log, exist_ok=True)

    def trace_env(name: str):
        if not args.trace_log:
            return {}
        return {"PUSHCDN_TRACE_LOG":
                os.path.join(args.trace_log, f"{name}.jsonl")}

    logdir = tempfile.mkdtemp(prefix="pushcdn-cluster-")
    db = os.path.join(logdir, "cdn.sqlite")
    bp = args.base_port
    if bp == 0:
        # pick the range BELOW the kernel's ephemeral floor: a listener
        # inside the ephemeral range races the outgoing-port allocator
        # (EADDRINUSE even with SO_REUSEADDR while a live connection —
        # ours or another suite's — holds the port locally). Below the
        # floor the kernel never hands the ports out, so only another
        # explicit listener can collide; probe every offset the cluster
        # derives (broker pub/priv, marshal, metrics blocks incl.
        # per-shard worker endpoints at parent + 1 + shard) and redraw.
        import random
        import socket
        try:
            with open("/proc/sys/net/ipv4/ip_local_port_range") as fh:
                eph_lo = int(fh.read().split()[0])
        except (OSError, ValueError, IndexError):
            eph_lo = 32768
        hi = max(10_001, min(eph_lo, 65_000) - 200)
        offsets = [*range(0, 4), 50, *range(100, 143)]
        while True:
            candidate = random.randrange(10_000, hi)
            try:
                for off in offsets:
                    with socket.socket() as s:
                        s.setsockopt(socket.SOL_SOCKET,
                                     socket.SO_REUSEADDR, 1)
                        s.bind(("127.0.0.1", candidate + off))
            except OSError:
                continue
            bp = candidate
            break
    # metrics layout: each broker parent gets a 20-port block so its
    # per-shard worker endpoints (parent + 1 + shard) never collide with
    # the next component even when both brokers spawn workers
    metrics_ports = {"broker0": bp + 100, "broker1": bp + 120,
                     "marshal": bp + 140, "client": bp + 141}
    broker_ports = {"broker0": bp + 100, "broker1": bp + 120}
    if args.shards > 1:
        metrics_ports["client2"] = bp + 142
    procs: list[tuple[str, subprocess.Popen]] = []

    def replace_proc(name: str, proc: subprocess.Popen) -> None:
        for idx, (n, _p) in enumerate(procs):
            if n == name:
                procs[idx] = (name, proc)
                return
        procs.append((name, proc))

    def spawn_broker(i: int, first_boot: bool = False) -> subprocess.Popen:
        env = {**trace_env(f"broker{i}"),
               "PUSHCDN_DRAIN_GRACE_S": str(DRAIN_GRACE_S)}
        if args.replay:
            env["PUSHCDN_RETAIN_TOPICS"] = "1"
        if args.churn:
            # tiny per-connection subscribe budget so the churn driver
            # forces shedding quickly; the ready window is generous so
            # the /readyz flip is externally observable
            env.update({"PUSHCDN_SUBSCRIBE_RATE": "2",
                        "PUSHCDN_SUBSCRIBE_BURST": "3",
                        "PUSHCDN_SHED_READY_S": str(SHED_READY_S)})
        shard_flags = []
        if i == 0:
            if first_boot:
                # hold broker0's listener binds open so the not-ready-
                # before-bind state is externally observable (a chaos
                # respawn skips the delay: nothing observes it then)
                env["PUSHCDN_BIND_DELAY_S"] = "1.5"
            if args.shards > 1:
                shard_flags = ["--shards", str(args.shards)]
                # deterministic round-robin accept distribution: the
                # two clients land on DIFFERENT workers, so their
                # directs must cross the shard boundary (this also
                # CI-covers the fd-handoff accept path; SO_REUSEPORT
                # is covered by benches/route_bench.py --shards)
                env["PUSHCDN_SHARD_ACCEPT"] = "handoff"
        chaos_flags = []
        if args.chaos:
            # a SIGKILLed broker must age out of placement fast, or the
            # marshal keeps handing its dead endpoint to the reconnecting
            # client for the full 60 s reference TTL
            chaos_flags = ["--heartbeat-interval", "1",
                           "--membership-ttl", "5"]
        audit_flags = []
        if args.audit:
            # fast anti-entropy so LedgerSync balance sheets (and, after
            # the chaos-leg respawn, the fresh incarnation's boot epoch)
            # propagate inside the audit deadlines; the SIGKILL leg also
            # needs the dead broker aged out of placement quickly
            audit_flags = ["--sync-interval", "2",
                           "--heartbeat-interval", "1",
                           "--membership-ttl", "5"]
        return spawn(
            "broker",
            "--discovery-endpoint", db,
            "--public-advertise-endpoint", f"127.0.0.1:{bp + i * 2}",
            "--public-bind-endpoint", f"127.0.0.1:{bp + i * 2}",
            "--private-advertise-endpoint", f"127.0.0.1:{bp + i * 2 + 1}",
            "--private-bind-endpoint", f"127.0.0.1:{bp + i * 2 + 1}",
            "--user-transport", "tcp",   # plain tcp for the local demo
            "--metrics-bind-endpoint",
            f"127.0.0.1:{metrics_ports[f'broker{i}']}",
            *shard_flags, *chaos_flags, *audit_flags,
            *(["--device-plane"] if args.device_plane else []),
            env_extra=env,
            log_path=os.path.join(logdir, f"broker{i}.log"))

    def spawn_marshal() -> subprocess.Popen:
        return spawn(
            "marshal",
            "--discovery-endpoint", db,
            "--bind-endpoint", f"127.0.0.1:{bp + 50}",
            "--metrics-bind-endpoint",
            f"127.0.0.1:{metrics_ports['marshal']}",
            "--user-transport", "tcp",
            env_extra=trace_env("marshal"),
            log_path=os.path.join(logdir, "marshal.log"))

    ok = True
    # chaos mode heartbeats every 1 s, so the marshal's load view is FRESH
    # and it correctly balances client2 onto broker1 — which starves the
    # sharded cross-shard check (it needs both clients on broker0). Spawn
    # broker1 only after both clients are placed: with one broker alive
    # the marshal has no choice, and co-location is deterministic instead
    # of an artifact of stale 10 s load reports.
    late_broker1 = args.chaos and args.shards > 1
    try:
        for i in range(1 if late_broker1 else 2):
            procs.append((f"broker{i}", spawn_broker(i, first_boot=True)))
            if i == 0:
                ok = check_readiness_before_bind(metrics_ports["broker0"]) \
                    and ok
        time.sleep(1.5)  # brokers register + mesh up
        procs.append(("marshal", spawn_marshal()))
        time.sleep(1.0)
        procs.append(("client", spawn(
            "client",
            "--marshal-endpoint", f"127.0.0.1:{bp + 50}",
            "--transport", "tcp",
            "--interval", "1.0", "--key-seed", "7",
            "--metrics-bind-endpoint", f"127.0.0.1:{metrics_ports['client']}",
            env_extra=trace_env("client"))))
        if args.shards > 1:
            time.sleep(1.0)  # client 1 accepts first -> worker 0
            procs.append(("client2", spawn(
                "client",
                "--marshal-endpoint", f"127.0.0.1:{bp + 50}",
                "--transport", "tcp",
                "--interval", "1.0", "--key-seed", "8",
                "--direct-to-seed", "7",  # cross-shard directs to client 1
                "--metrics-bind-endpoint",
                f"127.0.0.1:{metrics_ports['client2']}",
                env_extra=trace_env("client2"))))
        if late_broker1:
            time.sleep(1.0)  # both clients placed on broker0 first
            procs.append(("broker1", spawn_broker(1, first_boot=True)))
            # mesh forms within ~1 s (chaos heartbeat); check_topology polls

        deadline = time.time() + args.duration
        echoed = False
        client = next(p for n, p in procs if n == "client")
        others = [(n, p) for n, p in procs if n != "client"]
        while time.time() < deadline:
            for name, proc in others:
                if proc.poll() is not None:
                    print(f"[cluster] FAIL: {name} died early")
                    if proc.stdout is not None:
                        print(proc.stdout.read()[-2000:])
                    else:
                        log = os.path.join(logdir, f"{name}.log")
                        if os.path.exists(log):
                            with open(log, errors="replace") as f:
                                print(f.read()[-2000:])
                    return 1
            line = client.stdout.readline()
            if line:
                sys.stdout.write(f"[client] {line}")
                if "recv direct" in line:
                    echoed = True
                    break
        if not echoed:
            print("[cluster] FAIL: client never echoed")
            return 1

        # ---- observability plane checks (ISSUE 5) ----
        ok = check_health(metrics_ports) and ok
        ok = check_topology(broker_ports,
                            expected_users=2 if args.shards > 1 else 1) \
            and ok
        if args.pump == "auto":
            # ---- fused data-plane pump (ISSUE 17): engaged with real
            # pumped frames on a capable kernel, honest skip otherwise
            ok = check_pump(broker_ports) and ok
        if args.rehome:
            # ---- elastic membership (ISSUE 12): operator /drain actively
            # re-homes the echo client to the surviving broker; runs
            # BEFORE the trace checks so trace_report --strict also
            # covers post-migration delivery chains
            ok = check_rehome(broker_ports, EchoWatch(client)) and ok
        if args.replay:
            # ---- durable topics (ISSUE 14): retained ring replay +
            # live handover through real processes; BEFORE the trace
            # checks so --strict also covers chains delivered alongside
            ok = check_replay(bp + 50, broker_ports) and ok
        if args.collector:
            # ---- one-pane collector (ISSUE 19): cdn_top --once --bundle
            # over every live endpoint, with the timeline + bundle +
            # pump-stage-telemetry assertions
            ok = check_collector(metrics_ports, broker_ports, logdir) \
                and ok
        if args.audit:
            # ---- conservation audit (ISSUE 20), clean leg: the live
            # mesh must merge to zero violations and zero unattributed
            # deficit in cdn_top --audit --once
            ok = check_audit(metrics_ports, broker_ports, logdir) and ok
        if args.shards > 1:
            # ---- sharded data plane (ISSUE 6): users on 2+ workers and
            # cross-shard directs carried by the handoff rings
            ok = check_shard_plane(metrics_ports["broker0"],
                                   args.shards) and ok
        if args.churn:
            # ---- admission control (ISSUE 7): forced overload sheds,
            # surfaces typed + /readyz + flightrec, then recovers
            ok = check_load_shed(bp + 50, broker_ports) and ok
        if args.topology:
            render_merged_topology(broker_ports)
        if args.trace_log:
            ok = check_trace_chain(args.trace_log) and ok
            ok = run_trace_report(args.trace_log) and ok
        if args.chaos:
            # ---- scripted chaos (this PR): broker SIGKILL / marshal
            # loss / discovery outage, each with its invariant + flight-
            # recorder correlation; runs LAST before drain because it
            # respawns processes the earlier checks assume stable
            ok = check_chaos(procs, replace_proc, spawn_broker,
                             spawn_marshal, EchoWatch(client),
                             broker_ports, metrics_ports, bp + 50,
                             db, logdir, args.shards,
                             events=tuple(
                                 e.strip() for e in
                                 args.chaos_events.split(",") if e.strip()
                             )) and ok
        if args.audit:
            # ---- conservation audit (ISSUE 20), chaos leg: SIGKILL
            # broker1, require its undelivered frames fully attributed,
            # respawn, require a clean balance again; runs after the
            # other checks because it kills a process they assume stable
            ok = check_audit_chaos(procs, replace_proc, spawn_broker,
                                   metrics_ports, broker_ports, logdir) \
                and ok
        # drain LAST: SIGINT broker1 and watch readiness flip before its
        # listeners close (the client may briefly reconnect after; every
        # earlier check has already run)
        broker1 = next(p for n, p in procs if n == "broker1")
        ok = check_drain("broker1", broker1, metrics_ports["broker1"]) and ok

        if not ok:
            return 1
        print("[cluster] OK: end-to-end echo through real processes")
        return 0
    finally:
        for _name, proc in procs:
            if proc.poll() is None:
                proc.send_signal(signal.SIGINT)
        # brokers drain for DRAIN_GRACE_S before exiting — give the grace
        # window (plus margin) before escalating, or the "clean shutdown"
        # is actually a SIGKILL mid-drain
        deadline = time.time() + DRAIN_GRACE_S + 2.0
        while time.time() < deadline and any(
                proc.poll() is None for _name, proc in procs):
            time.sleep(0.1)
        for _name, proc in procs:
            if proc.poll() is None:
                proc.kill()


if __name__ == "__main__":
    sys.exit(main())
